"""Ingest-pipeline A/B bench: catchup with the staged pipeline off vs on.

The ISSUE 3 acceptance artifact: both runs land in ONE committed file
(``bench_ingest_pipeline.json``) together with the serial per-stage
``read_ms``/``encode_ms``/``dispatch_ms`` breakdown from the bench's
device probe, so the overlap the pipeline buys — and what it costs on a
host that cannot overlap — is on the record:

- ``dispatch_ms`` is what the pipelined host loop pays per chunk once
  read + encode are off its critical path (the ISSUE's "toward the
  device floor" claim, measured);
- ``off``/``on`` are best-of-N catchup runs over the same journal with
  fresh engine + store per rep, the "on" run oracle-verified and its
  stage telemetry (queue depths, stall counters) recorded;
- ``host_cores`` qualifies the comparison: on a single-core host the
  three stages timeslice one CPU, so the thread handoffs are pure
  overhead and "off" wins — which is exactly why the runner's "auto"
  mode gates on a multi-core host (see StreamRunner._pipeline_on).

Env knobs: STREAMBENCH_INGEST_BENCH_EVENTS (default 400000),
STREAMBENCH_INGEST_BENCH_REPS (default 3).
"""

from __future__ import annotations

import importlib.util
import json
import os
import random
import sys
import tempfile
import time


def _load_bench():
    """Import bench.py as a module (its probe is the ONE stage-timing
    implementation; duplicating it here would let the two drift)."""
    here = os.path.dirname(os.path.abspath(__file__))
    spec = importlib.util.spec_from_file_location(
        "bench_for_ingest", os.path.join(here, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, here)
    from streambench_tpu.config import default_config
    from streambench_tpu.datagen import gen
    from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner
    from streambench_tpu.io.fakeredis import make_store
    from streambench_tpu.io.journal import FileBroker
    from streambench_tpu.io.redis_schema import as_redis, seed_campaigns

    import jax

    n_events = int(os.environ.get("STREAMBENCH_INGEST_BENCH_EVENTS",
                                  "400000"))
    reps = max(int(os.environ.get("STREAMBENCH_INGEST_BENCH_REPS", "3")), 1)
    bench = _load_bench()
    tmp_base = "/dev/shm" if os.path.isdir("/dev/shm") else None

    out: dict = {
        "metric": "staged ingest pipeline catchup A/B",
        "platform": jax.default_backend(),
        "host_cores": os.cpu_count() or 1,
        "events": n_events,
    }
    with tempfile.TemporaryDirectory(dir=tmp_base) as wd:
        cfg = default_config(jax_window_slots=2048, jax_scan_batches=8,
                             jax_batch_size=8192)
        broker = FileBroker(os.path.join(wd, "broker"))
        r = as_redis(make_store())
        gen.do_setup(r, cfg, broker=broker, events_num=n_events,
                     rng=random.Random(42), workdir=wd)
        mapping = gen.load_ad_mapping_file(
            os.path.join(wd, gen.AD_TO_CAMPAIGN_FILE))
        camps = sorted(set(mapping.values()))

        # serial per-stage breakdown (bench.py's device probe, shared)
        out["stage_ms"] = bench._measure_device_time(cfg, mapping, broker)

        def measure(mode: str) -> dict:
            row: dict = {"reps_events_per_s": []}
            best = None
            for _ in range(reps):
                r_rep = as_redis(make_store())
                seed_campaigns(r_rep, camps)
                eng = AdAnalyticsEngine(cfg, mapping, redis=r_rep)
                eng.warmup()
                runner = StreamRunner(eng, broker.reader(cfg.kafka_topic),
                                      ingest_pipeline=mode)
                t0 = time.monotonic()
                stats = runner.run_catchup()
                eng.close()
                dt = max(time.monotonic() - t0, 1e-9)
                v = round(stats.events / dt, 1)
                row["reps_events_per_s"].append(v)
                if best is None or v > best[0]:
                    best = (v, stats, runner, r_rep)
            v, stats, runner, r_rep = best
            row["best_events_per_s"] = v
            row["events"] = stats.events
            row["batches"] = stats.batches
            if runner._pipeline is not None:
                row["telemetry"] = runner._pipeline.telemetry()
            row["_store"] = r_rep
            return row

        off = measure("off")
        on = measure("on")
        # oracle-verify the pipelined run: overlap must not cost a count
        correct, differ, missing = gen.check_correct(
            on.pop("_store"), workdir=wd, log=lambda s: None,
            time_divisor_ms=cfg.jax_time_divisor_ms)
        off.pop("_store")
        on["oracle"] = ("exact" if not differ and not missing
                        else f"INVALID differ={differ} missing={missing}")
        out["off"] = off
        out["on"] = on
        out["speedup_on_vs_off"] = round(
            on["best_events_per_s"] / off["best_events_per_s"], 4)
        if out["host_cores"] <= 1:
            out["note"] = (
                "single-core host: the three stages timeslice one CPU, so "
                "thread handoffs are pure overhead and 'off' wins — the "
                "runner's 'auto' mode therefore gates the pipeline on a "
                "multi-core host; dispatch_ms in stage_ms is what the "
                "pipelined host loop pays per chunk once read+encode are "
                "off its critical path (the overlap headroom)")

    path = os.path.join(here, "bench_ingest_pipeline.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0 if on["oracle"] == "exact" else 1


if __name__ == "__main__":
    sys.exit(main())
