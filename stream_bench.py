#!/usr/bin/env python3
"""Benchmark harness — the ``stream-bench.sh`` peer (reference: 409-line bash
``run()`` case dispatch, ``stream-bench.sh:117-409``).

Same operation grammar: a list of operation names, each dispatched by
``run()``; composite ``JAX_TEST`` mirrors ``FLINK_TEST``
(``stream-bench.sh:301-315``): start services -> start engine -> start load
-> sleep TEST_TIME -> stop load (collect stats) -> stop engine -> stop
services.  Same knobs via env vars (``stream-bench.sh:9-40``): ``TOPIC``,
``PARTITIONS``, ``LOAD``, ``TEST_TIME``, ``REDIS_HOST``, ``REDIS_PORT``,
``WORKDIR``, ``CONF_FILE``.

Differences by design:
- services are Python subprocesses with pidfiles (no process-grep
  ``pid_match``, ``stream-bench.sh:42-46`` — pidfiles are exact);
- there is no ZooKeeper/Kafka daemon: the broker is the file journal
  (``streambench_tpu.io.journal``), and Redis is the in-repo RESP server
  (``streambench_tpu.io.fakeredis``) unless ``REDIS_HOST`` points elsewhere;
- SETUP compiles nothing to download: it only writes ``localConf.yaml``
  (``stream-bench.sh:123-138``) and pre-builds the native encoder.

Usage:  python stream_bench.py SETUP START_REDIS ... | JAX_TEST | STOP_ALL
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import signal
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))

# --- env knobs (names per stream-bench.sh:9-40) ---
TOPIC = os.environ.get("TOPIC", "ad-events")
PARTITIONS = int(os.environ.get("PARTITIONS", "1"))
LOAD = int(os.environ.get("LOAD", "1000"))               # events/sec
TEST_TIME = float(os.environ.get("TEST_TIME", "240"))    # seconds
REDIS_HOST = os.environ.get("REDIS_HOST", "127.0.0.1")
REDIS_PORT = int(os.environ.get("REDIS_PORT", "6379"))
WORKDIR = os.path.abspath(os.environ.get("WORKDIR", "./bench-run"))
CONF_FILE = os.environ.get("CONF_FILE", os.path.join(WORKDIR, "localConf.yaml"))
SHARDED = os.environ.get("SHARDED", "") not in ("", "0", "false", "no")
STOP_STATS_GRACE_S = float(os.environ.get("STOP_STATS_GRACE", "2.5"))
# Engine selection (BASELINE configs #1-#4) + execution-mode knobs, the
# peer of the reference harness driving every engine (stream-bench.sh:286-343)
ENGINE = os.environ.get("ENGINE", "exact")   # exact|hll|sliding|session|reach
MICROBATCH = os.environ.get("MICROBATCH", "") not in ("", "0", "false", "no")
CHECKPOINT_DIR = os.environ.get("CHECKPOINT_DIR", "")
# Real-Kafka opt-in: "host:9092[,host2:9092]" routes every broker through
# io.kafka.KafkaBroker instead of the file journal (the reference's
# firehose, stream-bench.sh:107-115).  Errors loudly if confluent-kafka
# is absent — no silent fallback.
KAFKA_BROKERS = os.environ.get("KAFKA_BROKERS", "")
# Hermetic fake-Kafka opt-in (io.fakekafka; ISSUE 20): KAFKA_FAKE=1
# routes make_broker through the recorded-protocol fake instead of
# requiring confluent-kafka.  Under the harness the fake always runs as
# a standalone TCP broker process (START_KAFKA/STOP_KAFKA, the
# FakeRedisServer lifecycle): the generator produces and the engine
# consumes over a real socket.  KAFKA_BROKERS picks the address
# (default 127.0.0.1:9092).  KAFKA_FAULT_* knobs arm seeded
# broker-surface chaos in the broker process (see
# streambench_tpu.io.fakekafka --help; ROBUSTNESS.md "Kafka edge").
KAFKA_FAKE = os.environ.get("KAFKA_FAKE", "") not in ("", "0", "false", "no")
KAFKA_HOST, KAFKA_PORT = "127.0.0.1", 9092
if KAFKA_BROKERS:
    _first = KAFKA_BROKERS.split(",")[0].strip()
    _h, _, _p = _first.partition(":")
    KAFKA_HOST = _h or KAFKA_HOST
    if _p:
        try:
            KAFKA_PORT = int(_p)
        except ValueError:
            pass
# the bootstrap written to localConf: an explicit KAFKA_BROKERS wins;
# KAFKA_FAKE alone points at the START_KAFKA broker's default address
KAFKA_BOOTSTRAP = (KAFKA_BROKERS or
                   (f"{KAFKA_HOST}:{KAFKA_PORT}" if KAFKA_FAKE else ""))
# Engine tuning knobs forwarded into localConf (jax.* keys): batches per
# device dispatch, window ring slots, parallel encode threads.
SCAN_BATCHES = int(os.environ.get("SCAN_BATCHES", "8"))
WINDOW_SLOTS = int(os.environ.get("WINDOW_SLOTS", "16"))
ENCODE_WORKERS = int(os.environ.get("ENCODE_WORKERS", "1"))
# Staged ingest pipeline (engine/ingest.py): off | on | auto
INGEST_PIPELINE = os.environ.get("INGEST_PIPELINE", "off")
# On-device event decode (ops/devdecode.py): off | on | auto — "on"
# ships raw journal blocks to the device and decodes inside the jitted
# step; "auto" follows the measured per-backend A/B (README "Device
# decode").  Default off: the host-encode hot path stays byte-identical.
DECODE_DEVICE = os.environ.get("DECODE_DEVICE", "off")
# Exactly-once writeback (ROBUSTNESS.md "Exactly-once"): epoch-fenced
# idempotent sink flushes + absolute-ledger reconcile on resume.
# Default off: the hot path stays byte-identical.
EXACTLY_ONCE = os.environ.get("EXACTLY_ONCE", "") not in (
    "", "0", "false", "no")
# Observability knobs (obs/; README "Observability") — all default-off:
# METRICS_INTERVAL_MS>0 journals <workdir>/metrics.jsonl at that cadence,
# OBS_LIFECYCLE=1 adds per-window latency attribution to it (read with
# `python -m streambench_tpu.obs attribution`), FLIGHTREC=1 arms the
# crash flight recorder (<workdir>/flight_<reason>.jsonl on failure).
METRICS_INTERVAL_MS = int(os.environ.get("METRICS_INTERVAL_MS", "0"))
OBS_LIFECYCLE = os.environ.get("OBS_LIFECYCLE", "") not in (
    "", "0", "false", "no")
FLIGHTREC = os.environ.get("FLIGHTREC", "") not in ("", "0", "false", "no")
# OBS_SPANS=1 arms span tracing (<workdir>/trace_<pid>.json, perfetto-
# loadable); OBS_OCCUPANCY=1 measures device occupancy (sampled
# block_until_ready -> device_busy_ratio in the engine's stats line);
# SLO_P99_MS / SLO_RATE_EVPS set objectives whose burn-rate breaches
# are journaled and whose pass/fail verdict rides the stats line.
OBS_SPANS = os.environ.get("OBS_SPANS", "") not in ("", "0", "false", "no")
OBS_OCCUPANCY = os.environ.get("OBS_OCCUPANCY", "") not in (
    "", "0", "false", "no")
SLO_P99_MS = int(os.environ.get("SLO_P99_MS", "0"))
SLO_RATE_EVPS = int(os.environ.get("SLO_RATE_EVPS", "0"))
# Data-path obs (obs layer 4): OBS_XFER=1 measures host->device bytes
# per wire format, OBS_DEVMEM=1 the compiled-kernel memory footprints +
# live-array census, OBS_SHARD=1 per-shard skew gauges (with SHARDED=1),
# OBS_CAPTURE=1 arms triggered profiler capture with a startup one-shot
# (<workdir>/xprof_<ms>_<reason>/).
OBS_XFER = os.environ.get("OBS_XFER", "") not in ("", "0", "false", "no")
OBS_DEVMEM = os.environ.get("OBS_DEVMEM", "") not in (
    "", "0", "false", "no")
OBS_SHARD = os.environ.get("OBS_SHARD", "") not in ("", "0", "false", "no")
OBS_CAPTURE = os.environ.get("OBS_CAPTURE", "") not in (
    "", "0", "false", "no")
# Query-path attribution for the reach serving tier (obs/queryattr):
# OBS_QUERY=1 decomposes every reach query's submit->reply latency into
# queue/batch/dispatch/reply segments, keeps a bounded slow-query log,
# and — with OBS_SPANS=1 — exports the ingest-contention ratio.
OBS_QUERY = os.environ.get("OBS_QUERY", "") not in ("", "0", "false", "no")
# Fleet observability (obs/fleet, ISSUE 15): OBS_FLEET=1 stamps shipped
# reach snapshots with the freshness-ledger wall times + writer origin,
# role-stamps the metrics journal, and is the flag the CI fleet leg
# forwards to replicas (--fleet) so replies decompose their age into
# fold_lag/ship_wait/tail_lag/serve hops.
OBS_FLEET = os.environ.get("OBS_FLEET", "") not in ("", "0", "false", "no")
# Multi-tenant host (engine/tenants, obs layer 9): TENANTS="a:exact,
# b:session,c:reach" runs N topologies in one process with tenant=
# metric namespaces + the device-time blame matrix; ADMISSION=1 arms
# the measurement-actuated admission controller (defer/shed the
# aggressor tenant when its dispatches burn a victim's SLO budget).
TENANTS = os.environ.get("TENANTS", "")
ADMISSION = os.environ.get("ADMISSION", "") not in ("", "0", "false", "no")

PID_DIR = os.path.join(WORKDIR, "pids")
LOG_DIR = os.path.join(WORKDIR, "logs")


def _broker_dir() -> str:
    """Journal broker location: RAM-backed when tmpfs has room.

    The journal is the Kafka stand-in, and on a disk-backed workdir a
    paced producer's write() can block for seconds under dirty-page
    writeback throttling — billed to the engine as window latency.
    User-facing outputs (seen.txt, logs, checkpoints) stay in WORKDIR;
    BROKER_DIR=... or an unwritable/too-small /dev/shm keeps the old
    disk behavior.
    """
    explicit = os.environ.get("BROKER_DIR", "")
    if explicit:
        return explicit
    try:
        sv = os.statvfs("/dev/shm")
        if sv.f_bavail * sv.f_frsize >= 4 << 30:
            # Key by full-path hash, not just basename: two checkouts both
            # running WORKDIR=./bench-run must not share (or clean away)
            # each other's journal.
            wd = os.path.abspath(WORKDIR)
            tag = hashlib.sha1(wd.encode()).hexdigest()[:10]
            return os.path.join(
                "/dev/shm", f"streambench-broker-{os.getuid()}",
                f"{os.path.basename(wd)}-{tag}")
    except OSError:
        pass
    return os.path.join(WORKDIR, "broker")


BROKER_DIR = _broker_dir()


def log(msg: str) -> None:
    print(msg, flush=True)


# ----------------------------------------------------------------------
# process lifecycle (pidfile versions of start_if_needed / stop_if_needed,
# stream-bench.sh:47-81)
# ----------------------------------------------------------------------

def _pidfile(name: str) -> str:
    return os.path.join(PID_DIR, f"{name}.pid")


def _proc_starttime(pid: int) -> str | None:
    """Kernel start time of ``pid`` (/proc stat field 22) — the
    pid-match half of stop_if_needed: a recycled pid belongs to a
    DIFFERENT process exactly when its start time differs, so STOP
    never kills a process it didn't start (the reference's pid_match
    greps argv, stream-bench.sh:42-46; start time is exact where argv
    can collide)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().rsplit(")", 1)[1].split()[19]
    except (OSError, IndexError):
        return None


def _alive(pid: int) -> bool:
    # Reap if it's our own child (else an exited child stays a zombie and
    # would look alive to kill(pid, 0) forever).
    try:
        os.waitpid(pid, os.WNOHANG)
    except ChildProcessError:
        pass
    try:
        os.kill(pid, 0)
    except (ProcessLookupError, PermissionError):
        return False
    try:  # a zombie of some other parent is not "running"
        with open(f"/proc/{pid}/stat") as f:
            return f.read().rsplit(")", 1)[1].split()[0] != "Z"
    except (FileNotFoundError, IndexError):
        return False


def running_pid(name: str) -> int | None:
    try:
        with open(_pidfile(name)) as f:
            fields = f.read().split()
            pid = int(fields[0])
            started = fields[1] if len(fields) > 1 else None
    except (FileNotFoundError, ValueError, IndexError):
        return None
    if not _alive(pid):
        return None
    # pid-match: a pidfile written with a start time only matches the
    # process that still carries it — a recycled pid reads as "not
    # running" instead of being adopted (or killed) by mistake
    if started is not None and _proc_starttime(pid) != started:
        return None
    return pid


def start_if_needed(name: str, argv: list[str]) -> int:
    pid = running_pid(name)
    if pid is not None:
        log(f"{name} is already running (pid {pid})...")
        return pid
    os.makedirs(PID_DIR, exist_ok=True)
    os.makedirs(LOG_DIR, exist_ok=True)
    env = dict(os.environ, PYTHONUNBUFFERED="1")
    logf = open(os.path.join(LOG_DIR, f"{name}.log"), "ab")
    proc = subprocess.Popen(argv, cwd=REPO_ROOT, stdout=logf, stderr=logf,
                            env=env, start_new_session=True)
    with open(_pidfile(name), "w") as f:
        # pid + kernel start time: STOP only ever signals the exact
        # process this harness started (see _proc_starttime)
        started = _proc_starttime(proc.pid)
        f.write(f"{proc.pid} {started}" if started else str(proc.pid))
    log(f"started {name} (pid {proc.pid})")
    return proc.pid


def stop_if_needed(name: str, timeout_s: float = 30.0) -> None:
    pid = running_pid(name)
    if pid is None:
        log(f"No running instances of {name}")
        return
    os.kill(pid, signal.SIGTERM)
    deadline = time.monotonic() + timeout_s
    while _alive(pid) and time.monotonic() < deadline:
        time.sleep(0.05)
    if _alive(pid):
        log(f"{name} (pid {pid}) did not exit; killing")
        os.kill(pid, signal.SIGKILL)
    try:
        os.remove(_pidfile(name))
    except FileNotFoundError:
        pass
    log(f"stopped {name}")


def _run_tool(argv: list[str], name: str) -> int:
    """Run a foreground step (seeding, stats), teeing output to its log."""
    os.makedirs(LOG_DIR, exist_ok=True)
    with open(os.path.join(LOG_DIR, f"{name}.log"), "ab") as logf:
        proc = subprocess.run(argv, cwd=REPO_ROOT, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT)
        logf.write(proc.stdout)
    sys.stdout.write(proc.stdout.decode("utf-8", "replace"))
    return proc.returncode


def _py(mod: str, *args: str) -> list[str]:
    return [sys.executable, "-m", mod, *args]


def _datagen(*args: str) -> list[str]:
    return _py("streambench_tpu.datagen", *args,
               "--configPath", CONF_FILE, "--workdir", WORKDIR,
               "--brokerDir", BROKER_DIR)


# ----------------------------------------------------------------------
# operations (the run() case arms, stream-bench.sh:117-398)
# ----------------------------------------------------------------------

def op_setup() -> None:
    """Write localConf.yaml from env vars (stream-bench.sh:123-138) and
    pre-build the native encoder (the only thing to 'compile')."""
    os.makedirs(WORKDIR, exist_ok=True)
    # Start from a fresh journal (don't pile on tmpfs) — EXCEPT on a
    # checkpoint-resume run: the snapshot's byte offsets index THIS
    # journal, and wiping it would make resume read nothing or garbage.
    if not CHECKPOINT_DIR:
        _clean_broker_dir()
    sys.path.insert(0, REPO_ROOT)
    from streambench_tpu.config import write_local_conf
    write_local_conf(CONF_FILE, {
        "kafka.bootstrap": KAFKA_BOOTSTRAP,
        "kafka.fake": KAFKA_FAKE,
        "kafka.brokers": ["localhost"],
        "zookeeper.servers": ["localhost"],
        "kafka.port": 9092,
        "zookeeper.port": 2181,
        "redis.host": REDIS_HOST,
        "redis.port": REDIS_PORT,
        "kafka.topic": TOPIC,
        "kafka.partitions": PARTITIONS,
        # micro-batch mode consumes one broker partition per mapper, so
        # the generated partition count IS the map parallelism
        "map.partitions": PARTITIONS,
        "process.hosts": 1,
        "process.cores": 4,
        "jax.scan.batches": SCAN_BATCHES,
        "jax.window.slots": WINDOW_SLOTS,
        "jax.encode.workers": ENCODE_WORKERS,
        "jax.ingest.pipeline": INGEST_PIPELINE,
        "jax.decode.device": DECODE_DEVICE,
        "jax.sink.exactly_once": EXACTLY_ONCE,
        "jax.metrics.interval.ms": METRICS_INTERVAL_MS,
        "jax.obs.lifecycle": OBS_LIFECYCLE,
        "jax.obs.flightrec.enabled": FLIGHTREC,
        "jax.obs.spans": OBS_SPANS,
        "jax.obs.occupancy": OBS_OCCUPANCY,
        "jax.slo.p99.ms": SLO_P99_MS,
        "jax.slo.rate.evps": SLO_RATE_EVPS,
        "jax.obs.xfer": OBS_XFER,
        "jax.obs.devmem": OBS_DEVMEM,
        "jax.obs.shard": OBS_SHARD,
        "jax.obs.capture.enabled": OBS_CAPTURE,
        # the env knob means "prove capture works": fire one bounded
        # window at startup so smoke runs always produce an xprof dir
        "jax.obs.capture.oneshot": OBS_CAPTURE,
        "jax.obs.query": OBS_QUERY,
        "jax.obs.fleet": OBS_FLEET,
        "jax.tenants": TENANTS,
        "jax.admission.enabled": ADMISSION,
    })
    log(f"wrote {CONF_FILE}")
    try:
        rc = subprocess.run(["make", "-s"], cwd=os.path.join(
            REPO_ROOT, "streambench_tpu", "native")).returncode
    except FileNotFoundError:  # no make on this host
        rc = 127
    log("native encoder ready" if rc == 0 else
        "native encoder build failed (python encoder will be used)")


def _external_redis_marker() -> str:
    return os.path.join(PID_DIR, "redis.external")


def _redis_alive(timeout_s: float = 1.0) -> bool:
    """Health-check PING against REDIS_HOST:REDIS_PORT (no spawn)."""
    sys.path.insert(0, REPO_ROOT)
    from streambench_tpu.io.resp import RespClient
    try:
        with RespClient(REDIS_HOST, REDIS_PORT,
                        timeout_s=timeout_s) as c:
            return c.ping() == "PONG"
    except OSError:
        return False


def op_start_redis() -> None:
    # External-Redis drive mode (ROADMAP item 5): redis.host/redis.port
    # pointing at an ALREADY-RUNNING server is adopted via a PING
    # health check instead of spawning a second one; a marker file
    # records the adoption so STOP leaves a server this harness never
    # started strictly alone (the spawn path's pidfile carries a
    # pid+starttime match for the same reason).
    if running_pid("redis") is None and _redis_alive():
        os.makedirs(PID_DIR, exist_ok=True)
        with open(_external_redis_marker(), "w") as f:
            f.write(f"{REDIS_HOST}:{REDIS_PORT}\n")
        log(f"redis already serving at {REDIS_HOST}:{REDIS_PORT} "
            "(external; adopted via PING, will not be stopped)")
    else:
        try:
            os.remove(_external_redis_marker())
        except FileNotFoundError:
            pass
        start_if_needed("redis", _py("streambench_tpu.io.fakeredis",
                                     "--host", REDIS_HOST,
                                     "--port", str(REDIS_PORT)))
    _wait_redis()
    # seed campaigns, like `lein run -n` right after redis start
    # (stream-bench.sh:182-186).  A checkpoint-resume run must NOT
    # regenerate ids: snapshots and journaled events are keyed to the
    # existing campaign/ad ids, so seed from the workdir files.
    seed_args = ["-n", "--reuse-ids"] if CHECKPOINT_DIR else ["-n"]
    rc = _run_tool(_datagen(*seed_args), "seed")
    if rc != 0:
        raise SystemExit(f"redis seeding failed (rc={rc})")


def _wait_redis(timeout_s: float = 15.0) -> None:
    sys.path.insert(0, REPO_ROOT)
    from streambench_tpu.io.resp import RespClient
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            with RespClient(REDIS_HOST, REDIS_PORT, timeout_s=1.0) as c:
                if c.ping() == "PONG":
                    return
        except OSError:
            pass
        if time.monotonic() > deadline:
            raise SystemExit("redis did not come up")
        time.sleep(0.1)


def op_stop_redis() -> None:
    marker = _external_redis_marker()
    if os.path.exists(marker):
        try:
            with open(marker) as f:
                where = f.read().strip()
        finally:
            os.remove(marker)
        log(f"external redis at {where} left running "
            "(not started by this harness)")
        return
    stop_if_needed("redis")


# ----------------------------------------------------------------------
# fake-Kafka broker lifecycle (ISSUE 20): the Redis half's twin — spawn
# or adopt a standalone io.fakekafka TCP broker with the same
# pid+starttime pidfile and external-adoption marker semantics
# ----------------------------------------------------------------------

#: KAFKA_FAULT_* env -> io.fakekafka CLI fault flags (the CI faulted
#: rung arms the broker's seeded chaos through these)
_KAFKA_FAULT_FLAGS = (
    ("KAFKA_FAULT_SEED", "--fault-seed"),
    ("KAFKA_FAULT_PRODUCE_RATE", "--fault-produce-rate"),
    ("KAFKA_FAULT_CONSUME_RATE", "--fault-consume-rate"),
    ("KAFKA_FAULT_CONN_DROP_RATE", "--fault-conn-drop-rate"),
    ("KAFKA_FAULT_DR_FAIL_RATE", "--fault-dr-fail-rate"),
    ("KAFKA_FAULT_OPS", "--fault-ops"),
    ("KAFKA_FAULT_DOWN", "--fault-down"),
)


def _external_kafka_marker() -> str:
    return os.path.join(PID_DIR, "kafka.external")


def _kafka_alive(timeout_s: float = 1.0) -> bool:
    """Liveness ping against KAFKA_HOST:KAFKA_PORT (no spawn)."""
    sys.path.insert(0, REPO_ROOT)
    from streambench_tpu.io.fakekafka import ping
    return ping(KAFKA_HOST, KAFKA_PORT, timeout_s=timeout_s)


def op_start_kafka() -> None:
    # Same adopt-or-spawn contract as op_start_redis: a broker already
    # serving at the address (started by the user or a parallel
    # harness) is adopted via ping + marker file and never stopped; the
    # spawn path owns its process via the pid+starttime pidfile.
    if running_pid("kafka") is None and _kafka_alive():
        os.makedirs(PID_DIR, exist_ok=True)
        with open(_external_kafka_marker(), "w") as f:
            f.write(f"{KAFKA_HOST}:{KAFKA_PORT}\n")
        log(f"kafka already serving at {KAFKA_HOST}:{KAFKA_PORT} "
            "(external; adopted via ping, will not be stopped)")
    else:
        try:
            os.remove(_external_kafka_marker())
        except FileNotFoundError:
            pass
        args = ["--host", KAFKA_HOST, "--port", str(KAFKA_PORT)]
        for env_name, flag in _KAFKA_FAULT_FLAGS:
            v = os.environ.get(env_name, "")
            if v:
                args += [flag, v]
        start_if_needed("kafka", _py("streambench_tpu.io.fakekafka",
                                     *args))
    _wait_kafka()


def _wait_kafka(timeout_s: float = 15.0) -> None:
    deadline = time.monotonic() + timeout_s
    while not _kafka_alive():
        pid = running_pid("kafka")
        if pid is not None and not _alive(pid):
            raise SystemExit("fake kafka broker died during startup; "
                             f"see {os.path.join(LOG_DIR, 'kafka.log')}")
        if time.monotonic() > deadline:
            raise SystemExit("fake kafka broker did not come up at "
                             f"{KAFKA_HOST}:{KAFKA_PORT}")
        time.sleep(0.1)


def op_stop_kafka() -> None:
    marker = _external_kafka_marker()
    if os.path.exists(marker):
        try:
            with open(marker) as f:
                where = f.read().strip()
        finally:
            os.remove(marker)
        log(f"external kafka at {where} left running "
            "(not started by this harness)")
        return
    stop_if_needed("kafka")


def op_start_load() -> None:
    start_if_needed("load", _datagen("-r", "-t", str(LOAD)))


def op_stop_load() -> None:
    """Kill the generator, then collect stats -> seen.txt/updated.txt
    (stream-bench.sh:231-236)."""
    had_load = running_pid("load") is not None
    stop_if_needed("load")
    if had_load:
        # let the engine's 1 Hz flusher drain the tail windows first
        time.sleep(STOP_STATS_GRACE_S)
    rc = _run_tool(_datagen("-g"), "stats")
    if rc != 0:
        log(f"stats collection failed (rc={rc})")


def _resolve_engine_platform() -> None:
    """Probe the configured JAX backend in a THROWAWAY subprocess and
    pin JAX_PLATFORMS=cpu for child processes when it will not
    initialize.

    Without this, an engine spawned while the hardware tunnel is wedged
    hangs inside backend init and the 300 s readiness wait times out —
    the same failure mode bench.py's probe exists to prevent.  The image
    sets JAX_PLATFORMS to the hardware plugin globally, so the env var
    being set proves nothing; the probe (which re-pins the config from
    the env exactly like every CLI entry point) is what proves the
    platform usable.  CPU is trusted without probing; probes at most
    once per harness process."""
    if getattr(_resolve_engine_platform, "_done", False):
        return
    _resolve_engine_platform._done = True  # type: ignore[attr-defined]
    want = os.environ.get("JAX_PLATFORMS", "")
    if want == "cpu":
        return
    from streambench_tpu.utils.platform import probe_backend

    ok, detail = probe_backend(timeout_s=90)
    if ok:
        log(f"JAX backend ({want or 'ambient'}) ok: {detail}")
    else:
        log(f"JAX backend ({want or 'ambient'}) will not initialize "
            f"({detail}); pinning child processes to CPU")
        os.environ["JAX_PLATFORMS"] = "cpu"


# Byte offset where the CURRENT engine instance's log begins (engine.log
# appends across runs); evidence checks read nothing before it.
_ENGINE_LOG_START = 0


def op_start_jax_processing() -> None:
    _resolve_engine_platform()
    args = ["--confPath", CONF_FILE, "--workdir", WORKDIR,
            "--brokerDir", BROKER_DIR]
    if SHARDED:
        args.append("--sharded")
    if ENGINE != "exact":
        args += ["--engine", ENGINE]
    if CHECKPOINT_DIR:
        args += ["--checkpointDir", CHECKPOINT_DIR]
    if running_pid("engine") is not None:
        log("engine is already running...")
        return
    logpath = os.path.join(LOG_DIR, "engine.log")
    log_start = os.path.getsize(logpath) if os.path.exists(logpath) else 0
    # Remember where THIS instance's log begins (the log appends), so
    # evidence checks never read a previous run's lines.
    global _ENGINE_LOG_START
    _ENGINE_LOG_START = log_start
    pid = start_if_needed("engine", _py("streambench_tpu.engine", *args))
    # Wait until the engine has pre-compiled and printed its ready marker,
    # so a following START_LOAD measures the stream, not XLA compilation.
    # Only look at log bytes written by THIS instance (the log appends).
    # The multi-tenant host prints "tenants up:" instead of "engine up:".
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        try:
            with open(logpath) as f:
                f.seek(log_start)
                txt = f.read()
                if "engine up:" in txt or "tenants up:" in txt:
                    return
        except FileNotFoundError:
            pass
        if not _alive(pid):
            raise SystemExit(f"engine died during startup; see {logpath}")
        time.sleep(0.2)
    raise SystemExit("engine did not become ready within 300s")


def op_stop_jax_processing() -> None:
    stop_if_needed("engine")


def op_jax_test() -> None:
    """Composite run, same sequence as FLINK_TEST (stream-bench.sh:301-315).
    ``MICROBATCH=1`` routes to the micro-batch composite, so
    ``ENGINE=hll MICROBATCH=1 CHECKPOINT_DIR=... JAX_TEST`` composes."""
    if MICROBATCH:
        op_jax_microbatch_test()
        return
    op_setup()
    # Fix the cause, not just the symptom, of the stale-engine false
    # pass: a composite test must never adopt an engine left over from a
    # previous (possibly crashed or hung) run via its pidfile.
    if running_pid("engine") is not None:
        log("stopping stale engine from a previous run")
        stop_if_needed("engine")
    # ... and only THIS run's stats may count as evidence
    try:
        os.unlink(os.path.join(WORKDIR, "seen.txt"))
    except OSError:
        pass
    op_start_redis()
    if KAFKA_FAKE:
        # broker process up BEFORE the engine/generator: both connect
        # to it over TCP (conf carries kafka.fake + the bootstrap)
        op_start_kafka()
    op_start_jax_processing()
    op_start_load()
    log(f"sleeping {TEST_TIME:.0f}s")
    time.sleep(TEST_TIME)
    op_stop_load()
    op_stop_jax_processing()
    if KAFKA_FAKE:
        op_stop_kafka()
    op_stop_redis()
    # A composite test that produced load but measured NOTHING is a
    # failure (observed: a stale hung engine from a crashed previous run
    # was reused via its pidfile and the test "passed" with zero
    # windows), not a quiet success.  The session and reach engines
    # write no canonical window rows, so their evidence is the engine's
    # own final stats line instead of seen.txt.
    if ENGINE in ("session", "reach"):
        evidence, what = "", "events"
        try:
            with open(os.path.join(LOG_DIR, "engine.log")) as f:
                f.seek(_ENGINE_LOG_START)  # only THIS run's lines
                for line in f:
                    if '"events"' in line:
                        evidence = line.strip()
        except OSError:
            pass
        ok = '"events": 0' not in evidence and evidence != ""
    else:
        what = "window rows"
        try:
            n_windows = sum(1 for _ in open(
                os.path.join(WORKDIR, "seen.txt")))
        except OSError:
            n_windows = 0
        ok = n_windows > 0
        evidence = f"{n_windows} rows"
    if not ok:
        raise SystemExit(
            f"JAX_TEST measured no {what} — the engine processed "
            "nothing (stale/hung engine process? check logs/engine.log)")
    log(f"JAX_TEST evidence: {evidence}")


def op_jax_microbatch() -> None:
    """Run the fork's count-based barrier-aligned micro-batch mode as a
    foreground catchup over the journaled topic (the fork replays its
    events file the same way, ``AdvertisingTopologyNative.java:97-99``),
    dumping the fork-format latency hash to Redis."""
    _resolve_engine_platform()
    args = ["--confPath", CONF_FILE, "--workdir", WORKDIR,
            "--brokerDir", BROKER_DIR, "--microbatch"]
    if ENGINE != "exact":
        args += ["--engine", ENGINE]
    if CHECKPOINT_DIR:
        args += ["--checkpointDir", CHECKPOINT_DIR]
    logpath = os.path.join(LOG_DIR, "microbatch.log")
    log_start = os.path.getsize(logpath) if os.path.exists(logpath) else 0
    rc = _run_tool(_py("streambench_tpu.engine", *args), "microbatch")
    if rc != 0:
        raise SystemExit(f"microbatch run failed (rc={rc})")
    # Same zero-measurement guard as JAX_TEST: a microbatch run that
    # folded no events (empty journal, silent load failure) must not
    # pass quietly.  Only THIS invocation's log bytes count.
    evidence = ""
    try:
        with open(logpath) as f:
            f.seek(log_start)
            for line in f:
                if '"events"' in line:
                    evidence = line.strip()
    except OSError:
        pass
    if not evidence or '"events": 0,' in evidence:
        raise SystemExit(
            "microbatch run measured no events — nothing was folded "
            "(empty journal? see logs/microbatch.log)")
    log(f"microbatch evidence: {evidence}")


def op_jax_microbatch_test() -> None:
    """Composite micro-batch run: journal a paced load, then fold it in
    barrier-aligned count windows (the fork's research flow)."""
    op_setup()
    op_start_redis()
    op_start_load()
    log(f"sleeping {TEST_TIME:.0f}s")
    time.sleep(TEST_TIME)
    stop_if_needed("load")
    op_jax_microbatch()
    op_stop_redis()


def op_jax_test_suite() -> None:
    """Sweep BASELINE configs #1-#4 (exact, hll, sliding, session), each
    as a fully isolated JAX_TEST in its own workdir + subprocess — the
    peer of the reference harness's per-engine composite tests
    (``stream-bench.sh:286-343``)."""
    summary = []
    for engine in ("exact", "hll", "sliding", "session"):
        wd = os.path.join(WORKDIR, f"suite-{engine}")
        log(f"=== JAX_TEST [{engine}] (workdir {wd}) ===")
        env = dict(os.environ, ENGINE=engine, WORKDIR=wd,
                   CONF_FILE=os.path.join(wd, "localConf.yaml"))
        cmd = [sys.executable, os.path.abspath(__file__), "JAX_TEST"]
        attempts = []

        def run_once():
            p = subprocess.run(cmd, env=env, cwd=REPO_ROOT,
                               capture_output=True, text=True)
            sys.stdout.write(p.stdout)
            sys.stderr.write(p.stderr)
            attempts.append(p.returncode)
            return p
        p = run_once()
        if p.returncode != 0:
            # One retry per family, gated on the startup-wedge signature
            # ("measured no events"): a tunneled-accelerator backend can
            # wedge during the engine's first compile for the whole
            # TEST_TIME while the same family passes cleanly moments
            # later.  Any OTHER failure (oracle diff, crash) fails
            # immediately — a retry must not launder intermittent bugs.
            wedge = "measured no events" in (p.stdout + p.stderr)
            if not wedge:
                raise SystemExit(f"JAX_TEST [{engine}] failed "
                                 f"(rc={p.returncode})")
            log(f"JAX_TEST [{engine}] hit the startup-wedge signature "
                f"(rc={p.returncode}); retrying once")
            p = run_once()
            if p.returncode != 0:
                raise SystemExit(f"JAX_TEST [{engine}] failed twice "
                                 f"(rc={p.returncode})")
        summary.append({"engine": engine, "attempt_rcs": attempts,
                        "retried": len(attempts) > 1})
        log(f"=== JAX_TEST [{engine}] done ===")
    out = os.path.join(WORKDIR, "jax_test_suite.json")
    with open(out, "w") as f:  # every attempt on the record
        json.dump({"timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
                   "families": summary}, f, indent=1)
    log(f"suite summary -> {out}: " + ", ".join(
        f"{s['engine']}{' (retried)' if s['retried'] else ''}"
        for s in summary))


def op_pytest_suite() -> None:
    """Run the FULL pytest suite PYTEST_RUNS times (default 3) and
    record every run's exit code + duration in ``test_suite_runs.json``
    — the committed deflake evidence (the reference's analog of a
    repeated LocalMode integration run,
    ``ApplicationWithDCWithoutDeserializerTest.java:19-45``).  Fails if
    any run fails."""
    runs = int(os.environ.get("PYTEST_RUNS", "3"))
    results = []
    for i in range(runs):
        log(f"=== pytest suite run {i + 1}/{runs} ===")
        t0 = time.time()
        p = subprocess.run(
            [sys.executable, "-m", "pytest", "tests/", "-q", "--tb=line"],
            cwd=REPO_ROOT, capture_output=True, text=True)
        tail = p.stdout.strip().splitlines()[-1:] or [""]
        results.append({
            "run": i + 1, "rc": p.returncode,
            "seconds": round(time.time() - t0, 1),
            "summary": tail[0],
        })
        log(f"run {i + 1}: rc={p.returncode} ({results[-1]['seconds']}s) "
            f"{tail[0]}")
    out = {"timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
           "runs": results,
           "all_green": all(r["rc"] == 0 for r in results)}
    with open(os.path.join(REPO_ROOT, "test_suite_runs.json"), "w") as f:
        json.dump(out, f, indent=1)
    if not out["all_green"]:
        raise SystemExit("pytest suite not consistently green")
    log(f"{runs} consecutive green suite runs recorded")


def _clean_broker_dir() -> None:
    """Remove this workdir's journal from tmpfs.

    A RAM-backed broker dir is not reclaimed by reboot-free hosts on its
    own, so successive runs would pin hundreds of MB of /dev/shm until
    reboot.  Only the tmpfs location is cleaned — a disk-backed
    WORKDIR/broker keeps the old reuse-per-workdir behavior — and only
    while no producer/engine holds it open.
    """
    if os.environ.get("BROKER_DIR"):
        return  # user-pinned location: never delete their journal
    if not BROKER_DIR.startswith("/dev/shm/"):
        return
    if any(running_pid(n) is not None for n in ("load", "engine")):
        return
    shutil.rmtree(BROKER_DIR, ignore_errors=True)


def op_stop_all() -> None:
    for name in ("load", "engine", "kafka", "redis"):
        stop_if_needed(name)
    _clean_broker_dir()


OPS: dict[str, object] = {
    "SETUP": op_setup,
    "START_REDIS": op_start_redis,
    "STOP_REDIS": op_stop_redis,
    "START_KAFKA": op_start_kafka,
    "STOP_KAFKA": op_stop_kafka,
    "START_LOAD": op_start_load,
    "STOP_LOAD": op_stop_load,
    "START_JAX_PROCESSING": op_start_jax_processing,
    "STOP_JAX_PROCESSING": op_stop_jax_processing,
    "JAX_TEST": op_jax_test,
    "JAX_TEST_SUITE": op_jax_test_suite,
    "PYTEST_SUITE": op_pytest_suite,
    "JAX_MICROBATCH": op_jax_microbatch,
    "JAX_MICROBATCH_TEST": op_jax_microbatch_test,
    "STOP_ALL": op_stop_all,
}


def run(op: str) -> None:
    """Dispatch one operation (the run() case statement,
    stream-bench.sh:117-398)."""
    fn = OPS.get(op)
    if fn is None:
        names = "|".join(OPS)
        log(f"UNKNOWN OPERATION '{op}'")
        log(f"Supported operations: {names}")
        raise SystemExit(1)
    fn()  # type: ignore[operator]


def main(argv: list[str]) -> int:
    if not argv:
        log("Usage: stream_bench.py OPERATION [...]")
        log(f"Supported operations: {'|'.join(OPS)}")
        return 1
    for op in argv:
        run(op)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
