#!/usr/bin/env python3
"""Multi-tenant interference bench: the ISSUE 19 tentpole proof.

Three tenants share ONE process and ONE device through
:class:`streambench_tpu.engine.tenants.MultiTenantHost`:

- **alpha** (session CMS) — steady ingest, the bystander.  Measured
  on this host its fold is device-light (~0.1 ms attributed busy per
  4k-event batch; the cost is host-side packing) — a tenant that
  shares the process but barely the device;
- **beta** (reach sketch folding, no serving) — the aggressor: a
  seeded flash crowd multiplies its batch size ~53x for a mid-run
  window.  Its MinHash/HLL fold IS device-heavy (~100 ms measured
  sync per 8k-event batch, one monolithic scan dispatch at the
  default ``jax.scan.batches``), which is what makes it capable of
  starving a co-tenant's query dispatches;
- **gamma** (reach serving) — the victim: a fixed-QPS query client
  with a ``reach_p99_ms`` SLO, answered live by its ReachQueryServer.

Two arms run the SAME seeded schedule (identical event bytes, identical
query mix):

- **off** — admission disabled.  The flash crowd's folds monopolise the
  shared device; gamma's queries queue behind them and the SLO
  breaches.  The per-tenant device-time ledger still runs, so the
  artifact carries the blame matrix NAMING beta from measured
  wait-overlap evidence — diagnosis without actuation.
- **on** — ``jax.admission.enabled``: the AdmissionController watches
  gamma's burn rate, confirms the breach over ``breach_ticks``, reads
  the blame matrix, and DEFERS beta's ingest (batches stay queued,
  nothing lost).  Gamma's queries keep their latency; when the crowd
  passes and the burn clears, the gate releases and beta's backlog
  drains in the tail.

Hard gates (full mode): the off arm must visibly breach
(``breach_ratio >= 0.15``), the on arm must hold
(``on < 0.5 * off``); at least one defer decision must carry
``tenant=beta, victim=gamma, blame_ms > 0``; the device-time partition
check (per-tenant attributed busy == samplers' measured busy) must
pass in BOTH arms; and both arms must fold the same events per tenant
(the deferred backlog is drained, not dropped).

Honest 1-core caveat: host loop, tenant folds, the query evaluator and
the samplers all share one CPU core, so "device interference" here is
device-queue + GIL + timeslice interference combined.  That is the
interference the blame matrix measures — the ledger intersects
MEASURED victim waits with MEASURED aggressor busy windows, whatever
the mechanism — but latency numbers do not decompose the way they
would on a real multi-tenant accelerator.

Usage:
    python bench_multitenant.py                  # full, writes bench_multitenant.json
    python bench_multitenant.py --smoke          # CI: short crowd, soft gates
    python bench_multitenant.py --out MTEN_r01.json
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time

COMPACT_LINE_MAX = 4096
REPO = os.path.dirname(os.path.abspath(__file__))
_T0 = time.monotonic()

#: reach-query SLO objective (ms): above a warm uncontended query
#: (measured p50 ~5 ms, steady-state max ~8 ms) and below a query
#: landing mid-crowd behind the aggressor's fold dispatches (measured
#: crowd p50 ~16 ms, p90 ~24 ms), so breaches measure interference,
#: not noise.  20 ms proved too high: ambient stalls (victim's own
#: periodic folds, plane flushes) and crowd stalls breached at the
#: same ~15% rate and the A/B arms could not separate.
OBJECTIVE_P99_MS = 12


def log(msg: str) -> None:
    print(f"[{time.monotonic() - _T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def compact_line(obj: dict) -> str:
    """One bounded stdout line: shed detail until it fits."""
    def dump(o):
        return json.dumps(o, separators=(",", ":"))

    line = dump(obj)
    if len(line) <= COMPACT_LINE_MAX:
        return line
    obj = json.loads(line)
    for strip in ("curve", "decisions", "matrix", "params"):
        obj.pop(strip, None)
        line = dump(obj)
        if len(line) <= COMPACT_LINE_MAX:
            return line
    return dump({k: obj[k] for k in ("phase", "ok") if k in obj})


# ----------------------------------------------------------------------
# seeded world + schedule (shared by both arms)
# ----------------------------------------------------------------------

def make_world(seed: int, campaigns_n: int = 20):
    from streambench_tpu.datagen.gen import EventSource
    from streambench_tpu.utils.ids import make_ids

    rng = random.Random(seed)
    campaigns = make_ids(campaigns_n, rng)
    ads = make_ids(campaigns_n * 10, rng)
    mapping = {a: campaigns[i // 10] for i, a in enumerate(ads)}
    src = EventSource(ads=ads, user_ids=make_ids(2000, rng),
                      page_ids=make_ids(100, rng), rng=rng)
    return campaigns, mapping, src


def make_schedule(src, *, duration_s: float, crowd: tuple,
                  steady_n: int, crowd_n: int, seed: int):
    """Seeded per-tenant ingest schedule: list of (t_s, tenant, lines)
    sorted by time.  Both arms replay the SAME byte stream."""
    start = 1_700_000_000_000
    clock = [start]

    def batch(n: int):
        ts = [clock[0] + 10 * i for i in range(n)]
        clock[0] += 10 * n
        return [s.encode() for s in src.events_at(ts)]

    sched = []
    c0, c1 = crowd
    t = 0.0
    while t < duration_s:
        sched.append((t, "alpha", batch(steady_n)))
        if c0 <= t < c1:
            sched.append((t, "beta", batch(crowd_n)))
            sched.append((t + 0.05, "beta", batch(crowd_n)))
        else:
            sched.append((t, "beta", batch(steady_n)))
        # gamma folds rarely and small: the victim's own fold
        # dispatches are ambient stalls that blur the A/B contrast
        if round(t * 10) % 20 == 0:  # every 2 s
            sched.append((t, "gamma", batch(64)))
        t = round(t + 0.1, 3)
    sched.sort(key=lambda x: x[0])
    return sched


def make_queries(campaigns, *, duration_s: float, qps: float, seed: int):
    """Fixed-QPS seeded query plan: (t_s, campaigns_subset, op)."""
    rng = random.Random(seed * 31 + 7)
    n = int(duration_s * qps)
    plan = []
    for i in range(n):
        subset = rng.sample(campaigns, rng.randint(2, 5))
        op = "overlap" if i % 3 == 0 else "union"
        plan.append((i / qps, subset, op))
    return plan


# ----------------------------------------------------------------------
# one arm
# ----------------------------------------------------------------------

def run_arm(on: bool, workdir: str, cfg, mapping, campaigns, sched,
            queries, *, duration_s: float, tail_s: float,
            objective_ms: int, seed: int) -> dict:
    from streambench_tpu.engine.tenants import MultiTenantHost
    from streambench_tpu.obs import MetricsRegistry, MetricsSampler

    arm_dir = os.path.join(workdir, f"mt_{'on' if on else 'off'}")
    os.makedirs(arm_dir, exist_ok=True)
    registry = MetricsRegistry()
    sampler = MetricsSampler(os.path.join(arm_dir, "metrics.jsonl"),
                             interval_ms=250, registry=registry,
                             role="host")
    specs = [
        {"name": "alpha", "kind": "session"},
        {"name": "beta", "kind": "reach"},
        # fast/slow burn windows scaled to bench duration: onset within
        # ~2 s of the crowd, recovery within ~2 s of it passing
        {"name": "gamma", "kind": "reach", "serve": True,
         "reach_p99_ms": objective_ms, "fast_s": 2.0, "slow_s": 6.0},
    ]
    host = MultiTenantHost(
        cfg, specs, mapping, campaigns=campaigns, registry=registry,
        sampler=sampler,
        # every fold dispatch timed: dense busy evidence for the ledger
        sample_every=1,
        admission=on,
        # breach_burn 12: steady-state jitter burns a few percent of
        # the budget; only the crowd's near-total burn (~50x+) may
        # actuate.  healthy_ticks 16 (4 s at the 0.25 s control
        # cadence) keeps the gate up across the whole crowd — a gated
        # aggressor makes the victim healthy, so a short healthy
        # window would release mid-crowd and flap.  escalate_ticks 400
        # (100 s, longer than any arm) means this bench NEVER sheds:
        # the defer-only arm must fold the SAME events as the off arm
        # (asserted below), and ambient burn while gated can hover
        # near breach_burn for the whole query window, so a reachable
        # escalation threshold silently turned defers into sheds at
        # full duration.  Escalation is proven in the unit tests.
        admission_kw={"breach_burn": 12.0, "breach_ticks": 2,
                      "healthy_ticks": 16, "escalate_ticks": 400,
                      "cooldown_s": 1.0},
    )
    host.warmup()
    serve = host.tenant("gamma").serve

    # primer: one small fold per tenant + a flush pushes the reach
    # planes, then warm queries compile the query kernel — all before
    # t0, excluded from the measured window
    for name in host.tenants():
        host.offer(name, sched[0][2][:32])
    host.step()
    host.flush_all()
    warm_done = threading.Event()
    warm_box = {"n": 0}

    def warm_cb(data):
        warm_box["n"] += 1
        if warm_box["n"] >= 4:
            warm_done.set()

    # both ops: a cold overlap kernel mid-run once cost ~400 ms and
    # queued enough queries to trip the burn gate before the crowd
    for wi in range(4):
        serve.submit(queries[0][1], "union" if wi % 2 else "overlap",
                     warm_cb, query_id=f"warm{int(on)}-{wi}")
    warm_done.wait(timeout=60)
    sampler.start()

    stop = threading.Event()

    def fold_loop():
        last_ctrl = last_flush = time.monotonic()
        while not stop.is_set():
            folded = host.step()
            now = time.monotonic()
            if on and now - last_ctrl >= 0.25:
                dec = host.control_step()
                if dec is not None:
                    log(f"admission: {dec['decision']} "
                        f"tenant={dec.get('tenant')} "
                        f"victim={dec.get('victim')} "
                        f"burn={dec.get('burn')} "
                        f"blame_ms={dec.get('blame_ms')}")
                last_ctrl = now
            # flush sparsely: pushing reach planes stalls the core for
            # tens of ms and showed up as victim breaches in both arms
            if now - last_flush >= 1.0:
                host.flush_all()
                last_flush = now
            if not folded:
                host.drain_waits()
                time.sleep(0.002)

    results: list = []
    res_lock = threading.Lock()

    def query_loop(t0: float):
        pos = 0
        pending = threading.Semaphore(256)

        def make_cb(i, t_submit):
            def cb(data):
                e2e_ms = (time.perf_counter() - t_submit) * 1000.0
                with res_lock:
                    results.append((i, e2e_ms, data))
                pending.release()
            return cb

        for i, (t_s, subset, op) in enumerate(queries):
            wait = t0 + t_s - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            pending.acquire()
            serve.submit(subset, op, make_cb(i, time.perf_counter()),
                         query_id=f"mt{int(on)}-{i}")
            pos += 1

    curve: list = []
    curve_stop = threading.Event()
    t0_box = {"t": None}

    def curve_loop():
        while not curve_stop.is_set():
            t0 = t0_box["t"]
            beta = host.tenant("beta")
            slo = host.tenant("gamma").slo
            row = {
                "t_s": (round(time.monotonic() - t0, 2) if t0 else None),
                "beta_queued": len(beta.queue),
                "beta_folded": beta.folded_batches,
                "gamma_burn_fast": (round(slo.fast_burn(), 2)
                                    if slo else None),
            }
            if on and host.admission is not None:
                row["gates"] = {t: g["mode"]
                                for t, g in host.admission.gates().items()}
            curve.append(row)
            curve_stop.wait(0.5)

    t_fold = threading.Thread(target=fold_loop, daemon=True)
    t_curve = threading.Thread(target=curve_loop, daemon=True)
    t_fold.start()
    t_curve.start()

    # settle: with the fold loop and sampler live, pace a handful of
    # uncounted queries for longer than the fast burn window (fast_s)
    # so warmup residue (slow first queries, first-fold stalls) ages
    # out of the SLO ring before t0.  Without this both arms opened
    # with burn 18-30 at t=1 s and the ON arm gated BEFORE the crowd.
    settle_n = 6
    settle_done = threading.Event()
    settle_box = {"n": 0}

    def settle_cb(data):
        settle_box["n"] += 1
        if settle_box["n"] >= settle_n:
            settle_done.set()

    for si in range(settle_n):
        serve.submit(queries[si % len(queries)][1],
                     "union" if si % 2 else "overlap", settle_cb,
                     query_id=f"settle{int(on)}-{si}")
        time.sleep(0.4)
    settle_done.wait(timeout=30)

    t0 = time.monotonic()
    t0_box["t"] = t0

    # ingest + queries paced off the same t0
    t_query = threading.Thread(target=query_loop, args=(t0,),
                               daemon=True)
    t_query.start()
    for t_s, name, lines in sched:
        wait = t0 + t_s - time.monotonic()
        if wait > 0:
            time.sleep(wait)
        host.offer(name, lines)
    t_query.join(timeout=duration_s + 120)

    # tail: no new traffic; the fold loop drains every queue (the ON
    # arm's gate must release once gamma's burn clears, then beta's
    # deferred backlog folds — deferral is accounted, never lost)
    tail_deadline = time.monotonic() + tail_s
    while time.monotonic() < tail_deadline:
        if all(not host.tenant(n).queue for n in host.tenants()):
            break
        time.sleep(0.1)
    drained = all(not host.tenant(n).queue for n in host.tenants())
    curve_stop.set()
    stop.set()
    t_fold.join(timeout=10)
    t_curve.join(timeout=10)
    summary = host.close()
    sampler.close(final={"multitenant": summary["multitenant"],
                         **({"admission": summary["admission"]}
                            if "admission" in summary else {})})

    # -- per-arm verdict -----------------------------------------------
    answered = shed = breaches = 0
    lat: list = []
    for _, e2e_ms, data in results:
        if data.get("shed") or data.get("error"):
            shed += 1
            breaches += 1
            continue
        answered += 1
        lat.append(e2e_ms)
        if e2e_ms > objective_ms:
            breaches += 1
    lat.sort()
    mt = summary["multitenant"]
    arm = {
        "sent": len(results), "answered": answered, "shed": shed,
        "breaches": breaches,
        "breach_ratio": (round(breaches / len(results), 4)
                         if results else None),
        "e2e_p50_ms": (round(lat[len(lat) // 2], 2) if lat else None),
        "e2e_p99_ms": (round(lat[min(len(lat) - 1,
                                     int(len(lat) * 0.99))], 2)
                       if lat else None),
        "events": {n: summary["tenants"][n]["events"]
                   for n in summary["tenants"]},
        "folded_batches": {n: summary["tenants"][n]["folded_batches"]
                           for n in summary["tenants"]},
        "dropped_batches": {n: summary["tenants"][n]["dropped_batches"]
                            for n in summary["tenants"]},
        "drained": drained,
        "blame": {"tenants": mt["tenants"], "matrix_ms": mt["matrix_ms"],
                  "wait_ms": mt["wait_ms"], "busy_ms": mt["busy_ms"],
                  "offdiag_ratio": mt["offdiag_ratio"]},
        "partition": mt["partition"],
        "slo": summary["tenants"]["gamma"].get("slo"),
        "curve": curve,
        "metrics_dir": arm_dir,
    }
    if "admission" in summary:
        arm["admission"] = summary["admission"]
        arm["decisions"] = [
            {k: d.get(k) for k in
             ("decision", "tenant", "victim", "burn", "blame_ms",
              "step", "released", "escalated") if k in d}
            for d in host.admission.decisions]
    return arm


# ----------------------------------------------------------------------

def run_multitenant(workdir: str, *, seed: int = 19,
                    duration_s: float = 14.0, crowd=(4.0, 10.0),
                    tail_s: float = 60.0, steady_n: int = 150,
                    crowd_n: int = 8000, qps: float = 20.0,
                    objective_ms: int = OBJECTIVE_P99_MS,
                    smoke: bool = False) -> dict:
    from streambench_tpu.config import default_config

    cfg = default_config(jax_batch_size=1024)
    campaigns, mapping, src = make_world(seed)
    sched = make_schedule(src, duration_s=duration_s, crowd=crowd,
                          steady_n=steady_n, crowd_n=crowd_n, seed=seed)
    queries = make_queries(campaigns, duration_s=duration_s, qps=qps,
                           seed=seed)
    crowd_batches = sum(1 for _, n, _l in sched if n == "beta")
    log(f"schedule: {len(sched)} batches "
        f"({sum(len(l) for _, _n, l in sched)} events, "
        f"beta {crowd_batches} batches), {len(queries)} queries, "
        f"crowd {crowd[0]}-{crowd[1]}s of {duration_s}s")

    off = run_arm(False, workdir, cfg, mapping, campaigns, sched,
                  queries, duration_s=duration_s, tail_s=tail_s,
                  objective_ms=objective_ms, seed=seed)
    log(f"off arm: breach_ratio {off['breach_ratio']} "
        f"(p99 {off['e2e_p99_ms']} ms), "
        f"offdiag {off['blame']['offdiag_ratio']}, "
        f"gamma blame row {off['blame']['matrix_ms'].get('gamma')}, "
        f"wait {off['blame']['wait_ms']}")
    on = run_arm(True, workdir, cfg, mapping, campaigns, sched,
                 queries, duration_s=duration_s, tail_s=tail_s,
                 objective_ms=objective_ms, seed=seed)
    log(f"on arm: breach_ratio {on['breach_ratio']} "
        f"(p99 {on['e2e_p99_ms']} ms), "
        f"admission {on.get('admission', {}).get('defers')} defers / "
        f"{on.get('admission', {}).get('releases')} releases")

    out = {
        "phase": "multitenant", "seed": seed,
        "duration_s": duration_s, "crowd_s": list(crowd),
        "objective_p99_ms": objective_ms, "qps": qps,
        "steady_n": steady_n, "crowd_n": crowd_n,
        "off": off, "on": on,
        "victim_breach_ratio_off": off["breach_ratio"],
        "victim_breach_ratio_on": on["breach_ratio"],
        "blame_offdiag_ratio": off["blame"]["offdiag_ratio"],
        "decisions": on.get("decisions", []),
        "caveat": "1-core host: device-queue, GIL and timeslice "
                  "interference are measured together; the blame "
                  "matrix intersects measured waits with measured "
                  "busy windows, whatever the mechanism",
    }

    # -- gates ----------------------------------------------------------
    for arm_name, arm in (("off", off), ("on", on)):
        assert arm["partition"]["ok"], (arm_name, arm["partition"])
        assert arm["drained"], (arm_name, "undrained queues")
        assert arm["answered"] + arm["shed"] == arm["sent"], arm
    # same bytes folded in both arms: deferral defers, never loses
    assert off["events"] == on["events"], (off["events"], on["events"])
    # the off arm's ledger must still NAME the aggressor (diagnosis
    # works without actuation): beta's column dominates gamma's row
    g_row = off["blame"]["matrix_ms"]["gamma"]
    assert g_row["beta"] > 0, off["blame"]
    assert g_row["beta"] >= g_row["alpha"], off["blame"]
    # at least one defer decision carrying the blame evidence
    defers = [d for d in out["decisions"]
              if d["decision"] == "defer"]
    assert defers, out["decisions"]
    assert defers[0]["tenant"] == "beta", defers[0]
    assert defers[0]["victim"] == "gamma", defers[0]
    assert defers[0]["blame_ms"] > 0, defers[0]
    assert on["admission"]["batches_deferred"] > 0, on["admission"]
    if smoke:
        # soft gate: the ON arm must not be WORSE; CI asserts the
        # decision + partition evidence, not the timing-dependent ratio
        assert off["breach_ratio"] is not None
        assert on["breach_ratio"] <= off["breach_ratio"], \
            (on["breach_ratio"], off["breach_ratio"])
    else:
        assert off["breach_ratio"] is not None \
            and off["breach_ratio"] >= 0.15, off["breach_ratio"]
        assert on["breach_ratio"] is not None \
            and on["breach_ratio"] < 0.5 * off["breach_ratio"], \
            (on["breach_ratio"], off["breach_ratio"])
    out["ok"] = True
    return out


def _compact(mt: dict) -> dict:
    return {
        "phase": mt["phase"], "ok": mt.get("ok"),
        "objective_p99_ms": mt["objective_p99_ms"],
        "crowd_s": mt["crowd_s"],
        "breach_ratio_off": mt["victim_breach_ratio_off"],
        "breach_ratio_on": mt["victim_breach_ratio_on"],
        "e2e_p99_ms": [mt["off"]["e2e_p99_ms"], mt["on"]["e2e_p99_ms"]],
        "blame_offdiag_ratio": mt["blame_offdiag_ratio"],
        "decisions": mt["decisions"],
        "admission": {k: mt["on"]["admission"][k]
                      for k in ("defers", "sheds", "releases", "holds",
                                "batches_deferred", "batches_shed")},
        "partition_ok": [mt["off"]["partition"]["ok"],
                         mt["on"]["partition"]["ok"]],
        "caveat": mt["caveat"],
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="CI: short crowd, soft breach-ratio gate")
    ap.add_argument("--out", default="bench_multitenant.json")
    ap.add_argument("--workdir", default="")
    args = ap.parse_args()
    budget_s = float(os.environ.get("STREAMBENCH_BENCH_BUDGET_S", "840"))

    import tempfile
    workdir = args.workdir or tempfile.mkdtemp(prefix="bench-mten-")
    os.makedirs(workdir, exist_ok=True)

    import jax
    doc: dict = {
        "schema": "MTEN", "smoke": bool(args.smoke),
        "backend": jax.default_backend(),
        "devices": jax.device_count(),
        "cpus": os.cpu_count(),
        "budget_s": budget_s,
    }

    if args.smoke:
        mt = run_multitenant(workdir, duration_s=8.0, crowd=(2.0, 6.0),
                             tail_s=20.0, smoke=True)
    else:
        mt = run_multitenant(workdir)
    doc["multitenant"] = mt
    print(compact_line(_compact(mt)), flush=True)
    log(f"multitenant ok: breach ratio "
        f"{mt['victim_breach_ratio_off']} -> "
        f"{mt['victim_breach_ratio_on']} across the flash crowd, "
        f"{len(mt['decisions'])} decisions, blame offdiag "
        f"{mt['blame_offdiag_ratio']}")

    doc["ok"] = bool(mt.get("ok"))
    doc["wall_s"] = round(time.monotonic() - _T0, 1)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
    log(f"wrote {args.out} ({doc['wall_s']}s)")
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
