#!/usr/bin/env python3
"""Reach-serving bench: materialize MinHash∪HLL sketches from a journal,
verify them against exact set arithmetic, then drive a concurrent
query storm through the pub/sub serving surface (ISSUE 10).

Three rungs, each emitting a compact (<= 4096 B) single-line JSON on
stdout (the PR 6 truncation-proof contract) with the full detail in the
``--out`` artifact:

- **small** — low cardinality (hundreds of devices/campaign): the
  device-materialized ``[C, k]``/``[C, R]`` planes must be BIT-EXACT
  equal to the numpy sketches computed from the oracle's exact
  per-campaign id sets (dedup/order invariance of the streamed fold),
  and every query's integer collision count must match the numpy
  evaluation exactly — the "oracle-exact at small cardinality" leg.
- **large** — >= 100k distinct devices: measured relative error vs
  exact set arithmetic must sit inside the theoretical bounds
  (union: 2·1.04/sqrt(R); overlap, relative to the union size:
  1/sqrt(k) + 1.04/sqrt(R) — ~6.25% + HLL term at k=256).
- **storm** — >= 1k concurrent queries through PubSubServer ->
  ReachQueryServer: all queries are admitted while the server holds,
  then the drain must take <= ceil(Q/batch) dispatches (batched
  evaluation, never one dispatch per query), with served/shed/p99 in
  the compact line.  A second, depth-starved server proves shed-oldest
  under overload (shed + served == sent, shed > 0).
- **attribution** (ISSUE 11) — the storm re-run with the query-path
  observability on (jax.obs.query + spans) and a CONCURRENT ingest
  thread re-folding the journal: every query's submit -> reply latency
  decomposes into queue/batch/dispatch/reply segments whose p50s sum
  to within 10% of the e2e p50, shed + answered queries each leave
  exactly one lifecycle record reconciling with
  ``streambench_reach_shed_total``, the perfetto trace validates with
  BOTH ingest and query lanes, and
  ``streambench_reach_contention_ratio`` measures the fraction of
  query queue-wait spent behind ingest dispatches.

Budget: self-caps at ``STREAMBENCH_BENCH_BUDGET_S`` (default 840 s <
the 870 s driver kill); the large rung is skipped (recorded, never
silent) when the envelope runs out.

Usage:
    python bench_reach.py                       # full, writes bench_reach.json
    python bench_reach.py --smoke               # CI: small + tiny storm
    python bench_reach.py --out REACH_r01.json
"""

from __future__ import annotations

import argparse
import json
import math
import os
import random
import sys
import threading
import time

import numpy as np

COMPACT_LINE_MAX = 4096
REPO = os.path.dirname(os.path.abspath(__file__))
_T0 = time.monotonic()


def log(msg: str) -> None:
    print(f"[{time.monotonic() - _T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def compact_line(obj: dict) -> str:
    """One bounded stdout line: shed detail until it fits."""
    def dump(o):
        return json.dumps(o, separators=(",", ":"))

    line = dump(obj)
    if len(line) <= COMPACT_LINE_MAX:
        return line
    obj = json.loads(line)
    for strip in ("per_query", "errors", "params", "host"):
        obj.pop(strip, None)
        line = dump(obj)
        if len(line) <= COMPACT_LINE_MAX:
            return line
    return dump({k: obj[k] for k in ("phase", "ok") if k in obj})


# ----------------------------------------------------------------------
# materialize: journal -> engine -> sketch planes
# ----------------------------------------------------------------------

def make_world(workdir: str, *, campaigns_n: int, users_n: int,
               events_n: int, seed: int):
    """Generator-shaped journal with a custom device universe (the
    stock do_setup pins 100 users; reach needs a configurable one)."""
    from streambench_tpu.datagen.gen import EventSource
    from streambench_tpu.utils.ids import make_ids

    rng = random.Random(seed)
    campaigns = make_ids(campaigns_n, rng)
    ads = make_ids(campaigns_n * 10, rng)
    mapping = {}
    for i, c in enumerate(campaigns):
        for a in ads[i * 10:(i + 1) * 10]:
            mapping[a] = c
    src = EventSource(ads=ads, user_ids=make_ids(users_n, rng),
                      page_ids=make_ids(100, rng), rng=rng)
    path = os.path.join(workdir, "reach-journal.txt")
    start = 1_700_000_000_000
    with open(path, "wb") as f:
        batch = 100_000
        for base in range(0, events_n, batch):
            hi = min(base + batch, events_n)
            ts = start + 10 * np.arange(base, hi, dtype=np.int64)
            blob = src.events_blob_at(ts)
            if blob is not None:
                f.write(blob)
            else:
                f.write(b"".join(src.event_at(int(t)).encode() + b"\n"
                                 for t in ts))
    return campaigns, mapping, path


def materialize(path: str, mapping: dict, campaigns: list, *,
                k: int, registers: int, batch: int = 8192, mesh=None):
    """Fold the journal through a ReachSketchEngine (block ingest where
    the native encoder is built, line fallback otherwise), or through
    the campaign-sharded ShardedReachEngine when ``mesh`` is given."""
    from streambench_tpu.config import default_config
    from streambench_tpu.engine.sketches import ReachSketchEngine

    cfg = default_config(jax_num_campaigns=len(campaigns),
                         jax_batch_size=batch)
    if mesh is not None:
        from streambench_tpu.parallel.reach import ShardedReachEngine

        eng = ShardedReachEngine(cfg, mapping, mesh,
                                 campaigns=campaigns, redis=None,
                                 k=k, registers=registers)
    else:
        eng = ReachSketchEngine(cfg, mapping, campaigns=campaigns,
                                redis=None, k=k, registers=registers)
    eng.warmup()
    t0 = time.monotonic()
    with open(path, "rb") as f:
        carry = b""
        while True:
            data = f.read(4 << 20)
            if not data:
                break
            data = carry + data
            nl = data.rfind(b"\n") + 1
            carry = data[nl:]
            eng.process_block(data[:nl])
        if carry:
            eng.process_block(carry + b"\n")
    eng.flush(final=True)
    wall = time.monotonic() - t0
    return eng, wall


def oracle_world(path: str, mapping: dict, campaigns: list):
    from streambench_tpu.reach import oracle as ro

    with open(path, "rb") as f:
        return ro.campaign_user_sets(f, mapping, campaigns)


# ----------------------------------------------------------------------
# query workloads
# ----------------------------------------------------------------------

def make_queries(campaigns: list, n: int, seed: int):
    rng = np.random.default_rng(seed)
    C = len(campaigns)
    masks = np.zeros((n, C), bool)
    overlap = np.zeros(n, bool)
    for i in range(n):
        m = int(rng.integers(1, 6))
        masks[i, rng.choice(C, size=min(m, C), replace=False)] = True
        overlap[i] = bool(rng.integers(0, 2))
    return masks, overlap


def error_stats(est, masks, overlap, sets, campaigns, *, k, R):
    """Measured relative errors vs exact set arithmetic (union relative
    to truth; overlap relative to the union size — the Jaccard
    estimator's natural scale)."""
    from streambench_tpu.reach import oracle as ro

    u_err, o_err = [], []
    for i in range(masks.shape[0]):
        sel = [campaigns[j] for j in range(masks.shape[1]) if masks[i, j]]
        op = "overlap" if overlap[i] else "union"
        truth, true_union = ro.exact_counts(sets, sel, op)
        if overlap[i]:
            o_err.append(abs(float(est[i]) - truth) / max(true_union, 1))
        else:
            u_err.append(abs(float(est[i]) - truth) / max(truth, 1))
    return (dict(mean=float(np.mean(u_err)), max=float(np.max(u_err)),
                 n=len(u_err)),
            dict(mean=float(np.mean(o_err)), max=float(np.max(o_err)),
                 n=len(o_err)))


# ----------------------------------------------------------------------
# rungs
# ----------------------------------------------------------------------

def run_verify(workdir: str, *, name: str, campaigns_n: int, users_n: int,
               events_n: int, k: int, registers: int, queries_n: int,
               seed: int, bitexact: bool) -> dict:
    from streambench_tpu.reach import oracle as ro
    from streambench_tpu.reach import query as rq

    campaigns, mapping, path = make_world(
        workdir, campaigns_n=campaigns_n, users_n=users_n,
        events_n=events_n, seed=seed)
    eng, mat_wall = materialize(path, mapping, campaigns,
                                k=k, registers=registers)
    names = list(eng.encoder.campaigns)
    sets = oracle_world(path, mapping, names)
    distinct = len(set().union(*sets.values())) if sets else 0
    out = {"phase": name, "events": eng.events_processed,
           "distinct_devices": distinct, "k": k, "registers": registers,
           "materialize_s": round(mat_wall, 2),
           "materialize_ev_s": int(eng.events_processed
                                   / max(mat_wall, 1e-9))}
    assert eng.events_processed == events_n, (eng.events_processed,
                                              events_n)
    if bitexact:
        em, er = ro.expected_state(sets, names, k, registers)
        assert (np.asarray(eng.state.mins) == em).all(), \
            "device mins != set-arithmetic oracle sketch"
        assert (np.asarray(eng.state.registers) == er).all(), \
            "device registers != set-arithmetic oracle sketch"
        out["sketch_bitexact"] = True
    masks, overlap = make_queries(names, queries_n, seed + 1)
    counter = rq.DispatchCounter()
    est, union, jacc, agree = rq.query_chunks(
        eng.state.mins, eng.state.registers, masks, overlap,
        counter=counter)
    out["queries"] = queries_n
    out["query_dispatches"] = counter.dispatches
    assert counter.dispatches == math.ceil(queries_n / rq.DEFAULT_BATCH)
    if bitexact:
        oa = ro.query_oracle_np(np.asarray(eng.state.mins),
                                np.asarray(eng.state.registers), masks)
        assert (agree == oa).all(), "device collision counts != oracle"
        out["queries_bitexact"] = True
        out["oracle"] = "exact"
    u_err, o_err = error_stats(est, masks, overlap, sets, names,
                               k=k, R=registers)
    ub = 2 * 1.04 / math.sqrt(registers)
    ob = 1.0 / math.sqrt(k) + 1.04 / math.sqrt(registers)
    out["union_rel_err"] = {**u_err, "bound": round(ub, 4)}
    out["overlap_rel_err_vs_union"] = {**o_err, "bound": round(ob, 4)}
    if name == "large":
        assert distinct >= 100_000, distinct
        assert u_err["mean"] <= ub, (u_err, ub)
        assert o_err["mean"] <= ob, (o_err, ob)
        out["error_within_bounds"] = True
    out["ok"] = True
    return out, eng, names, sets, path


def run_storm(eng, names, *, queries_n: int, clients: int, depth: int,
              batch: int, expect_shed: bool, phase: str) -> dict:
    from streambench_tpu.dimensions.pubsub import PubSubClient, PubSubServer
    from streambench_tpu.reach.serve import ReachQueryServer

    srv = ReachQueryServer(names, depth=depth, batch=batch, hold=True)
    eng.attach_reach(srv)
    ps = PubSubServer(port=0).start()
    ps.register_query("reach", srv.handle)
    host, port = ps.address
    per = queries_n // clients
    results: list = [None] * clients
    rng = np.random.default_rng(1234)
    picks = [
        [list(rng.choice(len(names), size=int(rng.integers(1, 5)),
                         replace=False)) for _ in range(per)]
        for _ in range(clients)]

    def run_client(ci: int) -> None:
        c = PubSubClient(host, port, timeout_s=120)
        t0s = {}
        for qi, sel in enumerate(picks[ci]):
            qid = ci * per + qi
            t0s[qid] = time.monotonic()
            c.request({"type": "reach",
                       "campaigns": [names[j] for j in sel],
                       "op": "overlap" if qid % 2 else "union",
                       "id": qid})
        got = []
        for _ in range(per):
            m = c.recv()["data"]
            got.append((m, time.monotonic() - t0s.get(m.get("id"), _T0)))
        results[ci] = got
        c.close()

    threads = [threading.Thread(target=run_client, args=(ci,))
               for ci in range(clients)]
    t_sub = time.monotonic()
    for t in threads:
        t.start()
    # every query admitted (or shed) before the drain starts: the
    # dispatch-count acceptance is about BATCHED evaluation of a
    # standing backlog of concurrent queries
    deadline = time.monotonic() + 120
    want_pending = queries_n if not expect_shed else depth
    while (srv.pending() < want_pending
           and srv.pending() + srv.shed < queries_n
           and time.monotonic() < deadline):
        time.sleep(0.01)
    submit_s = time.monotonic() - t_sub
    t_drain = time.monotonic()
    srv.resume()
    for t in threads:
        t.join(timeout=120)
    drain_s = time.monotonic() - t_drain
    summary = srv.summary()
    ps.close()
    srv.close()
    answers = [m for got in results if got for m, _ in got]
    assert len(answers) == clients * per, (len(answers), clients * per)
    served = [m for m in answers if "estimate" in m]
    shed = [m for m in answers if m.get("shed")]
    assert len(served) == summary["served"]
    assert len(served) + len(shed) == clients * per
    out = {"phase": phase, "sent": clients * per, "clients": clients,
           "served": summary["served"], "shed": summary["shed"],
           "dispatches": summary["dispatches"], "batch": batch,
           "queue_depth": depth,
           "submit_s": round(submit_s, 2),
           "drain_s": round(drain_s, 2),
           "p50_ms": summary.get("p50_ms"),
           "p99_ms": summary.get("p99_ms"),
           "qps": round(summary["served"] / max(drain_s, 1e-9), 1)}
    if expect_shed:
        assert summary["shed"] > 0, summary
    else:
        assert summary["shed"] == 0, summary
        assert summary["served"] == clients * per
        # the acceptance number: a standing storm of Q queries drains
        # in at most ceil(Q/batch) dispatches, never one per query
        assert summary["dispatches"] <= math.ceil(
            (clients * per) / batch), summary
        assert all(m["epoch"] == eng.reach_epoch for m in served)
    out["ok"] = True
    return out


def run_attribution(eng, names, journal_path: str, workdir: str, *,
                    queries_n: int, gap_s: float, depth: int,
                    batch: int, shed_burst: int, slo_ms: int = 250,
                    ingest_gap_s: float = 0.01,
                    phase: str = "attribution") -> dict:
    """The ISSUE 11 rung: a paced pub/sub query storm with query-path
    observability ON, concurrent with an ingest thread re-folding the
    journal (idempotent for cumulative sketches — the served state
    never changes, only device occupancy does), followed by a shed
    burst for the reconciliation check.

    The ingest side is PACED (``ingest_gap_s`` between block folds):
    on this 1-core host an unthrottled re-fold loop saturates both the
    interpreter and the device queue and the query worker starves
    outright — the ratio would measure GIL starvation, not device
    contention.  Paced, each query's queue wait genuinely overlaps
    some ingest dispatches and the ratio reads as designed."""
    import jax

    from streambench_tpu.dimensions.pubsub import PubSubClient, PubSubServer
    from streambench_tpu.obs import MetricsRegistry, SpanTracer
    from streambench_tpu.obs.queryattr import SEGMENTS, QueryLifecycle
    from streambench_tpu.obs.spans import validate_chrome_trace
    from streambench_tpu.reach.serve import ReachQueryServer

    reg = MetricsRegistry()
    spans = SpanTracer(capacity=16384, registry=reg)
    old_sink = eng.tracer.sink
    spans.attach(eng.tracer)         # ingest folds -> the shared ring
    ql = QueryLifecycle(reg, slo_ms=slo_ms, slowlog_max=64, spans=spans)
    srv = ReachQueryServer(names, depth=depth, batch=batch,
                           registry=reg, queryattr=ql, spans=spans)
    eng.attach_reach(srv)
    ps = PubSubServer(port=0).start()
    ps.register_query("reach", srv.handle)
    host, port = ps.address

    ingest_stop = threading.Event()
    folded = {"events": 0}

    def ingest() -> None:
        # re-fold the journal in a loop: real device dispatches (real
        # contention for the query worker) with idempotent state.
        # block_until_ready after each block is the backpressure the
        # runner's flush path provides in production — without it the
        # async dispatch stream outruns the device without bound and
        # query waits grow with the backlog instead of measuring it
        while not ingest_stop.is_set():
            with open(journal_path, "rb") as f:
                carry = b""
                while not ingest_stop.is_set():
                    data = f.read(256 << 10)
                    if not data:
                        break
                    data = carry + data
                    nl = data.rfind(b"\n") + 1
                    carry = data[nl:]
                    eng.process_block(data[:nl])
                    # the fold-sync window is the measured
                    # device-busy evidence the contention ratio
                    # intersects query queue-waits with
                    t_d = time.perf_counter_ns()
                    jax.block_until_ready(eng.state.mins)
                    ql.note_ingest_busy(t_d, time.perf_counter_ns())
                    folded["events"] = eng.events_processed
                    time.sleep(ingest_gap_s)

    rng = np.random.default_rng(4321)
    answers: list = []
    splits: list = []

    def storm() -> None:
        c = PubSubClient(host, port, timeout_s=120)
        pending = 0
        for qi in range(queries_n):
            sel = [names[j] for j in rng.choice(
                len(names), size=int(rng.integers(1, 5)),
                replace=False)]
            c.request({"type": "reach", "campaigns": sel,
                       "op": "overlap" if qi % 2 else "union",
                       "id": qi, "trace": f"bench-{qi}",
                       "sent_ms": int(time.time() * 1000)})
            pending += 1
            # paced, but bounded in flight so a slow drain never
            # deadlocks the blocking client against its own sends
            while pending > 64:
                d = c.recv()["data"]
                answers.append(d)
                s = c.latency_split(d)
                if s is not None:
                    splits.append(s)
                pending -= 1
            time.sleep(gap_s)
        for _ in range(pending):
            d = c.recv()["data"]
            answers.append(d)
            s = c.latency_split(d)
            if s is not None:
                splits.append(s)
        c.close()

    t_ing = threading.Thread(target=ingest, daemon=True)
    t_storm = threading.Thread(target=storm)
    t0 = time.monotonic()
    t_ing.start()
    t_storm.start()
    t_storm.join(timeout=300)
    ingest_stop.set()
    t_ing.join(timeout=60)
    storm_s = time.monotonic() - t0
    assert not t_storm.is_alive(), "attribution storm never finished"
    assert len(answers) == queries_n, (len(answers), queries_n)
    assert all("estimate" in d or d.get("shed") for d in answers)
    served_storm = sum("estimate" in d for d in answers)

    # shed burst: overload a held server so shed lifecycle records and
    # the shed counter must reconcile exactly
    srv.pause()
    got_burst: list = []
    for qi in range(shed_burst):
        srv.submit([names[qi % len(names)]], "union",
                   lambda d: got_burst.append(d), query_id=f"b{qi}")
    srv.resume()
    deadline = time.monotonic() + 120
    while len(got_burst) < shed_burst and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(got_burst) == shed_burst
    jax.block_until_ready(eng.state.mins)
    srv.close()
    summary = srv.summary()
    ps.close()
    eng.tracer.sink = old_sink

    qsum = ql.summary()
    # --- reconciliation: every query leaves exactly ONE lifecycle
    # record, and shed records == the Prometheus shed counter ---------
    shed_counter = int(reg.counter("streambench_reach_shed_total").value)
    assert qsum["shed_records"] == summary["shed"] == shed_counter, (
        qsum["shed_records"], summary["shed"], shed_counter)
    assert qsum["served_records"] == summary["served"], (
        qsum["served_records"], summary["served"])
    assert qsum["served_records"] + qsum["shed_records"] == (
        queries_n + shed_burst), qsum
    assert summary["shed"] > 0, "shed burst produced no sheds"

    # --- segment partition: p50s sum to ~the e2e p50 -----------------
    segs = {seg: qsum["segments"][seg] for seg in SEGMENTS}
    p50_sum = sum(s.get("p50", 0.0) for s in segs.values())
    e2e_p50 = qsum["e2e_ms"].get("p50", 0.0)
    seg_sum_ratio = p50_sum / e2e_p50 if e2e_p50 else 0.0
    # exact-sum check (no bucket error): segment sums total the e2e sum
    sum_exact = sum(s.get("sum", 0.0) for s in segs.values())
    assert abs(sum_exact - qsum["e2e_ms"]["sum"]) <= max(
        1e-6 * qsum["e2e_ms"]["sum"], 5e-3), (sum_exact, qsum["e2e_ms"])
    assert abs(seg_sum_ratio - 1.0) <= 0.10, (
        f"segment p50 sum {p50_sum:.3f} vs e2e p50 {e2e_p50:.3f} "
        f"({seg_sum_ratio:.3f})")

    # --- perfetto trace: both lanes on one clock ---------------------
    trace_path = os.path.join(workdir, "trace_reach_attr.json")
    spans.dump(trace_path, run="bench-reach-attribution")
    doc = json.load(open(trace_path))
    problems = validate_chrome_trace(doc)
    assert problems == [], problems
    cats = {e.get("cat") for e in doc["traceEvents"]
            if e.get("ph") == "X"}
    assert "query" in cats and "stage" in cats, cats

    cont = qsum["contention"]
    net = sorted(s.get("network_ms", 0.0) for s in splits)
    srvms = sorted(s.get("server_ms", 0.0) for s in splits)
    out = {
        "phase": phase, "queries": queries_n, "shed_burst": shed_burst,
        "served": summary["served"], "shed": summary["shed"],
        "served_storm": served_storm,
        "dispatches": summary["dispatches"],
        "storm_s": round(storm_s, 2),
        "ingest_events_folded": folded["events"],
        "segments": {seg: {"p50": round(s.get("p50", 0.0), 3),
                           "p99": round(s.get("p99", 0.0), 3),
                           "count": s.get("count", 0)}
                     for seg, s in segs.items()},
        "e2e_ms": {k: (round(v, 3) if isinstance(v, float) else v)
                   for k, v in qsum["e2e_ms"].items()},
        "seg_sum_ratio": round(seg_sum_ratio, 4),
        "segment_sum_exact": True,
        "shed_reconciled": True,
        "contention_ratio": cont["ratio"],
        "contention": {"queue_wait_ms": cont["queue_wait_ms"],
                       "ingest_overlap_ms": cont["ingest_overlap_ms"]},
        "slow_queries": qsum["slow_queries"],
        "slo_ms": slo_ms,
        "client_split": {
            "n": len(splits),
            "server_p50_ms": round(srvms[len(srvms) // 2], 3)
            if srvms else None,
            "network_p50_ms": round(net[len(net) // 2], 3)
            if net else None,
        },
        "trace": {"path": os.path.basename(trace_path),
                  "events": len(doc["traceEvents"]),
                  "lanes": sorted(c for c in cats if c)},
        "ok": True,
    }
    return out


# ----------------------------------------------------------------------
# ISSUE 14 scale-out rungs
# ----------------------------------------------------------------------

def run_sharded_child(n: int) -> int:
    """Child of ``--sharded-rung N`` (the parent pinned the virtual
    device count in XLA_FLAGS before this process imported jax): fold
    one journal through the single-device AND campaign-sharded reach
    engines, assert plane + query bit-identity, and read the collective
    table out of the compiled query program — the "exactly 2 cross-
    shard collectives per query dispatch" acceptance."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from streambench_tpu.parallel import collectives
    from streambench_tpu.parallel.mesh import build_mesh
    from streambench_tpu.reach import query as rq

    workdir = tempfile.mkdtemp(prefix=f"bench-reach-shard{n}-")
    assert jax.device_count() >= n, (jax.device_count(), n)
    k, registers = 128, 256
    campaigns, mapping, path = make_world(
        workdir, campaigns_n=40, users_n=3000, events_n=60_000, seed=29)
    ref, ref_wall = materialize(path, mapping, campaigns,
                                k=k, registers=registers)
    names = list(ref.encoder.campaigns)
    mesh = build_mesh(data=1, campaign=n)
    eng, wall = materialize(path, mapping, campaigns,
                            k=k, registers=registers, mesh=mesh)
    host = eng.host_state()
    assert (host.mins == np.asarray(ref.state.mins)).all(), \
        "sharded mins != single-device"
    assert (host.registers == np.asarray(ref.state.registers)).all(), \
        "sharded registers != single-device"

    masks, overlap = make_queries(names, 256, 31)
    e0, u0, j0, a0 = rq.query_chunks(ref.state.mins, ref.state.registers,
                                     masks, overlap)
    e1, u1, j1, a1 = eng.batch_query(masks, overlap)
    assert (a0 == a1).all(), "sharded agree counts != single-device"
    assert (e0 == e1).all(), "sharded estimates != single-device"

    report = eng.collective_report(query_batch=256)
    q = report["query"]["per_dispatch"]
    if n > 1:
        assert q["ops"] == 2, q
        assert q["by_kind"] == {"all-reduce": 2}, q

    # timed query dispatch, both arms (virtual-mesh caveat applies)
    def timed(fn, reps=5):
        ts = []
        for _ in range(reps):
            t0 = time.monotonic()
            jax.block_until_ready(fn())
            ts.append((time.monotonic() - t0) * 1000)
        return round(min(ts), 2)

    mq = jnp.asarray(masks)
    oq = jnp.asarray(overlap)
    single_ms = timed(lambda: rq.batch_query(
        ref.state.mins, ref.state.registers, mq, oq))
    sharded_ms = timed(lambda: eng.batch_query(masks, overlap)[0])

    out = {
        "phase": f"sharded_n{n}", "devices": n,
        "events": eng.events_processed,
        "oracle": "bit-identical planes + queries vs single-device",
        "bitexact": True,
        "materialize_ev_s": int(eng.events_processed / max(wall, 1e-9)),
        "single_ev_s": int(ref.events_processed / max(ref_wall, 1e-9)),
        "query_collectives": {
            "per_dispatch_ops": q["ops"],
            "per_dispatch_bytes": q["bytes"],
            "by_kind": q["by_kind"],
        },
        "scan_collectives": {
            "per_dispatch_ops":
                report["scan"]["per_dispatch"]["ops"],
            "per_dispatch_bytes":
                report["scan"]["per_dispatch"]["bytes"],
        },
        "query_ms_256": {"single": single_ms, "sharded": sharded_ms},
        "ok": True,
    }
    print(compact_line(out), flush=True)
    return 0


def run_sharded_rungs(deadline: float) -> dict:
    """Parent side: one subprocess per device count (XLA_FLAGS must be
    pinned before jax import — the bench_multichip rule)."""
    import re
    import subprocess

    out: dict = {}
    for n in (1, 2, 8):
        if time.monotonic() > deadline - 120:
            out[f"n{n}"] = {"skipped": "budget"}
            log(f"sharded n={n} skipped: budget")
            continue
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       "", env.get("XLA_FLAGS", ""))
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--sharded-rung", str(n)],
                env=env, capture_output=True, text=True,
                timeout=max(deadline - time.monotonic(), 60))
        except subprocess.TimeoutExpired:
            out[f"n{n}"] = {"error": "timeout"}
            continue
        line = next((ln for ln in
                     reversed(proc.stdout.strip().splitlines())
                     if ln.startswith("{")), None)
        if proc.returncode != 0 or line is None:
            out[f"n{n}"] = {"error": "child failed", "rc": proc.returncode,
                            "tail": proc.stderr[-500:]}
            continue
        out[f"n{n}"] = json.loads(line)
        print(compact_line(out[f"n{n}"]), flush=True)
        log(f"sharded n={n} ok: "
            f"{out[f'n{n}']['query_collectives']['per_dispatch_ops']} "
            f"collectives/query dispatch")
    out["ok"] = all((out.get(f"n{n}") or {}).get("ok")
                    for n in (1, 2, 8))
    return out


def run_cache_ab(eng, names, *, distinct: int = 48, repeats: int = 8,
                 phase: str = "cache_ab") -> dict:
    """Cache on/off A/B on a repeated-query mix, in-process against the
    writer-attached server (the measured quantity is the server-side
    submit -> reply latency — the layer the cache removes).  Fill phase
    answers each distinct set once (all misses), then the repeated mix
    storms the standing cache.  Acceptance: cache-hit p99 at least 10x
    below the cache-miss p99."""
    import threading

    from streambench_tpu.obs import MetricsRegistry
    from streambench_tpu.reach.cache import ReachQueryCache
    from streambench_tpu.reach.serve import ReachQueryServer

    rng = np.random.default_rng(77)
    qsets = []
    for _ in range(distinct):
        sel = [names[j] for j in rng.choice(
            len(names), size=int(rng.integers(1, 5)), replace=False)]
        qsets.append((sel, "overlap" if rng.integers(0, 2) else "union"))
    mix = [qsets[i % distinct] for i in range(distinct * repeats)]
    rng.shuffle(mix)

    arms: dict = {}
    for arm in ("on", "off"):
        reg = MetricsRegistry()
        cache = (ReachQueryCache(4096, registry=reg)
                 if arm == "on" else None)
        srv = ReachQueryServer(names, depth=8192, batch=64,
                               registry=reg, cache=cache)
        eng.attach_reach(srv)
        lock = threading.Lock()
        lats: list = []

        def submit_wave(wave):
            pending = threading.Event()
            want = len(wave)
            for sel, op in wave:
                t0 = time.perf_counter_ns()

                def cb(d, t0=t0):
                    with lock:
                        lats.append(
                            ((time.perf_counter_ns() - t0) / 1e6,
                             bool(d.get("cached")), d))
                        if len(lats) >= want0 + want:
                            pending.set()
                srv.submit(sel, op, cb)
            pending.wait(timeout=120)

        want0 = 0
        t_fill = time.monotonic()
        submit_wave(qsets)                       # fill: all misses
        fill_s = time.monotonic() - t_fill
        want0 = len(lats)
        t_mix = time.monotonic()
        submit_wave(mix)                         # repeated mix
        mix_s = time.monotonic() - t_mix
        srv.close()
        assert len(lats) == distinct + len(mix), (len(lats), arm)
        fill_lats = sorted(v for v, _, _ in lats[:distinct])
        mix_rows = lats[distinct:]
        hit_lats = sorted(v for v, c, _ in mix_rows if c)
        miss_lats = sorted([v for v, c, _ in mix_rows if not c]
                           or fill_lats)

        def p(q, xs):
            return round(xs[min(len(xs) - 1, int(len(xs) * q))], 3) \
                if xs else None

        arms[arm] = {
            "queries": distinct + len(mix),
            "fill_s": round(fill_s, 2), "mix_s": round(mix_s, 3),
            "mix_qps": int(len(mix) / max(mix_s, 1e-9)),
            "hits": len(hit_lats),
            "hit_p50_ms": p(0.5, hit_lats), "hit_p99_ms": p(0.99, hit_lats),
            "miss_p50_ms": p(0.5, miss_lats),
            "miss_p99_ms": p(0.99, miss_lats),
            "dispatches": srv.dispatches,
        }
        if cache is not None:
            arms[arm]["cache"] = cache.summary()
            # the repeated mix must be all hits: the fill answered every
            # distinct set and nothing was evicted or invalidated
            assert len(hit_lats) == len(mix), (len(hit_lats), len(mix))
            assert all("estimate" in d for _, _, d in mix_rows)
        else:
            assert not hit_lats

    on = arms["on"]
    ratio = (on["miss_p99_ms"] / on["hit_p99_ms"]
             if on["hit_p99_ms"] else None)
    out = {"phase": phase, "distinct_sets": distinct,
           "repeats": repeats, "arms": arms,
           "hit_ratio": arms["on"]["cache"]["hit_ratio"],
           "miss_over_hit_p99": round(ratio, 1) if ratio else None,
           "speedup_qps": round(
               on["mix_qps"] / max(arms["off"]["mix_qps"], 1), 2)}
    assert ratio is not None and ratio >= 10.0, (
        f"cache-hit p99 {on['hit_p99_ms']} not >= 10x below miss p99 "
        f"{on['miss_p99_ms']}")
    out["hit_p99_10x_below_miss"] = True
    out["ok"] = True
    return out


def _merge_intervals(raw: list) -> list:
    merged: list = []
    for s, e in sorted(raw):
        if merged and s <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], e)
        else:
            merged.append([s, e])
    return merged


def _overlap_ns(lo: int, hi: int, merged: list) -> int:
    total = 0
    for s, e in merged:
        if e <= lo:
            continue
        if s >= hi:
            break
        total += min(hi, e) - max(lo, s)
    return total


def run_replica_scaleout(eng, names, journal_path: str, workdir: str, *,
                         replica_counts=(1, 2), queries_n: int = 240,
                         gap_s: float = 0.004, ship_ms: int = 400,
                         ingest_gap_s: float = 0.6,
                         phase: str = "replica_scaleout") -> dict:
    """The off-writer serving rung: the writer folds ingest and ships
    snapshots; R replica PROCESSES tail the log and answer a storm.

    Measured headlines (the 1-core-honest set): the off-writer
    contention ratio (replica queue-waits intersected with the writer's
    measured fold-sync windows over the shared CLOCK_MONOTONIC — the
    REACH_r02 writer-attached baseline read 0.61 at ~30% ingest duty),
    reply staleness vs the shipping cadence, cache behavior at the
    replicas, and shed + served == sent with every reply epoch-stamped.
    The throughput-vs-replicas table is recorded but the scaling CLAIM
    is gated on cpu count: replica processes timeslice one core here.

    The ingest pacing matches the baseline's ~30% duty cycle (the
    comparison is only meaningful at matched duty): writer-attached,
    queue waits CORRELATE with ingest busy (0.61 ≈ 2x the duty —
    queries literally queue behind folds); off-writer they can only
    overlap by timeslicing coincidence, so the ratio collapses toward
    the duty floor.  Both the measured duty and the ratio/duty
    correlation land in the artifact so the claim is auditable.
    """
    import signal
    import subprocess
    import threading

    from streambench_tpu.dimensions.pubsub import PubSubClient, PubSubServer
    from streambench_tpu.dimensions.store import DurableDimensionStore
    from streambench_tpu.reach.replica import SnapshotShipper

    import jax

    ship_dir = os.path.join(workdir, "ship")
    store = DurableDimensionStore(ship_dir)
    # fleet freshness (ISSUE 15): stamped records + a live writer
    # origin endpoint so the replicas' clock-offset estimate runs the
    # real ping path; replicas launch with --fleet and their replies
    # carry the hop decomposition the artifact summarizes
    origin_ps = PubSubServer(port=0).start()
    o_host, o_port = origin_ps.address
    shipper = SnapshotShipper(store, names, interval_ms=ship_ms,
                              origin={"addr": f"{o_host}:{o_port}",
                                      "pid": os.getpid(),
                                      "role": "writer"})
    eng.attach_shipper(shipper)

    ingest_stop = threading.Event()
    busy: list = []
    folded = {"events0": eng.events_processed, "events": 0, "wall": 0.0}

    def ingest() -> None:
        t_start = time.monotonic()
        while not ingest_stop.is_set():
            with open(journal_path, "rb") as f:
                carry = b""
                while not ingest_stop.is_set():
                    data = f.read(128 << 10)
                    if not data:
                        break
                    data = carry + data
                    nl = data.rfind(b"\n") + 1
                    carry = data[nl:]
                    eng.process_block(data[:nl])
                    t0 = time.monotonic_ns()
                    jax.block_until_ready(eng.state.mins)
                    busy.append((t0, time.monotonic_ns()))
                    eng.flush()      # push -> ship at cadence
                    folded["events"] = (eng.events_processed
                                        - folded["events0"])
                    folded["wall"] = time.monotonic() - t_start
                    time.sleep(ingest_gap_s)

    t_ing = threading.Thread(target=ingest, daemon=True)
    t_ing.start()

    ladder: dict = {}
    all_waits: list = []
    try:
        for n_rep in replica_counts:
            procs = []
            addrs = []
            for _ in range(n_rep):
                p = subprocess.Popen(
                    [sys.executable, "-m",
                     "streambench_tpu.reach.replica",
                     "--ship", ship_dir, "--poll-ms", "150",
                     "--batch", "64", "--dump-queue-waits",
                     "--fleet"],
                    env={**os.environ, "JAX_PLATFORMS": "cpu"},
                    cwd=REPO, stdout=subprocess.PIPE,
                    stderr=subprocess.DEVNULL, text=True)
                procs.append(p)
            for p in procs:
                line = p.stdout.readline()
                assert line.startswith("replica: pubsub="), line
                hp = line.split("pubsub=")[1].split()[0]
                host, port = hp.rsplit(":", 1)
                addrs.append((host, int(port)))
            log(f"{n_rep} replica(s) up: {addrs}")

            answers: list = [[] for _ in addrs]

            def storm(ci: int) -> None:
                host, port = addrs[ci]
                c = PubSubClient(host, port, timeout_s=120)
                # wait until this replica actually serves (first poll
                # must load a shipped record; shed-only replies mean
                # not ready — retry a few times, they COUNT as sheds
                # in the replica's ledger but not in this storm's)
                # fresh id per attempt: the server's request-id dedup
                # (ISSUE 16) silently drops an id it already answered
                for wi in range(100):
                    c.request({"type": "reach", "campaigns": [names[0]],
                               "op": "union", "id": f"warm{wi}"})
                    if "estimate" in c.recv()["data"]:
                        break
                    time.sleep(0.2)
                rng = np.random.default_rng(1000 + ci)
                pending = 0
                for qi in range(queries_n):
                    sel = [names[j] for j in rng.choice(
                        len(names), size=int(rng.integers(1, 4)),
                        replace=False)]
                    c.request({"type": "reach", "campaigns": sel,
                               "op": "overlap" if qi % 2 else "union",
                               "id": qi})
                    pending += 1
                    while pending > 32:
                        answers[ci].append(c.recv()["data"])
                        pending -= 1
                    time.sleep(gap_s)
                for _ in range(pending):
                    answers[ci].append(c.recv()["data"])
                c.close()

            t0 = time.monotonic()
            threads = [threading.Thread(target=storm, args=(ci,))
                       for ci in range(n_rep)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            storm_s = time.monotonic() - t0

            stats = []
            for p in procs:
                p.send_signal(signal.SIGTERM)
                out_tail, _ = p.communicate(timeout=60)
                line = next((ln for ln in
                             reversed(out_tail.strip().splitlines())
                             if ln.startswith("{")), "{}")
                stats.append(json.loads(line))

            flat = [d for got in answers for d in got]
            served = [d for d in flat if "estimate" in d]
            shed = [d for d in flat if d.get("shed")]
            assert len(served) + len(shed) == n_rep * queries_n, (
                len(served), len(shed))
            assert served, "replica storm served nothing"
            # every reply epoch-stamped; every served one staleness-
            # stamped and inside the replica staleness bound
            assert all("plane_epoch" in d for d in flat)
            stales = [d["staleness_ms"] for d in served]
            assert all(s <= 10_000 for s in stales), max(stales)
            # fleet freshness (ISSUE 15): every served reply carries
            # the hop decomposition and the hops sum to its staleness
            # within per-hop rounding (+-0.25 ms over four hops)
            for d in served:
                fr = d["freshness"]
                hop_sum = sum(fr[f"{h}_ms"] for h in
                              ("fold_lag", "ship_wait", "tail_lag",
                               "serve"))
                assert abs(hop_sum - fr["staleness_ms"]) <= 0.25, fr
                assert d["staleness_ms"] == fr["staleness_ms"]
            cache_hits = sum(
                ((s.get("serve") or {}).get("cache") or {}).get(
                    "hits", 0) for s in stats)
            for s in stats:
                waits = s.get("queue_waits_ns") or []
                all_waits.extend(waits)
            # per-hop p99s out of the replicas' exit summaries (worst
            # replica wins per hop — the fleet's honest tail)
            fresh_p99: dict = {}
            for s in stats:
                hops = (((s.get("serve") or {}).get("freshness") or {})
                        .get("hops") or {})
                for hop, summ in hops.items():
                    p99 = (summ or {}).get("p99")
                    if isinstance(p99, (int, float)):
                        fresh_p99[hop] = max(fresh_p99.get(hop, 0.0),
                                             round(float(p99), 1))
            clocks = [s.get("clock") for s in stats if s.get("clock")]
            stales_sorted = sorted(stales)
            ladder[f"r{n_rep}"] = {
                "freshness_p99_ms": fresh_p99,
                "clock_applied": all(c.get("applied") for c in clocks)
                if clocks else None,
                "replicas": n_rep,
                "sent": n_rep * queries_n,
                "served": len(served), "shed": len(shed),
                "qps": round(len(served) / max(storm_s, 1e-9), 1),
                "storm_s": round(storm_s, 2),
                "cache_hits": cache_hits,
                "staleness_p50_ms": stales_sorted[len(stales) // 2],
                "staleness_max_ms": stales_sorted[-1],
                "epoch_stamped": True,
                "ingest_events_folded": folded["events"],
            }
            log(f"replicas={n_rep}: qps {ladder[f'r{n_rep}']['qps']} "
                f"staleness p50 "
                f"{ladder[f'r{n_rep}']['staleness_p50_ms']} ms")
    finally:
        ingest_stop.set()
        t_ing.join(timeout=60)
        origin_ps.close()
        store.close()

    # off-writer contention: replica queue waits (their processes'
    # CLOCK_MONOTONIC) vs the writer's measured fold-sync windows
    merged_busy = _merge_intervals([list(b) for b in busy])
    wait_total = sum(max(b - a, 0) for a, b in all_waits)
    overlap = sum(_overlap_ns(a, b, merged_busy)
                  for a, b in all_waits if b > a)
    ratio = round(overlap / wait_total, 4) if wait_total else 0.0
    # writer busy duty over the measurement span: the coincidence
    # floor — off-writer, a queue wait can only overlap ingest busy by
    # timeslicing chance, so ratio ≈ duty; writer-attached the
    # baseline read ~2x its duty (waits queued BEHIND folds)
    busy_ns = sum(e - s for s, e in merged_busy)
    span_ns = (merged_busy[-1][1] - merged_busy[0][0]) if merged_busy \
        else 0
    duty = round(busy_ns / span_ns, 4) if span_ns else 0.0
    ingest_evps = int(folded["events"] / max(folded["wall"], 1e-9))
    # fleet freshness headline: worst per-hop p99 across the ladder
    fleet_fresh: dict = {}
    for rung in ladder.values():
        for hop, p99 in (rung.get("freshness_p99_ms") or {}).items():
            fleet_fresh[hop] = max(fleet_fresh.get(hop, 0.0), p99)
    out = {
        "phase": phase, "ladder": ladder,
        "freshness_p99_ms": fleet_fresh,
        "offwriter_contention_ratio": ratio,
        "writer_attached_baseline": 0.61,   # REACH_r02 @ ~30% duty
        "ingest_busy_duty": duty,
        "contention_over_duty": round(ratio / duty, 2) if duty else None,
        "queue_wait_ms": round(wait_total / 1e6, 1),
        "ingest_overlap_ms": round(overlap / 1e6, 1),
        "busy_windows": len(busy),
        "ingest_sustained_ev_s": ingest_evps,
        "ships": shipper.ships,
        "ship_interval_ms": ship_ms,
        "cpus": os.cpu_count(),
        "scaling_claim_gated": os.cpu_count() == 1,
        "note": ("replica processes timeslice 1 core: the qps ladder "
                 "is recorded, the scaling claim waits for real "
                 "silicon; the transferable wins are the off-writer "
                 "contention ratio (≈ the duty coincidence floor, vs "
                 "0.61 ≈ 2x duty writer-attached), bounded staleness, "
                 "and cache hits"
                 if os.cpu_count() == 1 else ""),
    }
    assert ratio < 0.61, (
        f"off-writer contention {ratio} not below the writer-attached "
        f"0.61 baseline (duty {duty})")
    out["below_writer_attached_baseline"] = True
    out["ok"] = True
    return out


def run_fleet_chaos(workdir: str, *, seed: int = 7, replicas_n: int = 2,
                    epochs_n: int = 14, queries_n: int = 160,
                    ship_gap_s: float = 0.4, gap_s: float = 0.02,
                    max_staleness_ms: int = 10_000,
                    phase: str = "fleet_chaos") -> dict:
    """The ISSUE 16 chaos rung: a routed replica fleet survives network
    + ship-log faults + crash-kills with VERIFIED shed-or-answer.

    Two arms off one deterministic plane sequence (seeded numpy, no
    engine — the invariants are about the serving fleet, not the fold):
    the CLEAN arm writes the full ship log upfront; the CHAOS arm
    writes it live at a cadence through the ship-fault hook while two
    in-process replicas (behind per-replica ``ChaosPubSub`` proxies
    sharing one injector) serve a router-fronted storm.  Mid-storm each
    replica is crash-killed once; the :class:`FleetSupervisor` respawns
    it at the SAME pinned port (the router's replica list stays valid)
    and the restart hook force-ships the writer's current planes.

    Verified invariants (chaos/verify.py, all hard gates on ``ok``):

    - ``sent == answered + shed`` by exact request id — the router
      never silently drops a query;
    - no answered reply served planes staler than the bound relative to
      what was DURABLE at submit time (driver and ship log share this
      host's clock);
    - post-heal the fleet converges on the writer's final epoch and the
      close-time reach record is bit-identical to the fault-free arm's
      — chaos may delay convergence, never change what is converged TO.

    Headline regress keys: ``router.failover_p99_ms`` (the cost of a
    failover episode) and ``router.shed_ratio`` (honesty is visible,
    not free) — both advisory, lower-is-better.
    """
    import socket
    import threading

    from streambench_tpu.chaos import (ChaosPubSub, FaultInjector,
                                       FaultPlan, FleetSupervisor,
                                       check_fleet_accounting,
                                       check_fleet_convergence,
                                       check_staleness_bound,
                                       ship_epoch_timeline)
    from streambench_tpu.dimensions.pubsub import PubSubClient
    from streambench_tpu.dimensions.store import (DurableDimensionStore,
                                                  LOG_NAME)
    from streambench_tpu.reach.replica import ReachReplica
    from streambench_tpu.reach.router import ReachRouter
    from streambench_tpu.utils.ids import now_ms

    camps = [f"fleet-c{i}" for i in range(8)]
    K, R = 64, 128

    def planes(epoch: int):
        rng = np.random.default_rng(seed * 1000 + epoch)
        mins = rng.integers(0, 1 << 32, size=(len(camps), K),
                            dtype=np.uint32)
        regs = rng.integers(0, 30, size=(len(camps), R)).astype(np.int32)
        return mins, regs

    # -- clean arm: the fault-free ship log, written upfront -----------
    clean_dir = os.path.join(workdir, "fleet_clean")
    clean_store = DurableDimensionStore(clean_dir)
    for e in range(1, epochs_n + 1):
        m, r = planes(e)
        clean_store.put_reach_sketches(m, r, camps, e, submit_ms=now_ms(),
                                       folded_ms=now_ms())
    clean_store.close()

    # -- chaos arm: live writer at a cadence through the fault hook ----
    # rates sized for the 1-core wall clock: every dropped request or
    # reply frame costs a full router-handle timeout, so the partition
    # window + drop rate dominate the rung's runtime, not its queries
    plan = FaultPlan.generate(
        seed, net_drop_rate=0.06, net_delay_rate=0.04, net_delay_ms=20,
        net_dup_rate=0.06, net_torn_rate=0.04, net_msgs=6000,
        partition_windows=((120, 30),),
        ship_rate=0.3, ship_ops=epochs_n)
    injector = FaultInjector(plan)
    chaos_dir = os.path.join(workdir, "fleet_chaos")
    chaos_store = DurableDimensionStore(chaos_dir)
    ship_filter = injector.attach_ship_chaos(chaos_store)
    chaos_log = os.path.join(chaos_dir, LOG_NAME)
    ship_lock = threading.Lock()
    last_epoch = {"e": 0}

    def ship(epoch: int) -> None:
        m, r = planes(epoch)
        with ship_lock:
            chaos_store.put_reach_sketches(
                m, r, camps, epoch, submit_ms=now_ms(),
                folded_ms=now_ms())
            last_epoch["e"] = max(last_epoch["e"], epoch)

    # boot ship OUTSIDE chaos (pre-storm state: the fleet must have
    # something intact to serve before adversity begins)
    chaos_store.ship_fault_hook = None
    ship(1)
    chaos_store.ship_fault_hook = ship_filter

    writer_stop = threading.Event()

    def writer() -> None:
        for e in range(2, epochs_n + 1):
            if writer_stop.is_set():
                return
            time.sleep(ship_gap_s)
            ship(e)

    t_writer = threading.Thread(target=writer, daemon=True)

    # -- the fleet: pinned-port replicas behind chaos proxies ----------
    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    rep_ports = [free_port() for _ in range(replicas_n)]
    reps: dict = {}
    proxies: list = []

    class _Handle:
        """In-process stand-in for a replica Popen: poll/kill close the
        live ReachReplica and sever its proxied connections (the wire
        view of a process death — ThreadingTCPServer handler threads
        would otherwise keep answering established sockets)."""

        def __init__(self, idx: int):
            self.idx = idx
            self.pid = os.getpid()
            self._code = None

        def poll(self):
            return self._code

        def kill(self):
            if self._code is not None:
                return
            rep = reps.pop(self.idx, None)
            if rep is not None:
                rep.close()
            proxies[self.idx].drop_conns()
            self._code = -9

        terminate = kill

    def spawn(idx: int, attempt: int):
        rep = ReachReplica(chaos_log, host="127.0.0.1",
                           port=rep_ports[idx], poll_ms=100,
                           max_staleness_ms=max_staleness_ms,
                           depth=256, batch=32).start()
        reps[idx] = rep
        return _Handle(idx)

    def on_restart(idx: int, attempt: int) -> None:
        # PR 15 restart-path forced ship: the respawned replica finds a
        # RECENT record instead of sitting shed-stale until the cadence
        if last_epoch["e"]:
            ship(last_epoch["e"])

    sup = FleetSupervisor(spawn, replicas_n, backoff_base_ms=40.0,
                          backoff_cap_ms=400.0, max_restarts=5,
                          healthy_after_s=0.3, seed=seed,
                          on_restart=on_restart,
                          counters=injector.counters).start()
    for idx in range(replicas_n):
        proxies.append(ChaosPubSub(("127.0.0.1", rep_ports[idx]),
                                   injector, name=f"-r{idx}").start())
    watch_stop = threading.Event()

    def watch() -> None:
        while not watch_stop.is_set():
            sup.step()
            time.sleep(0.05)

    t_watch = threading.Thread(target=watch, daemon=True)

    # timeout sized post-warm: the union/overlap kernels are compiled
    # during the direct warm-up below and the jit cache is process-wide
    # (respawned replicas reuse it), so a healthy reply is milliseconds
    # and 1.5 s is pure fault headroom
    router = ReachRouter([f"{h}:{p}" for h, p in
                          (pr.address for pr in proxies)],
                         timeout_s=1.5, retries=1).start()
    r_host, r_port = router.address

    sent_ids: list = []
    replies: list = []
    stamped: list = []      # (submit_ms, reply) for the staleness bound
    kill_at = {queries_n // 3: 0, (2 * queries_n) // 3: 1}
    rng = np.random.default_rng(seed)
    try:
        # warm DIRECT (off-proxy: no plan indices consumed; JAX compile
        # for these shapes is shared process-wide by the jit cache)
        for idx in range(replicas_n):
            wc = PubSubClient("127.0.0.1", rep_ports[idx], timeout_s=60)
            for wi in range(200):
                try:
                    d = wc.request({"type": "reach",
                                    "campaigns": [camps[0]],
                                    "op": "union",
                                    "id": f"warm{idx}-{wi}"},
                                   timeout_s=10.0)
                except (TimeoutError, ConnectionError, OSError):
                    time.sleep(0.1)
                    continue
                if "estimate" in d:
                    break
                time.sleep(0.1)
            wc.close()
        t_writer.start()
        t_watch.start()
        c = PubSubClient(r_host, r_port, timeout_s=120)
        t0 = time.monotonic()
        for qi in range(queries_n):
            idx = kill_at.get(qi)
            if idx is not None:
                sup.kill(idx)
                log(f"fleet chaos: crash-killed replica {idx} at "
                    f"query {qi}")
            sel = sorted(camps[j] for j in rng.choice(
                len(camps), size=int(rng.integers(1, 4)), replace=False))
            qid = f"fc{qi}"
            submit_ms = now_ms()
            # driver->router link is clean TCP: the router ALWAYS
            # terminates a query (answer, error, or honest shed), so no
            # driver-side retry — ids stay 1:1 for exact accounting
            data = c.request({"type": "reach", "campaigns": sel,
                              "op": "overlap" if qi % 3 == 0 else "union",
                              "id": qid}, timeout_s=60.0)
            sent_ids.append(qid)
            replies.append(data)
            stamped.append((submit_ms, data))
            time.sleep(gap_s)
        storm_s = time.monotonic() - t0
        c.close()
        t_writer.join(timeout=60)

        # -- heal: respawns settle, then the forced clean close ship ---
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            sup.step()
            if all(sup.alive(i) for i in range(replicas_n)):
                break
            time.sleep(0.05)
        # written twice: a trailing torn stub (no newline) would eat
        # exactly one following append; the plan is exhausted here so
        # the second copy is always intact
        ship(epochs_n)
        ship(epochs_n)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            eps = [getattr(reps.get(i), "server", None) and
                   reps[i].server.epoch for i in range(replicas_n)]
            if all(e == epochs_n for e in eps):
                break
            time.sleep(0.1)
        replica_epochs = [
            (reps[i].server.epoch
             if i in reps and reps[i].server is not None else None)
            for i in range(replicas_n)]
    finally:
        watch_stop.set()
        t_watch.join(timeout=10)
        writer_stop.set()
        router.close()
        for rep in list(reps.values()):
            rep.close()
        for pr in proxies:
            pr.close()
        chaos_store.close()

    # -- the verdict ---------------------------------------------------
    v = check_fleet_accounting(
        sent_ids, replies,
        repro=f"bench_reach.py run_fleet_chaos seed={seed}")
    check_staleness_bound(stamped, ship_epoch_timeline(chaos_log),
                          max_staleness_ms, verdict=v, slack_ms=50)
    check_fleet_convergence(chaos_log, replica_epochs,
                            clean_ship_path=os.path.join(clean_dir,
                                                         LOG_NAME),
                            verdict=v)
    log(v.summary())

    rt = router.summary()
    proxy_stats: dict = {}
    for pr in proxies:
        for k2, n in pr.stats.items():
            proxy_stats[k2] = proxy_stats.get(k2, 0) + n
    sup_sum = sup.summary()

    # per-role journals for `obs fleet` (ISSUE 16): the router row
    # (routed/failovers/shed_ratio sub-line) and the supervisor row
    # (restart events + net-fault counters) render from these exactly
    # like any live sampler journal; CI asserts on the table and ships
    # them as failure artifacts
    fleet_dir = os.path.join(workdir, "fleet_chaos")
    os.makedirs(fleet_dir, exist_ok=True)
    stamp = now_ms()
    with open(os.path.join(fleet_dir, "router_metrics.jsonl"), "w",
              encoding="utf-8") as f:
        f.write(json.dumps({"kind": "final", "role": "router",
                            "pid": os.getpid(), "ts_ms": stamp,
                            "router": rt}) + "\n")
    with open(os.path.join(fleet_dir, "supervisor_metrics.jsonl"), "w",
              encoding="utf-8") as f:
        for slot in sup_sum["replicas"]:
            for _ in range(slot["restarts"]):
                f.write(json.dumps(
                    {"kind": "event", "event": "replica_restart",
                     "role": "supervisor", "pid": os.getpid(),
                     "ts_ms": stamp, "idx": slot["idx"]}) + "\n")
        f.write(json.dumps({"kind": "final", "role": "supervisor",
                            "pid": os.getpid(), "ts_ms": stamp,
                            "faults": injector.counters.snapshot()})
                + "\n")

    out = {
        "phase": phase, "seed": seed, "replicas": replicas_n,
        "epochs": epochs_n,
        "sent": v.sent, "answered": v.answered, "shed": v.shed,
        "accounting_exact": not (v.duplicate_ids or v.missing_ids
                                 or v.unexpected_ids),
        "stale_violations": len(v.stale_violations),
        "max_staleness_ms": max_staleness_ms,
        "lagging_replicas": v.lagging_replicas,
        "bit_identical_final": not v.divergent,
        "writer_epoch": v.writer_epoch,
        "storm_s": round(storm_s, 2),
        "router": {k2: rt.get(k2) for k2 in
                   ("routed", "answered", "shed", "failovers",
                    "shed_ratio", "failover_p50_ms", "failover_p99_ms",
                    "qps")},
        "proxy": proxy_stats,
        "supervisor": {"restarts": sup_sum["restarts"],
                       "kills": sup_sum["kills"],
                       "gave_up": sup_sum["gave_up"]},
        "faults": injector.counters.snapshot(),
    }
    assert out["accounting_exact"], v.summary()
    assert out["stale_violations"] == 0, v.stale_violations[:5]
    assert not v.lagging_replicas and not v.divergent, v.summary()
    assert sup_sum["restarts"] >= 2, sup_sum
    assert rt.get("failovers", 0) >= 1 and "failover_p99_ms" in rt, rt
    out["ok"] = v.ok
    return out


# ----------------------------------------------------------------------
# ISSUE 17: the SLO autopilot rung
# ----------------------------------------------------------------------

def qps_ramp_schedule(*, seed: int, duration_s: float, qps0: float,
                      qps1: float, ramp=(0.2, 0.7),
                      burst_rate_hz: float = 0.5,
                      burst_n: int = 8) -> list:
    """Seeded ramp/burst arrival offsets (ISSUE 17 satellite; the
    ROADMAP 4(a) load shape scoped to the query side): a Poisson
    arrival process whose rate ramps piecewise-linearly ``qps0 ->
    qps1`` between the ``ramp`` fractions of the run, plus Poisson
    bursts (``burst_rate_hz`` expected bursts/s, each landing
    ``burst_n`` simultaneous arrivals).  Deterministic under the run
    seed — both bench arms replay the identical schedule."""
    rng = np.random.default_rng(seed)
    lo, hi = ramp
    t, out = 0.0, []
    while True:
        frac = min(t / duration_s, 1.0)
        if frac <= lo:
            rate = qps0
        elif frac >= hi:
            rate = qps1
        else:
            rate = qps0 + (qps1 - qps0) * (frac - lo) / (hi - lo)
        t += float(rng.exponential(1.0 / rate))
        if t >= duration_s:
            return out
        out.append(round(t, 4))
        # per-arrival burst draw with probability burst_rate_hz/rate
        # => bursts arrive ~Poisson(burst_rate_hz) per second at any
        # ramp position, independent of the base rate
        if float(rng.random()) < burst_rate_hz / rate:
            out.extend([round(t, 4)] * (burst_n - 1))


def run_autoscale(workdir: str, *, seed: int = 13,
                  duration_s: float = 14.0, tail_s: float = 7.0,
                  qps0: float = 6.0, qps1: float = 36.0,
                  burst_n: int = 24, camps_n: int = 16,
                  K: int = 8192, R: int = 4096,
                  ship0_ms: int = 2000, objective_staleness_ms: int = 1000,
                  objective_p99_ms: int = 15, max_replicas: int = 3,
                  clients: int = 16, phase: str = "autoscale") -> dict:
    """The ISSUE 17 tentpole proof: a seeded >=5x QPS ramp (with
    Poisson bursts) against the replica fleet, two arms off the SAME
    schedule.

    The OFF arm is the fleet as configured: one replica, a lazy
    2 s ship cadence — replies breach the staleness objective between
    ships and ramp bursts overrun the depth-2 queue into honest
    overloaded sheds.  The ON arm runs :class:`AutoscaleController`
    on a 250 ms cadence over LIVE fleet evidence: the staleness breach
    diagnoses ``fold_lag`` (the age sits upstream of the tailer) and
    halves the ship cadence; overloaded sheds diagnose ``serve`` and
    grow the fleet through ``FleetSupervisor.spawn()`` +
    ``router.add_replica`` (sheds become failover redirects); the
    post-ramp idle goes healthy and gracefully retires a replica
    (deregister -> drain -> stop).  Every decision carries the
    freshness-hop p99 evidence that justified it; the controller
    journal + per-role finals render the ``obs fleet`` controller
    sub-line, and the shared SpanTracer puts the whole episode on one
    ``obs trace --merge`` timeline.

    Headline regress keys (advisory): ``autoscale.breach_ratio_on``
    (lower) and ``autoscale.decisions`` (higher).
    """
    import socket

    from streambench_tpu.chaos import FleetSupervisor
    from streambench_tpu.dimensions.pubsub import PubSubClient
    from streambench_tpu.dimensions.store import (DurableDimensionStore,
                                                  LOG_NAME)
    from streambench_tpu.obs import (AutoscaleController, FlightRecorder,
                                     MetricsRegistry, MetricsSampler,
                                     SpanTracer)
    from streambench_tpu.reach.replica import ReachReplica, SnapshotShipper
    from streambench_tpu.reach.router import ReachRouter
    from streambench_tpu.utils.ids import now_ms

    camps = [f"as-c{i}" for i in range(camps_n)]
    rng0 = np.random.default_rng(seed * 1000)
    mins0 = rng0.integers(0, 1 << 32, size=(len(camps), K),
                          dtype=np.uint32)
    regs0 = rng0.integers(0, 30, size=(len(camps), R)).astype(np.int32)
    objective = {"staleness_ms": objective_staleness_ms,
                 "p99_ms": objective_p99_ms}
    schedule = qps_ramp_schedule(seed=seed, duration_s=duration_s,
                                 qps0=qps0, qps1=qps1,
                                 burst_n=burst_n)
    qrng = np.random.default_rng(seed + 1)
    qsets = [sorted(camps[j] for j in qrng.choice(
        len(camps), size=int(qrng.integers(2, 7)), replace=False))
        for _ in range(len(schedule))]
    fleet_dir = os.path.join(workdir, "autoscale_fleet")
    os.makedirs(fleet_dir, exist_ok=True)

    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def _arm(on: bool) -> dict:
        arm_dir = os.path.join(workdir,
                               f"autoscale_{'on' if on else 'off'}")
        store = DurableDimensionStore(arm_dir)
        ship_log = os.path.join(arm_dir, LOG_NAME)
        shipper = SnapshotShipper(store, camps, interval_ms=ship0_ms)
        ship_lock = threading.Lock()
        folds = {"n": 0}
        writer_stop = threading.Event()

        def fold_tick(force: bool = False) -> None:
            # epoch stays 1 throughout: due()'s epoch-bump bypass must
            # not defeat the cadence the controller is tuning
            with ship_lock:
                folds["n"] += 1
                shipper.note_state(mins0, regs0, 1,
                                   watermark=folds["n"], force=force,
                                   folded_ms=now_ms())

        def writer() -> None:
            while not writer_stop.is_set():
                fold_tick()
                writer_stop.wait(0.2)

        rep_ports = [free_port() for _ in range(max_replicas)]
        reps: dict = {}
        tracer = SpanTracer(capacity=8192) if on else None

        class _Handle:
            """In-process replica-Popen stand-in (the fleet-chaos
            idiom): poll/kill/terminate close the live ReachReplica."""

            def __init__(self, idx: int):
                self.idx = idx
                self.pid = os.getpid()
                self._code = None

            def poll(self):
                return self._code

            def _stop(self, code: int) -> None:
                if self._code is not None:
                    return
                rep = reps.pop(self.idx, None)
                if rep is not None:
                    rep.close()
                self._code = code

            def kill(self):
                self._stop(-9)

            def terminate(self):
                self._stop(0)

        def spawn(idx: int, attempt: int):
            # cache OFF: the campaign-set mix repeats under a pinned
            # epoch, and the result cache would absorb the whole ramp
            # from the admission path — this rung loads the DISPATCH
            # path (serving capacity), which is what replica count buys
            rep = ReachReplica(ship_log, host="127.0.0.1",
                               port=rep_ports[idx], poll_ms=100,
                               max_staleness_ms=30_000,
                               cache_capacity=0, depth=2,
                               batch=2, fleet=True,
                               spans=tracer).start()
            reps[idx] = rep
            return _Handle(idx)

        fold_tick(force=True)   # boot record: replicas load at start
        sup = FleetSupervisor(spawn, 1, backoff_base_ms=40.0,
                              backoff_cap_ms=400.0,
                              healthy_after_s=0.3, seed=seed).start()
        router = ReachRouter([f"127.0.0.1:{rep_ports[0]}"],
                             timeout_s=5.0, retries=1).start()
        r_host, r_port = router.address

        # warm direct (compile is process-wide; ON-arm spawns reuse it)
        wc = PubSubClient("127.0.0.1", rep_ports[0], timeout_s=60)
        for wi in range(200):
            try:
                d = wc.request({"type": "reach", "campaigns": [camps[0]],
                                "op": "union",
                                "id": f"aswarm{int(on)}-{wi}"},
                               timeout_s=10.0)
            except (TimeoutError, ConnectionError, OSError):
                time.sleep(0.1)
                continue
            if "estimate" in d:
                break
            time.sleep(0.1)
        wc.close()

        ctrl = None
        sampler = None
        ctrl_stop = threading.Event()
        t_ctrl = None
        if on:
            registry = MetricsRegistry()
            ctrl_dir = os.path.join(fleet_dir, "controller")
            os.makedirs(ctrl_dir, exist_ok=True)
            sampler = MetricsSampler(
                os.path.join(ctrl_dir, "metrics.jsonl"),
                interval_ms=500, registry=registry, role="controller")
            flightrec = FlightRecorder(ctrl_dir)

            def collect():
                ts = now_ms()
                recs = []
                for idx, rep in list(reps.items()):
                    srv = rep.server
                    if srv is not None:
                        recs.append({"kind": "snapshot",
                                     "role": "replica",
                                     "pid": 1000 + idx, "ts_ms": ts,
                                     "reach_query": srv.summary()})
                recs.append({"kind": "snapshot", "role": "router",
                             "pid": os.getpid(), "ts_ms": ts,
                             "router": router.summary()})
                recs.append({"kind": "snapshot", "role": "writer",
                             "pid": os.getpid(), "ts_ms": ts,
                             "reach_ship": shipper.summary()})
                return recs

            def spawn_hook() -> bool:
                if len(sup.slots) >= len(rep_ports):
                    return False
                idx = sup.spawn()
                # force-ship so the newcomer loads a record within one
                # poll instead of shedding stale for a full cadence
                fold_tick(force=True)
                router.add_replica(f"127.0.0.1:{rep_ports[idx]}")
                return True

            def retire_hook() -> bool:
                for idx in range(len(sup.slots) - 1, 0, -1):
                    slot = sup.slots[idx]
                    if slot.retired or slot.gave_up \
                            or not sup.alive(idx):
                        continue
                    addr = f"127.0.0.1:{rep_ports[idx]}"
                    return sup.retire(
                        idx,
                        deregister=lambda i: router.remove_replica(addr),
                        drain_s=0.1, grace_s=2.0)
                return False

            ctrl = AutoscaleController(
                collect, objective=objective,
                spawn_replica=spawn_hook, retire_replica=retire_hook,
                shipper=shipper, min_ship_interval_ms=400,
                replicas=1, min_replicas=1, max_replicas=max_replicas,
                breach_ticks=2, healthy_ticks=4, cooldown_s=1.5,
                window_steps=6, sampler=sampler, flightrec=flightrec,
                registry=registry)

            def ctrl_loop() -> None:
                while not ctrl_stop.is_set():
                    with tracer.span("autoscale_step", cat="autoscale"):
                        dec = ctrl.step()
                    if dec is not None:
                        with tracer.span(
                                f"autoscale_{dec['decision']}",
                                cat="autoscale"):
                            pass
                        log(f"autoscale: {dec['decision']} "
                            f"[{dec['verdict']}->{dec['knob']}] "
                            f"replicas={dec['replicas']}")
                    ctrl_stop.wait(0.25)

            t_ctrl = threading.Thread(target=ctrl_loop, daemon=True)
            sampler.add_collector(
                lambda rec, dt_s: rec.__setitem__("autoscale",
                                                  ctrl.summary()))
            sampler.start()

        # curve sampler: both arms record the same shape
        curve: list = []
        curve_stop = threading.Event()
        t0_box = {"t": None}

        def curve_loop() -> None:
            while not curve_stop.is_set():
                t0 = t0_box["t"]
                stale = None
                for rep in list(reps.values()):
                    srv = rep.server
                    if srv is not None:
                        s2 = srv.summary().get("staleness_ms")
                        if isinstance(s2, (int, float)):
                            stale = max(stale or 0.0, float(s2))
                curve.append({
                    "t_s": (round(time.monotonic() - t0, 2)
                            if t0 else None),
                    "replicas": len(reps),
                    "staleness_ms": stale,
                    "routed": router.routed, "shed": router.shed,
                    "failovers": router.failovers,
                    "ship_interval_ms": shipper.interval_ms})
                curve_stop.wait(0.5)

        t_writer = threading.Thread(target=writer, daemon=True)
        t_curve = threading.Thread(target=curve_loop, daemon=True)
        results: list = []
        res_lock = threading.Lock()
        pos = {"i": 0}
        rep_finals: dict = {}
        try:
            t_writer.start()
            t_curve.start()
            if t_ctrl is not None:
                t_ctrl.start()
            t0 = time.monotonic()
            t0_box["t"] = t0

            def client_worker() -> None:
                c = PubSubClient(r_host, r_port, timeout_s=60)
                while True:
                    with res_lock:
                        i = pos["i"]
                        pos["i"] += 1
                    if i >= len(schedule):
                        break
                    wait = t0 + schedule[i] - time.monotonic()
                    if wait > 0:
                        time.sleep(wait)
                    submit = time.perf_counter()
                    try:
                        data = c.request(
                            {"type": "reach", "campaigns": qsets[i],
                             "op": "overlap" if i % 3 == 0 else "union",
                             "id": f"as{int(on)}-{i}"}, timeout_s=30.0)
                    except (TimeoutError, ConnectionError, OSError) as e:
                        data = {"error": f"transport:{e!r}"}
                    e2e_ms = (time.perf_counter() - submit) * 1000.0
                    with res_lock:
                        results.append((i, e2e_ms, data))
                c.close()

            workers = [threading.Thread(target=client_worker,
                                        daemon=True)
                       for _ in range(clients)]
            for w in workers:
                w.start()
            for w in workers:
                w.join(timeout=duration_s + 90)
            storm_s = time.monotonic() - t0
            # post-ramp tail: traffic stops, the writer keeps folding —
            # the ON arm's controller must go healthy and retire
            if on:
                deadline = time.monotonic() + tail_s
                while time.monotonic() < deadline:
                    if ctrl.actions.get("scale_down"):
                        break
                    time.sleep(0.2)
        finally:
            ctrl_stop.set()
            if t_ctrl is not None:
                t_ctrl.join(timeout=10)
            curve_stop.set()
            t_curve.join(timeout=10)
            writer_stop.set()
            t_writer.join(timeout=10)
            if sampler is not None:
                sampler.close(final={"autoscale": (ctrl.summary()
                                                   if ctrl else None)})
            for idx, rep in list(reps.items()):
                if rep.server is not None:
                    rep_finals[idx] = rep.server.summary()
            router.close()
            sup.stop(grace_s=2.0)
            for rep in list(reps.values()):
                rep.close()
            store.close()

        # -- per-arm verdict -------------------------------------------
        answered = shed = breaches = 0
        stale_breaches = lat_breaches = 0
        lat: list = []
        stales: list = []
        for _, e2e_ms, data in results:
            if data.get("shed"):
                shed += 1
                breaches += 1
                continue
            if data.get("error"):
                breaches += 1
                continue
            answered += 1
            lat.append(e2e_ms)
            st = data.get("staleness_ms")
            if isinstance(st, (int, float)):
                stales.append(float(st))
                if st > objective_staleness_ms:
                    stale_breaches += 1
                    breaches += 1
            # reported, NOT in breach_ratio: on a 1-core host replica
            # count cannot reduce burst latency (CPU timeslices — the
            # scaling_claim_gated caveat), so the held objective is
            # staleness; lat_breaches shows both arms suffer bursts
            # alike, which is the caveat made visible in the artifact
            if e2e_ms > objective_p99_ms:
                lat_breaches += 1
        stales.sort()
        lat.sort()
        rt = router.summary()
        arm = {
            "sent": len(results), "answered": answered, "shed": shed,
            "breaches": breaches, "stale_breaches": stale_breaches,
            "lat_breaches": lat_breaches,
            "breach_ratio": (round(breaches / len(results), 4)
                             if results else None),
            "staleness_p50_ms": (round(stales[len(stales) // 2], 1)
                                 if stales else None),
            "staleness_p99_ms": (round(stales[min(
                len(stales) - 1, int(len(stales) * 0.99))], 1)
                if stales else None),
            "e2e_p50_ms": (round(lat[len(lat) // 2], 2)
                           if lat else None),
            "e2e_p99_ms": (round(lat[min(len(lat) - 1,
                                         int(len(lat) * 0.99))], 2)
                           if lat else None),
            "storm_s": round(storm_s, 2),
            "router": {k: rt.get(k) for k in
                       ("routed", "answered", "shed", "failovers",
                        "shed_ratio", "failover_p99_ms",
                        "e2e_p50_ms", "e2e_p99_ms", "qps")},
            "ship_interval_final_ms": shipper.interval_ms,
            "ships": shipper.ships,
            "curve": curve,
        }
        if on:
            sup_sum = sup.summary()
            arm["controller"] = ctrl.summary()
            arm["decisions"] = [
                {k: d.get(k) for k in
                 ("decision", "verdict", "knob", "replicas", "step",
                  "from_ms", "to_ms", "evidence") if k in d}
                for d in ctrl.decisions]
            arm["replicas_max"] = len(sup.slots)
            arm["retired"] = sup_sum["retired"]
            arm["supervisor"] = {k: sup_sum[k] for k in
                                 ("restarts", "kills", "gave_up",
                                  "retired")}
            # per-role journals for `obs fleet` + CI artifacts (the
            # controller's own journal is live via its sampler)
            rdir = os.path.join(fleet_dir, "router")
            os.makedirs(rdir, exist_ok=True)
            stamp = now_ms()
            with open(os.path.join(rdir, "metrics.jsonl"), "w",
                      encoding="utf-8") as f:
                f.write(json.dumps({"kind": "final", "role": "router",
                                    "pid": os.getpid(),
                                    "ts_ms": stamp,
                                    "router": rt}) + "\n")
            for idx, ssum in rep_finals.items():
                rep_dir = os.path.join(fleet_dir, f"replica_{idx}")
                os.makedirs(rep_dir, exist_ok=True)
                with open(os.path.join(rep_dir, "metrics.jsonl"), "w",
                          encoding="utf-8") as f:
                    f.write(json.dumps({"kind": "final",
                                        "role": "replica",
                                        "pid": 1000 + idx,
                                        "ts_ms": stamp,
                                        "reach_query": ssum}) + "\n")
            tracer.dump(os.path.join(fleet_dir,
                                     "trace_controller.json"),
                        run="autoscale")
        return arm

    off = _arm(False)
    on = _arm(True)

    # replica finals were closed with their processes; journal the ON
    # arm's controller decision log + router final (written in _arm) —
    # the `obs fleet` table over fleet_dir is the CI assertion surface
    out = {
        "phase": phase, "seed": seed,
        "duration_s": duration_s, "qps0": qps0, "qps1": qps1,
        "ramp_x": round(qps1 / qps0, 1),
        "schedule_n": len(schedule),
        "objective": objective, "ship0_ms": ship0_ms,
        "off": off, "on": on,
        "breach_ratio_off": off["breach_ratio"],
        "breach_ratio_on": on["breach_ratio"],
        "decisions": on["controller"]["decisions"],
        "fleet_dir": fleet_dir,
    }
    if (os.cpu_count() or 1) <= 1:
        # REACH_r03 precedent: replica latency/qps gains timeslice on
        # 1 core (measured: burst p99 identical at 1 vs 3 replicas) —
        # the HELD objective is staleness (cadence actuation); the
        # p99 breach still proves the scale-up path end to end, and
        # lat_breaches lands in both arms to keep the gate visible
        out["caveat"] = "scaling_claim_gated: 1-core host, replica " \
                        "latency gains timeslice; held objective is " \
                        "staleness via ship-cadence actuation, " \
                        "scale-up path proven but not latency-credited"

    # hard gates: the OFF arm must visibly breach, the ON arm must hold
    assert off["breach_ratio"] is not None \
        and off["breach_ratio"] >= 0.15, off
    assert on["breach_ratio"] is not None \
        and on["breach_ratio"] < 0.5 * off["breach_ratio"], \
        (on["breach_ratio"], off["breach_ratio"])
    ctrl_sum = on["controller"]
    assert ctrl_sum["decisions"] >= 2, ctrl_sum
    assert ctrl_sum["scale_ups"] >= 1, ctrl_sum
    assert ctrl_sum["ship_tunes"] >= 1, ctrl_sum
    assert on["retired"] >= 1, on["supervisor"]
    assert on["replicas_max"] >= 2, on["replicas_max"]
    for d in on["decisions"]:
        ev = d.get("evidence") or {}
        assert ev.get("hop_p99_ms"), d
    for arm_d in (off, on):
        assert arm_d["answered"] + arm_d["shed"] == arm_d["sent"], arm_d
    out["ok"] = True
    return out


def run_deltaship(workdir: str, *, seed: int = 29,
                  ladder=(20_000, 200_000, 1_000_000),
                  k: int = 8, registers: int = 8, ticks: int = 8,
                  touches: int = 4000, cadence_ms: int = 150) -> dict:
    """ISSUE 18: full vs delta shipping over the SAME Zipf touch
    journal, per campaign-count rung.

    The engine path is infeasible at C=1M on this host (``make_world``
    would intern 10M ad-id strings), so the rung drives the shipper /
    chain-tailer surface directly: a seeded per-tick journal of
    Zipf-touched campaign rows is folded into writer planes (min/max —
    the exact merge algebra), then each arm ships at a paced cadence
    from its own store and a ChainTailer folds its log.  Measured per
    arm, steady state only (both arms ship one bootstrap base BEFORE
    the window — the delta arm is judged on its deltas, not amortized
    bases): ship bytes/tick, gather wall ms/tick (p50/p99), staleness
    at the matched cadence, and the tightest sustainable cadence
    (= ship wall p99 — an interval shorter than one ship can't hold).
    Exit checks: both tailer views bit-identical to the writer planes
    and to each other."""
    import hashlib
    import shutil

    from streambench_tpu.dimensions.store import (
        LOG_NAME,
        DurableDimensionStore,
    )
    from streambench_tpu.reach.deltaship import ChainTailer, DeltaShipper
    from streambench_tpu.reach.replica import SnapshotShipper

    EMPTY = np.uint32(0xFFFFFFFF)
    out: dict = {"phase": "deltaship", "k": k, "registers": registers,
                 "ticks": ticks, "touches": touches,
                 "cadence_ms": cadence_ms, "ladder": {}, "ok": False}

    def digest(mins, regs):
        h = hashlib.sha256()
        h.update(np.ascontiguousarray(mins, np.uint32).tobytes())
        h.update(np.ascontiguousarray(regs, np.int32).tobytes())
        return h.hexdigest()

    for C in ladder:
        rng = np.random.default_rng(seed + C)
        # per-tick touch journal, shared by both arms: Zipf-skewed row
        # picks (hot campaigns dominate — the realistic dirty set) +
        # the row values that fold into them
        journal = []
        for _ in range(ticks):
            idx = np.unique((rng.zipf(1.3, touches) - 1) % C).astype(
                np.int64)
            journal.append((
                idx,
                rng.integers(0, 2**32, (idx.size, k), dtype=np.uint32),
                rng.integers(0, 30, (idx.size, registers),
                             dtype=np.int32)))
        camps = [f"c{i:07d}" for i in range(C)]
        rung: dict = {"C": C, "touched_rows_mean": round(
            float(np.mean([j[0].size for j in journal])), 1)}
        views = {}
        for arm in ("full", "delta"):
            d = os.path.join(workdir, f"deltaship_{arm}_{C}")
            shutil.rmtree(d, ignore_errors=True)
            store = DurableDimensionStore(d)
            cls = DeltaShipper if arm == "delta" else SnapshotShipper
            ship = cls(store, camps, interval_ms=1)
            tail = ChainTailer(os.path.join(d, LOG_NAME))
            mins = np.full((C, k), EMPTY, np.uint32)
            regs = np.zeros((C, registers), np.int32)
            # bootstrap base OUTSIDE the measured window (both arms):
            # the delta arm's steady state is deltas between periodic
            # bases, and ticks << base_every would otherwise smear one
            # base across the mean
            ship.note_state(mins, regs, 1, watermark=0)
            tail.poll()
            bytes_t, ms_t, rows_t, stale_t = [], [], [], []
            wall0 = time.monotonic()
            for t, (idx, mrows, rrows) in enumerate(journal):
                sched = wall0 + (t + 1) * cadence_ms / 1000.0
                # no force (a forced ship is a BASE under delta mode —
                # the restart-path contract); the 2 ms floor keeps the
                # interval_ms=1 cadence gate deterministically open
                # even when an arm has fallen behind its schedule
                time.sleep(max(sched - time.monotonic(), 0.002))
                mins[idx] = np.minimum(mins[idx], mrows)
                regs[idx] = np.maximum(regs[idx], rrows)
                shipped = ship.note_state(
                    mins, regs, 1, watermark=t + 1,
                    dirty_rows=(idx if arm == "delta" else None))
                assert shipped
                tail.poll()
                # staleness the matched cadence actually delivers: how
                # far behind the tick's schedule the tailer's folded
                # view landed (a ship slower than the cadence pushes
                # every later tick further behind)
                stale_t.append((time.monotonic() - sched) * 1e3)
                bytes_t.append(ship.bytes_last)
                rows_t.append(ship.rows_last)
                ms_t.append(ship.ship_ms_last)
            view = tail.poll() or tail._view
            views[arm] = digest(view["mins"], view["registers"])
            ms_sorted = sorted(ms_t)
            p99 = ms_sorted[min(len(ms_sorted) - 1,
                                int(0.99 * len(ms_sorted)))]
            rung[arm] = {
                "bytes_per_tick": int(np.mean(bytes_t)),
                "rows_per_tick_mean": round(float(np.mean(rows_t)), 1),
                "ship_ms_p50": round(ms_sorted[len(ms_sorted) // 2], 3),
                "ship_ms_p99": round(p99, 3),
                "sustainable_cadence_ms": round(p99, 3),
                "staleness_p99_ms": round(sorted(stale_t)[
                    min(len(stale_t) - 1, int(0.99 * len(stale_t)))], 1),
                "log_bytes": os.path.getsize(os.path.join(d, LOG_NAME)),
                "ships": ship.ships,
                "bases": getattr(ship, "bases", ship.ships),
                "deltas": getattr(ship, "deltas", 0),
                "tailer": tail.stats(),
            }
            assert views[arm] == digest(mins, regs), \
                f"{arm} tailer view != writer planes at C={C}"
            store.close()
            shutil.rmtree(d, ignore_errors=True)
        # ISSUE 18's wire-format claim, checked per rung: the delta
        # arm must ship a FRACTION of the full arm's bytes while its
        # tailer lands on the bit-identical planes
        rung["bit_identical"] = views["full"] == views["delta"]
        rung["bytes_ratio"] = round(
            rung["full"]["bytes_per_tick"]
            / max(rung["delta"]["bytes_per_tick"], 1), 1)
        out["ladder"][f"c{C}"] = rung
        log(f"deltaship C={C}: bytes/tick {rung['full']['bytes_per_tick']}"
            f" -> {rung['delta']['bytes_per_tick']} "
            f"({rung['bytes_ratio']}x), ship p99 "
            f"{rung['full']['ship_ms_p99']} -> "
            f"{rung['delta']['ship_ms_p99']} ms, bit_identical "
            f"{rung['bit_identical']}")
        assert rung["bit_identical"], f"arm divergence at C={C}"
        assert rung["delta"]["deltas"] == ticks, rung["delta"]
        if C >= 500_000:
            # the acceptance rung: >= 10x fewer bytes, strictly
            # tighter sustainable cadence, no staleness giveback
            assert rung["bytes_ratio"] >= 10.0, rung["bytes_ratio"]
            assert (rung["delta"]["sustainable_cadence_ms"]
                    < rung["full"]["sustainable_cadence_ms"]), rung
            assert (rung["delta"]["staleness_p99_ms"]
                    <= rung["full"]["staleness_p99_ms"]), rung
    # regress keys come from the SMALLEST rung — present in smoke and
    # full artifacts alike, so CI's advisory compare is like-for-like
    first = out["ladder"][f"c{ladder[0]}"]
    out["ship_bytes_per_tick"] = first["delta"]["bytes_per_tick"]
    out["ship_ms_per_tick"] = first["delta"]["ship_ms_p99"]
    out["bytes_ratio"] = first["bytes_ratio"]
    out["ok"] = True
    return out


def _deltaship_compact(ds: dict) -> dict:
    """The rung's <= 4096 B stdout headline (full detail in --out)."""
    return {
        "phase": ds["phase"], "ok": ds.get("ok"),
        "cadence_ms": ds.get("cadence_ms"),
        "rungs": {
            name: {
                "bytes_ratio": r.get("bytes_ratio"),
                "bit_identical": r.get("bit_identical"),
                "full_bytes": (r.get("full") or {}).get("bytes_per_tick"),
                "delta_bytes": (r.get("delta") or {}).get(
                    "bytes_per_tick"),
                "full_ship_p99_ms": (r.get("full") or {}).get(
                    "ship_ms_p99"),
                "delta_ship_p99_ms": (r.get("delta") or {}).get(
                    "ship_ms_p99"),
                "full_stale_p99_ms": (r.get("full") or {}).get(
                    "staleness_p99_ms"),
                "delta_stale_p99_ms": (r.get("delta") or {}).get(
                    "staleness_p99_ms"),
            } for name, r in (ds.get("ladder") or {}).items()},
        **({"skipped": ds["skipped"]} if "skipped" in ds else {}),
    }


def _autoscale_compact(asc: dict) -> dict:
    """The rung's <= 4096 B stdout headline (full detail in --out)."""
    on, off = asc["on"], asc["off"]
    return {
        "phase": asc["phase"], "ok": asc.get("ok"),
        "ramp_x": asc["ramp_x"], "schedule_n": asc["schedule_n"],
        "objective": asc["objective"],
        "breach_ratio_off": asc["breach_ratio_off"],
        "breach_ratio_on": asc["breach_ratio_on"],
        "decisions": asc["decisions"],
        "controller": on["controller"],
        "replicas_max": on["replicas_max"], "retired": on["retired"],
        "off_router": off["router"], "on_router": on["router"],
        "ship_ms": [asc["ship0_ms"], on["ship_interval_final_ms"]],
        **({"caveat": asc["caveat"]} if "caveat" in asc else {}),
    }


# ----------------------------------------------------------------------

def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="CI: small rung + tiny storm only")
    ap.add_argument("--out", default="bench_reach.json")
    ap.add_argument("--workdir", default="")
    ap.add_argument("--sharded-rung", type=int, default=0,
                    help=argparse.SUPPRESS)  # child mode (ISSUE 14)
    args = ap.parse_args()
    if args.sharded_rung:
        return run_sharded_child(args.sharded_rung)
    budget_s = float(os.environ.get("STREAMBENCH_BENCH_BUDGET_S", "840"))
    deadline = _T0 + budget_s

    import tempfile
    workdir = args.workdir or tempfile.mkdtemp(prefix="bench-reach-")
    os.makedirs(workdir, exist_ok=True)

    import jax
    doc: dict = {
        "schema": "REACH", "smoke": bool(args.smoke),
        "backend": jax.default_backend(),
        "devices": jax.device_count(),
        "cpus": os.cpu_count(),
        "budget_s": budget_s,
    }
    ok = True

    # -- small rung: bit-exact vs exact set arithmetic ------------------
    small, eng_s, names_s, _, journal_s = run_verify(
        workdir, name="small", campaigns_n=40, users_n=500,
        events_n=50_000, k=256, registers=256, queries_n=256,
        seed=17, bitexact=True)
    doc["small"] = small
    print(compact_line(small), flush=True)
    log(f"small rung ok: bit-exact, {small['distinct_devices']} devices")

    # -- large rung + storm --------------------------------------------
    if args.smoke:
        storm = run_storm(eng_s, names_s, queries_n=60, clients=2,
                          depth=256, batch=32, expect_shed=False,
                          phase="storm")
        doc["storm"] = storm
        print(compact_line(storm), flush=True)
        shed = run_storm(eng_s, names_s, queries_n=120, clients=2,
                         depth=16, batch=16, expect_shed=True,
                         phase="shed")
        doc["shed"] = shed
        print(compact_line(shed), flush=True)
        attr = run_attribution(
            eng_s, names_s, journal_s, workdir, queries_n=120,
            gap_s=0.005, depth=128, batch=16, shed_burst=200)
        doc["attribution"] = attr
        print(compact_line(attr), flush=True)
        log(f"attribution ok: seg_sum_ratio {attr['seg_sum_ratio']} "
            f"contention {attr['contention_ratio']}")
        # repeats matches the full run's mix shape so the hit-ratio
        # regress row compares like against like (ratio = r/(r+1))
        cab = run_cache_ab(eng_s, names_s, distinct=16, repeats=8)
        doc["cache_ab"] = cab
        print(compact_line(cab), flush=True)
        log(f"cache A/B ok: miss/hit p99 {cab['miss_over_hit_p99']}x")
        fc = run_fleet_chaos(workdir, queries_n=60, epochs_n=10,
                             ship_gap_s=0.3)
        doc["fleet_chaos"] = fc
        print(compact_line(fc), flush=True)
        log(f"fleet chaos ok: {fc['answered']} answered + {fc['shed']} "
            f"shed == {fc['sent']} sent, "
            f"{fc['supervisor']['restarts']} restarts, failover p99 "
            f"{fc['router'].get('failover_p99_ms')} ms")
        asc = run_autoscale(workdir, duration_s=8.0, tail_s=8.0,
                            qps0=5.0, qps1=30.0)
        doc["autoscale"] = asc
        print(compact_line(_autoscale_compact(asc)), flush=True)
        log(f"autoscale ok: breach ratio {asc['breach_ratio_off']} -> "
            f"{asc['breach_ratio_on']} under a {asc['ramp_x']}x ramp, "
            f"{asc['decisions']} decisions, "
            f"{asc['on']['controller']['scale_ups']} scale-ups, "
            f"{asc['on']['retired']} retired")
        # ISSUE 18 delta-ship rung, smallest ladder step only: the
        # regress keys come from this rung in full mode too, so the
        # smoke artifact stays comparable against REACH_r07
        ds = run_deltaship(workdir, ladder=(20_000,))
        doc["deltaship"] = ds
        print(compact_line(_deltaship_compact(ds)), flush=True)
        log(f"deltaship ok: {ds['bytes_ratio']}x fewer bytes/tick at "
            f"C=20k, bit-identical replica planes")
    elif time.monotonic() > deadline - 120:
        doc["large"] = {"skipped": "budget"}
        doc["storm"] = {"skipped": "budget"}
        doc["attribution"] = {"skipped": "budget"}
        ok = False
        log("budget exhausted before the large rung — recorded, not silent")
    else:
        large, eng_l, names_l, _, journal_l = run_verify(
            workdir, name="large", campaigns_n=100, users_n=130_000,
            events_n=600_000, k=256, registers=1024, queries_n=512,
            seed=23, bitexact=True)
        doc["large"] = large
        print(compact_line(large), flush=True)
        log(f"large rung ok: {large['distinct_devices']} distinct devices, "
            f"union err {large['union_rel_err']['mean']:.4f} "
            f"overlap err {large['overlap_rel_err_vs_union']['mean']:.4f}")
        storm = run_storm(eng_l, names_l, queries_n=1200, clients=6,
                          depth=2048, batch=256, expect_shed=False,
                          phase="storm")
        assert storm["served"] >= 1000
        doc["storm"] = storm
        print(compact_line(storm), flush=True)
        log(f"storm ok: {storm['served']} served in "
            f"{storm['dispatches']} dispatches, p99 {storm['p99_ms']} ms")
        shed = run_storm(eng_l, names_l, queries_n=300, clients=2,
                         depth=64, batch=64, expect_shed=True,
                         phase="shed")
        doc["shed"] = shed
        print(compact_line(shed), flush=True)
        log(f"shed rung ok: {shed['shed']} shed of {shed['sent']}")
        # ISSUE 11: the storm re-run with query obs on + concurrent
        # ingest — segment decomposition, shed reconcile, contention
        # ingest_gap_s tuned to a ~30% duty cycle: this engine's
        # per-block fold+sync is ~110 ms (C=100, R=1024), and a
        # near-100% duty cycle makes the latency distribution bimodal
        # around the fold time — the p50-sum check then compares
        # medians across modes instead of decomposing the typical
        # path.  The ~9 folds the paced storm spans still put real
        # ingest-busy windows under the queue waits (the tail
        # dominates total wait, so the contention ratio stays
        # evidence-backed).
        attr = run_attribution(
            eng_l, names_l, journal_l, workdir, queries_n=400,
            gap_s=0.008, depth=128, batch=64, shed_burst=240,
            ingest_gap_s=0.25)
        doc["attribution"] = attr
        print(compact_line(attr), flush=True)
        log(f"attribution ok: seg_sum_ratio {attr['seg_sum_ratio']} "
            f"contention {attr['contention_ratio']} "
            f"({attr['ingest_events_folded']} ev folded concurrently)")
        # ---- ISSUE 14 scale-out rungs --------------------------------
        cab = run_cache_ab(eng_l, names_l)
        doc["cache_ab"] = cab
        print(compact_line(cab), flush=True)
        log(f"cache A/B ok: miss/hit p99 {cab['miss_over_hit_p99']}x, "
            f"hit ratio {cab['hit_ratio']}")
        doc["sharded"] = run_sharded_rungs(deadline)
        if time.monotonic() > deadline - 150:
            doc["replica_scaleout"] = {"skipped": "budget"}
            ok = False
            log("budget exhausted before the replica rung — recorded")
        else:
            rsc = run_replica_scaleout(eng_l, names_l, journal_l,
                                       workdir)
            doc["replica_scaleout"] = rsc
            print(compact_line(rsc), flush=True)
            log(f"replica rung ok: off-writer contention "
                f"{rsc['offwriter_contention_ratio']} "
                f"(writer-attached baseline 0.61)")
        # ---- ISSUE 16 fleet chaos rung -------------------------------
        if time.monotonic() > deadline - 60:
            doc["fleet_chaos"] = {"skipped": "budget"}
            ok = False
            log("budget exhausted before the fleet chaos rung — recorded")
        else:
            fc = run_fleet_chaos(workdir)
            doc["fleet_chaos"] = fc
            print(compact_line(fc), flush=True)
            log(f"fleet chaos ok: {fc['answered']} answered + "
                f"{fc['shed']} shed == {fc['sent']} sent, "
                f"{fc['supervisor']['restarts']} restarts, failover "
                f"p99 {fc['router'].get('failover_p99_ms')} ms, final "
                f"record bit-identical to the fault-free arm")
        # ---- ISSUE 17 SLO autopilot rung -----------------------------
        if time.monotonic() > deadline - 70:
            doc["autoscale"] = {"skipped": "budget"}
            ok = False
            log("budget exhausted before the autoscale rung — recorded")
        else:
            asc = run_autoscale(workdir)
            doc["autoscale"] = asc
            print(compact_line(_autoscale_compact(asc)), flush=True)
            log(f"autoscale ok: breach ratio {asc['breach_ratio_off']} "
                f"-> {asc['breach_ratio_on']} under a {asc['ramp_x']}x "
                f"ramp, {asc['decisions']} decisions, "
                f"{asc['on']['controller']['scale_ups']} scale-ups, "
                f"{asc['on']['retired']} retired")
        # ---- ISSUE 18 delta-ship C-ladder rung -----------------------
        if time.monotonic() > deadline - 45:
            doc["deltaship"] = {"skipped": "budget"}
            ok = False
            log("budget exhausted before the delta-ship rung — recorded")
        else:
            ds = run_deltaship(workdir)
            doc["deltaship"] = ds
            print(compact_line(_deltaship_compact(ds)), flush=True)
            top = ds["ladder"]["c1000000"]
            log(f"deltaship ok: {top['bytes_ratio']}x fewer bytes/tick "
                f"at C=1M (ship p99 {top['full']['ship_ms_p99']} -> "
                f"{top['delta']['ship_ms_p99']} ms), bit-identical "
                f"replica planes at every rung")

    # regress-gate keys (obs/regress.py normalize_bench reads doc.reach)
    storm_doc = doc.get("storm") or {}
    if storm_doc.get("ok"):
        doc["reach"] = {"qps": storm_doc["qps"],
                        "p99_ms": storm_doc["p99_ms"]}
    attr_doc = doc.get("attribution") or {}
    if attr_doc.get("ok") and "reach" in doc:
        # per-segment p50s + contention ratio, the ISSUE 11 regress keys
        doc["reach"]["segments"] = {
            seg: d["p50"] for seg, d in attr_doc["segments"].items()}
        doc["reach"]["contention_ratio"] = attr_doc["contention_ratio"]
    # ISSUE 14 regress keys: cache hit ratio (repeated mix), replica
    # staleness, off-writer contention
    cab_doc = doc.get("cache_ab") or {}
    if cab_doc.get("ok") and "reach" in doc:
        doc["reach"]["cache_hit_ratio"] = cab_doc["hit_ratio"]
    rsc_doc = doc.get("replica_scaleout") or {}
    if rsc_doc.get("ok") and "reach" in doc:
        ladder = rsc_doc.get("ladder") or {}
        first = ladder.get("r1") or {}
        doc["reach"]["staleness_ms"] = first.get("staleness_p50_ms")
        doc["reach"]["offwriter_contention_ratio"] = \
            rsc_doc["offwriter_contention_ratio"]
        # ISSUE 15 fleet freshness regress keys (obs/regress reads
        # doc.reach.freshness: total + per-hop p99s, all lower=better)
        fresh = rsc_doc.get("freshness_p99_ms") or {}
        if fresh:
            doc["reach"]["freshness"] = {
                "total_p99_ms": fresh.get("total"),
                **{f"{hop}_p99_ms": fresh.get(hop)
                   for hop in ("fold_lag", "ship_wait", "tail_lag",
                               "serve")}}
    # ISSUE 16 regress keys: router failover cost + shed honesty (both
    # advisory, lower=better — obs/regress reads doc.reach.router)
    fc_doc = doc.get("fleet_chaos") or {}
    if fc_doc.get("ok") and "reach" in doc:
        frt = fc_doc.get("router") or {}
        doc["reach"]["router"] = {
            "failover_p99_ms": frt.get("failover_p99_ms"),
            "shed_ratio": frt.get("shed_ratio")}
    # ISSUE 17 regress keys (advisory): the controller-on arm's breach
    # ratio (lower=better) and how many decisions the ramp took
    asc_doc = doc.get("autoscale") or {}
    if asc_doc.get("ok") and "reach" in doc:
        doc["reach"]["autoscale"] = {
            "breach_ratio_on": asc_doc["breach_ratio_on"],
            "breach_ratio_off": asc_doc["breach_ratio_off"],
            "decisions": asc_doc["decisions"]}
    # ISSUE 18 regress keys (advisory): delta-arm ship bytes + wall ms
    # per tick at the smallest rung (smoke-comparable) + the full/delta
    # bytes ratio — obs/regress reads doc.reach.deltaship
    ds_doc = doc.get("deltaship") or {}
    if ds_doc.get("ok") and "reach" in doc:
        doc["reach"]["deltaship"] = {
            "ship_bytes_per_tick": ds_doc["ship_bytes_per_tick"],
            "ship_ms_per_tick": ds_doc["ship_ms_per_tick"],
            "bytes_ratio": ds_doc["bytes_ratio"]}
    phases = ["small", "storm", "shed", "attribution", "cache_ab",
              "fleet_chaos", "autoscale", "deltaship"]
    if not args.smoke:
        phases += ["large", "sharded", "replica_scaleout"]
    doc["ok"] = ok and all(
        (doc.get(p) or {}).get("ok") for p in phases)
    doc["wall_s"] = round(time.monotonic() - _T0, 1)
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
    print(compact_line({"phase": "summary", "ok": doc["ok"],
                        "wall_s": doc["wall_s"],
                        "reach": doc.get("reach"),
                        "out": args.out}), flush=True)
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
