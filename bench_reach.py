#!/usr/bin/env python3
"""Reach-serving bench: materialize MinHash∪HLL sketches from a journal,
verify them against exact set arithmetic, then drive a concurrent
query storm through the pub/sub serving surface (ISSUE 10).

Three rungs, each emitting a compact (<= 4096 B) single-line JSON on
stdout (the PR 6 truncation-proof contract) with the full detail in the
``--out`` artifact:

- **small** — low cardinality (hundreds of devices/campaign): the
  device-materialized ``[C, k]``/``[C, R]`` planes must be BIT-EXACT
  equal to the numpy sketches computed from the oracle's exact
  per-campaign id sets (dedup/order invariance of the streamed fold),
  and every query's integer collision count must match the numpy
  evaluation exactly — the "oracle-exact at small cardinality" leg.
- **large** — >= 100k distinct devices: measured relative error vs
  exact set arithmetic must sit inside the theoretical bounds
  (union: 2·1.04/sqrt(R); overlap, relative to the union size:
  1/sqrt(k) + 1.04/sqrt(R) — ~6.25% + HLL term at k=256).
- **storm** — >= 1k concurrent queries through PubSubServer ->
  ReachQueryServer: all queries are admitted while the server holds,
  then the drain must take <= ceil(Q/batch) dispatches (batched
  evaluation, never one dispatch per query), with served/shed/p99 in
  the compact line.  A second, depth-starved server proves shed-oldest
  under overload (shed + served == sent, shed > 0).
- **attribution** (ISSUE 11) — the storm re-run with the query-path
  observability on (jax.obs.query + spans) and a CONCURRENT ingest
  thread re-folding the journal: every query's submit -> reply latency
  decomposes into queue/batch/dispatch/reply segments whose p50s sum
  to within 10% of the e2e p50, shed + answered queries each leave
  exactly one lifecycle record reconciling with
  ``streambench_reach_shed_total``, the perfetto trace validates with
  BOTH ingest and query lanes, and
  ``streambench_reach_contention_ratio`` measures the fraction of
  query queue-wait spent behind ingest dispatches.

Budget: self-caps at ``STREAMBENCH_BENCH_BUDGET_S`` (default 840 s <
the 870 s driver kill); the large rung is skipped (recorded, never
silent) when the envelope runs out.

Usage:
    python bench_reach.py                       # full, writes bench_reach.json
    python bench_reach.py --smoke               # CI: small + tiny storm
    python bench_reach.py --out REACH_r01.json
"""

from __future__ import annotations

import argparse
import json
import math
import os
import random
import sys
import threading
import time

import numpy as np

COMPACT_LINE_MAX = 4096
REPO = os.path.dirname(os.path.abspath(__file__))
_T0 = time.monotonic()


def log(msg: str) -> None:
    print(f"[{time.monotonic() - _T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def compact_line(obj: dict) -> str:
    """One bounded stdout line: shed detail until it fits."""
    def dump(o):
        return json.dumps(o, separators=(",", ":"))

    line = dump(obj)
    if len(line) <= COMPACT_LINE_MAX:
        return line
    obj = json.loads(line)
    for strip in ("per_query", "errors", "params", "host"):
        obj.pop(strip, None)
        line = dump(obj)
        if len(line) <= COMPACT_LINE_MAX:
            return line
    return dump({k: obj[k] for k in ("phase", "ok") if k in obj})


# ----------------------------------------------------------------------
# materialize: journal -> engine -> sketch planes
# ----------------------------------------------------------------------

def make_world(workdir: str, *, campaigns_n: int, users_n: int,
               events_n: int, seed: int):
    """Generator-shaped journal with a custom device universe (the
    stock do_setup pins 100 users; reach needs a configurable one)."""
    from streambench_tpu.datagen.gen import EventSource
    from streambench_tpu.utils.ids import make_ids

    rng = random.Random(seed)
    campaigns = make_ids(campaigns_n, rng)
    ads = make_ids(campaigns_n * 10, rng)
    mapping = {}
    for i, c in enumerate(campaigns):
        for a in ads[i * 10:(i + 1) * 10]:
            mapping[a] = c
    src = EventSource(ads=ads, user_ids=make_ids(users_n, rng),
                      page_ids=make_ids(100, rng), rng=rng)
    path = os.path.join(workdir, "reach-journal.txt")
    start = 1_700_000_000_000
    with open(path, "wb") as f:
        batch = 100_000
        for base in range(0, events_n, batch):
            hi = min(base + batch, events_n)
            ts = start + 10 * np.arange(base, hi, dtype=np.int64)
            blob = src.events_blob_at(ts)
            if blob is not None:
                f.write(blob)
            else:
                f.write(b"".join(src.event_at(int(t)).encode() + b"\n"
                                 for t in ts))
    return campaigns, mapping, path


def materialize(path: str, mapping: dict, campaigns: list, *,
                k: int, registers: int, batch: int = 8192):
    """Fold the journal through a ReachSketchEngine (block ingest where
    the native encoder is built, line fallback otherwise)."""
    from streambench_tpu.config import default_config
    from streambench_tpu.engine.sketches import ReachSketchEngine

    cfg = default_config(jax_num_campaigns=len(campaigns),
                         jax_batch_size=batch)
    eng = ReachSketchEngine(cfg, mapping, campaigns=campaigns,
                            redis=None, k=k, registers=registers)
    eng.warmup()
    t0 = time.monotonic()
    with open(path, "rb") as f:
        carry = b""
        while True:
            data = f.read(4 << 20)
            if not data:
                break
            data = carry + data
            nl = data.rfind(b"\n") + 1
            carry = data[nl:]
            eng.process_block(data[:nl])
        if carry:
            eng.process_block(carry + b"\n")
    eng.flush(final=True)
    wall = time.monotonic() - t0
    return eng, wall


def oracle_world(path: str, mapping: dict, campaigns: list):
    from streambench_tpu.reach import oracle as ro

    with open(path, "rb") as f:
        return ro.campaign_user_sets(f, mapping, campaigns)


# ----------------------------------------------------------------------
# query workloads
# ----------------------------------------------------------------------

def make_queries(campaigns: list, n: int, seed: int):
    rng = np.random.default_rng(seed)
    C = len(campaigns)
    masks = np.zeros((n, C), bool)
    overlap = np.zeros(n, bool)
    for i in range(n):
        m = int(rng.integers(1, 6))
        masks[i, rng.choice(C, size=min(m, C), replace=False)] = True
        overlap[i] = bool(rng.integers(0, 2))
    return masks, overlap


def error_stats(est, masks, overlap, sets, campaigns, *, k, R):
    """Measured relative errors vs exact set arithmetic (union relative
    to truth; overlap relative to the union size — the Jaccard
    estimator's natural scale)."""
    from streambench_tpu.reach import oracle as ro

    u_err, o_err = [], []
    for i in range(masks.shape[0]):
        sel = [campaigns[j] for j in range(masks.shape[1]) if masks[i, j]]
        op = "overlap" if overlap[i] else "union"
        truth, true_union = ro.exact_counts(sets, sel, op)
        if overlap[i]:
            o_err.append(abs(float(est[i]) - truth) / max(true_union, 1))
        else:
            u_err.append(abs(float(est[i]) - truth) / max(truth, 1))
    return (dict(mean=float(np.mean(u_err)), max=float(np.max(u_err)),
                 n=len(u_err)),
            dict(mean=float(np.mean(o_err)), max=float(np.max(o_err)),
                 n=len(o_err)))


# ----------------------------------------------------------------------
# rungs
# ----------------------------------------------------------------------

def run_verify(workdir: str, *, name: str, campaigns_n: int, users_n: int,
               events_n: int, k: int, registers: int, queries_n: int,
               seed: int, bitexact: bool) -> dict:
    from streambench_tpu.reach import oracle as ro
    from streambench_tpu.reach import query as rq

    campaigns, mapping, path = make_world(
        workdir, campaigns_n=campaigns_n, users_n=users_n,
        events_n=events_n, seed=seed)
    eng, mat_wall = materialize(path, mapping, campaigns,
                                k=k, registers=registers)
    names = list(eng.encoder.campaigns)
    sets = oracle_world(path, mapping, names)
    distinct = len(set().union(*sets.values())) if sets else 0
    out = {"phase": name, "events": eng.events_processed,
           "distinct_devices": distinct, "k": k, "registers": registers,
           "materialize_s": round(mat_wall, 2),
           "materialize_ev_s": int(eng.events_processed
                                   / max(mat_wall, 1e-9))}
    assert eng.events_processed == events_n, (eng.events_processed,
                                              events_n)
    if bitexact:
        em, er = ro.expected_state(sets, names, k, registers)
        assert (np.asarray(eng.state.mins) == em).all(), \
            "device mins != set-arithmetic oracle sketch"
        assert (np.asarray(eng.state.registers) == er).all(), \
            "device registers != set-arithmetic oracle sketch"
        out["sketch_bitexact"] = True
    masks, overlap = make_queries(names, queries_n, seed + 1)
    counter = rq.DispatchCounter()
    est, union, jacc, agree = rq.query_chunks(
        eng.state.mins, eng.state.registers, masks, overlap,
        counter=counter)
    out["queries"] = queries_n
    out["query_dispatches"] = counter.dispatches
    assert counter.dispatches == math.ceil(queries_n / rq.DEFAULT_BATCH)
    if bitexact:
        oa = ro.query_oracle_np(np.asarray(eng.state.mins),
                                np.asarray(eng.state.registers), masks)
        assert (agree == oa).all(), "device collision counts != oracle"
        out["queries_bitexact"] = True
        out["oracle"] = "exact"
    u_err, o_err = error_stats(est, masks, overlap, sets, names,
                               k=k, R=registers)
    ub = 2 * 1.04 / math.sqrt(registers)
    ob = 1.0 / math.sqrt(k) + 1.04 / math.sqrt(registers)
    out["union_rel_err"] = {**u_err, "bound": round(ub, 4)}
    out["overlap_rel_err_vs_union"] = {**o_err, "bound": round(ob, 4)}
    if name == "large":
        assert distinct >= 100_000, distinct
        assert u_err["mean"] <= ub, (u_err, ub)
        assert o_err["mean"] <= ob, (o_err, ob)
        out["error_within_bounds"] = True
    out["ok"] = True
    return out, eng, names, sets, path


def run_storm(eng, names, *, queries_n: int, clients: int, depth: int,
              batch: int, expect_shed: bool, phase: str) -> dict:
    from streambench_tpu.dimensions.pubsub import PubSubClient, PubSubServer
    from streambench_tpu.reach.serve import ReachQueryServer

    srv = ReachQueryServer(names, depth=depth, batch=batch, hold=True)
    eng.attach_reach(srv)
    ps = PubSubServer(port=0).start()
    ps.register_query("reach", srv.handle)
    host, port = ps.address
    per = queries_n // clients
    results: list = [None] * clients
    rng = np.random.default_rng(1234)
    picks = [
        [list(rng.choice(len(names), size=int(rng.integers(1, 5)),
                         replace=False)) for _ in range(per)]
        for _ in range(clients)]

    def run_client(ci: int) -> None:
        c = PubSubClient(host, port, timeout_s=120)
        t0s = {}
        for qi, sel in enumerate(picks[ci]):
            qid = ci * per + qi
            t0s[qid] = time.monotonic()
            c.request({"type": "reach",
                       "campaigns": [names[j] for j in sel],
                       "op": "overlap" if qid % 2 else "union",
                       "id": qid})
        got = []
        for _ in range(per):
            m = c.recv()["data"]
            got.append((m, time.monotonic() - t0s.get(m.get("id"), _T0)))
        results[ci] = got
        c.close()

    threads = [threading.Thread(target=run_client, args=(ci,))
               for ci in range(clients)]
    t_sub = time.monotonic()
    for t in threads:
        t.start()
    # every query admitted (or shed) before the drain starts: the
    # dispatch-count acceptance is about BATCHED evaluation of a
    # standing backlog of concurrent queries
    deadline = time.monotonic() + 120
    want_pending = queries_n if not expect_shed else depth
    while (srv.pending() < want_pending
           and srv.pending() + srv.shed < queries_n
           and time.monotonic() < deadline):
        time.sleep(0.01)
    submit_s = time.monotonic() - t_sub
    t_drain = time.monotonic()
    srv.resume()
    for t in threads:
        t.join(timeout=120)
    drain_s = time.monotonic() - t_drain
    summary = srv.summary()
    ps.close()
    srv.close()
    answers = [m for got in results if got for m, _ in got]
    assert len(answers) == clients * per, (len(answers), clients * per)
    served = [m for m in answers if "estimate" in m]
    shed = [m for m in answers if m.get("shed")]
    assert len(served) == summary["served"]
    assert len(served) + len(shed) == clients * per
    out = {"phase": phase, "sent": clients * per, "clients": clients,
           "served": summary["served"], "shed": summary["shed"],
           "dispatches": summary["dispatches"], "batch": batch,
           "queue_depth": depth,
           "submit_s": round(submit_s, 2),
           "drain_s": round(drain_s, 2),
           "p50_ms": summary.get("p50_ms"),
           "p99_ms": summary.get("p99_ms"),
           "qps": round(summary["served"] / max(drain_s, 1e-9), 1)}
    if expect_shed:
        assert summary["shed"] > 0, summary
    else:
        assert summary["shed"] == 0, summary
        assert summary["served"] == clients * per
        # the acceptance number: a standing storm of Q queries drains
        # in at most ceil(Q/batch) dispatches, never one per query
        assert summary["dispatches"] <= math.ceil(
            (clients * per) / batch), summary
        assert all(m["epoch"] == eng.reach_epoch for m in served)
    out["ok"] = True
    return out


def run_attribution(eng, names, journal_path: str, workdir: str, *,
                    queries_n: int, gap_s: float, depth: int,
                    batch: int, shed_burst: int, slo_ms: int = 250,
                    ingest_gap_s: float = 0.01,
                    phase: str = "attribution") -> dict:
    """The ISSUE 11 rung: a paced pub/sub query storm with query-path
    observability ON, concurrent with an ingest thread re-folding the
    journal (idempotent for cumulative sketches — the served state
    never changes, only device occupancy does), followed by a shed
    burst for the reconciliation check.

    The ingest side is PACED (``ingest_gap_s`` between block folds):
    on this 1-core host an unthrottled re-fold loop saturates both the
    interpreter and the device queue and the query worker starves
    outright — the ratio would measure GIL starvation, not device
    contention.  Paced, each query's queue wait genuinely overlaps
    some ingest dispatches and the ratio reads as designed."""
    import jax

    from streambench_tpu.dimensions.pubsub import PubSubClient, PubSubServer
    from streambench_tpu.obs import MetricsRegistry, SpanTracer
    from streambench_tpu.obs.queryattr import SEGMENTS, QueryLifecycle
    from streambench_tpu.obs.spans import validate_chrome_trace
    from streambench_tpu.reach.serve import ReachQueryServer

    reg = MetricsRegistry()
    spans = SpanTracer(capacity=16384, registry=reg)
    old_sink = eng.tracer.sink
    spans.attach(eng.tracer)         # ingest folds -> the shared ring
    ql = QueryLifecycle(reg, slo_ms=slo_ms, slowlog_max=64, spans=spans)
    srv = ReachQueryServer(names, depth=depth, batch=batch,
                           registry=reg, queryattr=ql, spans=spans)
    eng.attach_reach(srv)
    ps = PubSubServer(port=0).start()
    ps.register_query("reach", srv.handle)
    host, port = ps.address

    ingest_stop = threading.Event()
    folded = {"events": 0}

    def ingest() -> None:
        # re-fold the journal in a loop: real device dispatches (real
        # contention for the query worker) with idempotent state.
        # block_until_ready after each block is the backpressure the
        # runner's flush path provides in production — without it the
        # async dispatch stream outruns the device without bound and
        # query waits grow with the backlog instead of measuring it
        while not ingest_stop.is_set():
            with open(journal_path, "rb") as f:
                carry = b""
                while not ingest_stop.is_set():
                    data = f.read(256 << 10)
                    if not data:
                        break
                    data = carry + data
                    nl = data.rfind(b"\n") + 1
                    carry = data[nl:]
                    eng.process_block(data[:nl])
                    # the fold-sync window is the measured
                    # device-busy evidence the contention ratio
                    # intersects query queue-waits with
                    t_d = time.perf_counter_ns()
                    jax.block_until_ready(eng.state.mins)
                    ql.note_ingest_busy(t_d, time.perf_counter_ns())
                    folded["events"] = eng.events_processed
                    time.sleep(ingest_gap_s)

    rng = np.random.default_rng(4321)
    answers: list = []
    splits: list = []

    def storm() -> None:
        c = PubSubClient(host, port, timeout_s=120)
        pending = 0
        for qi in range(queries_n):
            sel = [names[j] for j in rng.choice(
                len(names), size=int(rng.integers(1, 5)),
                replace=False)]
            c.request({"type": "reach", "campaigns": sel,
                       "op": "overlap" if qi % 2 else "union",
                       "id": qi, "trace": f"bench-{qi}",
                       "sent_ms": int(time.time() * 1000)})
            pending += 1
            # paced, but bounded in flight so a slow drain never
            # deadlocks the blocking client against its own sends
            while pending > 64:
                d = c.recv()["data"]
                answers.append(d)
                s = c.latency_split(d)
                if s is not None:
                    splits.append(s)
                pending -= 1
            time.sleep(gap_s)
        for _ in range(pending):
            d = c.recv()["data"]
            answers.append(d)
            s = c.latency_split(d)
            if s is not None:
                splits.append(s)
        c.close()

    t_ing = threading.Thread(target=ingest, daemon=True)
    t_storm = threading.Thread(target=storm)
    t0 = time.monotonic()
    t_ing.start()
    t_storm.start()
    t_storm.join(timeout=300)
    ingest_stop.set()
    t_ing.join(timeout=60)
    storm_s = time.monotonic() - t0
    assert not t_storm.is_alive(), "attribution storm never finished"
    assert len(answers) == queries_n, (len(answers), queries_n)
    assert all("estimate" in d or d.get("shed") for d in answers)
    served_storm = sum("estimate" in d for d in answers)

    # shed burst: overload a held server so shed lifecycle records and
    # the shed counter must reconcile exactly
    srv.pause()
    got_burst: list = []
    for qi in range(shed_burst):
        srv.submit([names[qi % len(names)]], "union",
                   lambda d: got_burst.append(d), query_id=f"b{qi}")
    srv.resume()
    deadline = time.monotonic() + 120
    while len(got_burst) < shed_burst and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(got_burst) == shed_burst
    jax.block_until_ready(eng.state.mins)
    srv.close()
    summary = srv.summary()
    ps.close()
    eng.tracer.sink = old_sink

    qsum = ql.summary()
    # --- reconciliation: every query leaves exactly ONE lifecycle
    # record, and shed records == the Prometheus shed counter ---------
    shed_counter = int(reg.counter("streambench_reach_shed_total").value)
    assert qsum["shed_records"] == summary["shed"] == shed_counter, (
        qsum["shed_records"], summary["shed"], shed_counter)
    assert qsum["served_records"] == summary["served"], (
        qsum["served_records"], summary["served"])
    assert qsum["served_records"] + qsum["shed_records"] == (
        queries_n + shed_burst), qsum
    assert summary["shed"] > 0, "shed burst produced no sheds"

    # --- segment partition: p50s sum to ~the e2e p50 -----------------
    segs = {seg: qsum["segments"][seg] for seg in SEGMENTS}
    p50_sum = sum(s.get("p50", 0.0) for s in segs.values())
    e2e_p50 = qsum["e2e_ms"].get("p50", 0.0)
    seg_sum_ratio = p50_sum / e2e_p50 if e2e_p50 else 0.0
    # exact-sum check (no bucket error): segment sums total the e2e sum
    sum_exact = sum(s.get("sum", 0.0) for s in segs.values())
    assert abs(sum_exact - qsum["e2e_ms"]["sum"]) <= max(
        1e-6 * qsum["e2e_ms"]["sum"], 5e-3), (sum_exact, qsum["e2e_ms"])
    assert abs(seg_sum_ratio - 1.0) <= 0.10, (
        f"segment p50 sum {p50_sum:.3f} vs e2e p50 {e2e_p50:.3f} "
        f"({seg_sum_ratio:.3f})")

    # --- perfetto trace: both lanes on one clock ---------------------
    trace_path = os.path.join(workdir, "trace_reach_attr.json")
    spans.dump(trace_path, run="bench-reach-attribution")
    doc = json.load(open(trace_path))
    problems = validate_chrome_trace(doc)
    assert problems == [], problems
    cats = {e.get("cat") for e in doc["traceEvents"]
            if e.get("ph") == "X"}
    assert "query" in cats and "stage" in cats, cats

    cont = qsum["contention"]
    net = sorted(s.get("network_ms", 0.0) for s in splits)
    srvms = sorted(s.get("server_ms", 0.0) for s in splits)
    out = {
        "phase": phase, "queries": queries_n, "shed_burst": shed_burst,
        "served": summary["served"], "shed": summary["shed"],
        "served_storm": served_storm,
        "dispatches": summary["dispatches"],
        "storm_s": round(storm_s, 2),
        "ingest_events_folded": folded["events"],
        "segments": {seg: {"p50": round(s.get("p50", 0.0), 3),
                           "p99": round(s.get("p99", 0.0), 3),
                           "count": s.get("count", 0)}
                     for seg, s in segs.items()},
        "e2e_ms": {k: (round(v, 3) if isinstance(v, float) else v)
                   for k, v in qsum["e2e_ms"].items()},
        "seg_sum_ratio": round(seg_sum_ratio, 4),
        "segment_sum_exact": True,
        "shed_reconciled": True,
        "contention_ratio": cont["ratio"],
        "contention": {"queue_wait_ms": cont["queue_wait_ms"],
                       "ingest_overlap_ms": cont["ingest_overlap_ms"]},
        "slow_queries": qsum["slow_queries"],
        "slo_ms": slo_ms,
        "client_split": {
            "n": len(splits),
            "server_p50_ms": round(srvms[len(srvms) // 2], 3)
            if srvms else None,
            "network_p50_ms": round(net[len(net) // 2], 3)
            if net else None,
        },
        "trace": {"path": os.path.basename(trace_path),
                  "events": len(doc["traceEvents"]),
                  "lanes": sorted(c for c in cats if c)},
        "ok": True,
    }
    return out


# ----------------------------------------------------------------------

def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="CI: small rung + tiny storm only")
    ap.add_argument("--out", default="bench_reach.json")
    ap.add_argument("--workdir", default="")
    args = ap.parse_args()
    budget_s = float(os.environ.get("STREAMBENCH_BENCH_BUDGET_S", "840"))
    deadline = _T0 + budget_s

    import tempfile
    workdir = args.workdir or tempfile.mkdtemp(prefix="bench-reach-")
    os.makedirs(workdir, exist_ok=True)

    import jax
    doc: dict = {
        "schema": "REACH", "smoke": bool(args.smoke),
        "backend": jax.default_backend(),
        "devices": jax.device_count(),
        "cpus": os.cpu_count(),
        "budget_s": budget_s,
    }
    ok = True

    # -- small rung: bit-exact vs exact set arithmetic ------------------
    small, eng_s, names_s, _, journal_s = run_verify(
        workdir, name="small", campaigns_n=40, users_n=500,
        events_n=50_000, k=256, registers=256, queries_n=256,
        seed=17, bitexact=True)
    doc["small"] = small
    print(compact_line(small), flush=True)
    log(f"small rung ok: bit-exact, {small['distinct_devices']} devices")

    # -- large rung + storm --------------------------------------------
    if args.smoke:
        storm = run_storm(eng_s, names_s, queries_n=60, clients=2,
                          depth=256, batch=32, expect_shed=False,
                          phase="storm")
        doc["storm"] = storm
        print(compact_line(storm), flush=True)
        shed = run_storm(eng_s, names_s, queries_n=120, clients=2,
                         depth=16, batch=16, expect_shed=True,
                         phase="shed")
        doc["shed"] = shed
        print(compact_line(shed), flush=True)
        attr = run_attribution(
            eng_s, names_s, journal_s, workdir, queries_n=120,
            gap_s=0.005, depth=128, batch=16, shed_burst=200)
        doc["attribution"] = attr
        print(compact_line(attr), flush=True)
        log(f"attribution ok: seg_sum_ratio {attr['seg_sum_ratio']} "
            f"contention {attr['contention_ratio']}")
    elif time.monotonic() > deadline - 120:
        doc["large"] = {"skipped": "budget"}
        doc["storm"] = {"skipped": "budget"}
        doc["attribution"] = {"skipped": "budget"}
        ok = False
        log("budget exhausted before the large rung — recorded, not silent")
    else:
        large, eng_l, names_l, _, journal_l = run_verify(
            workdir, name="large", campaigns_n=100, users_n=130_000,
            events_n=600_000, k=256, registers=1024, queries_n=512,
            seed=23, bitexact=True)
        doc["large"] = large
        print(compact_line(large), flush=True)
        log(f"large rung ok: {large['distinct_devices']} distinct devices, "
            f"union err {large['union_rel_err']['mean']:.4f} "
            f"overlap err {large['overlap_rel_err_vs_union']['mean']:.4f}")
        storm = run_storm(eng_l, names_l, queries_n=1200, clients=6,
                          depth=2048, batch=256, expect_shed=False,
                          phase="storm")
        assert storm["served"] >= 1000
        doc["storm"] = storm
        print(compact_line(storm), flush=True)
        log(f"storm ok: {storm['served']} served in "
            f"{storm['dispatches']} dispatches, p99 {storm['p99_ms']} ms")
        shed = run_storm(eng_l, names_l, queries_n=300, clients=2,
                         depth=64, batch=64, expect_shed=True,
                         phase="shed")
        doc["shed"] = shed
        print(compact_line(shed), flush=True)
        log(f"shed rung ok: {shed['shed']} shed of {shed['sent']}")
        # ISSUE 11: the storm re-run with query obs on + concurrent
        # ingest — segment decomposition, shed reconcile, contention
        # ingest_gap_s tuned to a ~30% duty cycle: this engine's
        # per-block fold+sync is ~110 ms (C=100, R=1024), and a
        # near-100% duty cycle makes the latency distribution bimodal
        # around the fold time — the p50-sum check then compares
        # medians across modes instead of decomposing the typical
        # path.  The ~9 folds the paced storm spans still put real
        # ingest-busy windows under the queue waits (the tail
        # dominates total wait, so the contention ratio stays
        # evidence-backed).
        attr = run_attribution(
            eng_l, names_l, journal_l, workdir, queries_n=400,
            gap_s=0.008, depth=128, batch=64, shed_burst=240,
            ingest_gap_s=0.25)
        doc["attribution"] = attr
        print(compact_line(attr), flush=True)
        log(f"attribution ok: seg_sum_ratio {attr['seg_sum_ratio']} "
            f"contention {attr['contention_ratio']} "
            f"({attr['ingest_events_folded']} ev folded concurrently)")

    # regress-gate keys (obs/regress.py normalize_bench reads doc.reach)
    storm_doc = doc.get("storm") or {}
    if storm_doc.get("ok"):
        doc["reach"] = {"qps": storm_doc["qps"],
                        "p99_ms": storm_doc["p99_ms"]}
    attr_doc = doc.get("attribution") or {}
    if attr_doc.get("ok") and "reach" in doc:
        # per-segment p50s + contention ratio, the ISSUE 11 regress keys
        doc["reach"]["segments"] = {
            seg: d["p50"] for seg, d in attr_doc["segments"].items()}
        doc["reach"]["contention_ratio"] = attr_doc["contention_ratio"]
    doc["ok"] = ok and all(
        (doc.get(p) or {}).get("ok") for p in
        (("small", "storm", "shed", "attribution") if args.smoke
         else ("small", "large", "storm", "shed", "attribution")))
    doc["wall_s"] = round(time.monotonic() - _T0, 1)
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
    print(compact_line({"phase": "summary", "ok": doc["ok"],
                        "wall_s": doc["wall_s"],
                        "reach": doc.get("reach"),
                        "out": args.out}), flush=True)
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
