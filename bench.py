"""Headline benchmark: sustained end-to-end events/sec, oracle-verified.

Reproduces the reference's benchmark shape (SURVEY.md §6): the YSB
ad-analytics pipeline — deserialize, filter "view", join ad->campaign,
count per (campaign, 10 s window), write canonical Redis schema — driven
from a journaled event stream, then checked window-by-window against the
golden model (``check-correct``, ``core.clj:215-237``).  The headline
metric is catchup-mode sustained throughput: how many events/sec the whole
engine (host encode + XLA window step + Redis flush) folds while staying
exactly correct.  A second phase paces events in real time (``-r -t N``,
``core.clj:183-204``) and reports the reference's true latency metric —
``time_updated − window_timestamp`` per window (``core.clj:149``) — as
p50/p99 + deciles on stderr.

Backend resolution is crash/hang-proof: the requested platform is probed
in a *subprocess* with a hard timeout and bounded retries; on failure the
bench pins itself to CPU and still lands a number (round 1 died with rc=1
inside in-process TPU init — that must never happen again).

Prints ONE JSON line on stdout: {"metric", "value", "unit",
"vs_baseline"}.  All diagnostics (platform, stage breakdown, latency
deciles) go to stderr.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import tempfile
import threading
import time

BASELINE_EVENTS_PER_S = 100_000.0

PROBE_TIMEOUT_S = float(os.environ.get("STREAMBENCH_BENCH_PROBE_TIMEOUT", "150"))
PROBE_ATTEMPTS = int(os.environ.get("STREAMBENCH_BENCH_PROBE_ATTEMPTS", "2"))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ----------------------------------------------------------------------
# backend resolution
def _probe_backend(env: dict, timeout_s: float) -> tuple[bool, str]:
    """Initialize jax in a THROWAWAY subprocess; return (ok, detail).

    In-process init can hang indefinitely when the hardware backend is
    wedged (observed: rc=1 crash in round 1, a 120 s+ hang when re-judged
    and again this round).  A subprocess can always be killed.
    """
    # Mirror pin_jax_platform: the image's sitecustomize overrides the
    # JAX_PLATFORMS env var via jax.config, so the probe must re-pin the
    # config or a cpu probe would still initialize the hardware backend.
    code = ("import os, jax;\n"
            "p = os.environ.get('JAX_PLATFORMS')\n"
            "if p: jax.config.update('jax_platforms', p)\n"
            "d = jax.devices(); print(jax.default_backend(), len(d))")
    try:
        p = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {timeout_s:.0f}s"
    if p.returncode != 0:
        tail = (p.stderr or "").strip().splitlines()[-1:]
        return False, f"probe rc={p.returncode}: {' '.join(tail)}"
    return True, p.stdout.strip()


def resolve_platform() -> str:
    """Pick a platform that is PROVEN to initialize, preferring the
    ambient/requested one (usually the TPU plugin).  Returns the platform
    string that was pinned into this process's environment."""
    want = os.environ.get("JAX_PLATFORMS", "")
    for attempt in range(1, PROBE_ATTEMPTS + 1):
        ok, detail = _probe_backend(dict(os.environ), PROBE_TIMEOUT_S)
        if ok:
            log(f"backend probe ok (attempt {attempt}): {detail}")
            return want or detail.split()[0]
        log(f"backend probe failed (attempt {attempt}/{PROBE_ATTEMPTS}, "
            f"platform={want or 'default'}): {detail}")
        if attempt < PROBE_ATTEMPTS:
            time.sleep(2.0)
    log("FALLING BACK TO CPU: the requested backend would not initialize. "
        "The number below is a CPU number — check chip availability "
        "(stale processes holding the device, tunnel down) and rerun.")
    os.environ["JAX_PLATFORMS"] = "cpu"
    return "cpu"


# ----------------------------------------------------------------------
def _paced_latency_phase(cfg, mapping, broker, r, workdir,
                         rate: int, duration_s: float) -> None:
    """Pace events in real time at ``rate`` ev/s and report the canonical
    latency metric from what landed in Redis (``core.clj:130-149``)."""
    from streambench_tpu.datagen import gen
    from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner
    from streambench_tpu.io.redis_schema import read_stats, seed_campaigns
    from streambench_tpu.metrics import decile_table

    # read_stats walks SMEMBERS campaigns (core.clj:131) — seed them.
    seed_campaigns(r, sorted(set(mapping.values())))
    topic = cfg.kafka_topic + "-paced"
    engine = AdAnalyticsEngine(cfg, mapping, redis=r)
    runner = StreamRunner(engine, broker.reader(topic))

    sent = {}

    def produce():
        sent["n"] = gen.run_paced(
            broker.writer(topic), rate, duration_s=duration_s,
            workdir=workdir, rng=random.Random(7),
            on_behind=lambda ms: log(f"paced generator behind {ms:.0f} ms"))

    t = threading.Thread(target=produce, daemon=True)
    t0 = time.monotonic()
    t.start()
    runner.run(duration_s=duration_s + 3.0, idle_timeout_s=2.0)
    t.join(timeout=10)
    engine.close()
    wall = time.monotonic() - t0
    stats = read_stats(r)
    lats = sorted(lat for _, lat in stats)
    log(f"paced phase: rate={rate}/s sent={sent.get('n')} "
        f"processed={runner.stats.events} wall={wall:.1f}s "
        f"windows={len(lats)}")
    if not lats:
        log("paced phase: no windows written — latency unavailable")
        return
    pick = lambda q: lats[min(int(q * len(lats)), len(lats) - 1)]
    log(f"window latency (time_updated - window_ts) at {rate} ev/s: "
        f"p50={pick(0.50)} ms p90={pick(0.90)} ms p99={pick(0.99)} ms "
        f"max={lats[-1]} ms over {len(lats)} windows")
    for rng_label, v in decile_table(lats):
        log(f"  decile {rng_label}: {v} ms")


def main() -> int:
    n_events = int(os.environ.get("STREAMBENCH_BENCH_EVENTS", "500000"))
    paced_rate = int(os.environ.get("STREAMBENCH_BENCH_PACED_RATE", "0"))
    paced_dur = float(os.environ.get("STREAMBENCH_BENCH_PACED_SECS", "35"))

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from streambench_tpu.utils.platform import pin_jax_platform

    platform = resolve_platform()
    pin_jax_platform(platform)

    import jax

    from streambench_tpu.config import default_config
    from streambench_tpu.datagen import gen
    from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner
    from streambench_tpu.io.fakeredis import FakeRedisStore
    from streambench_tpu.io.journal import FileBroker
    from streambench_tpu.io.redis_schema import as_redis

    log(f"backend={jax.default_backend()} devices={len(jax.devices())} "
        f"events={n_events}")
    cfg = default_config()

    with tempfile.TemporaryDirectory() as wd:
        r = as_redis(FakeRedisStore())
        broker = FileBroker(os.path.join(wd, "broker"))
        t0 = time.monotonic()
        gen.do_setup(r, cfg, broker=broker, events_num=n_events,
                     rng=random.Random(42), workdir=wd)
        log(f"generated {n_events} events in {time.monotonic()-t0:.1f}s")
        mapping = gen.load_ad_mapping_file(
            os.path.join(wd, gen.AD_TO_CAMPAIGN_FILE))

        # Warm the jit cache with a same-shape engine so compile time
        # (~20-40 s on first TPU use) doesn't pollute the measurement.
        t0 = time.monotonic()
        warm = AdAnalyticsEngine(cfg, mapping)
        warm_reader = broker.reader(cfg.kafka_topic)
        warm.process_lines(warm_reader.poll(cfg.jax_batch_size))
        warm.flush()
        log(f"jit warmup done in {time.monotonic()-t0:.1f}s "
            f"(method={warm.method})")

        engine = AdAnalyticsEngine(cfg, mapping, redis=r)
        runner = StreamRunner(engine, broker.reader(cfg.kafka_topic))
        stats = runner.run_catchup()
        log(f"processed {stats.events} events in {stats.wall_s:.2f}s; "
            f"windows={stats.windows_written} dropped={engine.dropped}")
        log(engine.tracer.report())
        engine.close()

        correct, differ, missing = gen.check_correct(
            r, workdir=wd, log=lambda s: None,
            time_divisor_ms=cfg.jax_time_divisor_ms)
        log(f"oracle: CORRECT={correct} DIFFER={differ} MISSING={missing}")
        if differ or missing or engine.dropped:
            log("BENCH INVALID: engine output incorrect")
            print(json.dumps({
                "metric": "sustained events/sec (oracle-verified)",
                "value": 0.0, "unit": "events/s", "vs_baseline": 0.0}))
            return 1

        value = round(stats.events_per_s, 1)

        # Phase 2 (diagnostic, stderr only): the reference's real metric —
        # p50/p99 window-writeback latency under sustained paced load at a
        # rate the engine provably absorbs (default: half the measured
        # catchup throughput, i.e. comfortably sustainable).
        rate = paced_rate or max(int(stats.events_per_s // 2), 1_000)
        try:
            _paced_latency_phase(cfg, mapping, broker,
                                 as_redis(FakeRedisStore()), wd,
                                 rate, paced_dur)
        except Exception as e:  # diagnostics must never kill the headline
            log(f"paced latency phase failed (non-fatal): {e!r}")

        print(json.dumps({
            "metric": "sustained events/sec (oracle-verified)",
            "value": value,
            "unit": "events/s",
            "vs_baseline": round(value / BASELINE_EVENTS_PER_S, 4),
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
