"""Headline benchmark: sustained end-to-end events/sec, oracle-verified.

Reproduces the reference's benchmark shape (SURVEY.md §6): the YSB
ad-analytics pipeline — deserialize, filter "view", join ad->campaign,
count per (campaign, 10 s window), write canonical Redis schema — driven
from a journaled event stream, then checked window-by-window against the
golden model (``check-correct``, ``core.clj:215-237``).  The headline
metric is catchup-mode sustained throughput: how many events/sec the whole
engine (host encode + XLA window step + Redis flush) folds while staying
exactly correct.  A second phase paces events in real time (``-r -t N``,
``core.clj:183-204``) and reports the reference's true latency metric —
``time_updated − window_timestamp`` per window (``core.clj:149``) — as
p50/p99 + deciles on stderr.

Backend resolution is crash/hang-proof: the requested platform is probed
in a *subprocess* with a hard timeout and bounded retries; on failure the
bench pins itself to CPU and still lands a number (round 1 died with rc=1
inside in-process TPU init — that must never happen again).

Prints the headline JSON line {"metric", "value", "unit", "vs_baseline",
...} on stdout after EVERY completed phase — catchup, each ladder rung,
each config row — so a consumer taking the last JSON line always gets
the richest completed view even if the process is killed mid-run.  All
diagnostics (platform, stage breakdown, latency deciles) go to stderr.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import subprocess
import sys
import tempfile
import time

BASELINE_EVENTS_PER_S = 100_000.0
# One paced-producer process can sustain about this rate with the native
# formatter; higher rates shard across processes (see _paced_latency_phase).
PRODUCER_MAX_RATE = 400_000


def _n_producers(rate: int) -> int:
    """Producer processes a paced rate shards across — THE one policy,
    used both to launch producers and to split per-producer knobs like
    the session row's user universe."""
    return max(1, -(-rate // PRODUCER_MAX_RATE))

PROBE_TIMEOUT_S = float(os.environ.get("STREAMBENCH_BENCH_PROBE_TIMEOUT", "90"))
# Keep retrying the hardware backend for this long before falling back to
# CPU.  A healthy backend passes the FIRST probe, so the window costs
# nothing when the chip is there.  Round 4 learned the hard way that the
# probe must live INSIDE the overall wall-clock envelope: a 900 s probe
# pushed every phase past the driver's kill timeout and the artifact died
# unparsed.  300 s still rides out a brief tunnel blip; the envelope
# (STREAMBENCH_BENCH_BUDGET_S) caps probe + measurement TOGETHER.
PROBE_WINDOW_S = float(os.environ.get("STREAMBENCH_BENCH_PROBE_WINDOW_S",
                                      "300"))
PROBE_RETRY_DELAY_S = 60.0


_T0 = time.monotonic()


def log(msg: str) -> None:
    print(f"[{time.monotonic() - _T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


# ----------------------------------------------------------------------
# backend resolution
def _probe_backend(env: dict, timeout_s: float) -> tuple[bool, str]:
    """Shared hang-proof subprocess probe (see utils.platform)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from streambench_tpu.utils.platform import probe_backend

    return probe_backend(env, timeout_s)


def resolve_platform(window_s: float = PROBE_WINDOW_S) -> str:
    """Pick a platform that is PROVEN to initialize, preferring the
    ambient/requested one (usually the TPU plugin).  Returns the platform
    string that was pinned into this process's environment.

    The hardware backend is retried every ~60 s across ``window_s``
    before the CPU fallback: a wedged chip tunnel often recovers within
    minutes, and a "TPU-native" bench that records a CPU number while
    the chip comes back two minutes later has failed its one job.  The
    window only spends time when the backend is actually down — and it is
    charged against the bench's OVERALL envelope, never added on top."""
    want = os.environ.get("JAX_PLATFORMS", "")
    t_end = time.monotonic() + window_s
    attempt = 0
    while True:
        attempt += 1
        # The FIRST attempt always gets the full hang-timeout — a healthy
        # backend must be able to answer even when the window is small
        # (else a slow-init chip would be misread as down and a CPU
        # number recorded).  Later attempts clamp to the remaining
        # window so a wedged backend can't overdraw the envelope.
        per_attempt = (PROBE_TIMEOUT_S if attempt == 1
                       else min(PROBE_TIMEOUT_S,
                                max(t_end - time.monotonic(), 15.0)))
        ok, detail = _probe_backend(dict(os.environ), per_attempt)
        if ok:
            log(f"backend probe ok (attempt {attempt}): {detail}")
            return want or detail.split()[0]
        remaining = t_end - time.monotonic()
        log(f"backend probe failed (attempt {attempt}, "
            f"platform={want or 'default'}, {remaining:.0f}s of probe "
            f"window left): {detail}")
        if remaining <= PROBE_RETRY_DELAY_S:
            break
        time.sleep(PROBE_RETRY_DELAY_S)
    log("FALLING BACK TO CPU: the requested backend would not initialize "
        f"within {window_s:.0f}s. The number below is a CPU number "
        "— check chip availability (stale processes holding the device, "
        "tunnel down) and rerun.")
    os.environ["JAX_PLATFORMS"] = "cpu"
    return "cpu"


# ----------------------------------------------------------------------
# Stdout lines must survive tail truncation (VERDICT item 6): the driver
# keeps only the LAST ~2 KB of stdout and parses the last JSON line, and
# the r05 run's single rich headline line was cut mid-JSON ("parsed":
# null in BENCH_r05.json).  Every emitted line is therefore COMPACT and
# hard-capped; the rich artifact lives in bench_latency.json only.
COMPACT_LINE_MAX = 4096


class HeadlineEmitter:
    """Parse-proof artifact emission (the round-4 failure mode: the
    driver SIGKILLed the bench before its single end-of-run print, and
    the whole run evaporated).

    Emission is re-done after EVERY completed phase — catchup, each
    ladder rung, each config row — two ways: the RICH view rewrites
    ``bench_latency.json`` atomically, and stdout gets one COMPACT
    single-line JSON summary (``<= COMPACT_LINE_MAX`` bytes, enforced by
    progressive field stripping) so a consumer that keeps only a tail of
    the log still ends on a parseable line.  A kill at any point leaves
    the richest completed view on record, mirroring the reference
    harness collecting stats even during teardown
    (``stream-bench.sh:231-236``)."""

    def __init__(self, latency_path: str):
        self.latency_path = latency_path
        self.headline: dict = {}

    def update(self, **fields) -> None:
        self.headline.update(fields)

    def compact_line(self) -> str:
        """The bounded per-phase stdout summary.  Keeps the contract
        keys consumers rely on (metric/value/unit/vs_baseline/phase,
        per-config compact rows) and the PR-6 measurement headlines
        (method table winner, device-decode A/B); sheds detail fields
        until it fits the cap."""
        h = self.headline
        rows = []
        for c in (h.get("configs") or []):
            row = {"config": c.get("config")}
            for k in ("catchup_events_per_s", "oracle", "skipped",
                      "error"):
                if c.get(k) is not None:
                    row[k] = c[k]
            p = c.get("paced")
            if isinstance(p, dict):
                row["paced_p99_ms"] = p.get("p99_ms")
                row["sustained"] = p.get("sustained")
            rows.append(row)
        dev = h.get("device") or {}
        sweep = h.get("latency_sweep") or {}
        xfer = h.get("xfer") or {}
        bpe = {f: d.get("bytes_per_event")
               for f, d in (xfer.get("formats") or {}).items()
               if d.get("bytes_per_event") is not None}
        compact = {
            "compact": True,
            "phase": h.get("phase"),
            "metric": h.get("metric"),
            "value": h.get("value"),
            "unit": h.get("unit"),
            "vs_baseline": h.get("vs_baseline"),
            "platform": h.get("platform"),
            "device_busy_ratio": (h.get("occupancy") or {}).get(
                "device_busy_ratio"),
            "max_sustained_rate": sweep.get("max_sustained_rate"),
            "configs": rows,
            "device": {k: dev[k] for k in (
                "chunk_events", "encode_ms", "dispatch_ms",
                "device_ms_meas", "decode_probe_ms",
                "decode_dispatch_ms", "decode_chunk_ms_pipelined")
                if k in dev} or None,
            "methods": h.get("methods_compact"),
            "device_decode": h.get("device_decode_ab"),
            # sliding A/B (ISSUE 12): legacy unrolled vs sliced fold
            # ev/s over the same journal, row-equality oracle
            "sliding_evps": (h.get("sliding_ab") or {}).get(
                "sliding_evps"),
            "sliding_sliced_evps": (h.get("sliding_ab") or {}).get(
                "sliding_sliced_evps"),
            # measured bytes/event per wire format + the col-basis
            # packed/unpacked ratio (the MULTICHIP packed_col_ratio peer)
            "bytes_per_event": bpe or None,
            "packed_unpacked_ratio": xfer.get("packed_unpacked_ratio"),
            "artifact": os.path.basename(self.latency_path),
        }
        line = json.dumps(compact)
        for drop in ("bytes_per_event", "device_decode", "methods",
                     "device", "configs", "max_sustained_rate"):
            if len(line) <= COMPACT_LINE_MAX:
                break
            compact.pop(drop, None)
            line = json.dumps(compact)
        return line

    def emit(self) -> None:
        side = {
            "platform": self.headline.get("platform"),
            "catchup_events_per_s": self.headline.get("value"),
            "configs": self.headline.get("configs"),
            "phase": self.headline.get("phase"),
            # measured device keys belong in the committed artifact too
            # — the README's evidence contract says every quoted number
            # lives here, and occupancy was stdout-only until r5
            "device": self.headline.get("device"),
            # per-method kernel micro-bench + the device-decode A/B
            # (ISSUE 6): the measured inputs default_method and
            # jax.decode.device=auto consult
            "methods": self.headline.get("methods"),
            "device_decode_ab": self.headline.get("device_decode_ab"),
            # sliding A/B (ISSUE 12): the sliced-fold measurement
            # jax.sliding.sliced=auto consults, next to its oracle
            "sliding_ab": self.headline.get("sliding_ab"),
            # per-window latency attribution of the best catchup rep
            # (obs.lifecycle; STREAMBENCH_BENCH_ATTRIBUTION=1 or a
            # metrics dir opts in) — the per-stage ms, per WINDOW
            "attribution": self.headline.get("attribution"),
            "device_occupancy_meas": self.headline.get(
                "device_occupancy_meas"),
            # obs.occupancy sampled measurement (device_busy_ratio +
            # dispatch histogram + recompile counters) and the
            # perfetto-loadable span trace (obs.spans)
            "occupancy": self.headline.get("occupancy"),
            "span_trace": self.headline.get("span_trace"),
            "trace": self.headline.get("trace"),
            # data-path obs (ISSUE 9): measured host->device bytes per
            # wire format + the compiled kernels' memory footprints
            "xfer": self.headline.get("xfer"),
            "devmem": self.headline.get("devmem"),
            **(self.headline.get("latency_sweep") or {}),
        }
        try:
            tmp = self.latency_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(side, f, indent=1)
            os.replace(tmp, self.latency_path)
        except OSError as e:
            log(f"could not write {self.latency_path}: {e}")
        print(self.compact_line(), flush=True)


# ----------------------------------------------------------------------
def _trace_occupancy(logdir: str) -> dict | None:
    """Parse a ``jax.profiler`` trace for REAL device busy time.

    Reads the xplane protobuf the profiler wrote (via the
    tensorboard_plugin_profile schema available in the image) and sums
    event durations per device-plane line, taking each plane's busiest
    line as its busy time — the standard device-utilization reading.
    Returns None when no trace/parser is available; the bench then keeps
    its measured (blocking-sample) figure instead.
    """
    try:
        import glob as _glob

        try:
            from tensorflow.tsl.profiler.protobuf import xplane_pb2
        except ImportError:  # plugin layout varies across images
            from tensorboard_plugin_profile.protobuf import xplane_pb2

        paths = _glob.glob(os.path.join(
            logdir, "**", "*.xplane.pb"), recursive=True)
        if not paths:
            return None
        out: dict[str, float] = {}
        for path in paths:
            space = xplane_pb2.XSpace()
            with open(path, "rb") as f:
                space.ParseFromString(f.read())
            for plane in space.planes:
                name = plane.name
                if not ("TPU" in name or "device" in name.lower()
                        or "GPU" in name):
                    continue
                best_line_ps = 0
                for line in plane.lines:
                    total = sum(ev.duration_ps for ev in line.events)
                    best_line_ps = max(best_line_ps, total)
                if best_line_ps:
                    out[name] = max(out.get(name, 0.0),
                                    best_line_ps / 1e9)  # -> ms
        return {"device_busy_ms": out} if out else None
    except Exception as e:  # tolerant: diagnostics only
        log(f"trace parse failed (non-fatal): {e!r}")
        return None


# ----------------------------------------------------------------------
def _measure_device_time(cfg, mapping, broker) -> dict:
    """Blocking-sample the compiled device program: fold one K-batch chunk
    repeatedly with ``block_until_ready`` and report device+dispatch time
    per chunk/event.  This is the round-3 'device-side evidence' the r02
    verdict demanded — the async hot path never blocks, so only a
    deliberate sample can observe device time."""
    import jax

    from streambench_tpu.engine import AdAnalyticsEngine

    eng = AdAnalyticsEngine(cfg, mapping)
    n = cfg.jax_batch_size * cfg.jax_scan_batches
    lines = broker.reader(cfg.kafka_topic).poll(max_records=n)
    # Measure the SAME ingest path the catchup loop uses: block mode
    # (raw bytes through the native scanner) when the engine supports it.
    block = (b"\n".join(lines) + b"\n") if lines else b""
    use_block = eng.supports_block_ingest

    def ingest() -> None:
        if use_block:
            eng.process_block(block)
        else:
            eng.process_chunk(lines)

    def warm_all() -> None:
        """Compile every program any phase can hit: engine.warmup()
        covers the single-batch step, every power-of-2 scan size (the
        streaming loop's adaptive batching walks through them), and the
        drain; one real ingest warms the host block path on top."""
        eng.warmup()
        ingest()
        jax.block_until_ready(eng.state.counts)

    if len(lines) < max(2 * cfg.jax_batch_size, 1):
        if lines:  # still warm the jit cache on whatever exists
            warm_all()
        return {}
    n = len(lines)
    warm_all()
    iters = 10
    # Round-trip latency: block after every chunk (includes one full
    # dispatch->execute->sync cycle; on a tunneled backend this is RPC-
    # latency-bound and is NOT the sustained cost).
    t0 = time.perf_counter()
    for _ in range(iters):
        ingest()
        jax.block_until_ready(eng.state.counts)
    round_trip_s = (time.perf_counter() - t0) / iters
    # Pipelined throughput: enqueue all chunks, block once — what the
    # async hot loop actually pays per chunk.
    t0 = time.perf_counter()
    for _ in range(iters):
        ingest()
    jax.block_until_ready(eng.state.counts)
    pipelined_s = (time.perf_counter() - t0) / iters
    # host encode share (runs inside the ingest call on the host thread)
    t0 = time.perf_counter()
    for _ in range(iters):
        if use_block:
            eng.encoder.carve_block(block, cfg.jax_batch_size)
        else:
            for off in range(0, n, cfg.jax_batch_size):
                eng._encode(lines[off:off + cfg.jax_batch_size],
                            cfg.jax_batch_size)
    encode_s = (time.perf_counter() - t0) / iters

    # Per-stage sample for the staged ingest pipeline (ISSUE 3): read
    # (journal poll alone), encode (above), and dispatch (folding
    # pre-encoded batches, async enqueue + one trailing block) — the
    # three stages the pipeline overlaps, measured serially so the
    # committed artifact shows what the overlap can hide.
    rd = broker.reader(cfg.kafka_topic)
    n_bytes = len(block) if use_block else sum(len(l) + 1 for l in lines)
    t0 = time.perf_counter()
    for _ in range(iters):
        rd.seek(0)
        if use_block:
            rd.poll_block(n_bytes)
        else:
            rd.poll(max_records=n)
    read_s = (time.perf_counter() - t0) / iters
    rd.close()
    pre_batches = eng.encode_raw_block(block) if use_block \
        else eng.encode_chunk_lines(lines)
    t0 = time.perf_counter()
    for _ in range(iters):
        eng.fold_batches(pre_batches)
    jax.block_until_ready(eng.state.counts)
    dispatch_s = (time.perf_counter() - t0) / iters

    # MEASURED device time (VERDICT r3 #1: "non-estimated device-time
    # breakdown"): pre-encode the chunk once, pre-place the stacked scan
    # columns, then time ONLY the compiled fold — dispatch amortized over
    # iters, one block at the end.  No subtraction involved.
    import jax.numpy as jnp
    import numpy as np

    from streambench_tpu.ops import windowcount as wc

    batches, _ = (eng.encoder.carve_block(block, cfg.jax_batch_size)
                  if use_block else (
                      [eng._encode(lines[off:off + cfg.jax_batch_size],
                                   cfg.jax_batch_size)
                       for off in range(0, n, cfg.jax_batch_size)], 0))
    K = cfg.jax_scan_batches
    group = batches[:K]
    cols = [jax.device_put(jnp.asarray(np.stack(
        [getattr(b, name) for b in group])))
        for name in ("ad_idx", "event_type", "event_time", "valid")]
    jax.block_until_ready(cols)
    state = eng.state
    dev_iters = max(iters, 10)
    t0 = time.perf_counter()
    for _ in range(dev_iters):
        state = wc.scan_steps(state, eng.join_table, *cols,
                              divisor_ms=eng.divisor,
                              lateness_ms=eng.lateness,
                              method=eng.method)
    jax.block_until_ready(state.counts)
    group_n = sum(b.n for b in group)
    device_meas_s = (time.perf_counter() - t0) / dev_iters
    device_est_s = max(pipelined_s - encode_s, 0.0)

    # Device-decode arm (ISSUE 6): the same chunk through the raw-bytes
    # path — the host stage is a layout PROBE (no columns), the decode
    # itself runs inside the fused device step.  Per-stage keys mirror
    # the host arm's encode/dispatch split so the artifact shows where
    # host encode_ms went.
    decode: dict = {"decode_supported": False}
    try:
        import dataclasses as _dc

        eng_dd = AdAnalyticsEngine(
            _dc.replace(cfg, jax_decode_device="on"), mapping)
        if eng_dd._devdecode is not None and use_block and block:
            eng_dd.warmup()
            eng_dd.process_block(block)        # compile real shapes
            jax.block_until_ready(eng_dd.state.counts)
            dd = eng_dd._devdecode
            t0 = time.perf_counter()
            for _ in range(iters):
                dd.prepare(block)
            probe_s = (time.perf_counter() - t0) / iters
            pre_blocks = eng_dd.encode_raw_block(block)
            t0 = time.perf_counter()
            for _ in range(iters):
                eng_dd.fold_batches(pre_blocks)
            jax.block_until_ready(eng_dd.state.counts)
            dd_dispatch_s = (time.perf_counter() - t0) / iters
            t0 = time.perf_counter()
            for _ in range(iters):
                eng_dd.process_block(block)
            jax.block_until_ready(eng_dd.state.counts)
            dd_pipe_s = (time.perf_counter() - t0) / iters
            decode = {
                "decode_supported": True,
                "decode_probe_ms": round(probe_s * 1e3, 3),
                "decode_dispatch_ms": round(dd_dispatch_s * 1e3, 3),
                "decode_chunk_ms_pipelined": round(dd_pipe_s * 1e3, 3),
                "decode_fallback_rows": eng_dd._devdecode.rows_fallback,
            }
    except Exception as e:  # the decode sample must not kill the probe
        log(f"device-decode sample failed (non-fatal): {e!r}")
        decode = {"decode_supported": False, "decode_error": repr(e)}
    return {
        **decode,
        "chunk_events": n,
        "ingest_mode": "block" if use_block else "lines",
        "round_trip_ms": round(round_trip_s * 1e3, 3),
        "chunk_ms_pipelined": round(pipelined_s * 1e3, 3),
        # per-stage serial costs of the three overlapped ingest stages
        "read_ms": round(read_s * 1e3, 3),
        "encode_ms": round(encode_s * 1e3, 3),
        "dispatch_ms": round(dispatch_s * 1e3, 3),
        "device_ms_est": round(device_est_s * 1e3, 3),
        "device_ns_per_event": round(device_est_s * 1e9 / n, 1),
        # measured on-device fold (scan of K batches, blocking sample)
        "device_meas_events": group_n,
        "device_ms_meas": round(device_meas_s * 1e3, 3),
        "device_ns_per_event_meas": round(
            device_meas_s * 1e9 / max(group_n, 1), 1),
    }


def _xfer_probe(cfg, mapping, broker, max_events: int) -> tuple:
    """Measured host->device bytes per wire format (obs.xfer) + the
    device-memory ledger (obs.devmem) — ISSUE 9's data-path numbers.

    Replays a bounded slice of the SAME journal through two fresh
    engines sharing ONE TransferLedger: the natural arm (packed where
    eligible) and a forced separate-column arm
    (``STREAMBENCH_WIRE_FORMAT=unpacked``), so the artifact's
    ``bytes_per_event`` per format and the packed/unpacked ratio are
    MEASURED on real dispatches — the static "8 B/ev packed vs 13 B/ev
    columns" comment made a column.  The packed arm also runs the
    memory_analysis ledger (out-of-line compiles: probe-only, exactly
    the PR 7 rule).  Engine output is identical in both arms (the
    packed path is bit-equal by construction and tested), so no oracle
    pass is spent here."""
    from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner
    from streambench_tpu.io.fakeredis import make_store
    from streambench_tpu.io.redis_schema import as_redis, seed_campaigns
    from streambench_tpu.obs import (
        DeviceMemoryLedger,
        MetricsRegistry,
        TransferLedger,
    )

    ledger = TransferLedger(MetricsRegistry(), sample_every=4)
    devmem = None
    for wire in ("packed", "unpacked"):
        prev = os.environ.pop("STREAMBENCH_WIRE_FORMAT", None)
        if wire == "unpacked":
            os.environ["STREAMBENCH_WIRE_FORMAT"] = "unpacked"
        try:
            r = as_redis(make_store())
            seed_campaigns(r, sorted(set(mapping.values())))
            engine = AdAnalyticsEngine(cfg, mapping, redis=r)
            engine.attach_obs(MetricsRegistry(), xfer=ledger)
            runner = StreamRunner(engine, broker.reader(cfg.kafka_topic))
            runner.run_catchup(max_events=max_events)
            if wire == "packed":
                devmem = DeviceMemoryLedger()
                devmem.analyze_engine(engine)
                devmem.refresh_census()
            engine.close()
        finally:
            os.environ.pop("STREAMBENCH_WIRE_FORMAT", None)
            if prev is not None:
                os.environ["STREAMBENCH_WIRE_FORMAT"] = prev
    # third arm, best-effort: the raw-bytes device-decode wire format —
    # the ~250 B/ev the chip-session experiment (ROADMAP item 2) is
    # about.  Ineligible configs just skip the arm.
    try:
        r = as_redis(make_store())
        seed_campaigns(r, sorted(set(mapping.values())))
        engine = AdAnalyticsEngine(
            dataclasses.replace(cfg, jax_decode_device="on"),
            mapping, redis=r)
        if engine._devdecode is not None:
            engine.attach_obs(MetricsRegistry(), xfer=ledger)
            runner = StreamRunner(engine, broker.reader(cfg.kafka_topic))
            runner.run_catchup(max_events=max_events)
        engine.close()
    except Exception:
        pass
    return ledger.summary(), (devmem.summary() if devmem else None)


def _paced_latency_phase(cfg, mapping, broker, r, workdir,
                         rate: int, duration_s: float,
                         run_id: int = 0,
                         engine_factory=None,
                         expect_windows: bool = True,
                         flush_interval_ms: int | None = None,
                         latency_from_engine: bool = False,
                         producer_args: list | None = None,
                         slo_p99_ms: int | None = None) -> dict:
    """Pace events in real time at ``rate`` ev/s and report the canonical
    latency metric from what landed in Redis (``core.clj:130-149``),
    with ONE sample per unique window (not per campaign-window row).

    ``engine_factory(redis)`` swaps the engine family (config rows reuse
    this phase); ``expect_windows=False`` skips the canonical-schema
    latency read for engines that write no window rows (session/CMS);
    ``slo_p99_ms`` arms live burn-rate SLO tracking (obs.slo) over the
    run's writeback-latency histogram and records the verdict under
    ``"slo"`` — the machine-checked form of the SLA judgment."""
    from streambench_tpu.datagen import gen
    from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner
    from streambench_tpu.io.redis_schema import (
        read_window_latencies,
        seed_campaigns,
    )
    from streambench_tpu.metrics import decile_table

    # read_stats walks SMEMBERS campaigns (core.clj:131) — seed them
    # (pointless when the walk is skipped; at 1e6 tenants it costs ~10 s).
    if expect_windows and not latency_from_engine:
        seed_campaigns(r, sorted(set(mapping.values())))
    # run_id keeps the topic unique even when the ladder revisits a rate
    # (a reused topic would replay the previous run's journal from offset
    # 0 and poison both the throughput and the latency stamps).
    topic = f"{cfg.kafka_topic}-paced-{run_id}-{rate}"
    # Shard the load across producer processes + partitions so the sweep
    # probes the ENGINE's ceiling, not the generator's (the reference
    # scales load the same way: kafka.partitions + parallel producers).
    # With the native formatter one producer sustains ~500k ev/s, and on
    # small hosts every extra process is contention — so split late.
    n_prod = _n_producers(rate)
    broker.create_topic(topic, n_prod)

    # Engine construction + warmup happen BEFORE the producers launch:
    # any cold XLA compile saturates the core with LLVM threads for
    # seconds, and a producer starved mid-emit builds schedule lag that
    # the sweep would bill as engine latency (observed: one 11 s emit).
    if engine_factory is None:
        engine = AdAnalyticsEngine(cfg, mapping, redis=r)
    else:
        engine = engine_factory(r)
    engine.warmup()
    reader = (broker.multi_reader(topic) if n_prod > 1
              else broker.reader(topic))
    runner = StreamRunner(engine, reader,
                          flush_interval_ms=flush_interval_ms)
    # Live SLO gate (obs.slo): a background sampler ticks the burn-rate
    # tracker over the writeback-latency histogram once a second; the
    # verdict block lands in the rung result (and so in the artifact).
    slo = slo_sampler = None
    if slo_p99_ms:
        from streambench_tpu.obs import (
            MetricsRegistry,
            MetricsSampler,
            SloTracker,
            engine_collector,
        )

        slo_reg = MetricsRegistry()
        engine.attach_obs(slo_reg)
        slo = SloTracker(slo_reg, p99_ms=slo_p99_ms,
                         rate_evps=0, budget=0.01,
                         fast_s=15.0, slow_s=60.0)
        slo_sampler = MetricsSampler(
            os.path.join(workdir, f"paced-slo-{run_id}-{rate}.jsonl"),
            interval_ms=1000, registry=slo_reg)
        slo_sampler.add_collector(engine_collector(
            engine, reader=reader, runner=runner, registry=slo_reg))
        slo_sampler.add_collector(slo.collect)
        slo_sampler.start()

    # Producers run as their OWN processes (the reference's generator is a
    # separate JVM, stream-bench.sh:229): in-process they contend with the
    # engine for the GIL and the measured "unsustained" rate would be the
    # producer's starvation, not the engine's limit.
    from streambench_tpu.config import write_local_conf

    conf_path = os.path.join(workdir, f"paced-{run_id}-{rate}.yaml")
    write_local_conf(conf_path, {"kafka.topic": topic})
    procs = []
    for p_idx in range(n_prod):
        share = rate // n_prod + (1 if p_idx < rate % n_prod else 0)
        prod_log = os.path.join(workdir,
                                f"paced-{run_id}-{rate}-{p_idx}.log")
        with open(prod_log, "wb") as logf:
            procs.append((prod_log, subprocess.Popen(
                [sys.executable, "-m", "streambench_tpu.datagen", "-r",
                 "-t", str(share), "--duration", str(duration_s),
                 "--partition", str(p_idx),
                 "--configPath", conf_path, "--workdir", workdir,
                 "--brokerDir", broker.root] + (producer_args or []),
                stdout=logf, stderr=subprocess.STDOUT,
                cwd=os.path.dirname(os.path.abspath(__file__)))))
        # Producers get scheduling priority over the engine when
        # possible (root only): the reference's generator runs on its
        # own hardware, so on a shared core it must not be starved by
        # engine threads - that would bill scheduler deficit as engine
        # latency.  setpriority on the CHILD pid from here (preexec_fn
        # is unsafe in a threaded parent).
        try:
            os.setpriority(os.PRIO_PROCESS, procs[-1][1].pid, -5)
        except OSError:
            pass

    sent = {}
    behind = {"n": 0, "max_ms": 0.0}
    t0 = time.monotonic()
    # idle_timeout covers producer hiccups only; 15 s tolerates a slow
    # producer start on a loaded single-core host without masking a real
    # mid-run stall (the run is bounded by duration_s regardless).
    runner.run(duration_s=duration_s + 5.0, idle_timeout_s=15.0)
    # Reap EVERY producer before judging any of them — raising on the
    # first bad one would orphan the rest, which then keep emitting into
    # the next sweep rung's measurement window.
    failures = []
    for prod_log, proc in procs:
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            # SIGTERM first: the producer's handler stops the paced loop
            # cleanly and still reports its true "emitted N" count.
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
            log(f"paced producer at {rate}/s overran its duration; stopped")
        if proc.returncode not in (0, -9):  # -9 = our own overrun kill
            with open(prod_log, "r", errors="replace") as f:
                failures.append(
                    f"rc={proc.returncode}: {f.read()[-400:]}")
    formatters: set[str] = set()
    for prod_log, proc in procs:
        with open(prod_log, "r", errors="replace") as f:
            for line in f:
                if line.startswith("emitted "):
                    sent["n"] = sent.get("n", 0) + int(line.split()[1])
                elif line.startswith("Falling behind"):
                    behind["n"] += 1
                    behind["max_ms"] = max(
                        behind["max_ms"], float(line.split()[-1][:-2]))
                elif line.startswith("formatter: "):
                    formatters.add(line.split()[-1])
    # ONE degraded (pure-Python, ~60x slower) producer is enough to
    # poison a rung's latencies — report the slowest path seen.
    formatter = ("python" if "python" in formatters
                 else ("native" if formatters else None))
    if failures:
        raise RuntimeError(
            f"{len(failures)} paced producer(s) failed: {failures[0]}")
    engine.close()
    if slo_sampler is not None:
        # closed AFTER engine.close(): the writer has drained, so the
        # final tick sees every written window before the verdict
        slo_sampler.close(final=None)
    wall = time.monotonic() - t0
    log(engine.tracer.report())
    if runner.stats.events == 0 and sent.get("n"):
        # Observed once (round 5): producers emitted, engine read nothing
        # for the whole run.  Record everything needed to diagnose a
        # recurrence instead of leaving a bare zero in the artifact.
        for p_idx in range(n_prod):
            path = broker.topic_path(topic, p_idx)
            try:
                size = os.path.getsize(path)
            except OSError as e:
                size = f"stat failed: {e}"
            log(f"ZERO-CONSUMPTION DIAGNOSTIC: topic={topic} "
                f"partition={p_idx} journal={path} bytes={size} "
                f"reader_offset={getattr(reader, 'offset', '?')}")
    if not expect_windows:
        lats = []
        # Engines without canonical window rows can still carry the
        # latency metric (VERDICT r4 #5): the session engine measures
        # close->absorb latency in a device histogram.
        qfn = getattr(engine, "latency_quantile", None)
        if qfn is not None:
            vals, n_sessions = qfn((0.5, 0.9, 0.99, 1.0))
            if n_sessions:
                out_extra = dict(
                    p50_ms=round(vals[0], 1), p90_ms=round(vals[1], 1),
                    p99_ms=round(vals[2], 1), max_ms=round(vals[3], 1),
                    latency_sessions=n_sessions,
                    latency_kind="session close->absorb")
            else:
                out_extra = {}
        else:
            out_extra = {}
    elif latency_from_engine:
        # Engine-side fork-style accounting (abs_window_ts -> LAST
        # writeback latency, AdvertisingTopologyNative.java:521-532):
        # same per-unique-window quantity as the Redis walk, WITHOUT
        # enumerating the campaign universe — the canonical get-stats
        # walk is O(campaigns) and a 1e6-tenant row would spend minutes
        # walking idle campaigns for the same numbers.
        lats = sorted(engine.window_latency.values())
    else:
        lats = sorted(read_window_latencies(r).values())
    out = {
        "rate": rate, "sent": sent.get("n"),
        "processed": runner.stats.events,
        "wall_s": round(wall, 1), "windows": len(lats),
        "generator_behind_events": behind["n"],
        "generator_behind_max_ms": behind["max_ms"],
        "generator_formatter": formatter,
        # independent wall-clock stall evidence from the engine's own
        # flush loop (StallDetector): the one-shot retry requires this
        # OR a generator gap on top of the percentile shape (ADVICE r5)
        "flush_stalls": runner.stall_detector.stalls,
        "flush_stall_max_ms": int(runner.stall_detector.max_gap_ms),
    }
    if slo is not None:
        out["slo"] = slo.verdict()
    log(f"paced phase: rate={rate}/s sent={sent.get('n')} "
        f"processed={runner.stats.events} wall={wall:.1f}s "
        f"unique_windows={len(lats)} behind={behind['n']} "
        f"behind_max={behind['max_ms']:.0f}ms formatter={formatter}")
    # the rung's topic is consumed; drop its journal so long sweeps don't
    # pile rate x duration x 250 B per rung onto tmpfs
    for p_idx in range(n_prod):
        try:
            os.unlink(broker.topic_path(topic, p_idx))
        except OSError:
            pass
    if not lats:
        if expect_windows:
            log("paced phase: no windows written — latency unavailable")
        elif out_extra:
            out.update(out_extra)
            log(f"session close->absorb latency at {rate} ev/s: "
                f"p50={out['p50_ms']} ms p99={out['p99_ms']} ms over "
                f"{out['latency_sessions']} closed sessions")
        return out
    pick = lambda q: lats[min(int(q * len(lats)), len(lats) - 1)]
    out.update(p50_ms=pick(0.50), p90_ms=pick(0.90), p99_ms=pick(0.99),
               max_ms=lats[-1])
    log(f"window latency (time_updated - window_ts) at {rate} ev/s: "
        f"p50={out['p50_ms']} ms p90={out['p90_ms']} ms "
        f"p99={out['p99_ms']} ms max={out['max_ms']} ms "
        f"over {len(lats)} unique windows")
    for rng_label, v in decile_table(lats):
        log(f"  decile {rng_label}: {v} ms")
    return out


MIN_RUNG_WINDOWS = 12
# Per-rung wall-time budget guard (VERDICT 6 / the BENCH_r04 rc-124
# lesson): a paced rung whose full duration would overrun the envelope
# is CLAMPED down to what fits (>= MIN_RUNG_S so it still yields a few
# unique windows) instead of either running past the driver's kill or
# silently vanishing.  A rung that cannot fit even clamped is skipped.
MIN_RUNG_S = 30.0
RUNG_MARGIN_S = 45.0


def _clamped_rung_duration(deadline: float | None, duration_s: float,
                           margin_s: float = RUNG_MARGIN_S,
                           now: float | None = None) -> float | None:
    """The duration one paced rung may use: the requested one when it
    fits the remaining budget (+margin for setup/teardown/judging),
    clamped down to the remaining room when only a shorter rung fits,
    None when not even ``MIN_RUNG_S`` does."""
    if deadline is None:
        return duration_s
    room = deadline - (time.monotonic() if now is None else now) - margin_s
    if room >= duration_s:
        return duration_s
    if room >= MIN_RUNG_S:
        return room
    return None


def _judge_rung(res: dict, sla_ms: int, duration_s: float,
                needs_windows: bool = True) -> None:
    """Annotate one paced rung with validity + sustained flags.

    PRODUCER HEALTH IS JUDGED FIRST (VERDICT r3 #2): a rung whose
    generator fell behind its own schedule, delivered materially less
    than rate x duration, or produced too few unique windows is not an
    engine measurement at all — it neither sustains nor fails the
    ladder; the ladder descends and tries a rate the host CAN generate.
    ``needs_windows=False`` for engines that write no canonical window
    rows (session/CMS): their "sustained" is keeping up with the load.
    """
    rate = res["rate"]
    sent = res.get("sent")
    behind_ms = res.get("generator_behind_max_ms") or 0
    expected = rate * duration_s
    reasons = []
    # Benign sub-5s scheduling lag shows up at high rates on shared-core
    # hosts and is already included in the observed latencies; tens of
    # seconds (round 3: 57.8 s) means the generator stopped generating.
    if behind_ms > 5_000:
        reasons.append(f"behind_max {behind_ms:.0f}ms")
    if sent is None or sent < 0.9 * expected:
        reasons.append(f"sent {sent} < 90% of {expected:.0f}")
    # duration-aware floor: the 125 s default yields 12-13 unique 10 s
    # windows; env-shortened smoke runs scale the requirement down
    need_windows = min(MIN_RUNG_WINDOWS, max(int(duration_s // 10), 1))
    if needs_windows and res.get("windows", 0) < need_windows:
        reasons.append(f"windows {res.get('windows', 0)} < "
                       f"{need_windows}")
    res["invalid_producer"] = bool(reasons)
    res["invalid_reasons"] = reasons or None
    p99 = res.get("p99_ms")
    if p99 is not None:
        # any engine that reports a p99 — canonical window rows OR the
        # session engine's close->absorb histogram — is judged on it
        latency_ok = p99 <= sla_ms
    else:
        latency_ok = not needs_windows
    res["sustained"] = (not reasons and latency_ok
                        and res["processed"] == sent)


# Independent-evidence thresholds for the stall retry (ADVICE r5): a
# producer that reported falling >= 1 s behind its own schedule, or a
# flush-loop wall-clock gap >= 3 s (3x the 1 Hz cadence, past the
# StallDetector's 2x warning threshold), corroborates a host/tunnel
# stall.  Without either, a tail-only blowout is treated as the
# engine's own regression and is NOT retried away.
STALL_EVIDENCE_BEHIND_MS = 1_000
STALL_EVIDENCE_FLUSH_GAP_MS = 3_000


def _stall_signature(res: dict, sla_ms: int) -> bool:
    """True when a failed paced run looks like a transient host/tunnel
    stall rather than the engine's limit.  Two conditions must BOTH
    hold (ADVICE r5 — the percentile shape alone can be produced by a
    real engine-side tail regression, e.g. a backed-up deferred-drain
    materialization, and must not be retried away):

    - the shape: every event was consumed and the MEDIAN window landed
      within the SLA — only the tail blew (a genuinely overloaded
      engine backs up continuously, dragging p50 past the SLA too);
    - independent stall evidence: the generator ALSO fell behind its
      own schedule (``behind_max`` gap), or the engine's flush loop
      recorded a wall-clock gap (``StallDetector.max_gap_ms``) — a
      host-wide pause some OTHER clock observed, not just the window
      latencies under judgment.
    """
    p50 = res.get("p50_ms")
    shape = (res.get("processed") == res.get("sent")
             and p50 is not None and p50 <= sla_ms
             and (res.get("p99_ms") or 0) > sla_ms)
    if not shape:
        return False
    behind = res.get("generator_behind_max_ms") or 0
    flush_gap = res.get("flush_stall_max_ms") or 0
    return (behind >= STALL_EVIDENCE_BEHIND_MS
            or flush_gap >= STALL_EVIDENCE_FLUSH_GAP_MS)


def _paced_with_stall_retry(run_paced, sla_ms: int, *, deadline: float,
                            reserve_s: float, key: str,
                            on_first=None) -> dict:
    """One config-row paced run with the ladder's one-shot
    stall-signature retry: a failed-but-median-within-SLA attempt (a
    multi-second host/tunnel stall inside the row's single paced run —
    weather, not the engine's limit) is re-run once when the time
    budget allows.  The first attempt is stamped ``stall_retried`` (the
    same key the ladder uses, so artifact consumers count retries one
    way), handed to ``on_first`` BEFORE the retry launches (so a
    raising retry can only add data, never destroy the measured
    attempt), and nested into the retry's ``stall_retry_of``.
    ``run_paced(attempt)`` must run AND judge one paced phase."""
    paced = run_paced(0)
    if (not paced["sustained"] and not paced["invalid_producer"]
            and _stall_signature(paced, sla_ms)
            and time.monotonic() + reserve_s < deadline):
        log(f"config [{key}] paced: retrying once — stall signature "
            f"(p50 {paced.get('p50_ms')} ms within SLA, only the tail "
            "blew)")
        paced["stall_retried"] = True
        if on_first is not None:
            on_first(paced)
        retry = run_paced(1)
        retry["stall_retry_of"] = paced
        return retry
    return paced


def _latency_sweep(cfg, mapping, broker, workdir, start_rate: int,
                   duration_s: float, sla_ms: int,
                   max_runs: int = 4, rate_ceiling: int | None = None,
                   deadline: float | None = None,
                   progress=None) -> dict:
    """Escalating-rate ladder (the reference's experimental method: find
    the max load the engine sustains at bounded latency,
    ``README.markdown:36-37``).  Starts at ``start_rate`` (the baseline
    load); each sustained run escalates 1.5x, each failed OR invalid run
    halves — adaptive descent converges on a rate the host can both
    generate and sustain, instead of burning the run budget retrying a
    rate the producer already proved it cannot emit.  A rate counts as
    sustained only on a VALID rung (healthy producer, >= 12 unique
    windows) where the engine consumed everything sent and p99
    unique-window latency is within the SLA."""
    from streambench_tpu.io.fakeredis import make_store
    from streambench_tpu.io.redis_schema import as_redis

    results = []
    best = None
    rate = start_rate
    run_id = 0
    runs_allowed = max_runs
    stall_retry_used = False
    while run_id < runs_allowed:
        rung_s = _clamped_rung_duration(deadline, duration_s)
        if rung_s is None:
            log("latency sweep stopped: bench time budget would be "
                "exceeded (headline must still print)")
            break
        if rung_s < duration_s:
            log(f"latency sweep rung clamped to {rung_s:.0f}s by the "
                "bench time budget")
        res = _paced_latency_phase(cfg, mapping, broker,
                                   as_redis(make_store()), workdir,
                                   rate, rung_s, run_id=run_id,
                                   slo_p99_ms=sla_ms)
        if rung_s < duration_s:
            res["duration_clamped_s"] = round(rung_s, 1)
        run_id += 1
        results.append(res)
        _judge_rung(res, sla_ms, rung_s)
        sustained = res["sustained"]
        if sustained:
            best = max(best or 0, rate)
        if progress is not None:  # re-emit after every completed rung
            progress({"sla_ms": sla_ms, "duration_s": duration_s,
                      "max_sustained_rate": best, "rates": results})
        log(f"rate {rate}/s: {'SUSTAINED' if sustained else 'NOT sustained'}"
            f" (p99={res.get('p99_ms')} ms, sla={sla_ms} ms"
            + (f", rung invalid: {res['invalid_reasons']}"
               if res["invalid_producer"] else "")
            + ")")
        if sustained:
            rate = int(rate * 1.5)
            if rate_ceiling and rate > rate_ceiling:
                break  # can't sustain beyond catchup throughput anyway
        else:
            if (not stall_retry_used and not res["invalid_producer"]
                    and _stall_signature(res, sla_ms)
                    and _clamped_rung_duration(deadline, duration_s)
                    is not None):
                # budget re-checked HERE so the flag is only stamped on
                # a rung whose retry actually runs (the loop-top check
                # would otherwise break first and record a phantom
                # retry)
                # Stall signature: the MAJORITY of windows landed within
                # the SLA and only the tail blew (a multi-second
                # host/tunnel stall inside a 2-minute rung, not the
                # engine's limit — recorded r5 cases: p50 11.6 s with
                # p99 27 s, and p50 11.4 s with p90 18.7 s; each one
                # anomalous rung halved the whole ladder).  A genuinely
                # overloaded engine backs up continuously and blows p50
                # too.  Re-run the same rate ONCE; both attempts stay
                # in the artifact.
                stall_retry_used = True
                res["stall_retried"] = True
                runs_allowed = max_runs + 1
                log(f"rate {rate}/s: retrying once — stall signature "
                    f"(p50 {res.get('p50_ms')} ms within SLA, only the "
                    "tail blew)")
                continue
            rate = max(int(rate * 0.5), 1_000)
            if best is not None and rate <= best:
                break
            if rate == 1_000 and results and results[-1]["rate"] == rate:
                break  # floor reached twice: stop burning budget
    return {"sla_ms": sla_ms, "duration_s": duration_s,
            "max_sustained_rate": best, "rates": results}


def _run_all_configs(cfg, mapping, broker, wd, n_events: int,
                     paced_secs: float, paced_rate: int,
                     sla_ms: int, deadline: float,
                     on_row=None) -> list[dict]:
    """BASELINE configs #2-#5, one measured row each (VERDICT r3 #5:
    'BASELINE names five configs, the artifact measures one').

    Each row = catchup throughput over the shared journal + a short
    paced phase at a modest rate.  Config #5 (sharded 1e6-campaign
    multi-tenant) generates its own dataset and runs the mesh-sharded
    engine over every available device (campaign-sharded state — on one
    chip the mesh is (1,1) but the shard_map/psum path is what runs)."""
    import jax

    from streambench_tpu.config import default_config
    from streambench_tpu.datagen import gen
    from streambench_tpu.engine import StreamRunner
    from streambench_tpu.engine.sketches import (
        HLLDistinctEngine,
        SessionCMSEngine,
        SlidingTDigestEngine,
    )
    from streambench_tpu.io.fakeredis import make_store
    from streambench_tpu.io.journal import FileBroker
    from streambench_tpu.io.redis_schema import as_redis, seed_campaigns
    from streambench_tpu.parallel import ShardedWindowEngine, build_mesh

    # Sketch states replicate per (campaign, slot): keep their rings
    # modest and let span-guard drains (deferred, non-blocking) recycle
    # slots — HLL at the catchup ring's W=2048 would be a [C, 2048, R]
    # register block for no measurement benefit.
    cfg_sketch = default_config(jax_window_slots=64,
                                jax_scan_batches=cfg.jax_scan_batches,
                                jax_batch_size=cfg.jax_batch_size,
                                jax_encode_workers=cfg.jax_encode_workers)

    rows: list[dict] = []

    def add(row: dict) -> None:
        rows.append(row)
        if on_row is not None:  # re-emit the artifact after every row
            on_row(rows)

    def measure(key: str, factory, cfg_row, mapping_row, broker_row,
                wd_row, expect_windows: bool = True,
                flush_interval_ms: int | None = None,
                margin_s: float = 90,
                latency_from_engine: bool = False,
                producer_args: list | None = None) -> None:
        if time.monotonic() + paced_secs + margin_s > deadline:
            add({"config": key, "skipped":
                         "bench time budget exhausted"})
            return
        # Best-of-N catchup, same rationale as the headline's reps: the
        # single-core host shows episodic multi-second degradation
        # windows, and one unlucky rep misreports the engine by 2-4x
        # (round 5 recorded HLL at 414k where a clean rep measures ~1M).
        reps_row = max(int(os.environ.get(
            "STREAMBENCH_BENCH_CONFIG_REPS", "2")), 1)
        camps = sorted(set(mapping_row.values()))  # loop-invariant
        seed = len(camps) <= 100_000  # nothing reads the set past that
        best = None  # (events_per_s, stats, engine)
        rep_values = []  # EVERY completed rep, recorded in the artifact
        err = None
        for rep in range(reps_row):
            if best is not None and (time.monotonic() + paced_secs
                                     + margin_s > deadline):
                break  # keep the rep we have; protect the paced phase
            engine = None
            try:
                r = as_redis(make_store())
                if seed:
                    seed_campaigns(r, camps)
                engine = factory(r)
                # Compile EVERY program the run can hit before the clock
                # starts: without this, rep 1 of every config row billed
                # the XLA compiles to the measurement (the compact drain
                # alone is ~7-12 s at C=1e6 on the tunneled chip — the
                # recorded rep-1-always-slower pattern was exactly this).
                engine.warmup()
                runner = StreamRunner(
                    engine, broker_row.reader(cfg_row.kafka_topic),
                    flush_interval_ms=flush_interval_ms)
                t0 = time.monotonic()
                stats = runner.run_catchup()
            except Exception as e:  # a failed rep must not kill the row
                log(f"config [{key}] catchup rep {rep + 1} failed "
                    f"(non-fatal): {e!r}")
                err = e
                if engine is not None:
                    try:  # release pool threads/device state before the
                        engine.close()  # next rep builds another engine
                    except Exception:
                        pass
                continue
            engine.close()
            total_s = max(time.monotonic() - t0, 1e-9)
            v = stats.events / total_s
            rep_values.append(round(v, 1))
            log(f"config [{key}] catchup rep {rep + 1}/{reps_row}: "
                f"{v:,.0f} ev/s")
            if best is None or v > best[0]:
                best = (v, stats, engine)
        if best is None:
            add({"config": key, "error": repr(err)})
            return
        v, stats, engine = best
        row = {
            "config": key,
            "catchup_events": stats.events,
            "catchup_events_per_s": round(v, 1),
            # methodology on the record: max of these completed reps
            # (artifact rows stay comparable across rounds)
            "catchup_reps_events_per_s": rep_values,
            "dropped": int(engine.dropped),
        }
        if flush_interval_ms:
            row["flush_interval_ms"] = flush_interval_ms
        log(f"config [{key}]: catchup best-of-{len(rep_values)} "
            f"{row['catchup_events_per_s']:,.0f} ev/s "
            f"({stats.events} events)")
        try:
            def run_paced(attempt: int) -> dict:
                paced = _paced_latency_phase(
                    cfg_row, mapping_row, broker_row,
                    as_redis(make_store()),
                    wd_row, paced_rate, paced_secs,
                    run_id=9000 + len(rows) + 500 * attempt,
                    engine_factory=factory,
                    expect_windows=expect_windows,
                    flush_interval_ms=flush_interval_ms,
                    latency_from_engine=latency_from_engine,
                    producer_args=producer_args)
                _judge_rung(paced, sla_ms, paced_secs,
                            needs_windows=expect_windows)
                return paced

            row["paced"] = _paced_with_stall_retry(
                run_paced, sla_ms,
                deadline=deadline, reserve_s=paced_secs + margin_s,
                key=key,
                on_first=lambda p: row.__setitem__("paced", p))
        except Exception as e:  # a config row must not kill the artifact
            log(f"config [{key}] paced phase failed (non-fatal): {e!r}")
            row["paced_error"] = repr(e)
        add(row)

    measure("hll_distinct",
            lambda r: HLLDistinctEngine(cfg_sketch, mapping, redis=r),
            cfg_sketch, mapping, broker, wd)
    measure("sliding_tdigest",
            lambda r: SlidingTDigestEngine(cfg_sketch, mapping, redis=r),
            cfg_sketch, mapping, broker, wd)
    # Session row: the default 100-user universe at a paced rate never
    # pauses longer than the 30 s gap, so no session would close inside
    # the row and the latency histogram would stay empty.  A user
    # universe sized to the rate (mean inter-arrival ~4 s against a 5 s
    # gap) gives a steady closure stream whose close->absorb latency is
    # the row's metric (VERDICT r4 #5).  The universe is split across
    # however many producer processes the rate shards into, and the
    # engine's session-slot capacity scales to hold it.
    sess_users = max(50_000, 4 * paced_rate)
    sess_cap = 1 << max(16, (2 * sess_users - 1).bit_length())
    sess_n_prod = _n_producers(paced_rate)
    measure("session_cms",
            lambda r: SessionCMSEngine(cfg_sketch, mapping, redis=r,
                                       gap_ms=5_000,
                                       user_capacity=sess_cap),
            cfg_sketch, mapping, broker, wd, expect_windows=False,
            producer_args=["--users",
                           str(max(sess_users // sess_n_prod, 1000))])

    # Config #5: 1e6-campaign multi-tenant, campaign-sharded mesh state.
    if time.monotonic() + paced_secs + 300 > deadline:
        add({"config": "sharded_1e6",
                     "skipped": "bench time budget exhausted"})
        return rows
    try:
        wd5 = os.path.join(wd, "config5")
        os.makedirs(wd5, exist_ok=True)
        broker5 = FileBroker(os.path.join(wd5, "broker"))
        # 1M events: at config5's ~150-200k ev/s a 500k catchup measures
        # only ~3 s — short enough that one host hiccup halves the
        # recorded number (observed 91k vs 193k across clean runs)
        ev5 = min(n_events, int(os.environ.get(
            "STREAMBENCH_BENCH_CONFIG5_EVENTS", "1000000")))
        # scan_batches=1: with the 64-slot ring every 16-batch group
        # outspans the span guard, so the scanned fold NEVER executes for
        # this row — but warmup would still compile all 5 scan shapes,
        # and each shard_map scan at C=1e6 is minutes of XLA compile on
        # a small host (the round-5 bench lost its config5 paced phase
        # to exactly that).  Per-batch folding is what actually runs.
        cfg5 = default_config(jax_window_slots=64,
                              jax_scan_batches=1,
                              jax_batch_size=cfg.jax_batch_size,
                              jax_num_campaigns=1_000_000,
                              jax_ads_per_campaign=1)
        t0 = time.monotonic()
        gen.do_setup(None, cfg5, broker=broker5, events_num=ev5,
                     num_campaigns=1_000_000, ads_per_campaign=1,
                     rng=random.Random(7), workdir=wd5)
        mapping5 = gen.load_ad_mapping_file(
            os.path.join(wd5, gen.AD_TO_CAMPAIGN_FILE))
        log(f"config5 dataset: {ev5} events over 1e6 campaigns in "
            f"{time.monotonic()-t0:.1f}s")
        devs = jax.devices()
        mesh = build_mesh(data=1, campaign=len(devs), devices=devs)
        # Drains gather only the host-tracked dirty campaign rows
        # (engine.pipeline._track_dirty_rows), so a drain at 1e6
        # campaigns costs ~30 ms, not a [1e6, W] host walk — a 2 s
        # cadence keeps time_updated (= window span 10 s + flush lag)
        # comfortably inside the 15 s SLA.
        measure("sharded_1e6",
                lambda r: ShardedWindowEngine(cfg5, mapping5, mesh,
                                              redis=r),
                cfg5, mapping5, broker5, wd5,
                flush_interval_ms=2_000, margin_s=240,
                latency_from_engine=True)
    except Exception as e:
        log(f"config5 row failed (non-fatal): {e!r}")
        add({"config": "sharded_1e6", "error": repr(e)})
    return rows


def main() -> int:
    # 2M events: at ~1M+ ev/s catchup the old 500k default measured well
    # under a second of wall time; this keeps the measurement window in
    # whole seconds without stretching generation unreasonably.
    n_events = int(os.environ.get("STREAMBENCH_BENCH_EVENTS", "2000000"))
    # Hard wall-clock budget for the WHOLE process, probe included
    # (round 4: probe time was budgeted on top and the driver's kill
    # landed before the single end-of-run print).  The envelope is
    # enforced two ways: every phase checks the deadline before starting,
    # and the headline is re-emitted after every completed phase so even
    # a kill inside a phase loses only that phase.
    # 840 s default: the harness driver kills at 870 s (BENCH_r04 died
    # rc-124 to exactly this); the envelope must end, artifact emitted,
    # BEFORE that kill.  Raise explicitly for longer standalone runs.
    budget_s = float(os.environ.get("STREAMBENCH_BENCH_BUDGET_S", "840"))
    paced_rate = int(os.environ.get("STREAMBENCH_BENCH_PACED_RATE", "0"))
    paced_dur = float(os.environ.get("STREAMBENCH_BENCH_PACED_SECS", "125"))
    sla_ms = int(os.environ.get("STREAMBENCH_BENCH_SLA_MS", "15000"))
    # Catchup-tuned engine geometry: the ring sized to hold the default
    # journal's full event-time span (2M events x 10 ms = ~5.6 h;
    # W=2048 slots x 10 s ~= 5.7 h safe span -> no mid-run span-guard
    # drains; they'd be deferred/non-blocking anyway, but zero keeps the
    # measured regime uniform) and K batches folded per dispatch.
    window_slots = int(os.environ.get("STREAMBENCH_BENCH_WINDOW_SLOTS",
                                      "2048"))
    batch_size = int(os.environ.get("STREAMBENCH_BENCH_BATCH", "8192"))

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from streambench_tpu.utils.platform import pin_jax_platform

    bench_deadline = _T0 + budget_s
    # A parseable line must exist on stdout BEFORE the probe: a wedged
    # chip burns the whole probe window, and a driver whose kill timeout
    # is shorter than the budget would otherwise find no JSON at all.
    emitter = HeadlineEmitter(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_latency.json"))
    emitter.update(metric="sustained events/sec (oracle PENDING)",
                   value=0.0, unit="events/s", vs_baseline=0.0,
                   platform="pending", configs=[], phase="probe")
    emitter.emit()
    # The probe window fits INSIDE the envelope: insisting on the TPU is
    # worth minutes, but never the phases' whole budget.  The reserve is
    # derived from the knobs that size the measured phases:
    # setup+warmup+catchup+oracle (~7 min at the 2M-event default) plus
    # two sweep rungs and the four config rows.
    phase_reserve = (420.0 + 2 * paced_dur
                     + 4 * float(os.environ.get(
                         "STREAMBENCH_BENCH_CONFIG_PACED_SECS", "45")))
    probe_window = max(min(PROBE_WINDOW_S,
                           bench_deadline - time.monotonic()
                           - phase_reserve), 0.0)
    if probe_window < PROBE_WINDOW_S:
        log(f"probe window clamped to {probe_window:.0f}s by the "
            f"{budget_s:.0f}s envelope (phase reserve "
            f"{phase_reserve:.0f}s)")
    platform = resolve_platform(probe_window)
    pin_jax_platform(platform)

    # Deeper scan on accelerators: each dispatch crosses the (possibly
    # tunneled) runtime once, so fold more batches per call where that
    # round trip is the expensive part; on CPU the extra stacking buys
    # nothing.
    scan_default = "8" if platform == "cpu" else "16"
    scan_batches = int(os.environ.get("STREAMBENCH_BENCH_SCAN_BATCHES",
                                      scan_default))

    import jax

    from streambench_tpu.config import default_config
    from streambench_tpu.datagen import gen
    from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner
    from streambench_tpu.io.fakeredis import make_store
    from streambench_tpu.io.journal import FileBroker
    from streambench_tpu.io.redis_schema import as_redis

    backend = jax.default_backend()
    log(f"backend={backend} devices={len(jax.devices())} events={n_events}")
    emitter.update(platform=backend, phase="setup")
    emitter.emit()
    # Multi-core hosts parse journal blocks on the encode pool (carve at
    # record boundaries, workers scan disjoint regions); on 1-2 cores the
    # pool is pure overhead.
    cpu_n = os.cpu_count() or 1
    encode_workers = int(os.environ.get(
        "STREAMBENCH_BENCH_ENCODE_WORKERS",
        str(min(6, cpu_n - 1) if cpu_n >= 4 else 1)))
    log(f"host cores={cpu_n} encode_workers={encode_workers}")
    cfg = default_config(jax_window_slots=window_slots,
                         jax_scan_batches=scan_batches,
                         jax_batch_size=batch_size,
                         jax_encode_workers=encode_workers)

    # RAM-backed workdir when available: the file broker is the in-process
    # Kafka analog, and on a disk-backed /tmp the paced producers' write()
    # calls can block for SECONDS under dirty-page writeback throttling
    # (observed as multi-second producer stalls right after the 500 MB
    # catchup journal was written) — which would be charged to the engine
    # as window latency.  Only if tmpfs can hold the run: ~250 B/event x
    # (journal + topic copy) + the paced rungs' topics, with headroom.
    tmp_base = os.environ.get("STREAMBENCH_BENCH_TMPDIR")
    need_bytes = n_events * 250 * 2 + 10 * (1 << 30)
    if tmp_base is None:
        try:
            sv = os.statvfs("/dev/shm")
            if sv.f_bavail * sv.f_frsize >= need_bytes:
                tmp_base = "/dev/shm"
            else:
                log("tmpfs too small for the dataset; workdir stays on "
                    "disk (paced latencies may include writeback stalls)")
        except OSError:
            pass
    with tempfile.TemporaryDirectory(dir=tmp_base) as wd:
        r = as_redis(make_store())
        broker = FileBroker(os.path.join(wd, "broker"))
        t0 = time.monotonic()
        gen.do_setup(r, cfg, broker=broker, events_num=n_events,
                     rng=random.Random(42), workdir=wd)
        log(f"generated {n_events} events in {time.monotonic()-t0:.1f}s")
        mapping = gen.load_ad_mapping_file(
            os.path.join(wd, gen.AD_TO_CAMPAIGN_FILE))

        # Warm the jit cache with a same-shape engine so compile time
        # (~20-40 s on first TPU use) doesn't pollute the measurement;
        # the same warm pass samples device time with blocking waits
        # (the async hot path never observes device completion).
        t0 = time.monotonic()
        device = _measure_device_time(cfg, mapping, broker)
        log(f"jit warmup done in {time.monotonic()-t0:.1f}s")
        if device:
            log(f"device sample: chunk of {device['chunk_events']} events — "
                f"round-trip {device['round_trip_ms']} ms, pipelined "
                f"{device['chunk_ms_pipelined']} ms/chunk (host encode "
                f"{device['encode_ms']} ms, device+dispatch est "
                f"{device['device_ms_est']} ms = "
                f"{device['device_ns_per_event']} ns/event)")
            if device.get("decode_supported"):
                log(f"device-decode sample: probe {device['decode_probe_ms']}"
                    f" ms + dispatch {device['decode_dispatch_ms']} ms "
                    f"(pipelined {device['decode_chunk_ms_pipelined']} ms) "
                    f"vs host encode {device['encode_ms']} ms — the encode "
                    "stage builds no columns on the decode arm")

        # Kernel-method micro-bench (VERDICT 7): per-method ns/event at
        # (this backend, this campaign bucket), winner cached so
        # engine.pipeline.default_method picks from measurement for
        # every engine built from here on.
        methods = None
        try:
            from streambench_tpu.ops import methodbench

            t0 = time.monotonic()
            methods = methodbench.measure_and_record(
                num_campaigns=cfg.jax_num_campaigns,
                window_slots=min(cfg.jax_window_slots, 64),
                batch_size=min(cfg.jax_batch_size, 4096),
                iters=10)
            log(f"method micro-bench ({time.monotonic() - t0:.1f}s): "
                f"winner={methods['winner']} "
                + " ".join(f"{m}={v.get('ns_per_event', 'err')}ns/ev"
                           for m, v in methods["methods"].items()))
        except Exception as e:
            log(f"method micro-bench failed (non-fatal): {e!r}")
        emitter.update(
            methods=methods,
            methods_compact=(
                {"winner": methods["winner"],
                 "ns_per_event": {m: v.get("ns_per_event")
                                  for m, v in methods["methods"].items()}}
                if methods else None))

        # optional kernel override (scatter|onehot|matmul|pallas); default
        # is the per-backend choice in engine.pipeline.default_method
        method = os.environ.get("STREAMBENCH_BENCH_METHOD") or None
        # Best-of-N catchup: the host shows episodic multi-second
        # degradation windows (system-time spikes, zero steal), and a
        # single-shot measurement at an unlucky moment would misreport
        # the engine by 2-3x.  Each rep replays the same journal through
        # a FRESH engine + store; the best rep's store is oracle-checked.
        reps = max(int(os.environ.get("STREAMBENCH_BENCH_REPS", "3")), 1)
        from streambench_tpu.io.redis_schema import seed_campaigns

        # Device trace (VERDICT r3 #1: "record a jax.profiler device
        # trace"): captured around one catchup rep, written OUTSIDE the
        # temp workdir so the artifact survives the run.  Default on for
        # hardware backends; STREAMBENCH_BENCH_TRACE=1/0 overrides.
        want_trace = os.environ.get("STREAMBENCH_BENCH_TRACE",
                                    "1" if backend != "cpu" else "0") == "1"
        trace_dir = os.environ.get(
            "STREAMBENCH_BENCH_TRACE_DIR",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "bench-trace"))
        if want_trace:
            # only THIS run's trace may exist: the parser globs every
            # xplane file under the dir, and a stale (longer) run's busy
            # time would be divided by this run's wall clock
            import shutil

            shutil.rmtree(trace_dir, ignore_errors=True)
        from streambench_tpu.trace import device_trace

        # Opt-in telemetry journal per catchup rep (obs/): set
        # STREAMBENCH_BENCH_METRICS_DIR to record each rep's live
        # throughput/backlog/latency time series for later
        # `python -m streambench_tpu.obs report|diff` reading — the
        # before/after evidence channel for perf PRs.
        metrics_dir = os.environ.get("STREAMBENCH_BENCH_METRICS_DIR")
        if metrics_dir:
            os.makedirs(metrics_dir, exist_ok=True)
        # Per-window latency attribution (obs.lifecycle): on whenever
        # telemetry is already journaling, or alone via
        # STREAMBENCH_BENCH_ATTRIBUTION=1.  Off by default — the stamp
        # upkeep (np.unique per fold) is small but nonzero, and the
        # headline throughput must not carry silent instrumentation.
        want_attr = bool(metrics_dir) or os.environ.get(
            "STREAMBENCH_BENCH_ATTRIBUTION", "0") == "1"
        # MEASURED device occupancy + span tracing (obs.occupancy /
        # obs.spans) ride every catchup rep by default.  Sampling is
        # 1-in-4 HERE (not the config default 32): a catchup rep folds
        # K=16-batch scan groups, so a 2M-event run is only ~16
        # dispatches — a sparser cadence measures nothing.  Each sample
        # syncs a scan-group boundary the async queue would have
        # reached within one chunk anyway.  The span ring is a
        # lock+append per stage span.  The README's occupancy claim
        # comes from THIS gauge now, not the pipelined-minus-encode
        # estimate.
        want_occ = os.environ.get("STREAMBENCH_BENCH_OCCUPANCY",
                                  "1") == "1"
        occ_sample = max(int(os.environ.get(
            "STREAMBENCH_BENCH_OCCUPANCY_SAMPLE", "4")), 1)
        want_spans = os.environ.get("STREAMBENCH_BENCH_SPANS",
                                    "1") == "1"

        best = None  # (value, stats, engine, store, total_s, attribution)
        best_obs = (None, None)   # (occupancy summary, span tracer)
        trace_occ = None
        rep_cost_s = 0.0
        for rep in range(reps):
            # Extra reps are a variance reducer, not a requirement: skip
            # them rather than risk the envelope (oracle + emission need
            # the reserve).
            if rep and time.monotonic() + rep_cost_s + 180 > bench_deadline:
                log(f"skipping catchup reps {rep + 1}..{reps}: time budget")
                break
            # every rep gets an identical fresh store (the setup store
            # additionally holds the ad-mapping keys; reps must be
            # interchangeable)
            r_rep = as_redis(make_store())
            seed_campaigns(r_rep, sorted(set(mapping.values())))
            engine = AdAnalyticsEngine(cfg, mapping, redis=r_rep,
                                       method=method)
            rep_reader = broker.reader(cfg.kafka_topic)
            from streambench_tpu.obs import (
                MetricsRegistry,
                OccupancySampler,
                SpanTracer,
            )

            obs_reg = MetricsRegistry()
            occ = spans_tr = None
            if want_occ:
                occ = OccupancySampler(obs_reg, sample_every=occ_sample)
                # every program was compiled by the device probe above;
                # any compile from here on is a mid-run stall the
                # artifact should show (steady-state-zero invariant)
                occ.mark_steady()
            if want_spans:
                spans_tr = SpanTracer(capacity=8192, registry=obs_reg)
            # STREAMBENCH_BENCH_INGEST=off|on|auto overrides the staged
            # ingest pipeline for the headline catchup (default: config)
            runner = StreamRunner(
                engine, rep_reader,
                ingest_pipeline=os.environ.get(
                    "STREAMBENCH_BENCH_INGEST", "").strip().lower() or None,
                spans=spans_tr)
            obs_sampler = None
            if (want_attr or occ is not None or spans_tr is not None
                    or metrics_dir):
                engine.attach_obs(obs_reg, lifecycle=want_attr,
                                  spans=spans_tr, occupancy=occ)
            if metrics_dir:
                from streambench_tpu.obs import (
                    MetricsSampler,
                    engine_collector,
                )

                obs_sampler = MetricsSampler(
                    os.path.join(metrics_dir,
                                 f"bench-metrics-rep{rep + 1}.jsonl"),
                    interval_ms=int(os.environ.get(
                        "STREAMBENCH_BENCH_METRICS_INTERVAL_MS", "500")),
                    registry=obs_reg)
                obs_sampler.add_collector(engine_collector(
                    engine, reader=rep_reader, runner=runner,
                    registry=obs_reg))
                obs_sampler.start()
            # The measured interval covers ingest + device folds + the
            # FULL canonical Redis writeback (engine.close drains the
            # async writer): stopping the clock at run_catchup() would
            # let the writer finish the last flush off the books.
            tracing = want_trace and rep == 0
            t0 = time.monotonic()
            with device_trace(trace_dir if tracing else None):
                stats = runner.run_catchup()
                engine.close()
            total_s = max(time.monotonic() - t0, 1e-9)
            v = stats.events / total_s
            if obs_sampler is not None:
                obs_sampler.close(final={
                    "events": stats.events, "wall_s": round(total_s, 2),
                    "events_per_s": round(v, 1),
                    "windows_written": stats.windows_written,
                    "faults": stats.faults})
            log(f"catchup rep {rep + 1}/{reps}: {stats.events} events in "
                f"{total_s:.2f}s (ingest {stats.wall_s:.2f}s) = "
                f"{v:,.0f} ev/s; windows={stats.windows_written} "
                f"dropped={engine.dropped}"
                + (" [traced]" if tracing else ""))
            if tracing:
                parsed = _trace_occupancy(trace_dir)
                if parsed:
                    busy = max(parsed["device_busy_ms"].values())
                    trace_occ = {
                        "trace_dir": trace_dir,
                        "busy_ms_by_plane": {
                            k: round(v_, 1) for k, v_ in
                            parsed["device_busy_ms"].items()},
                        "occupancy": round(busy / (total_s * 1e3), 4),
                    }
                    log(f"trace: device busy {busy:.0f} ms over "
                        f"{total_s*1e3:.0f} ms wall = "
                        f"{trace_occ['occupancy']:.1%} occupancy")
            rep_cost_s = max(rep_cost_s, total_s)
            lc = getattr(engine, "_obs_lifecycle", None)
            occ_summary = occ.summary() if occ is not None else None
            if occ is not None:
                occ.close()   # stop counting compiles for this rep
            if occ_summary is not None:
                log(f"occupancy rep {rep + 1}: device_busy_ratio="
                    f"{occ_summary['device_busy_ratio']:.4f} "
                    f"({occ_summary['sampled']} sampled of "
                    f"{occ_summary['dispatches']} dispatches, "
                    f"steady compiles "
                    f"{(occ_summary.get('compiles') or {}).get('compiles_steady')})")
            if best is None or v > best[0]:
                best = (v, stats, engine, r_rep, total_s,
                        lc.summary() if lc is not None else None)
                best_obs = (occ_summary, spans_tr)
        value, stats, engine, r_best, total_s, attribution = best
        occupancy_meas, best_spans = best_obs
        span_trace = None
        if best_spans is not None and len(best_spans):
            trace_path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "trace_bench.json")
            best_spans.dump(trace_path, run="bench-catchup")
            span_trace = {"path": os.path.basename(trace_path),
                          "spans": len(best_spans),
                          "dropped": best_spans.dropped}
            log(f"span trace: {trace_path} ({span_trace['spans']} "
                f"spans) — perfetto-loadable; `python -m "
                f"streambench_tpu.obs trace` summarizes it")
        value = round(value, 1)
        log(f"engine: method={engine.method} W={engine.W} "
            f"B={engine.batch_size} K={engine.scan_batches} "
            f"best-of-{reps}")
        log(engine.tracer.report())
        util = None
        if device and total_s > 0:
            # from the MEASURED device-only fold time (blocking sample of
            # the compiled scan), not the pipelined-minus-encode estimate
            per_event_s = (device["device_ms_meas"] / 1e3
                           / max(device["device_meas_events"], 1))
            util = per_event_s * stats.events / total_s
            log(f"device occupancy during catchup (measured fold time x "
                f"events / wall): {util:.1%}")

        # The measured headline exists from here on; every later phase
        # enriches and RE-EMITS it (parsers take the last JSON line).
        exact_row = {
            "config": "exact_count",
            "catchup_events": stats.events,
            "catchup_events_per_s": value,
            "dropped": int(engine.dropped),
            "oracle": "pending",
            "paced": None,
        }
        # The metric string must not claim verification before the oracle
        # has run: a kill during check_correct leaves this line last.
        emitter.update(
            metric="sustained events/sec (oracle PENDING)",
            value=value, unit="events/s",
            vs_baseline=round(value / BASELINE_EVENTS_PER_S, 4),
            platform=backend,
            device=device or None,
            attribution=attribution,
            device_occupancy_meas=round(util, 4) if util else None,
            # the sampled-dispatch measurement (obs.occupancy): the
            # device_busy_ratio key README quotes, next to the older
            # fold-time extrapolation above for continuity
            occupancy=occupancy_meas,
            span_trace=span_trace,
            trace=trace_occ,
            latency_sweep=None,
            configs=[exact_row],
            phase="catchup (oracle pending)")
        emitter.emit()

        correct, differ, missing = gen.check_correct(
            r_best, workdir=wd, log=lambda s: None,
            time_divisor_ms=cfg.jax_time_divisor_ms)
        log(f"oracle: CORRECT={correct} DIFFER={differ} MISSING={missing}")
        if differ or missing or engine.dropped:
            log("BENCH INVALID: engine output incorrect")
            exact_row["oracle"] = (f"INVALID: differ={differ} "
                                   f"missing={missing} "
                                   f"dropped={int(engine.dropped)}")
            emitter.update(
                metric="sustained events/sec (oracle-verified)",
                value=0.0, vs_baseline=0.0, phase="invalid")
            emitter.emit()
            return 1
        exact_row["oracle"] = "exact"
        emitter.update(metric="sustained events/sec (oracle-verified)",
                       phase="catchup")
        emitter.emit()

        # Device-decode A/B (ISSUE 6): one catchup rep over the SAME
        # journal with decode on the device, oracle-checked, committed
        # either way; the measured winner feeds jax.decode.device=auto
        # through the shared measurement cache.
        dd_ab = None
        if device.get("decode_supported"):
            try:
                r_dd = as_redis(make_store())
                seed_campaigns(r_dd, sorted(set(mapping.values())))
                eng_dd = AdAnalyticsEngine(
                    dataclasses.replace(cfg, jax_decode_device="on"),
                    mapping, redis=r_dd, method=method)
                eng_dd.warmup()
                runner_dd = StreamRunner(
                    eng_dd, broker.reader(cfg.kafka_topic),
                    ingest_pipeline=os.environ.get(
                        "STREAMBENCH_BENCH_INGEST", "").strip().lower()
                    or None)
                t0 = time.monotonic()
                stats_dd = runner_dd.run_catchup()
                eng_dd.close()
                dd_s = max(time.monotonic() - t0, 1e-9)
                c_dd, d_dd, m_dd = gen.check_correct(
                    r_dd, workdir=wd, log=lambda s: None,
                    time_divisor_ms=cfg.jax_time_divisor_ms)
                v_dd = round(stats_dd.events / dd_s, 1)
                on_exact = not (d_dd or m_dd or int(eng_dd.dropped))
                dd_ab = {
                    "off_events_per_s": value,
                    "on_events_per_s": v_dd,
                    "on_oracle": ("exact" if on_exact else
                                  f"INVALID: differ={d_dd} "
                                  f"missing={m_dd} "
                                  f"dropped={int(eng_dd.dropped)}"),
                    "fallback_rows": eng_dd._devdecode.rows_fallback,
                    "winner": ("device" if on_exact and v_dd > value
                               else "host"),
                }
                log(f"device-decode A/B: off {value:,.0f} ev/s vs on "
                    f"{v_dd:,.0f} ev/s (oracle "
                    f"{dd_ab['on_oracle']}) -> auto gates "
                    f"{dd_ab['winner']}")
                try:
                    from streambench_tpu.ops import methodbench

                    methodbench.record(f"{backend}/devdecode", dd_ab)
                except Exception:
                    pass
            except Exception as e:  # the A/B must not kill the headline
                log(f"device-decode A/B failed (non-fatal): {e!r}")
                dd_ab = {"error": repr(e)}
        emitter.update(device_decode_ab=dd_ab, phase="device_decode_ab")
        emitter.emit()

        # Sliding A/B (ISSUE 12): the legacy unrolled fold vs the sliced
        # one-claim-one-scatter fold, each a full catchup over the SAME
        # journal with a fresh store.  Oracle = exact row equality
        # between the arms (the legacy arm is itself pinned to the
        # reference sliding model by tests/test_windows.py) plus equal
        # membership-granular dropped.  The measured sliding-family
        # table lands in the shared cache so jax.sliding.sliced=auto
        # resolves from measurement.
        sliding_ab = None
        if (os.environ.get("STREAMBENCH_BENCH_SLIDING", "1") != "0"
                and time.monotonic() + 180 < bench_deadline):
            try:
                from streambench_tpu.engine.sketches import (
                    SlidingTDigestEngine,
                )
                from streambench_tpu.io.redis_schema import (
                    read_seen_counts,
                )
                sl_table = None
                try:
                    from streambench_tpu.ops import methodbench

                    t0 = time.monotonic()
                    sl_table = methodbench.measure_and_record_sliding(
                        num_campaigns=cfg.jax_num_campaigns,
                        window_slots=max(
                            min(cfg.jax_window_slots, 2048), 128),
                        batch_size=min(cfg.jax_batch_size, 4096),
                        iters=10)
                    log(f"sliding micro-bench "
                        f"({time.monotonic() - t0:.1f}s): "
                        f"winner={sl_table['winner']} "
                        + " ".join(
                            f"{m}={v.get('ns_per_event', 'err')}ns/ev"
                            for m, v in sl_table["methods"].items()))
                except Exception as e:
                    log(f"sliding micro-bench failed (non-fatal): {e!r}")

                def _sliding_arm(mode: str):
                    """Best-of-N catchup (the headline/config-row
                    methodology: this 1-core host swings 2-4x run to
                    run; every rep's value is recorded)."""
                    reps_sl = max(int(os.environ.get(
                        "STREAMBENCH_BENCH_SLIDING_REPS", "3")), 1)
                    vals = []
                    rows = dropped = events_n = None
                    for _ in range(reps_sl):
                        if (vals and time.monotonic() + 90
                                > bench_deadline):
                            break
                        r_sl = as_redis(make_store())
                        seed_campaigns(r_sl,
                                       sorted(set(mapping.values())))
                        eng = SlidingTDigestEngine(
                            cfg, mapping, redis=r_sl, sliced=mode)
                        eng.warmup()
                        runner_sl = StreamRunner(
                            eng, broker.reader(cfg.kafka_topic))
                        t0 = time.monotonic()
                        stats_sl = runner_sl.run_catchup()
                        eng.close()
                        s = max(time.monotonic() - t0, 1e-9)
                        vals.append(round(stats_sl.events / s, 1))
                        dropped = int(eng.dropped)
                        events_n = stats_sl.events
                    # every rep replays the same journal into a fresh
                    # store: rows are deterministic, so the cross-arm
                    # oracle reads ONE store (the walk costs seconds at
                    # sliding row volumes — off the timed window, but
                    # on the bench budget)
                    rows = read_seen_counts(r_sl)
                    return max(vals), vals, rows, dropped, events_n

                v_leg, reps_leg, rows_leg, d_leg, ev_sl = \
                    _sliding_arm("off")
                v_sl, reps_sl_v, rows_sl, d_sl, _ = _sliding_arm("on")
                match = rows_leg == rows_sl and d_leg == d_sl
                sliding_ab = {
                    "events": ev_sl,
                    "sliding_evps": v_leg,
                    "sliding_sliced_evps": v_sl,
                    "reps_evps": reps_leg,
                    "sliced_reps_evps": reps_sl_v,
                    "dropped": d_leg,
                    "oracle": ("exact" if match else
                               f"ROWS DIFFER: legacy={len(rows_leg)} "
                               f"sliced={len(rows_sl)} "
                               f"dropped {d_leg}/{d_sl}"),
                    "winner": ("sliced" if match and v_sl > v_leg
                               else "legacy"),
                    "table": ({"winner": sl_table["winner"],
                               "ns_per_event": {
                                   m: v.get("ns_per_event")
                                   for m, v in
                                   sl_table["methods"].items()}}
                              if sl_table else None),
                }
                log(f"sliding A/B: legacy {v_leg:,.0f} ev/s vs sliced "
                    f"{v_sl:,.0f} ev/s ({v_sl / max(v_leg, 1e-9):.2f}x, "
                    f"oracle {sliding_ab['oracle']}) -> auto resolves "
                    f"{sliding_ab['winner']}")
            except Exception as e:  # must not kill the headline
                log(f"sliding A/B failed (non-fatal): {e!r}")
                sliding_ab = {"error": repr(e)}
        emitter.update(sliding_ab=sliding_ab, phase="sliding_ab")
        emitter.emit()

        # Data-path transfer + memory probe (ISSUE 9): measured
        # bytes/event per wire format on real dispatches + the compiled
        # kernels' memory_analysis footprints — the columns ROADMAP
        # items 1-2 gate the chip session on.  Bounded replay, never
        # fatal, skipped when the envelope is short.
        xfer_block = devmem_block = None
        if (os.environ.get("STREAMBENCH_BENCH_XFER", "1") != "0"
                and time.monotonic() + 150 < bench_deadline):
            try:
                xfer_events = int(os.environ.get(
                    "STREAMBENCH_BENCH_XFER_EVENTS", "200000"))
                xfer_block, devmem_block = _xfer_probe(
                    cfg, mapping, broker, xfer_events)
                fmts = (xfer_block or {}).get("formats") or {}
                log("xfer probe: " + ", ".join(
                    f"{f} {d['bytes_per_event']} B/ev"
                    for f, d in sorted(fmts.items())
                    if d.get("bytes_per_event") is not None)
                    + (f"; packed/unpacked ratio "
                       f"{xfer_block['packed_unpacked_ratio']} "
                       f"({xfer_block.get('ratio_basis')})"
                       if xfer_block.get("packed_unpacked_ratio")
                       is not None else ""))
                if devmem_block:
                    log(f"devmem: peak footprint "
                        f"{devmem_block['peak_footprint_bytes']:,} B "
                        f"(state {devmem_block['state_bytes']:,} B + "
                        f"largest kernel)")
            except Exception as e:
                log(f"xfer probe failed (non-fatal): {e!r}")
        emitter.update(xfer=xfer_block, devmem=devmem_block,
                       phase="xfer_probe")
        emitter.emit()

        # Phase 2: the reference's real metric — p99 window-writeback
        # latency under sustained paced load (core.clj:130-149), as an
        # escalating-rate sweep reporting the max rate the engine
        # sustains within the SLA.
        start_rate = paced_rate or int(min(BASELINE_EVENTS_PER_S,
                                           max(value / 2, 1_000)))
        sweep_runs = int(os.environ.get("STREAMBENCH_BENCH_SWEEP_RUNS",
                                        "4"))

        def sweep_progress(partial: dict) -> None:
            valid = [x for x in partial["rates"] if x.get("sustained")]
            exact_row["paced"] = (valid or partial["rates"])[-1]
            emitter.update(latency_sweep=partial, phase="latency_sweep")
            emitter.emit()

        sweep = {}
        try:
            sweep = _latency_sweep(cfg, mapping, broker, wd, start_rate,
                                   paced_dur, sla_ms, max_runs=sweep_runs,
                                   rate_ceiling=int(value),
                                   deadline=bench_deadline,
                                   progress=sweep_progress)
        except Exception as e:  # diagnostics must never kill the headline
            log(f"paced latency sweep failed (non-fatal): {e!r}")
        if sweep:  # never wipe partial rungs sweep_progress already kept
            emitter.update(latency_sweep=sweep)

        # Phase 3: the full BASELINE config suite — a measured row per
        # aggregation family (#2 HLL, #3 sliding+t-digest, #4
        # session+CMS, #5 sharded 1e6-campaign), next to #1's headline.
        configs = [exact_row]
        if os.environ.get("STREAMBENCH_BENCH_CONFIGS", "1") != "0":
            cfg_rate = int(os.environ.get(
                "STREAMBENCH_BENCH_CONFIG_RATE", "20000"))
            cfg_secs = float(os.environ.get(
                "STREAMBENCH_BENCH_CONFIG_PACED_SECS", "45"))
            suite_rows: list = []  # survives a mid-suite exception

            def on_row(rows: list) -> None:
                suite_rows[:] = rows
                emitter.update(configs=[exact_row] + rows,
                               phase="config_suite")
                emitter.emit()

            try:  # rows arrive via on_row; the return value adds nothing
                _run_all_configs(
                    cfg, mapping, broker, wd, n_events, cfg_secs,
                    cfg_rate, sla_ms, bench_deadline, on_row=on_row)
            except Exception as e:
                import traceback

                log(f"config suite failed (non-fatal): {e!r}\n"
                    + traceback.format_exc())
            configs += suite_rows

        emitter.update(configs=configs, phase="complete")
        emitter.emit()
    return 0


if __name__ == "__main__":
    sys.exit(main())
