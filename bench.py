"""Headline benchmark: sustained end-to-end events/sec, oracle-verified.

Reproduces the reference's benchmark shape (SURVEY.md §6): the YSB
ad-analytics pipeline — deserialize, filter "view", join ad->campaign,
count per (campaign, 10 s window), write canonical Redis schema — driven
from a journaled event stream, then checked window-by-window against the
golden model (``check-correct``, ``core.clj:215-237``).  The headline
metric is catchup-mode sustained throughput: how many events/sec the whole
engine (host encode + XLA window step + Redis flush) folds while staying
exactly correct.  A second phase paces events in real time (``-r -t N``,
``core.clj:183-204``) and reports the reference's true latency metric —
``time_updated − window_timestamp`` per window (``core.clj:149``) — as
p50/p99 + deciles on stderr.

Backend resolution is crash/hang-proof: the requested platform is probed
in a *subprocess* with a hard timeout and bounded retries; on failure the
bench pins itself to CPU and still lands a number (round 1 died with rc=1
inside in-process TPU init — that must never happen again).

Prints ONE JSON line on stdout: {"metric", "value", "unit",
"vs_baseline"}.  All diagnostics (platform, stage breakdown, latency
deciles) go to stderr.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import tempfile
import time

BASELINE_EVENTS_PER_S = 100_000.0

PROBE_TIMEOUT_S = float(os.environ.get("STREAMBENCH_BENCH_PROBE_TIMEOUT", "90"))
PROBE_ATTEMPTS = int(os.environ.get("STREAMBENCH_BENCH_PROBE_ATTEMPTS", "2"))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ----------------------------------------------------------------------
# backend resolution
def _probe_backend(env: dict, timeout_s: float) -> tuple[bool, str]:
    """Initialize jax in a THROWAWAY subprocess; return (ok, detail).

    In-process init can hang indefinitely when the hardware backend is
    wedged (observed: rc=1 crash in round 1, a 120 s+ hang when re-judged
    and again this round).  A subprocess can always be killed.
    """
    # Mirror pin_jax_platform: the image's sitecustomize overrides the
    # JAX_PLATFORMS env var via jax.config, so the probe must re-pin the
    # config or a cpu probe would still initialize the hardware backend.
    code = ("import os, jax;\n"
            "p = os.environ.get('JAX_PLATFORMS')\n"
            "if p: jax.config.update('jax_platforms', p)\n"
            "d = jax.devices(); print(jax.default_backend(), len(d))")
    try:
        p = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {timeout_s:.0f}s"
    if p.returncode != 0:
        tail = (p.stderr or "").strip().splitlines()[-1:]
        return False, f"probe rc={p.returncode}: {' '.join(tail)}"
    return True, p.stdout.strip()


def resolve_platform() -> str:
    """Pick a platform that is PROVEN to initialize, preferring the
    ambient/requested one (usually the TPU plugin).  Returns the platform
    string that was pinned into this process's environment."""
    want = os.environ.get("JAX_PLATFORMS", "")
    for attempt in range(1, PROBE_ATTEMPTS + 1):
        ok, detail = _probe_backend(dict(os.environ), PROBE_TIMEOUT_S)
        if ok:
            log(f"backend probe ok (attempt {attempt}): {detail}")
            return want or detail.split()[0]
        log(f"backend probe failed (attempt {attempt}/{PROBE_ATTEMPTS}, "
            f"platform={want or 'default'}): {detail}")
        if attempt < PROBE_ATTEMPTS:
            time.sleep(2.0)
    log("FALLING BACK TO CPU: the requested backend would not initialize. "
        "The number below is a CPU number — check chip availability "
        "(stale processes holding the device, tunnel down) and rerun.")
    os.environ["JAX_PLATFORMS"] = "cpu"
    return "cpu"


# ----------------------------------------------------------------------
def _measure_device_time(cfg, mapping, broker) -> dict:
    """Blocking-sample the compiled device program: fold one K-batch chunk
    repeatedly with ``block_until_ready`` and report device+dispatch time
    per chunk/event.  This is the round-3 'device-side evidence' the r02
    verdict demanded — the async hot path never blocks, so only a
    deliberate sample can observe device time."""
    import jax

    from streambench_tpu.engine import AdAnalyticsEngine

    eng = AdAnalyticsEngine(cfg, mapping)
    n = cfg.jax_batch_size * cfg.jax_scan_batches
    lines = broker.reader(cfg.kafka_topic).poll(max_records=n)
    # Measure the SAME ingest path the catchup loop uses: block mode
    # (raw bytes through the native scanner) when the engine supports it.
    block = (b"\n".join(lines) + b"\n") if lines else b""
    use_block = eng.supports_block_ingest

    def ingest() -> None:
        if use_block:
            eng.process_block(block)
        else:
            eng.process_chunk(lines)

    def warm_all() -> None:
        """Compile every program any phase can hit: engine.warmup()
        covers the single-batch step, every power-of-2 scan size (the
        streaming loop's adaptive batching walks through them), and the
        drain; one real ingest warms the host block path on top."""
        eng.warmup()
        ingest()
        jax.block_until_ready(eng.state.counts)

    if len(lines) < max(2 * cfg.jax_batch_size, 1):
        if lines:  # still warm the jit cache on whatever exists
            warm_all()
        return {}
    n = len(lines)
    warm_all()
    iters = 10
    # Round-trip latency: block after every chunk (includes one full
    # dispatch->execute->sync cycle; on a tunneled backend this is RPC-
    # latency-bound and is NOT the sustained cost).
    t0 = time.perf_counter()
    for _ in range(iters):
        ingest()
        jax.block_until_ready(eng.state.counts)
    round_trip_s = (time.perf_counter() - t0) / iters
    # Pipelined throughput: enqueue all chunks, block once — what the
    # async hot loop actually pays per chunk.
    t0 = time.perf_counter()
    for _ in range(iters):
        ingest()
    jax.block_until_ready(eng.state.counts)
    pipelined_s = (time.perf_counter() - t0) / iters
    # host encode share (runs inside the ingest call on the host thread)
    t0 = time.perf_counter()
    for _ in range(iters):
        if use_block:
            eng.encoder.carve_block(block, cfg.jax_batch_size)
        else:
            for off in range(0, n, cfg.jax_batch_size):
                eng._encode(lines[off:off + cfg.jax_batch_size],
                            cfg.jax_batch_size)
    encode_s = (time.perf_counter() - t0) / iters
    device_s = max(pipelined_s - encode_s, 0.0)
    return {
        "chunk_events": n,
        "ingest_mode": "block" if use_block else "lines",
        "round_trip_ms": round(round_trip_s * 1e3, 3),
        "chunk_ms_pipelined": round(pipelined_s * 1e3, 3),
        "encode_ms": round(encode_s * 1e3, 3),
        "device_ms_est": round(device_s * 1e3, 3),
        "device_ns_per_event": round(device_s * 1e9 / n, 1),
    }


def _paced_latency_phase(cfg, mapping, broker, r, workdir,
                         rate: int, duration_s: float,
                         run_id: int = 0) -> dict:
    """Pace events in real time at ``rate`` ev/s and report the canonical
    latency metric from what landed in Redis (``core.clj:130-149``),
    with ONE sample per unique window (not per campaign-window row)."""
    from streambench_tpu.datagen import gen
    from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner
    from streambench_tpu.io.redis_schema import (
        read_window_latencies,
        seed_campaigns,
    )
    from streambench_tpu.metrics import decile_table

    # read_stats walks SMEMBERS campaigns (core.clj:131) — seed them.
    seed_campaigns(r, sorted(set(mapping.values())))
    # run_id keeps the topic unique even when the ladder revisits a rate
    # (a reused topic would replay the previous run's journal from offset
    # 0 and poison both the throughput and the latency stamps).
    topic = f"{cfg.kafka_topic}-paced-{run_id}-{rate}"
    # Shard the load across producer processes + partitions so the sweep
    # probes the ENGINE's ceiling, not the generator's (the reference
    # scales load the same way: kafka.partitions + parallel producers).
    # With the native formatter one producer sustains ~500k ev/s, and on
    # small hosts every extra process is contention — so split late.
    n_prod = max(1, -(-rate // 400_000))
    broker.create_topic(topic, n_prod)

    # Engine construction + warmup happen BEFORE the producers launch:
    # any cold XLA compile saturates the core with LLVM threads for
    # seconds, and a producer starved mid-emit builds schedule lag that
    # the sweep would bill as engine latency (observed: one 11 s emit).
    engine = AdAnalyticsEngine(cfg, mapping, redis=r)
    engine.warmup()
    reader = (broker.multi_reader(topic) if n_prod > 1
              else broker.reader(topic))
    runner = StreamRunner(engine, reader)

    # Producers run as their OWN processes (the reference's generator is a
    # separate JVM, stream-bench.sh:229): in-process they contend with the
    # engine for the GIL and the measured "unsustained" rate would be the
    # producer's starvation, not the engine's limit.
    from streambench_tpu.config import write_local_conf

    conf_path = os.path.join(workdir, f"paced-{run_id}-{rate}.yaml")
    write_local_conf(conf_path, {"kafka.topic": topic})
    procs = []
    for p_idx in range(n_prod):
        share = rate // n_prod + (1 if p_idx < rate % n_prod else 0)
        prod_log = os.path.join(workdir,
                                f"paced-{run_id}-{rate}-{p_idx}.log")
        with open(prod_log, "wb") as logf:
            procs.append((prod_log, subprocess.Popen(
                [sys.executable, "-m", "streambench_tpu.datagen", "-r",
                 "-t", str(share), "--duration", str(duration_s),
                 "--partition", str(p_idx),
                 "--configPath", conf_path, "--workdir", workdir,
                 "--brokerDir", broker.root],
                stdout=logf, stderr=subprocess.STDOUT,
                cwd=os.path.dirname(os.path.abspath(__file__)))))
        # Producers get scheduling priority over the engine when
        # possible (root only): the reference's generator runs on its
        # own hardware, so on a shared core it must not be starved by
        # engine threads - that would bill scheduler deficit as engine
        # latency.  setpriority on the CHILD pid from here (preexec_fn
        # is unsafe in a threaded parent).
        try:
            os.setpriority(os.PRIO_PROCESS, procs[-1][1].pid, -5)
        except OSError:
            pass

    sent = {}
    behind = {"n": 0, "max_ms": 0.0}
    t0 = time.monotonic()
    # idle_timeout covers producer hiccups only; 15 s tolerates a slow
    # producer start on a loaded single-core host without masking a real
    # mid-run stall (the run is bounded by duration_s regardless).
    runner.run(duration_s=duration_s + 5.0, idle_timeout_s=15.0)
    # Reap EVERY producer before judging any of them — raising on the
    # first bad one would orphan the rest, which then keep emitting into
    # the next sweep rung's measurement window.
    failures = []
    for prod_log, proc in procs:
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            # SIGTERM first: the producer's handler stops the paced loop
            # cleanly and still reports its true "emitted N" count.
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
            log(f"paced producer at {rate}/s overran its duration; stopped")
        if proc.returncode not in (0, -9):  # -9 = our own overrun kill
            with open(prod_log, "r", errors="replace") as f:
                failures.append(
                    f"rc={proc.returncode}: {f.read()[-400:]}")
    formatters: set[str] = set()
    for prod_log, proc in procs:
        with open(prod_log, "r", errors="replace") as f:
            for line in f:
                if line.startswith("emitted "):
                    sent["n"] = sent.get("n", 0) + int(line.split()[1])
                elif line.startswith("Falling behind"):
                    behind["n"] += 1
                    behind["max_ms"] = max(
                        behind["max_ms"], float(line.split()[-1][:-2]))
                elif line.startswith("formatter: "):
                    formatters.add(line.split()[-1])
    # ONE degraded (pure-Python, ~60x slower) producer is enough to
    # poison a rung's latencies — report the slowest path seen.
    formatter = ("python" if "python" in formatters
                 else ("native" if formatters else None))
    if failures:
        raise RuntimeError(
            f"{len(failures)} paced producer(s) failed: {failures[0]}")
    engine.close()
    wall = time.monotonic() - t0
    log(engine.tracer.report())
    by_window = read_window_latencies(r)
    lats = sorted(by_window.values())
    out = {
        "rate": rate, "sent": sent.get("n"),
        "processed": runner.stats.events,
        "wall_s": round(wall, 1), "windows": len(lats),
        "generator_behind_events": behind["n"],
        "generator_behind_max_ms": behind["max_ms"],
        "generator_formatter": formatter,
    }
    log(f"paced phase: rate={rate}/s sent={sent.get('n')} "
        f"processed={runner.stats.events} wall={wall:.1f}s "
        f"unique_windows={len(lats)} behind={behind['n']} "
        f"behind_max={behind['max_ms']:.0f}ms formatter={formatter}")
    if not lats:
        log("paced phase: no windows written — latency unavailable")
        return out
    pick = lambda q: lats[min(int(q * len(lats)), len(lats) - 1)]
    out.update(p50_ms=pick(0.50), p90_ms=pick(0.90), p99_ms=pick(0.99),
               max_ms=lats[-1])
    log(f"window latency (time_updated - window_ts) at {rate} ev/s: "
        f"p50={out['p50_ms']} ms p90={out['p90_ms']} ms "
        f"p99={out['p99_ms']} ms max={out['max_ms']} ms "
        f"over {len(lats)} unique windows")
    for rng_label, v in decile_table(lats):
        log(f"  decile {rng_label}: {v} ms")
    return out


def _latency_sweep(cfg, mapping, broker, workdir, start_rate: int,
                   duration_s: float, sla_ms: int,
                   max_runs: int = 3, rate_ceiling: int | None = None,
                   deadline: float | None = None) -> dict:
    """Escalating-rate ladder (the reference's experimental method: find
    the max load the engine sustains at bounded latency,
    ``README.markdown:36-37``).  Starts at ``start_rate`` (the baseline
    load); each sustained run escalates 1.5x, each failed run halves —
    so the ladder converges on the ceiling instead of betting every run
    on a precomputed guess.  A rate counts as sustained when the engine
    consumed everything sent and p99 unique-window latency is within
    the SLA."""
    from streambench_tpu.io.fakeredis import make_store
    from streambench_tpu.io.redis_schema import as_redis

    results = []
    best = None
    rate = start_rate
    retried: set[int] = set()
    for run_id in range(max_runs):
        if deadline is not None and (
                time.monotonic() + duration_s + 45 > deadline):
            log("latency sweep stopped: bench time budget would be "
                "exceeded (headline must still print)")
            break
        res = _paced_latency_phase(cfg, mapping, broker,
                                   as_redis(make_store()), workdir,
                                   rate, duration_s, run_id=run_id)
        results.append(res)
        p99 = res.get("p99_ms")
        sustained = (p99 is not None and p99 <= sla_ms
                     and res["processed"] == res.get("sent"))
        res["sustained"] = sustained
        # A rung whose PRODUCER fell seconds behind its own schedule is
        # not a valid engine measurement (the generator is supposed to
        # be healthy load, like the reference's dedicated-node
        # generator): mark it and retry the same rate once instead of
        # letting generator starvation walk the ladder down.
        starved = (not sustained
                   and res.get("generator_behind_max_ms", 0) > 5_000)
        res["invalid_producer"] = starved
        log(f"rate {rate}/s: {'SUSTAINED' if sustained else 'NOT sustained'}"
            f" (p99={p99} ms, sla={sla_ms} ms"
            + (", producer starved - rung invalid" if starved else "")
            + ")")
        if starved and rate not in retried:
            retried.add(rate)
            continue  # re-run the same rate (still bounded by max_runs)
        if sustained:
            best = max(best or 0, rate)
            rate = int(rate * 1.5)
            if rate_ceiling and rate > rate_ceiling:
                break  # can't sustain beyond catchup throughput anyway
        else:
            rate = max(int(rate * 0.5), 1_000)
            if best is not None and rate <= best:
                break
    return {"sla_ms": sla_ms, "duration_s": duration_s,
            "max_sustained_rate": best, "rates": results}


def main() -> int:
    # 2M events: at ~1M+ ev/s catchup the old 500k default measured well
    # under a second of wall time; this keeps the measurement window in
    # whole seconds without stretching generation unreasonably.
    n_events = int(os.environ.get("STREAMBENCH_BENCH_EVENTS", "2000000"))
    # Hard wall-clock budget: external runners may kill the bench at an
    # unknown timeout, and a dead headline is worse than a short sweep.
    budget_s = float(os.environ.get("STREAMBENCH_BENCH_BUDGET_S", "1500"))
    bench_deadline = time.monotonic() + budget_s
    paced_rate = int(os.environ.get("STREAMBENCH_BENCH_PACED_RATE", "0"))
    paced_dur = float(os.environ.get("STREAMBENCH_BENCH_PACED_SECS", "125"))
    sla_ms = int(os.environ.get("STREAMBENCH_BENCH_SLA_MS", "15000"))
    # Catchup-tuned engine geometry: the ring sized to hold the default
    # journal's full event-time span (2M events x 10 ms = ~5.6 h;
    # W=2048 slots x 10 s ~= 5.7 h safe span -> no mid-run span-guard
    # drains; they'd be deferred/non-blocking anyway, but zero keeps the
    # measured regime uniform) and K batches folded per dispatch.
    window_slots = int(os.environ.get("STREAMBENCH_BENCH_WINDOW_SLOTS",
                                      "2048"))
    batch_size = int(os.environ.get("STREAMBENCH_BENCH_BATCH", "8192"))

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from streambench_tpu.utils.platform import pin_jax_platform

    platform = resolve_platform()
    pin_jax_platform(platform)

    # Deeper scan on accelerators: each dispatch crosses the (possibly
    # tunneled) runtime once, so fold more batches per call where that
    # round trip is the expensive part; on CPU the extra stacking buys
    # nothing.
    scan_default = "8" if platform == "cpu" else "16"
    scan_batches = int(os.environ.get("STREAMBENCH_BENCH_SCAN_BATCHES",
                                      scan_default))

    import jax

    from streambench_tpu.config import default_config
    from streambench_tpu.datagen import gen
    from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner
    from streambench_tpu.io.fakeredis import make_store
    from streambench_tpu.io.journal import FileBroker
    from streambench_tpu.io.redis_schema import as_redis

    backend = jax.default_backend()
    log(f"backend={backend} devices={len(jax.devices())} events={n_events}")
    cfg = default_config(jax_window_slots=window_slots,
                         jax_scan_batches=scan_batches,
                         jax_batch_size=batch_size)

    # RAM-backed workdir when available: the file broker is the in-process
    # Kafka analog, and on a disk-backed /tmp the paced producers' write()
    # calls can block for SECONDS under dirty-page writeback throttling
    # (observed as multi-second producer stalls right after the 500 MB
    # catchup journal was written) — which would be charged to the engine
    # as window latency.  Only if tmpfs can hold the run: ~250 B/event x
    # (journal + topic copy) + the paced rungs' topics, with headroom.
    tmp_base = None
    need_bytes = n_events * 250 * 2 + 10 * (1 << 30)
    try:
        sv = os.statvfs("/dev/shm")
        if sv.f_bavail * sv.f_frsize >= need_bytes:
            tmp_base = "/dev/shm"
        else:
            log("tmpfs too small for the dataset; workdir stays on disk "
                "(paced latencies may include writeback stalls)")
    except OSError:
        pass
    with tempfile.TemporaryDirectory(dir=tmp_base) as wd:
        r = as_redis(make_store())
        broker = FileBroker(os.path.join(wd, "broker"))
        t0 = time.monotonic()
        gen.do_setup(r, cfg, broker=broker, events_num=n_events,
                     rng=random.Random(42), workdir=wd)
        log(f"generated {n_events} events in {time.monotonic()-t0:.1f}s")
        mapping = gen.load_ad_mapping_file(
            os.path.join(wd, gen.AD_TO_CAMPAIGN_FILE))

        # Warm the jit cache with a same-shape engine so compile time
        # (~20-40 s on first TPU use) doesn't pollute the measurement;
        # the same warm pass samples device time with blocking waits
        # (the async hot path never observes device completion).
        t0 = time.monotonic()
        device = _measure_device_time(cfg, mapping, broker)
        log(f"jit warmup done in {time.monotonic()-t0:.1f}s")
        if device:
            log(f"device sample: chunk of {device['chunk_events']} events — "
                f"round-trip {device['round_trip_ms']} ms, pipelined "
                f"{device['chunk_ms_pipelined']} ms/chunk (host encode "
                f"{device['encode_ms']} ms, device+dispatch est "
                f"{device['device_ms_est']} ms = "
                f"{device['device_ns_per_event']} ns/event)")

        # optional kernel override (scatter|onehot|matmul|pallas); default
        # is the per-backend choice in engine.pipeline.default_method
        method = os.environ.get("STREAMBENCH_BENCH_METHOD") or None
        # Best-of-N catchup: the host shows episodic multi-second
        # degradation windows (system-time spikes, zero steal), and a
        # single-shot measurement at an unlucky moment would misreport
        # the engine by 2-3x.  Each rep replays the same journal through
        # a FRESH engine + store; the best rep's store is oracle-checked.
        reps = max(int(os.environ.get("STREAMBENCH_BENCH_REPS", "3")), 1)
        from streambench_tpu.io.redis_schema import seed_campaigns

        best = None  # (value, stats, engine, store, total_s)
        for rep in range(reps):
            # every rep gets an identical fresh store (the setup store
            # additionally holds the ad-mapping keys; reps must be
            # interchangeable)
            r_rep = as_redis(make_store())
            seed_campaigns(r_rep, sorted(set(mapping.values())))
            engine = AdAnalyticsEngine(cfg, mapping, redis=r_rep,
                                       method=method)
            runner = StreamRunner(engine, broker.reader(cfg.kafka_topic))
            # The measured interval covers ingest + device folds + the
            # FULL canonical Redis writeback (engine.close drains the
            # async writer): stopping the clock at run_catchup() would
            # let the writer finish the last flush off the books.
            t0 = time.monotonic()
            stats = runner.run_catchup()
            engine.close()
            total_s = max(time.monotonic() - t0, 1e-9)
            v = stats.events / total_s
            log(f"catchup rep {rep + 1}/{reps}: {stats.events} events in "
                f"{total_s:.2f}s (ingest {stats.wall_s:.2f}s) = "
                f"{v:,.0f} ev/s; windows={stats.windows_written} "
                f"dropped={engine.dropped}")
            if best is None or v > best[0]:
                best = (v, stats, engine, r_rep, total_s)
        value, stats, engine, r_best, total_s = best
        log(f"engine: method={engine.method} W={engine.W} "
            f"B={engine.batch_size} K={engine.scan_batches} "
            f"best-of-{reps}")
        log(engine.tracer.report())
        util = None
        if device and total_s > 0:
            chunks = stats.events / max(device["chunk_events"], 1)
            util = device["device_ms_est"] / 1e3 * chunks / total_s
            log(f"est device occupancy during catchup: {util:.1%} of wall")

        correct, differ, missing = gen.check_correct(
            r_best, workdir=wd, log=lambda s: None,
            time_divisor_ms=cfg.jax_time_divisor_ms)
        log(f"oracle: CORRECT={correct} DIFFER={differ} MISSING={missing}")
        if differ or missing or engine.dropped:
            log("BENCH INVALID: engine output incorrect")
            print(json.dumps({
                "metric": "sustained events/sec (oracle-verified)",
                "value": 0.0, "unit": "events/s", "vs_baseline": 0.0,
                "platform": backend}))
            return 1

        value = round(value, 1)

        # Phase 2: the reference's real metric — p99 window-writeback
        # latency under sustained paced load (core.clj:130-149), as an
        # escalating-rate sweep reporting the max rate the engine
        # sustains within the SLA.
        start_rate = paced_rate or int(min(BASELINE_EVENTS_PER_S,
                                           max(value / 2, 1_000)))
        sweep_runs = int(os.environ.get("STREAMBENCH_BENCH_SWEEP_RUNS",
                                        "3"))
        sweep = {}
        try:
            sweep = _latency_sweep(cfg, mapping, broker, wd, start_rate,
                                   paced_dur, sla_ms, max_runs=sweep_runs,
                                   rate_ceiling=int(value),
                                   deadline=bench_deadline)
        except Exception as e:  # diagnostics must never kill the headline
            log(f"paced latency sweep failed (non-fatal): {e!r}")

        headline = {
            "metric": "sustained events/sec (oracle-verified)",
            "value": value,
            "unit": "events/s",
            "vs_baseline": round(value / BASELINE_EVENTS_PER_S, 4),
            "platform": backend,
            "device": device or None,
            "device_occupancy_est": round(util, 4) if util else None,
            "latency_sweep": sweep or None,
        }
        try:
            with open(os.path.join(os.path.dirname(
                    os.path.abspath(__file__)), "bench_latency.json"),
                    "w") as f:
                json.dump({"platform": backend, "catchup_events_per_s":
                           value, **sweep}, f, indent=1)
        except OSError as e:
            log(f"could not write bench_latency.json: {e}")
        print(json.dumps(headline))
    return 0


if __name__ == "__main__":
    sys.exit(main())
