"""Headline benchmark: sustained end-to-end events/sec, oracle-verified.

Reproduces the reference's benchmark shape (SURVEY.md §6): the YSB
ad-analytics pipeline — deserialize, filter "view", join ad->campaign,
count per (campaign, 10 s window), write canonical Redis schema — driven
from a journaled event stream, then checked window-by-window against the
golden model (``check-correct``, ``core.clj:215-237``).  The metric is
catchup-mode sustained throughput: how many events/sec the whole engine
(host encode + XLA window step + Redis flush) folds while staying exactly
correct.

Baseline: 100k events/s, a representative published single-node Flink YSB
operating point (the reference repo itself publishes no numbers,
``README.markdown:39-42``; BASELINE.json "published" is empty).  The
north-star target is 10x that.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Diagnostics go to stderr.
"""

from __future__ import annotations

import json
import os
import random
import sys
import tempfile
import time

BASELINE_EVENTS_PER_S = 100_000.0


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> int:
    n_events = int(os.environ.get("STREAMBENCH_BENCH_EVENTS", "500000"))

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from streambench_tpu.utils.platform import pin_jax_platform

    pin_jax_platform()  # honor JAX_PLATFORMS even under sitecustomize

    import jax

    from streambench_tpu.config import default_config
    from streambench_tpu.datagen import gen
    from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner
    from streambench_tpu.io.fakeredis import FakeRedisStore
    from streambench_tpu.io.journal import FileBroker
    from streambench_tpu.io.redis_schema import as_redis

    log(f"backend={jax.default_backend()} devices={len(jax.devices())} "
        f"events={n_events}")
    cfg = default_config()

    with tempfile.TemporaryDirectory() as wd:
        r = as_redis(FakeRedisStore())
        broker = FileBroker(os.path.join(wd, "broker"))
        t0 = time.monotonic()
        gen.do_setup(r, cfg, broker=broker, events_num=n_events,
                     rng=random.Random(42), workdir=wd)
        log(f"generated {n_events} events in {time.monotonic()-t0:.1f}s")
        mapping = gen.load_ad_mapping_file(
            os.path.join(wd, gen.AD_TO_CAMPAIGN_FILE))

        # Warm the jit cache with a same-shape engine so compile time
        # (~20-40 s on first TPU use) doesn't pollute the measurement.
        warm = AdAnalyticsEngine(cfg, mapping)
        warm_reader = broker.reader(cfg.kafka_topic)
        warm.process_lines(warm_reader.poll(cfg.jax_batch_size))
        warm.flush()
        log("jit warmup done")

        engine = AdAnalyticsEngine(cfg, mapping, redis=r)
        runner = StreamRunner(engine, broker.reader(cfg.kafka_topic))
        stats = runner.run_catchup()
        engine.close()
        log(f"processed {stats.events} events in {stats.wall_s:.2f}s; "
            f"windows={stats.windows_written} dropped={engine.dropped}")

        correct, differ, missing = gen.check_correct(
            r, workdir=wd, log=lambda s: None,
            time_divisor_ms=cfg.jax_time_divisor_ms)
        log(f"oracle: CORRECT={correct} DIFFER={differ} MISSING={missing}")
        if differ or missing or engine.dropped:
            log("BENCH INVALID: engine output incorrect")
            print(json.dumps({
                "metric": "sustained events/sec (oracle-verified)",
                "value": 0.0, "unit": "events/s", "vs_baseline": 0.0}))
            return 1

        value = round(stats.events_per_s, 1)
        print(json.dumps({
            "metric": "sustained events/sec (oracle-verified)",
            "value": value,
            "unit": "events/s",
            "vs_baseline": round(value / BASELINE_EVENTS_PER_S, 4),
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
