#!/usr/bin/env python3
"""Production-cardinality sketch-memory bench (ISSUE 13 / ROADMAP 2).

Proves the SALSA merge-on-overflow plane's claim HONESTLY, with the
device-memory budget MEASURED by the obs.devmem ledger functions (state
bytes from the live pytrees, per-kernel argument/output/temp bytes from
``memory_analysis``), never estimated:

- **cards** — the cardinality sweep.  One Zipf-skewed key-weight stream
  per rung (every key appears; the head holds counts far past a byte so
  merges MUST fire — asserted, not assumed), folded through two arms at
  one power-of-two device-memory budget:

    * ``fixed`` — ``ops/cms.py`` [4, Ws/4] int32 (the largest
      power-of-two width fitting the budget),
    * ``salsa`` — ``ops/salsa.py`` [4, Ws] uint8 + packed merge bitmaps
      (~1.09 B/cell -> 4x the counters in ~the same bytes).

  Per (rung, arm): ledger-measured state bytes, update-kernel
  arg/out/temp bytes, fold throughput (rows/s of key-weight updates —
  weight-linearity makes one weighted update exactly equal that many
  unit events), and the p99/p50 absolute point-query error vs exact
  numpy counts over a 64k-key sample.  The headline gate is the ROADMAP
  item-2 criterion: **salsa at 4x the distinct keys holds p99 error <=
  the fixed arm's** (salsa@4N vs fixed@N, same budget).

- **hh_ab** — legacy vs SALSA ``SessionCMSEngine`` over the SAME
  generated journal, oracle-checked: the two arms' heavy-hitter rows
  must be IDENTICAL (at session-scale weights no counter exceeds a
  byte, and an unmerged SALSA plane reads bit-identically to the fixed
  sketch), and every reported estimate must upper-bound the exact
  per-user click count from a python sessionizer over the journal.

- **hllx** — the hyper-extended ladder rung: distinct + calibrated
  log-moment + soft-cap errors vs exact counts at 100k+ distinct keys,
  from one register plane.

Every phase emits one compact (<= 4096 B) single-line JSON on stdout
(the PR 6 truncation-proof contract); the full detail goes to
``--out`` (committed as SKETCH_r01.json).  Self-caps at
``STREAMBENCH_BENCH_BUDGET_S`` (default 840 s < the 870 s driver
kill); rungs skipped for budget are recorded, never silent.

Usage:
    python bench_sketch.py                     # full, writes bench_sketch.json
    python bench_sketch.py --smoke             # CI: tiny rungs
    python bench_sketch.py --out SKETCH_r01.json
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

import numpy as np

COMPACT_LINE_MAX = 4096
_T0 = time.monotonic()


def log(msg: str) -> None:
    print(f"[{time.monotonic() - _T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def budget_left(total_s: float) -> float:
    return total_s - (time.monotonic() - _T0)


def compact_line(obj: dict) -> str:
    def dump(o):
        return json.dumps(o, separators=(",", ":"))

    line = dump(obj)
    if len(line) <= COMPACT_LINE_MAX:
        return line
    obj = json.loads(line)
    for strip in ("rungs", "rows", "kernels", "host", "params"):
        obj.pop(strip, None)
        line = dump(obj)
        if len(line) <= COMPACT_LINE_MAX:
            return line
    return dump({k: obj[k] for k in ("phase", "ok") if k in obj})


def emit(obj: dict) -> None:
    print(compact_line(obj), flush=True)


# ----------------------------------------------------------------------
# cards: the cardinality sweep
# ----------------------------------------------------------------------

def zipf_stream(n_keys: int, extra_events: int, seed: int):
    """Every key once + a Zipf(0.9) head of extra weight: distinct
    cardinality is exactly ``n_keys`` and the head's counts run far
    past a byte (the merge path MUST fire).  Returns (keys int32,
    weights int32) shuffled, plus the exact per-key counts."""
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, n_keys + 1, dtype=np.float64)
    p = ranks ** -0.9
    p /= p.sum()
    extra = np.floor(extra_events * p).astype(np.int64)
    counts = 1 + extra                      # exact per-key totals
    keys = np.arange(n_keys, dtype=np.int32)
    order = rng.permutation(n_keys)
    return keys[order], counts[order].astype(np.int32), counts


def fold_arm(init_state, update, keys, weights, batch: int):
    """Fold the key-weight stream; returns (state, rows_per_s)."""
    import jax
    import jax.numpy as jnp

    state = init_state
    n = keys.shape[0]
    pad = (-n) % batch
    if pad:
        keys = np.concatenate([keys, np.zeros(pad, np.int32)])
        weights = np.concatenate([weights, np.zeros(pad, np.int32)])
    mask = np.ones(n + pad, bool)
    mask[n:] = False
    # warm the compiled update off the clock
    state = update(state, jnp.asarray(keys[:batch]),
                   jnp.asarray(weights[:batch]),
                   jnp.asarray(mask[:batch] & False))
    jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
    t0 = time.perf_counter()
    for i in range(0, n + pad, batch):
        state = update(state, jnp.asarray(keys[i:i + batch]),
                       jnp.asarray(weights[i:i + batch]),
                       jnp.asarray(mask[i:i + batch]))
    jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
    dt = time.perf_counter() - t0
    return state, n / dt


def run_cards(width_salsa: int, batch: int, rungs, extra_events: int,
              sample: int, budget_s: float) -> dict:
    import jax.numpy as jnp

    from streambench_tpu.obs.devmem import kernel_memory, state_nbytes
    from streambench_tpu.ops import cms, salsa

    depth = 4
    width_fixed = width_salsa // 4      # largest pow2 within the budget
    out: dict = {
        "phase": "cards", "depth": depth,
        "width_salsa": width_salsa, "width_fixed": width_fixed,
        "batch": batch, "extra_events": extra_events, "rungs": [],
    }
    # ledger-measured budget: the live state pytrees, not arithmetic
    budget_bytes = state_nbytes(salsa.init_state(depth, width_salsa))
    fixed_bytes = state_nbytes(cms.init_state(depth, width_fixed))
    assert fixed_bytes <= budget_bytes, (fixed_bytes, budget_bytes)
    out["budget_bytes"] = budget_bytes
    out["fixed_state_bytes"] = fixed_bytes
    # one-time compiled-kernel footprints at this geometry (the
    # transient side of the ledger; costs an out-of-line compile each)
    zk = jnp.zeros((batch,), jnp.int32)
    zm = jnp.zeros((batch,), bool)
    out["kernels"] = {
        "fixed_update": kernel_memory(
            cms.update, cms.init_state(depth, width_fixed), zk, zk, zm),
        "salsa_update": kernel_memory(
            salsa.update, salsa.init_state(depth, width_salsa), zk, zk,
            zm),
    }

    rng = np.random.default_rng(1234)
    for n_keys in rungs:
        if budget_left(budget_s) < 60:
            out["rungs"].append({"n_keys": n_keys,
                                 "skipped": "budget exhausted"})
            log(f"cards rung {n_keys}: SKIPPED (budget)")
            continue
        keys, weights, counts = zipf_stream(n_keys, extra_events,
                                            seed=n_keys)
        q = min(sample, n_keys)
        # sample the head (where merges live) + a uniform tail slice
        q_keys = np.unique(np.concatenate(
            [np.arange(min(1024, n_keys)),
             rng.choice(n_keys, q, replace=False)])).astype(np.int32)
        exact = counts[q_keys.astype(np.int64)]
        rung = {"n_keys": int(n_keys),
                "events": int(counts.sum()),
                "max_count": int(counts.max())}
        for arm, init, upd, W in (
                ("fixed", cms.init_state(depth, width_fixed),
                 cms.update, width_fixed),
                ("salsa", salsa.init_state(depth, width_salsa),
                 salsa.update, width_salsa)):
            st, rows_s = fold_arm(init, upd, keys, weights, batch)
            est = np.asarray(
                (cms.query if arm == "fixed" else salsa.query)(
                    st, jnp.asarray(q_keys))).astype(np.int64)
            err = est - exact
            assert (err >= 0).all(), (
                f"{arm} under-counted: min err {err.min()}")
            row = {
                "state_bytes": state_nbytes(st),
                "rows_per_s": round(rows_s),
                "p50_err": int(np.percentile(err, 50)),
                "p99_err": int(np.percentile(err, 99)),
                "max_err": int(err.max()),
                "bytes_per_key": round(state_nbytes(st) / n_keys, 3),
            }
            if arm == "salsa":
                s = salsa.stats(st)
                row["merged_pairs"] = s["merged_pairs"]
                row["merged_quads"] = s["merged_quads"]
                if counts.max() > 255:
                    assert s["merged_pairs"] > 0, \
                        "head counts exceed a byte but nothing merged"
            rung[arm] = row
            log(f"cards {n_keys} {arm}: p99_err={row['p99_err']} "
                f"state={row['state_bytes']} rows/s={row['rows_per_s']}")
        out["rungs"].append(rung)

    # the ROADMAP item-2 gate: salsa@4N p99 err <= fixed@N p99 err
    done = [r for r in out["rungs"] if "salsa" in r]
    by_n = {r["n_keys"]: r for r in done}
    pairs = []
    for r in done:
        n4 = r["n_keys"] * 4
        if n4 in by_n:
            pairs.append({
                "fixed_n": r["n_keys"], "salsa_n": n4,
                "fixed_p99": r["fixed"]["p99_err"],
                "salsa_p99": by_n[n4]["salsa"]["p99_err"],
                "ok": by_n[n4]["salsa"]["p99_err"]
                      <= r["fixed"]["p99_err"],
            })
    out["pairs_4x"] = pairs
    out["ok"] = bool(pairs) and all(p["ok"] for p in pairs)
    if done:
        top = max(done, key=lambda r: r["n_keys"])
        out["top_n_keys"] = top["n_keys"]
        out["bytes_per_key"] = top["salsa"]["bytes_per_key"]
        out["p99_err"] = top["salsa"]["p99_err"]
        out["fixed_err"] = top["fixed"]["p99_err"]
        out["salsa_evps"] = top["salsa"]["rows_per_s"]
        out["fixed_evps"] = top["fixed"]["rows_per_s"]
        out["merged_pairs"] = top["salsa"]["merged_pairs"]
    return out


# ----------------------------------------------------------------------
# hh_ab: legacy vs salsa session engines over one journal
# ----------------------------------------------------------------------

def run_hh_ab(workdir: str, events: int, batch: int) -> dict:
    import jax  # noqa: F401  (platform pinned by caller)

    from streambench_tpu.config import default_config
    from streambench_tpu.datagen import gen
    from streambench_tpu.engine import StreamRunner
    from streambench_tpu.engine.sketches import SessionCMSEngine
    from streambench_tpu.io.fakeredis import FakeRedisStore
    from streambench_tpu.io.journal import FileBroker
    from streambench_tpu.io.redis_schema import as_redis

    cfg = default_config(jax_batch_size=batch)
    broker = FileBroker(os.path.join(workdir, "broker"))
    gen.do_setup(as_redis(FakeRedisStore()), cfg, broker=broker,
                 events_num=events, rng=random.Random(13),
                 workdir=workdir)
    mapping = gen.load_ad_mapping_file(
        os.path.join(workdir, gen.AD_TO_CAMPAIGN_FILE))

    # exact per-user click totals (the sessionizer oracle: counts are
    # additive over a user's closed sessions, so the upper-bound check
    # needs only the total — session boundaries cancel out)
    clicks: dict[str, int] = {}
    for line in broker.read_all(cfg.kafka_topic):
        ev = json.loads(line)
        if ev["event_type"] == "click":
            clicks[ev["user_id"]] = clicks.get(ev["user_id"], 0) + 1

    out: dict = {"phase": "hh_ab", "events": events}
    rows = {}
    for mode in ("fixed", "salsa"):
        r = as_redis(FakeRedisStore())
        eng = SessionCMSEngine(cfg, mapping, redis=r, top_k=16,
                               cms_mode=mode)
        t0 = time.perf_counter()
        StreamRunner(eng, broker.reader(cfg.kafka_topic)).run_catchup()
        eng.close()
        dt = time.perf_counter() - t0
        hh = eng.heavy_hitters()
        rows[mode] = hh
        over = [est - clicks.get(u, 0) for u, est in hh]
        assert all(o >= 0 for o in over), (mode, min(over))
        out[mode] = {
            "ev_s": round(events / dt),
            "top_k": len(hh),
            "mean_overestimate": (round(float(np.mean(over)), 2)
                                  if over else None),
            "sketch": eng.sketch_summary(merges=True),
        }
        log(f"hh_ab {mode}: {len(hh)} hitters, "
            f"{out[mode]['ev_s']} ev/s, "
            f"state {out[mode]['sketch']['state_bytes']} B")
    out["rows_identical"] = rows["fixed"] == rows["salsa"]
    out["oracle"] = "upper-bound vs exact per-user clicks"
    out["ok"] = out["rows_identical"] and bool(rows["fixed"])
    return out


# ----------------------------------------------------------------------
# hllx: distinct + frequency moments from one plane
# ----------------------------------------------------------------------

def run_hllx(n_keys: int, extra_events: int) -> dict:
    import jax.numpy as jnp

    from streambench_tpu.ops import hllx

    C, G, R = 8, 8, 128
    keys, weights, counts = zipf_stream(n_keys, extra_events, seed=5)
    # counts above the ladder truncate the log moment — cap the head
    weights = np.minimum(weights, 120).astype(np.int32)
    counts = np.minimum(counts, 120)
    st = hllx.init_state(C, G, R)
    join = jnp.asarray(np.concatenate(
        [np.arange(C, dtype=np.int32), np.array([-1], np.int32)]))
    B = 65_536
    camp_of = (keys.astype(np.int64) % C).astype(np.int32)
    t0 = time.perf_counter()
    # weight w = w occurrences: fold w distinct (user, time) tokens by
    # repeating each key w times with distinct times, batched
    rep_keys = np.repeat(keys, weights)
    rep_camp = np.repeat(camp_of, weights)
    rep_time = (10 * np.arange(rep_keys.size)).astype(np.int32)
    order = np.random.default_rng(9).permutation(rep_keys.size)
    rep_keys, rep_camp, rep_time = (rep_keys[order], rep_camp[order],
                                    rep_time[order])
    for i in range(0, rep_keys.size, B):
        n = min(B, rep_keys.size - i)
        pad = B - n
        st = hllx.step(
            st, join,
            jnp.asarray(np.concatenate(
                [rep_camp[i:i + n], np.zeros(pad)]).astype(np.int32)),
            jnp.asarray(np.concatenate(
                [rep_keys[i:i + n], np.zeros(pad)]).astype(np.int32)),
            jnp.zeros((B,), jnp.int32),
            jnp.asarray(np.concatenate(
                [rep_time[i:i + n], np.zeros(pad)]).astype(np.int32)),
            jnp.asarray(np.concatenate(
                [np.ones(n, bool), np.zeros(pad, bool)])))
    dt = time.perf_counter() - t0
    m = {k: np.asarray(v) for k, v in hllx.moments(st).items()}
    # exact per-campaign statistics
    errs_d, errs_l = [], []
    for c in range(C):
        sel = camp_of == c
        cs = counts[sel.nonzero()[0]]
        true_d = int(sel.sum())
        true_l = float(np.log2(1 + cs).sum())
        errs_d.append(abs(m["distinct"][c] - true_d) / true_d)
        errs_l.append(abs(m["log_moment"][c] - true_l) / true_l)
    return {
        "phase": "hllx", "n_keys": n_keys, "events": int(rep_keys.size),
        "groups": G, "registers": R,
        "ev_s": round(rep_keys.size / dt),
        "distinct_rel_err_mean": round(float(np.mean(errs_d)), 4),
        "log_moment_rel_err_mean": round(float(np.mean(errs_l)), 4),
        "f1_exact": bool((m["totals"].sum() == rep_keys.size)),
        # the distinct rungs ARE HLL estimates: gate at 1.5x the
        # theoretical 1.04/sqrt(R) std (mean |rel err| expects ~0.8x
        # of it); the calibrated log moment gets its documented slack
        "ok": float(np.mean(errs_d)) < 1.5 * 1.04 / np.sqrt(R)
              and float(np.mean(errs_l)) < 0.2,
    }


# ----------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="bench_sketch.json")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    budget_s = float(os.environ.get("STREAMBENCH_BENCH_BUDGET_S", 840))
    workdir = args.workdir or os.path.abspath(
        ".bench-sketch-smoke" if args.smoke else ".bench-sketch")
    os.makedirs(workdir, exist_ok=True)

    if args.smoke:
        cards_kw = dict(width_salsa=1 << 15, batch=16_384,
                        rungs=[1 << 12, 1 << 14], extra_events=60_000,
                        sample=4096)
        hh_events, hh_batch = 30_000, 2048
        hllx_kw = dict(n_keys=20_000, extra_events=40_000)
    else:
        cards_kw = dict(width_salsa=1 << 21, batch=1 << 17,
                        rungs=[1 << 17, 1 << 18, 1 << 19, 1 << 20],
                        extra_events=1_000_000, sample=1 << 16)
        hh_events, hh_batch = 150_000, 4096
        hllx_kw = dict(n_keys=150_000, extra_events=300_000)

    doc: dict = {
        "bench": "sketch", "smoke": bool(args.smoke),
        "budget_s": budget_s,
        "host": {"cpus": os.cpu_count(),
                 "platform": sys.platform},
    }
    rc = 0
    try:
        cards = run_cards(budget_s=budget_s, **cards_kw)
        emit(cards)
        doc["cards"] = cards
        # the regress-gate block (obs.regress.normalize_bench)
        doc["sketch"] = {
            "budget_bytes": cards.get("budget_bytes"),
            "bytes_per_key": cards.get("bytes_per_key"),
            "p99_err": cards.get("p99_err"),
            "fixed_err": cards.get("fixed_err"),
            "salsa_evps": cards.get("salsa_evps"),
            "fixed_evps": cards.get("fixed_evps"),
            "top_n_keys": cards.get("top_n_keys"),
            "pairs_4x": cards.get("pairs_4x"),
            "ok": cards.get("ok"),
        }

        if budget_left(budget_s) > 60:
            hh = run_hh_ab(workdir, hh_events, hh_batch)
            emit(hh)
            doc["hh_ab"] = hh
        else:
            doc["hh_ab"] = {"skipped": "budget exhausted"}

        if budget_left(budget_s) > 30:
            hx = run_hllx(**hllx_kw)
            emit(hx)
            doc["hllx"] = hx
        else:
            doc["hllx"] = {"skipped": "budget exhausted"}

        doc["ok"] = bool(
            doc["sketch"].get("ok")
            and doc.get("hh_ab", {}).get("ok", True)
            and doc.get("hllx", {}).get("ok", True))
    except Exception as e:  # emit the failure compactly, never die mute
        doc["ok"] = False
        doc["error"] = repr(e)[:500]
        rc = 1
        import traceback
        traceback.print_exc()

    doc["wall_s"] = round(time.monotonic() - _T0, 1)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    emit({"phase": "summary", "ok": doc["ok"],
          "wall_s": doc["wall_s"],
          "bytes_per_key": doc.get("sketch", {}).get("bytes_per_key"),
          "salsa_err": doc.get("sketch", {}).get("p99_err"),
          "fixed_err": doc.get("sketch", {}).get("fixed_err"),
          "pairs_4x": doc.get("sketch", {}).get("pairs_4x"),
          "out": args.out})
    return rc if doc["ok"] else 1


if __name__ == "__main__":
    from streambench_tpu.utils.platform import pin_jax_platform

    pin_jax_platform()
    raise SystemExit(main())
