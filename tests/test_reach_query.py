"""Batched reach-query evaluation (reach/query.py) + the serving
surfaces it rides: bit-exact collision counts vs the numpy oracle,
exact set-arithmetic truth at small cardinality, dispatch amortization
(ceil(Q/batch), never one dispatch per query), the pub/sub "reach"
query verb end-to-end, and durable-store sketch round-trips."""

import numpy as np
import pytest

from streambench_tpu.ops import minhash
from streambench_tpu.reach import oracle as ro
from streambench_tpu.reach import query as rq

C, K, R = 9, 128, 64
NAMES = [f"camp{i}" for i in range(C)]


@pytest.fixture(scope="module")
def world():
    """Deterministic per-campaign device sets with real overlap (shared
    pool + per-campaign tail) + the materialized sketch planes."""
    rng = np.random.default_rng(42)
    shared = set(int(x) for x in rng.integers(0, 10**6, 300))
    sets = {}
    for i, name in enumerate(NAMES):
        own = set(int(x) for x in rng.integers(10**7 * (i + 1),
                                               10**7 * (i + 1) + 10**6,
                                               200 + 37 * i))
        take = set(x for x in shared if rng.random() < 0.5)
        sets[name] = own | take
    mins, regs = ro.expected_state(sets, NAMES, K, R)
    return sets, mins, regs


def make_queries(rng, n):
    masks = np.zeros((n, C), bool)
    overlap = np.zeros(n, bool)
    for i in range(n):
        sel = rng.choice(C, size=rng.integers(1, 5), replace=False)
        masks[i, sel] = True
        overlap[i] = bool(rng.integers(0, 2))
    return masks, overlap


def test_agree_counts_bit_exact_vs_numpy_oracle(world):
    sets, mins, regs = world
    rng = np.random.default_rng(1)
    masks, overlap = make_queries(rng, 100)
    counter = rq.DispatchCounter()
    est, union, jacc, agree = rq.query_chunks(
        mins, regs, masks, overlap, batch=32, counter=counter)
    np.testing.assert_array_equal(
        agree, ro.query_oracle_np(mins, regs, masks))
    assert counter.dispatches == int(np.ceil(100 / 32))
    # jaccard/estimate derive deterministically from agree/union
    np.testing.assert_allclose(jacc, agree / K, rtol=1e-6)


def test_estimates_inside_error_bounds(world):
    """Measured relative error vs exact set arithmetic: union within
    the HLL bound, overlap within the relative-to-union Jaccard bound
    (the k=256 -> ~6.25% acceptance figure, here at K=128)."""
    sets, mins, regs = world
    rng = np.random.default_rng(2)
    masks, overlap = make_queries(rng, 120)
    est, union, jacc, _ = rq.query_chunks(mins, regs, masks, overlap)
    u_err, o_err = [], []
    for i in range(masks.shape[0]):
        sel = [NAMES[j] for j in range(C) if masks[i, j]]
        op = "overlap" if overlap[i] else "union"
        truth, true_union = ro.exact_counts(sets, sel, op)
        if overlap[i]:
            o_err.append(abs(est[i] - truth) / max(true_union, 1))
        else:
            u_err.append(abs(est[i] - truth) / max(truth, 1))
    # mean measured error within the theoretical (2-sigma-ish) bounds
    assert np.mean(u_err) <= rq.union_bound(R) * 2, np.mean(u_err)
    assert np.mean(o_err) <= rq.overlap_bound(K, R), np.mean(o_err)


def test_empty_selection_and_padding_rows_evaluate_to_zero(world):
    _, mins, regs = world
    masks = np.zeros((3, C), bool)
    masks[1, 0] = True
    est, union, jacc, agree = rq.query_chunks(
        mins, regs, masks, np.array([False, False, True]), batch=8)
    assert est[0] == 0 and agree[0] == 0      # empty union query
    assert est[1] > 0                          # real row unaffected
    assert agree[2] == 0 and est[2] == 0       # overlap over nothing


def test_single_campaign_overlap_is_identity(world):
    """m=1 'overlap' degenerates to the campaign itself: J=1 (every
    slot agrees with itself), estimate == union estimate."""
    _, mins, regs = world
    masks = np.zeros((C, C), bool)
    np.fill_diagonal(masks, True)
    est, union, jacc, agree = rq.query_chunks(
        mins, regs, masks, np.ones(C, bool))
    np.testing.assert_array_equal(agree, np.full(C, K))
    np.testing.assert_allclose(est, union, rtol=1e-6)


def test_disjoint_campaigns_overlap_zero():
    """Two campaigns with no shared devices: every slot disagrees (up
    to 32-bit hash ties, absent at this size) -> intersection 0."""
    sets = {"a": set(range(1000)), "b": set(range(5000, 6000))}
    mins, regs = ro.expected_state(sets, ["a", "b"], K, R)
    masks = np.ones((1, 2), bool)
    est, union, jacc, agree = rq.query_chunks(
        mins, regs, masks, np.ones(1, bool))
    assert agree[0] == 0 and est[0] == 0.0


# -------------------------------------------------- pub/sub query verb
def test_pubsub_reach_verb_round_trip(world):
    import jax.numpy as jnp

    from streambench_tpu.dimensions.pubsub import PubSubClient, PubSubServer
    from streambench_tpu.reach.serve import ReachQueryServer

    sets, mins, regs = world
    srv = ReachQueryServer(NAMES, depth=64, batch=16)
    srv.update_state(jnp.asarray(mins), jnp.asarray(regs), epoch=7)
    ps = PubSubServer(port=0).start()
    ps.register_query("reach", srv.handle)
    host, port = ps.address
    try:
        c = PubSubClient(host, port)
        c.request({"type": "reach", "campaigns": NAMES[:2],
                   "op": "union", "id": "q1"})
        c.request({"type": "reach", "campaigns": NAMES[:3],
                   "op": "overlap", "id": "q2"})
        got = {m["data"]["id"]: m["data"] for m in (c.recv(), c.recv())}
        assert got["q1"]["epoch"] == 7 and got["q1"]["estimate"] > 0
        assert got["q2"]["op"] == "overlap"
        assert 0.0 < got["q2"]["bound"] < 1.0
        # malformed verbs answer, never hang or kill the connection
        c.request({"type": "reach", "campaigns": ["nope"],
                   "op": "union", "id": "q3"})
        assert c.recv()["data"]["error"] == "unknown_campaign"
        c.request({"type": "reach", "campaigns": NAMES[:1],
                   "op": "median", "id": "q4"})
        assert "error" in c.recv()["data"]
        c.close()
    finally:
        srv.close()
        ps.close()


def test_register_query_refuses_reserved_verbs():
    from streambench_tpu.dimensions.pubsub import PubSubServer

    ps = PubSubServer(port=0).start()
    try:
        with pytest.raises(ValueError):
            ps.register_query("subscribe", lambda m, r: None)
    finally:
        ps.close()


# ------------------------------------------------- durable-store leg
def test_store_sketch_round_trip(tmp_path, world):
    """Materialized sketches survive the durable store: put -> reopen
    -> replay -> identical query answers (serving from the store, not
    the engine)."""
    from streambench_tpu.dimensions.store import DurableDimensionStore

    sets, mins, regs = world
    with DurableDimensionStore(str(tmp_path)) as st:
        st.put_rows([("campA", 0, {"clicks:SUM": 3})])
        st.put_reach_sketches(mins, regs, NAMES, epoch=5)
    with DurableDimensionStore(str(tmp_path)) as st2:
        rec = st2.reach_sketches()
        assert rec is not None and rec["epoch"] == 5
        np.testing.assert_array_equal(rec["mins"], mins)
        np.testing.assert_array_equal(rec["registers"], regs)
        assert rec["campaigns"] == NAMES
        # normal rows coexist with the sketch record
        assert st2.get("campA", 0)["clicks:SUM"] == 3
        st2.compact()
    with DurableDimensionStore(str(tmp_path)) as st3:
        rec = st3.reach_sketches()   # compaction kept the latest sketch
        assert rec is not None and rec["epoch"] == 5
        masks = np.zeros((2, C), bool)
        masks[0, :3] = True
        masks[1, [0, 4]] = True
        agree = ro.query_oracle_np(rec["mins"], rec["registers"], masks)
        np.testing.assert_array_equal(
            agree, ro.query_oracle_np(mins, regs, masks))
