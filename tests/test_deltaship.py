"""Incremental delta ships (reach/deltaship.py, ISSUE 18): chain-
stamped dirty-row records between periodic bases, the chain-validating
tailer (gap/damage => serve last consistent state, resync at the next
base, NEVER a half-folded plane), the Δ/C cutover, the force=>BASE
restart-path contract, store replay + mid-chain compaction, ship
faults landing on delta records, engine dirty-row tracking, and the
seeded drop/tear property sweep."""

import hashlib
import json
import os

import numpy as np
import pytest

from streambench_tpu.dimensions.store import LOG_NAME, DurableDimensionStore
from streambench_tpu.obs import MetricsRegistry
from streambench_tpu.reach.deltaship import (
    DELTA_KIND,
    REACH_PLANES,
    ChainTailer,
    DeltaShipper,
    decode_delta_record,
    merge_rows,
)
from streambench_tpu.reach.replica import SnapshotShipper

C, K, R = 24, 4, 4
EMPTY = np.uint32(0xFFFFFFFF)
NAMES = [f"c{i}" for i in range(C)]


def fresh_planes(c=C):
    return (np.full((c, K), EMPTY, np.uint32),
            np.zeros((c, R), np.int32))


def touch(rng, mins, regs, n=5):
    """One tick's worth of row touches; returns the touched indices."""
    idx = np.unique(rng.integers(0, mins.shape[0], n))
    mins[idx] = np.minimum(
        mins[idx], rng.integers(0, 2**32, (idx.size, K), dtype=np.uint32))
    regs[idx] = np.maximum(
        regs[idx], rng.integers(0, 30, (idx.size, R), dtype=np.int32))
    return idx


def digest(view):
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(view["mins"], np.uint32).tobytes())
    h.update(np.ascontiguousarray(view["registers"], np.int32).tobytes())
    return h.hexdigest()


def ship_path(tmp_path):
    return os.path.join(str(tmp_path), LOG_NAME)


# --------------------------------------------------------- wire format
def test_delta_record_roundtrip(tmp_path):
    store = DurableDimensionStore(str(tmp_path))
    idx = np.array([3, 7], np.int32)
    rows = {"mins": np.arange(2 * K, dtype=np.uint32).reshape(2, K),
            "regs": np.arange(2 * R, dtype=np.int32).reshape(2, R)}
    n = store.put_reach_delta(idx, rows, epoch=2, seq=5, prev_seq=4,
                              watermark=70_000, folded_ms=1, submit_ms=2)
    assert n > 0
    line = open(ship_path(tmp_path)).read().strip()
    rec = json.loads(line)
    assert rec["kind"] == DELTA_KIND and len(line) + 1 == n
    d = decode_delta_record(rec)
    assert d is not None
    assert d["seq"] == 5 and d["ps"] == 4 and d["epoch"] == 2
    assert np.array_equal(d["idx"], idx)
    assert np.array_equal(d["rows"]["mins"], rows["mins"])
    assert np.array_equal(d["rows"]["registers"], rows["regs"])
    assert d["watermark"] == 70_000


def test_decode_delta_rejects_damage():
    assert decode_delta_record({"kind": "nope"}) is None
    # missing chain stamps / unparseable payloads are None, not raises
    assert decode_delta_record({"kind": DELTA_KIND, "seq": 1}) is None
    # payload/index length skew (a corrupt tail): reshape must fail
    import base64 as b64
    assert decode_delta_record(
        {"kind": DELTA_KIND, "seq": 1, "ps": 0, "k": K, "r": R,
         "idx": b64.b64encode(np.zeros(2, np.int32).tobytes()).decode(),
         "mins": b64.b64encode(np.zeros(K, np.uint32).tobytes()).decode(),
         "regs": b64.b64encode(
             np.zeros(2 * R, np.int32).tobytes()).decode()}) is None


def test_merge_rows_is_min_max_and_copies_readonly():
    mins, regs = fresh_planes()
    ro = {"mins": np.frombuffer(mins.tobytes(), np.uint32).reshape(C, K),
          "registers": np.frombuffer(regs.tobytes(),
                                     np.int32).reshape(C, R)}
    assert not ro["mins"].flags.writeable
    idx = np.array([1], np.int32)
    rows = {"mins": np.full((1, K), 9, np.uint32),
            "registers": np.full((1, R), 9, np.int32)}
    merge_rows(ro, idx, rows, REACH_PLANES)
    assert ro["mins"].flags.writeable           # lazily copied
    assert (ro["mins"][1] == 9).all() and (ro["registers"][1] == 9).all()
    # idempotent re-fold: min/max absorb the same rows
    merge_rows(ro, idx, rows, REACH_PLANES)
    assert (ro["mins"][1] == 9).all() and (ro["registers"][1] == 9).all()


# ------------------------------------------------------------- shipper
def test_deltashipper_chain_base_cadence_and_counts(tmp_path):
    store = DurableDimensionStore(str(tmp_path))
    ship = DeltaShipper(store, NAMES, interval_ms=1, base_every=4)
    rng = np.random.default_rng(3)
    mins, regs = fresh_planes()
    import time
    for t in range(9):
        idx = touch(rng, mins, regs)
        assert ship.note_state(mins, regs, 1, watermark=t,
                               dirty_rows=idx)
        time.sleep(0.002)
    # first ship is a base (new epoch), then 4 deltas per base period
    assert ship.bases == 2 and ship.deltas == 7 and ship.ships == 9
    # the log carries a contiguous seq chain
    kinds, seqs = [], []
    for line in open(ship_path(tmp_path)):
        rec = json.loads(line)
        kinds.append(rec["kind"])
        seqs.append(rec["seq"])
    assert seqs == list(range(1, 10))
    assert kinds[0] == "reach_sketch" and kinds.count("reach_sketch") == 2


def test_cutover_large_dirty_set_ships_base(tmp_path):
    store = DurableDimensionStore(str(tmp_path))
    ship = DeltaShipper(store, NAMES, interval_ms=1, cutover_frac=0.5)
    mins, regs = fresh_planes()
    import time
    assert ship.note_state(mins, regs, 1, dirty_rows=np.arange(2))
    time.sleep(0.002)
    # dirty covers >= cutover_frac * C: a delta would cost more than
    # the base it replaces — ship the base, restart the chain
    assert ship.note_state(mins, regs, 1,
                           dirty_rows=np.arange(C // 2 + 1))
    assert ship.bases == 2 and ship.cutovers == 1 and ship.deltas == 0


def test_empty_dirty_set_ships_heartbeat_delta(tmp_path):
    store = DurableDimensionStore(str(tmp_path))
    ship = DeltaShipper(store, NAMES, interval_ms=1)
    mins, regs = fresh_planes()
    import time
    assert ship.note_state(mins, regs, 1, dirty_rows=np.arange(1))
    time.sleep(0.002)
    # a quiet cadence tick still ships a zero-row delta: the chain and
    # the replica's staleness anchor stay alive without plane bytes
    assert ship.note_state(mins, regs, 1, watermark=5,
                           dirty_rows=np.array([], np.int64))
    assert ship.deltas == 1 and ship.rows_last == 0
    tail = ChainTailer(ship_path(tmp_path))
    view = tail.poll()
    assert view["watermark"] == 5
    assert tail.stats()["deltas_folded"] == 1


def test_force_ships_base_under_delta_mode(tmp_path):
    """ISSUE 18 satellite bugfix: the restart-path forced ship must be
    a BASE — a respawned writer's dirty set is empty, and a forced
    delta would ship nothing and strand replicas."""
    store = DurableDimensionStore(str(tmp_path))
    ship = DeltaShipper(store, NAMES, interval_ms=10**9)
    rng = np.random.default_rng(5)
    mins, regs = fresh_planes()
    touch(rng, mins, regs)
    assert ship.note_state(mins, regs, 1, dirty_rows=np.arange(1))
    # same epoch, cadence closed, dirty EMPTY (the respawn case):
    # force must bypass the gate AND pick the base branch
    assert ship.note_state(mins, regs, 1, force=True,
                           dirty_rows=np.array([], np.int64))
    assert ship.bases == 2 and ship.deltas == 0
    tail = ChainTailer(ship_path(tmp_path))
    view = tail.poll()
    assert np.array_equal(view["mins"], mins)
    assert tail.stats()["bases_loaded"] == 2


def test_epoch_bump_ships_base_immediately(tmp_path):
    store = DurableDimensionStore(str(tmp_path))
    ship = DeltaShipper(store, NAMES, interval_ms=10**9)
    mins, regs = fresh_planes()
    assert ship.note_state(mins, regs, 1, dirty_rows=np.arange(1))
    # epoch bump: ships NOW (cadence bypassed) and as a base (replicas
    # must not fold cross-epoch deltas)
    assert ship.due(2)
    assert ship.note_state(mins, regs, 2, dirty_rows=np.arange(1))
    assert ship.bases == 2 and ship.deltas == 0


def test_shipper_gauges_and_summary(tmp_path):
    reg = MetricsRegistry()
    store = DurableDimensionStore(str(tmp_path))
    ship = DeltaShipper(store, NAMES, interval_ms=1, registry=reg)
    rng = np.random.default_rng(7)
    mins, regs = fresh_planes()
    import time
    ship.note_state(mins, regs, 1, dirty_rows=np.arange(1))
    time.sleep(0.002)
    idx = touch(rng, mins, regs, n=3)
    ship.note_state(mins, regs, 1, dirty_rows=idx)
    s = ship.summary()
    assert s["mode"] == "delta" and s["ships"] == 2
    assert s["rows_per_tick"] == idx.size
    assert 0 < s["bytes_per_tick"] < s["bytes_total"]
    assert s["ship_ms_per_tick"] >= 0
    text = reg.render_prometheus()
    assert "streambench_ship_bytes_per_tick" in text
    assert "streambench_ship_rows_per_tick" in text
    assert "streambench_ship_ms_per_tick" in text
    # the full-plane shipper reports the same surface (mode=full)
    full = SnapshotShipper(store, NAMES, interval_ms=1)
    full.note_state(mins, regs, 9)
    assert full.summary()["mode"] == "full"
    assert full.summary()["rows_per_tick"] == C


# -------------------------------------------------------- chain tailer
def test_tailer_folds_chain_bit_identically(tmp_path):
    store = DurableDimensionStore(str(tmp_path))
    ship = DeltaShipper(store, NAMES, interval_ms=1, base_every=100)
    tail = ChainTailer(ship_path(tmp_path))
    rng = np.random.default_rng(11)
    mins, regs = fresh_planes()
    import time
    for t in range(8):
        idx = touch(rng, mins, regs)
        assert ship.note_state(mins, regs, 1, watermark=t,
                               dirty_rows=idx)
        time.sleep(0.002)
        view = tail.poll()
        # every prefix of the chain folds to the writer's exact planes
        assert np.array_equal(view["mins"], mins)
        assert np.array_equal(view["registers"], regs)
        assert view["watermark"] == t
    st = tail.stats()
    assert st["bases_loaded"] == 1 and st["deltas_folded"] == 7
    assert st["gaps"] == 0 and st["damaged"] == 0


def test_tailer_gap_freezes_view_until_next_base(tmp_path):
    store = DurableDimensionStore(str(tmp_path))
    ship = DeltaShipper(store, NAMES, interval_ms=1, base_every=100)
    rng = np.random.default_rng(13)
    mins, regs = fresh_planes()
    import time
    assert ship.note_state(mins, regs, 1,
                           dirty_rows=np.arange(1))           # base
    frozen = (mins.copy(), regs.copy())
    time.sleep(0.002)
    idx = touch(rng, mins, regs)
    assert ship.note_state(mins, regs, 1, dirty_rows=idx)     # delta 2
    # drop delta seq=2 from the log: the tailer must detect ps skew
    lines = open(ship_path(tmp_path)).readlines()
    time.sleep(0.002)
    idx = touch(rng, mins, regs)
    ship.note_state(mins, regs, 1, dirty_rows=idx)            # delta 3
    tail = ChainTailer(ship_path(tmp_path))
    lines3 = open(ship_path(tmp_path)).readlines()
    with open(ship_path(tmp_path), "w") as f:
        f.writelines([lines3[0]] + lines3[2:])
    view = tail.poll()
    # base loaded; delta 3 does NOT chain off seq 1 — view is the
    # base, never a half-fold
    assert np.array_equal(view["mins"], frozen[0])
    assert np.array_equal(view["registers"], frozen[1])
    assert tail.stats()["gaps"] == 1 and tail.stats()["seq"] is None
    # further deltas stay dropped while desynced
    time.sleep(0.002)
    idx = touch(rng, mins, regs)
    ship.note_state(mins, regs, 1, dirty_rows=idx)            # delta 4
    assert tail.poll() is None
    assert tail.stats()["gaps"] == 2
    # next base resyncs to the live planes
    ship.note_state(mins, regs, 1, force=True)
    view = tail.poll()
    assert np.array_equal(view["mins"], mins)
    assert tail.stats()["resyncs"] == 1


def test_ship_faults_land_on_delta_records(tmp_path):
    """PR 16's torn/corrupt ship faults hit delta records through the
    same store hook; the tailer treats both as a broken chain."""
    store = DurableDimensionStore(str(tmp_path))
    faults = {2: "torn", 4: "corrupt"}     # 0-based appended-record idx
    count = {"n": 0}

    def hook(data):
        kind = faults.get(count["n"])
        count["n"] += 1
        if kind == "torn":
            return data[: len(data) // 2], False
        if kind == "corrupt":
            half = len(data) // 2
            return data[:half] + "\x00" * (len(data) - half - 1) + "\n", \
                False
        return data, True

    store.ship_fault_hook = hook
    ship = DeltaShipper(store, NAMES, interval_ms=1, base_every=100)
    tail = ChainTailer(ship_path(tmp_path))
    rng = np.random.default_rng(17)
    mins, regs = fresh_planes()
    import time
    for t in range(6):
        idx = touch(rng, mins, regs)
        ship.note_state(mins, regs, 1, dirty_rows=idx)
        time.sleep(0.002)
        view = tail.poll()
        if view is not None:
            # whatever the tailer serves is a consistent prefix fold —
            # between the torn record and the resync it simply stays
            # behind; it NEVER diverges from some writer state
            assert view["epoch"] == 1
    # recovery: a forced base resyncs the tailer to the live planes
    ship.note_state(mins, regs, 1, force=True)
    view = tail.poll()
    assert np.array_equal(view["mins"], mins)
    assert np.array_equal(view["registers"], regs)
    st = tail.stats()
    assert st["damaged"] + st["gaps"] >= 1
    assert st["resyncs"] >= 1


def test_tailer_legacy_base_only_log(tmp_path):
    """Full-ship logs (no seq, no deltas) read exactly like before:
    newest base wins."""
    store = DurableDimensionStore(str(tmp_path))
    ship = SnapshotShipper(store, NAMES, interval_ms=1)
    rng = np.random.default_rng(19)
    mins, regs = fresh_planes()
    import time
    for t in range(3):
        touch(rng, mins, regs)
        ship.note_state(mins, regs, t)     # epoch bump each tick
        time.sleep(0.002)
    tail = ChainTailer(ship_path(tmp_path))
    view = tail.poll()
    assert view["epoch"] == 2
    assert np.array_equal(view["mins"], mins)
    assert tail.stats()["bases_loaded"] == 3


# ------------------------------------------- seeded drop/tear property
@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5, 6, 7, 8])
def test_property_chain_gap_resync(tmp_path, seed):
    """Drop or tear arbitrary delta records: every polled view must be
    one of the writer's per-tick states (never half-folded), and the
    tailer must converge bit-identically after the next base."""
    rng = np.random.default_rng(seed)
    store = DurableDimensionStore(str(tmp_path))

    plan = {}       # appended-record index -> fault
    count = {"n": 0}

    def hook(data):
        kind = plan.get(count["n"])
        count["n"] += 1
        if kind == "drop":
            return "", False
        if kind == "torn":
            return data[: max(len(data) // 2, 1)], False
        return data, True

    store.ship_fault_hook = hook
    ship = DeltaShipper(store, NAMES, interval_ms=1, base_every=6)
    tail = ChainTailer(ship_path(tmp_path))
    mins, regs = fresh_planes()
    import time
    tick_digests = set()
    gaps_seen = False
    for t in range(20):
        # ~1 in 3 records damaged, bases included
        if rng.random() < 0.34:
            plan[t] = "drop" if rng.random() < 0.5 else "torn"
        idx = touch(rng, mins, regs)
        ship.note_state(mins, regs, 1, watermark=t, dirty_rows=idx)
        tick_digests.add(digest({"mins": mins, "registers": regs}))
        time.sleep(0.002)
        view = tail.poll()
        if view is not None:
            # consistency invariant: the served fold equals SOME
            # writer tick state — no half-folded plane, ever
            assert digest(view) in tick_digests, \
                f"half-folded plane served (seed {seed}, tick {t})"
        st = tail.stats()
        gaps_seen = gaps_seen or st["gaps"] > 0 or st["damaged"] > 0
    # convergence: an undamaged forced base always resyncs exactly.
    # Two bases: a trailing torn record (no newline) glues onto the
    # next append, so the first recovery base may itself be lost —
    # exactly the torn-tail behavior PR 16's chaos filter produces.
    ship.note_state(mins, regs, 1, force=True)
    ship.note_state(mins, regs, 1, force=True)
    view = tail.poll()
    assert view is not None
    assert np.array_equal(view["mins"], mins)
    assert np.array_equal(view["registers"], regs)
    # the sweep is only meaningful if damage actually landed somewhere
    # across the seeds; per-seed it may or may not hit a delta
    if plan:
        assert count["n"] > max(plan)


# ------------------------------------------------- store replay/compact
def test_store_replay_folds_delta_chain(tmp_path):
    store = DurableDimensionStore(str(tmp_path))
    ship = DeltaShipper(store, NAMES, interval_ms=1, base_every=100)
    rng = np.random.default_rng(23)
    mins, regs = fresh_planes()
    import time
    for t in range(5):
        idx = touch(rng, mins, regs)
        ship.note_state(mins, regs, 1, watermark=t, dirty_rows=idx)
        time.sleep(0.002)
    store.close()
    re = DurableDimensionStore(str(tmp_path))
    rv = re.reach_sketches()
    assert np.array_equal(rv["mins"], mins)
    assert np.array_equal(rv["registers"], regs)
    assert rv["watermark"] == 4


def test_replica_poll_once_over_delta_log(tmp_path):
    """Replica-level integration: ReachReplica's tailer folds deltas
    and serves the folded planes (poll_once test hook, no threads)."""
    from streambench_tpu.reach.replica import ReachReplica

    store = DurableDimensionStore(str(tmp_path))
    ship = DeltaShipper(store, NAMES, interval_ms=1, base_every=100)
    rng = np.random.default_rng(29)
    mins, regs = fresh_planes()
    import time
    for t in range(4):
        idx = touch(rng, mins, regs)
        ship.note_state(mins, regs, 1, watermark=70_000 + t,
                        dirty_rows=idx)
        time.sleep(0.002)
    rep = ReachReplica(ship_path(tmp_path), cache_capacity=0)
    try:
        assert rep.poll_once()
        assert rep.server is not None and rep.server.epoch == 1
        s = rep.summary()
        assert s["tailer"]["deltas_folded"] == 3
        srv_mins, srv_regs = rep.server._state[0], rep.server._state[1]
        assert np.array_equal(np.asarray(srv_mins), mins)
        assert np.array_equal(np.asarray(srv_regs), regs)
    finally:
        rep.close()
