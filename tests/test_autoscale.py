"""SLO autopilot (ISSUE 17): AutoscaleController safety rails, the
graceful retire path, router scale-up/down plumbing, and the obs
surfaces — all against injected clocks (no sockets, no sleeps).

Contracts pinned here:

- a priming step never actuates (history must not read as a breach);
- hysteresis: a breach must persist ``breach_ticks`` consecutive
  windows before any knob turns;
- per-knob cooldowns: a confirmed breach inside a cooldown is a
  ``hold``, never an actuation (flap refusal), and a cooling top
  verdict falls through to the runner-up knob instead of starving it;
- bounds: max_replicas / cadence floor are refusals counted on
  ``at_limit``, not silent clamps that journal fake decisions;
- scale-down only after a sustained healthy streak, via the
  supervisor's graceful retire (deregister FIRST, terminate not kill,
  counted separately from crash kills, never respawned);
- every journaled decision carries freshness-hop p99 evidence and is
  mirrored to sampler/flight-recorder/instruments;
- default-off: a controller with no hooks wired actuates nothing
  (the byte-identical pin for controller-off runs);
- ``obs fleet`` renders the controller sub-line, and ``--watch
  --iterations N`` renders exactly N reports.
"""

import json
import os

from streambench_tpu.chaos.fleet_supervisor import FleetSupervisor
from streambench_tpu.obs import AutoscaleController, MetricsRegistry
from streambench_tpu.obs.fleet import render_fleet, summarize_fleet

OBJECTIVE = {"staleness_ms": 1000, "p99_ms": 100}


def replica_rec(pid=1000, *, staleness_ms=100.0, p99_ms=5.0,
                hops=None):
    rq = {"staleness_ms": staleness_ms, "p99_ms": p99_ms, "qps": 10.0,
          "served": 50, "shed": 0, "shed_stale": 0}
    rq["freshness"] = {"hops": {h: {"p99": v} for h, v in
                               (hops or {"serve": staleness_ms}).items()}}
    return {"kind": "snapshot", "role": "replica", "pid": pid,
            "ts_ms": 1_000, "reach_query": rq}


def router_rec(**kw):
    rt = {"routed": 100, "answered": 100, "shed": 0, "failovers": 0,
          "replicas": [{}]}
    rt.update(kw)
    return {"kind": "snapshot", "role": "router", "pid": 2,
            "ts_ms": 1_001, "router": rt}


def stale_recs():
    """Staleness breach, age in the serve hop -> fold_lag/ship knob."""
    return [replica_rec(staleness_ms=1500,
                        hops={"fold_lag": 5, "tail_lag": 60,
                              "serve": 1400})]


def hot_recs():
    """Router front-door p99 breach -> serve/replica_count knob."""
    return [replica_rec(staleness_ms=100, p99_ms=4),
            router_rec(e2e_p99_ms=250.0)]


def healthy_recs():
    return [replica_rec(staleness_ms=100, p99_ms=4)]


class _Shipper:
    def __init__(self, interval_ms=2000):
        self.interval_ms = interval_ms


def _ctrl(collect, **kw):
    clock = {"t": 0.0}
    kw.setdefault("objective", OBJECTIVE)
    ctrl = AutoscaleController(collect, clock=lambda: clock["t"],
                               sleep=lambda s: None, **kw)
    return ctrl, clock


# ----------------------------------------------------------------------
# controller safety rails


def test_priming_step_never_actuates():
    ship = _Shipper(2000)
    ctrl, _ = _ctrl(stale_recs, shipper=ship,
                    min_ship_interval_ms=500, breach_ticks=1)
    assert ctrl.step() is None            # priming: record, don't act
    assert ship.interval_ms == 2000 and not ctrl.decisions
    dec = ctrl.step()                     # same breach, now confirmed
    assert dec["decision"] == "ship_faster"
    assert (dec["from_ms"], dec["to_ms"]) == (2000, 1000)


def test_hysteresis_requires_consecutive_breach_windows():
    ship = _Shipper(2000)
    ctrl, _ = _ctrl(stale_recs, shipper=ship,
                    min_ship_interval_ms=500, breach_ticks=3)
    assert [ctrl.step() for _ in range(3)] == [None, None, None]
    assert ctrl.step()["decision"] == "ship_faster"


def test_cooldown_counts_holds_then_reacts_after_expiry():
    ship = _Shipper(2000)
    ctrl, clock = _ctrl(stale_recs, shipper=ship,
                        min_ship_interval_ms=500, breach_ticks=1,
                        cooldown_s=10.0)
    ctrl.step()
    assert ctrl.step()["decision"] == "ship_faster"
    clock["t"] = 1.0
    assert ctrl.step() is None and ctrl.holds == 1   # flap refused
    assert ship.interval_ms == 1000
    clock["t"] = 11.0
    assert ctrl.step()["to_ms"] == 500


def test_cooling_top_verdict_falls_through_to_runner_up():
    ship = _Shipper(2000)
    spawned = []
    ctrl, _ = _ctrl(lambda: stale_recs()
                    + [router_rec(e2e_p99_ms=250.0)],
                    shipper=ship, min_ship_interval_ms=500,
                    spawn_replica=lambda: spawned.append(1) or True,
                    max_replicas=3, breach_ticks=1, cooldown_s=60.0)
    ctrl.step()
    first = ctrl.step()["decision"]
    second = ctrl.step()["decision"]      # first knob cooling
    assert {first, second} == {"ship_faster", "scale_up"}
    assert spawned and ship.interval_ms == 1000
    assert ctrl.step() is None and ctrl.holds == 1   # both cooling now


def test_bounds_are_refusals_counted_on_at_limit():
    ctrl, _ = _ctrl(hot_recs, spawn_replica=lambda: True,
                    replicas=2, max_replicas=2, breach_ticks=1)
    ctrl.step(), ctrl.step()
    assert ctrl.replicas == 2 and not ctrl.decisions
    assert ctrl.at_limit >= 1
    ship = _Shipper(500)
    ctrl2, _ = _ctrl(stale_recs, shipper=ship,
                     min_ship_interval_ms=500, breach_ticks=1)
    ctrl2.step(), ctrl2.step()
    assert ship.interval_ms == 500 and ctrl2.at_limit >= 1


def test_healthy_streak_retires_with_cooldown_between():
    retired = []
    ctrl, clock = _ctrl(healthy_recs, replicas=3, min_replicas=1,
                        retire_replica=lambda: retired.append(1) or True,
                        healthy_ticks=2, breach_ticks=1,
                        cooldown_s=10.0)
    ctrl.step()                                        # priming
    assert ctrl.step() is None                         # streak 1
    dec = ctrl.step()                                  # streak 2
    assert dec["decision"] == "scale_down" and ctrl.replicas == 2
    clock["t"] = 1.0
    ctrl.step()                                        # streak 1 again
    assert ctrl.step() is None and ctrl.holds == 1     # cooling
    clock["t"] = 20.0
    ctrl.step()
    assert ctrl.replicas == 1 and len(retired) == 2
    # at the floor: healthy forever, never retires below min_replicas
    clock["t"] = 60.0
    for _ in range(5):
        assert ctrl.step() is None
    assert ctrl.replicas == 1


def test_retire_hook_refusal_keeps_the_count():
    ctrl, _ = _ctrl(healthy_recs, replicas=2,
                    retire_replica=lambda: False, healthy_ticks=1,
                    breach_ticks=1)
    ctrl.step(), ctrl.step()
    assert ctrl.replicas == 2 and not ctrl.decisions


def test_shed_redirects_ride_the_failover_counter():
    fo = {"n": 0}
    ctrl, _ = _ctrl(lambda: healthy_recs()
                    + [router_rec(failovers=fo["n"])])
    ctrl.step()
    fo["n"] = 3
    ctrl.step()
    fo["n"] = 3
    ctrl.step()
    assert ctrl.shed_redirects == 3
    assert ctrl.summary()["shed_redirects"] == 3


def test_default_off_no_hooks_actuates_nothing():
    ctrl, _ = _ctrl(lambda: stale_recs()
                    + [router_rec(e2e_p99_ms=250.0)], breach_ticks=1)
    for _ in range(6):
        ctrl.step()
    s = ctrl.summary()
    assert s["decisions"] == 0 and s["replicas"] == 1
    assert not ctrl.actions


def test_decisions_journal_evidence_and_mirror_everywhere():
    notes, frames = [], []

    class _Sampler:
        def annotate(self, event, **fields):
            notes.append((event, fields))

    class _Rec:
        def record(self, cat, **fields):
            frames.append((cat, fields))

    reg = MetricsRegistry()
    ctrl, _ = _ctrl(hot_recs, spawn_replica=lambda: True,
                    breach_ticks=1, sampler=_Sampler(),
                    flightrec=_Rec(), registry=reg)
    ctrl.step()
    dec = ctrl.step()
    assert dec["decision"] == "scale_up"
    assert dec["evidence"]["hop_p99_ms"]        # hop-backed, always
    assert dec["why"]
    assert notes[0][0] == "autoscale_decision"
    assert notes[0][1]["evidence"]["hop_p99_ms"]
    assert frames[0][0] == "autoscale"
    names = {m.name for m in reg.collect()}
    assert {"streambench_autoscale_decisions_total",
            "streambench_autoscale_replicas_total",
            "streambench_autoscale_shed_redirects_total"} <= names
    dec_ctr = reg.counter("streambench_autoscale_decisions_total")
    rep_g = reg.gauge("streambench_autoscale_replicas_total")
    assert dec_ctr.value == 1 and rep_g.value == 2


# ----------------------------------------------------------------------
# supervisor graceful retire (vs crash kill)


class _FakeProc:
    def __init__(self, pid=4242):
        self.pid = pid
        self.code = None
        self.terminated = False

    def poll(self):
        return self.code

    def kill(self):
        self.code = -9

    def terminate(self):
        self.terminated = True
        self.code = 0


def _fleet(n=2, **kw):
    clock = {"t": 0.0}
    procs = []

    def spawn(idx, attempt):
        p = _FakeProc(pid=5000 + idx)
        procs.append(p)
        return p

    sup = FleetSupervisor(spawn, n, clock=lambda: clock["t"],
                          sleep=lambda s: None, **kw).start()
    return sup, clock, procs


def test_retire_deregisters_first_terminates_and_never_respawns():
    sup, clock, procs = _fleet(2)
    order = []
    assert sup.retire(1, deregister=lambda i: order.append(("dereg", i)),
                      drain_s=0.0) is True
    assert order == [("dereg", 1)]
    assert procs[1].terminated and procs[1].code == 0   # SIGTERM, not -9
    assert not sup.alive(1) and sup.alive(0)
    assert sup.retire(1) is False                       # idempotent
    clock["t"] = 60.0
    assert sup.step() == 0                              # no respawn
    s = sup.summary()
    assert s["retired"] == 1 and s["active"] == 1
    assert s["kills"] == 0 and s["restarts"] == 0
    assert sup.counters.get("retires") == 1


def test_retire_is_not_a_crash_but_kill_is():
    sup, clock, procs = _fleet(2)
    sup.kill(0)
    assert procs[0].code == -9
    sup.retire(1, drain_s=0.0)
    s = sup.summary()
    assert s["kills"] == 1 and s["retired"] == 1


def test_spawn_grows_the_fleet():
    sup, clock, procs = _fleet(1)
    idx = sup.spawn()
    assert idx == 1 and len(sup.slots) == 2 and sup.alive(1)
    assert sup.counters.get("spawns") == 1


# ----------------------------------------------------------------------
# router scale plumbing + the e2e latency window


def _router():
    from streambench_tpu.reach.router import ReachRouter
    return ReachRouter(["127.0.0.1:7101"], host="127.0.0.1", port=0)


def test_router_add_remove_replica():
    import pytest

    r = _router()
    r.add_replica("127.0.0.1:7102")
    assert [h.addr for h in r.handles] == ["127.0.0.1:7101",
                                           "127.0.0.1:7102"]
    assert r.remove_replica("127.0.0.1:7101") is True
    assert [h.addr for h in r.handles] == ["127.0.0.1:7102"]
    assert r.remove_replica("127.0.0.1:9999") is False
    with pytest.raises(ValueError):
        r.remove_replica("127.0.0.1:7102")   # never empty the fleet


def test_router_e2e_percentiles_use_a_recent_window():
    import time as _t

    from streambench_tpu.reach.router import E2E_WINDOW_S

    r = _router()
    now = _t.monotonic()
    # an old burst (outside the window) must decay out of the summary,
    # or a past breach reads as live forever and retire never fires
    r._e2e_ring = [(now - E2E_WINDOW_S - 1.0, 500.0)] * 50 \
        + [(now, 5.0)] * 10
    s = r.summary()
    assert s["e2e_recent_n"] == 10 and s["e2e_p99_ms"] == 5.0


# ----------------------------------------------------------------------
# e2e: controller + supervisor + fake procs, scale up then retire


def test_controller_scales_fleet_up_then_retires_over_fake_procs():
    sup, clock, procs = _fleet(1, healthy_after_s=0.0)
    hot = {"on": True}

    def collect():
        return hot_recs() if hot["on"] else healthy_recs()

    ctrl, cclock = _ctrl(
        collect,
        spawn_replica=lambda: sup.spawn() is not None,
        retire_replica=lambda: sup.retire(len(sup.slots) - 1,
                                          drain_s=0.0),
        replicas=1, max_replicas=2, breach_ticks=2, healthy_ticks=2,
        cooldown_s=1.0)
    ctrl.step()                     # priming
    ctrl.step()                     # breach streak 1
    dec = ctrl.step()               # streak 2 -> scale_up
    assert dec["decision"] == "scale_up"
    assert len(sup.slots) == 2 and sup.alive(1)
    hot["on"] = False               # ramp over: fleet goes healthy
    cclock["t"] = 10.0
    ctrl.step()
    dec = ctrl.step()
    assert dec["decision"] == "scale_down"
    assert sup.summary()["retired"] == 1 and procs[1].terminated
    assert ctrl.replicas == 1 and sup.alive(0)
    assert sup.summary()["kills"] == 0


# ----------------------------------------------------------------------
# obs surfaces


def test_fleet_report_renders_controller_sub_line():
    ctrl, _ = _ctrl(hot_recs, spawn_replica=lambda: True,
                    breach_ticks=1)
    ctrl.step(), ctrl.step()
    recs = healthy_recs() + [
        {"kind": "snapshot", "role": "controller", "pid": 9,
         "ts_ms": 2_000, "autoscale": ctrl.summary()}]
    out = render_fleet(summarize_fleet(recs))
    assert "autoscale: replicas 2" in out
    assert "last scale_up[serve->replica_count]" in out


def test_fleet_decision_events_alone_still_render():
    recs = [{"kind": "event", "event": "autoscale_decision",
             "ts_ms": 1, "decision": "ship_faster",
             "verdict": "fold_lag", "knob": "ship_cadence",
             "replicas": 1}]
    s = summarize_fleet(recs)
    row = next(a for a in s["roles"] if a.get("autoscale"))
    assert row["autoscale"]["decisions"] == 1
    assert "ship_faster[fold_lag->ship_cadence]" in render_fleet(s)


def test_obs_fleet_watch_renders_bounded_iterations(tmp_path, capsys):
    from streambench_tpu.obs.__main__ import main

    d = tmp_path / "fleet" / "replica_0"
    os.makedirs(d)
    with open(d / "metrics.jsonl", "w") as f:
        f.write(json.dumps(replica_rec()) + "\n")
    rc = main(["fleet", str(tmp_path / "fleet"), "--watch",
               "--interval-s", "0.01", "--iterations", "2"])
    assert rc == 0
    assert capsys.readouterr().out.count("fleet report:") == 2


# ----------------------------------------------------------------------
# the seeded QPS schedule (bench rung input)


def test_qps_ramp_schedule_is_seed_deterministic():
    import bench_reach

    a = bench_reach.qps_ramp_schedule(seed=13, duration_s=10.0,
                                      qps0=5.0, qps1=30.0)
    b = bench_reach.qps_ramp_schedule(seed=13, duration_s=10.0,
                                      qps0=5.0, qps1=30.0)
    c = bench_reach.qps_ramp_schedule(seed=14, duration_s=10.0,
                                      qps0=5.0, qps1=30.0)
    assert a == b and a != c
    assert a == sorted(a) and 0.0 <= a[0] and a[-1] <= 10.0
    # the ramp actually ramps: the back half is denser than the front
    front = sum(1 for t in a if t < 5.0)
    assert len(a) - front > front
