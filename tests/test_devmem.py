"""Device-memory ledger (ISSUE 9 tentpole, obs.devmem): XLA
memory_analysis sums vs hand-computed shapes for a tiny kernel, the
per-engine peak-footprint estimate, the fails-closed kernel hook, the
live-array census, and the sampler collector cadence."""

import jax
import jax.numpy as jnp
import pytest

from streambench_tpu.obs import DeviceMemoryLedger
from streambench_tpu.obs.devmem import (
    kernel_memory,
    live_array_census,
    state_nbytes,
)


def test_kernel_memory_matches_hand_computed_shapes():
    """A [1024] f32 + [1024] f32 -> [1024] f32 kernel: XLA's own
    argument/output accounting must equal the dtype arithmetic."""
    f = jax.jit(lambda x, y: x + y)
    x = jnp.ones(1024, jnp.float32)
    rep = kernel_memory(f, x, x)
    if not rep["supported"]:
        pytest.skip(f"memory_analysis unsupported: {rep['error']}")
    assert rep["argument_bytes"] == 2 * 1024 * 4
    assert rep["output_bytes"] == 1024 * 4
    assert rep["total_bytes"] == (rep["argument_bytes"]
                                  + rep["output_bytes"]
                                  + rep.get("temp_bytes", 0))


def test_kernel_memory_static_kwargs_and_failure_shape():
    g = jax.jit(lambda x, *, k: x * k, static_argnames=("k",))
    rep = kernel_memory(g, jnp.ones(16, jnp.int32), k=3)
    if rep["supported"]:
        assert rep["argument_bytes"] == 16 * 4
    # a kernel that cannot lower never raises into obs callers
    bad = kernel_memory(jax.jit(lambda x: x), "not-an-array")
    assert bad["supported"] is False and "error" in bad


def test_state_nbytes_over_pytree():
    state = {"a": jnp.zeros((4, 8), jnp.int32),
             "b": (jnp.zeros(3, jnp.float32), None, 7)}
    # non-array leaves (None, ints) contribute nothing
    assert state_nbytes(state) == 4 * 8 * 4 + 3 * 4
    assert state_nbytes(None) == 0


def test_live_array_census_sees_new_arrays():
    before = live_array_census()
    if not before.get("supported"):
        pytest.skip(f"live_arrays unsupported: {before.get('error')}")
    keep = [jnp.ones(2048, jnp.float32) for _ in range(3)]
    jax.block_until_ready(keep)
    after = live_array_census()
    assert after["count"] >= before["count"] + 3
    assert after["bytes"] >= before["bytes"] + 3 * 2048 * 4
    # the [2048] f32 arrays land in the 8192-byte power-of-two bucket
    b = after["buckets"].get("8192")
    assert b is not None and b["count"] >= 3
    del keep


def test_ledger_peak_footprint_and_gauges():
    from streambench_tpu.obs import MetricsRegistry

    reg = MetricsRegistry()
    led = DeviceMemoryLedger(reg, census_every=2)
    led.state_bytes = 1000
    led.kernels["small"] = {"supported": True, "total_bytes": 50}
    led.kernels["big"] = {"supported": True, "total_bytes": 700}
    led.kernels["broken"] = {"supported": False, "error": "nope"}
    # peak = persistent state + the LARGEST single kernel working set
    assert led.peak_footprint_bytes() == 1700
    rec: dict = {}
    led.collect(rec, 1.0)                # tick 0: census refreshed
    assert rec["devmem"]["peak_footprint_bytes"] == 1700
    assert rec["devmem"]["state_bytes"] == 1000
    census0 = rec["devmem"].get("live")
    rec2: dict = {}
    led.collect(rec2, 1.0)               # tick 1: census NOT refreshed
    assert rec2["devmem"].get("live") is census0
    if census0 and census0.get("supported"):
        assert reg.gauge(
            "streambench_devmem_live_arrays").value == census0["count"]


def test_analyze_engine_real_kernels(tmp_path):
    """On a real exact-count engine the ledger reports every hot kernel
    with XLA's accounting, and the step kernel's argument bytes are
    exactly state + join table + the packed wire columns."""
    import random

    from streambench_tpu.config import default_config
    from streambench_tpu.datagen import gen
    from streambench_tpu.engine import AdAnalyticsEngine
    from streambench_tpu.io.fakeredis import FakeRedisStore
    from streambench_tpu.io.journal import FileBroker
    from streambench_tpu.io.redis_schema import as_redis, seed_campaigns

    cfg = default_config(jax_batch_size=256, jax_scan_batches=2)
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(as_redis(FakeRedisStore()), cfg, broker=broker,
                 events_num=200, rng=random.Random(5),
                 workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    r = as_redis(FakeRedisStore())
    seed_campaigns(r, sorted(set(mapping.values())))
    engine = AdAnalyticsEngine(cfg, mapping, redis=r)
    led = DeviceMemoryLedger()
    rep = led.analyze_engine(engine)
    assert led.state_bytes == state_nbytes(engine.state)
    kernels = {n: k for n, k in rep["kernels"].items()
               if k.get("supported")}
    if not kernels:
        pytest.skip("memory_analysis unsupported on this backend")
    step = kernels.get("step_packed") or kernels.get("step")
    assert step is not None and "drain" in kernels
    expect_cols = (2 if "step_packed" in kernels
                   else 4) * engine.batch_size * 4
    join_bytes = engine.join_table.nbytes
    assert step["argument_bytes"] == (led.state_bytes + join_bytes
                                      + expect_cols)
    assert rep["peak_footprint_bytes"] >= led.state_bytes
    engine.close()


def test_devmem_kernels_hook_fails_closed(tmp_path):
    """An engine whose device hooks the base list cannot describe (the
    HLL sketch overrides _device_step) reports NO kernel table rather
    than a wrong one — state + census only."""
    import random

    from streambench_tpu.config import default_config
    from streambench_tpu.datagen import gen
    from streambench_tpu.engine.sketches import HLLDistinctEngine
    from streambench_tpu.io.fakeredis import FakeRedisStore
    from streambench_tpu.io.journal import FileBroker
    from streambench_tpu.io.redis_schema import as_redis

    cfg = default_config(jax_batch_size=256)
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(as_redis(FakeRedisStore()), cfg, broker=broker,
                 events_num=200, rng=random.Random(6),
                 workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    engine = HLLDistinctEngine(cfg, mapping,
                               redis=as_redis(FakeRedisStore()))
    assert engine._devmem_kernels() == []
    led = DeviceMemoryLedger()
    rep = led.analyze_engine(engine)
    assert rep["kernels"] == {}
    assert rep["state_bytes"] > 0        # HLL registers are real bytes
    assert rep["peak_footprint_bytes"] == rep["state_bytes"]
    engine.close()
