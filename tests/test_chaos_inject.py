"""Chaos layer unit surface: deterministic plans, atomic sink faults,
loss-free journal damage, scripted crash points.

The injection contracts the recovery harness depends on are pinned here:
same seed -> same plan; a faulted sink op applies NOTHING; a journal
fault never loses a byte (damaged records are NUL-marked and rewound);
an exhausted crash script never raises again.
"""

import pytest

from streambench_tpu.chaos import (
    ChaosJournalReader,
    CrashScheduler,
    EngineCrash,
    FaultInjector,
    FaultPlan,
)
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import JournalReader, JournalWriter
from streambench_tpu.io.redis_schema import as_redis
from streambench_tpu.io.resp import RespError


def test_fault_plan_seeded_deterministic():
    a = FaultPlan.generate(42, sink_rate=0.3, sink_ops=50,
                           journal_rate=0.3, journal_polls=50, crashes=5)
    b = FaultPlan.generate(42, sink_rate=0.3, sink_ops=50,
                           journal_rate=0.3, journal_polls=50, crashes=5)
    assert a == b
    c = FaultPlan.generate(43, sink_rate=0.3, sink_ops=50,
                           journal_rate=0.3, journal_polls=50, crashes=5)
    assert a != c
    assert FaultPlan.zeros().is_zero and not a.is_zero


def test_sink_faults_are_atomic_and_scheduled():
    """A faulted op raises the mapped error and forwards nothing; clean
    ops pass through untouched."""
    store = FakeRedisStore()
    plan = FaultPlan(sink_faults={0: "refused", 2: "timeout", 3: "resp"})
    proxy = FaultInjector(plan).wrap_redis(as_redis(store))
    with pytest.raises(ConnectionRefusedError):
        proxy.execute("SET", "k", "v")
    assert store.get("k") is None            # nothing applied
    assert proxy.execute("SET", "k", "v") == "OK"   # op 1: clean
    with pytest.raises(TimeoutError):
        proxy.pipeline_execute([("SET", "k", "w")])
    with pytest.raises(RespError):
        proxy.execute("GET", "k")
    assert store.get("k") == "v"             # only the clean op landed
    assert proxy.execute("GET", "k") == "v"


def test_sink_proxy_hides_native_store_probe():
    """The engine's ``redis._store`` probe must miss, or flushes would
    bypass the faultable path through the in-C bulk writeback."""
    proxy = FaultInjector(FaultPlan.zeros()).wrap_redis(
        as_redis(FakeRedisStore()))
    assert getattr(proxy, "_store", None) is None


def _write_topic(tmp_path, n=50):
    path = str(tmp_path / "t.jsonl")
    lines = [f'{{"rec": {i}, "pad": "{"x" * 40}"}}'.encode()
             for i in range(n)]
    with JournalWriter(path) as w:
        w.append_many(lines)
    return path, lines


@pytest.mark.parametrize("kind", ["truncated", "torn", "corrupt"])
def test_journal_faults_lose_nothing(tmp_path, kind):
    """Reading the whole topic through a faulting wrapper yields every
    original record exactly once; injected damage is NUL-marked garbage
    that can never parse as an event."""
    path, lines = _write_topic(tmp_path)
    plan = FaultPlan(journal_faults={0: kind, 2: kind, 3: kind})
    inj = FaultInjector(plan)
    r = inj.wrap_reader(JournalReader(path))
    got, garbage = [], []
    for _ in range(100):
        batch = r.poll(8)
        if not batch and r.offset == len(b"".join(l + b"\n" for l in lines)):
            break
        for line in batch:
            (garbage if b"\x00" in line else got).append(line)
    assert got == lines                      # every record, once, in order
    assert inj.counters.get("journal_faults") == 3
    if kind != "truncated":
        assert garbage                       # damage was actually delivered
    assert all(b"\x00" in g for g in garbage)


@pytest.mark.parametrize("kind", ["truncated", "torn", "corrupt"])
def test_journal_faults_block_mode_lose_nothing(tmp_path, kind):
    path, lines = _write_topic(tmp_path)
    inj = FaultInjector(FaultPlan(journal_faults={0: kind, 1: kind}))
    r = inj.wrap_reader(JournalReader(path))
    got, garbage = [], []
    while True:
        data = r.poll_block(512)
        if not data:
            break
        for line in data.split(b"\n"):
            if line:
                (garbage if b"\x00" in line else got).append(line)
    assert got == lines
    assert all(b"\x00" in g for g in garbage)


def test_zero_plan_wrappers_are_passthrough(tmp_path):
    path, lines = _write_topic(tmp_path, n=10)
    inj = FaultInjector(FaultPlan.zeros())
    r = inj.wrap_reader(JournalReader(path))
    assert r.poll(100) == lines
    assert inj.counters.snapshot() == {}
    store = FakeRedisStore()
    proxy = inj.wrap_redis(as_redis(store))
    assert proxy.execute("SET", "a", "1") == "OK"
    assert store.get("a") == "1"


def test_crash_scheduler_script_and_reset():
    sched = CrashScheduler([("batch", 2), ("flush", 1)])
    sched.point("batch")                     # batch #1: armed at #2
    with pytest.raises(EngineCrash):
        sched.point("batch")
    assert sched.remaining == 1
    sched.reset()                            # restart: counts restart
    sched.point("batch")                     # not a flush: no crash
    with pytest.raises(EngineCrash):
        sched.point("flush")
    assert sched.exhausted
    for _ in range(5):                       # exhausted: never raises again
        sched.point("batch")
        sched.point("flush")
    assert sched.counters.get("crashes_injected") == 2


def test_wrap_reader_rejects_multireader(tmp_path):
    from streambench_tpu.io.journal import FileBroker, MultiReader

    broker = FileBroker(str(tmp_path / "b"))
    broker.create_topic("t", partitions=2)
    with pytest.raises(TypeError):
        FaultInjector(FaultPlan.zeros()).wrap_reader(
            MultiReader([broker.reader("t", 0), broker.reader("t", 1)]))
