"""Dispatch-amortization + deferred-drain paths (round-3 perf work).

The catchup hot loop folds K stacked micro-batches per device dispatch
(``AdAnalyticsEngine.process_chunk`` -> ``ops.windowcount.scan_steps``)
and defers drain materialization off the hot path
(``_drain_device`` parks device arrays; ``_materialize_drains`` pulls
them at flush/snapshot time).  These tests pin that every such shortcut
is invisible to correctness: chunked == per-line, snapshots see parked
deltas, and the sharded scan matches the per-batch sharded step.
"""

import random

import jax
import numpy as np

from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.encode import EventEncoder
from streambench_tpu.engine.pipeline import AdAnalyticsEngine


def make_lines(n, seed=0, start=1_700_000_000_000, spacing_ms=10):
    campaigns = [f"c{i}" for i in range(10)]
    mapping = {f"ad{i}_{j}": campaigns[i]
               for i in range(10) for j in range(10)}
    src = gen.EventSource(ads=list(mapping),
                          user_ids=[f"u{i}" for i in range(20)],
                          page_ids=["p"], rng=random.Random(seed))
    lines = [src.event_at(start + spacing_ms * i).encode()
             for i in range(n)]
    return lines, mapping, campaigns


def drained_pending(eng):
    """Drain + materialize WITHOUT flushing (flush clears the pending
    buffers); pending_counts folds the numpy drain triples into the
    dict view."""
    eng._drain_device()
    eng._materialize_drains()
    return eng.pending_counts()


def run_engine(lines, mapping, campaigns, *, chunked, slots=16,
               batch=256, scan_batches=4):
    cfg = default_config(jax_batch_size=batch, jax_window_slots=slots,
                         jax_scan_batches=scan_batches)
    eng = AdAnalyticsEngine(cfg, mapping, campaigns=campaigns)
    if chunked:
        step = batch * scan_batches
        for off in range(0, len(lines), step):
            eng.process_chunk(lines[off:off + step])
    else:
        for off in range(0, len(lines), batch):
            eng.process_lines(lines[off:off + batch])
    return eng


def test_chunked_equals_per_line():
    lines, mapping, campaigns = make_lines(5000, seed=2)
    a = run_engine(lines, mapping, campaigns, chunked=False)
    b = run_engine(lines, mapping, campaigns, chunked=True)
    assert a.events_processed == b.events_processed == 5000
    assert a.dropped == 0 and b.dropped == 0
    pa, pb = drained_pending(a), drained_pending(b)
    assert pa == pb and sum(pa.values()) > 0


def test_chunked_spanning_many_windows_uses_guard():
    # 4000 events at 100 ms spacing = 400 s of event time against a
    # W=16 ring (80 s safe span): groups must drain via the span guard
    # (or fall back per-batch) and still be exact.
    lines, mapping, campaigns = make_lines(4000, seed=3, spacing_ms=100)
    a = run_engine(lines, mapping, campaigns, chunked=False)
    b = run_engine(lines, mapping, campaigns, chunked=True)
    assert b.dropped == 0
    pa, pb = drained_pending(a), drained_pending(b)
    assert pa == pb and sum(pb.values()) > 0
    # many distinct windows were actually produced
    assert len({ts for _, ts in pb}) > 16


def test_chunk_ragged_tail_and_empty():
    lines, mapping, campaigns = make_lines(1000, seed=4)
    cfg = default_config(jax_batch_size=256, jax_scan_batches=4)
    eng = AdAnalyticsEngine(cfg, mapping, campaigns=campaigns)
    eng.process_chunk([])                 # no-op
    eng.process_chunk(lines[:700])        # 2 full + 1 ragged batch
    eng.process_chunk(lines[700:])        # 1 full + ragged
    assert eng.events_processed == 1000
    assert sum(drained_pending(eng).values()) == sum(
        1 for ln in lines if b'"view"' in ln)


def test_snapshot_sees_parked_drains():
    # Force a drain (parked, not materialized), then snapshot: the parked
    # deltas must appear in the snapshot's pending list, not vanish.
    lines, mapping, campaigns = make_lines(600, seed=5)
    cfg = default_config(jax_batch_size=128, jax_window_slots=16)
    eng = AdAnalyticsEngine(cfg, mapping, campaigns=campaigns)
    eng.process_lines(lines[:256])
    eng._drain_device()                   # parks device arrays
    assert eng._undrained
    snap = eng.snapshot(offset=0)
    assert not eng._undrained             # materialized by snapshot
    total = sum(n for _, _, n in snap.pending)
    views = sum(1 for ln in lines[:256] if b'"view"' in ln)
    assert total == views

    # restore into a fresh engine and continue: totals stay exact
    eng2 = AdAnalyticsEngine(cfg, mapping, campaigns=campaigns)
    eng2.restore(snap)
    eng2.process_lines(lines[256:])
    all_views = sum(1 for ln in lines if b'"view"' in ln)
    assert sum(drained_pending(eng2).values()) == all_views


def test_sharded_scan_matches_per_batch_step():
    from streambench_tpu.parallel import build_mesh
    from streambench_tpu.parallel.sharded import ShardedWindowEngine

    lines, mapping, campaigns = make_lines(2048, seed=6)
    mesh = build_mesh(data=2, campaign=4, devices=jax.devices()[:8])

    cfg = default_config(jax_batch_size=256, jax_scan_batches=4)
    a = ShardedWindowEngine(cfg, mapping, mesh, campaigns=campaigns)
    for off in range(0, len(lines), 256):
        a.process_lines(lines[off:off + 256])

    b = ShardedWindowEngine(cfg, mapping, mesh, campaigns=campaigns)
    assert b.SCAN_SUPPORTED
    b.process_chunk(lines)

    pa, pb = drained_pending(a), drained_pending(b)
    assert pa == pb and sum(pa.values()) > 0
    assert a.dropped == b.dropped == 0


def test_failed_redis_write_is_reclaimed_and_retried():
    """A transient Redis outage must not undercount windows: the writer
    thread retains failed batches and the next flush retries them."""
    from streambench_tpu.io.fakeredis import FakeRedisStore
    from streambench_tpu.io.redis_schema import as_redis, read_seen_counts

    class FlakyRedis:
        def __init__(self, inner):
            self._inner = inner
            self.fail = False

        def execute(self, *a):
            if self.fail:
                raise OSError("redis down")
            return self._inner.execute(*a)

        def pipeline_execute(self, cmds):
            if self.fail:
                raise OSError("redis down")
            return self._inner.pipeline_execute(cmds)

    from streambench_tpu.io.redis_schema import seed_campaigns

    lines, mapping, campaigns = make_lines(512, seed=9)
    inner = as_redis(FakeRedisStore())
    seed_campaigns(inner, campaigns)
    r = FlakyRedis(inner)
    cfg = default_config(jax_batch_size=128)
    eng = AdAnalyticsEngine(cfg, mapping, campaigns=campaigns, redis=r)

    eng.process_lines(lines[:256])
    r.fail = True
    eng.flush(time_updated=111)      # write fails in the writer thread
    eng.drain_writes()
    r.fail = False
    eng.process_lines(lines[256:])
    eng.flush(time_updated=222)      # reclaims + retries the failed rows
    eng.close()

    total = sum(n for per in read_seen_counts(inner).values()
                for n in per.values())
    views = sum(1 for ln in lines if b'"view"' in ln)
    assert total == views


def test_block_ingest_equals_line_ingest():
    """process_block (native zero-copy scan) must produce byte-identical
    window deltas to the line path, including bad lines and a ragged
    tail."""
    import pytest

    from streambench_tpu import native
    if native.load() is None:
        pytest.skip("native library unavailable")
    lines, mapping, campaigns = make_lines(4000, seed=12)
    lines.insert(100, b"not json at all")
    lines.insert(2000, b'{"weird": 1}')

    a = run_engine(lines, mapping, campaigns, chunked=True)

    cfg = default_config(jax_batch_size=256, jax_scan_batches=4)
    b_eng = AdAnalyticsEngine(cfg, mapping, campaigns=campaigns)
    assert b_eng.supports_block_ingest
    data = b"\n".join(lines) + b"\n"
    # feed in uneven block slices ending on line boundaries
    cut = data.find(b"\n", len(data) // 3) + 1
    events = b_eng.process_block(data[:cut])
    events += b_eng.process_block(data[cut:])
    assert events == 4000  # the 2 bad lines are not events

    assert drained_pending(a) == drained_pending(b_eng)


def test_poll_block_roundtrip_and_offset(tmp_path):
    from streambench_tpu.io.journal import FileBroker

    broker = FileBroker(str(tmp_path))
    w = broker.writer("t")
    w.append(b"aaa")
    w.append(b"bb")
    w.flush()
    r = broker.reader("t")
    data = r.poll_block()
    assert data == b"aaa\nbb\n"
    assert r.offset == 7
    # mixing modes with pending read-ahead is refused
    w.append(b"c")
    w.append(b"d")
    w.flush()
    got = r.poll(max_records=1)
    assert got == [b"c"] and r._readahead
    import pytest
    with pytest.raises(RuntimeError):
        r.poll_block()


def test_parallel_encode_pool_matches_sequential():
    lines, mapping, campaigns = make_lines(3000, seed=13)
    cfg1 = default_config(jax_batch_size=256, jax_scan_batches=4)
    a = AdAnalyticsEngine(cfg1, mapping, campaigns=campaigns)
    a.process_chunk(lines)

    cfg2 = default_config(jax_batch_size=256, jax_scan_batches=4,
                          jax_encode_workers=3)
    b = AdAnalyticsEngine(cfg2, mapping, campaigns=campaigns)
    assert b._encode_pool is not None
    b.process_chunk(lines)

    assert a.events_processed == b.events_processed == 3000
    assert drained_pending(a) == drained_pending(b)


def test_parallel_block_carve_matches_single(tmp_path):
    """Block ingest + encode pool compose (VERDICT r3 weak #3): carving
    the block on N workers must fold the same events into the same
    counts as the single-threaded block scanner, with full batches
    (repacked worker tails) reaching the device."""
    lines, mapping, campaigns = make_lines(5000, seed=17)
    data = b"".join(l + b"\n" for l in lines)

    cfg1 = default_config(jax_batch_size=256, jax_scan_batches=4)
    a = AdAnalyticsEngine(cfg1, mapping, campaigns=campaigns)
    if not a.supports_block_ingest:
        import pytest
        pytest.skip("native encoder unavailable")
    a.process_block(data)

    cfg2 = default_config(jax_batch_size=256, jax_scan_batches=4,
                          jax_encode_workers=3)
    b = AdAnalyticsEngine(cfg2, mapping, campaigns=campaigns)
    assert b._encode_pool is not None and b.supports_block_ingest
    b.process_block(data)

    assert a.events_processed == b.events_processed == 5000
    assert drained_pending(a) == drained_pending(b)

    # unterminated trailing record: consumed offset must stop before it,
    # and the tail is parsed via the line fallback identically
    data2 = data + b'{"user_id": "trunc'
    c = AdAnalyticsEngine(cfg2, mapping, campaigns=campaigns)
    batches, start = c._encode_pool.carve_block_parallel(data2, 256)
    assert start == len(data)
    assert sum(bb.n for bb in batches) == 5000
    # worker tails were repacked: every batch but the last is full
    assert all(bb.n == 256 for bb in batches[:-1])


def test_repack_batches_preserves_order():
    from streambench_tpu.encode.encoder import repack_batches

    lines, mapping, campaigns = make_lines(700, seed=3)
    enc = EventEncoder(mapping, campaigns)
    # three ragged batches (n < B), order-significant event times
    batches = [enc.encode(lines[0:300], 512),
               enc.encode(lines[300:400], 512),
               enc.encode(lines[400:700], 512)]
    out = repack_batches(batches, 512)
    assert [b.n for b in out] == [512, 188]
    times = np.concatenate([b.event_time[:b.n] for b in out])
    ref = np.concatenate([b.event_time[:b.n] for b in batches])
    assert np.array_equal(times, ref)
    assert all(b.base_time_ms == batches[0].base_time_ms for b in out)


def test_compact_drain_matches_dense(monkeypatch):
    """Device-compacted drains (large key spaces) must be invisible to
    correctness, including the cap-overflow dense fallback."""
    lines, mapping, campaigns = make_lines(4000, seed=23)
    cfg = default_config(jax_batch_size=256, jax_scan_batches=4)

    dense = AdAnalyticsEngine(cfg, mapping, campaigns=campaigns)
    dense.process_chunk(lines)
    want = drained_pending(dense)

    # force the compact path (it gates itself to accelerator backends)
    monkeypatch.setattr(AdAnalyticsEngine, "_use_compact_drain",
                        lambda self: True)
    compact = AdAnalyticsEngine(cfg, mapping, campaigns=campaigns)
    compact.process_chunk(lines)
    assert drained_pending(compact) == want

    # cap smaller than the live cells: nnz > cap -> dense fallback
    monkeypatch.setattr(AdAnalyticsEngine, "COMPACT_DRAIN_CAP", 8)
    overflow = AdAnalyticsEngine(cfg, mapping, campaigns=campaigns)
    overflow.process_chunk(lines)
    assert drained_pending(overflow) == want


def test_dirty_rows_drain_matches_dense(monkeypatch):
    """Host-tracked dirty-row drains (the large-key-space path: the
    drain gathers only touched campaign rows) must be invisible to
    correctness — including the rows-cap overflow fallback and an
    empty-tracker drain."""
    lines, mapping, campaigns = make_lines(4000, seed=29)
    cfg = default_config(jax_batch_size=256, jax_scan_batches=4)

    dense = AdAnalyticsEngine(cfg, mapping, campaigns=campaigns)
    dense.process_chunk(lines)
    want = drained_pending(dense)

    # force tracking on (it gates itself to C*W >= 2^22)
    monkeypatch.setattr(AdAnalyticsEngine, "_track_dirty_rows",
                        lambda self: True)
    rows_eng = AdAnalyticsEngine(cfg, mapping, campaigns=campaigns)
    rows_eng.process_chunk(lines)
    # the tracker saw every batch
    assert rows_eng._dirty_rows
    assert drained_pending(rows_eng) == want
    # drained: tracker reset ("rows_host" parked on CPU,
    # "rows_compact" on accelerators)
    assert rows_eng._dirty_rows == []

    # an immediate second drain has nothing tracked: no parked entry
    before = len(rows_eng._undrained)
    rows_eng._drain_device()
    assert len(rows_eng._undrained) == before

    # cap smaller than the touched set: falls back to the full-space
    # strategies (dense on CPU) and still matches
    monkeypatch.setattr(AdAnalyticsEngine, "DIRTY_ROWS_CAP", 2)
    overflow = AdAnalyticsEngine(cfg, mapping, campaigns=campaigns)
    overflow.process_chunk(lines)
    assert drained_pending(overflow) == want


def test_dirty_rows_device_branch_matches_dense(monkeypatch):
    """The accelerator-side rows drain (``flush_deltas_rows_compact``
    on-device gather+compaction + the "rows_compact" materialize arm) —
    config #5's TPU path — must match the dense drain.  CPU CI
    otherwise only ever runs the "rows_host" branch, so the backend
    probe is patched to force the device branch (the ops themselves are
    backend-generic)."""
    import streambench_tpu.engine.pipeline as pipeline_mod

    lines, mapping, campaigns = make_lines(4000, seed=37)
    cfg = default_config(jax_batch_size=256, jax_scan_batches=4)

    dense = AdAnalyticsEngine(cfg, mapping, campaigns=campaigns)
    dense.process_chunk(lines)
    want = drained_pending(dense)

    monkeypatch.setattr(AdAnalyticsEngine, "_track_dirty_rows",
                        lambda self: True)
    monkeypatch.setattr(pipeline_mod.jax, "default_backend",
                        lambda: "tpu")
    eng = AdAnalyticsEngine(cfg, mapping, campaigns=campaigns)
    eng.process_chunk(lines)
    eng._drain_device()
    assert eng._undrained and eng._undrained[-1][0] == "rows_compact"
    monkeypatch.undo()  # materialize/compare on the real backend
    eng._materialize_drains()
    eng._fold_pending_arrays()
    assert dict(eng._pending) == want


def test_dirty_rows_seeded_after_restore(monkeypatch):
    """A restored snapshot may carry undrained counts the tracker never
    saw; restore must seed the tracker so the next drain finds them."""
    lines, mapping, campaigns = make_lines(2000, seed=31)
    cfg = default_config(jax_batch_size=256, jax_scan_batches=4)

    monkeypatch.setattr(AdAnalyticsEngine, "_track_dirty_rows",
                        lambda self: True)
    src = AdAnalyticsEngine(cfg, mapping, campaigns=campaigns)
    src.process_chunk(lines)
    # snapshot WITH undrained device counts: _snapshot_sync materializes
    # parked drains but the un-drained device state is captured raw
    snap = src.snapshot(offset=0)
    want = drained_pending(src)

    dst = AdAnalyticsEngine(cfg, mapping, campaigns=campaigns)
    dst.restore(snap)
    assert dst._dirty_rows  # seeded from the snapshot's live rows
    got = drained_pending(dst)
    # the restored engine's drain must surface the same counts (pending
    # from the snapshot plus the drained device cells)
    for k, v in want.items():
        assert got.get(k) == v, (k, got.get(k), v)
