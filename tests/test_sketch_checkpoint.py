"""Checkpoint/resume for the sketch engines (BASELINE configs #2-#4).

Each test kills a run partway (engine object discarded), restores a FRESH
engine from the newest snapshot, finishes the stream, and requires the
final output to equal an uninterrupted run of the same engine — bit-for-bit
where the aggregation is batch-invariant (HLL register maxes, CMS adds,
sliding counts).  That is a stronger property than the reference offers:
its only resume story is re-reading from the earliest Kafka offset
(``AdvertisingTopologyNative.java:92``, ``AdvertisingSpark.scala:64``).

The intern-table round-trip is the load-bearing part: HLL hashes and CMS
rows are keyed by *interned* user indices, so a resumed encoder must
re-assign identical indices (see ``_SketchEngineBase``).
"""

import random

import numpy as np
import pytest

from streambench_tpu.checkpoint import Checkpointer
from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.engine import StreamRunner
from streambench_tpu.engine.sketches import (
    HLLDistinctEngine,
    SessionCMSEngine,
    SlidingTDigestEngine,
)
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import as_redis, read_seen_counts


def setup_run(tmp_path, events=8000, batch=512):
    cfg = default_config(jax_batch_size=batch)
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=events,
                 rng=random.Random(77), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    return cfg, broker, mapping


def crash_and_resume(tmp_path, cfg, broker, mapping, make_engine,
                     crash_after=4000):
    """Run to ``crash_after`` events with checkpointing, discard the
    engine, restore a fresh one, finish.  Returns the resumed engine and
    its redis."""
    ckdir = str(tmp_path / "ckpt")
    r = as_redis(FakeRedisStore())

    eng1 = make_engine(cfg, mapping, r)
    run1 = StreamRunner(eng1, broker.reader(cfg.kafka_topic),
                        checkpointer=Checkpointer(ckdir),
                        checkpoint_interval_ms=0)
    run1.run_catchup(max_events=crash_after)
    # crash: eng1 is gone; its last checkpoint (written at run_catchup
    # exit) survives

    eng2 = make_engine(cfg, mapping, r)
    run2 = StreamRunner(eng2, broker.reader(cfg.kafka_topic),
                        checkpointer=Checkpointer(ckdir),
                        checkpoint_interval_ms=0)
    assert run2.resume(), "no snapshot found to resume from"
    assert eng2.events_processed == eng1.events_processed
    run2.run_catchup()
    eng2.close()
    return eng2, r


def uninterrupted(cfg, broker, mapping, make_engine):
    r = as_redis(FakeRedisStore())
    eng = make_engine(cfg, mapping, r)
    StreamRunner(eng, broker.reader(cfg.kafka_topic)).run_catchup()
    eng.close()
    return eng, r


def test_hll_kill_resume_equals_uninterrupted(tmp_path):
    cfg, broker, mapping = setup_run(tmp_path)
    mk = lambda c, m, r: HLLDistinctEngine(c, m, redis=r, registers=128)
    base_eng, base_r = uninterrupted(cfg, broker, mapping, mk)
    res_eng, res_r = crash_and_resume(tmp_path, cfg, broker, mapping, mk)
    assert res_eng.dropped == 0
    # register maxes are batch-invariant and intern-consistent: the
    # resumed run's estimates must EQUAL the uninterrupted run's
    assert read_seen_counts(res_r) == read_seen_counts(base_r)
    np.testing.assert_array_equal(
        np.asarray(res_eng.state.registers),
        np.asarray(base_eng.state.registers))


def test_sliding_tdigest_kill_resume_counts_exact(tmp_path):
    cfg, broker, mapping = setup_run(tmp_path, events=6000)
    mk = lambda c, m, r: SlidingTDigestEngine(c, m, redis=r, slide_ms=1000)
    base_eng, base_r = uninterrupted(cfg, broker, mapping, mk)
    res_eng, res_r = crash_and_resume(tmp_path, cfg, broker, mapping, mk,
                                      crash_after=3000)
    assert res_eng.dropped == 0
    # sliding counts are exact deltas -> must match bit-for-bit
    assert read_seen_counts(res_r) == read_seen_counts(base_r)
    # the digest survived the round-trip: total weight equals views seen
    # (digest content is wall-clock latency, so only weights compare)
    assert (np.asarray(res_eng.digest.weights).sum()
            == np.asarray(base_eng.digest.weights).sum())
    q = res_eng.quantiles()
    assert (q[:, 0] <= q[:, 1] + 1e-3).all()


def test_sliced_sliding_kill_resume_and_plane_roundtrip(tmp_path):
    """ISSUE 12: the sliced engine's [C, S, W] bucket plane survives
    kill/resume (counts exact vs an uninterrupted sliced run AND vs the
    legacy fold), and a snapshot round-trip restores the plane bit for
    bit."""
    cfg, broker, mapping = setup_run(tmp_path, events=6000)
    mk = lambda c, m, r: SlidingTDigestEngine(c, m, redis=r,
                                              slide_ms=1000, sliced="on")
    base_eng, base_r = uninterrupted(cfg, broker, mapping, mk)
    res_eng, res_r = crash_and_resume(tmp_path, cfg, broker, mapping, mk,
                                      crash_after=3000)
    assert res_eng.sliced and res_eng.dropped == 0
    assert read_seen_counts(res_r) == read_seen_counts(base_r)
    # ...and equals the LEGACY fold's rows on the same journal
    leg_eng, leg_r = uninterrupted(
        cfg, broker, mapping,
        lambda c, m, r: SlidingTDigestEngine(c, m, redis=r,
                                             slide_ms=1000, sliced="off"))
    assert read_seen_counts(leg_r) == read_seen_counts(base_r)
    assert leg_eng.dropped == res_eng.dropped

    # direct snapshot round-trip: the 3-D plane (flattened into the 2-D
    # Snapshot.counts slot) restores bit-identically
    snap = base_eng.snapshot(offset=123)
    fresh = mk(cfg, mapping, as_redis(FakeRedisStore()))
    fresh.restore(snap)
    np.testing.assert_array_equal(np.asarray(fresh.state.counts),
                                  np.asarray(base_eng.state.counts))
    np.testing.assert_array_equal(np.asarray(fresh.state.window_ids),
                                  np.asarray(base_eng.state.window_ids))

    # a sliced snapshot must not restore into a legacy engine (the
    # counts slot carries a different plane) — and vice versa
    with pytest.raises(ValueError, match="sliced"):
        leg_eng.restore(snap)
    with pytest.raises(ValueError, match="sliced"):
        base_eng.restore(leg_eng.snapshot(offset=1))


def test_session_cms_kill_resume_equals_uninterrupted(tmp_path):
    cfg, broker, mapping = setup_run(tmp_path)
    mk = lambda c, m, r: SessionCMSEngine(c, m, redis=r, top_k=8)
    base_eng, base_r = uninterrupted(cfg, broker, mapping, mk)
    res_eng, res_r = crash_and_resume(tmp_path, cfg, broker, mapping, mk)
    assert res_eng.dropped == 0
    assert res_eng.session_clicks == base_eng.session_clicks
    assert res_eng.sessions_closed == base_eng.sessions_closed
    np.testing.assert_array_equal(
        np.asarray(res_eng.cms.table), np.asarray(base_eng.cms.table))
    assert dict(res_eng.heavy_hitters()) == dict(base_eng.heavy_hitters())


def test_cross_family_restore_refused(tmp_path):
    cfg, broker, mapping = setup_run(tmp_path, events=2000)
    r = as_redis(FakeRedisStore())
    hll_eng = HLLDistinctEngine(cfg, mapping, redis=r)
    StreamRunner(hll_eng, broker.reader(cfg.kafka_topic)).run_catchup()
    snap = hll_eng.snapshot(offset=0)

    sess = SessionCMSEngine(cfg, mapping)
    with pytest.raises(ValueError, match="engine family"):
        sess.restore(snap)

    from streambench_tpu.engine import AdAnalyticsEngine
    exact = AdAnalyticsEngine(cfg, mapping)
    with pytest.raises(ValueError, match="engine family"):
        exact.restore(snap)


def test_hll_geometry_mismatch_refused(tmp_path):
    cfg, broker, mapping = setup_run(tmp_path, events=2000)
    eng = HLLDistinctEngine(cfg, mapping, registers=128)
    snap = eng.snapshot(offset=0)
    other = HLLDistinctEngine(cfg, mapping, registers=256)
    with pytest.raises(ValueError, match="num_registers"):
        other.restore(snap)


def test_sketch_snapshot_roundtrips_through_disk(tmp_path):
    """extra arrays (registers, digests, intern tables incl. bytes
    dtypes) must survive the npz encode/decode unchanged."""
    cfg, broker, mapping = setup_run(tmp_path, events=2000)
    eng = HLLDistinctEngine(cfg, mapping, registers=128)
    StreamRunner(eng, broker.reader(cfg.kafka_topic)).run_catchup()
    ck = Checkpointer(str(tmp_path / "ck"))
    ck.save(eng.snapshot(offset=123))
    snap = ck.load()
    assert snap is not None and snap.offset == 123
    np.testing.assert_array_equal(snap.extra["hll_registers"],
                                  np.asarray(eng.state.registers))
    users, _ = eng.encoder.dump_intern_tables()
    from streambench_tpu.engine.sketches import _SketchEngineBase
    assert _SketchEngineBase._unpack_keys(
        snap.extra["user_blob"], snap.extra["user_offs"]) == users


def test_intern_pack_preserves_nul_and_duplicate_prefixes():
    """Keys with trailing NULs must round-trip exactly; an "S"-dtype
    array would strip them and collapse b'a' with b'a\\x00'."""
    from streambench_tpu.engine.sketches import _SketchEngineBase as S

    keys = [b"a", b"a\x00", b"", b"x\x00y", b"\x00"]
    blob, offs = S._pack_keys(keys)
    assert S._unpack_keys(blob, offs) == keys
    empty_blob, empty_offs = S._pack_keys([])
    assert S._unpack_keys(empty_blob, empty_offs) == []
