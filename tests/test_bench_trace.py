"""The bench's xplane trace parser against a synthetic device trace.

The TPU occupancy plumbing (``bench._trace_occupancy``) has only ever
run against real hardware traces, which this environment cannot
produce — so a fabricated ``.xplane.pb`` exercises the parse path
(VERDICT r4 #8: "TPU path exercised in a unit test via a fake xplane
dir") and pins the busiest-line-per-plane reading.
"""

import pytest

import bench


def _write_xplane(path, planes):
    """planes: {plane_name: [line_event_durations_ps, ...]} where each
    entry is a list of per-line lists of event durations."""
    xplane_pb2 = pytest.importorskip(
        "tensorflow.tsl.profiler.protobuf.xplane_pb2",
        reason="no xplane proto in this image")
    space = xplane_pb2.XSpace()
    for name, lines in planes.items():
        plane = space.planes.add()
        plane.name = name
        for durations in lines:
            line = plane.lines.add()
            for d in durations:
                ev = line.events.add()
                ev.duration_ps = d
    path.write_bytes(space.SerializeToString())


def test_trace_occupancy_reads_busiest_device_line(tmp_path):
    sub = tmp_path / "plugins" / "profile" / "run1"
    sub.mkdir(parents=True)
    _write_xplane(sub / "host.xplane.pb", {
        # device plane: two lines; the busiest (3e9 ps = 3 ms) wins
        "/device:TPU:0": [[1_000_000_000, 2_000_000_000],
                          [500_000_000]],
        # host plane: ignored (not a device plane)
        "/host:CPU": [[9_000_000_000_000]],
    })
    out = bench._trace_occupancy(str(tmp_path))
    assert out is not None
    busy = out["device_busy_ms"]
    assert list(busy) == ["/device:TPU:0"]
    assert busy["/device:TPU:0"] == pytest.approx(3.0)


def test_trace_occupancy_empty_dir_returns_none(tmp_path):
    assert bench._trace_occupancy(str(tmp_path)) is None
