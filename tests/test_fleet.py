"""Fleet observability (obs layer 6, ISSUE 15): clock-offset estimator
units (symmetric exact, asymmetric bounded, jitter refusal), the
pub/sub ping verb, the freshness-ledger hop partition on a real
writer->replica run (hops sum to the reply's staleness_ms), cache hits
carrying the PLANE's reply-time freshness, metrics federation + the
``obs fleet`` CLI, merged-trace validation with named process lanes,
and off-flag reply bit-identity."""

import json
import os
import time

import jax.numpy as jnp
import numpy as np
import pytest

from streambench_tpu.config import default_config
from streambench_tpu.dimensions.store import DurableDimensionStore
from streambench_tpu.obs import clock as obs_clock
from streambench_tpu.obs.fleet import (
    FleetCollector,
    merge_traces,
    parse_role_spec,
    render_fleet,
    summarize_fleet,
    trace_process_names,
)
from streambench_tpu.obs.spans import validate_chrome_trace
from streambench_tpu.ops import minhash
from streambench_tpu.reach.replica import ReachReplica, SnapshotShipper
from streambench_tpu.reach.serve import (
    FRESHNESS_HOPS,
    ReachQueryServer,
    freshness_hops,
)
from streambench_tpu.utils.ids import now_ms

NAMES = ["c0", "c1", "c2"]


def fold_state(users, C=3, k=16, R=16):
    st = minhash.init_state(C, k, R)
    join = jnp.asarray(np.arange(C, dtype=np.int32))
    B = len(users)
    return minhash.step(
        st, join,
        jnp.asarray(np.zeros(B, np.int32)),
        jnp.asarray(np.asarray(users, np.int32)),
        jnp.zeros(B, jnp.int32), jnp.zeros(B, jnp.int32),
        jnp.ones(B, bool))


def ask(host, port, campaigns, qid, op="union"):
    from streambench_tpu.dimensions.pubsub import PubSubClient

    c = PubSubClient(host, port, timeout_s=20)
    c.request({"type": "reach", "campaigns": campaigns, "op": op,
               "id": qid})
    out = c.recv()["data"]
    c.close()
    return out


# ----------------------------------------------------- clock estimator
def _samples(true_offset, delays):
    """Synthetic ping samples: (d1, d2) network delays per exchange,
    server clock ahead of local by ``true_offset`` ms."""
    out = []
    t0 = 1_000_000.0
    for d1, d2 in delays:
        ts = t0 + d1 + true_offset
        out.append((t0, ts, t0 + d1 + d2))
        t0 += 100.0
    return out


def test_clock_symmetric_rtt_exact():
    # symmetric delay: the midpoint method recovers the offset EXACTLY
    est = obs_clock.offset_from_samples(
        _samples(1234.5, [(5, 5), (8, 8), (3, 3)]))
    assert est["applied"]
    assert est["offset_ms"] == pytest.approx(1234.5, abs=1e-6)
    assert est["rtt_min_ms"] == pytest.approx(6.0)
    # uncertainty = min-rtt/2 + quantization floor
    assert est["uncertainty_ms"] == pytest.approx(3.5)


def test_clock_asymmetric_bounded():
    # asymmetric delay errs by at most rtt/2, and the reported
    # uncertainty covers it
    est = obs_clock.offset_from_samples(_samples(-500.0, [(9, 1)]))
    assert abs(est["offset_ms"] - (-500.0)) <= est["uncertainty_ms"]
    assert abs(est["offset_ms"] - (-500.0)) <= 5.0 + 1e-6


def test_clock_jitter_threshold_refusal():
    # offsets spread past the threshold: reported, NEVER applied
    est = obs_clock.offset_from_samples(
        _samples(0.0, [(1, 1), (200, 1), (1, 200)]),
        jitter_threshold_ms=50.0)
    assert not est["applied"]
    assert est["jitter_ms"] > 50.0
    # a huge min-rtt alone also refuses
    est = obs_clock.offset_from_samples(
        _samples(0.0, [(120, 120)]), jitter_threshold_ms=50.0)
    assert not est["applied"]
    # and applied=False means to_local_ms keeps raw stamps
    assert obs_clock.to_local_ms(777.0, est) == 777.0
    applied = obs_clock.offset_from_samples(_samples(100.0, [(2, 2)]))
    assert obs_clock.to_local_ms(777.0, applied) == pytest.approx(677.0)


def test_clock_no_samples():
    est = obs_clock.offset_from_samples([])
    assert not est["applied"] and est["samples"] == 0


def test_ping_verb_and_live_sync():
    from streambench_tpu.dimensions.pubsub import PubSubClient, PubSubServer

    ps = PubSubServer(port=0).start()
    try:
        host, port = ps.address
        c = PubSubClient(host, port, timeout_s=10)
        c.request({"type": "ping", "id": 7})
        d = c.recv()["data"]
        c.close()
        assert d["id"] == 7
        assert abs(d["t"] - now_ms()) < 5_000
        # live estimate against the same process: offset ~0.  A very
        # generous jitter threshold keeps this deterministic on a
        # loaded 1-core host — the refusal gate has its own unit tests
        est = obs_clock.sync_pubsub(host, port, n=8,
                                    jitter_threshold_ms=2_000)
        assert est["applied"], est
        assert abs(est["offset_ms"]) <= est["uncertainty_ms"] + 50.0
    finally:
        ps.close()


# ------------------------------------------------- freshness partition
def test_freshness_hops_partition_and_clamp():
    base = float(now_ms())
    fresh = {"folded_ms": base - 400, "submit_ms": base - 300,
             "shipped_ms": base - 290, "loaded_ms": base - 50}
    hops = freshness_hops(fresh, reply_ms=base)
    assert hops["fold_lag"] == pytest.approx(100.0)
    assert hops["ship_wait"] == pytest.approx(10.0)
    assert hops["tail_lag"] == pytest.approx(240.0)
    assert hops["serve"] == pytest.approx(50.0)
    assert sum(hops[h] for h in FRESHNESS_HOPS) == pytest.approx(
        hops["total"])
    # a backwards stamp (uncorrected skew) clamps monotone: hops stay
    # >= 0 and the partition contract survives
    fresh = {"folded_ms": base - 100, "submit_ms": base - 150,
             "shipped_ms": base - 160, "loaded_ms": base - 10}
    hops = freshness_hops(fresh, reply_ms=base)
    assert all(hops[h] >= 0 for h in FRESHNESS_HOPS)
    assert sum(hops[h] for h in FRESHNESS_HOPS) == pytest.approx(
        hops["total"])


def test_writer_to_replica_freshness_partition(tmp_path):
    """The acceptance shape, in-process: a writer ships stamped
    records (origin = a live pub/sub endpoint for the clock ping), a
    fleet-mode replica loads them, and EVERY served reply — misses and
    cache hits — carries a freshness decomposition whose hops sum to
    its staleness_ms within rounding tolerance."""
    from streambench_tpu.dimensions.pubsub import PubSubServer

    origin_ps = PubSubServer(port=0).start()
    o_host, o_port = origin_ps.address
    store = DurableDimensionStore(str(tmp_path))
    ship = SnapshotShipper(store, NAMES, interval_ms=1,
                           origin={"addr": f"{o_host}:{o_port}",
                                   "pid": os.getpid(),
                                   "role": "writer"})
    st = fold_state([10, 20, 30])
    folded_at = now_ms()
    ship.note_state(st.mins, st.registers, 2, 70_000,
                    folded_ms=folded_at)
    rep = ReachReplica(store.path, poll_ms=20_000, fleet=True)
    rep.pubsub.start()
    try:
        assert rep.poll_once()
        # the clock synced against the live origin (same process, so a
        # passing estimate reads ~0 offset); on a loaded 1-core host
        # the jitter gate may legitimately REFUSE — either way the
        # estimate ran, is recorded, and every reply echoes its verdict
        assert rep.clock is not None, "clock sync never ran"
        assert "error" not in rep.clock, rep.clock
        applied = rep.clock["applied"]
        if applied:
            assert abs(rep.clock["offset_ms"]) <= 50.0
        host, port = rep.address
        replies = [ask(host, port, ["c0", "c1"], i) for i in range(4)]
        for i, d in enumerate(replies):
            assert "estimate" in d, d
            fr = d["freshness"]
            hop_sum = sum(fr[f"{h}_ms"] for h in FRESHNESS_HOPS)
            # per-hop rounding to 0.1 ms: the sum check carries 0.25 ms
            assert hop_sum == pytest.approx(fr["staleness_ms"],
                                            abs=0.25), fr
            assert d["staleness_ms"] == fr["staleness_ms"]
            assert fr["clock"]["applied"] is applied
            if i > 0:
                # repeats hit the (epoch, campaign-set) cache — and
                # must carry the PLANE's freshness recomputed at reply
                # time, not the fill-time hops (cache.CACHEABLE_KEYS)
                assert d.get("cached") is True
                assert fr["staleness_ms"] >= \
                    replies[0]["freshness"]["staleness_ms"]
        # the decomposition is fold-anchored: a reply asked AFTER
        # t_before carries at least t_before - folded_at of age (the
        # anchor may shift by the applied clock correction, and hop
        # rounding trims up to 0.25 ms)
        t_before = now_ms()
        d_last = ask(host, port, ["c0", "c2"], "anchor")
        off = abs(rep.clock["offset_ms"]) if applied else 0.0
        assert d_last["freshness"]["staleness_ms"] >= \
            (t_before - folded_at) - off - 1
        # the summary side: per-hop histograms counted one sample per
        # served reply, so the p99 table explains exactly these replies
        served = len(replies) + 1     # + the anchor probe above
        fr_sum = rep.server.summary()["freshness"]
        assert fr_sum["hops"]["total"]["count"] == served
        for hop in FRESHNESS_HOPS:
            assert fr_sum["hops"][hop]["count"] == served
    finally:
        rep.close()
        store.close()
        origin_ps.close()


def test_off_flag_replies_bit_identical(tmp_path):
    """Writer stamps ride every shipped record, but a fleet-OFF
    replica's replies are byte-identical to the PR 14 shape: no
    freshness block, staleness anchored at the SHIP stamp (not the
    fold stamp the fleet anchor uses)."""
    store = DurableDimensionStore(str(tmp_path))
    ship = SnapshotShipper(store, NAMES, interval_ms=1,
                           origin={"addr": "127.0.0.1:1", "pid": 1})
    st = fold_state([1, 2, 3])
    # a fold stamp 60 s in the past: the fleet anchor would read ~60 s
    # of staleness; the off-flag ship anchor reads ~0
    ship.note_state(st.mins, st.registers, 0, 1,
                    folded_ms=now_ms() - 60_000)
    rep = ReachReplica(store.path, poll_ms=20_000)   # fleet OFF
    rep.pubsub.start()
    try:
        assert rep.poll_once()
        d = ask(*rep.address, ["c0"], 1)
        assert "estimate" in d
        assert set(d) == {"op", "estimate", "union", "jaccard", "bound",
                          "epoch", "plane_epoch", "id", "staleness_ms"}
        assert d["staleness_ms"] < 30_000      # ship-anchored, not fold
        assert rep.clock is None               # no ping ever attempted
    finally:
        rep.close()
        store.close()


def test_freshness_high_water_flightrec():
    """Satellite: the replica-side flight recorder gets rate-limited
    fleet_freshness_high_water records (doubling high-water, hop
    decomposition attached) so a staleness-shed storm's crash dump
    explains itself."""
    from streambench_tpu.obs import FlightRecorder, MetricsRegistry

    fr = FlightRecorder(".")
    reg = MetricsRegistry()
    srv = ReachQueryServer(NAMES, registry=reg, flightrec=fr,
                           max_staleness_ms=60_000)
    st = fold_state([5, 6])
    base = now_ms()
    srv.update_state(st.mins, st.registers, 0, shipped_ms=base,
                     freshness={"folded_ms": base - 20_000,
                                "submit_ms": base - 19_000,
                                "shipped_ms": base - 18_000,
                                "loaded_ms": base - 100})
    got = []
    srv.submit(["c0"], "union", lambda d: got.append(d))
    deadline = time.monotonic() + 10
    while not got and time.monotonic() < deadline:
        time.sleep(0.01)
    srv.close()
    assert got and "freshness" in got[0]
    recs = [r for r in fr.snapshot()
            if r["kind"] == "fleet_freshness_high_water"]
    assert recs, fr.snapshot()
    assert recs[-1]["staleness_ms"] >= 20_000 - 100
    assert all(f"{h}_ms" in recs[-1] for h in FRESHNESS_HOPS)
    assert srv.freshness_high_water >= 20_000 - 100


def test_writer_attached_fleet_stamps(tmp_path):
    """jax.obs.fleet on the writer: its attached server's replies gain
    the degenerate decomposition (live planes: fold_lag + serve only),
    still summing to the reply's staleness."""
    from streambench_tpu.engine.sketches import ReachSketchEngine

    mapping = {f"ad{i}": NAMES[i % 3] for i in range(9)}
    cfg = default_config(jax_num_campaigns=3)
    eng = ReachSketchEngine(cfg, mapping, campaigns=NAMES, redis=None,
                            k=16, registers=16)
    object.__setattr__(cfg, "jax_obs_fleet", True)
    lines = b"".join(
        json.dumps({"user_id": f"u{i}", "page_id": "p", "ad_id": "ad0",
                    "ad_type": "banner", "event_type": "view",
                    "event_time": str(1_700_000_000_000 + i)}).encode()
        + b"\n" for i in range(50))
    eng.process_block(lines)
    srv = ReachQueryServer(NAMES)
    eng.attach_reach(srv)
    got = []
    srv.submit(["c0"], "union", lambda d: got.append(d))
    deadline = time.monotonic() + 10
    while not got and time.monotonic() < deadline:
        time.sleep(0.01)
    srv.close()
    d = got[0]
    fr = d["freshness"]
    assert fr["tail_lag_ms"] == 0.0 and fr["ship_wait_ms"] == 0.0
    assert sum(fr[f"{h}_ms"] for h in FRESHNESS_HOPS) == pytest.approx(
        fr["staleness_ms"], abs=0.25)


def test_restore_and_reattach_force_ship(tmp_path):
    """Satellite fix: the restart path ships IMMEDIATELY.  A
    supervisor-restarted writer re-attaches its shipper (possibly with
    an unchanged epoch — the crashed-before-first-checkpoint shape)
    and restores mid-cadence; both paths must put the live planes in
    the log now, not one cadence tick later."""
    from streambench_tpu.engine.sketches import ReachSketchEngine

    mapping = {f"ad{i}": NAMES[i % 3] for i in range(9)}
    cfg = default_config(jax_num_campaigns=3)
    store = DurableDimensionStore(str(tmp_path))
    ship = SnapshotShipper(store, NAMES, interval_ms=10**9)

    def make_engine():
        return ReachSketchEngine(cfg, mapping, campaigns=NAMES,
                                 redis=None, k=16, registers=16)

    a = make_engine()
    a.attach_shipper(ship)
    assert ship.ships == 1              # attach force-ships
    a.flush()
    assert ship.ships == 1              # cadence holds mid-lineage
    snap = a.snapshot(0)

    # restart WITHOUT a checkpoint: same epoch (0), cadence not due —
    # exactly the shape that used to leave replicas on the pre-crash
    # record until the next tick
    b = make_engine()
    b.attach_shipper(ship)
    assert ship.ships == 2, "re-attach after restart must force a ship"

    # restart WITH a checkpoint: restore bumps the epoch and must ship
    # the restored planes immediately, cadence notwithstanding
    c = make_engine()
    c.attach_shipper(ship)
    assert ship.ships == 3
    c.restore(snap)
    assert ship.ships == 4, "restore must force a ship"
    assert store.reach_sketches()["epoch"] == c.reach_epoch
    store.close()


# -------------------------------------------------- metrics federation
def _write_journal(path, role, pid, records, ts_base=1_000):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        for i, rec in enumerate(records):
            out = {"kind": "snapshot", "seq": i,
                   "ts_ms": ts_base + i * 100, "uptime_ms": i * 100,
                   "pid": pid}
            if role:
                out["role"] = role
            out.update(rec)
            f.write(json.dumps(out) + "\n")


def test_fleet_collector_merges_roles(tmp_path):
    wpath = str(tmp_path / "writer" / "metrics.jsonl")
    rpath = str(tmp_path / "replica" / "metrics.jsonl")
    _write_journal(wpath, "writer", 100, [
        {"events": 1000, "events_per_s": 500.0},
        {"events": 2000, "events_per_s": 600.0},
        {"kind": "event", "event": "restart", "restarts": 1},
    ])
    _write_journal(rpath, "replica", 200, [
        {"reach_query": {
            "served": 40, "shed": 2, "qps": 80.0, "plane_epoch": 3,
            "staleness_ms": 450.0,
            "cache": {"hit_ratio": 0.75},
            "freshness": {"hops": {
                "fold_lag": {"count": 40, "p99": 120.0},
                "ship_wait": {"count": 40, "p99": 2.0},
                "tail_lag": {"count": 40, "p99": 180.0},
                "serve": {"count": 40, "p99": 300.0},
                "total": {"count": 40, "p99": 600.0}},
                "high_water_ms": 650.0}},
         "clock": {"offset_ms": 1.2, "uncertainty_ms": 3.0,
                   "applied": True}},
    ])
    # rotation stitch: a rotated writer journal half is covered too
    # (the current file's records continue the rotated half's clock)
    os.replace(wpath, wpath + ".1")
    _write_journal(wpath, "writer", 100, [
        {"events": 3000, "events_per_s": 700.0}], ts_base=2_000)

    out_path = str(tmp_path / "fleet.jsonl")
    coll = FleetCollector([(None, wpath), (None, rpath)],
                          out_path=out_path)
    records = coll.collect()
    assert os.path.exists(out_path)
    assert all("role" in r for r in records)
    roles = {r["role"] for r in records}
    assert roles == {"writer", "replica"}
    # rotation stitched: ALL writer snapshots present
    assert sum(r.get("kind") == "snapshot" and r["role"] == "writer"
               for r in records) == 3
    # ts-ordered merge
    ts = [r["ts_ms"] for r in records]
    assert ts == sorted(ts)

    s = summarize_fleet(records, path=out_path)
    assert s["processes"] == 2
    by_role = {a["role"]: a for a in s["roles"]}
    w, r = by_role["writer"], by_role["replica"]
    assert w["events"] == 3000 and w["restarts"] == 1
    assert w["events_per_s_mean"] == pytest.approx(600.0)
    assert r["qps"] == 80.0 and r["cache_hit_ratio"] == 0.75
    assert r["staleness_ms"] == 450.0
    assert r["freshness_p99_ms"]["total"] == 600.0
    assert r["clock"]["applied"] is True
    text = render_fleet(s)
    assert "writer" in text and "replica" in text
    assert "fold_lag 120.0" in text and "total 600.0" in text

    # the merged fleet.jsonl round-trips through the same summarizer
    from streambench_tpu.obs.report import load_records

    again = summarize_fleet(load_records(out_path), path=out_path)
    assert again["processes"] == 2


def test_fleet_cli(tmp_path, capsys):
    from streambench_tpu.obs.__main__ import main

    wpath = str(tmp_path / "writer" / "metrics.jsonl")
    rpath = str(tmp_path / "rep" / "metrics.jsonl")
    _write_journal(wpath, "writer", 1, [{"events": 10,
                                         "events_per_s": 5.0}])
    _write_journal(rpath, None, 2, [{"reach_query": {"qps": 9.0,
                                                     "served": 3}}])
    out = str(tmp_path / "fleet.jsonl")
    rc = main(["fleet", f"writer={wpath}", rpath, "--out", out,
               "--json"])
    assert rc == 0
    s = json.loads(capsys.readouterr().out)
    assert s["processes"] == 2
    # the bare path's role was inferred from its directory name
    assert {a["role"] for a in s["roles"]} == {"writer", "rep"}
    assert os.path.exists(out)
    # directory discovery: one arg, scan <dir>/*/metrics.jsonl
    rc = main(["fleet", str(tmp_path), "--json"])
    assert rc == 0
    s = json.loads(capsys.readouterr().out)
    assert s["processes"] == 2


# ----------------------------------------------------- trace stitching
def _trace_doc(pid, wall0_ms, names):
    events = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": 1,
               "args": {"name": "main"}}]
    for i, name in enumerate(names):
        events.append({"name": name, "cat": "stage", "ph": "X",
                       "ts": 1000.0 * i, "dur": 500.0,
                       "pid": pid, "tid": 1})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"run": "t", "wall0_ms": wall0_ms,
                          "spans": len(names), "spans_dropped": 0}}


def test_merge_traces_aligns_clocks_and_names_lanes(tmp_path):
    a = str(tmp_path / "trace_100.json")
    b = str(tmp_path / "trace_200.json")
    json.dump(_trace_doc(100, 50_000, ["device_scan", "drain"]),
              open(a, "w"))
    json.dump(_trace_doc(200, 50_250, ["query_dispatch"]),
              open(b, "w"))
    doc = merge_traces([("writer", a), ("replica", b)])
    assert validate_chrome_trace(doc) == []
    lanes = trace_process_names(doc)
    assert lanes == {100: "writer", 200: "replica"}
    # the later process's events shifted by the wall-epoch delta so
    # both sit on one timeline
    xs = {(e["pid"], e["name"]): e["ts"]
          for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert xs[(100, "device_scan")] == 0.0
    assert xs[(200, "query_dispatch")] == pytest.approx(250_000.0)


def test_trace_merge_cli(tmp_path, capsys):
    from streambench_tpu.obs.__main__ import main

    a = str(tmp_path / "trace_1.json")
    b = str(tmp_path / "trace_2.json")
    json.dump(_trace_doc(11, 1_000, ["encode"]), open(a, "w"))
    json.dump(_trace_doc(22, 2_000, ["query_reply"]), open(b, "w"))
    out = str(tmp_path / "merged.json")
    rc = main(["trace", f"writer={a}", f"replica={b}", "--merge",
               "--out", out, "--json"])
    assert rc == 0
    s = json.loads(capsys.readouterr().out)
    assert s["processes"] == {"11": "writer", "22": "replica"}
    merged = json.load(open(out))
    assert validate_chrome_trace(merged) == []
    assert len(trace_process_names(merged)) == 2
    # multiple paths WITHOUT --merge is a usage error, not a guess
    assert main(["trace", a, b]) == 2


def test_parse_role_spec(tmp_path):
    p = tmp_path / "x=weird.json"
    p.write_text("{}")
    # an existing path containing '=' stays a path
    assert parse_role_spec(str(p)) == (None, str(p))
    assert parse_role_spec("writer=/tmp/m.jsonl") == (
        "writer", "/tmp/m.jsonl")


def test_sampler_role_and_pid_stamps(tmp_path):
    from streambench_tpu.obs import MetricsSampler

    path = str(tmp_path / "metrics.jsonl")
    s = MetricsSampler(path, interval_ms=10_000, role="replica")
    s.annotate("restart", restarts=1)
    s.close(final={"ok": True})
    recs = [json.loads(line) for line in open(path)]
    assert all(r["pid"] == os.getpid() for r in recs)
    assert all(r["role"] == "replica" for r in recs)
    assert recs[0]["kind"] == "event" and recs[-1]["kind"] == "final"
