"""Collective-cost accountant (parallel.collectives): parser units on
synthetic HLO, and hand-computed expectations against the REAL compiled
sharded scans — the numbers MULTICHIP_r06 cites must be derivable by
hand from (mesh, batch, K).

Hand model for the exact-count scan on a (data=2, campaign=2) mesh with
global batch B and K stacked batches (all columns int32, scalars 4 B):

- per-batch arm: every folded batch gathers its columns inside the scan
  body (4 unpacked / 2 packed all-reduces of [B] = 4*B bytes each) plus
  one scalar drop-counter psum -> K * (cols * 4B + 4) bytes,
  K * (cols + 1) ops per dispatch.
- hoisted arm: the stacked [K, B] columns gather ONCE per dispatch
  (cols all-reduces of 4*K*B bytes) plus ONE scalar psum ->
  cols * 4*K*B + 4 bytes, cols + 1 ops per dispatch.
"""

import numpy as np
import pytest

from streambench_tpu.parallel import collectives

# ----------------------------------------------------------------------
# parser units: synthetic HLO, no jax involved
# ----------------------------------------------------------------------

FAKE_HLO = """\
HloModule jit_body, entry_computation_layout={()->()}

%region_1.28 (Arg_0.29: s32[], Arg_1.30: s32[]) -> s32[] {
  %Arg_0.29 = s32[] parameter(0)
  ROOT %add.31 = s32[] add(s32[] %Arg_0.29, s32[] %Arg_0.29)
}

%scan_body (param.1: (s32[], s32[32])) -> (s32[], s32[32]) {
  %all-reduce.1 = s32[32]{0} all-reduce(s32[32]{0} %p), channel_id=1, replica_groups={{0,2},{1,3}}, use_global_device_ids=true, to_apply=%region_1.28
  %all-reduce.2 = s32[] all-reduce(s32[] %q), channel_id=2, replica_groups={{0,1},{2,3}}, to_apply=%region_1.28
  %fusion.1 = s32[32]{0} fusion(s32[32]{0} %all-reduce.1), kind=kLoop, calls=%fused
}

%inner_body (param.2: (s32[], s32[8])) -> (s32[], s32[8]) {
  %add.9 = s32[] add(s32[] %a, s32[] %b)
}

ENTRY %main.1_spmd (param.3: s32[3,16]) -> (s32[8,8], s32[]) {
  %all-gather.7 = s32[3,32]{1,0} all-gather(s32[3,16]{1,0} %param.3), channel_id=3, replica_groups={{0,2},{1,3}}, dimensions={1}
  %while.5 = (s32[], s32[32]{0}) while((s32[], s32[32]{0}) %tuple.1), condition=%cond, body=%scan_body
  %while.6 = (s32[], s32[8]{0}) while((s32[], s32[8]{0}) %tuple.2), condition=%cond2, body=%inner_body
}
"""


def test_shape_bytes_units():
    assert collectives.shape_bytes("s32[32]{0}") == 128
    assert collectives.shape_bytes("s32[3,16]{1,0}") == 192
    assert collectives.shape_bytes("s32[]") == 4
    assert collectives.shape_bytes("pred[64]{0}") == 64
    assert collectives.shape_bytes("(s32[8]{0}, f32[8]{0})") == 64
    assert collectives.shape_bytes("bf16[2,2]") == 8


def test_synthetic_hlo_classification():
    ops = collectives.collective_ops(FAKE_HLO)
    by_name = {o.name: o for o in ops}
    assert set(by_name) == {"all-reduce.1", "all-reduce.2", "all-gather.7"}
    # defining lines only: the fusion USE of %all-reduce.1 is not an op
    ar1 = by_name["all-reduce.1"]
    assert ar1.kind == "all-reduce" and ar1.in_loop
    assert ar1.payload_bytes == 128 and ar1.group_size == 2
    assert by_name["all-reduce.2"].payload_bytes == 4
    ag = by_name["all-gather.7"]
    assert ag.kind == "all-gather" and not ag.in_loop
    assert ag.payload_bytes == 4 * 3 * 32

    s = collectives.summarize(FAKE_HLO, scan_len=3)
    assert s["top_level"]["ops"] == 1
    assert s["per_loop_iteration"]["ops"] == 2
    assert s["per_dispatch"]["ops"] == 1 + 3 * 2
    assert s["per_dispatch"]["bytes"] == 384 + 3 * (128 + 4)
    # the scalar psum is excluded from column accounting
    assert s["per_dispatch"]["column_bytes"] == 384 + 3 * 128
    assert s["per_dispatch"]["column_ops"] == 1 + 3
    assert s["per_dispatch"]["by_kind"] == {"all-gather": 1,
                                            "all-reduce": 6}


def test_publish_gauges_mirrors_report():
    from streambench_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()
    report = {"step": {"per_dispatch": {"ops": 3, "bytes": 100}},
              "scan": {"per_dispatch": {"ops": 5, "bytes": 900}},
              "packed": True}
    collectives.publish_gauges(reg, report)
    vals = {(m.name, m.labels.get("kernel")): m.value
            for m in reg._metrics.values()}
    assert vals[("streambench_collective_ops", "scan")] == 5
    assert vals[("streambench_collective_bytes", "step")] == 100


# ----------------------------------------------------------------------
# hand-computed expectations against the real compiled scans
# ----------------------------------------------------------------------

def test_scan_arms_match_hand_computed_costs():
    import jax
    import jax.numpy as jnp

    from streambench_tpu.parallel import build_mesh
    from streambench_tpu.parallel.sharded import (
        _build_scan,
        _build_scan_packed,
        sharded_init_state,
    )

    mesh = build_mesh(data=2, campaign=2, devices=jax.devices()[:4])
    K, B, C, W = 3, 32, 16, 8
    jt = jnp.zeros((65,), jnp.int32)
    st = sharded_init_state(C, W, mesh)
    zi = lambda *s: jnp.zeros(s, jnp.int32)  # noqa: E731
    state_args = (st.counts, st.window_ids, st.watermark, st.dropped, jt)
    ucols = (zi(K, B), zi(K, B), zi(K, B), jnp.zeros((K, B), bool))
    pcols = (zi(K, B), zi(K, B))
    col = 4 * B  # one gathered int32 [B] column

    def rep(fn, cols):
        return collectives.report_for(fn, *state_args, *cols, scan_len=K)

    r = rep(_build_scan(mesh, 10_000, 60_000, 0, False), ucols)
    assert r["per_dispatch"]["ops"] == K * 5
    assert r["per_dispatch"]["bytes"] == K * (4 * col + 4)
    assert r["top_level"]["ops"] == 0

    r = rep(_build_scan(mesh, 10_000, 60_000, 0, True), ucols)
    # the tentpole claim: ONE gather per column per dispatch (vs K),
    # plus one scalar psum — and nothing left inside the loop
    assert r["per_dispatch"]["ops"] == 5
    assert r["per_dispatch"]["column_ops"] == 4
    assert r["per_dispatch"]["bytes"] == 4 * K * col + 4
    assert r["per_loop_iteration"]["ops"] == 0

    r = rep(_build_scan_packed(mesh, 10_000, 60_000, 0, False), pcols)
    assert r["per_dispatch"]["ops"] == K * 3
    assert r["per_dispatch"]["bytes"] == K * (2 * col + 4)

    r = rep(_build_scan_packed(mesh, 10_000, 60_000, 0, True), pcols)
    assert r["per_dispatch"]["ops"] == 3
    assert r["per_dispatch"]["column_bytes"] == 2 * K * col
    # the parallel/sharded.py:121-136 claim, finally as a number:
    # packed column traffic is exactly half of unpacked
    unpacked = rep(_build_scan(mesh, 10_000, 60_000, 0, True), ucols)
    assert (r["per_dispatch"]["column_bytes"] * 2
            == unpacked["per_dispatch"]["column_bytes"])


def test_engine_collective_report_and_gauges(tmp_path):
    """The engine-level surface: report shape, obs gauges, and the
    packed step gathering 2 columns + 1 scalar psum."""
    import jax

    from streambench_tpu.config import default_config
    from streambench_tpu.obs.registry import MetricsRegistry
    from streambench_tpu.parallel import ShardedWindowEngine, build_mesh

    cfg = default_config(jax_batch_size=64, jax_window_slots=16)
    mapping = {f"ad{i}": f"c{i % 4}" for i in range(16)}
    mesh = build_mesh(data=2, campaign=2, devices=jax.devices()[:4])
    eng = ShardedWindowEngine(cfg, mapping, mesh)
    reg = MetricsRegistry()
    eng.attach_obs(reg)
    rep = eng.collective_report(k=2)
    assert rep["packed"] is True
    assert rep["step"]["per_dispatch"]["ops"] == 3
    assert rep["scan"]["per_dispatch"]["ops"] == 3
    # scan gathers the [2, B] stack: twice the step's column bytes
    assert (rep["scan"]["per_dispatch"]["column_bytes"]
            == 2 * rep["step"]["per_dispatch"]["column_bytes"])
    vals = {(m.name, m.labels.get("kernel")): m.value
            for m in reg._metrics.values()
            if m.name.startswith("streambench_collective")}
    assert vals[("streambench_collective_ops", "scan")] == 3
    assert vals[("streambench_collective_bytes", "scan")] > 0
