"""Sliding + session window ops vs pure-Python reference models."""

import numpy as np
import pytest

from streambench_tpu.ops import session, sliding
from streambench_tpu.ops import windowcount as wc


# ------------------------------------------------------------ sliding
def ref_sliding_counts(events, join, size, slide):
    """events: (ad, etype, t, valid); returns {(campaign, wid): count}."""
    out = {}
    for ad, et, t, v in events:
        if not v or et != 0 or join[ad] < 0:
            continue
        base = t // slide
        for k in range(size // slide):
            wid = base - k
            if wid < 0:
                continue
            out[(join[ad], wid)] = out.get((join[ad], wid), 0) + 1
    return out


def test_sliding_counts_match_reference():
    rng = np.random.default_rng(21)
    C, W = 5, 96  # ring must cover lateness_eff + span at slide granularity
    n_ads = 15
    join = np.concatenate(
        [rng.integers(0, C, n_ads).astype(np.int32), [-1]])
    st = wc.init_state(C, W)
    all_events = []
    for _ in range(6):
        B = 256
        ad = rng.integers(0, n_ads, B).astype(np.int32)
        et = rng.integers(0, 3, B).astype(np.int32)
        tm = np.sort(rng.integers(70_000, 82_000, B)).astype(np.int32)
        valid = rng.random(B) < 0.9
        st = sliding.step(st, join, ad, et, tm, valid,
                          size_ms=10_000, slide_ms=1_000)
        all_events += list(zip(ad.tolist(), et.tolist(), tm.tolist(),
                               valid.tolist()))
    assert int(st.dropped) == 0
    expected = ref_sliding_counts(all_events, join, 10_000, 1_000)
    counts = np.asarray(st.counts)
    wids = np.asarray(st.window_ids)
    got = {}
    for s in range(W):
        if wids[s] < 0:
            continue
        for c in range(C):
            if counts[c, s]:
                got[(c, int(wids[s]))] = int(counts[c, s])
    assert got == expected


def test_sliding_methods_bit_identical():
    """The factored one-hot matmul form (VERDICT 8: the unrolled S=10
    masked scatters folded into one MXU pass) is bit-identical to the
    scatter original — counts, ring ids, watermark, AND the
    membership-granular dropped counter — including late events and
    ring-eviction churn."""
    rng = np.random.default_rng(5)
    C, W, B = 7, 32, 512
    n_ads = 21
    join = np.concatenate(
        [rng.integers(0, C, n_ads).astype(np.int32), [-1]])
    ad = rng.integers(0, n_ads + 1, B).astype(np.int32)
    et = rng.integers(0, 3, B).astype(np.int32)
    # wide time spread: forces lateness drops and slot eviction
    tm = rng.integers(0, 400_000, B).astype(np.int32)
    valid = rng.random(B) < 0.9
    outs = {}
    for method in ("scatter", "matmul", "onehot", "pallas"):
        st = wc.init_state(C, W)
        for off in range(0, B, 128):
            sl = slice(off, off + 128)
            st = sliding.step(st, join, ad[sl], et[sl], tm[sl],
                              valid[sl], size_ms=10_000, slide_ms=1_000,
                              lateness_ms=20_000, method=method)
        outs[method] = (np.asarray(st.counts), np.asarray(st.window_ids),
                        int(st.watermark), int(st.dropped))
    base = outs["scatter"]
    assert base[3] > 0, "plan never exercised the dropped path"
    for method, got in outs.items():
        assert np.array_equal(got[0], base[0]), method
        assert np.array_equal(got[1], base[1]), method
        assert got[2:] == base[2:], method


def test_sliding_rejects_ring_smaller_than_memberships():
    import pytest

    st = wc.init_state(2, 8)   # 8 slots < 10 memberships
    join = np.array([0, -1], np.int32)
    z = np.zeros(4, np.int32)
    with pytest.raises(ValueError, match="ring too small"):
        sliding.step(st, join, z, z, z, np.ones(4, bool),
                     size_ms=10_000, slide_ms=1_000)


# ------------------------------------------------------- sliced fold
def _flush_rows(deltas, wids, into):
    deltas = np.asarray(deltas)
    wids = np.asarray(wids)
    for c, s in zip(*np.nonzero(deltas)):
        if wids[s] >= 0:
            key = (int(c), int(wids[s]))
            into[key] = into.get(key, 0) + int(deltas[c, s])


@pytest.mark.parametrize("seed,size_ms,slide_ms,lateness_ms",
                         [(0, 10_000, 1_000, 20_000),
                          (1, 8_000, 2_000, 9_000),
                          (2, 16_000, 1_000, 31_000)])
def test_sliced_vs_unrolled_flushed_rows(seed, size_ms, slide_ms,
                                         lateness_ms):
    """ISSUE 12 bit-identity sweep: the sliced fold's FLUSHED window
    rows, membership-granular ``dropped``, and watermark equal the
    unrolled per-k fold's across adversarial batches — late events
    (within and beyond allowed lateness, so partially-late membership
    drops fire), duplicate rows, invalid rows, non-view types, join
    misses, and pre-origin (wid < 0) events — under a realistic flush
    cadence.  Ring sized for the span-guard regime (the documented
    equivalence domain — the engine's span guard enforces it live)."""
    rng = np.random.default_rng(seed)
    S = size_ms // slide_ms
    late_eff = sliding.effective_lateness(size_ms, slide_ms, lateness_ms)
    C, B = 5, 192
    W = late_eff // slide_ms + 3 * S + 8
    n_ads = 15
    join = np.concatenate(
        [rng.integers(0, C, n_ads).astype(np.int32), [-1]])
    st_l = wc.init_state(C, W)
    st_s = sliding.init_sliced(C, W, S)
    rows_l: dict = {}
    rows_s: dict = {}
    t0 = 4 * size_ms

    def drain():
        nonlocal st_l, st_s
        dl, wl, st_l = wc.flush_deltas(st_l, divisor_ms=slide_ms,
                                       lateness_ms=late_eff)
        _flush_rows(dl, wl, rows_l)
        ds, ws, st_s = sliding.flush_sliced(st_s, size_ms=size_ms,
                                            slide_ms=slide_ms,
                                            lateness_ms=lateness_ms)
        _flush_rows(ds, ws, rows_s)

    for it in range(12):
        ad = rng.integers(0, n_ads + 1, B).astype(np.int32)
        et = rng.integers(0, 3, B).astype(np.int32)
        # spread: on-time, late-but-allowed, beyond-lateness, and a few
        # pre-origin stragglers
        tm = (t0 + rng.integers(-(lateness_ms + 2 * size_ms),
                                size_ms, B)).astype(np.int32)
        tm[rng.random(B) < 0.02] = rng.integers(0, slide_ms)
        tm = np.maximum(tm, 0)
        # duplicates: repeat a slice of the batch verbatim
        tm[B // 2:B // 2 + 8] = tm[:8]
        ad[B // 2:B // 2 + 8] = ad[:8]
        et[B // 2:B // 2 + 8] = et[:8]
        valid = rng.random(B) < 0.9
        st_l = sliding.step(st_l, join, ad, et, tm, valid,
                            size_ms=size_ms, slide_ms=slide_ms,
                            lateness_ms=lateness_ms)
        st_s = sliding.step_sliced(st_s, join, ad, et, tm, valid,
                                   size_ms=size_ms, slide_ms=slide_ms,
                                   lateness_ms=lateness_ms)
        t0 += size_ms // 2
        if it % 3 == 2:
            drain()
    drain()
    assert int(st_l.dropped) > 0, "sweep never exercised membership drops"
    assert int(st_l.watermark) == int(st_s.watermark)
    assert int(st_l.dropped) == int(st_s.dropped)
    assert rows_l == rows_s


def test_sliced_rejects_bad_geometry():
    join = np.array([0, -1], np.int32)
    z = np.zeros(4, np.int32)
    st = sliding.init_sliced(2, 8, 10)   # 8 slots < 10 memberships
    with pytest.raises(ValueError, match="ring too small"):
        sliding.step_sliced(st, join, z, z, z, np.ones(4, bool),
                            size_ms=10_000, slide_ms=1_000)
    st = sliding.init_sliced(2, 64, 5)   # plane carries wrong S
    with pytest.raises(ValueError, match="lateness classes"):
        sliding.step_sliced(st, join, z, z, z, np.ones(4, bool),
                            size_ms=10_000, slide_ms=1_000)


def test_sliced_flush_frees_closed_buckets():
    """A bucket slot frees exactly when the LAST window containing it
    closes (same ``_still_open`` rule as the legacy ring under the
    effective lateness), and a freed window reconstructs to zero —
    never re-emitted — on later drains."""
    size, slide, late = 10_000, 1_000, 20_000
    late_eff = sliding.effective_lateness(size, slide, late)
    C, W, S = 2, 96, 10
    join = np.array([0, 1, -1], np.int32)
    st = sliding.init_sliced(C, W, S)
    tm = np.array([70_000, 70_000 + late_eff + 1_500], np.int32)
    st = sliding.step_sliced(st, join, np.array([0, 1], np.int32),
                             np.zeros(2, np.int32), tm, np.ones(2, bool),
                             size_ms=size, slide_ms=slide,
                             lateness_ms=late)
    deltas, wids, st2 = sliding.flush_sliced(st, size_ms=size,
                                             slide_ms=slide,
                                             lateness_ms=late)
    # the first event's bucket (id 70) closed: its slot is freed
    w2 = np.asarray(st2.window_ids)
    assert (w2[np.asarray(st.window_ids) == 70] == -1).all()
    # a second drain with nothing new emits nothing
    d2, w2ids, _ = sliding.flush_sliced(st2, size_ms=size, slide_ms=slide,
                                        lateness_ms=late)
    assert int(np.asarray(d2).sum()) == 0


def test_sliding_flush_uses_effective_lateness():
    late_eff = sliding.effective_lateness(10_000, 1_000, 60_000)
    C, W = 2, 96
    join = np.array([0, 1, -1], np.int32)
    st = wc.init_state(C, W)
    tm = np.array([70_000, 70_000 + late_eff + 1_500], np.int32)
    st = sliding.step(st, join, np.array([0, 1], np.int32),
                      np.zeros(2, np.int32), tm, np.ones(2, bool),
                      size_ms=10_000, slide_ms=1_000)
    deltas, wids, st2 = wc.flush_deltas(st, divisor_ms=1_000,
                                        lateness_ms=late_eff)
    # the first event's earliest window (wid 61) is now closed
    w2 = np.asarray(st2.window_ids)
    assert (w2[np.asarray(wids) == 61] == -1).all()


# ------------------------------------------------------------ session
def ref_sessions(events, gap):
    """events: (user, etype, t) sorted arbitrarily; returns list of
    (user, start, end, clicks) for ALL sessions (closed + open)."""
    per_user: dict[int, list[int]] = {}
    from collections import defaultdict
    evs = defaultdict(list)
    for u, et, t in events:
        evs[u].append((t, et))
    out = []
    for u, rows in evs.items():
        rows.sort()
        start, last, clicks = None, None, 0
        for t, et in rows:
            if start is None:
                start, last, clicks = t, t, 0
            elif t - last > gap:
                out.append((u, start, last, clicks))
                start, last, clicks = t, t, 0
            last = t
            clicks += 1 if et == 1 else 0
        if start is not None:
            out.append((u, start, last, clicks))
    return sorted(out)


def collect_closed(*closed_batches):
    out = []
    for cb in closed_batches:
        v = np.asarray(cb.valid)
        for i in np.flatnonzero(v):
            out.append((int(cb.user[i]), int(cb.start[i]),
                        int(cb.end[i]), int(cb.clicks[i])))
    return out


def test_session_windows_match_reference():
    rng = np.random.default_rng(31)
    U, B = 16, 128
    st = session.init_state(U)
    gap = 30_000
    all_events = []
    emitted = []
    t0 = 70_000
    for step_i in range(8):
        user = rng.integers(0, U, B).astype(np.int32)
        et = rng.integers(0, 3, B).astype(np.int32)
        # spread events so some gaps exceed 30 s per user
        tm = np.sort(t0 + rng.integers(0, 60_000, B)).astype(np.int32)
        t0 += 60_000
        valid = np.ones(B, bool)
        st, cb, cc = session.step(st, user, et, tm, valid, gap_ms=gap)
        emitted += collect_closed(cb, cc)
        all_events += list(zip(user.tolist(), et.tolist(), tm.tolist()))
    st, fin = session.flush(st, gap_ms=gap, force=True)
    emitted += collect_closed(fin)
    assert int(st.dropped) == 0
    assert sorted(emitted) == ref_sessions(all_events, gap)


def test_session_flush_by_watermark():
    st = session.init_state(4)
    user = np.array([1, 2], np.int32)
    et = np.ones(2, np.int32)
    tm = np.array([70_000, 71_000], np.int32)
    st, cb, cc = session.step(st, user, et, tm, np.ones(2, bool))
    # advance watermark far past user 1+2's last events
    st, cb2, cc2 = session.step(
        st, np.array([3], np.int32), np.ones(1, np.int32),
        np.array([200_000], np.int32), np.ones(1, bool))
    st, closed = session.flush(st, gap_ms=30_000, lateness_ms=60_000)
    got = collect_closed(closed)
    assert (1, 70_000, 70_000, 1) in got and (2, 71_000, 71_000, 1) in got
    # user 3's session is still open
    assert all(u != 3 for u, *_ in got)


def test_session_capacity_overflow_drops():
    st = session.init_state(2)
    user = np.array([0, 1, 5], np.int32)   # 5 >= capacity
    st, cb, cc = session.step(st, user, np.ones(3, np.int32),
                              np.array([70_000, 70_001, 70_002], np.int32),
                              np.ones(3, bool))
    assert int(st.dropped) == 1


def test_session_late_event_does_not_regress_carry():
    """A late-but-in-gap event must not pull the carried session's last
    activity (or a later gap decision) backwards (code-review finding)."""
    st = session.init_state(4)
    # open a session for user 1 ending at t=100_000
    st, cb, cc = session.step(
        st, np.array([1], np.int32), np.ones(1, np.int32),
        np.array([100_000], np.int32), np.ones(1, bool))
    # late event at 90_000 (within 60s lateness, within 30s gap)
    st, cb, cc = session.step(
        st, np.array([1], np.int32), np.ones(1, np.int32),
        np.array([90_000], np.int32), np.ones(1, bool))
    assert int(st.last_time[1]) == 100_000  # not regressed to 90_000
    assert not (np.asarray(cb.valid).any() or np.asarray(cc.valid).any())
    # event at 125_000: 25s after true last activity -> SAME session
    st, cb, cc = session.step(
        st, np.array([1], np.int32), np.ones(1, np.int32),
        np.array([125_000], np.int32), np.ones(1, bool))
    assert not (np.asarray(cb.valid).any() or np.asarray(cc.valid).any())
    st, fin = session.flush(st, force=True)
    got = collect_closed(fin)
    assert got == [(1, 90_000, 125_000, 3)]


def test_session_late_batch_then_split_in_one_batch():
    """Late event + far event in ONE batch: the in-batch gap test must use
    the carried last activity, not just the previous in-batch event."""
    st = session.init_state(4)
    st, cb, cc = session.step(
        st, np.array([1], np.int32), np.ones(1, np.int32),
        np.array([100_000], np.int32), np.ones(1, bool))
    st, cb, cc = session.step(
        st, np.array([1, 1], np.int32), np.ones(2, np.int32),
        np.array([90_000, 125_000], np.int32), np.ones(2, bool))
    # 125_000 - 100_000 = 25s <= gap: still one session, nothing closed
    assert not (np.asarray(cb.valid).any() or np.asarray(cc.valid).any())
    st, fin = session.flush(st, force=True)
    assert collect_closed(fin) == [(1, 90_000, 125_000, 3)]


def test_session_far_late_event_is_its_own_session():
    """An event more than gap_ms BEFORE the carried session's start must
    not merge into it (code-review finding)."""
    st = session.init_state(4)
    st, cb, cc = session.step(
        st, np.array([1], np.int32), np.ones(1, np.int32),
        np.array([100_000], np.int32), np.ones(1, bool))
    # 50s before the carried span start, gap is 30s -> separate session
    st, cb, cc = session.step(
        st, np.array([1], np.int32), np.ones(1, np.int32),
        np.array([50_000], np.int32), np.ones(1, bool))
    got = collect_closed(cb, cc)
    st, fin = session.flush(st, force=True)
    got += collect_closed(fin)
    assert sorted(got) == [(1, 50_000, 50_000, 1), (1, 100_000, 100_000, 1)]
