"""The bench's parse-proof emission contract, as a regression test.

The driver records bench stdout and takes the LAST JSON line; it may
SIGKILL the process at an unknown timeout.  Round 4 lost its entire
artifact to a single end-of-run print, so round 5 made the bench
re-emit the headline after every completed phase.  These tests pin that
contract: a line exists almost immediately, every line parses, and a
SIGKILL mid-run still leaves a parseable last line.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SMOKE_ENV = {
    "JAX_PLATFORMS": "cpu",
    "STREAMBENCH_BENCH_EVENTS": "30000",
    "STREAMBENCH_BENCH_REPS": "1",
    "STREAMBENCH_BENCH_SWEEP_RUNS": "1",
    "STREAMBENCH_BENCH_PACED_SECS": "5",
    "STREAMBENCH_BENCH_PACED_RATE": "2000",
    "STREAMBENCH_BENCH_CONFIGS": "0",  # skip the sketch/config suite
    # skip the sliding A/B phase: ~6 engine warmups + reps would
    # triple this smoke's wall time; the A/B keys' parse contract is
    # pinned by the CI bench-smoke step instead
    "STREAMBENCH_BENCH_SLIDING": "0",
    # the artifact side file must not clobber the repo's committed one
    "STREAMBENCH_BENCH_TRACE": "0",
}


def _env(tmp_path, extra=None):
    env = dict(os.environ)
    env.update(SMOKE_ENV)
    # the copied bench.py must find the package
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # the bench's workdir lands under pytest's tmp (pruned even when a
    # SIGKILL skips the bench's own TemporaryDirectory cleanup)
    env["STREAMBENCH_BENCH_TMPDIR"] = str(tmp_path)
    env.update(extra or {})
    return env


def _json_lines(text: str):
    out = []
    for line in text.splitlines():
        if line.startswith("{"):
            out.append(json.loads(line))  # EVERY emitted line must parse
    return out


@pytest.fixture()
def bench_copy(tmp_path):
    """bench.py run from a copy next to a scratch streambench_tpu import
    path, so its bench_latency.json lands in tmp, not the repo."""
    import shutil

    shutil.copy(os.path.join(REPO, "bench.py"), tmp_path / "bench.py")
    return str(tmp_path / "bench.py")


def test_bench_emits_parseable_line_per_phase(bench_copy, tmp_path):
    p = subprocess.run(
        [sys.executable, bench_copy], env=_env(tmp_path), cwd=REPO,
        capture_output=True, text=True, timeout=420)
    assert p.returncode == 0, p.stderr[-800:]
    lines = _json_lines(p.stdout)
    # probe, setup, pre-oracle, post-oracle, >=1 rung, complete
    assert len(lines) >= 5
    phases = [d["phase"] for d in lines]
    assert phases[0] == "probe" and phases[-1] == "complete"
    last = lines[-1]
    assert last["metric"] == "sustained events/sec (oracle-verified)"
    assert last["value"] > 0
    assert last["unit"] == "events/s"
    # the pre-oracle line must NOT claim verification
    pending = [d for d in lines if "pending" in d["phase"]]
    assert all("PENDING" in d["metric"] for d in pending)
    # VERDICT 6: every stdout line is the COMPACT form and fits the
    # hard cap, so a consumer keeping only a log TAIL still ends on a
    # parseable line (BENCH_r05's rich line was cut mid-JSON)
    for raw in p.stdout.splitlines():
        if raw.startswith("{"):
            assert len(raw) <= 4096, len(raw)
    assert all(d.get("compact") for d in lines)
    # driver simulation: the last 2 KB of stdout still yields the line
    tail = p.stdout[-2000:]
    tail_lines = [l for l in tail.splitlines() if l.startswith("{")]
    assert tail_lines and json.loads(tail_lines[-1])["value"] > 0
    # the side artifact holds the RICH view and mirrors the final line
    side = json.load(open(tmp_path / "bench_latency.json"))
    assert side["phase"] == "complete"
    assert side["catchup_events_per_s"] == last["value"]
    # the per-method table + winner landed in the artifact (VERDICT 7)
    assert side["methods"]["winner"] in side["methods"]["methods"]


def test_compact_line_survives_oversized_fields():
    """Progressive stripping: a pathologically rich headline still
    emits under the cap, shedding detail fields first but never the
    metric/value contract keys."""
    bench = _load_bench("bench_mod_compact")
    em = bench.HeadlineEmitter("/tmp/nonexistent-bench-latency.json")
    em.update(metric="sustained events/sec (oracle-verified)",
              value=123.0, unit="events/s", vs_baseline=1.0,
              platform="cpu", phase="complete",
              configs=[{"config": f"c{i}", "catchup_events_per_s": i,
                        "oracle": "exact", "paced": {"p99_ms": i}}
                       for i in range(400)],
              latency_sweep={"max_sustained_rate": 1},
              methods_compact={"winner": "scatter",
                               "ns_per_event": {"scatter": 1.0}})
    line = em.compact_line()
    assert len(line) <= bench.COMPACT_LINE_MAX
    d = json.loads(line)
    assert d["value"] == 123.0 and d["metric"]
    # a normal-sized headline keeps its detail fields
    em.update(configs=[{"config": "exact_count",
                        "catchup_events_per_s": 1.0}])
    d = json.loads(em.compact_line())
    assert d["configs"][0]["config"] == "exact_count"
    assert d["methods"]["winner"] == "scatter"


def test_rung_budget_guard_clamps_and_skips():
    """BENCH_r04 died rc-124 to the driver's kill; the guard clamps a
    rung that would overrun the envelope and skips one that cannot fit
    even at the floor."""
    bench = _load_bench("bench_mod_guard")
    now = 1000.0
    deadline = now + 300.0
    # plenty of room: full duration
    assert bench._clamped_rung_duration(deadline, 125.0, margin_s=45,
                                        now=now) == 125.0
    # tight room: clamped to what fits (>= the floor)
    got = bench._clamped_rung_duration(now + 130.0, 125.0, margin_s=45,
                                       now=now)
    assert got is not None and bench.MIN_RUNG_S <= got < 125.0
    # no room at all: skip
    assert bench._clamped_rung_duration(now + 60.0, 125.0, margin_s=45,
                                        now=now) is None
    # no deadline: untouched
    assert bench._clamped_rung_duration(None, 125.0) == 125.0


def test_bench_sigkill_leaves_parseable_artifact(bench_copy, tmp_path):
    """SIGKILL right after the oracle-verified catchup emission (the
    earliest point the driver's kill matters): whatever already hit
    stdout must parse, with the newest line the richest view."""
    import selectors

    proc = subprocess.Popen(
        [sys.executable, bench_copy], env=_env(tmp_path), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    got = []
    deadline = time.monotonic() + 300
    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    buf = ""
    try:
        # a selector-bounded read loop: a wedged bench FAILS the test at
        # the deadline instead of hanging the suite on readline()
        while time.monotonic() < deadline and len(got) < 4:
            if not sel.select(timeout=max(deadline - time.monotonic(),
                                          0.1)):
                continue
            chunk = os.read(proc.stdout.fileno(), 65536).decode(
                "utf-8", "replace")
            if not chunk:
                break
            buf += chunk
            while "\n" in buf:
                line, buf = buf.split("\n", 1)
                if line.startswith("{"):
                    got.append(line)
        proc.send_signal(signal.SIGKILL)
    finally:
        sel.close()
        proc.wait(timeout=30)
    assert len(got) >= 4, "bench never reached its catchup emission"
    last = json.loads(got[-1])
    assert last["value"] > 0
    assert last["configs"][0]["config"] == "exact_count"


def _load_bench(name="bench_mod"):
    """Import bench.py as a throwaway module (its CLI lives under
    __main__, so module-level exec is side-effect-free)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_ladder_retries_stall_signature_once(monkeypatch):
    """A failed rung whose p90 is within the SLA (only the extreme tail
    blew — the multi-second host/tunnel stall signature) is re-run once
    at the SAME rate instead of halving the ladder; both attempts stay
    in the artifact."""
    bench = _load_bench()

    calls = []

    def fake_phase(cfg, mapping, broker, redis, wd, rate, dur,
                   run_id=0, **kw):
        calls.append(rate)
        row = {"rate": rate, "sent": int(rate * dur),
               "processed": int(rate * dur), "windows": 14,
               "generator_behind_max_ms": 0, "generator_behind_events": 0,
               "p50_ms": 11_500, "p90_ms": 11_600, "p99_ms": 11_700}
        if len(calls) == 1:  # first attempt: stall-shaped tail blowout
            # p90 PAST the SLA too (the longer-stall shape from the
            # recorded r5 run: p50 11.4 s, p90 18.7 s, p99 20.8 s) —
            # the signature is judged on the MEDIAN, not p90
            row["p90_ms"] = 18_700
            row["p99_ms"] = 27_000
            # independent evidence: the engine's own flush loop ALSO
            # recorded a multi-second wall-clock gap (required since
            # the ADVICE r5 gating — shape alone no longer retries)
            row["flush_stall_max_ms"] = 8_000
        return row

    monkeypatch.setattr(bench, "_paced_latency_phase", fake_phase)
    sweep = bench._latency_sweep(None, None, None, None, 100_000, 125.0,
                                 15_000, max_runs=4,
                                 rate_ceiling=120_000)
    assert calls[0] == 100_000 and calls[1] == 100_000, calls
    assert sweep["rates"][0].get("stall_retried") is True
    assert sweep["max_sustained_rate"] == 100_000
    # a second tail blowout would NOT be retried (one per ladder)
    assert sum(1 for r in sweep["rates"] if r.get("stall_retried")) == 1


def test_stall_signature_requires_independent_evidence():
    """ADVICE r5: the percentile shape (processed==sent, p50<=SLA,
    p99>SLA) can be produced by a REAL engine-side tail regression, so
    it must not be retried away on its own — only when the generator
    also fell behind or the flush loop recorded a wall-clock gap."""
    bench = _load_bench("bench_mod3")
    shape = {"rate": 10_000, "sent": 100, "processed": 100,
             "p50_ms": 11_000, "p99_ms": 27_000}
    # shape alone: NOT a stall signature (a real tail regression)
    assert not bench._stall_signature(dict(shape), 15_000)
    # generator gap corroborates
    assert bench._stall_signature(
        dict(shape, generator_behind_max_ms=1_500), 15_000)
    # flush-loop wall-clock gap corroborates
    assert bench._stall_signature(
        dict(shape, flush_stall_max_ms=4_000), 15_000)
    # evidence below the thresholds does not
    assert not bench._stall_signature(
        dict(shape, generator_behind_max_ms=200, flush_stall_max_ms=2_000),
        15_000)
    # evidence without the shape (median blown too) never retries
    assert not bench._stall_signature(
        dict(shape, p50_ms=16_000, flush_stall_max_ms=9_000), 15_000)


def test_config_row_stall_retry_parks_first_attempt():
    """The config-row paced retry must stamp the ladder's stall_retried
    key on the first attempt, hand it to on_first BEFORE re-running (a
    raising retry must not destroy the measured attempt), and skip the
    retry entirely when the median blew the SLA or the budget is gone."""
    bench = _load_bench("bench_mod2")

    def make_row(p50, p99):
        return {"rate": 20_000, "sent": 100, "processed": 100,
                "sustained": p99 <= 15_000, "invalid_producer": False,
                "p50_ms": p50, "p90_ms": p50, "p99_ms": p99,
                # independent stall evidence (required by the gating)
                "flush_stall_max_ms": 8_000}

    # stall shape: retried, first attempt parked before attempt 2 runs
    parked = []
    attempts = []

    def run_paced(attempt):
        attempts.append((attempt, list(parked)))
        return make_row(11_400, 27_000 if attempt == 0 else 11_500)

    out = bench._paced_with_stall_retry(
        run_paced, 15_000, deadline=time.monotonic() + 10_000,
        reserve_s=1.0, key="t", on_first=parked.append)
    assert out["sustained"] and out["stall_retry_of"]["stall_retried"]
    assert attempts[1][1], "first attempt must be parked before retry"

    # overload shape (median blown): no retry
    calls = []
    out = bench._paced_with_stall_retry(
        lambda a: calls.append(a) or make_row(16_000, 27_000),
        15_000, deadline=time.monotonic() + 10_000, reserve_s=1.0,
        key="t")
    assert calls == [0] and "stall_retry_of" not in out

    # stall shape but budget exhausted: no retry
    calls = []
    out = bench._paced_with_stall_retry(
        lambda a: calls.append(a) or make_row(11_400, 27_000),
        15_000, deadline=time.monotonic() + 0.5, reserve_s=1.0, key="t")
    assert calls == [0] and "stall_retried" not in out
