"""Generator + oracle tests: modes -n/-s/-r/-g/-c against the fake Redis."""

import json
import random

from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import as_redis, write_window


def test_new_setup_seeds_campaigns_and_mapping(tmp_path):
    r = as_redis(FakeRedisStore())
    campaigns = gen.do_new_setup(r, rng=random.Random(1), workdir=str(tmp_path))
    assert len(campaigns) == 100
    assert len(r.execute("SMEMBERS", "campaigns")) == 100
    # id files exist and load (the fixed load-ids)
    loaded = gen.load_ids(str(tmp_path))
    assert loaded is not None
    cs, ads = loaded
    assert cs == campaigns and len(ads) == 1000
    # join table seeded: every ad GETs to a campaign
    assert r.execute("GET", ads[0]) in campaigns
    # mapping file parses in both formats
    m = gen.load_ad_mapping_file(str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    assert len(m) == 1000 and m[ads[0]] in campaigns


def test_csv_mapping_format(tmp_path):
    p = tmp_path / "map.csv"
    p.write_text("ad1,campA\nad2 , campB\n")
    assert gen.load_ad_mapping_file(str(p)) == {"ad1": "campA", "ad2": "campB"}


def test_event_wire_format():
    src = gen.EventSource(ads=["adX"], user_ids=["u"], page_ids=["p"],
                          rng=random.Random(7))
    ev = json.loads(src.event_at(123456))
    assert set(ev) == {"user_id", "page_id", "ad_id", "ad_type",
                       "event_type", "event_time", "ip_address"}
    assert ev["ad_id"] == "adX"
    assert ev["event_time"] == "123456"      # stringified ms, as in core.clj
    assert ev["ip_address"] == "1.2.3.4"
    assert ev["ad_type"] in gen.AD_TYPES
    assert ev["event_type"] in gen.EVENT_TYPES


def test_skew_injection_bounds():
    rng = random.Random(3)
    src = gen.EventSource(ads=["a"], user_ids=["u"], page_ids=["p"],
                          with_skew=True, rng=rng)
    t0 = 1_000_000
    times = [int(json.loads(src.event_at(t0))["event_time"])
             for _ in range(5000)]
    assert all(t0 - 60_050 <= t <= t0 + 50 for t in times)
    assert any(t != t0 for t in times)


def test_setup_catchup_and_golden_model(tmp_path):
    cfg = default_config()
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    n = gen.do_setup(r, cfg, broker=broker, events_num=5000,
                     rng=random.Random(42), workdir=str(tmp_path))
    assert n == 5000
    journal = (tmp_path / gen.KAFKA_JSON_FILE).read_text().strip().splitlines()
    assert len(journal) == 5000
    # broker topic mirrors the journal
    assert len(list(broker.read_all(cfg.kafka_topic))) == 5000
    # event_time spacing is 10 ms (core.clj:94)
    t0 = int(json.loads(journal[0])["event_time"])
    t1 = int(json.loads(journal[1])["event_time"])
    assert t1 - t0 == 10

    golden = gen.dostats(str(tmp_path))
    total = sum(sum(b.values()) for b in golden.values())
    views = sum(1 for l in journal if json.loads(l)["event_type"] == "view")
    assert total == views > 0


def test_check_correct_detects_good_and_bad(tmp_path):
    cfg = default_config()
    r = as_redis(FakeRedisStore())
    gen.do_setup(r, cfg, events_num=2000, rng=random.Random(9),
                 workdir=str(tmp_path))
    golden = gen.dostats(str(tmp_path))
    # write the golden answers into Redis: everything must be CORRECT
    for campaign, buckets in golden.items():
        for bucket, count in buckets.items():
            write_window(r, campaign, bucket * 10_000, count)
    logs = []
    correct, differ, missing = gen.check_correct(r, str(tmp_path),
                                                 log=logs.append)
    assert differ == 0 and missing == 0 and correct > 0

    # corrupt one window -> exactly one DIFFER
    camp = next(iter(golden))
    bucket = next(iter(golden[camp]))
    write_window(r, camp, bucket * 10_000, 999)
    correct2, differ2, missing2 = gen.check_correct(r, str(tmp_path),
                                                    log=logs.append)
    assert differ2 == 1 and missing2 == 0


def test_paced_run_rate_and_journal(tmp_path):
    r = as_redis(FakeRedisStore())
    gen.do_new_setup(r, rng=random.Random(5), workdir=str(tmp_path))
    broker = FileBroker(str(tmp_path / "broker"))
    broker.create_topic("ad-events")
    with broker.writer("ad-events") as sink:
        sent = gen.run_paced(sink, throughput=20_000, duration_s=0.3,
                             workdir=str(tmp_path))
    # ~6000 events expected in 0.3 s at 20k/s; allow generous slack
    assert 3000 <= sent <= 9000
    lines = list(broker.read_all("ad-events"))
    assert len(lines) == sent
    # event_time monotone non-decreasing (scheduled times)
    times = [int(json.loads(l)["event_time"]) for l in lines[:200]]
    assert times == sorted(times)


def test_get_stats_files(tmp_path):
    r = as_redis(FakeRedisStore())
    from streambench_tpu.io.redis_schema import seed_campaigns
    seed_campaigns(r, ["c1"])
    write_window(r, "c1", 10_000, 5, time_updated=13_000)
    stats = gen.get_stats(r, workdir=str(tmp_path))
    assert stats == [(5, 3000)]
    assert (tmp_path / "seen.txt").read_text() == "5\n"
    assert (tmp_path / "updated.txt").read_text() == "3000\n"


def test_reseed_reuses_existing_ids(tmp_path):
    """Checkpoint-resume seeding: -n --reuse-ids must keep the workdir's
    campaign/ad ids (snapshots + journaled events are keyed to them);
    regenerating would unkey every replayed event (found as zero-count
    resumed windows in the micro-batch CLI flow)."""
    import random

    from streambench_tpu.io.fakeredis import FakeRedisStore
    from streambench_tpu.io.redis_schema import as_redis

    r = as_redis(FakeRedisStore())
    campaigns = gen.do_new_setup(r, rng=random.Random(3),
                                 workdir=str(tmp_path))
    mapping1 = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))

    r2 = as_redis(FakeRedisStore())
    got = gen.do_reseed(r2, workdir=str(tmp_path))
    assert got == campaigns
    mapping2 = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    assert mapping1 == mapping2
    assert r2.execute("SMEMBERS", "campaigns") == sorted(campaigns)
    # and the join table landed
    some_ad = next(iter(mapping1))
    assert r2.execute("GET", some_ad) == mapping1[some_ad]

    # no id files -> None (caller falls back to a fresh setup)
    assert gen.do_reseed(r2, workdir=str(tmp_path / "empty")) is None
