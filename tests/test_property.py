"""Property-based tests (SURVEY.md §5.2: "property tests replace
sanitizers").

Randomized adversarial streams — skewed/late timestamps, reordering,
ragged and over-wide batches, garbage lines, duplicate windows — checked
against the pure-Python golden model (``dostats``, ``core.clj:101-128``)
and against differential twins (native vs Python encoder, scatter vs
one-hot).  The two race conditions fixed in round 1 (barrier wake-up,
shared encoder) would both have been caught by the churn test here.
"""

import json
import random as pyrandom

import numpy as np
import pytest

# CI installs hypothesis; hosts without it get a clean skip instead of
# a perpetual collection error in the tier-1 line
pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.encode.encoder import EventEncoder
from streambench_tpu.engine import AdAnalyticsEngine
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.redis_schema import (
    as_redis,
    read_seen_counts,
    seed_campaigns,
)
from streambench_tpu.ops import windowcount as wc

# One fixed geometry across examples: every example reuses the same jit
# cache entries (shapes/statics identical), so the suite stays fast.
C, A, B = 7, 30, 256
DIV, LATE = 10_000, 60_000
MAPPING = {f"ad{i}": f"camp{i % C}" for i in range(A)}
MAPPING_ADS = sorted(MAPPING)

SETTINGS = dict(max_examples=15, deadline=None,
                suppress_health_check=[HealthCheck.too_slow])


def make_line(ad: str, etype: str, t: int, user="u1", page="p1",
              ad_type="banner") -> bytes:
    # the generator's exact field order (make-kafka-event-at,
    # core.clj:175-181) so the fast path is exercised
    return json.dumps({
        "user_id": user, "page_id": page, "ad_id": ad, "ad_type": ad_type,
        "event_type": etype, "event_time": str(t),
    }).encode()


@st.composite
def event_stream(draw, max_events=1500):
    """A stream with bounded skew + lateness (the generator's contract:
    +-50 ms skew, occasional late events, core.clj:166-173), plus local
    reordering — never later than the allowed lateness, so the golden
    model and the engine must agree EXACTLY."""
    n = draw(st.integers(10, max_events))
    rng = pyrandom.Random(draw(st.integers(0, 2**31)))
    t = 70_000
    lines = []
    for _ in range(n):
        t += rng.randint(0, 300)  # up to ~window-sized gaps over the run
        skew = rng.randint(-50, 50)
        late = rng.randint(0, 50_000) if rng.random() < 0.02 else 0
        ts = max(t + skew - late, 0)
        ad = rng.choice(MAPPING_ADS) if rng.random() < 0.95 else "unknown-ad"
        etype = rng.choice(["view", "view", "click", "purchase"])
        lines.append(make_line(ad, etype, ts, user=f"u{rng.randint(0, 20)}"))
    return lines


@given(stream=event_stream(), chunking=st.integers(1, 4))
@settings(**SETTINGS)
def test_engine_matches_dostats_on_adversarial_streams(stream, chunking):
    cfg = default_config(jax_batch_size=B)
    r = as_redis(FakeRedisStore())
    seed_campaigns(r, sorted(set(MAPPING.values())))
    eng = AdAnalyticsEngine(cfg, MAPPING, redis=r)
    rng = pyrandom.Random(1234)
    i = 0
    while i < len(stream):
        # ragged AND over-wide chunks: 1..chunking*B lines per call
        step_n = rng.randint(1, chunking * B)
        eng.process_lines(stream[i:i + step_n])
        i += step_n
        if rng.random() < 0.3:
            eng.flush()  # duplicate flushes of still-open windows
    eng.close()
    assert eng.dropped == 0

    golden = gen.dostats(events=stream, mapping_path=None,
                         time_divisor_ms=DIV,
                         mapping=MAPPING)
    got = read_seen_counts(r)
    flat_got = {(c, w // DIV): n for c in got for w, n in got[c].items()}
    flat_want = {(c, b): n for c, per in golden.items()
                 for b, n in per.items()}
    assert flat_got == flat_want


@given(stream=event_stream(max_events=400),
       garbage=st.lists(st.binary(min_size=0, max_size=80), max_size=10))
@settings(**SETTINGS)
def test_native_and_python_encoders_identical(stream, garbage):
    """Differential: the C++ fast path and the pure-Python encoder must
    produce byte-identical columns, intern tables, and bad-line counts —
    on clean streams AND with garbage interleaved."""
    native_mod = pytest.importorskip("streambench_tpu.native")
    if native_mod.load() is None:
        pytest.skip("native library unavailable")
    from streambench_tpu.encode.native_encoder import NativeEventEncoder

    rng = pyrandom.Random(7)
    lines = list(stream)
    for g in garbage:
        lines.insert(rng.randrange(len(lines) + 1), g)

    e_py = EventEncoder(MAPPING, divisor_ms=DIV, lateness_ms=LATE)
    e_nat = NativeEventEncoder(MAPPING, divisor_ms=DIV, lateness_ms=LATE)
    i = 0
    while i < len(lines):
        n = rng.randint(1, B)
        chunk = lines[i:i + n]
        i += n
        b_py = e_py.encode(chunk, B)
        b_nat = e_nat.encode(chunk, B)
        assert b_py.n == b_nat.n
        assert b_py.base_time_ms == b_nat.base_time_ms
        for col in ("ad_idx", "event_type", "event_time", "user_idx",
                    "page_idx", "ad_type", "valid"):
            np.testing.assert_array_equal(
                getattr(b_py, col), getattr(b_nat, col), err_msg=col)
    assert e_py.dump_intern_tables() == e_nat.dump_intern_tables()
    assert e_py.bad_lines == e_nat.bad_lines


@given(data=st.data())
@settings(**SETTINGS)
def test_windowcount_conservation_and_method_equivalence(data):
    """Invariant: counted + dropped == wanted, for any input; scatter and
    one-hot agree bit-for-bit.  Exercises duplicate window ids, ring
    eviction, and pre-base (negative-window) events."""
    rng = np.random.default_rng(data.draw(st.integers(0, 2**31)))
    W = 8
    n_steps = data.draw(st.integers(1, 4))
    join = np.concatenate([rng.integers(0, C, A).astype(np.int32), [-1]])
    s1 = wc.init_state(C, W)
    s2 = wc.init_state(C, W)
    wanted_total = 0
    for _ in range(n_steps):
        ad = rng.integers(0, A + 1, B).astype(np.int32)  # incl unknown
        et = rng.integers(0, 3, B).astype(np.int32)
        # wild times: spans bigger than the ring, duplicates, pre-base
        tm = rng.integers(-20_000, 300_000, B).astype(np.int32)
        valid = rng.random(B) < 0.9
        s1 = wc.step(s1, join, ad, et, tm, valid, divisor_ms=DIV,
                     lateness_ms=20_000, method="scatter")
        s2 = wc.step(s2, join, ad, et, tm, valid, divisor_ms=DIV,
                     lateness_ms=20_000, method="onehot")
        wanted_total += int(((et == 0) & valid & (join[ad] >= 0)).sum())
    np.testing.assert_array_equal(np.asarray(s1.counts),
                                  np.asarray(s2.counts))
    np.testing.assert_array_equal(np.asarray(s1.window_ids),
                                  np.asarray(s2.window_ids))
    assert int(s1.dropped) == int(s2.dropped)
    assert int(np.asarray(s1.counts).sum()) + int(s1.dropped) == wanted_total


@given(seed=st.integers(0, 2**31), windows=st.integers(1, 3),
       extra=st.integers(0, 59))
@settings(max_examples=8, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
def test_microbatch_barrier_churn(seed, windows, extra):
    """Thread-churned partitions with ragged tails: every fully assembled
    window's merged counts must equal the golden segment count over the
    union of the partitions' chunks; leftover tails never emit."""
    import tempfile

    from streambench_tpu.engine.microbatch import run_microbatch
    from streambench_tpu.io.journal import FileBroker

    P, psize = 3, 20
    cfg = default_config(window_size=P * psize, map_partitions=P)
    rng = pyrandom.Random(seed)
    broker = FileBroker(tempfile.mkdtemp(prefix="mbprop-"))
    golden = [dict() for _ in range(windows)]
    for p in range(P):
        w = broker.writer(cfg.kafka_topic, p)
        # exactly `windows` full chunks, plus a ragged never-emitted tail
        # on partition 0
        n = windows * psize + (extra if p == 0 else 0)
        for j in range(n):
            ad = rng.choice(MAPPING_ADS)
            etype = rng.choice(["view", "click"])
            w.append(make_line(ad, etype, 70_000 + j))
            if j < windows * psize and etype == "view":
                k = j // psize
                camp = MAPPING[ad]
                golden[k][camp] = golden[k].get(camp, 0) + 1
        w.close()

    merged, results = run_microbatch(cfg, broker, MAPPING)
    assert len(merged) == windows
    campaigns = sorted(set(MAPPING.values()))
    for k in range(windows):
        got = {campaigns[i]: int(v) for i, v in enumerate(merged[k]) if v}
        assert got == golden[k], f"window {k}"


@given(stream=event_stream(), chunking=st.integers(1, 4))
@settings(**SETTINGS)
def test_deferred_drains_match_dostats_on_adversarial_streams(
        stream, chunking):
    """The tunneled-accelerator flush mode (drains parked one cycle,
    forced on CPU here) under ragged chunking + random mid-stream
    flushes must still agree with the golden model exactly — a
    lost/duplicated parked cycle would show as a count diff."""
    import os

    # manual save/restore (not monkeypatch: hypothesis re-runs the body
    # many times against one function-scoped fixture instance, which
    # trips a health check) — a pre-existing value must survive
    prior = os.environ.get("STREAMBENCH_DEFER_DRAIN_PULL")
    os.environ["STREAMBENCH_DEFER_DRAIN_PULL"] = "1"
    try:
        cfg = default_config(jax_batch_size=B)
        r = as_redis(FakeRedisStore())
        seed_campaigns(r, sorted(set(MAPPING.values())))
        eng = AdAnalyticsEngine(cfg, MAPPING, redis=r)
        assert eng._defer_pull
        rng = pyrandom.Random(4321)
        i = 0
        while i < len(stream):
            step_n = rng.randint(1, chunking * B)
            eng.process_lines(stream[i:i + step_n])
            i += step_n
            if rng.random() < 0.4:
                eng.flush()  # non-final: parks the fresh drain
        eng.close()  # final: materializes every parked cycle
        assert eng.dropped == 0

        golden = gen.dostats(events=stream, mapping_path=None,
                             time_divisor_ms=DIV, mapping=MAPPING)
        got = read_seen_counts(r)
        flat_got = {(c, w // DIV): n
                    for c in got for w, n in got[c].items()}
        flat_want = {(c, b): n for c, per in golden.items()
                     for b, n in per.items()}
        assert flat_got == flat_want
    finally:
        if prior is None:
            os.environ.pop("STREAMBENCH_DEFER_DRAIN_PULL", None)
        else:
            os.environ["STREAMBENCH_DEFER_DRAIN_PULL"] = prior
