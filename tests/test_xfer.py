"""Host->device transfer ledger + shard-skew gauges (ISSUE 9 tentpole,
obs.xfer): exact per-format byte accounting, the timed-sample cadence,
the MEASURED packed/unpacked ratio on a real engine run (the MULTICHIP
packed_col_ratio basis), off-flag bit-identity of sink counts, and the
per-shard skew tracker on the virtual mesh."""

import random

import numpy as np
import pytest

from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import (
    as_redis,
    read_seen_counts,
    seed_campaigns,
)
from streambench_tpu.obs import MetricsRegistry, ShardSkew, TransferLedger


def test_ledger_per_format_accounting_and_ratio():
    reg = MetricsRegistry()
    led = TransferLedger(reg, sample_every=0)
    # packed wire: 2 int32 columns = 8 B/ev; unpacked: 3 int32 + bool
    # = 13 B/ev wire, 16 B/ev at int32 column width
    for _ in range(4):
        led.note_dispatch("packed", 100, 800, 800)
        led.note_dispatch("unpacked", 100, 1300, 1600)
    s = led.summary()
    assert s["dispatches"] == 8
    pk, up = s["formats"]["packed"], s["formats"]["unpacked"]
    assert pk == {"dispatches": 4, "events": 400, "wire_bytes": 3200,
                  "col_bytes": 3200, "bytes_per_event": 8.0,
                  "col_bytes_per_event": 8.0}
    assert up["bytes_per_event"] == 13.0
    assert up["col_bytes_per_event"] == 16.0
    # the ratio is computed on the int32 column basis (the
    # parallel.collectives / MULTICHIP packed_col_ratio accounting),
    # NOT the raw wire basis where bools shrink the denominator
    assert s["packed_unpacked_ratio"] == 0.5
    assert s["ratio_basis"] == "col_bytes"
    assert led.bytes_per_event("packed") == 8.0
    assert reg.counter("streambench_xfer_bytes_total",
                       labels={"format": "packed"}).value == 3200
    assert reg.counter("streambench_xfer_events_total",
                       labels={"format": "unpacked"}).value == 400
    assert reg.gauge("streambench_xfer_bytes_per_event",
                     labels={"format": "unpacked"}).value == 13.0
    # no timing requested: no sampled block
    assert "sampled" not in s and "xfer_ms" not in s


def test_timed_sample_cadence_and_link_rate():
    reg = MetricsRegistry()
    led = TransferLedger(reg, sample_every=4)
    buf = np.zeros(4096, np.int32)
    for _ in range(10):
        led.note_dispatch("packed", 256, buf.nbytes,
                          sample_arrays=[buf])
    assert led.dispatches == 10
    assert led.sampled == 2              # dispatches 4 and 8
    s = led.summary()
    assert s["sampled"] == 2
    assert s["sampled_bytes"] == 2 * buf.nbytes
    assert s["sampled_ms_total"] > 0
    assert s["xfer_mb_s"] > 0            # measured, never inferred
    assert s["xfer_ms"]["count"] == 2
    assert reg.counter("streambench_xfer_sampled_total").value == 2
    # sample_every=0 disables timing even with arrays offered
    led0 = TransferLedger(None, sample_every=0)
    led0.note_dispatch("packed", 1, 8, sample_arrays=[buf])
    assert led0.sampled == 0


def _setup_journal(tmp_path, cfg, events=6000, seed=11):
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(as_redis(FakeRedisStore()), cfg, broker=broker,
                 events_num=events, rng=random.Random(seed),
                 workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    return broker, mapping


def test_engine_measured_ratio_and_off_flag_bit_identity(
        tmp_path, monkeypatch):
    """The acceptance numbers: replaying the SAME journal through a
    packed and a forced separate-column arm measures a col-basis
    packed/unpacked ratio within 10% of 0.5 (it is 0.5 by construction:
    2 int32 wire columns vs 4), and attaching the ledger changes no
    sink count — the ledger only OBSERVES."""
    from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner

    cfg = default_config(jax_batch_size=256, jax_scan_batches=2)
    broker, mapping = _setup_journal(tmp_path, cfg)

    def run(wire, ledger):
        if wire == "unpacked":
            monkeypatch.setenv("STREAMBENCH_WIRE_FORMAT", "unpacked")
        else:
            monkeypatch.delenv("STREAMBENCH_WIRE_FORMAT",
                               raising=False)
        r = as_redis(FakeRedisStore())
        seed_campaigns(r, sorted(set(mapping.values())))
        engine = AdAnalyticsEngine(cfg, mapping, redis=r)
        if ledger is not None:
            engine.attach_obs(MetricsRegistry(), xfer=ledger)
        runner = StreamRunner(engine, broker.reader(cfg.kafka_topic))
        stats = runner.run_catchup()
        engine.close()
        monkeypatch.delenv("STREAMBENCH_WIRE_FORMAT", raising=False)
        return stats, read_seen_counts(r)

    led = TransferLedger(MetricsRegistry(), sample_every=8)
    stats_pk, counts_pk = run("packed", led)
    stats_up, counts_up = run("unpacked", led)
    s = led.summary()
    pk, up = s["formats"]["packed"], s["formats"]["unpacked"]
    assert pk["events"] == up["events"] == 6000
    assert pk["dispatches"] > 0 and up["dispatches"] > 0
    # the engine really dispatched both wire forms of the same journal
    assert pk["wire_bytes"] < up["wire_bytes"]
    # MEASURED ratio within 10% of 0.5 (MULTICHIP_r06 packed_col_ratio)
    assert s["packed_unpacked_ratio"] == pytest.approx(0.5, rel=0.10)
    assert led.sampled > 0 and s["xfer_mb_s"] > 0
    # bit-identity: both wire formats and the un-observed run write
    # identical canonical sink state
    stats_off, counts_off = run("packed", None)
    assert counts_pk == counts_up == counts_off
    assert any(counts_off.values())
    assert (stats_pk.events == stats_up.events == stats_off.events)
    assert (stats_pk.windows_written == stats_off.windows_written)


def test_shard_skew_tracker_accumulates_and_gauges():
    reg = MetricsRegistry()
    sk = ShardSkew(reg, n_shards=4)
    assert sk.summary() is None          # nothing dispatched yet
    sk.note(np.array([10, 0, 0, 0], np.int32),
            np.array([8, 0, 0, 0], np.int32))
    sk.note(np.array([0, 2, 2, 2], np.int32),
            np.array([0, 2, 2, 2], np.int32))
    s = sk.summary()
    assert s["shards"] == 4 and s["dispatches"] == 2
    assert s["rows"] == [8, 2, 2, 2]
    assert s["wanted"] == [10, 2, 2, 2]
    assert s["dropped"] == [2, 0, 0, 0]
    # max/mean: 8 / 3.5
    assert s["imbalance_ratio"] == pytest.approx(8 / 3.5, rel=1e-3)
    assert reg.gauge("streambench_shard_rows",
                     labels={"shard": "0"}).value == 8
    assert reg.gauge("streambench_shard_dropped",
                     labels={"shard": "0"}).value == 2
    assert (reg.gauge("streambench_shard_imbalance_ratio").value
            == pytest.approx(8 / 3.5, rel=1e-3))


def test_sharded_engine_shard_skew_rows_reconcile(tmp_path):
    """The stats kernel variants ride per-shard (wanted, routed) out of
    the real sharded dispatch path: shard rows sum to the events the
    engine counted, per-shard drops reconcile with the global drop
    counter, and the stats arm changes no sink count."""
    import jax

    from streambench_tpu.engine import StreamRunner
    from streambench_tpu.parallel import ShardedWindowEngine, build_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = default_config(jax_batch_size=256, jax_scan_batches=2)
    broker, mapping = _setup_journal(tmp_path, cfg)

    def run(skew):
        mesh = build_mesh(data=2, campaign=4, devices=jax.devices())
        r = as_redis(FakeRedisStore())
        seed_campaigns(r, sorted(set(mapping.values())))
        engine = ShardedWindowEngine(cfg, mapping, mesh, redis=r)
        if skew is not None:
            engine.attach_obs(MetricsRegistry(), shard=skew)
        runner = StreamRunner(engine, broker.reader(cfg.kafka_topic))
        stats = runner.run_catchup()
        dropped = engine.dropped
        engine.close()
        return stats, dropped, read_seen_counts(r)

    sk = ShardSkew(MetricsRegistry(), n_shards=4)
    stats_on, dropped_on, counts_on = run(sk)
    stats_off, dropped_off, counts_off = run(None)
    s = sk.summary()
    assert s is not None and s["shards"] == 4
    assert s["dispatches"] > 0
    # routed rows across shards = events counted on device; wanted -
    # routed = the engine's late/lost drop accounting
    assert sum(s["rows"]) + dropped_on == sum(s["wanted"])
    assert sum(s["wanted"]) > 0
    assert all(r >= 0 for r in s["rows"])
    assert s["imbalance_ratio"] >= 1.0
    # the stats kernels are separate programs; sink output identical
    assert counts_on == counts_off
    assert any(counts_off.values())
    assert stats_on.events == stats_off.events
    assert dropped_on == dropped_off


def test_collector_journals_xfer_and_shard_blocks():
    from streambench_tpu.metrics import FaultCounters
    from streambench_tpu.obs import engine_collector
    from streambench_tpu.trace import Tracer

    class _Eng:
        tracer = Tracer()
        faults = FaultCounters()
        events_processed = 0
        _obs_hist = None

        def telemetry(self):
            return {"events": 0, "windows_written": 0,
                    "watermark_lag_ms": None, "sink_dirty_rows": 0,
                    "pending_rows": 0}

    eng = _Eng()
    led = TransferLedger(None, sample_every=0)
    led.note_dispatch("packed", 10, 80)
    sk = ShardSkew(None, n_shards=2)
    eng._obs_xfer = led
    eng._obs_shard = sk
    rec: dict = {}
    engine_collector(eng, registry=MetricsRegistry())(rec, 1.0)
    assert rec["xfer"]["formats"]["packed"]["events"] == 10
    assert "shard_skew" not in rec       # no dispatch yet -> no block
    sk.note(np.array([1, 1], np.int32), np.array([1, 1], np.int32))
    rec2: dict = {}
    engine_collector(eng, registry=MetricsRegistry())(rec2, 1.0)
    assert rec2["shard_skew"]["rows"] == [1, 1]
    # without the ledgers the keys are absent — old journals unchanged
    eng2 = _Eng()
    rec3: dict = {}
    engine_collector(eng2, registry=MetricsRegistry())(rec3, 1.0)
    assert "xfer" not in rec3 and "shard_skew" not in rec3
