"""The broker contract, pinned: one test suite, N broker implementations.

``FileBroker`` (hermetic, always available) and ``KafkaBroker`` (real
cluster via confluent-kafka) must be interchangeable behind the same
reader/writer/broker surface — the reference swaps its Kafka source for a
file source the same way (``FileBasedDataSource`` vs the Kafka consumer,
``AdvertisingTopologyNative.java:88-99``).  Real-KafkaBroker rows run
only when the client library AND a live broker
(STREAMBENCH_KAFKA_BROKERS) exist; the guard behavior itself is always
tested.  The fake rows (ISSUE 20) run ``KafkaBroker`` for REAL — same
adapter code, confluent surface served by ``io.fakekafka`` — once
through the in-process injection seam (``kafka.use_clients``) and once
over a live TCP broker thread, so the contract executes in every image.
"""

import os

import pytest

from streambench_tpu.io import fakekafka, kafka
from streambench_tpu.io.journal import FileBroker


def _file_broker(tmp_path):
    return FileBroker(str(tmp_path / "broker"))


def _kafka_broker(tmp_path):
    brokers = os.environ.get("STREAMBENCH_KAFKA_BROKERS")
    if not kafka.available():
        pytest.skip("confluent-kafka not installed")
    if not brokers:
        pytest.skip("no live broker (set STREAMBENCH_KAFKA_BROKERS)")
    return kafka.KafkaBroker(brokers)


#: TCP broker threads started by a row, stopped by the autouse fixture
_SERVERS: list = []


def _fake_inproc_broker(tmp_path):
    # the injection seam itself is under test: install the fake client
    # bundle module-wide and let KafkaBroker resolve Producer/Consumer/
    # AdminClient through ``_clients()`` exactly as the real path would
    kafka.use_clients(fakekafka.clients(fakekafka.FakeCluster()))
    return kafka.KafkaBroker(fakekafka.INPROC)


def _fake_tcp_broker(tmp_path):
    # a real socket between adapter and broker: the FakeKafkaServer
    # thread speaks the record protocol the standalone START_KAFKA
    # process serves
    srv = fakekafka.FakeKafkaServer()
    srv.start()
    _SERVERS.append(srv)
    return kafka.KafkaBroker(f"{srv.host}:{srv.port}",
                             clients=fakekafka.clients())


@pytest.fixture(autouse=True)
def _reset_fake_kafka():
    yield
    kafka.use_clients(None)
    while _SERVERS:
        _SERVERS.pop().stop()


BROKERS = [_file_broker, _kafka_broker, _fake_inproc_broker,
           _fake_tcp_broker]


@pytest.mark.parametrize("make", BROKERS)
def test_roundtrip_and_tailing(tmp_path, make):
    b = make(tmp_path)
    b.create_topic("t", partitions=1)
    w = b.writer("t")
    r = b.reader("t")
    w.append(b"one")
    w.append_many([b"two", b"three\n"])
    w.flush()
    got = r.poll_blocking(timeout_s=5.0, max_records=2)
    got += r.poll_blocking(timeout_s=5.0)
    assert got == [b"one", b"two", b"three"]
    # tail: nothing new yet
    assert r.poll() == []
    w.append(b"four")
    w.flush()
    assert r.poll_blocking(timeout_s=5.0) == [b"four"]
    w.close()
    r.close()


@pytest.mark.parametrize("make", BROKERS)
def test_offset_seek_resume(tmp_path, make):
    b = make(tmp_path)
    b.create_topic("s", partitions=1)
    w = b.writer("s")
    w.append_many([b"a", b"b", b"c", b"d"])
    w.flush()
    r = b.reader("s")
    assert r.poll_blocking(timeout_s=5.0, max_records=2) == [b"a", b"b"]
    mark = r.offset  # the checkpoint unit: opaque monotonic int
    assert r.poll_blocking(timeout_s=5.0) == [b"c", b"d"]
    r.seek(mark)
    assert r.poll_blocking(timeout_s=5.0) == [b"c", b"d"]
    # a fresh reader from the marked offset sees the same suffix
    r2 = b.reader("s", offset=mark)
    assert r2.poll_blocking(timeout_s=5.0) == [b"c", b"d"]
    r.close()
    r2.close()
    w.close()


@pytest.mark.parametrize("make", BROKERS)
def test_partitions_and_multi_reader(tmp_path, make):
    b = make(tmp_path)
    b.create_topic("p", partitions=3)
    assert b.partitions("p") == [0, 1, 2]
    for part in range(3):
        w = b.writer("p", part)
        w.append(f"m{part}".encode())
        w.flush()
        w.close()
    with b.multi_reader("p") as mr:
        got = set()
        for _ in range(50):
            got.update(mr.poll())
            if len(got) == 3:
                break
        assert got == {b"m0", b"m1", b"m2"}
    assert set(b.read_all("p")) == {b"m0", b"m1", b"m2"}


def test_unavailable_guard_raises_actionably():
    if kafka.available():  # pragma: no cover - image has no confluent-kafka
        pytest.skip("confluent-kafka IS installed here")
    assert not kafka.available()
    with pytest.raises(kafka.KafkaUnavailableError, match="FileBroker"):
        kafka.KafkaWriter("localhost:9092", "t")
    with pytest.raises(kafka.KafkaUnavailableError):
        kafka.KafkaReader("localhost:9092", "t")
    with pytest.raises(kafka.KafkaUnavailableError):
        kafka.KafkaBroker("localhost:9092")


def test_make_broker_switch_point(tmp_path):
    # no brokers named -> the hermetic file journal
    b = kafka.make_broker(None, str(tmp_path / "j"))
    assert isinstance(b, FileBroker)
    b2 = kafka.make_broker("", str(tmp_path / "j2"))
    assert isinstance(b2, FileBroker)
    if not kafka.available():
        # brokers named but no client library: ERROR, never a silent
        # file-journal pretending to be the configured cluster
        with pytest.raises(kafka.KafkaUnavailableError,
                           match="KAFKA_BROKERS"):
            kafka.make_broker("localhost:9092", str(tmp_path / "j3"))


def test_engine_cli_reaches_kafka_adapter(tmp_path):
    """kafka.bootstrap in the config must route the engine CLI through
    make_broker — in this image that means the actionable
    KafkaUnavailableError, not a quiet FileBroker."""
    import subprocess
    import sys

    from streambench_tpu.config import write_local_conf

    if kafka.available():  # pragma: no cover
        pytest.skip("confluent-kafka IS installed here")
    conf = tmp_path / "conf.yaml"
    write_local_conf(conf, {"kafka.bootstrap": "kafkahost:9092",
                            "redis.host": ":inprocess:"})
    # engine needs a mapping file; write a minimal one
    (tmp_path / "ad-to-campaign-ids.txt").write_text("ad1,c1\n")
    p = subprocess.run(
        [sys.executable, "-m", "streambench_tpu.engine",
         "--confPath", str(conf), "--workdir", str(tmp_path),
         "--catchup"],
        capture_output=True, text=True, timeout=120,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert p.returncode != 0
    assert "KafkaUnavailable" in p.stderr or "confluent-kafka" in p.stderr
