"""Snapshot-shipped reach read replicas (reach/replica.py, ISSUE 14):
shipper cadence + epoch-bump immediacy, the ship-log tailer (torn
tails), replica serving with plane_epoch/staleness_ms stamps, the
staleness-bound shed property (a reply's plane_epoch is never older
than the bound allows — stale planes shed instead), and shed-or-answer
exactness under a chaos storm with a replica attached."""

import json
import os
import random
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from streambench_tpu.config import default_config
from streambench_tpu.dimensions.store import DurableDimensionStore
from streambench_tpu.ops import minhash
from streambench_tpu.reach.replica import (
    DEFAULT_MAX_STALENESS_MS,
    ReachReplica,
    ShipLogTailer,
    SnapshotShipper,
    decode_ship_record,
)
from streambench_tpu.utils.ids import now_ms

NAMES = ["c0", "c1", "c2"]


def fold_state(users, C=3, k=16, R=16):
    st = minhash.init_state(C, k, R)
    join = jnp.asarray(np.arange(C, dtype=np.int32))
    B = len(users)
    return minhash.step(
        st, join,
        jnp.asarray(np.zeros(B, np.int32)),
        jnp.asarray(np.asarray(users, np.int32)),
        jnp.zeros(B, jnp.int32), jnp.zeros(B, jnp.int32),
        jnp.ones(B, bool))


# ------------------------------------------------------------ shipper
def test_shipper_cadence_and_epoch_bump(tmp_path):
    store = DurableDimensionStore(str(tmp_path))
    ship = SnapshotShipper(store, NAMES, interval_ms=10_000)
    st = fold_state([1, 2, 3])
    assert ship.note_state(st.mins, st.registers, 0, 70_000)
    # within the cadence, same epoch: suppressed
    assert not ship.due(0)
    assert not ship.note_state(st.mins, st.registers, 0, 70_000)
    # an epoch bump ships IMMEDIATELY (replicas must learn about a
    # restore within one poll, not one cadence)
    assert ship.due(1)
    assert ship.note_state(st.mins, st.registers, 1, 70_000)
    # force bypasses the cadence (the writer's close-time ship)
    assert ship.note_state(st.mins, st.registers, 1, 80_000,
                           force=True)
    assert ship.ships == 3
    store.close()
    # the shipped record is the PR 10 base64 plane record + watermark
    rec = DurableDimensionStore(str(tmp_path)).reach_sketches()
    assert rec["epoch"] == 1 and rec["watermark"] == 80_000
    assert np.array_equal(rec["mins"], np.asarray(st.mins))
    assert np.array_equal(rec["registers"], np.asarray(st.registers))


def test_tailer_incremental_and_torn_tail(tmp_path):
    store = DurableDimensionStore(str(tmp_path))
    ship = SnapshotShipper(store, NAMES, interval_ms=1)
    tail = ShipLogTailer(store.path)
    assert tail.poll() is None
    st = fold_state([1])
    ship.note_state(st.mins, st.registers, 0, 1)
    rec = tail.poll()
    assert rec is not None and rec["epoch"] == 0
    assert tail.poll() is None            # nothing new
    ship.note_state(st.mins, st.registers, 1, 2)
    ship.note_state(st.mins, st.registers, 2, 3)
    rec = tail.poll()
    assert rec["epoch"] == 2              # newest of the batch wins
    # a torn tail line stays buffered until its newline lands
    good = json.dumps({"kind": "reach_sketch", "t": now_ms(),
                       "epoch": 7, "c": NAMES, "k": 16, "r": 16,
                       "mins": rec_b64(st.mins),
                       "regs": rec_b64(st.registers, np.int32)})
    with open(store.path, "a") as f:
        f.write(good[: len(good) // 2])
        f.flush()
    assert tail.poll() is None
    with open(store.path, "a") as f:
        f.write(good[len(good) // 2:] + "\n")
    assert tail.poll()["epoch"] == 7
    store.close()


def rec_b64(arr, dtype=np.uint32):
    import base64

    return base64.b64encode(
        np.ascontiguousarray(np.asarray(arr), dtype=dtype).tobytes()
    ).decode()


# ------------------------------------------------------------ replica
def ask(host, port, campaigns, qid, op="union"):
    from streambench_tpu.dimensions.pubsub import PubSubClient

    c = PubSubClient(host, port, timeout_s=20)
    c.request({"type": "reach", "campaigns": campaigns, "op": op,
               "id": qid})
    out = c.recv()["data"]
    c.close()
    return out


def test_replica_serves_epoch_stamped_and_staleness_bounded(tmp_path):
    store = DurableDimensionStore(str(tmp_path))
    ship = SnapshotShipper(store, NAMES, interval_ms=1)
    st = fold_state([10, 20, 30])
    shipped_at = now_ms()
    ship.note_state(st.mins, st.registers, 3, 70_000)
    # deterministic tailing: start only the endpoint, poll by hand
    rep = ReachReplica(store.path, poll_ms=20_000)
    rep.pubsub.start()
    try:
        assert rep.poll_once()
        host, port = rep.address
        d = ask(host, port, ["c0", "c1"], 1)
        assert "estimate" in d
        # the staleness-bound property: the reply's plane epoch is the
        # newest shipped epoch and its staleness honestly measures the
        # record age (bounded by cadence + poll in a healthy loop)
        assert d["plane_epoch"] == 3
        assert 0 <= d["staleness_ms"] <= (now_ms() - shipped_at) + 50
        assert d["staleness_ms"] <= DEFAULT_MAX_STALENESS_MS
        # expected estimate == single-device evaluation of the planes
        from streambench_tpu.reach import query as rq

        m = np.zeros((1, 3), bool)
        m[0, :2] = True
        want, *_ = rq.batch_query(st.mins, st.registers,
                                  jnp.asarray(m),
                                  jnp.asarray([False]))
        assert d["estimate"] == round(float(np.asarray(want)[0]), 2)
    finally:
        rep.close()
        store.close()


def test_replica_sheds_before_first_epoch_and_past_bound(tmp_path):
    """The shed-not-stale contract: no epoch loaded -> shed; planes
    older than the bound -> shed with reason + evidence; a fresh ship
    resumes answering."""
    store = DurableDimensionStore(str(tmp_path))
    ship = SnapshotShipper(store, NAMES, interval_ms=1)
    rep = ReachReplica(store.path, poll_ms=20_000,
                       max_staleness_ms=300)
    rep.pubsub.start()
    try:
        host, port = rep.address
        d = ask(host, port, ["c0"], 1)
        assert d.get("shed") and d.get("reason") == "stale"
        assert d["plane_epoch"] is None
        assert rep.shed_before_load == 1

        st = fold_state([1, 2])
        ship.note_state(st.mins, st.registers, 0, 1)
        assert rep.poll_once()
        d = ask(host, port, ["c0"], 2)
        assert "estimate" in d and d["plane_epoch"] == 0

        # age the planes past the bound: shed, with the evidence
        time.sleep(0.4)
        d = ask(host, port, ["c0"], 3)
        assert d.get("shed") and d.get("reason") == "stale"
        assert d["plane_epoch"] == 0 and d["staleness_ms"] > 300
        assert rep.server.shed_stale >= 1

        # a fresh ship resumes service on the new record
        ship.note_state(st.mins, st.registers, 1, 2)
        assert rep.poll_once()
        d = ask(host, port, ["c0"], 4)
        assert "estimate" in d and d["plane_epoch"] == 1
        # invariants: every query shed or answered, none lost
        s = rep.server.summary()
        assert s["served"] + s["shed"] == 3  # (q2..q4; q1 pre-server)
    finally:
        rep.close()
        store.close()


def test_replica_chaos_storm_sheds_or_answers_exactly(tmp_path):
    """Chaos with a replica attached: concurrent epoch bumps (the
    restore signature) + re-ships while a query storm runs against the
    replica.  Every query sheds or answers; every answer's plane_epoch
    is one of the shipped epochs; after the dust settles answers carry
    the LIVE epoch."""
    from streambench_tpu.dimensions.pubsub import PubSubClient

    store = DurableDimensionStore(str(tmp_path))
    ship = SnapshotShipper(store, NAMES, interval_ms=1)
    states = {e: fold_state(list(range(1, 3 + e * 5))) for e in range(5)}
    ship.note_state(states[0].mins, states[0].registers, 0, 1)
    rep = ReachReplica(store.path, poll_ms=5,
                       max_staleness_ms=5_000).start()
    stop = threading.Event()

    def chaos():
        rng = random.Random(9)
        e = 0
        while not stop.is_set():
            e = min(e + rng.choice([0, 1]), 4)
            st = states[e]
            ship.note_state(st.mins, st.registers, e,
                            1 + e, force=True)
            time.sleep(0.02)

    t = threading.Thread(target=chaos)
    t.start()
    answers = []
    try:
        host, port = rep.address
        c = PubSubClient(host, port, timeout_s=30)
        n = 120
        for i in range(n):
            c.request({"type": "reach",
                       "campaigns": [NAMES[i % 3]],
                       "op": "union", "id": i})
            answers.append(c.recv()["data"])
            time.sleep(0.002)
        c.close()
    finally:
        stop.set()
        t.join(timeout=10)
    assert len(answers) == 120
    assert all(("estimate" in d) or d.get("shed") for d in answers)
    served = [d for d in answers if "estimate" in d]
    assert served, "storm served nothing"
    assert all(d["plane_epoch"] in range(5) for d in served)
    assert all("staleness_ms" in d for d in served)
    # settle: the poller converges on the final shipped record
    time.sleep(0.2)
    rep.poll_once()
    d = ask(*rep.address, ["c0"], "final")
    assert "estimate" in d
    assert d["plane_epoch"] == rep.server.epoch
    s = rep.summary()
    assert s["serve"]["served"] + s["serve"]["shed"] \
        + s["shed_before_load"] == 121
    rep.close()
    store.close()
