"""Sketch-aggregation engines end-to-end (BASELINE configs #2-#4): HLL
distinct counts vs exact distinct, sliding-window counts vs a golden
model with t-digest quantiles, and session heavy hitters vs exact
per-user clicks."""

import json
import random

import numpy as np

from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.engine import StreamRunner
from streambench_tpu.engine.sketches import (
    HLLDistinctEngine,
    SessionCMSEngine,
    SlidingTDigestEngine,
)
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import as_redis, read_seen_counts


def setup_run(tmp_path, events=12_000, batch=512, **cfg_kw):
    cfg = default_config(jax_batch_size=batch, **cfg_kw)
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=events,
                 rng=random.Random(77), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    lines = [l for l in broker.read_all(cfg.kafka_topic)]
    return cfg, r, broker, mapping, lines


def test_hll_distinct_engine_close_to_exact(tmp_path):
    cfg, r, broker, mapping, lines = setup_run(tmp_path)
    eng = HLLDistinctEngine(cfg, mapping, redis=r, registers=256)
    runner = StreamRunner(eng, broker.reader(cfg.kafka_topic))
    stats = runner.run_catchup()
    eng.close()
    assert stats.events == 12_000 and eng.dropped == 0

    # golden: exact distinct users per (campaign, window) over views
    golden: dict[tuple[str, int], set] = {}
    for line in lines:
        ev = json.loads(line)
        if ev["event_type"] != "view":
            continue
        key = (mapping[ev["ad_id"]],
               int(ev["event_time"]) // 10_000 * 10_000)
        golden.setdefault(key, set()).add(ev["user_id"])

    got = read_seen_counts(r)
    assert set((c, w) for c in got for w in got[c]) == set(golden)
    rel_errs = []
    for (c, w), users in golden.items():
        est = got[c][w]
        rel_errs.append(abs(est - len(users)) / max(len(users), 1))
    # HLL with 256 registers: ~6.5% std error; mean well under that
    assert np.mean(rel_errs) < 0.1, np.mean(rel_errs)


def test_hll_absolute_reflush_does_not_accumulate(tmp_path):
    """Flushing twice mid-window must not double the estimate (HSET, not
    HINCRBY)."""
    cfg, r, broker, mapping, lines = setup_run(tmp_path, events=2000)
    eng = HLLDistinctEngine(cfg, mapping, redis=r)
    runner = StreamRunner(eng, broker.reader(cfg.kafka_topic),
                          flush_interval_ms=0)  # flush every poll round
    runner.run_catchup()
    eng.close()
    golden_total = len({(mapping[json.loads(l)["ad_id"]],
                         int(json.loads(l)["event_time"]) // 10_000)
                        for l in lines if json.loads(l)["event_type"] == "view"})
    got = read_seen_counts(r)
    n_windows = sum(len(v) for v in got.values())
    assert n_windows == golden_total  # windows exist once, not duplicated
    # every estimate is near its exact distinct count, impossible if
    # re-flushes accumulated
    exact: dict[tuple[str, int], set] = {}
    for line in lines:
        ev = json.loads(line)
        if ev["event_type"] == "view":
            exact.setdefault(
                (mapping[ev["ad_id"]],
                 int(ev["event_time"]) // 10_000 * 10_000),
                set()).add(ev["user_id"])
    for (c, w), users in exact.items():
        assert got[c][w] <= 2 * len(users)


def test_sliding_tdigest_engine_counts_and_quantiles(tmp_path):
    cfg, r, broker, mapping, lines = setup_run(tmp_path, events=6000)
    eng = SlidingTDigestEngine(cfg, mapping, redis=r, slide_ms=1000)
    runner = StreamRunner(eng, broker.reader(cfg.kafka_topic))
    stats = runner.run_catchup()
    eng.close()
    assert stats.events == 6000 and eng.dropped == 0

    # golden: each view lands in the 10 sliding windows covering it
    golden: dict[tuple[str, int], int] = {}
    for line in lines:
        ev = json.loads(line)
        if ev["event_type"] != "view":
            continue
        c = mapping[ev["ad_id"]]
        t = int(ev["event_time"])
        for k in range(10):
            start = (t // 1000 - k) * 1000
            if start + 10_000 > t >= start:
                golden[(c, start)] = golden.get((c, start), 0) + 1
    got = read_seen_counts(r)
    flat = {(c, w): n for c in got for w, n in got[c].items()}
    assert flat == golden

    # quantiles dumped per campaign, ordered p50 <= p90 <= p99
    q = eng.quantiles()
    assert q.shape == (eng.encoder.num_campaigns, 3)
    assert (q[:, 0] <= q[:, 1] + 1e-3).all() and (q[:, 1] <= q[:, 2] + 1e-3).all()
    table = r.hgetall(f"{cfg.redis_hashtable}_quantiles")
    assert len(table) == eng.encoder.num_campaigns * 3


def test_session_cms_engine_heavy_hitters(tmp_path):
    cfg, r, broker, mapping, lines = setup_run(tmp_path, events=8000)
    eng = SessionCMSEngine(cfg, mapping, redis=r, top_k=8)
    runner = StreamRunner(eng, broker.reader(cfg.kafka_topic))
    stats = runner.run_catchup()
    eng.close()
    assert stats.events == 8000 and eng.dropped == 0

    # golden sessionization: per user, split click counts on >30s gaps
    # (generator emits 10ms apart so each user's events form ONE session;
    # total clicks per user == sum of their session clicks)
    clicks: dict[str, int] = {}
    for line in lines:
        ev = json.loads(line)
        if ev["event_type"] == "click":
            clicks[ev["user_id"]] = clicks.get(ev["user_id"], 0) + 1
    assert eng.session_clicks == sum(clicks.values())
    assert eng.sessions_closed >= len(clicks) > 0

    hh = dict(eng.heavy_hitters())
    assert hh  # someone clicked
    true_top = max(clicks.values())
    # CMS overestimates only; top-k estimates must dominate true top talliers
    for user, est in hh.items():
        assert est >= clicks.get(user, 0)
    assert max(hh.values()) >= true_top
    table = r.hgetall(f"{cfg.redis_hashtable}_hh")
    assert len(table) == len(hh)


def test_hll_scan_matches_per_batch():
    """HLL's scanned kernel must produce the same registers as the
    per-batch step (process_chunk with scan vs process_lines)."""
    import random as pyrandom

    import numpy as np

    from streambench_tpu.config import default_config
    from streambench_tpu.datagen import gen
    from streambench_tpu.engine.sketches import HLLDistinctEngine

    campaigns = [f"c{i}" for i in range(5)]
    mapping = {f"ad{i}": campaigns[i % 5] for i in range(20)}
    src = gen.EventSource(ads=list(mapping),
                          user_ids=[f"u{i}" for i in range(200)],
                          page_ids=["p"], rng=pyrandom.Random(4))
    lines = [src.event_at(1_700_000_000_000 + 15 * i).encode()
             for i in range(3000)]

    cfg = default_config(jax_batch_size=256, jax_scan_batches=4)
    a = HLLDistinctEngine(cfg, mapping, campaigns=campaigns)
    for off in range(0, len(lines), 256):
        a.process_lines(lines[off:off + 256])

    b = HLLDistinctEngine(cfg, mapping, campaigns=campaigns)
    assert b.SCAN_SUPPORTED
    b.process_chunk(lines)

    np.testing.assert_array_equal(np.asarray(a.state.registers),
                                  np.asarray(b.state.registers))
    assert int(a.state.watermark) == int(b.state.watermark)


def test_session_fused_scan_matches_per_batch():
    """The fused session+CMS+ring scan must agree with the per-batch
    path on every piece of state."""
    import random as pyrandom

    import numpy as np

    from streambench_tpu.config import default_config
    from streambench_tpu.datagen import gen
    from streambench_tpu.engine.sketches import SessionCMSEngine

    campaigns = [f"c{i}" for i in range(5)]
    mapping = {f"ad{i}": campaigns[i % 5] for i in range(20)}
    src = gen.EventSource(ads=list(mapping),
                          user_ids=[f"u{i}" for i in range(50)],
                          page_ids=["p"], rng=pyrandom.Random(9))
    # 40 ms stride x 50 users -> 2 s between a user's events; use a
    # small gap so sessions actually close mid-stream
    lines = [src.event_at(1_700_000_000_000 + 40 * i).encode()
             for i in range(4000)]

    cfg = default_config(jax_batch_size=256, jax_scan_batches=4)
    a = SessionCMSEngine(cfg, mapping, campaigns=campaigns, gap_ms=1_000)
    for off in range(0, len(lines), 256):
        a.process_lines(lines[off:off + 256])

    b = SessionCMSEngine(cfg, mapping, campaigns=campaigns, gap_ms=1_000)
    assert b.SCAN_SUPPORTED
    b.process_chunk(lines)

    assert a.sessions_closed == b.sessions_closed > 0
    assert a.session_clicks == b.session_clicks > 0
    np.testing.assert_array_equal(np.asarray(a.cms.table),
                                  np.asarray(b.cms.table))
    np.testing.assert_array_equal(np.asarray(a.state.last_time),
                                  np.asarray(b.state.last_time))
    # Candidate rings: the per-batch path's exact top-M ring (capacity
    # 128 >= 50 users) holds EVERY user that closed a session; the scan
    # path funnels candidates through the chunk-local hash table, where
    # a salted collision may shadow a key for that chunk — so its ring
    # is a subset, and must still cover nearly all closers (a key is
    # only missing if shadowed in every chunk where it closed).
    ka = np.asarray(a.topk.keys)
    kb = np.asarray(b.topk.keys)
    sa = set(ka[ka >= 0].tolist())
    sb = set(kb[kb >= 0].tolist())
    assert sb <= sa
    assert len(sb) >= 0.8 * len(sa)


def test_sliding_fused_scan_matches_per_batch_counts():
    """The fused sliding+digest scan must agree with the per-batch path
    on window counts (digest samples share semantics but not identical
    host timestamps, so compare structure not bytes there)."""
    import random as pyrandom

    import numpy as np

    from streambench_tpu.config import default_config
    from streambench_tpu.datagen import gen
    from streambench_tpu.engine.sketches import SlidingTDigestEngine

    campaigns = [f"c{i}" for i in range(5)]
    mapping = {f"ad{i}": campaigns[i % 5] for i in range(20)}
    src = gen.EventSource(ads=list(mapping),
                          user_ids=[f"u{i}" for i in range(50)],
                          page_ids=["p"], rng=pyrandom.Random(6))
    lines = [src.event_at(1_700_000_000_000 + 10 * i).encode()
             for i in range(3000)]

    cfg = default_config(jax_batch_size=256, jax_scan_batches=4)
    a = SlidingTDigestEngine(cfg, mapping, campaigns=campaigns)
    for off in range(0, len(lines), 256):
        a.process_lines(lines[off:off + 256])

    b = SlidingTDigestEngine(cfg, mapping, campaigns=campaigns)
    assert b.SCAN_SUPPORTED
    b.process_chunk(lines)

    # the two paths drain at different points; compare the fully
    # materialized pending deltas, not raw device counts
    for eng in (a, b):
        eng._drain_device()
        eng._materialize_drains()
    pa, pb = a.pending_counts(), b.pending_counts()
    assert pa == pb
    assert sum(pa.values()) > 0
    assert int(a.state.watermark) == int(b.state.watermark)
    # digests saw the same sample COUNT per campaign (values differ by
    # host-clock capture instants)
    wa = np.asarray(a.digest.weights).sum(axis=1)
    wb = np.asarray(b.digest.weights).sum(axis=1)
    np.testing.assert_array_equal(wa, wb)


def test_session_latency_quantile_reads_histogram():
    """latency_quantile interpolates the device histogram correctly and
    reports (values, count); empty histogram reports ([], 0)."""
    import jax.numpy as jnp

    from streambench_tpu.engine.sketches import (
        LAT_BIN_MS,
        LAT_BINS,
        SessionCMSEngine,
    )
    from streambench_tpu.config import default_config

    mapping = {f"ad{i}": f"c{i % 5}" for i in range(20)}
    eng = SessionCMSEngine(default_config(), mapping)
    assert eng.latency_quantile((0.5, 0.99)) == ([], 0)

    hist = [0] * LAT_BINS
    hist[0] = 50   # [0, 250) ms
    hist[3] = 50   # [750, 1000) ms
    eng.lat_hist = jnp.asarray(hist, jnp.int32)
    vals, n = eng.latency_quantile((0.5, 1.0))
    assert n == 100
    # p50 sits at the boundary of bin 0; p100 at the top of bin 3
    assert 0 <= vals[0] <= 1 * LAT_BIN_MS
    assert 3 * LAT_BIN_MS <= vals[1] <= 4 * LAT_BIN_MS
    # overflow bin reports its lower edge
    hist = [0] * LAT_BINS
    hist[LAT_BINS - 1] = 10
    eng.lat_hist = jnp.asarray(hist, jnp.int32)
    vals, n = eng.latency_quantile((0.5,))
    assert n == 10 and vals[0] == (LAT_BINS - 1) * LAT_BIN_MS
