"""Span tracing (ISSUE 8 tentpole, obs.spans): the bounded ring stays
bounded with counted evictions, Tracer spans forward thread-aware, the
Chrome trace export validates against the perfetto-required schema,
span totals agree with the Tracer's aggregate stage table on a real
run, the flight recorder embeds the span tail, and the ``obs trace``
CLI validates/summarizes."""

import json
import random
import threading
import time

import pytest

from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import as_redis
from streambench_tpu.obs import MetricsRegistry, SpanTracer
from streambench_tpu.obs.spans import (
    summarize_trace,
    validate_chrome_trace,
)
from streambench_tpu.trace import Tracer


def test_ring_bounded_and_evictions_counted():
    sp = SpanTracer(capacity=16)
    for i in range(40):
        sp.add(f"s{i}", i * 1000, 500)
    assert len(sp) == 16
    assert sp.dropped == 24
    # oldest evicted, newest kept
    names = [s["name"] for s in sp.snapshot()]
    assert names[0] == "s24" and names[-1] == "s39"
    assert [s["name"] for s in sp.tail(3)] == ["s37", "s38", "s39"]


def test_tracer_sink_forwards_with_thread_identity():
    sp = SpanTracer(capacity=64)
    tr = Tracer()
    sp.attach(tr)
    with tr.span("encode"):
        pass

    def other():
        with tr.span("redis_flush"):
            pass

    t = threading.Thread(target=other, name="fake-writer")
    t.start()
    t.join()
    spans = sp.snapshot()
    assert [s["name"] for s in spans] == ["encode", "redis_flush"]
    assert spans[0]["cat"] == "stage"
    assert spans[1]["thread"] == "fake-writer"
    assert spans[0]["tid"] != spans[1]["tid"]
    # the aggregate table recorded the same spans (sink is additive)
    snap = tr.snapshot()
    assert snap["encode"][0] == 1 and snap["redis_flush"][0] == 1
    # an unattached tracer stays sink-less (the default-off contract)
    assert Tracer().sink is None


def test_chrome_trace_schema_and_thread_metadata():
    sp = SpanTracer(capacity=64)
    # start stamps are perf_counter_ns values; ts is relative to the
    # tracer's construction epoch
    sp.add("encode", sp._t0_ns + 1_000_000, 250_000, cat="stage")
    sp.add("device_step", sp._t0_ns + 2_000_000, 100_000, cat="stage",
           args={"batch": 1})
    doc = sp.chrome_trace(run="unit")
    assert validate_chrome_trace(doc) == []
    evs = doc["traceEvents"]
    ms = [e for e in evs if e["ph"] == "M"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(ms) == 1 and ms[0]["name"] == "thread_name"
    assert len(xs) == 2
    # microsecond clock: 250000 ns span -> 250 us dur
    enc = next(e for e in xs if e["name"] == "encode")
    assert enc["dur"] == pytest.approx(250.0)
    assert enc["ts"] == pytest.approx(1000.0)
    assert doc["otherData"]["run"] == "unit"
    # every X event's tid has a thread_name metadata row
    assert {e["tid"] for e in xs} <= {e["tid"] for e in ms}


def test_validate_rejects_malformed_docs():
    assert validate_chrome_trace([]) != []
    assert validate_chrome_trace({"no": 1}) != []
    assert validate_chrome_trace(
        {"traceEvents": [{"name": "x", "ph": "X", "pid": 1,
                          "tid": 1}]}) != []   # X without ts/dur
    assert validate_chrome_trace(
        {"traceEvents": [{"name": "x", "ph": "Q", "pid": 1, "tid": 1,
                          "ts": 0, "dur": 1}]}) != []  # unknown phase


def test_trace_cli_summarizes_and_rejects(tmp_path, capsys):
    from streambench_tpu.obs.__main__ import main as obs_main

    sp = SpanTracer(capacity=64)
    with sp.span("encode"):
        time.sleep(0.002)
    sp.add("device_step", 0, 1_000_000, cat="stage")
    path = str(tmp_path / "trace_unit.json")
    sp.dump(path, run="cli")
    assert obs_main(["trace", path]) == 0
    out = capsys.readouterr().out
    assert "span trace" in out and "encode" in out
    assert obs_main(["trace", path, "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["events"] == 2 and "encode" in parsed["by_name"]
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        f.write('{"traceEvents": "nope"}')
    assert obs_main(["trace", bad]) == 2
    notjson = str(tmp_path / "notjson.json")
    with open(notjson, "w") as f:
        f.write("}{")
    assert obs_main(["trace", notjson]) == 2


def test_flightrec_dump_embeds_span_tail(tmp_path):
    from streambench_tpu.obs import FlightRecorder

    sp = SpanTracer(capacity=256)
    for i in range(100):
        sp.add(f"s{i}", i * 1000, 10)
    fr = FlightRecorder(str(tmp_path), capacity=32)
    fr.record("tick", events=1)
    fr.span_source = sp.tail
    path = fr.dump("crash", terminal={"event": "crash", "error": "x"})
    recs = [json.loads(l) for l in open(path)]
    # spans block sits just before the terminal record
    assert recs[-1]["kind"] == "fault"
    assert recs[-2]["kind"] == "spans"
    spans = recs[-2]["spans"]
    assert len(spans) == FlightRecorder.SPAN_TAIL
    assert spans[-1]["name"] == "s99"
    # the spans record is dump-only: the ring itself keeps capacity
    # for feeder records and a second dump gets a FRESH tail
    sp.add("s100", 1, 1)
    path2 = fr.dump("crash")
    recs2 = [json.loads(l) for l in open(path2)]
    span_recs = [r for r in recs2 if r["kind"] == "spans"]
    assert len(span_recs) == 1
    assert span_recs[0]["spans"][-1]["name"] == "s100"
    # a broken span source must not eat the dump
    fr.span_source = lambda n: (_ for _ in ()).throw(RuntimeError())
    path3 = fr.dump("crash")
    assert [json.loads(l) for l in open(path3)]


def test_engine_run_spans_match_tracer_aggregates(tmp_path):
    """Catchup run with spans attached: the per-stage sum of exported
    spans equals the Tracer's aggregate table (same clock, same spans
    — the consistency contract between the timeline and the stage
    report), and the trace file validates."""
    from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner

    cfg = default_config(jax_batch_size=256, jax_scan_batches=2)
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=6000,
                 rng=random.Random(5), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    engine = AdAnalyticsEngine(cfg, mapping, redis=r)
    reg = MetricsRegistry()
    spans = SpanTracer(capacity=65536, registry=reg)
    engine.attach_obs(reg, spans=spans)
    runner = StreamRunner(engine, broker.reader(cfg.kafka_topic),
                          spans=spans)
    runner.run_catchup()
    engine.close()
    assert spans.dropped == 0   # capacity sized to hold the whole run
    doc = spans.chrome_trace(run="test")
    assert validate_chrome_trace(doc) == []
    s = summarize_trace(doc)
    # read/encode/dispatch/flush/sink all present on the timeline
    assert "journal_read" in s["by_name"]
    assert "encode" in s["by_name"]
    assert "device_step" in s["by_name"] or "device_scan" in s["by_name"]
    assert "drain" in s["by_name"]
    assert "redis_flush" in s["by_name"]
    # span-sum vs aggregate-segment consistency: for every stage the
    # Tracer counted, the exported spans carry the same call count and
    # the same total time (one clock, one recording — only float
    # rounding of ns -> us apart)
    for stage, (calls, total_ns, _mx) in engine.tracer.snapshot().items():
        agg = s["by_name"][stage]
        assert agg["count"] == calls, stage
        assert agg["total_ms"] == pytest.approx(total_ns / 1e6,
                                                rel=1e-3, abs=0.01)
    # the writer thread's sink spans are on their own thread
    by_tid = {}
    for e in doc["traceEvents"]:
        if e["ph"] == "X":
            by_tid.setdefault(e["name"], set()).add(e["tid"])
    assert by_tid["redis_flush"] != by_tid["device_step" if "device_step"
                                           in by_tid else "device_scan"]
    # registry counters track the ring
    assert reg.counter("streambench_spans_total").value == len(spans)
