"""RESP client + fake server + canonical schema tests.

The socket tests exercise the real wire protocol; the schema tests run both
over the socket and in-process to prove the two paths are interchangeable.
"""

import pytest

from streambench_tpu.io.fakeredis import FakeRedisServer, FakeRedisStore
from streambench_tpu.io.resp import RespClient, RespError, encode_command
from streambench_tpu.io import redis_schema as schema


@pytest.fixture(scope="module")
def server():
    with FakeRedisServer() as s:
        yield s


@pytest.fixture()
def client(server):
    c = RespClient("127.0.0.1", server.port)
    c.flushall()
    yield c
    c.close()


def test_encode_command():
    assert encode_command("SET", "k", "v") == b"*3\r\n$3\r\nSET\r\n$1\r\nk\r\n$1\r\nv\r\n"
    assert encode_command("HINCRBY", "h", "f", 5).endswith(b"$1\r\n5\r\n")


def test_basic_commands_over_socket(client):
    assert client.ping() == "PONG"
    assert client.set("k", "v") == "OK"
    assert client.get("k") == "v"
    assert client.get("missing") is None
    assert client.sadd("s", "a", "b") == 2
    assert client.smembers("s") == ["a", "b"]
    assert client.hset("h", "f", "1") == 1
    assert client.hget("h", "f") == "1"
    assert client.hincrby("h", "n", 5) == 5
    assert client.hincrby("h", "n", 2) == 7
    assert client.lpush("l", "x") == 1
    assert client.lpush("l", "y") == 2
    assert client.llen("l") == 2
    assert client.lrange("l", 0, 2) == ["y", "x"]
    assert client.hgetall("h") == {"f": "1", "n": "7"}


def test_wrongtype_and_unknown(client):
    client.set("k", "v")
    with pytest.raises(RespError):
        client.hget("k", "f")
    with pytest.raises(RespError):
        client.execute("SUBSCRIBE", "chan")


def test_pipeline(client):
    replies = client.pipeline_execute(
        [("SET", "a", "1"), ("GET", "a"), ("GET", "nope"), ("HGET", "a", "f")]
    )
    assert replies[0] == "OK" and replies[1] == "1" and replies[2] is None
    assert isinstance(replies[3], RespError)  # WRONGTYPE surfaced per-command


def test_binary_safe_values(client):
    client.set("bin", "sp ace\r\nnew{line}")
    assert client.get("bin") == "sp ace\r\nnew{line}"


@pytest.fixture(params=["socket", "inprocess"])
def anyredis(request, server):
    if request.param == "socket":
        c = RespClient("127.0.0.1", server.port)
        c.flushall()
        yield c
        c.close()
    else:
        yield schema.as_redis(FakeRedisStore())


def test_canonical_schema_roundtrip(anyredis):
    r = anyredis
    schema.seed_campaigns(r, ["campA", "campB"])
    schema.seed_ad_mapping(r, {"ad1": "campA", "ad2": "campB"})
    assert schema.load_ad_mapping(r, ["ad1", "ad2", "ad3"]) == {
        "ad1": "campA", "ad2": "campB"}

    schema.write_window(r, "campA", 10000, 5, time_updated=12345)
    schema.write_window(r, "campA", 10000, 3, time_updated=12999)  # accumulate
    schema.write_window(r, "campA", 20000, 7, time_updated=25000)
    schema.write_window(r, "campB", 10000, 1, time_updated=11000)

    counts = schema.read_seen_counts(r)
    assert counts["campA"] == {10000: 8, 20000: 7}
    assert counts["campB"] == {10000: 1}

    stats = sorted(schema.read_stats(r))
    # (seen, time_updated - window_ts)
    assert stats == [(1, 1000), (7, 5000), (8, 2999)]


def test_pipelined_writeback_matches_single(anyredis):
    r = anyredis
    schema.seed_campaigns(r, ["c1", "c2"])
    n = schema.write_windows_pipelined(
        r,
        [("c1", 10000, 4), ("c1", 20000, 2), ("c2", 10000, 9),
         ("c1", 10000, 6)],  # same window twice in one flush
        time_updated=50000,
    )
    assert n == 4
    counts = schema.read_seen_counts(r)
    assert counts["c1"] == {10000: 10, 20000: 2}
    assert counts["c2"] == {10000: 9}
    # windows list holds exactly one entry per distinct window
    wl = r.execute("HGET", "c1", "windows")
    assert sorted(r.execute("LRANGE", wl, 0, 10)) == ["10000", "20000"]


def test_wrongtype_campaign_skips_rows_without_poisoning(anyredis):
    """A campaign key that already exists as a string must neither shadow
    into a dual-type state, nor poison the uuid cache with RespError
    replies, nor abort the batch (the flusher's retained-batch retry
    would then double-apply the rows before the conflict)."""
    r = anyredis
    schema.seed_campaigns(r, ["good"], flush=True)
    r.execute("SET", "bad", "i-am-a-string")
    cache: dict = {}
    rows = [("good", 10000, 3), ("bad", 10000, 5), ("good", 20000, 2)]
    schema.write_windows_pipelined(r, rows, time_updated=50000, cache=cache)
    # healthy rows landed exactly once
    counts = schema.read_seen_counts(r)
    assert counts["good"] == {10000: 3, 20000: 2}
    # the string key survived untouched
    assert r.execute("GET", "bad") == "i-am-a-string"
    # cache carries no entry derived from an error reply
    for (c, _w), u in cache.get("win", {}).items():
        assert c == "good" and isinstance(u, str) and "WRONGTYPE" not in u
    for c, u in cache.get("list", {}).items():
        assert c == "good" and isinstance(u, str) and "WRONGTYPE" not in u
    # a retry of the same batch accumulates only the healthy rows again
    schema.write_windows_pipelined(r, rows, time_updated=51000, cache=cache)
    counts = schema.read_seen_counts(r)
    assert counts["good"] == {10000: 6, 20000: 4}
    assert r.execute("GET", "bad") == "i-am-a-string"


def test_wrongtype_campaign_skips_rows_native_store():
    from streambench_tpu import native
    from streambench_tpu.io.fakeredis import NativeRedisStore

    lib = native.load()
    if lib is None or not hasattr(lib, "sbr_new"):
        pytest.skip("native store not built")
    r = schema.as_redis(NativeRedisStore(lib))
    schema.seed_campaigns(r, ["good"], flush=True)
    r.execute("SET", "bad", "i-am-a-string")
    rows = [("good", 10000, 3), ("bad", 10000, 5), ("good", 20000, 2)]
    schema.write_windows_pipelined(r, rows, time_updated=50000)
    counts = schema.read_seen_counts(r)
    assert counts["good"] == {10000: 3, 20000: 2}
    assert r.execute("GET", "bad") == "i-am-a-string"


def test_latency_hash_roundtrip(anyredis):
    r = anyredis
    idx1 = schema.dump_latency_hash(r, "t1", {100: 5, 200: 8}, 999)
    idx2 = schema.dump_latency_hash(r, "t1", {100: 7}, 1234)
    assert (idx1, idx2) == (1, 2)
    running, per_idx = schema.read_latency_hash(r, "t1")
    assert running == {1: 999, 2: 1234}
    assert per_idx == {1: {100: 5, 200: 8}, 2: {100: 7}}
