"""Latency decile reporter, stall detector, tracer, and the r2c/c2r
host<->device handoff benchmark (SURVEY.md §5.1/§5.5: the Apex
ProcessTimeAwareStore report and the fork's WindowedArrowFormatBolter /
LatencyRecordBolter experiment, re-expressed for the TPU engine)."""

import random

from streambench_tpu import handoff
from streambench_tpu.datagen import gen
from streambench_tpu.encode.encoder import EventEncoder
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.redis_schema import as_redis
from streambench_tpu.metrics import LatencyTracker, StallDetector, decile_table
from streambench_tpu.trace import Tracer


def test_latency_tracker_trims_warmup_and_tail():
    # 13 buckets; first 10 (warm-up) + last (incomplete) must be excluded,
    # leaving buckets 10..11 (ProcessTimeAwareStore.java:129-140 semantics).
    t = LatencyTracker(window_ms=10_000, ignore_first=10)
    for b in range(13):
        bucket = b * 10_000
        # update lands (b+1 windows) late for key "a", +5 ms for key "b"
        t.record("a", bucket, bucket + 10_000 + 100 * b)
        t.record("b", bucket, bucket + 10_000 + 100 * b + 5)
    lats = t.final_latencies()
    # kept buckets: 10 and 11 -> latencies 1000,1005,1100,1105
    assert lats == [1000, 1005, 1100, 1105]
    report = t.report()
    assert "4 samples" in report and "0 - 10" in report


def test_latency_tracker_needs_enough_buckets():
    t = LatencyTracker(ignore_first=10)
    for b in range(11):
        t.record("k", b * 10_000, b * 10_000 + 12_000)
    assert t.final_latencies() == []
    assert "not enough" in t.report()


def test_decile_table_matches_reference_grouping():
    # outputGroupByCount: row i = sorted[step*(i+1)], last row = max
    lats = list(range(100))
    rows = decile_table(lats)
    assert len(rows) == 10
    assert rows[0] == ("0 - 10", 10)
    assert rows[8] == ("80 - 90", 90)
    assert rows[9] == ("90 - 100", 99)
    assert decile_table([]) == []
    single = decile_table([7])  # fewer samples than groups: all rows = max
    assert len(single) == 10 and all(v == 7 for _, v in single)


def test_decile_table_small_samples_spread():
    # Under 10 samples the old integer step (n // 10 == 0) repeated
    # sorted[0] across the first nine rows; proportional indices must
    # spread the order statistics instead.
    rows = decile_table([1, 2, 3, 4, 5])
    assert [v for _, v in rows] == [1, 2, 2, 3, 3, 4, 4, 5, 5, 5]
    rows7 = decile_table([10, 20, 30, 40, 50, 60, 70])
    vals7 = [v for _, v in rows7]
    assert vals7[0] == 10 and vals7[-1] == 70
    assert len(set(vals7)) >= 5          # not collapsed onto the min
    assert vals7 == sorted(vals7)        # monotone non-decreasing


def test_stall_detector_warns_on_gap():
    warnings = []
    sd = StallDetector(expected_period_ms=1000, warn=warnings.append)
    assert sd.tick(10_000) is None          # first tick: no baseline
    assert sd.tick(11_000) is None          # on cadence
    assert sd.tick(14_000) == 3000          # 3 s gap > 2 s threshold
    assert sd.stalls == 1 and "3000 ms" in warnings[0]


def test_tracer_spans_and_report():
    tr = Tracer()
    for _ in range(3):
        with tr.span("encode"):
            pass
    tr.add("device_step", 2_000_000)  # 2 ms
    assert tr.stages["encode"].calls == 3
    rep = tr.report()
    assert "encode" in rep and "device_step" in rep
    d = tr.as_dict()
    assert d["device_step"]["total_ms"] == 2.0
    tr.enabled = False
    with tr.span("encode"):
        pass
    assert tr.stages["encode"].calls == 3  # disabled span not recorded


def _make_windows(n_windows=3, batch=64):
    rng = random.Random(9)
    campaigns = gen.make_ids(10, rng)
    ads = gen.make_ids(100, rng)
    mapping = {a: campaigns[i % 10] for i, a in enumerate(ads)}
    src = gen.EventSource(ads=ads, user_ids=gen.make_ids(5, rng),
                          page_ids=gen.make_ids(5, rng), rng=rng)
    base = 1_700_000_000_000
    windows, starts = [], []
    for w in range(n_windows):
        ts = [base + w * 10_000 + i for i in range(batch)]
        windows.append([e.encode() for e in src.events_at(ts)])
        starts.append(base + w * 10_000)
    return mapping, campaigns, windows, starts


def test_handoff_roundtrip_and_redis_schema():
    mapping, campaigns, windows, starts = _make_windows()
    enc = EventEncoder(mapping, campaigns)
    samples = handoff.run_handoff(enc, windows, starts)
    assert len(samples) == 3
    assert all(s.r2c_ms > 0 and s.c2r_ms > 0 for s in samples)
    assert [s.window_start_ms for s in samples] == starts

    r = as_redis(FakeRedisStore())
    handoff.dump_handoff(r, "t1_handoff", samples)
    got = handoff.read_handoff(r, "t1_handoff")
    assert set(got) == set(starts)
    w, r2c, c2r = got[starts[0]]
    assert r2c > 0 and c2r > 0
