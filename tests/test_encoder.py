"""Encoder tests: wire-format parsing, interning, fallback, tbl format."""

import json
import random

import numpy as np

from streambench_tpu.datagen import gen
from streambench_tpu.encode import VIEW, EventEncoder


def make_encoder(n_campaigns=3, ads_per=2):
    campaigns = [f"camp{i}" for i in range(n_campaigns)]
    mapping = {}
    for i, c in enumerate(campaigns):
        for j in range(ads_per):
            mapping[f"ad{i}_{j}"] = c
    return EventEncoder(mapping, campaigns), mapping


def test_fast_path_parses_generator_output():
    enc, mapping = make_encoder()
    src = gen.EventSource(ads=list(mapping), user_ids=["u1", "u2"],
                          page_ids=["p1"], rng=random.Random(0))
    lines = [src.event_at(1_000_000 + 10 * i).encode() for i in range(100)]
    batch = enc.encode(lines)
    assert batch.n == 100 and enc.fallback_lines == 0 and enc.bad_lines == 0
    # rebased to window start minus one lateness span (60 s)
    assert batch.base_time_ms == 1_000_000 - 60_000
    # cross-check each row against json.loads
    for i, line in enumerate(lines):
        ev = json.loads(line)
        assert enc.ads[batch.ad_idx[i]] == ev["ad_id"]
        assert batch.event_time[i] == int(ev["event_time"]) - 940_000
        et = ["view", "click", "purchase"][batch.event_type[i]]
        assert et == ev["event_type"]
    assert batch.valid.all()


def test_slow_path_reordered_json():
    enc, _ = make_encoder()
    line = json.dumps({"event_time": "5000", "ad_id": "ad0_0",
                       "event_type": "view", "user_id": "u",
                       "page_id": "p", "ad_type": "banner"}).encode()
    batch = enc.encode([line])
    assert batch.n == 1 and enc.fallback_lines == 1 and enc.bad_lines == 0
    assert batch.event_type[0] == VIEW


def test_bad_lines_masked():
    enc, _ = make_encoder()
    batch = enc.encode([b"not json at all", b'{"event_time": "nope"}'],
                       batch_size=4)
    assert batch.n == 0 and enc.bad_lines == 2
    assert not batch.valid.any()


def test_unknown_ad_maps_to_negative_campaign():
    enc, _ = make_encoder()
    line = json.dumps({"user_id": "u", "page_id": "p", "ad_id": "mystery",
                       "ad_type": "banner", "event_type": "view",
                       "event_time": "10000"}).encode()
    b = enc.encode([line])
    assert b.ad_idx[0] == enc.unknown_ad
    assert enc.join_table[b.ad_idx[0]] == -1


def test_padding_and_batch_size():
    enc, mapping = make_encoder()
    src = gen.EventSource(ads=list(mapping), user_ids=["u"], page_ids=["p"],
                          rng=random.Random(1))
    lines = [src.event_at(20_000 + i).encode() for i in range(3)]
    b = enc.encode(lines, batch_size=8)
    assert b.batch_size == 8 and b.n == 3
    assert b.valid.sum() == 3 and not b.valid[3:].any()


def test_user_interning_stable():
    enc, mapping = make_encoder()
    mk = lambda u: json.dumps({"user_id": u, "page_id": "p", "ad_id": "ad0_0",
                               "ad_type": "mail", "event_type": "click",
                               "event_time": "30000"}).encode()
    b = enc.encode([mk("alice"), mk("bob"), mk("alice")])
    assert b.user_idx[0] == b.user_idx[2] != b.user_idx[1]


def test_tbl_format():
    enc, _ = make_encoder()
    lines = [b"u1|p1|ad0_0|banner|view|40000", b"u2|p2|ad1_0|mail|click|40010",
             b"garbage-line"]
    b = enc.encode_tbl(lines, batch_size=4)
    assert b.n == 2 and enc.bad_lines == 1
    # rebased to 40000 - 60000 lateness margin
    assert b.event_time[0] == 60_000 and b.event_time[1] == 60_010
    assert enc.join_table[b.ad_idx[1]] == 1


def test_join_table_matches_mapping():
    enc, mapping = make_encoder(n_campaigns=5, ads_per=3)
    for ad, camp in mapping.items():
        assert enc.campaigns[enc.join_table[enc.ad_index[ad.encode()]]] == camp
    assert np.array_equal(enc.join_table[-1:], [-1])
