"""ShardedReachEngine (parallel/reach.py, ISSUE 14): bit-identity with
the single-device minhash kernels over adversarial shard splits and
seeds, query evaluation next to the shards (agree counts AND float
estimates exact), the two-collective HLO claim, engine end-to-end
equality through the real runner, and the snapshot upgrade path."""

import random

import jax.numpy as jnp
import numpy as np
import pytest

from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.engine.runner import StreamRunner
from streambench_tpu.engine.sketches import ReachSketchEngine
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import as_redis
from streambench_tpu.ops import minhash
from streambench_tpu.parallel.mesh import build_mesh
from streambench_tpu.parallel.reach import (
    ShardedReachEngine,
    _build_reach_query,
    _build_reach_scan,
    _build_reach_step,
    pad_campaigns,
    sharded_reach_init,
)
from streambench_tpu.reach import query as rq

C, K_SLOTS, R = 10, 32, 32
MESHES = [(8, 1), (4, 2), (2, 4), (1, 8), (2, 2)]


def make_join(n_ads=14):
    # several ads per campaign + an unknown-ad slot (-1): join misses
    # are part of the adversarial mix
    rng = np.random.default_rng(3)
    return np.concatenate([rng.integers(0, C, n_ads - 1),
                           [-1]]).astype(np.int32)


def rand_batches(rng, n_batches, B, join):
    out = []
    t = 70_000
    for _ in range(n_batches):
        out.append((
            rng.integers(0, len(join), B).astype(np.int32),
            rng.integers(0, 4000, B).astype(np.int32),
            rng.integers(0, 3, B).astype(np.int32),
            (t + rng.integers(0, 5_000, B)).astype(np.int32),
            rng.random(B) < 0.9,
        ))
        t += 5_000
    return out


def fold_ref(join, batches):
    st = minhash.init_state(C, K_SLOTS, R)
    jt = jnp.asarray(join)
    for ad, user, et, tm, v in batches:
        st = minhash.step(st, jt, jnp.asarray(ad), jnp.asarray(user),
                          jnp.asarray(et), jnp.asarray(tm),
                          jnp.asarray(v))
    return st


def assert_planes_equal(sharded, ref, label):
    assert np.array_equal(np.asarray(sharded.mins)[:C],
                          np.asarray(ref.mins)), label
    assert np.array_equal(np.asarray(sharded.registers)[:C],
                          np.asarray(ref.registers)), label
    assert int(sharded.watermark) == int(ref.watermark), label


@pytest.mark.parametrize("dshape", MESHES)
def test_step_scan_packed_bit_identity(dshape):
    """Per-batch step, hoisted scan, and packed hoisted scan all land
    the exact single-device planes on every mesh split."""
    from streambench_tpu.ops import windowcount as wc

    nd, nc = dshape
    mesh = build_mesh(data=nd, campaign=nc)
    join = make_join()
    jt = jnp.asarray(join)
    for seed in (0, 1):
        rng = np.random.default_rng(seed)
        batches = rand_batches(rng, 4, nd * 16, join)
        ref = fold_ref(join, batches)

        # per-batch step sequence
        st = sharded_reach_init(C, K_SLOTS, R, mesh)
        fn = _build_reach_step(mesh)
        for ad, user, et, tm, v in batches:
            mins, regs, wm = fn(st.mins, st.registers, st.watermark,
                                jt, ad, user, et, tm, v)
            st = minhash.ReachState(mins, regs, wm, st.dropped)
        assert_planes_equal(st, ref, f"step mesh={dshape} seed={seed}")

        # hoisted scan over the stacked batches
        st2 = sharded_reach_init(C, K_SLOTS, R, mesh)
        scan = _build_reach_scan(mesh)
        stacks = [np.stack(cols) for cols in zip(*batches)]
        mins, regs, wm = scan(st2.mins, st2.registers, st2.watermark,
                              jt, *stacks)
        st2 = minhash.ReachState(mins, regs, wm, st2.dropped)
        assert_planes_equal(st2, ref, f"scan mesh={dshape} seed={seed}")

        # packed hoisted scan (packed word + user + time)
        st3 = sharded_reach_init(C, K_SLOTS, R, mesh)
        pscan = _build_reach_scan(mesh, packed=True)
        packed = np.stack([np.asarray(wc.pack_columns(a, e, v))
                           for a, _, e, _, v in batches])
        mins, regs, wm = pscan(
            st3.mins, st3.registers, st3.watermark, jt,
            packed, stacks[1], stacks[3])
        st3 = minhash.ReachState(mins, regs, wm, st3.dropped)
        assert_planes_equal(st3, ref, f"packed mesh={dshape} seed={seed}")


@pytest.mark.parametrize("dshape", [(1, 8), (2, 4), (4, 2)])
def test_query_next_to_shards_bit_identity(dshape):
    """The two-collective sharded query returns the single-device
    batch_query's results exactly — integer collision counts AND the
    float estimates (the merge runs on integers; the float arithmetic
    is the same post-merge graph)."""
    nd, nc = dshape
    mesh = build_mesh(data=nd, campaign=nc)
    join = make_join()
    rng = np.random.default_rng(7)
    ref = fold_ref(join, rand_batches(rng, 4, 64, join))

    Q = 24
    masks = np.zeros((Q, C), bool)
    overlap = np.zeros(Q, bool)
    for i in range(Q - 2):   # leave 2 all-False rows (padding shape)
        masks[i, rng.choice(C, size=int(rng.integers(1, 6)),
                            replace=False)] = True
        overlap[i] = bool(rng.integers(0, 2))
    e0, u0, j0, a0 = rq.batch_query(ref.mins, ref.registers,
                                    jnp.asarray(masks),
                                    jnp.asarray(overlap))

    st = sharded_reach_init(C, K_SLOTS, R, mesh)
    st = minhash.ReachState(
        jnp.asarray(np.concatenate(
            [np.asarray(ref.mins),
             np.full((pad_campaigns(C, mesh) - C, K_SLOTS),
                     minhash.EMPTY, np.uint32)])),
        jnp.asarray(np.concatenate(
            [np.asarray(ref.registers),
             np.zeros((pad_campaigns(C, mesh) - C, R), np.int32)])),
        st.watermark, st.dropped)
    qfn = _build_reach_query(mesh)
    mp = np.concatenate(
        [masks, np.zeros((Q, pad_campaigns(C, mesh) - C), bool)],
        axis=1)
    e1, u1, j1, a1 = qfn(st.mins, st.registers, jnp.asarray(mp),
                         jnp.asarray(overlap))
    assert np.array_equal(np.asarray(a0), np.asarray(a1))
    assert np.array_equal(np.asarray(e0), np.asarray(e1))
    assert np.array_equal(np.asarray(u0), np.asarray(u1))
    assert np.array_equal(np.asarray(j0), np.asarray(j1))


def test_query_dispatch_is_exactly_two_collectives():
    """The transferable claim, read from the compiled program: one
    all-reduce min + one all-reduce max per query dispatch on a
    multi-shard mesh — independent of Q, C, and the campaign fan-out."""
    from streambench_tpu.parallel import collectives

    mesh = build_mesh(data=1, campaign=8)
    st = sharded_reach_init(C, K_SLOTS, R, mesh)
    Cp = pad_campaigns(C, mesh)
    qfn = _build_reach_query(mesh)
    rep = collectives.report_for(
        qfn, st.mins, st.registers,
        jnp.zeros((64, Cp), bool), jnp.zeros((64,), bool))
    per = rep["per_dispatch"]
    assert per["ops"] == 2, per
    assert per["by_kind"] == {"all-reduce": 2}, per
    # payload: [Q, k] uint32 pmin + [Q, k + R] uint32 pmax
    assert per["bytes"] == 64 * K_SLOTS * 4 + 64 * (K_SLOTS + R) * 4


def test_engine_end_to_end_and_query_callable(tmp_path):
    """ShardedReachEngine through the real runner on a generator
    journal: planes and served query results bit-identical to the
    single-device ReachSketchEngine; batch padding exercised."""
    cfg = default_config(jax_batch_size=250)  # 250 % data-axis pads
    broker = FileBroker(str(tmp_path / "broker"))
    r1 = as_redis(FakeRedisStore())
    gen.do_setup(r1, cfg, broker=broker, events_num=5_000,
                 rng=random.Random(11), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))

    mesh = build_mesh(data=4, campaign=2)
    eng = ShardedReachEngine(cfg, mapping, mesh, redis=None,
                             k=K_SLOTS, registers=R)
    assert eng._data_pad == 2  # 250 % 4
    stats = StreamRunner(eng, broker.reader(cfg.kafka_topic)
                         ).run_catchup()
    assert stats.events == 5_000

    ref = ReachSketchEngine(cfg, mapping, redis=None,
                            k=K_SLOTS, registers=R)
    StreamRunner(ref, broker.reader(cfg.kafka_topic)).run_catchup()

    host = eng.host_state()
    assert np.array_equal(host.mins, np.asarray(ref.state.mins))
    assert np.array_equal(host.registers,
                          np.asarray(ref.state.registers))

    # queries evaluated next to the shards == single-device evaluation
    names = list(eng.encoder.campaigns)
    rng = np.random.default_rng(2)
    Q = 16
    masks = np.zeros((Q, len(names)), bool)
    overlap = np.zeros(Q, bool)
    for i in range(Q):
        masks[i, rng.choice(len(names), size=2, replace=False)] = True
        overlap[i] = bool(i % 2)
    es, us, js, ags = eng.batch_query(masks, overlap)
    e0, u0, j0, a0 = rq.batch_query(
        ref.state.mins, ref.state.registers, jnp.asarray(masks),
        jnp.asarray(overlap))
    assert np.array_equal(ags, np.asarray(a0))
    assert np.array_equal(es, np.asarray(e0))

    # the serving path routes through the injected sharded evaluator
    from streambench_tpu.reach.serve import ReachQueryServer

    srv = ReachQueryServer(names, depth=32, batch=8)
    eng.attach_reach(srv)
    got = []
    try:
        srv.submit([names[0], names[1]], "union",
                   lambda d: got.append(d), query_id=1)
        deadline = 50
        while not got and deadline:
            import time

            time.sleep(0.1)
            deadline -= 1
    finally:
        srv.close()
    assert got and "estimate" in got[0], got
    i0, i1 = names.index(names[0]), names.index(names[1])
    m = np.zeros((1, len(names)), bool)
    m[0, [i0, i1]] = True
    want, *_ = rq.batch_query(ref.state.mins, ref.state.registers,
                              jnp.asarray(m), jnp.asarray([False]))
    assert got[0]["estimate"] == round(float(np.asarray(want)[0]), 2)


def test_snapshot_roundtrip_and_upgrade_path(tmp_path):
    """Sharded -> sharded snapshot round trip, and the upgrade path: a
    single-device reach snapshot restores into the sharded engine with
    campaign padding (epoch bumps on restore, serving stays exact)."""
    cfg = default_config(jax_batch_size=256)
    broker = FileBroker(str(tmp_path / "broker"))
    r = as_redis(FakeRedisStore())
    gen.do_setup(r, cfg, broker=broker, events_num=3_000,
                 rng=random.Random(4), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    mesh = build_mesh(data=1, campaign=8)

    ref = ReachSketchEngine(cfg, mapping, redis=None, k=K_SLOTS,
                            registers=R)
    StreamRunner(ref, broker.reader(cfg.kafka_topic)).run_catchup()
    snap = ref.snapshot(offset=123)

    eng = ShardedReachEngine(cfg, mapping, mesh, redis=None,
                             k=K_SLOTS, registers=R)
    eng.restore(snap)
    assert eng.reach_epoch == ref.reach_epoch + 1
    host = eng.host_state()
    assert np.array_equal(host.mins, np.asarray(ref.state.mins))
    assert np.array_equal(host.registers,
                          np.asarray(ref.state.registers))

    snap2 = eng.snapshot(offset=456)
    eng2 = ShardedReachEngine(cfg, mapping, mesh, redis=None,
                              k=K_SLOTS, registers=R)
    eng2.restore(snap2)
    assert np.array_equal(eng2.host_state().mins, host.mins)
    assert np.array_equal(eng2.host_state().registers, host.registers)
