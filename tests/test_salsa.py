"""SALSA merge-on-overflow sketch (ops/salsa.py, ISSUE 13): the
transition vs its closed-form numpy oracle (the homomorphism property
means the expected state is a pure function of exact per-cell totals),
hand-pinned overflow/merge promotions, the shard-order-invariant merge
algebra (mirroring tests/test_minhash.py), the SF two-stage mode, the
geometry-validated merges across every sketch family, and the session
engine in salsa mode — fixed-mode A/B, kill/resume with merged bitmaps
live."""

import random

import jax.numpy as jnp
import numpy as np
import pytest

from streambench_tpu.ops import cms, hll, minhash, salsa

D, W = 4, 64


def rand_batch(rng, B=128, keyspace=48, wmax=120):
    return (rng.integers(0, keyspace, B).astype(np.int32),
            rng.integers(0, wmax, B).astype(np.int32),
            rng.random(B) > 0.2)


def fold(state, batches):
    for k, w, m in batches:
        state = salsa.update(state, jnp.asarray(k), jnp.asarray(w),
                             jnp.asarray(m))
    return state


def assert_state_equal(a, b):
    np.testing.assert_array_equal(np.asarray(a.table), np.asarray(b.table))
    np.testing.assert_array_equal(np.asarray(a.m1), np.asarray(b.m1))
    np.testing.assert_array_equal(np.asarray(a.m2), np.asarray(b.m2))
    assert int(a.total) == int(b.total)


# ----------------------------------------------------- oracle differential
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_update_matches_closed_form_oracle(seed):
    """Arbitrary batch sequence -> state == oracle_encode(exact totals)
    bit for bit, and query == the oracle's final-geometry read."""
    rng = np.random.default_rng(seed)
    batches = [rand_batch(rng) for _ in range(6)]
    st = fold(salsa.init_state(D, W), batches)
    tot = salsa.oracle_totals_np(batches, D, W)
    table, m1, m2 = salsa.oracle_encode_np(tot)
    np.testing.assert_array_equal(np.asarray(st.table), table)
    np.testing.assert_array_equal(np.asarray(st.m1), m1)
    np.testing.assert_array_equal(np.asarray(st.m2), m2)
    keys = np.arange(48, dtype=np.int32)
    np.testing.assert_array_equal(
        np.asarray(salsa.query(st, jnp.asarray(keys))),
        salsa.oracle_query_np(tot, keys))


def test_estimates_upper_bound_exact_counts():
    rng = np.random.default_rng(3)
    batches = [rand_batch(rng) for _ in range(8)]
    st = fold(salsa.init_state(D, W), batches)
    exact = np.zeros(48, np.int64)
    for k, w, m in batches:
        np.add.at(exact, k, np.where(m, w, 0))
    got = np.asarray(salsa.query(
        st, jnp.asarray(np.arange(48, dtype=np.int32))))
    assert (got >= exact).all()


def test_cell_bits_16_starts_pair_merged():
    rng = np.random.default_rng(4)
    batches = [rand_batch(rng) for _ in range(4)]
    st = fold(salsa.init_state(D, W, cell_bits=16), batches)
    tot = salsa.oracle_totals_np(batches, D, W)
    table, m1, m2 = salsa.oracle_encode_np(tot, cell_bits=16)
    np.testing.assert_array_equal(np.asarray(st.table), table)
    np.testing.assert_array_equal(np.asarray(st.m1), m1)
    np.testing.assert_array_equal(np.asarray(st.m2), m2)
    assert salsa.stats(st)["merged_pairs"] == D * W // 2


# --------------------------------------------------- overflow transitions
def test_overflow_promotes_pair_then_quad():
    """Hand-pinned promotion ladder for one key: solo byte until 255,
    16-bit pair past it, 32-bit quad past 65535 — merge bits and the
    decoded value checked at each stage."""
    key = jnp.asarray(np.zeros(1, np.int32))
    one = jnp.asarray(np.ones(1, np.int32))
    valid = jnp.asarray(np.ones(1, bool))

    st = salsa.init_state(D, W)
    st = salsa.update(st, key, jnp.asarray(np.array([200], np.int32)),
                      valid)
    s = salsa.stats(st)
    assert s["merged_pairs"] == 0 and s["merged_quads"] == 0
    assert int(salsa.query(st, key)[0]) == 200

    # cross 255: every row's cell overflows its byte -> D pair merges
    st = salsa.update(st, key, jnp.asarray(np.array([100], np.int32)),
                      valid)
    s = salsa.stats(st)
    assert s["merged_pairs"] == D and s["merged_quads"] == 0
    assert int(salsa.query(st, key)[0]) == 300

    # cross 65535: the merged pairs overflow 16 bits -> D quad merges
    st = salsa.update(st, key, jnp.asarray(np.array([70_000], np.int32)),
                      valid)
    s = salsa.stats(st)
    assert s["merged_quads"] == D and s["merged_pairs"] == 2 * D
    assert int(salsa.query(st, key)[0]) == 70_300
    # a single update may promote solo -> quad directly
    st2 = salsa.update(salsa.init_state(D, W), key,
                       jnp.asarray(np.array([100_000], np.int32)), valid)
    assert salsa.stats(st2)["merged_quads"] == D
    assert int(salsa.query(st2, key)[0]) == 100_000
    assert int(st.total) == 70_300 and int(st2.total) == 100_000
    _ = one  # noqa: F841


def test_colliding_keys_merge_and_stay_upper_bounds():
    """Sum-on-merge (the deviation from SALSA's max, module docstring):
    two keys sharing row 0's CELL push its total past a byte; the pair
    widens and both keys report the summed (upper-bound) value."""
    st = salsa.init_state(D, W)
    cols = salsa.oracle_cols_np(np.arange(4096, dtype=np.int32), D, W)
    k0 = 0
    sib = np.nonzero((cols[0] == cols[0][k0])
                     & (np.arange(4096) != k0))[0]
    assert sib.size, "no row-0 cell collision in 4096 keys"
    k1 = int(sib[0])
    keys = jnp.asarray(np.array([k0, k1], np.int32))
    st = salsa.update(st, keys,
                      jnp.asarray(np.array([200, 200], np.int32)),
                      jnp.asarray(np.ones(2, bool)))
    got = np.asarray(salsa.query(st, keys))
    assert (got >= 200).all()
    # row 0's cell totals 400 > 255 -> its pair merged
    assert salsa.stats(st)["merged_pairs"] >= 1


# ------------------------------------------------------- merge algebra
@pytest.mark.parametrize("seed", [11, 12, 13])
def test_merge_shard_order_invariance(seed):
    """Random shard split + arbitrary merge order -> bit-identical
    plane, equal to the single-engine fold (the homomorphism)."""
    rng = np.random.default_rng(seed)
    pyrng = random.Random(seed)
    batches = [rand_batch(rng, wmax=300) for _ in range(10)]
    reference = fold(salsa.init_state(D, W), batches)
    S = pyrng.choice([2, 3, 4])
    shards = [[] for _ in range(S)]
    for b in batches:
        shards[pyrng.randrange(S)].append(b)
    partials = [fold(salsa.init_state(D, W), sh) for sh in shards]
    pyrng.shuffle(partials)
    merged = partials[0]
    for p in partials[1:]:
        merged = salsa.merge(merged, p)
    assert_state_equal(merged, reference)


def test_merge_commutative_associative():
    rng = np.random.default_rng(7)
    sts = [fold(salsa.init_state(D, W), [rand_batch(rng, wmax=200)
                                         for _ in range(2)])
           for _ in range(3)]
    a, b, c = sts
    assert_state_equal(salsa.merge(a, b), salsa.merge(b, a))
    assert_state_equal(salsa.merge(salsa.merge(a, b), c),
                       salsa.merge(a, salsa.merge(b, c)))


# --------------------------------------------------------- two-stage CMS
def test_two_stage_upper_bound_and_small_reads():
    rng = np.random.default_rng(9)
    st = cms.init_two_stage(depth=4, width=512, small_width=64)
    exact = np.zeros(64, np.int64)
    for _ in range(6):
        k, w, m = rand_batch(rng, keyspace=64)
        exact_w = np.where(m, w, 0)
        np.add.at(exact, k, exact_w)
        st = cms.update2(st, jnp.asarray(k), jnp.asarray(w),
                         jnp.asarray(m))
    keys = jnp.asarray(np.arange(64, dtype=np.int32))
    small = np.asarray(cms.query_small(st, keys))
    fat = np.asarray(cms.query(st.fat, keys))
    seen = exact > 0
    assert (small[seen] >= exact[seen]).all()
    assert (fat[seen] >= exact[seen]).all()
    # point_query dispatch reads the small stage for CMS2State
    np.testing.assert_array_equal(
        np.asarray(cms.point_query(st, keys)), small)
    assert int(cms.sk_total(st)) == int(exact.sum())


def test_two_stage_merge_refuses():
    a = cms.init_two_stage(depth=4, width=256)
    with pytest.raises(ValueError, match="does not merge"):
        cms.merge2(a, a)


# --------------------------------------- geometry-validated merges (all)
def test_salsa_merge_geometry_mismatch_raises():
    a = salsa.init_state(4, 64)
    b = salsa.init_state(4, 128)
    with pytest.raises(ValueError, match=r"salsa\.merge.*64.*128"):
        salsa.merge(a, b)


def test_cms_merge_geometry_mismatch_raises():
    a = cms.init_state(depth=4, width=64)
    b = cms.init_state(depth=2, width=64)
    with pytest.raises(ValueError, match=r"cms\.merge.*\(4, 64\).*\(2, 64\)"):
        cms.merge(a, b)


def test_hll_merge_geometry_mismatch_raises():
    a = hll.init_state(4, 8, num_registers=32)
    b = hll.init_state(4, 8, num_registers=64)
    with pytest.raises(ValueError, match=r"hll\.merge.*32.*64"):
        hll.merge(a, b)
    # a differing window axis is caught by the register check too
    c = hll.init_state(4, 16, num_registers=32)
    with pytest.raises(ValueError, match=r"hll\.merge"):
        hll.merge(a, c)
    # hand-built ring drift (registers equal, ring not): named error
    d = hll.HLLState(registers=a.registers,
                     window_ids=jnp.zeros((5,), jnp.int32),
                     watermark=a.watermark, dropped=a.dropped)
    with pytest.raises(ValueError, match="window-ring"):
        hll.merge(a, d)


def test_hll_merge_valid_states():
    """Merging same-ring partials: registers max, dropped summed."""
    a = hll.init_state(3, 4, num_registers=32)
    b = hll.init_state(3, 4, num_registers=32)
    ra = a.registers.at[0, 0, 0].set(5)
    rb = b.registers.at[0, 0, 0].set(3)
    m = hll.merge(a._replace(registers=ra, dropped=jnp.int32(2)),
                  b._replace(registers=rb, dropped=jnp.int32(1)))
    assert int(m.registers[0, 0, 0]) == 5 and int(m.dropped) == 3


def test_minhash_merge_geometry_mismatch_raises():
    a = minhash.init_state(4, k=32, num_registers=32)
    b = minhash.init_state(4, k=64, num_registers=32)
    with pytest.raises(ValueError, match=r"minhash\.merge.*32.*64"):
        minhash.merge(a, b)
    c = minhash.init_state(4, k=32, num_registers=64)
    with pytest.raises(ValueError, match="register mismatch"):
        minhash.merge(a, c)


# ------------------------------------------------- session engine, salsa
def _session_world(tmp_path, events=8000, seed=77):
    from streambench_tpu.config import default_config
    from streambench_tpu.datagen import gen
    from streambench_tpu.io.fakeredis import FakeRedisStore
    from streambench_tpu.io.journal import FileBroker
    from streambench_tpu.io.redis_schema import as_redis

    cfg = default_config(jax_batch_size=512)
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(as_redis(FakeRedisStore()), cfg, broker=broker,
                 events_num=events, rng=random.Random(seed),
                 workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    return cfg, broker, mapping


def test_session_engine_salsa_matches_fixed_rows(tmp_path):
    """At no-overflow scale the SALSA plane shares the fixed sketch's
    hash and min-read, so the heavy-hitter report is IDENTICAL — the
    A/B oracle the CI session leg runs at engine-CLI level."""
    from streambench_tpu.engine import StreamRunner
    from streambench_tpu.engine.sketches import SessionCMSEngine
    from streambench_tpu.io.fakeredis import FakeRedisStore
    from streambench_tpu.io.redis_schema import as_redis

    cfg, broker, mapping = _session_world(tmp_path)

    def run(mode):
        eng = SessionCMSEngine(cfg, mapping,
                               redis=as_redis(FakeRedisStore()),
                               top_k=8, cms_mode=mode)
        StreamRunner(eng, broker.reader(cfg.kafka_topic)).run_catchup()
        eng.close()     # force-closes the open sessions into the sketch
        return eng, eng.heavy_hitters()

    e_fix, hh_fix = run("fixed")
    e_sal, hh_sal = run("salsa")
    assert hh_fix, "no heavy hitters closed — workload drifted"
    assert hh_fix == hh_sal
    assert e_sal.sessions_closed == e_fix.sessions_closed
    assert e_sal.session_clicks == e_fix.session_clicks
    # the memory claim, ledger-measured: >3.5x smaller state
    fix_b = e_fix.sketch_summary()["state_bytes"]
    sal_b = e_sal.sketch_summary()["state_bytes"]
    assert sal_b * 3.5 < fix_b, (sal_b, fix_b)


def test_session_engine_salsa_checkpoint_roundtrip_with_merges(tmp_path):
    """Kill/resume with merged bitmaps LIVE: fold enough weight through
    one user to force pair merges, snapshot, restore into a fresh
    engine, and continue — plane, bitmaps, ring, and counters must
    round-trip exactly and the continued fold must equal the
    uninterrupted one."""
    import jax.numpy as jnp  # noqa: F811
    from streambench_tpu.config import default_config
    from streambench_tpu.engine.sketches import SessionCMSEngine

    cfg = default_config(jax_batch_size=256)
    mapping = {"a": "c"}

    def feed(eng, lo, hi, seed):
        # heavy per-user click streams with 2s gaps -> closures whose
        # weights push cells past 255 (gap_ms=1000 below)
        rng = np.random.default_rng(seed)
        t = lo
        while t < hi:
            B = 256
            user = rng.integers(0, 50, B).astype(np.int32)
            et = np.ones(B, np.int32)            # all clicks
            tm = (t + np.sort(rng.integers(0, 1_000, B))).astype(np.int32)
            valid = np.ones(B, bool)
            eng._device_step(type("B", (), dict(
                user_idx=user, event_type=et, event_time=tm,
                valid=valid))())
            t += 3_000
        eng._drain_device()

    def mk():
        return SessionCMSEngine(cfg, mapping, campaigns=["c"],
                                gap_ms=1_000, cms_mode="salsa",
                                cms_width=64)

    a = mk()
    feed(a, 0, 60_000, seed=1)
    assert salsa.stats(a.cms)["merged_pairs"] > 0, \
        "no merges — the round-trip would not cover live bitmaps"
    snap = a.snapshot(offset=123)

    b = mk()
    b.restore(snap)
    assert_state_equal(a.cms, b.cms)
    assert b.sessions_closed == a.sessions_closed
    assert b.session_clicks == a.session_clicks
    np.testing.assert_array_equal(np.asarray(a.topk.keys),
                                  np.asarray(b.topk.keys))

    # continue both: uninterrupted vs resumed must stay bit-identical
    # (ring compared directly — this test feeds raw indices past the
    # encoder, so there are no interned names to reverse-look-up)
    feed(a, 60_000, 120_000, seed=2)
    feed(b, 60_000, 120_000, seed=2)
    assert_state_equal(a.cms, b.cms)
    np.testing.assert_array_equal(np.asarray(a.topk.keys),
                                  np.asarray(b.topk.keys))
    np.testing.assert_array_equal(np.asarray(a.topk.ests),
                                  np.asarray(b.topk.ests))


def test_session_engine_mode_mismatch_restore_raises(tmp_path):
    from streambench_tpu.config import default_config
    from streambench_tpu.engine.sketches import SessionCMSEngine

    cfg = default_config()
    mapping = {"a": "c"}
    a = SessionCMSEngine(cfg, mapping, campaigns=["c"], cms_mode="salsa")
    snap = a.snapshot(offset=0)
    b = SessionCMSEngine(cfg, mapping, campaigns=["c"], cms_mode="fixed")
    with pytest.raises(ValueError, match="cms_mode"):
        b.restore(snap)


def test_session_engine_salsa_two_stage_refused():
    from streambench_tpu.config import default_config
    from streambench_tpu.engine.sketches import SessionCMSEngine

    with pytest.raises(ValueError, match="does not compose"):
        SessionCMSEngine(default_config(), {"a": "c"}, campaigns=["c"],
                         cms_mode="salsa", cms_stages=2)
