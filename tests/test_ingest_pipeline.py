"""Staged ingest pipeline (engine.ingest): equivalence + quiesce contract.

The acceptance properties of ISSUE 3:

- pipeline ON produces byte-identical results to the serial loops (same
  events, same Redis window state, oracle-exact) in both catchup and
  paced mode, block mode and line mode, single- and multi-partition;
- ``quiesce()`` returns an offset covering exactly the FOLDED blocks —
  never read-ahead — so checkpoint/resume replays in-flight prefetched
  blocks instead of skipping them;
- pipeline OFF is the default and leaves the serial byte-path untouched
  (pinned implicitly by every pre-existing runner test).
"""

import os
import random

import pytest

from streambench_tpu.config import default_config
from streambench_tpu.checkpoint import Checkpointer
from streambench_tpu.datagen import gen
from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner
from streambench_tpu.engine.ingest import EOF, IngestPipeline
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import (
    as_redis,
    read_seen_counts,
    seed_campaigns,
)


def setup_run(tmp_path, events=20_000, partitions=1, **cfg_over):
    cfg = default_config(jax_batch_size=256, jax_scan_batches=2, **cfg_over)
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=events,
                 rng=random.Random(7), workdir=str(tmp_path),
                 partitions=partitions)
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    return cfg, r, broker, mapping


def fresh_store(tmp_path):
    r = as_redis(FakeRedisStore())
    seed_campaigns(r, gen.load_ids(str(tmp_path))[0])
    return r


def run_mode(cfg, mapping, broker, r, mode, catchup=True, reader=None):
    eng = AdAnalyticsEngine(cfg, mapping, redis=r)
    if reader is None:
        reader = broker.reader(cfg.kafka_topic)
    runner = StreamRunner(eng, reader, ingest_pipeline=mode)
    if catchup:
        stats = runner.run_catchup()
    else:
        stats = runner.run(idle_timeout_s=0.5)
    eng.close()
    return stats, runner


def test_catchup_pipelined_matches_serial_and_oracle(tmp_path):
    cfg, r, broker, mapping = setup_run(tmp_path)
    base_stats, _ = run_mode(cfg, mapping, broker, r, "off")
    baseline = read_seen_counts(r)

    r2 = fresh_store(tmp_path)
    stats, runner = run_mode(cfg, mapping, broker, r2, "on")
    assert stats.events == base_stats.events
    assert read_seen_counts(r2) == baseline
    correct, differ, missing = gen.check_correct(
        r2, workdir=str(tmp_path), log=lambda s: None)
    assert differ == 0 and missing == 0 and correct > 0
    tel = runner._pipeline.telemetry()
    assert tel["records_read"] == tel["records_folded"] == stats.events


def test_streaming_pipelined_matches_serial(tmp_path):
    """run() with the pipeline: buffer-timeout batching lives in the
    reader stage; an idle journal ends the run via the idle timeout."""
    cfg, r, broker, mapping = setup_run(tmp_path, events=8_000)
    run_mode(cfg, mapping, broker, r, "off", catchup=False)
    baseline = read_seen_counts(r)
    r2 = fresh_store(tmp_path)
    stats, _ = run_mode(cfg, mapping, broker, r2, "on", catchup=False)
    assert stats.events == 8_000
    assert read_seen_counts(r2) == baseline


def test_line_mode_pipeline_without_native_encoder(tmp_path):
    """Engines without block ingest (pure-Python encoder) take the
    pipeline's line mode; results stay identical."""
    cfg, r, broker, mapping = setup_run(
        tmp_path, events=8_000, jax_use_native_encoder=False)
    run_mode(cfg, mapping, broker, r, "off")
    baseline = read_seen_counts(r)
    r2 = fresh_store(tmp_path)
    stats, runner = run_mode(cfg, mapping, broker, r2, "on")
    assert not runner._pipeline.block_mode
    assert stats.events == 8_000
    assert read_seen_counts(r2) == baseline


def test_multi_partition_pipeline_line_mode(tmp_path):
    """MultiReader has no poll_block, so the pipeline runs line mode and
    tracks the per-partition offsets VECTOR as its folded position."""
    cfg, r, broker, mapping = setup_run(tmp_path, events=8_000,
                                        partitions=3)
    reader = broker.multi_reader(cfg.kafka_topic)
    stats, runner = run_mode(cfg, mapping, broker, r, "on", reader=reader)
    assert stats.events == 8_000
    pos = runner._pipeline.position()
    assert isinstance(pos, list) and len(pos) == 3
    # every partition fully consumed: folded position == file sizes
    sizes = [os.path.getsize(broker.topic_path(cfg.kafka_topic, p))
             for p in range(3)]
    assert pos == sizes


def test_quiesce_returns_only_folded_offsets(tmp_path):
    """The checkpoint contract, driven by hand: quiesce() must return
    the offset of the LAST COMMITTED block — read-ahead and encoded but
    unfolded items never advance it."""
    cfg, r, broker, mapping = setup_run(tmp_path, events=4_000)
    eng = AdAnalyticsEngine(cfg, mapping, redis=r)
    reader = broker.reader(cfg.kafka_topic)
    pipe = IngestPipeline(eng, reader, batch_size=256, chunk_records=512,
                          catchup=True, block_queue=2, batch_queue=2)
    try:
        # before anything folds, the folded position is the start
        assert pipe.quiesce() == 0
        pipe.resume()
        item = None
        while item is None:
            item = pipe.get(timeout_s=0.2)
        assert item is not EOF
        # got an encoded item but did NOT fold/commit it: still 0
        assert pipe.quiesce() == 0
        pipe.resume()
        eng.fold_batches(item.batches)
        pipe.commit(item)
        off = pipe.quiesce()
        pipe.resume()
        assert off == item.end_pos > 0
        # the offset covers exactly the folded block: re-reading from it
        # yields the REMAINING events (nothing skipped, nothing doubled)
        with broker.reader(cfg.kafka_topic, offset=off) as check:
            rest = sum(len(check.poll()) for _ in range(50))
        assert item.records + rest == 4_000
    finally:
        pipe.close()
        eng.close()


def test_checkpoint_resume_with_pipeline_is_exact(tmp_path):
    """Cut a pipelined run short (max_events), resume a fresh runner
    from its checkpoint, finish — totals exact, oracle-exact: quiesce
    offsets never skip an unfolded block."""
    cfg, r, broker, mapping = setup_run(tmp_path, events=12_000,
                                        jax_ingest_pipeline="on")
    ckpt = Checkpointer(str(tmp_path / "ckpt"))
    eng = AdAnalyticsEngine(cfg, mapping, redis=r)
    runner = StreamRunner(eng, broker.reader(cfg.kafka_topic),
                          checkpointer=ckpt)
    runner.run_catchup(max_events=6_000)
    eng.close()

    eng2 = AdAnalyticsEngine(cfg, mapping, redis=r)
    runner2 = StreamRunner(eng2, broker.reader(cfg.kafka_topic),
                           checkpointer=ckpt)
    assert runner2.resume()
    runner2.run_catchup()
    eng2.close()
    assert eng2.events_processed == 12_000
    correct, differ, missing = gen.check_correct(
        r, workdir=str(tmp_path), log=lambda s: None)
    assert differ == 0 and missing == 0 and correct > 0


def test_stage_error_propagates_to_host(tmp_path):
    """A reader-thread failure must surface on the host thread from
    get(), preserving its type (the supervisor's catch surface)."""
    cfg, r, broker, mapping = setup_run(tmp_path, events=2_000)

    class FailingReader:
        offset = 0

        def poll(self, max_records=65536):
            raise ConnectionError("broker gone")

    eng = AdAnalyticsEngine(cfg, mapping, redis=r)
    runner = StreamRunner(eng, FailingReader(), ingest_pipeline="on")
    with pytest.raises(ConnectionError):
        runner.run_catchup()
    eng.close()


def test_auto_mode_gates_on_block_mode_and_cores(tmp_path, monkeypatch):
    """"auto" resolves to the serial loop unless block-mode ingest is
    available AND the host has more than one core."""
    cfg, r, broker, mapping = setup_run(tmp_path, events=2_000)
    eng = AdAnalyticsEngine(cfg, mapping, redis=r)
    runner = StreamRunner(eng, broker.reader(cfg.kafka_topic),
                          ingest_pipeline="auto")
    import os as os_mod

    monkeypatch.setattr(os_mod, "cpu_count", lambda: 1)
    assert not runner._pipeline_on()
    monkeypatch.setattr(os_mod, "cpu_count", lambda: 8)
    assert runner._pipeline_on() == eng.supports_block_ingest
    eng.close()