"""Oracle-verified crash recovery: the at-least-once contract, executed.

The acceptance property (ISSUE 1): with faults injected on all three
surfaces — sink outage, torn journal reads, >= 3 mid-run crashes — under
a fixed seed, the supervised run completes and every per-window Redis
count satisfies ``oracle <= count <= oracle + replay_bound``; with an
all-zeros fault plan the chaos layer is an exact pass-through.
"""

import random

from streambench_tpu.chaos import (
    FaultInjector,
    FaultPlan,
    Supervisor,
    check_at_least_once,
    replay_note,
)
from streambench_tpu.checkpoint import Checkpointer
from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import as_redis, read_seen_counts


def setup_run(tmp_path, events=12_000, batch=256, **cfg_over):
    cfg = default_config(jax_batch_size=batch, jax_scan_batches=2,
                         jax_sink_retry_base_ms=1, jax_sink_retry_cap_ms=4,
                         **cfg_over)
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=events,
                 rng=random.Random(7), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    return cfg, r, broker, mapping


def make_factory(cfg, r, broker, mapping, inj, ckpt):
    """Fresh engine + wrapped reader + runner per supervised attempt."""
    def make_runner():
        eng = AdAnalyticsEngine(cfg, mapping, redis=inj.wrap_redis(r))
        reader = inj.wrap_reader(broker.reader(cfg.kafka_topic))
        return StreamRunner(eng, reader, checkpointer=ckpt,
                            crash_points=inj.scheduler)
    return make_runner


def supervise(tmp_path, cfg, r, broker, mapping, plan, seed=1):
    inj = FaultInjector(plan)
    ckpt = Checkpointer(str(tmp_path / "ckpt"))
    sup = Supervisor(make_factory(cfg, r, broker, mapping, inj, ckpt),
                     backoff_base_ms=1, backoff_cap_ms=4, seed=seed)
    st = sup.run(catchup=True)
    assert st.completed, f"supervised run did not complete: {st.errors}"
    sup.runner.engine.close()
    return st, inj, sup


def test_all_three_surfaces_within_oracle_bounds(tmp_path):
    """The headline acceptance run: sink outage + scattered sink errors,
    torn/truncated/corrupt journal reads, and a 4-crash script, all from
    one fixed seed."""
    cfg, r, broker, mapping = setup_run(tmp_path)
    plan = FaultPlan.generate(
        1234,
        sink_rate=0.25, sink_ops=30, sink_outage=(5, 6),
        journal_rate=0.4, journal_polls=12,
        crashes=0)
    # explicit crash script (generate()'s randomized ordinals can land on
    # boundaries a fast CPU catchup never reaches; the acceptance run
    # must inject >= 3 actual crashes)
    plan = FaultPlan(seed=plan.seed, sink_faults=plan.sink_faults,
                     journal_faults=plan.journal_faults,
                     crashes=(("batch", 5), ("flush", 1), ("batch", 2),
                              ("checkpoint", 1)))
    st, inj, sup = supervise(tmp_path, cfg, r, broker, mapping, plan)
    assert st.crashes >= 3
    assert inj.counters.get("chaos_sink_faults") > 0
    assert inj.counters.get("journal_faults") > 0
    v = check_at_least_once(r, str(tmp_path),
                            broker.topic_path(cfg.kafka_topic),
                            st.replay_segments, st.carried,
                            repro=replay_note(
                                seed=plan.seed,
                                topic_path=broker.topic_path(
                                    cfg.kafka_topic)))
    assert v.ok, (v.summary(), v.undercounts[:3], v.overcounts[:3])
    assert v.windows > 0
    # cumulative accounting survived every crash: the resumed engine's
    # event count (restored from snapshots) covers the whole journal
    assert sup.runner.engine.events_processed == 12_000


def test_all_three_surfaces_with_ingest_pipeline(tmp_path):
    """The headline acceptance run again with the staged ingest pipeline
    ON (ISSUE 3): crashes land while the reader/encode stages hold
    prefetched blocks in flight, and the at-least-once bound must still
    verify — quiesce()/folded offsets never skip an unfolded block, and
    read-ahead past the crash offset is replayed, not lost."""
    cfg, r, broker, mapping = setup_run(tmp_path,
                                        jax_ingest_pipeline="on")
    plan = FaultPlan.generate(
        1234,
        sink_rate=0.25, sink_ops=30, sink_outage=(5, 6),
        journal_rate=0.4, journal_polls=12,
        crashes=0)
    plan = FaultPlan(seed=plan.seed, sink_faults=plan.sink_faults,
                     journal_faults=plan.journal_faults,
                     crashes=(("batch", 5), ("flush", 1), ("batch", 2),
                              ("checkpoint", 1)))
    st, inj, sup = supervise(tmp_path, cfg, r, broker, mapping, plan)
    assert st.crashes >= 3
    assert inj.counters.get("chaos_sink_faults") > 0
    assert inj.counters.get("journal_faults") > 0
    v = check_at_least_once(r, str(tmp_path),
                            broker.topic_path(cfg.kafka_topic),
                            st.replay_segments, st.carried)
    assert v.ok, (v.summary(), v.undercounts[:3], v.overcounts[:3])
    assert v.windows > 0
    assert sup.runner.engine.events_processed == 12_000
    # the final attempt really ran the staged pipeline
    assert sup.runner._pipeline is not None


def test_crash_between_flush_and_checkpoint_overcounts_within_bound(
        tmp_path):
    """The documented replay window, hit on purpose: crash right after a
    flush whose writes landed but BEFORE the covering snapshot — the
    replayed counts must exceed the oracle yet stay within the recorded
    replay-segment bound (proves the bound check is not vacuous)."""
    cfg, r, broker, mapping = setup_run(tmp_path, events=6_000)
    # attempt 1: crash at batch 3 (no checkpoint yet -> full replay);
    # attempt 2: crash at the final flush, after its write landed and
    # before the final checkpoint; attempt 3: completes.
    plan = FaultPlan(crashes=(("batch", 3), ("flush", 1)))
    st, _, _ = supervise(tmp_path, cfg, r, broker, mapping, plan)
    assert st.crashes == 2
    v = check_at_least_once(r, str(tmp_path),
                            broker.topic_path(cfg.kafka_topic),
                            st.replay_segments, st.carried)
    assert v.ok, (v.summary(), v.undercounts[:3], v.overcounts[:3])
    # the flush-then-crash attempt replays from offset 0: counts land
    # twice, strictly above the oracle, inside the segment bound
    assert v.within_bound > 0 and v.max_overcount > 0


def test_zero_plan_is_exact_passthrough(tmp_path):
    """Chaos layer attached with an all-zeros plan == no chaos layer:
    identical Redis window state and identical run accounting."""
    cfg, r, broker, mapping = setup_run(tmp_path, events=6_000)

    plain = AdAnalyticsEngine(cfg, mapping, redis=r)
    ps = StreamRunner(plain, broker.reader(cfg.kafka_topic)).run_catchup()
    plain.close()
    baseline = read_seen_counts(r)

    r2 = as_redis(FakeRedisStore())
    from streambench_tpu.io.redis_schema import seed_campaigns

    seed_campaigns(r2, gen.load_ids(str(tmp_path))[0])
    inj = FaultInjector(FaultPlan.zeros())
    eng = AdAnalyticsEngine(cfg, mapping, redis=inj.wrap_redis(r2))
    cs = StreamRunner(eng, inj.wrap_reader(broker.reader(cfg.kafka_topic)),
                      crash_points=inj.scheduler).run_catchup()
    eng.close()

    assert read_seen_counts(r2) == baseline
    assert (cs.events, cs.batches, cs.windows_written) == \
        (ps.events, ps.batches, ps.windows_written)
    assert inj.counters.snapshot() == {}
    assert cs.faults == ps.faults == {}


def test_sink_outage_only_recovers_exactly(tmp_path):
    """A pure sink outage (no crashes): retained batches + backoff +
    reconnect retry until the outage lifts; final counts oracle-exact."""
    cfg, r, broker, mapping = setup_run(tmp_path, events=6_000)
    plan = FaultPlan(sink_faults={i: "refused" for i in range(8)})
    st, inj, _ = supervise(tmp_path, cfg, r, broker, mapping, plan)
    assert st.crashes == 0 and st.attempts == 1
    correct, differ, missing = gen.check_correct(
        r, str(tmp_path), log=lambda s: None)
    assert differ == 0 and missing == 0 and correct > 0
    assert inj.counters.get("chaos_sink_faults") > 0
    assert st.stats.faults.get("sink_errors", 0) > 0
    assert st.stats.faults.get("sink_retries", 0) > 0
