"""Count-based micro-batch mode (the fork's barrier-aligned windows,
``AdvertisingTopologyNative.java:167-254``): golden-model window counts,
barrier agreement across partitions, fork-format latency dump, and
end-of-stream behavior with unequal partitions."""

import json
import random
import threading

import numpy as np

from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.engine.microbatch import (
    LocalWindowBarrier,
    RedisWindowBarrier,
    run_microbatch,
)
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import as_redis, read_latency_hash


def setup(tmp_path, events=1800, partitions=3, window_size=300):
    cfg = default_config(window_size=window_size, map_partitions=partitions)
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(None, cfg, broker=broker, events_num=events,
                 rng=random.Random(21), workdir=str(tmp_path),
                 partitions=partitions)
    mapping = gen.load_ad_mapping_file(str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    campaigns, _ = gen.load_ids(str(tmp_path))
    return cfg, broker, mapping, campaigns


def golden_windows(broker, cfg, mapping, campaigns):
    """Recompute expected per-(window, campaign) view counts from the
    partition journals — the count-window analog of dostats."""
    P = cfg.map_partitions
    psize = cfg.window_size // P
    cidx = {c: i for i, c in enumerate(campaigns)}
    per_part = []
    for p in range(P):
        with broker.reader(cfg.kafka_topic, p) as r:
            lines = []
            while True:
                got = r.poll()
                if not got:
                    break
                lines.extend(got)
        per_part.append(lines)
    n_windows = min(len(l) // psize for l in per_part)
    out = []
    for k in range(n_windows):
        counts = np.zeros(len(campaigns), np.int64)
        for p in range(P):
            for line in per_part[p][k * psize:(k + 1) * psize]:
                ev = json.loads(line)
                if ev["event_type"] == "view":
                    counts[cidx[mapping[ev["ad_id"]]]] += 1
        out.append(counts)
    return out


def test_microbatch_matches_golden_model(tmp_path):
    cfg, broker, mapping, campaigns = setup(tmp_path)
    merged, results = run_microbatch(cfg, broker, mapping, campaigns)
    expected = golden_windows(broker, cfg, mapping, campaigns)

    assert len(merged) == len(expected) == 6  # 1800 / 300
    got = [merged[k] for k in sorted(merged)]
    for g, e in zip(got, expected):
        np.testing.assert_array_equal(g.astype(np.int64), e)
    # every partition saw every window with the same stamps
    stamps = [r.stamps for r in results]
    assert stamps[0] == stamps[1] == stamps[2]
    assert all(r.windows == 6 and r.events == 600 for r in results)
    assert all(lat >= 0 for r in results for lat in r.latency.values())


def test_redis_barrier_agrees_and_is_delete_race_free(tmp_path):
    cfg, broker, mapping, campaigns = setup(tmp_path, events=900,
                                            window_size=300)
    r = as_redis(FakeRedisStore())
    barrier = RedisWindowBarrier(r, "barrier_tbl", cfg.map_partitions)
    merged, results = run_microbatch(cfg, broker, mapping, campaigns,
                                     barrier=barrier)
    assert len(merged) == 3
    stamps = [res.stamps for res in results]
    assert stamps[0] == stamps[1] == stamps[2]
    # per-window stamp fields persist (nothing HDEL'd mid-wait) and the
    # counter wrapped back to 0 after each full rendezvous
    for k in range(3):
        assert r.hget("barrier_tbl", f"start_time:{k}") is not None
    assert r.hget("barrier_tbl", "partition_count") == "0"


def test_latency_dump_uses_fork_hash_schema(tmp_path):
    cfg, broker, mapping, campaigns = setup(tmp_path, events=900,
                                            window_size=300)
    r = as_redis(FakeRedisStore())
    merged, results = run_microbatch(cfg, broker, mapping, campaigns,
                                     redis=r)
    running, per_idx = read_latency_hash(r, cfg.redis_hashtable)
    # one dump per partition: thread_idx 1..3
    assert set(per_idx) == {1, 2, 3}
    for idx in per_idx:
        # one latency per window, except when consecutive windows share a
        # millisecond stamp (fork-format latency maps are stamp-keyed)
        assert 1 <= len(per_idx[idx]) <= 3
        assert running[idx] >= 0


def test_unequal_partitions_end_without_deadlock(tmp_path):
    """One partition runs dry a window early: peers must be released (the
    rendezvous can never complete again) and the extra window dropped."""
    cfg, broker, mapping, campaigns = setup(tmp_path, events=1800,
                                            window_size=300)
    # truncate partition 2 to one window's worth of lines
    path = broker.topic_path(cfg.kafka_topic, 2)
    lines = open(path, "rb").read().splitlines()[:100]
    with open(path, "wb") as f:
        f.write(b"".join(l + b"\n" for l in lines))

    done = []
    t = threading.Thread(
        target=lambda: done.append(
            run_microbatch(cfg, broker, mapping, campaigns)),
        daemon=True)
    t.start()
    t.join(30)
    assert not t.is_alive(), "microbatch run deadlocked on unequal partitions"
    merged, results = done[0]
    assert len(merged) == 1  # only the first window assembled everywhere
    assert results[2].windows == 1


def test_missing_partition_is_an_error_not_empty_result(tmp_path):
    """map.partitions > generated partitions must fail loudly, not return
    {'windows': 0} silently."""
    import pytest

    cfg, broker, mapping, campaigns = setup(tmp_path, events=300,
                                            partitions=1, window_size=300)
    cfg = default_config(window_size=300, map_partitions=3)
    with pytest.raises(ValueError, match="no partition"):
        run_microbatch(cfg, broker, mapping, campaigns)


def test_barrier_timeout_is_an_error_not_eos():
    """A mid-stream barrier timeout must surface, not masquerade as
    end-of-stream."""
    import pytest

    b = LocalWindowBarrier(2, timeout_s=0.05)
    with pytest.raises(TimeoutError, match="failed to arrive"):
        b.arrive(0)  # the second partition never shows up


def test_unequal_partitions_redis_barrier_no_timeout(tmp_path):
    """Same end-of-stream scenario with the Redis barrier: the dry
    partition's abort broadcast must release peers promptly (no 60s
    timeout, no spurious error)."""
    import time as _time

    cfg, broker, mapping, campaigns = setup(tmp_path, events=1800,
                                            window_size=300)
    path = broker.topic_path(cfg.kafka_topic, 2)
    lines = open(path, "rb").read().splitlines()[:100]
    with open(path, "wb") as f:
        f.write(b"".join(l + b"\n" for l in lines))

    r = as_redis(FakeRedisStore())
    barrier = RedisWindowBarrier(r, "bt", cfg.map_partitions, timeout_s=20)
    t0 = _time.monotonic()
    merged, results = run_microbatch(cfg, broker, mapping, campaigns,
                                     barrier=barrier)
    assert _time.monotonic() - t0 < 10  # released by abort, not timeout
    assert len(merged) == 1 and results[2].windows == 1


def test_redis_barrier_reset_clears_stale_run_residue(tmp_path):
    """ADVICE r1 (medium): an aborted run leaves partition_count residue
    (every spinner had HINCRBY'd) and an aborted broadcast in the shared
    hashtable; the driver-side reset() — NOT a per-partition constructor
    clear — must scrub both, or a rerun mis-elects window owners."""
    r = as_redis(FakeRedisStore())
    # previous aborted run: 2 of 3 partitions had arrived, plus broadcast,
    # plus completed-window stamps (a stale stamp would satisfy a spinner
    # instantly, so partitions would stop rendezvousing at all)
    r.execute("HSET", "bt", "partition_count", "2")
    r.execute("HSET", "bt", "aborted", "1")
    r.execute("HSET", "bt", "start_time:0", "12345")
    b = RedisWindowBarrier(r, "bt", 1)
    b.reset()  # the single driver-side reset point
    assert r.hget("bt", "start_time:0") is None
    stamp = b.arrive(0)  # single partition: owner immediately
    assert stamp > 12345  # a fresh stamp, not the stale one
    # owner election happened at count==1, not at stale 2+1
    assert r.hget("bt", "partition_count") == "0"


def test_redis_barrier_construction_has_no_side_effects(tmp_path):
    """ADVICE r1 (low): a late partition's constructor must not erase a
    live run's end-of-stream broadcast."""
    r = as_redis(FakeRedisStore())
    r.execute("HSET", "bt", "aborted", "1")  # live broadcast from a peer
    RedisWindowBarrier(r, "bt", 3)  # late construction
    assert r.hget("bt", "aborted") == "1"  # broadcast survives


def test_redis_barrier_run_id_namespaces_fields(tmp_path):
    """Two runs sharing a hashtable but distinct run_ids can't see each
    other's counter, stamps, or abort broadcast."""
    r = as_redis(FakeRedisStore())
    a = RedisWindowBarrier(r, "bt", 1, run_id="runA")
    z = RedisWindowBarrier(r, "bt", 1, run_id="runZ")
    a.reset()
    z.reset()
    a.abort()  # run A ends
    assert z.arrive(0) > 0  # run Z is unaffected
    assert r.hget("bt", "start_time:runZ:0") is not None
    assert r.hget("bt", "aborted:runA") == "1"


def test_local_barrier_stamps_shared():
    b = LocalWindowBarrier(4)
    out = [[] for _ in range(4)]

    def worker(i):
        for k in range(5):
            out[i].append(b.arrive(k))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for k in range(5):
        assert len({out[i][k] for i in range(4)}) == 1  # same stamp
    assert out[0] == sorted(out[0])  # stamps never go backwards
