"""Checkpoint/resume (SURVEY section 5.4): crash mid-stream, restore the
newest snapshot, replay the journal tail — every window still CORRECT.

The reference has no working checkpointing (Flink's enableCheckpointing is
commented out, AdvertisingTopologyNative.java:81-84); its only resume story
is Kafka offsets.  These tests pin the stronger guarantee the rebuild
provides: snapshot = exact (offset, state) pair.
"""

import os
import random

import numpy as np
import pytest

from streambench_tpu.checkpoint import Checkpointer, Snapshot
from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import as_redis


def setup_run(tmp_path, events=12_000, batch=512):
    cfg = default_config(jax_batch_size=batch)
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=events,
                 rng=random.Random(7), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    return cfg, r, broker, mapping


def test_crash_resume_matches_oracle(tmp_path):
    """Process half, snapshot, *discard the engine* (the crash), build a
    fresh engine + reader from the checkpoint, finish: oracle-exact."""
    cfg, r, broker, mapping = setup_run(tmp_path)
    ckpt = Checkpointer(str(tmp_path / "ckpt"))

    eng1 = AdAnalyticsEngine(cfg, mapping, redis=r)
    reader1 = broker.reader(cfg.kafka_topic)
    runner1 = StreamRunner(eng1, reader1, checkpointer=ckpt)
    runner1.run_catchup(max_events=6000)
    # run_catchup saved a final snapshot after its final flush
    snap = ckpt.load()
    assert snap is not None and snap.offset == reader1.offset
    del eng1, runner1  # crash

    eng2 = AdAnalyticsEngine(cfg, mapping, redis=r)
    reader2 = broker.reader(cfg.kafka_topic)
    runner2 = StreamRunner(eng2, reader2, checkpointer=ckpt)
    assert runner2.resume()
    assert reader2.offset == snap.offset
    runner2.run_catchup()
    eng2.close()

    correct, differ, missing = gen.check_correct(r, str(tmp_path),
                                                 log=lambda s: None)
    assert differ == 0 and missing == 0 and correct > 0
    assert eng2.events_processed == 12_000


def test_snapshot_restore_roundtrip_exact(tmp_path):
    """snapshot() -> restore() onto a fresh engine reproduces device state,
    pending deltas, latency ledger, and encoder base bit-exactly."""
    cfg, r, broker, mapping = setup_run(tmp_path, events=4000, batch=256)
    eng = AdAnalyticsEngine(cfg, mapping, redis=r)
    reader = broker.reader(cfg.kafka_topic)
    StreamRunner(eng, reader).run_catchup(max_events=2000)
    # leave undrained device counts AND a pending buffer behind
    eng._drain_device()
    snap = eng.snapshot(reader.offset)

    eng2 = AdAnalyticsEngine(cfg, mapping, redis=r)
    eng2.restore(snap)
    assert eng2.encoder.base_time_ms == eng.encoder.base_time_ms
    np.testing.assert_array_equal(np.asarray(eng2.state.counts),
                                  np.asarray(eng.state.counts))
    np.testing.assert_array_equal(np.asarray(eng2.state.window_ids),
                                  np.asarray(eng.state.window_ids))
    assert int(eng2.state.watermark) == int(eng.state.watermark)
    assert int(eng2.state.dropped) == int(eng.state.dropped)
    assert dict(eng2._pending) == dict(eng._pending)
    assert eng2.window_latency == eng.window_latency
    assert eng2.events_processed == eng.events_processed


def test_campaign_count_mismatch_rejected(tmp_path):
    cfg, r, broker, mapping = setup_run(tmp_path, events=100, batch=64)
    eng = AdAnalyticsEngine(cfg, mapping, redis=r)
    snap = eng.snapshot(0)
    snap.meta["num_campaigns"] = 7
    with pytest.raises(ValueError, match="num_campaigns"):
        eng.restore(snap)


def test_ring_geometry_mismatch_rejected(tmp_path):
    """A snapshot taken under one (W, divisor, lateness) must not restore
    into an engine with another: window ids/slots would be reinterpreted
    and counts silently corrupted."""
    cfg, r, broker, mapping = setup_run(tmp_path, events=100, batch=64)
    eng = AdAnalyticsEngine(cfg, mapping, redis=r)
    for key in ("window_slots", "divisor_ms", "lateness_ms"):
        snap = eng.snapshot(0)
        snap.meta[key] += 1
        with pytest.raises(ValueError, match=key):
            eng.restore(snap)


def test_reader_seek_clears_handle_and_readahead(tmp_path):
    """resume() must physically reposition an already-polled reader: the
    open file handle and the read-ahead buffer both hold the old spot."""
    from streambench_tpu.io.journal import JournalReader, JournalWriter

    path = str(tmp_path / "t.jsonl")
    with JournalWriter(path) as w:
        w.append_many([f"line{i}" for i in range(6)])
    r = JournalReader(path)
    assert r.poll(2) == [b"line0", b"line1"]  # rest lands in read-ahead
    mid = r.offset
    assert r.poll(2) == [b"line2", b"line3"]
    r.seek(mid)
    assert r.poll(100) == [b"line2", b"line3", b"line4", b"line5"]
    assert r.offset == os.path.getsize(path)


def test_checkpointer_rotation_and_torn_file(tmp_path):
    ck = Checkpointer(str(tmp_path / "ck"), keep=2)
    mk = lambda off: Snapshot(
        offset=off, meta=dict(base_time_ms=0, span_start=None,
                              events_processed=off, windows_written=0,
                              started_ms=0, last_event_ms=0,
                              num_campaigns=3),
        counts=np.zeros((3, 4), np.int32),
        window_ids=np.full(4, -1, np.int32), watermark=0, dropped=0,
        pending=[(1, 20_000, 5)], latency=[(20_000, 12)])
    p1 = ck.save(mk(100))
    p2 = ck.save(mk(200))
    p3 = ck.save(mk(300))
    import os
    assert not os.path.exists(p1) and os.path.exists(p2)  # pruned to keep=2
    # tear the newest file: load falls back to the previous snapshot
    with open(p3, "wb") as f:
        f.write(b"\x00" * 10)
    snap = ck.load()
    assert snap is not None and snap.offset == 200
    assert snap.pending == [(1, 20_000, 5)]
    assert snap.latency == [(20_000, 12)]

    # a new Checkpointer in the same dir continues the sequence
    ck2 = Checkpointer(str(tmp_path / "ck"), keep=2)
    ck2.save(mk(400))
    assert ck2.load().offset == 400


def test_snapshot_mid_deferral_carries_parked_cycle(tmp_path, monkeypatch):
    """A snapshot taken while drain cycles are parked (deferred-pull
    mode, forced on CPU) must carry the parked deltas —
    ``_snapshot_sync`` drains BOTH lists — so crash-after-snapshot +
    restore reproduces exactly the uninterrupted engine's Redis
    contents."""
    from tests.test_scan_chunk import make_lines

    from streambench_tpu.io.redis_schema import (
        read_seen_counts,
        seed_campaigns,
    )

    monkeypatch.setenv("STREAMBENCH_DEFER_DRAIN_PULL", "1")
    lines, mapping, campaigns = make_lines(3000, seed=5)
    cfg = default_config(jax_batch_size=256, jax_window_slots=16)
    r = as_redis(FakeRedisStore())
    seed_campaigns(r, campaigns)
    src = AdAnalyticsEngine(cfg, mapping, campaigns=campaigns, redis=r)
    assert src._defer_pull
    src.process_chunk(lines[:2000])
    src.flush()  # parks the first cycle (nothing written yet)
    src.process_chunk(lines[2000:])
    src.flush()  # materializes+writes cycle 1; parks cycle 2
    snap = src.snapshot(offset=0)
    src.drain_writes()
    del src  # crash: no close(), the parked cycle only lives in snap

    dst = AdAnalyticsEngine(cfg, mapping, campaigns=campaigns, redis=r)
    dst.restore(snap)
    dst.close()  # writes the snapshot-carried pending

    r2 = as_redis(FakeRedisStore())
    seed_campaigns(r2, campaigns)
    ref = AdAnalyticsEngine(cfg, mapping, campaigns=campaigns, redis=r2)
    ref.process_chunk(lines)
    ref.close()
    assert read_seen_counts(r) == read_seen_counts(r2)


def test_restore_watermark_sentinel_states(tmp_path):
    """_restore_host must gate the host watermark mirror on the NEG
    sentinel, not truthiness (ADVICE.md): a legitimate relative
    watermark of 0 is SET (host_wm = base), the NEG 'no events' value
    and a pre-first-event base are UNSET (None)."""
    from streambench_tpu.ops import windowcount as wc

    cfg, r, broker, mapping = setup_run(tmp_path, events=100, batch=64)
    eng = AdAnalyticsEngine(cfg, mapping, redis=r)
    base = 1_000_000

    def restored(watermark, base_time_ms=base):
        snap = eng.snapshot(0)
        snap.watermark = watermark
        snap.meta["base_time_ms"] = base_time_ms
        dst = AdAnalyticsEngine(cfg, mapping, redis=r)
        dst.restore(snap)
        return dst._host_wm

    assert restored(0) == base              # legit zero watermark: SET
    assert restored(12_345) == base + 12_345
    assert restored(wc.NEG) is None         # 'no events' sentinel: unset
    assert restored(0, base_time_ms=None) is None  # pre-first-event snap
