"""Fleet diagnosis engine (ISSUE 17): evidence folding + the verdict
table.

``diagnose`` is pure — (evidence window, objective) -> ranked verdicts
— so every bottleneck family is pinned table-driven over synthetic
fleet journals:

- fold-bound: staleness breach whose age sits in the serve hop (the
  REACH_r04 hop physics: slow ship cadence ages the record WHILE
  serving) -> ``fold_lag`` / ship-cadence knob;
- tail-bound: the tail_lag hop dominates the breached staleness ->
  ``tail_lag`` / poll-interval knob;
- serve-bound: overloaded sheds (or a router e2e p99 breach) without
  contention evidence -> ``serve`` / replica-count knob;
- contention-bound: p99 breach, queue segment dominant, measured
  contention ratio >= 0.5 -> ``contention`` / batch-cadence knob;
- healthy: nothing breached -> no knob.

Counter semantics are differenced: a historic shed burst folded into
``prev`` must NOT read as a live breach.
"""

import pytest

from streambench_tpu.obs.diagnose import (
    KNOB_BATCH,
    KNOB_POLL,
    KNOB_REPLICAS,
    KNOB_SHIP,
    VERDICT_CONTENTION,
    VERDICT_FOLD,
    VERDICT_HEALTHY,
    VERDICT_SERVE,
    VERDICT_TAIL,
    diagnose,
    evidence_window,
)

OBJECTIVE = {"staleness_ms": 1000, "p99_ms": 100}


def replica_rec(pid=1000, *, staleness_ms=100.0, p99_ms=5.0, qps=10.0,
                served=50, shed=0, shed_stale=0, queue_high_water=1,
                hops=None, contention=None, segments=None,
                kind="snapshot"):
    rq = {"staleness_ms": staleness_ms, "p99_ms": p99_ms, "qps": qps,
          "served": served, "shed": shed, "shed_stale": shed_stale,
          "queue_high_water": queue_high_water}
    if hops is not None:
        rq["freshness"] = {"hops": {h: {"p99": v}
                                    for h, v in hops.items()}}
    if contention is not None or segments is not None:
        rq["query_obs"] = {
            "contention": {"ratio": contention},
            "segments": {s: {"p99": v}
                         for s, v in (segments or {}).items()}}
    return {"kind": kind, "role": "replica", "pid": pid,
            "ts_ms": 1_000, "reach_query": rq}


def router_rec(**kw):
    rt = {"routed": 100, "answered": 100, "shed": 0, "failovers": 0,
          "replicas": [{}, {}]}
    rt.update(kw)
    return {"kind": "snapshot", "role": "router", "pid": 2,
            "ts_ms": 1_001, "router": rt}


def top(verdicts):
    return verdicts[0]["verdict"], verdicts[0]["knob"]


# ----------------------------------------------------------------------
# evidence_window folding


def test_window_gauges_max_counters_sum_across_replicas():
    w = evidence_window([
        replica_rec(1000, staleness_ms=200, p99_ms=3, qps=10,
                    served=40, shed=2, hops={"tail_lag": 20}),
        replica_rec(1001, staleness_ms=900, p99_ms=8, qps=5,
                    served=10, shed=1, hops={"tail_lag": 80}),
    ])
    assert w["replicas"] == 2
    assert w["staleness_ms"] == 900          # worst case
    assert w["p99_ms"] == 8
    assert w["qps"] == 15.0                  # total work
    assert w["served"] == 50 and w["shed"] == 3
    assert w["hop_p99_ms"]["tail_lag"] == 80


def test_window_latest_snapshot_wins_per_role_pid():
    w = evidence_window([
        replica_rec(1000, served=10),
        replica_rec(1000, served=25),        # later record, same pid
    ])
    assert w["served"] == 25


def test_window_ignores_event_kinds_and_folds_router_ship_slo():
    w = evidence_window([
        {"kind": "event", "event": "whatever", "ts_ms": 5,
         "reach_query": {"served": 999}},
        replica_rec(shed=5, shed_stale=2),
        router_rec(shed=3, failovers=7, e2e_p99_ms=140.0),
        {"kind": "snapshot", "role": "writer", "pid": 3, "ts_ms": 6,
         "reach_ship": {"ships": 4, "interval_ms": 400}},
        {"kind": "snapshot", "role": "writer", "pid": 3, "ts_ms": 7,
         "slo": {"burn": {"60000": 0.5, "300000": 1.5}}},
    ])
    assert w["served"] == 50                 # event record ignored
    assert w["shed_overloaded"] == 3         # shed - shed_stale
    assert w["router_shed"] == 3 and w["router_failovers"] == 7
    assert w["router_replicas"] == 2
    # the router's front-door e2e p99 feeds the window's p99: a
    # serialized replica handle queues at the router, invisible to any
    # replica's own submit->reply percentiles
    assert w["p99_ms"] == 140.0
    assert w["ships"] == 4 and w["ship_interval_ms"] == 400
    assert w["slo_burn_max"] == 1.5


# ----------------------------------------------------------------------
# the verdict table


def test_fold_bound_staleness_breach_without_tail_dominance():
    # REACH_r04 hop physics: 2 s cadence ages the record while serving
    # — the growth is in the serve hop, the prescription is still the
    # ship cadence (the age accrued upstream of the tailer)
    w = evidence_window([replica_rec(
        staleness_ms=1500, hops={"fold_lag": 5, "ship_wait": 3,
                                 "tail_lag": 90, "serve": 1400})])
    v, k = top(diagnose(w, objective=OBJECTIVE))
    assert (v, k) == (VERDICT_FOLD, KNOB_SHIP)


def test_tail_bound_when_tail_hop_dominates():
    w = evidence_window([replica_rec(
        staleness_ms=1400, hops={"fold_lag": 30, "ship_wait": 20,
                                 "tail_lag": 1200, "serve": 150})])
    v, k = top(diagnose(w, objective=OBJECTIVE))
    assert (v, k) == (VERDICT_TAIL, KNOB_POLL)


def test_serve_bound_on_overloaded_sheds_without_staleness_breach():
    w = evidence_window([replica_rec(staleness_ms=100, shed=12,
                                     shed_stale=0)])
    out = diagnose(w, objective=OBJECTIVE)
    v, k = top(out)
    assert (v, k) == (VERDICT_SERVE, KNOB_REPLICAS)
    assert out[0]["evidence"]["shed_overloaded"] == 12


def test_serve_bound_on_router_e2e_p99_breach():
    w = evidence_window([replica_rec(staleness_ms=100, p99_ms=4),
                         router_rec(e2e_p99_ms=250.0)])
    v, k = top(diagnose(w, objective=OBJECTIVE))
    assert (v, k) == (VERDICT_SERVE, KNOB_REPLICAS)


def test_contention_bound_queue_dominant_with_measured_ratio():
    w = evidence_window([replica_rec(
        staleness_ms=100, p99_ms=180, contention=0.8,
        segments={"queue": 150, "batch": 5, "dispatch": 20,
                  "reply": 2})])
    v, k = top(diagnose(w, objective=OBJECTIVE))
    assert (v, k) == (VERDICT_CONTENTION, KNOB_BATCH)


def test_low_contention_ratio_falls_back_to_serve():
    w = evidence_window([replica_rec(
        staleness_ms=100, p99_ms=180, contention=0.1,
        segments={"queue": 150, "batch": 5, "dispatch": 20,
                  "reply": 2})])
    v, k = top(diagnose(w, objective=OBJECTIVE))
    assert (v, k) == (VERDICT_SERVE, KNOB_REPLICAS)


def test_healthy_when_nothing_breaches():
    w = evidence_window([replica_rec(staleness_ms=100, p99_ms=4)])
    out = diagnose(w, objective=OBJECTIVE)
    assert len(out) == 1
    assert top(out) == (VERDICT_HEALTHY, None)
    assert out[0]["score"] == 0.0


def test_dual_breach_ranks_both_verdicts():
    w = evidence_window([replica_rec(
        staleness_ms=2500, shed=8, shed_stale=0,
        hops={"fold_lag": 5, "tail_lag": 50, "serve": 2400})])
    out = diagnose(w, objective=OBJECTIVE)
    names = [v["verdict"] for v in out]
    assert VERDICT_FOLD in names and VERDICT_SERVE in names
    assert out[0]["score"] >= out[-1]["score"]


def test_prev_differencing_historic_sheds_do_not_breach():
    cur = evidence_window([replica_rec(staleness_ms=100, shed=12,
                                       shed_stale=0)])
    prev = dict(cur)                        # same cumulative counters
    out = diagnose(cur, objective=OBJECTIVE, prev=prev)
    assert top(out) == (VERDICT_HEALTHY, None)
    # ... while NEW sheds since prev still breach
    newer = evidence_window([replica_rec(staleness_ms=100, shed=20,
                                         shed_stale=0)])
    v, k = top(diagnose(newer, objective=OBJECTIVE, prev=prev))
    assert (v, k) == (VERDICT_SERVE, KNOB_REPLICAS)


def test_every_verdict_carries_measured_evidence():
    w = evidence_window([replica_rec(
        staleness_ms=1500, shed=5, shed_stale=1,
        hops={"fold_lag": 5, "tail_lag": 90, "serve": 1400})])
    for v in diagnose(w, objective=OBJECTIVE):
        ev = v["evidence"]
        assert ev["hop_p99_ms"]            # non-empty hop decomposition
        assert ev["objective"] == OBJECTIVE
        assert v["why"]


def test_partial_objective_only_checks_named_limits():
    w = evidence_window([replica_rec(staleness_ms=5000,
                                     hops={"serve": 4900})])
    # no staleness limit in the objective -> no staleness verdict
    out = diagnose(w, objective={"p99_ms": 100})
    assert top(out) == (VERDICT_HEALTHY, None)


@pytest.mark.parametrize("records", [[], [{"kind": "snapshot"}],
                                     [{"not": "a fleet record"}]])
def test_empty_or_foreign_windows_are_healthy(records):
    w = evidence_window(records)
    assert top(diagnose(w, objective=OBJECTIVE)) == (VERDICT_HEALTHY,
                                                     None)
