"""Kernel-method micro-bench (ops.methodbench): the measured path
``default_method`` consults, at smoke sizes (VERDICT 7)."""

import json
import subprocess
import sys

import pytest

from streambench_tpu.ops import methodbench


@pytest.fixture()
def cache(tmp_path, monkeypatch):
    path = tmp_path / "method_bench.json"
    monkeypatch.setenv("STREAMBENCH_METHOD_CACHE", str(path))
    return path


def test_measure_methods_smoke(cache):
    res = methodbench.measure_methods(num_campaigns=8, window_slots=4,
                                      batch_size=64, iters=2)
    assert set(res["methods"]) == set(methodbench.METHODS)
    for m, v in res["methods"].items():
        assert "ns_per_event" in v or "error" in v, m
    assert res["winner"] in methodbench.METHODS
    timed = res["methods"][res["winner"]]["ns_per_event"]
    assert timed > 0


def test_measure_and_record_roundtrip(cache):
    res = methodbench.measure_and_record(num_campaigns=8, window_slots=4,
                                         batch_size=64, iters=1)
    assert cache.exists()
    data = json.loads(cache.read_text())
    key = methodbench.method_key(res["backend"], 8)
    assert data[key]["winner"] == res["winner"]
    # the consult path default_method uses
    assert methodbench.cached_winner(res["backend"], 8) == res["winner"]
    # a different campaign bucket is NOT trusted
    assert methodbench.cached_winner(res["backend"], 8192) is None
    assert methodbench.cached_winner("no-such-backend", 8) is None


def test_default_method_consults_measurement(cache):
    import jax

    from streambench_tpu.engine.pipeline import default_method

    backend = jax.default_backend()
    heuristic = default_method(100)
    # a measured winner overrides the heuristic for its bucket...
    other = "matmul" if heuristic != "matmul" else "scatter"
    methodbench.record(methodbench.method_key(backend, 100),
                       {"winner": other})
    assert default_method(100) == other
    # ...but a corrupt entry falls back to the heuristic
    methodbench.record(methodbench.method_key(backend, 100),
                       {"winner": "not-a-method"})
    assert default_method(100) == heuristic
    # unknown geometry never consults the cache
    assert default_method(None) == default_method(None)


def test_cache_tolerates_garbage_file(cache):
    cache.write_text("{ not json")
    assert methodbench.cached_winner("cpu", 8) is None
    methodbench.record("cpu/devdecode", {"winner": "host"})
    assert methodbench.cached_value("cpu/devdecode") == {"winner": "host"}


def test_cli_smoke_records_measured_winner(cache):
    """CI's measured-path exercise: the module CLI at --smoke sizes
    writes BOTH family tables — the count winner default_method
    consults AND the sliding table jax.sliding.sliced=auto consults."""
    p = subprocess.run(
        [sys.executable, "-m", "streambench_tpu.ops.methodbench",
         "--smoke"],
        capture_output=True, text=True, timeout=300,
        env={**__import__("os").environ,
             "STREAMBENCH_METHOD_CACHE": str(cache),
             "JAX_PLATFORMS": "cpu"})
    assert p.returncode == 0, p.stderr[-500:]
    res = json.loads(p.stdout)
    count = res["count"]
    assert count["winner"] in methodbench.METHODS
    assert methodbench.cached_winner(count["backend"], 8) == \
        count["winner"]
    # the sliding table exists (ISSUE 12 CI contract)
    sl = res["sliding"]
    assert sl["winner"] in methodbench.SLIDING_METHODS
    assert set(sl["methods"]) == set(methodbench.SLIDING_METHODS)
    assert methodbench.sliding_winner(
        sl["backend"], sl["memberships"]) == sl["winner"]


def test_measure_sliding_smoke_and_winner_roundtrip(cache):
    res = methodbench.measure_and_record_sliding(
        num_campaigns=8, window_slots=128, batch_size=64, iters=1)
    assert set(res["methods"]) == set(methodbench.SLIDING_METHODS)
    assert res["winner"] in methodbench.SLIDING_METHODS
    assert res["memberships"] == 10
    assert methodbench.sliding_winner(res["backend"], 10) == res["winner"]
    # a different S-bucket is NOT trusted
    assert methodbench.sliding_winner(res["backend"], 5) is None
    # the auto resolution consults the measurement
    from streambench_tpu.engine.sketches import _sliced_auto

    methodbench.record(methodbench.sliding_key(res["backend"], 10),
                       {"winner": "scatter"})
    assert _sliced_auto(res["backend"], 10, 8, 128) is False
    methodbench.record(methodbench.sliding_key(res["backend"], 10),
                       {"winner": "sliced"})
    assert _sliced_auto(res["backend"], 10, 8, 128) is True
    # unmeasured geometry: sliced by default where the plane fits...
    assert _sliced_auto(res["backend"], 5, 8, 128) is True
    # ...never where it cannot (S > W or plane too large)
    assert _sliced_auto(res["backend"], 10, 8, 8) is False
    assert _sliced_auto(res["backend"], 10, 1 << 22, 128) is False
