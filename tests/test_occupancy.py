"""Measured device occupancy (ISSUE 8 tentpole, obs.occupancy): 1-in-N
sampling cadence, busy-ratio extrapolation, the recompile detector's
steady-state-zero invariant, engine integration (sampling must not
change a single count), and the sampler journal block."""

import random

import jax
import jax.numpy as jnp
import pytest

from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import (
    as_redis,
    read_seen_counts,
    seed_campaigns,
)
from streambench_tpu.obs import (
    CompileWatcher,
    MetricsRegistry,
    OccupancySampler,
)


def test_sampling_cadence_and_counters():
    reg = MetricsRegistry()
    occ = OccupancySampler(reg, sample_every=4, watch_compiles=False)
    x = jnp.ones(8)
    for _ in range(10):
        occ.note_dispatch(x)
    assert occ.dispatches == 10
    assert occ.sampled == 2          # dispatches 4 and 8
    s = occ.summary()
    assert s["sample_every"] == 4
    assert s["device_busy_ms_sampled"] > 0
    assert 0.0 <= s["device_busy_ratio"]
    assert s["dispatch_ms"]["count"] == 2
    assert reg.counter(
        "streambench_device_dispatches_total").value == 10
    assert reg.counter(
        "streambench_device_sampled_dispatches_total").value == 2
    assert (reg.gauge("streambench_device_busy_ratio").value
            == pytest.approx(occ.busy_ratio(), rel=0.5))
    occ.close()


def test_sample_every_one_times_every_dispatch():
    occ = OccupancySampler(None, sample_every=1, watch_compiles=False)
    x = jnp.ones(4)
    for _ in range(3):
        occ.note_dispatch(x)
    assert occ.dispatches == 3 and occ.sampled == 3
    s = occ.summary()
    assert s["device_busy_ms_sampled"] > 0
    assert s["device_busy_ratio"] > 0
    # extrapolation factor 1: the ratio never exceeds busy/wall by more
    # than clock skew between the two monotonic reads
    assert (s["device_busy_ms_sampled"]
            <= occ.busy_ratio() * occ.wall_ms() * 1.5 + 0.01)
    # no registry: summary still works, just without the histogram
    assert "dispatch_ms" not in s


def test_compile_watcher_steady_state_zero_invariant():
    reg = MetricsRegistry()
    w = CompileWatcher(reg)
    if not w.supported:
        pytest.skip("jax.monitoring unavailable")
    # a fresh shape compiles and is counted pre-steady
    f = jax.jit(lambda v: v + 7)
    f(jnp.ones(3))
    pre = w.summary()["compiles_total"]
    assert pre >= 1
    w.mark_steady()
    # cache hit on the SAME jitted callable: NOT a compile
    f(jnp.ones(3))
    w.assert_steady_zero()
    # a new shape after steady: the PR 7 gotcha made executable
    jax.jit(lambda v: v * 9)(jnp.ones(5))
    s = w.summary()
    assert s["compiles_steady"] >= 1
    with pytest.raises(AssertionError):
        w.assert_steady_zero()
    assert reg.counter("streambench_compiles_total").value >= pre + 1
    assert reg.counter(
        "streambench_compiles_steady_total").value >= 1
    w.close()
    # closed watchers no longer count
    before = w.summary()["compiles_total"]
    jax.jit(lambda v: v - 2)(jnp.ones(6))
    assert w.summary()["compiles_total"] == before


def test_engine_sampling_bit_identity_of_counts(tmp_path):
    """The occupancy sampler only OBSERVES: replaying the SAME journal
    with sampling on, every window count written to the sink is
    identical to the unsampled run, event and window totals included."""
    from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner

    cfg = default_config(jax_batch_size=256, jax_scan_batches=2)
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(as_redis(FakeRedisStore()), cfg, broker=broker,
                 events_num=6000, rng=random.Random(9),
                 workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))

    def run(occupancy):
        r = as_redis(FakeRedisStore())
        seed_campaigns(r, sorted(set(mapping.values())))
        engine = AdAnalyticsEngine(cfg, mapping, redis=r)
        if occupancy is not None:
            engine.attach_obs(MetricsRegistry(), occupancy=occupancy)
        runner = StreamRunner(engine, broker.reader(cfg.kafka_topic))
        stats = runner.run_catchup()
        engine.close()
        return stats, r

    occ = OccupancySampler(MetricsRegistry(), sample_every=2,
                           watch_compiles=False)
    stats_on, r_on = run(occ)
    stats_off, r_off = run(None)
    assert occ.dispatches > 0 and occ.sampled > 0
    assert stats_on.events == stats_off.events
    assert stats_on.windows_written == stats_off.windows_written
    # canonical-schema equality: every (campaign, window) seen_count
    counts_on = read_seen_counts(r_on)
    counts_off = read_seen_counts(r_off)
    assert counts_on == counts_off
    assert any(counts_on.values())   # the comparison saw real windows
    occ.close()


def test_collector_journals_occupancy_block(tmp_path):
    from streambench_tpu.metrics import FaultCounters
    from streambench_tpu.obs import engine_collector
    from streambench_tpu.trace import Tracer

    class _Eng:
        tracer = Tracer()
        faults = FaultCounters()
        events_processed = 0
        _obs_hist = None

        def telemetry(self):
            return {"events": 0, "windows_written": 0,
                    "watermark_lag_ms": None, "sink_dirty_rows": 0,
                    "pending_rows": 0}

    eng = _Eng()
    reg = MetricsRegistry()
    occ = OccupancySampler(reg, sample_every=2, watch_compiles=False)
    occ.note_dispatch(jnp.ones(2))
    occ.note_dispatch(jnp.ones(2))
    eng._obs_occupancy = occ
    rec: dict = {}
    engine_collector(eng, registry=reg)(rec, 1.0)
    assert rec["occupancy"]["dispatches"] == 2
    assert rec["occupancy"]["sampled"] == 1
    occ.close()
    # without the sampler the key is absent — old journals unchanged
    eng2 = _Eng()
    rec2: dict = {}
    engine_collector(eng2, registry=MetricsRegistry())(rec2, 1.0)
    assert "occupancy" not in rec2
