"""StreamRunner's adaptive dispatch target, pinned (ISSUE 3 satellite).

The streaming loop grows its dispatch target toward one scan chunk while
the reader keeps returning FULL reads (backlog: the producer is ahead),
and snaps back to one batch on any short read so steady-state latency
stays governed by ``buffer_timeout``.  In block mode the backlog
judgment is by BYTES with an explicit empty-read guard — an empty read
must never count as full, or a tiny byte budget at ``room == 1`` would
busy-spin on an idle stream.  These tests drive the SERIAL loop with
scripted readers and a stub engine, so the policy is observable directly
(poll sizes asked, chunk sizes dispatched, poll counts while idle).
"""

from __future__ import annotations

import time

from streambench_tpu.config import default_config
from streambench_tpu.engine.runner import StreamRunner
from streambench_tpu.metrics import FaultCounters

B = 32  # batch size for every test (small so doubling is cheap)
K = 4   # scan_batches -> chunk cap = 128


class StubEngine:
    """Minimal engine surface the runner touches: counts what it folds."""

    scan_batches = K
    supports_block_ingest = False

    def __init__(self):
        self.cfg = default_config(jax_batch_size=B, jax_scan_batches=K)
        self.faults = FaultCounters()
        self.events_processed = 0
        self.chunks: list[int] = []   # records per dispatch, in order

    def process_chunk(self, lines):
        self.chunks.append(len(lines))
        self.events_processed += len(lines)

    def process_block(self, data):
        n = data.count(b"\n")
        self.chunks.append(n)
        self.events_processed += n

    def flush(self, final=False):
        return 0


class BlockStubEngine(StubEngine):
    supports_block_ingest = True


class ScriptedReader:
    """Line-mode reader: serves ``supply`` lines, recording each poll's
    ``max_records`` (the runner's room = target - pending)."""

    def __init__(self, supply: int, short_after: int | None = None,
                 short_size: int = 3):
        self.supply = supply
        self.polls: list[int] = []
        self.offset = 0
        self.short_after = short_after  # polls before going short
        self.short_size = short_size

    def poll(self, max_records=65536):
        self.polls.append(max_records)
        n = max_records
        if (self.short_after is not None
                and len(self.polls) > self.short_after):
            n = min(n, self.short_size)
        n = min(n, self.supply)
        self.supply -= n
        self.offset += n
        return [b"x"] * n


class ScriptedBlockReader:
    """Block-mode reader: serves ``blocks`` (bytes) one per poll, then
    empties; records every byte budget asked."""

    def __init__(self, blocks: list[bytes]):
        self.blocks = list(blocks)
        self.budgets: list[int] = []
        self.offset = 0

    def poll_block(self, max_bytes=None):
        self.budgets.append(max_bytes)
        if not self.blocks:
            return b""
        data = self.blocks.pop(0)
        if max_bytes is not None and len(data) > max_bytes:
            # serve a budget-sized prefix at a record boundary
            cut = data.rfind(b"\n", 0, max_bytes) + 1
            data, rest = data[:cut], data[cut:]
            if rest:
                self.blocks.insert(0, rest)
        self.offset += len(data)
        return data

    def poll(self, max_records=65536):  # line fallback, unused
        raise AssertionError("block-mode test must not fall back to poll")


def make_runner(engine, reader, **kw):
    kw.setdefault("buffer_timeout_ms", 10_000)  # never dispatch by age
    return StreamRunner(engine, reader, **kw)


def test_full_reads_double_target_to_chunk_cap():
    """Backlog: every poll returns exactly what was asked (full reads),
    so the target doubles B -> 2B -> 4B and the first dispatch is one
    whole scan chunk (K*B), not K separate batches."""
    eng = StubEngine()
    reader = ScriptedReader(supply=2 * K * B)
    runner = make_runner(eng, reader)
    runner.run(max_events=2 * K * B)
    # polls asked: B (target B), then B (room after doubling to 2B),
    # then 2B (doubled to 4B) — growth is observable in the rooms
    assert reader.polls[0] == B
    assert reader.polls[1] == B
    assert reader.polls[2] == 2 * B
    # dispatches are whole chunks at the cap
    assert eng.chunks[0] == K * B, eng.chunks
    assert all(c <= K * B for c in eng.chunks)


def test_short_read_snaps_target_back_to_batch_size():
    """After the target grew under backlog, one SHORT read (producer
    caught up: got < room and pending < one batch) snaps the target
    back to batch_size — observable in the very next poll's room and in
    the partial batch dispatching alone at buffer timeout instead of
    waiting to refill a chunk-sized target."""
    eng = StubEngine()
    # exactly one grown chunk of backlog, then a 10-record dribble
    reader = ScriptedReader(supply=K * B + 10)
    runner = make_runner(eng, reader, buffer_timeout_ms=30)
    runner.run(idle_timeout_s=0.1)
    # growth: rooms 32, 32, 64 fill the 128 target -> chunk dispatch
    assert reader.polls[:3] == [B, B, 2 * B]
    assert eng.chunks[0] == K * B
    # the grown target carries over: poll 4 asks a full chunk, gets 10
    assert reader.polls[3] == K * B
    # SNAP-BACK: with 10 pending the next room is batch_size - 10, not
    # chunk-size - 10 (target back to one batch)
    assert reader.polls[4] == B - 10, reader.polls[:6]
    # and the 10-record partial dispatches alone once it is timeout-old
    assert eng.chunks[1:] == [10], eng.chunks


def test_block_mode_byte_budget_doubles_and_caps():
    """Block mode: full BYTE reads double the budget toward the chunk
    cap (room * EST_EVENT_BYTES), judged by bytes not record count."""
    est = StreamRunner.EST_EVENT_BYTES
    # each block exactly fills whatever budget is asked: build one big
    # backlog blob the reader slices per budget
    line = b"y" * (est - 1) + b"\n"         # exactly est bytes per record
    eng = BlockStubEngine()
    reader = ScriptedBlockReader([line * (4 * K * B)])
    runner = make_runner(eng, reader)
    runner.run(max_events=2 * K * B)
    assert reader.budgets[0] == B * est
    # full byte reads: budget doubles (room 2B - B pending = B, then 2B)
    assert reader.budgets[1] == B * est
    assert reader.budgets[2] == 2 * B * est
    assert eng.chunks[0] == K * B


def test_block_mode_room_one_idle_stream_does_not_busy_spin():
    """The ``room == 1`` edge (ISSUE 3 satellite): pending is one record
    short of the target, the stream goes idle, and every poll returns
    empty.  An empty read must never be judged ``full_read`` (len(data)
    >= budget - est holds vacuously at 0 >= 0!) — the loop must hit its
    1 ms yield, not busy-spin re-polling at 100% CPU."""
    line = b"z" * 99 + b"\n"                 # 100 B records, short reads
    eng = BlockStubEngine()
    # one partial block leaves pending = B - 1 (room 1), then idle
    reader = ScriptedBlockReader([line * (B - 1)])
    runner = make_runner(eng, reader, buffer_timeout_ms=40)
    t0 = time.monotonic()
    runner.run(idle_timeout_s=0.08)
    wall = time.monotonic() - t0
    polls = len(reader.budgets)
    # the run spans ~40 ms of room==1 empty polls + ~80 ms of idle; a
    # 1 ms yield per empty poll bounds the count near wall/1ms — a
    # busy-spin logs hundreds of polls per millisecond instead
    assert polls < max(wall, 0.05) * 4000, (
        f"busy-spin: {polls} polls in {wall:.2f}s")
    # the timeout-aged partial batch must still have been dispatched
    assert sum(eng.chunks) == B - 1


def test_room_one_empty_read_keeps_target_stable():
    """Regression guard on the full_read judgment itself: an idle
    stream's empty reads must not double the target (got > 0 is part of
    the block-mode backlog test)."""
    est = StreamRunner.EST_EVENT_BYTES
    line = b"z" * 99 + b"\n"
    eng = BlockStubEngine()
    reader = ScriptedBlockReader([line * (B - 1)])
    runner = make_runner(eng, reader, buffer_timeout_ms=40)
    runner.run(idle_timeout_s=0.05)
    # every budget asked while idle stays at room-scale (never doubled
    # past the batch target by phantom "full" empty reads)
    assert all(b <= B * est for b in reader.budgets), reader.budgets[:5]
