"""Hyper-extended HLL ladder (ops/hllx.py, ISSUE 13): the fold vs a
numpy hash-mirror register oracle, rung-0 bit-identity with the plain
user HLL, scan/packed-scan bit-identity, the shard-order-invariant
merge algebra, calibrated estimator accuracy vs exact numpy counts, and
the engine's close-row + checkpoint round-trip."""

import random

import jax.numpy as jnp
import numpy as np
import pytest

from streambench_tpu.ops import hllx
from streambench_tpu.reach import oracle as ro

C, G, R = 5, 8, 128
JOIN = np.array([0, 0, 1, 2, 3, 4, -1], np.int32)


def rand_batch(rng, B=256, ads=6, users=300):
    t0 = int(rng.integers(0, 10**6))
    return dict(
        ad_idx=rng.integers(0, ads, B).astype(np.int32),
        user_idx=rng.integers(0, users, B).astype(np.int32),
        event_type=rng.integers(0, 3, B).astype(np.int32),
        event_time=(t0 + 10 * np.arange(B)).astype(np.int32),
        valid=rng.random(B) > 0.15,
    )


def fold(state, batches):
    join = jnp.asarray(JOIN)
    for b in batches:
        state = hllx.step(state, join, jnp.asarray(b["ad_idx"]),
                          jnp.asarray(b["user_idx"]),
                          jnp.asarray(b["event_type"]),
                          jnp.asarray(b["event_time"]),
                          jnp.asarray(b["valid"]))
    return state


def oracle_registers(batches):
    """Independent numpy mirror of the ladder fold (reach.oracle hash
    mirrors; scalar loop, no vectorized sharing with the op)."""
    regs = np.zeros((C, G, R), np.int32)
    totals = np.zeros(C, np.int64)
    salts = ro.salts_np(G)
    p = R.bit_length() - 1
    for b in batches:
        for a, u, e, t, v in zip(b["ad_idx"], b["user_idx"],
                                 b["event_type"], b["event_time"],
                                 b["valid"]):
            camp = JOIN[a]
            if not (v and e == 0 and camp >= 0):
                continue
            totals[camp] += 1
            hu = ro.splitmix32_np(np.array([u], np.int32))[0]
            ht = ro.splitmix32_np(np.array([t], np.int32))[0]
            he = ro.splitmix32_np(
                np.array([hu ^ ht], np.uint32).astype(np.int64)
                .astype(np.int32))[0]
            for g in range(G):
                tok = np.uint32(he) & np.uint32((1 << g) - 1)
                if g == 0:
                    h = np.uint32(hu)
                else:
                    h = ro.splitmix32_np(
                        np.array([np.uint32(hu) ^ salts[g] ^ tok],
                                 np.uint32).astype(np.int64)
                        .astype(np.int32))[0]
                j = int(np.uint32(h) & np.uint32(R - 1))
                rank = int(ro.rank_np(np.array([h], np.uint32), p)[0])
                regs[camp, g, j] = max(regs[camp, g, j], rank)
    return regs, totals


# --------------------------------------------------------------- fold
def test_step_matches_numpy_register_oracle():
    rng = np.random.default_rng(3)
    batches = [rand_batch(rng, B=64) for _ in range(3)]
    st = fold(hllx.init_state(C, G, R), batches)
    regs, totals = oracle_registers(batches)
    np.testing.assert_array_equal(np.asarray(st.registers), regs)
    np.testing.assert_array_equal(np.asarray(st.totals), totals)
    assert int(st.dropped) == 0


def test_rung0_bit_identical_to_plain_user_hll():
    """The distinct rung hashes the bare user mix — its registers must
    equal a windowless fold of ops/hll.py's hash over the same users
    (the hllx engine's distinct answer IS the plain HLL answer)."""
    from streambench_tpu.ops.hll import splitmix32, _rank

    rng = np.random.default_rng(5)
    batches = [rand_batch(rng) for _ in range(4)]
    st = fold(hllx.init_state(C, G, R), batches)
    want = np.zeros((C, R), np.int32)
    p = R.bit_length() - 1
    for b in batches:
        h = np.asarray(splitmix32(jnp.asarray(b["user_idx"])))
        j = (h & np.uint32(R - 1)).astype(np.int64)
        rank = np.asarray(_rank(jnp.asarray(h), p))
        camp = JOIN[b["ad_idx"]]
        ok = b["valid"] & (b["event_type"] == 0) & (camp >= 0)
        for c, jj, r, o in zip(camp, j, rank, ok):
            if o:
                want[c, jj] = max(want[c, jj], r)
    np.testing.assert_array_equal(np.asarray(st.registers[:, 0, :]),
                                  want)


def test_scan_and_packed_scan_bit_identical():
    from streambench_tpu.ops import windowcount as wc

    rng = np.random.default_rng(6)
    batches = [rand_batch(rng, B=128) for _ in range(4)]
    seq = fold(hllx.init_state(C, G, R), batches)
    stacked = {k: np.stack([b[k] for b in batches]) for k in batches[0]}
    scanned = hllx.scan_steps(
        hllx.init_state(C, G, R), jnp.asarray(JOIN),
        jnp.asarray(stacked["ad_idx"]), jnp.asarray(stacked["user_idx"]),
        jnp.asarray(stacked["event_type"]),
        jnp.asarray(stacked["event_time"]), jnp.asarray(stacked["valid"]))
    for a, b in zip(seq, scanned):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    packed = np.stack([np.asarray(wc.pack_columns(
        b["ad_idx"], b["event_type"], b["valid"])) for b in batches])
    pscan = hllx.scan_steps_packed(
        hllx.init_state(C, G, R), jnp.asarray(JOIN), jnp.asarray(packed),
        jnp.asarray(stacked["user_idx"]),
        jnp.asarray(stacked["event_time"]))
    np.testing.assert_array_equal(np.asarray(seq.registers),
                                  np.asarray(pscan.registers))
    np.testing.assert_array_equal(np.asarray(seq.totals),
                                  np.asarray(pscan.totals))


def test_replay_is_idempotent():
    """Folding the same batches twice changes no registers (the
    at-least-once replay property the time-derived token buys) — only
    the exact F1 counter double-counts, as documented."""
    rng = np.random.default_rng(7)
    batches = [rand_batch(rng) for _ in range(3)]
    once = fold(hllx.init_state(C, G, R), batches)
    twice = fold(once, batches)
    np.testing.assert_array_equal(np.asarray(once.registers),
                                  np.asarray(twice.registers))


# ------------------------------------------------------- merge algebra
@pytest.mark.parametrize("seed", [11, 12])
def test_merge_shard_order_invariance(seed):
    rng = np.random.default_rng(seed)
    pyrng = random.Random(seed)
    batches = [rand_batch(rng, B=128) for _ in range(8)]
    reference = fold(hllx.init_state(C, G, R), batches)
    S = pyrng.choice([2, 3])
    shards = [[] for _ in range(S)]
    for b in batches:
        shards[pyrng.randrange(S)].append(b)
    partials = [fold(hllx.init_state(C, G, R), sh) for sh in shards]
    pyrng.shuffle(partials)
    merged = partials[0]
    for p in partials[1:]:
        merged = hllx.merge(merged, p)
    np.testing.assert_array_equal(np.asarray(merged.registers),
                                  np.asarray(reference.registers))
    np.testing.assert_array_equal(np.asarray(merged.totals),
                                  np.asarray(reference.totals))


def test_merge_geometry_mismatch_raises():
    a = hllx.init_state(C, G, R)
    b = hllx.init_state(C, G, 64)
    with pytest.raises(ValueError, match=r"hllx\.merge.*128.*64"):
        hllx.merge(a, b)


# ----------------------------------------------------------- estimators
def test_moments_track_exact_statistics():
    """Seeded Zipf workload: distinct within HLL error, calibrated
    log-moment within 15%, soft caps within 4 sigma of their exact
    soft-cap values, F1 exact."""
    rng = np.random.default_rng(21)
    st = hllx.init_state(C, G, R)
    events = []
    for c in range(C):
        counts = np.minimum(rng.zipf(1.3, 400), 128)
        for k, n in enumerate(counts):
            events.extend((c, c * 100_000 + k) for _ in range(n))
    rng.shuffle(events)
    ev = np.array(events, np.int64)
    times = (10 * np.arange(len(ev))).astype(np.int32)
    B = 512
    ad_of_c = np.array([0, 2, 3, 4, 5], np.int32)  # one ad per campaign
    for i in range(0, len(ev), B):
        n = min(B, len(ev) - i)
        pad = B - n
        st = hllx.step(
            st, jnp.asarray(JOIN),
            jnp.asarray(np.concatenate(
                [ad_of_c[ev[i:i + n, 0]], np.zeros(pad)]).astype(np.int32)),
            jnp.asarray(np.concatenate(
                [ev[i:i + n, 1], np.zeros(pad)]).astype(np.int32)),
            jnp.zeros((B,), jnp.int32),
            jnp.asarray(np.concatenate(
                [times[i:i + n], np.zeros(pad)]).astype(np.int32)),
            jnp.asarray(np.concatenate(
                [np.ones(n, bool), np.zeros(pad, bool)])))
    m = {k: np.asarray(v) for k, v in hllx.moments(st).items()}
    from collections import Counter
    cnt = Counter((int(c), int(u)) for c, u in ev)
    for c in range(C):
        cs = np.array([n for (cc, _), n in cnt.items() if cc == c])
        assert abs(m["distinct"][c] - len(cs)) / len(cs) < 0.2
        logm = np.log2(1 + cs).sum()
        assert abs(m["log_moment"][c] - logm) / logm < 0.15, (
            c, logm, m["log_moment"][c])
        assert int(m["totals"][c]) == int(cs.sum())
        for g in (2, 4, 6):
            t = 1 << g
            exact_sc = (t * (1 - (1 - 1 / t) ** cs)).sum()
            rel = abs(m["softcap"][c, g] - exact_sc) / max(exact_sc, 1)
            assert rel < 4 * 1.04 / np.sqrt(R), (c, g, rel)


# --------------------------------------------------------------- engine
def test_hllx_engine_end_to_end_and_checkpoint(tmp_path):
    from streambench_tpu.config import default_config
    from streambench_tpu.datagen import gen
    from streambench_tpu.engine import StreamRunner
    from streambench_tpu.engine.sketches import HLLXEngine
    from streambench_tpu.io.fakeredis import FakeRedisStore
    from streambench_tpu.io.journal import FileBroker
    from streambench_tpu.io.redis_schema import as_redis

    cfg = default_config(jax_batch_size=512)
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=6000,
                 rng=random.Random(77), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    eng = HLLXEngine(cfg, mapping, redis=r)
    stats = StreamRunner(eng, broker.reader(cfg.kafka_topic)).run_catchup()
    assert stats.events == 6000 and eng.dropped == 0
    m = eng.moments()
    assert int(m["totals"].sum()) > 0
    # F1 == exact wanted views (the engine's own counter is exact)
    import json as _json
    views = sum(1 for line in broker.read_all(cfg.kafka_topic)
                if _json.loads(line)["event_type"] == "view")
    assert int(m["totals"].sum()) == views

    snap = eng.snapshot(offset=7)
    eng2 = HLLXEngine(cfg, mapping, redis=None)
    eng2.restore(snap)
    np.testing.assert_array_equal(np.asarray(eng.state.registers),
                                  np.asarray(eng2.state.registers))
    np.testing.assert_array_equal(np.asarray(eng.state.totals),
                                  np.asarray(eng2.state.totals))

    eng.close()
    rows = r.hgetall(f"{cfg.redis_hashtable}_hllx")
    assert rows and any(str(k).endswith(":distinct") for k in rows), \
        list(rows)[:4]
    # close rows agree with the device estimates
    names = list(eng.encoder.campaigns)
    c0 = next(c for c in range(len(names)) if m["totals"][c] > 0)
    assert int(rows[f"{names[c0]}:views"]) == int(m["totals"][c0])