"""Exactly-once writeback: the fence protocol, executed (ISSUE 5).

The acceptance property: with ``jax.sink.exactly_once`` on, a supervised
chaos run over all three fault surfaces — INCLUDING the non-atomic
partial-apply sink fault the at-least-once model cannot represent —
finishes with ``redis_count(w) == oracle(w)`` for EVERY window, no
bound, no slack.  Plus the unit surfaces: zombie-writer epoch fencing,
fence-based retry dedup, taint-driven absolute reconcile, and the
``rows_lost`` shutdown accounting (satellite).
"""

import json
import random

import pytest

from streambench_tpu.chaos import (
    FaultInjector,
    FaultPlan,
    Supervisor,
    check_exactly_once,
    replay_note,
)
from streambench_tpu.checkpoint import Checkpointer
from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import (
    as_redis,
    fence_key,
    read_seen_counts,
    seed_campaigns,
)

XO = {"jax_sink_exactly_once": True}


# ----------------------------------------------------------------------
# unit surface: engines driven by hand over a tiny ad space
# ----------------------------------------------------------------------

MAPPING = {f"ad{i}": f"camp{i % 3}" for i in range(9)}


def view_lines(n, t0=1_000_000, step=10):
    return [json.dumps({"user_id": "u", "page_id": "p",
                        "ad_id": f"ad{i % 9}", "ad_type": "banner",
                        "event_type": "view",
                        "event_time": str(t0 + i * step),
                        "ip_address": "1.2.3.4"}).encode()
            for i in range(n)]


def make_engine(r, **over):
    cfg = default_config(jax_batch_size=64, jax_sink_retry_base_ms=1,
                         jax_sink_retry_cap_ms=2, **XO, **over)
    return AdAnalyticsEngine(cfg, MAPPING, redis=r)


def total_counts(r):
    return {(c, ts): n for c, per in read_seen_counts(r).items()
            for ts, n in per.items()}


def test_flag_off_writes_no_fence():
    """Default-off: no fence key, no ledger — the sink state is
    byte-identical to the pre-fence writeback."""
    r = as_redis(FakeRedisStore())
    seed_campaigns(r, ["camp0", "camp1", "camp2"])
    cfg = default_config(jax_batch_size=64)
    eng = AdAnalyticsEngine(cfg, MAPPING, redis=r)
    eng.process_lines(view_lines(100))
    eng.flush()
    eng.close()
    assert r.execute("HGET", fence_key(cfg.kafka_topic), "seq") is None
    assert eng._sink_totals == {} and not eng._taint


def test_fenced_flush_commits_fence_and_counts():
    r = as_redis(FakeRedisStore())
    seed_campaigns(r, ["camp0", "camp1", "camp2"])
    eng = make_engine(r)
    eng.process_lines(view_lines(200))
    eng.flush()
    eng.drain_writes()
    fk = fence_key(eng.cfg.kafka_topic)
    assert r.execute("HGET", fk, "epoch") == "1"
    assert r.execute("HGET", fk, "seq") == "1"
    assert r.execute("HGET", fk, "intent") == "1"
    counts = total_counts(r)
    assert sum(counts.values()) == 200
    eng.process_lines(view_lines(200))
    eng.flush()
    eng.drain_writes()
    assert r.execute("HGET", fk, "seq") == "2"
    assert sum(total_counts(r).values()) == 400
    eng.close()


def test_zombie_writer_is_fenced_out():
    """Satellite: two writers on one sink — the older epoch's flush must
    be rejected and counted (``fence_conflicts``), the newer epoch's
    rows must land intact."""
    r = as_redis(FakeRedisStore())
    seed_campaigns(r, ["camp0", "camp1", "camp2"])
    a = make_engine(r)
    a.process_lines(view_lines(90))
    a.flush()
    a.drain_writes()                      # epoch 1, 90 views on the sink
    before = total_counts(r)
    assert sum(before.values()) == 90

    b = make_engine(r)                    # same sink, fresh lineage
    b.process_lines(view_lines(90, t0=2_000_000))
    b.flush()
    b.drain_writes()                      # claims epoch 2
    fk = fence_key(b.cfg.kafka_topic)
    assert r.execute("HGET", fk, "epoch") == "2"

    # the superseded writer keeps draining: its flush must be DROPPED,
    # not applied and not retained for retry
    a.process_lines(view_lines(90))
    a.flush()
    a.drain_writes()
    assert a.faults.get("fence_conflicts") >= 1
    assert not a._writer.has_failed()
    after = total_counts(r)
    # epoch-1 windows untouched by the stale flush, epoch-2 rows intact
    for key, n in before.items():
        assert after[key] == n, (key, after[key], n)
    assert sum(after.values()) == 180
    b.close()
    # a.close() must not raise: fenced-out batches are not "unwritten"
    a.close()


class _ApplyThenRaise:
    """Sink proxy: applies the window-mutation pipeline FULLY, then
    raises — the response-lost timeout (the fence commit is on the sink
    but the writer saw an error)."""

    def __init__(self, target):
        self._target = target
        self.armed = 0

    def execute(self, *args):
        return self._target.execute(*args)

    def pipeline_execute(self, commands):
        cmds = list(commands)
        res = self._target.pipeline_execute(cmds)
        if self.armed and any(c[0] in ("HINCRBY",) or
                              (c[0] == "HSET" and "intent" in c)
                              for c in cmds):
            self.armed -= 1
            raise TimeoutError("stub: response lost after full apply")
        return res

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._target, name)


def test_fence_dedup_suppresses_retry_of_landed_flush():
    """A flush whose pipeline fully landed but whose response was lost
    must NOT be re-applied: the commit fence proves it landed, the retry
    is suppressed, and the counts stay exact."""
    store = as_redis(FakeRedisStore())
    seed_campaigns(store, ["camp0", "camp1", "camp2"])
    proxy = _ApplyThenRaise(store)
    eng = make_engine(proxy)
    eng.process_lines(view_lines(120))
    proxy.armed = 1
    eng.flush()
    eng.drain_writes()
    assert eng.faults.get("dedup_suppressed_flushes") == 1
    assert not eng._writer.has_failed()   # nothing retained
    assert sum(total_counts(store).values()) == 120
    # and the windows are NOT tainted: next flush is plain deltas
    eng.process_lines(view_lines(120))
    eng.flush()
    eng.drain_writes()
    assert sum(total_counts(store).values()) == 240
    assert eng.faults.get("reconciled_windows") == 0
    eng.close()


def test_partial_apply_is_reconciled_absolute():
    """The partial-apply fault: a prefix of the pipeline lands, the
    fence commit does not.  The retry must rewrite the tainted windows
    ABSOLUTE from the ledger — final counts exact, never prefix-doubled."""
    store = as_redis(FakeRedisStore())
    seed_campaigns(store, ["camp0", "camp1", "camp2"])
    inj = FaultInjector(FaultPlan(sink_faults={4: "partial"}))
    eng = make_engine(inj.wrap_redis(store))
    eng.process_lines(view_lines(120))
    # sink op stream: 0 = attach fence read, 1 = epoch claim, 2 = writer
    # epoch pre-check, 3 = existence probes, 4 = the mutation pipeline
    # -> PARTIAL apply (intent lands + a prefix of rows, commit doesn't)
    eng.flush()
    eng.drain_writes()
    assert eng.faults.get("sink_errors") >= 1
    fk = fence_key(eng.cfg.kafka_topic)
    # the partial signature: intent ran ahead of the commit seq
    assert int(store.execute("HGET", fk, "intent") or 0) \
        > int(store.execute("HGET", fk, "seq") or 0)
    # retry path: reclaim taints the windows, next flush rewrites them
    eng.flush()
    eng.drain_writes()
    assert eng.faults.get("reconciled_windows") > 0
    assert sum(total_counts(store).values()) == 120
    eng.close()
    assert total_counts(store) == {
        ("camp0", 1_000_000): 40, ("camp1", 1_000_000): 40,
        ("camp2", 1_000_000): 40}


def test_rows_lost_counted_at_close(tmp_path):
    """Satellite bugfix: rows abandoned when close() exhausts
    CLOSE_RETRY_LIMIT are counted as ``rows_lost`` in FaultCounters (and
    close still raises — a silent-loss run can never exit clean)."""
    class _DeadSink:
        def execute(self, *args):
            raise ConnectionRefusedError("down")

        def pipeline_execute(self, commands):
            raise ConnectionRefusedError("down")

    eng = AdAnalyticsEngine(
        default_config(jax_batch_size=64, jax_sink_retry_base_ms=1,
                       jax_sink_retry_cap_ms=2),
        MAPPING, redis=_DeadSink())
    eng.CLOSE_RETRY_LIMIT = 2
    eng.process_lines(view_lines(50))
    with pytest.raises(RuntimeError, match="rows lost"):
        eng.close()
    assert eng.faults.get("rows_lost") > 0
    assert eng.faults.get("sink_errors") > 0


def test_replay_note_embeds_node_and_seed(monkeypatch):
    monkeypatch.setenv("PYTEST_CURRENT_TEST",
                       "tests/test_x.py::test_y[3] (call)")
    note = replay_note(seed=1234, topic_path="/tmp/topic",
                       overrides={"jax.sink.exactly_once": True})
    assert "python -m pytest 'tests/test_x.py::test_y[3]' -q" in note
    assert "seed=1234" in note and "/tmp/topic" in note
    assert "jax.sink.exactly_once=True" in note


# ----------------------------------------------------------------------
# acceptance: supervised chaos sweeps with the flag on
# ----------------------------------------------------------------------

def setup_run(tmp_path, events=12_000, **cfg_over):
    # redis_hashtable="": the close-time fork latency dump is
    # diagnostics, not counts — keeping it off the faulted op stream
    # keeps the plan indices on the writeback path under test
    cfg = default_config(jax_batch_size=256, jax_scan_batches=2,
                         jax_sink_retry_base_ms=1, jax_sink_retry_cap_ms=4,
                         redis_hashtable="", **XO, **cfg_over)
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=events,
                 rng=random.Random(7), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    return cfg, r, broker, mapping


def supervise(tmp_path, cfg, r, broker, mapping, plan, seed=1):
    inj = FaultInjector(plan)
    ckpt = Checkpointer(str(tmp_path / "ckpt"))

    def make_runner():
        eng = AdAnalyticsEngine(cfg, mapping, redis=inj.wrap_redis(r))
        reader = inj.wrap_reader(broker.reader(cfg.kafka_topic))
        return StreamRunner(eng, reader, checkpointer=ckpt,
                            crash_points=inj.scheduler)

    sup = Supervisor(make_runner, backoff_base_ms=1, backoff_cap_ms=4,
                     seed=seed, max_no_progress_restarts=8)
    st = sup.run(catchup=True)
    assert st.completed, f"supervised run did not complete: {st.errors}"
    sup.runner.engine.close()
    return st, inj, sup


def acceptance_plan(partial=True):
    """The ISSUE-1 acceptance faults + the partial-apply surface.  The
    fenced writeback spends ~3 sink ops per flush attempt (fence
    pre-check, apply, landed-check on failure), so the density is lower
    than the at-least-once plan's over a wider index window — the same
    count of faulted ops, without starving close()'s bounded retries of
    any clean tail."""
    plan = FaultPlan.generate(
        1234,
        sink_rate=0.12, sink_ops=60, sink_outage=(5, 6),
        sink_partial_rate=0.08 if partial else 0.0,
        journal_rate=0.4, journal_polls=12,
        crashes=0)
    return FaultPlan(seed=plan.seed, sink_faults=plan.sink_faults,
                     journal_faults=plan.journal_faults,
                     crashes=(("batch", 5), ("flush", 1), ("batch", 2),
                              ("checkpoint", 1)))


def test_all_three_surfaces_exactly_once(tmp_path):
    """The headline: sink outage + scattered faults + PARTIAL pipeline
    applies + torn journal reads + a 4-crash script — and every window
    still equals the oracle exactly."""
    cfg, r, broker, mapping = setup_run(tmp_path)
    plan = acceptance_plan()
    assert any(k == "partial" for k in plan.sink_faults.values()), \
        "plan rolled no partial-apply fault; widen sink_partial_rate"
    st, inj, sup = supervise(tmp_path, cfg, r, broker, mapping, plan)
    assert st.crashes >= 3
    assert inj.counters.get("chaos_sink_faults") > 0
    assert inj.counters.get("journal_faults") > 0
    v = check_exactly_once(
        r, str(tmp_path),
        repro=replay_note(seed=plan.seed,
                          topic_path=broker.topic_path(cfg.kafka_topic),
                          overrides={"jax.sink.exactly_once": True}))
    assert v.ok, (v.summary(), v.undercounts[:3], v.overcounts[:3])
    assert v.windows > 0 and v.exact == v.windows
    assert sup.runner.engine.events_processed == 12_000


def test_all_three_surfaces_exactly_once_with_ingest_pipeline(tmp_path):
    """The same sweep with the staged ingest pipeline ON: fenced flushes
    and folded-offset checkpoints must compose."""
    cfg, r, broker, mapping = setup_run(tmp_path,
                                        jax_ingest_pipeline="on")
    plan = acceptance_plan()
    st, inj, sup = supervise(tmp_path, cfg, r, broker, mapping, plan)
    assert st.crashes >= 3
    v = check_exactly_once(
        r, str(tmp_path),
        repro=replay_note(seed=plan.seed,
                          topic_path=broker.topic_path(cfg.kafka_topic),
                          overrides={"jax.sink.exactly_once": True,
                                     "jax.ingest.pipeline": "on"}))
    assert v.ok, (v.summary(), v.undercounts[:3], v.overcounts[:3])
    assert v.exact == v.windows > 0
    assert sup.runner.engine.events_processed == 12_000
    assert sup.runner._pipeline is not None


def test_crash_after_flush_reconciles_to_exact(tmp_path):
    """The replay window hit on purpose (the at-least-once suite's
    within-bound scenario): crash right after a flush landed, BEFORE the
    covering snapshot.  With the fence on, the resume must DETECT the
    unfenced flush (sink_seq > snapshot_seq) and reconcile to exact
    equality — the overcount the bound used to allow is gone."""
    cfg, r, broker, mapping = setup_run(tmp_path, events=6_000)
    plan = FaultPlan(crashes=(("batch", 3), ("flush", 1)))
    st, _, sup = supervise(tmp_path, cfg, r, broker, mapping, plan)
    assert st.crashes == 2
    # the resumed attempts saw the unfenced flushes and reconciled
    merged = dict(st.stats.faults)
    assert merged.get("sink_unfenced_resumes", 0) > 0, merged
    assert merged.get("reconciled_windows", 0) > 0, merged
    v = check_exactly_once(r, str(tmp_path))
    assert v.ok, (v.summary(), v.undercounts[:3], v.overcounts[:3])
    assert v.exact == v.windows > 0


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("xo")
    cfg = default_config(jax_batch_size=256, jax_scan_batches=2,
                         jax_sink_retry_base_ms=1, jax_sink_retry_cap_ms=4,
                         redis_hashtable="", **XO)
    broker = FileBroker(str(tmp / "broker"))
    gen.do_setup(None, cfg, broker=broker, events_num=6_000,
                 rng=random.Random(11), workdir=str(tmp))
    mapping = gen.load_ad_mapping_file(str(tmp / gen.AD_TO_CAMPAIGN_FILE))
    campaigns, _ = gen.load_ids(str(tmp))
    return tmp, cfg, broker, mapping, campaigns


def xo_sweep_seed(dataset, tmp_path, seed: int, flightrec=None) -> None:
    """One randomized supervised run under the flag; asserts EXACT
    oracle equality (the 4-seed subset is the tier-1 CI leg)."""
    tmp, cfg, broker, mapping, campaigns = dataset
    rng = random.Random(seed)
    crashes = []
    for _ in range(rng.randrange(1, 5)):
        kind = rng.choice(("batch", "batch", "flush", "checkpoint"))
        n = rng.randrange(1, 9) if kind == "batch" else 1
        crashes.append((kind, n))
    plan = FaultPlan.generate(seed, sink_rate=0.08, sink_ops=24,
                              sink_partial_rate=0.12)
    plan = FaultPlan(seed=seed, sink_faults=plan.sink_faults,
                     crashes=tuple(crashes))
    inj = FaultInjector(plan)
    r = as_redis(FakeRedisStore())
    seed_campaigns(r, campaigns)
    ckpt = Checkpointer(str(tmp_path / f"ckpt-{seed}"))

    def make_runner():
        eng = AdAnalyticsEngine(cfg, mapping, redis=inj.wrap_redis(r))
        reader = inj.wrap_reader(broker.reader(cfg.kafka_topic))
        return StreamRunner(eng, reader, checkpointer=ckpt,
                            crash_points=inj.scheduler,
                            flightrec=flightrec)

    topic = broker.topic_path(cfg.kafka_topic)
    repro = replay_note(seed=seed, topic_path=topic,
                        overrides={"jax.sink.exactly_once": True,
                                   "jax.batch.size": 256})
    sup = Supervisor(make_runner, backoff_base_ms=1, backoff_cap_ms=2,
                     seed=seed, max_no_progress_restarts=len(crashes) + 1,
                     flightrec=flightrec)
    st = sup.run(catchup=True)
    assert st.completed and not st.gave_up, (seed, st.errors, repro)
    sup.runner.engine.close()
    v = check_exactly_once(r, str(tmp), repro=repro)
    assert v.ok, (seed, v.summary(), v.undercounts[:3], v.overcounts[:3])
    assert v.exact == v.windows > 0, (seed, repro)
    assert sup.runner.engine.events_processed == 6_000, (seed, repro)


@pytest.mark.parametrize("seed", range(4))
def test_randomized_crash_boundaries_exactly_once_fast(dataset, tmp_path,
                                                       seed):
    # flight recorder armed (satellite: a red CI sweep ships its black
    # box — the workflow uploads flight_*.jsonl from the basetemp)
    from streambench_tpu.obs import FlightRecorder

    xo_sweep_seed(dataset, tmp_path, seed,
                  flightrec=FlightRecorder(str(tmp_path), capacity=64))


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.parametrize("seed", range(4, 24))
def test_randomized_crash_boundaries_exactly_once_sweep(dataset, tmp_path,
                                                        seed):
    xo_sweep_seed(dataset, tmp_path, seed)
