"""WebSocket transport parity for the pub/sub query path (VERDICT r3
missing #2): the reference serves live aggregate queries over
``ws://<gateway>/pubsub`` (``ConfigUtil.java:22-34``); the server here
speaks real RFC 6455 on the same port as the JSON-lines fallback."""

import json
import socket

from streambench_tpu.dimensions.pubsub import (
    PubSubClient,
    PubSubServer,
    WebSocketClient,
    _ws_accept,
    query_uri,
    ws_encode,
    ws_read_frame,
)


def test_handshake_accept_is_rfc6455_exact():
    # the worked example from RFC 6455 §1.3
    assert _ws_accept("dGhlIHNhbXBsZSBub25jZQ==") == \
        "s3pPLMBiTxaQ9kYGzzhZRbK+xOo="


def test_frame_roundtrip_all_length_classes():
    import io

    for n in (0, 1, 125, 126, 65535, 65536):
        payload = bytes(i & 0xFF for i in range(n))
        for mask in (False, True):
            buf = io.BytesIO(ws_encode(payload, mask=mask))
            opcode, got = ws_read_frame(buf)
            assert opcode == 0x1 and got == payload, (n, mask)


def test_ws_subscribe_receives_published_data():
    srv = PubSubServer().start()
    host, port = srv.address
    assert query_uri(host, port) == f"ws://{host}:{port}/pubsub"
    try:
        c = WebSocketClient(host, port)
        c.subscribe("agg")
        # subscription registration is async; wait for it
        import time
        deadline = time.monotonic() + 5
        while (srv.subscriber_count("agg") == 0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert srv.publish("agg", {"campaign": "c1", "count": 7}) == 1
        msg = c.recv()
        assert msg == {"type": "data", "topic": "agg",
                       "data": {"campaign": "c1", "count": 7}}
        assert c.ping(b"hb") == b"hb"
        c.close()
    finally:
        srv.close()


def test_ws_and_jsonlines_clients_share_topics():
    """Both transports are the same pub/sub bus: a websocket publisher's
    message reaches a JSON-lines subscriber and vice versa."""
    import time

    srv = PubSubServer().start()
    host, port = srv.address
    try:
        ws = WebSocketClient(host, port)
        nl = PubSubClient(host, port)
        ws.subscribe("t")
        nl.subscribe("t")
        deadline = time.monotonic() + 5
        while (srv.subscriber_count("t") < 2
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert srv.publish("t", [1, 2]) == 2
        assert ws.recv()["data"] == [1, 2]
        assert nl.recv()["data"] == [1, 2]
        # gateway parity: a client-side publish fans out too
        ws.publish("t", {"from": "ws"})
        assert nl.recv()["data"] == {"from": "ws"}
        ws.close()
        nl.close()
    finally:
        srv.close()


def test_non_websocket_http_request_is_rejected():
    srv = PubSubServer().start()
    host, port = srv.address
    try:
        s = socket.create_connection((host, port), timeout=5)
        s.sendall(b"GET /pubsub HTTP/1.1\r\nHost: x\r\n\r\n")
        resp = s.recv(64)
        assert b"400" in resp
        s.close()
    finally:
        srv.close()


def test_jsonlines_first_message_not_swallowed():
    """The transport sniff reads the first line; a JSON-lines client's
    subscribe in that first line must still register."""
    import time

    srv = PubSubServer().start()
    host, port = srv.address
    try:
        s = socket.create_connection((host, port), timeout=5)
        s.sendall(json.dumps({"type": "subscribe", "topic": "x"}).encode()
                  + b"\n")
        deadline = time.monotonic() + 5
        while (srv.subscriber_count("x") == 0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert srv.publish("x", 1) == 1
        f = s.makefile("rb")
        assert json.loads(f.readline())["data"] == 1
        s.close()
    finally:
        srv.close()


def test_split_frame_across_idle_gap_does_not_desync():
    """A frame whose header and payload arrive >1 s apart (the server's
    socket timeout) must still parse: the recv-based stream keeps
    already-received bytes across timeouts instead of discarding them
    (BufferedReader would), so a mid-frame timeout cannot desync the
    framing."""
    import time

    from streambench_tpu.dimensions.pubsub import _ws_accept as _  # noqa

    srv = PubSubServer().start()
    host, port = srv.address
    try:
        import base64 as b64
        import os as _os

        s = socket.create_connection((host, port), timeout=10)
        key = b64.b64encode(_os.urandom(16)).decode()
        s.sendall((f"GET /pubsub HTTP/1.1\r\nHost: x\r\n"
                   f"Upgrade: websocket\r\nConnection: Upgrade\r\n"
                   f"Sec-WebSocket-Key: {key}\r\n\r\n").encode())
        # drain the 101 response
        f = s.makefile("rb")
        while True:
            line = f.readline()
            if line in (b"\r\n", b"\n", b""):
                break
        frame = ws_encode(
            json.dumps({"type": "subscribe", "topic": "gap"}).encode(),
            mask=True)
        s.sendall(frame[:3])          # header + 1 byte of mask
        time.sleep(1.6)               # > the server's 1 s socket timeout
        s.sendall(frame[3:])          # rest of the frame
        deadline = time.monotonic() + 5
        while (srv.subscriber_count("gap") == 0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert srv.subscriber_count("gap") == 1
        assert srv.publish("gap", "ok") == 1
        opcode, payload = ws_read_frame(f)
        assert opcode == 0x1 and json.loads(payload)["data"] == "ok"
        s.close()
    finally:
        srv.close()


def test_fragmented_message_and_junk_json_tolerated():
    """FIN=0 + continuation fragments reassemble into one message
    (RFC 6455 §5.4); non-object JSON ('5', '[1,2]') is ignored, not a
    handler crash."""
    import base64 as b64
    import os as _os
    import time

    srv = PubSubServer().start()
    host, port = srv.address
    try:
        s = socket.create_connection((host, port), timeout=10)
        key = b64.b64encode(_os.urandom(16)).decode()
        s.sendall((f"GET /pubsub HTTP/1.1\r\nHost: x\r\n"
                   f"Upgrade: websocket\r\nConnection: Upgrade\r\n"
                   f"Sec-WebSocket-Key: {key}\r\n\r\n").encode())
        f = s.makefile("rb")
        while True:
            if f.readline() in (b"\r\n", b"\n", b""):
                break
        # junk first: valid JSON, not a message object
        s.sendall(ws_encode(b"5", mask=True))
        s.sendall(ws_encode(b"[1,2]", mask=True))
        # then a subscribe split across text + continuation frames
        msg = json.dumps({"type": "subscribe", "topic": "frag"}).encode()
        s.sendall(ws_encode(msg[:7], opcode=0x1, mask=True, fin=False))
        s.sendall(ws_encode(msg[7:], opcode=0x0, mask=True, fin=True))
        deadline = time.monotonic() + 5
        while (srv.subscriber_count("frag") == 0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert srv.subscriber_count("frag") == 1
        assert srv.publish("frag", "ok") == 1
        opcode, payload = ws_read_frame(f)
        assert json.loads(payload)["data"] == "ok"
        s.close()
    finally:
        srv.close()


def test_slow_client_does_not_stall_other_replies():
    """ISSUE 14 satellite: replies ride a per-connection queue drained
    by a per-connection writer, so one slow client socket (tiny recv
    buffer, never read) cannot stall a reply batch to healthy clients —
    the reach worker's reply loop must never block on a stranger's TCP
    window."""
    import threading
    import time

    srv = PubSubServer().start()
    host, port = srv.address
    try:
        # a "reach-like" query verb that answers every request with a
        # burst of replies to EVERY subscriber-ish connection the way
        # the serve worker does: synchronously, in one loop
        replies: list = []

        def verb(msg, reply):
            reply({"id": msg.get("id"), "answer": True})

        srv.register_query("q", verb)

        # slow victim: subscribes to a topic, never reads, tiny buffer
        slow = socket.create_connection((host, port))
        slow.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 1024)
        slow.sendall(b'{"type": "subscribe", "topic": "t"}\n')
        time.sleep(0.2)

        # saturate the slow client's queue/window with fat payloads
        blob = "x" * 4096
        for _ in range(64):
            srv.publish("t", {"blob": blob})

        # a healthy client's query replies must land promptly even
        # while the slow connection is wedged
        fast = PubSubClient(host, port, timeout_s=10)
        t0 = time.monotonic()
        for i in range(20):
            fast.request({"type": "q", "id": i})
            got = fast.recv()["data"]
            assert got == {"id": i, "answer": True}
        elapsed = time.monotonic() - t0
        fast.close()
        slow.close()
        # pre-queue, each publish to the wedged socket could eat up to
        # timeout_s (1 s) INSIDE the publisher; 20 round trips staying
        # well under one such stall proves the decoupling
        assert elapsed < 5.0, elapsed
    finally:
        srv.close()
