"""Reach serving under load and under chaos (reach/serve.py, ISSUE 10):
shed-oldest admission, epoch tagging across engine restore, the
jax.reach.slo.p99.ms burn-rate objective, and the acceptance sweep — a
pub/sub query storm concurrent with a sink-outage + crash FaultPlan
where every query sheds or answers, nothing crashes, and no post-resume
answer carries a stale epoch."""

import random
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from streambench_tpu.config import default_config
from streambench_tpu.engine.sketches import ReachSketchEngine
from streambench_tpu.ops import minhash
from streambench_tpu.reach.serve import ReachQueryServer


def tiny_state(C=4, k=16, R=16, seed=0):
    rng = np.random.default_rng(seed)
    st = minhash.init_state(C, k, R)
    join = jnp.asarray(np.arange(C, dtype=np.int32))
    B = 64
    return minhash.step(
        st, join,
        jnp.asarray(rng.integers(0, C, B).astype(np.int32)),
        jnp.asarray(rng.integers(0, 1 << 20, B).astype(np.int32)),
        jnp.zeros(B, jnp.int32), jnp.zeros(B, jnp.int32),
        jnp.ones(B, bool))


# ----------------------------------------------------------- admission
def test_shed_oldest_beyond_depth_and_counters():
    st = tiny_state()
    srv = ReachQueryServer(list("abcd"), depth=5, batch=4, hold=True)
    srv.update_state(st.mins, st.registers, epoch=1)
    got = []
    try:
        for i in range(12):
            srv.submit(["a"], "union", lambda d: got.append(d),
                       query_id=i)
        # held: 12 in, depth 5 -> 7 oldest shed already
        assert srv.shed == 7 and srv.pending() == 5
        shed_ids = sorted(d["id"] for d in got if d.get("shed"))
        assert shed_ids == list(range(7))   # OLDEST were shed
        srv.resume()
        deadline = time.monotonic() + 10
        while len(got) < 12 and time.monotonic() < deadline:
            time.sleep(0.01)
        answered = [d for d in got if "estimate" in d]
        assert len(answered) == 5 and srv.served == 5
        assert {d["id"] for d in answered} == set(range(7, 12))
        # drain of 5 at batch=4 -> exactly ceil(5/4)=2 dispatches
        assert srv.dispatches == 2
        s = srv.summary()
        assert s["shed"] == 7 and s["served"] == 5
        assert s["p99_ms"] >= 0
    finally:
        srv.close()


def test_bad_requests_answer_without_queueing():
    srv = ReachQueryServer(["a"], depth=4, batch=2)
    got = []
    try:
        assert not srv.submit([], "union", lambda d: got.append(d))
        assert not srv.submit(["a"], "p99", lambda d: got.append(d))
        assert not srv.submit(["zzz"], "union",
                              lambda d: got.append(d))
        assert srv.rejected == 3 and srv.pending() == 0
        assert all("error" in d for d in got)
    finally:
        srv.close()


def test_close_without_state_sheds_stragglers():
    srv = ReachQueryServer(["a"], depth=8, batch=4)   # no state pushed
    got = []
    srv.submit(["a"], "union", lambda d: got.append(d), query_id="s")
    srv.close()
    assert got and got[0].get("shed") is True


# ------------------------------------------------------------- epochs
def test_engine_restore_bumps_epoch_and_pushes(tmp_path):
    from streambench_tpu.utils.ids import make_ids

    rng = random.Random(3)
    campaigns = make_ids(5, rng)
    ads = make_ids(10, rng)
    mapping = {a: campaigns[i // 2] for i, a in enumerate(ads)}
    cfg = default_config(jax_num_campaigns=5, jax_batch_size=128)
    eng = ReachSketchEngine(cfg, mapping, campaigns=campaigns,
                            k=16, registers=16)
    srv = ReachQueryServer(list(eng.encoder.campaigns), depth=16,
                           batch=4)
    try:
        eng.attach_reach(srv)
        assert srv.epoch == 0
        lines = [
            ('{"user_id": "u%d", "page_id": "p", "ad_id": "%s", '
             '"ad_type": "banner", "event_type": "view", '
             '"event_time": "%d", "ip_address": "1.2.3.4"}'
             % (i, ads[i % 10], 1_000_000 + i * 10)).encode()
            for i in range(400)]
        eng.process_chunk(lines)
        eng.flush()
        snap = eng.snapshot(offset=1)
        before = np.asarray(eng.state.mins).copy()
        eng.restore(snap)             # resume on the SAME engine
        assert eng.reach_epoch == 1 and srv.epoch == 1
        np.testing.assert_array_equal(np.asarray(eng.state.mins), before)
        # a fresh engine restoring the same snapshot also moves PAST the
        # snapshot's recorded epoch (strictly increasing across lineages)
        eng2 = ReachSketchEngine(cfg, mapping, campaigns=campaigns,
                                 k=16, registers=16)
        snap2 = eng.snapshot(offset=2)     # carries reach_epoch=1
        eng2.restore(snap2)
        assert eng2.reach_epoch == 2
        got = []
        srv.submit([campaigns[0]], "union", lambda d: got.append(d))
        deadline = time.monotonic() + 10
        while not got and time.monotonic() < deadline:
            time.sleep(0.01)
        assert got and got[0]["epoch"] == 1
    finally:
        srv.close()


# ------------------------------------------------------- SLO objective
def test_reach_slo_objective_burn_and_verdict():
    from streambench_tpu.obs import MetricsRegistry
    from streambench_tpu.obs.slo import SloTracker
    from streambench_tpu.reach.serve import LATENCY_HIST

    clock = {"t": 0.0}
    reg = MetricsRegistry()
    slo = SloTracker(reg, reach_p99_ms=100, budget=0.1, fast_s=5,
                     slow_s=20, clock=lambda: clock["t"])
    assert slo.active
    hist = reg.histogram(LATENCY_HIST)   # the shared serve instrument
    for _ in range(20):
        clock["t"] += 1
        hist.observe(10)
        rec: dict = {}
        slo.collect(rec, 1.0)
        assert rec["slo"]["burn"]["reach"]["fast"] == 0.0
    for _ in range(4):
        clock["t"] += 1
        hist.observe(10_000)
        rec = {}
        slo.collect(rec, 1.0)
    burns = rec["slo"]["burn"]["reach"]
    assert burns["fast"] == pytest.approx(8.0, rel=0.01)
    assert burns["slow"] == pytest.approx(2.0, rel=0.01)
    assert rec["slo"]["in_breach"] and slo.breaches == 1
    assert rec["slo"]["total_reach"] == 24
    v = slo.verdict()
    assert v["objectives"]["reach_p99_ms"] == 100
    assert v["total_reach"] == 24 and v["bad_reach"] == 4
    assert v["pass"] is False


# ----------------------------------------------------- chaos acceptance
def test_query_storm_under_sink_outage_and_crashes(tmp_path):
    """The acceptance sweep: a pub/sub query storm runs concurrently
    with a supervised reach run whose FaultPlan injects a sink outage
    and mid-run crashes.  Every query sheds or answers (none lost, no
    crash propagates to a client), and once the run has resumed and
    completed, fresh answers carry the LIVE epoch — never a stale one."""
    from streambench_tpu.chaos import FaultInjector, FaultPlan, Supervisor
    from streambench_tpu.checkpoint import Checkpointer
    from streambench_tpu.datagen import gen
    from streambench_tpu.dimensions.pubsub import PubSubClient, PubSubServer
    from streambench_tpu.engine.runner import StreamRunner
    from streambench_tpu.io.fakeredis import FakeRedisStore
    from streambench_tpu.io.journal import FileBroker
    from streambench_tpu.io.redis_schema import as_redis

    # flush every ~1 ms so checkpoints land BETWEEN batches: the crash
    # must find a snapshot to resume from, or restore (and the epoch
    # bump under test) would never run on this fast a catchup
    cfg = default_config(jax_batch_size=256, jax_scan_batches=2,
                         jax_flush_interval_ms=1,
                         jax_sink_retry_base_ms=1,
                         jax_sink_retry_cap_ms=4)
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=6_000,
                 rng=random.Random(7), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    campaigns = gen.load_ids(str(tmp_path))[0]

    from streambench_tpu.dimensions.store import DurableDimensionStore
    from streambench_tpu.reach.replica import ReachReplica, SnapshotShipper

    plan = FaultPlan.generate(77, sink_rate=0.3, sink_ops=8,
                              sink_outage=(0, 4), crashes=0)
    plan = FaultPlan(seed=plan.seed, sink_faults=plan.sink_faults,
                     journal_faults=plan.journal_faults,
                     crashes=(("batch", 3), ("batch", 2)))
    inj = FaultInjector(plan)
    ckpt = Checkpointer(str(tmp_path / "ckpt"))
    srv = ReachQueryServer(campaigns, depth=8, batch=4)
    ps = PubSubServer(port=0).start()
    ps.register_query("reach", srv.handle)
    # ISSUE 14: a replica rides the same chaos run — the shipper runs
    # inside every crashing lineage, the replica tails across crashes
    ship_store = DurableDimensionStore(str(tmp_path / "ship"))
    engines = []

    def make_runner():
        eng = ReachSketchEngine(cfg, mapping, campaigns=campaigns,
                                redis=inj.wrap_redis(r), k=16,
                                registers=16)
        eng.attach_reach(srv)
        eng.attach_shipper(SnapshotShipper(ship_store, campaigns,
                                           interval_ms=1))
        engines.append(eng)
        reader = inj.wrap_reader(broker.reader(cfg.kafka_topic))
        return StreamRunner(eng, reader, checkpointer=ckpt,
                            crash_points=inj.scheduler)

    host, port = ps.address
    done = threading.Event()
    storm: dict = {"sent": 0, "answers": [], "errors": []}

    def client():
        try:
            c = PubSubClient(host, port, timeout_s=30)
            while not done.is_set():
                sel = [campaigns[storm["sent"] % len(campaigns)]]
                c.request({"type": "reach", "campaigns": sel,
                           "op": "union", "id": storm["sent"]})
                storm["sent"] += 1
                storm["answers"].append(c.recv())
                time.sleep(0.005)
            c.close()
        except Exception as e:   # a crash must never reach a client
            storm["errors"].append(repr(e))

    t = threading.Thread(target=client)
    t.start()
    try:
        sup = Supervisor(make_runner, backoff_base_ms=1,
                         backoff_cap_ms=4, seed=1)
        st = sup.run(catchup=True)
        assert st.completed, st.errors
        assert st.crashes >= 2
        live = engines[-1]
        assert live.reach_epoch >= 1       # resumed lineages bumped
        assert live.events_processed == 6_000
        # the sink outage lands on the close-time reach-hash write (the
        # only sink op this engine issues); serving must survive it
        try:
            live.close()
        except Exception:
            pass
        assert inj.counters.get("chaos_sink_faults") > 0
        # post-resume storm: answers must carry the LIVE epoch only
        done.set()
        t.join(timeout=30)
        assert not storm["errors"], storm["errors"]
        c = PubSubClient(host, port, timeout_s=30)
        final = []
        for i in range(10):
            c.request({"type": "reach", "campaigns": campaigns[:3],
                       "op": "overlap", "id": f"final{i}"})
            final.append(c.recv()["data"])
        c.close()
        for d in final:
            assert d.get("shed") or d["epoch"] == live.reach_epoch, d
        assert any("estimate" in d for d in final)
        # the storm's ledger: every query shed or answered, none lost
        data = [a["data"] for a in storm["answers"]]
        assert len(data) == storm["sent"]
        assert all(("estimate" in d) or d.get("shed") for d in data)
        published = {e.reach_epoch for e in engines} | {0}
        assert {d["epoch"] for d in data if "epoch" in d} <= published
        # replica across the chaos: tails the shipped records (written
        # by every crashed-and-resumed lineage) and answers with a
        # published epoch and exact single-device results
        import numpy as _np

        from streambench_tpu.reach import query as _rq

        rep = ReachReplica(ship_store.path, poll_ms=20_000)
        rep.pubsub.start()
        try:
            assert rep.poll_once(), "no shipped record survived chaos"
            rh, rp = rep.address
            c = PubSubClient(rh, rp, timeout_s=30)
            c.request({"type": "reach", "campaigns": campaigns[:2],
                       "op": "union", "id": "rep"})
            d = c.recv()["data"]
            c.close()
            assert "estimate" in d, d
            assert d["plane_epoch"] in published
            assert "staleness_ms" in d
            rec = ship_store.reach_sketches()
            m = _np.zeros((1, len(campaigns)), bool)
            m[0, :2] = True
            want, *_ = _rq.batch_query(
                jnp.asarray(rec["mins"]), jnp.asarray(rec["registers"]),
                jnp.asarray(m), jnp.asarray([False]))
            assert d["estimate"] == round(float(_np.asarray(want)[0]), 2)
        finally:
            rep.close()
            ship_store.close()
    finally:
        done.set()
        t.join(timeout=10)
        srv.close()
        ps.close()
