"""SLO burn-rate gates (ISSUE 8 tentpole, obs.slo): count_le bucket
arithmetic, fast/slow window burn rates, breach/recovery transitions
journaled + gauged, the rate objective, and the verdict block."""

import pytest

from streambench_tpu.obs import MetricsRegistry, SloTracker
from streambench_tpu.obs.flightrec import FlightRecorder
from streambench_tpu.obs.registry import StreamingHistogram


def test_histogram_count_le_bucket_resolution():
    h = StreamingHistogram("h", lo=1, hi=1000, growth=2.0)
    # buckets: <=1, (1,2], (2,4], ... (512,1024], overflow
    for v in (0.5, 1.0, 3.0, 100.0, 5000.0):
        h.observe(v)
    assert h.count_le(1) == 2
    assert h.count_le(4) == 3
    assert h.count_le(1e9) == 5      # everything, overflow included
    # bucket resolution: x inside a bucket counts the whole bucket
    assert h.count_le(2.5) == 3      # the (2,4] bucket is included
    assert h.count == 5


def _tracker(p99=100, rate=0, budget=0.1, fast=5, slow=20,
             flightrec=None, annotate=None):
    clock = {"t": 0.0}
    reg = MetricsRegistry()
    slo = SloTracker(reg, p99_ms=p99, rate_evps=rate, budget=budget,
                     fast_s=fast, slow_s=slow, annotate=annotate,
                     flightrec=flightrec, clock=lambda: clock["t"])
    hist = reg.histogram(
        "streambench_window_latency_ms",
        "window writeback latency (time_updated - window_ts), ms")
    return reg, slo, hist, clock


def test_latency_burn_rates_fast_vs_slow_windows():
    reg, slo, hist, clock = _tracker(budget=0.1, fast=5, slow=20)
    # 20 good ticks, then bad ones: fast window saturates first
    for i in range(20):
        clock["t"] += 1
        hist.observe(10)
        rec: dict = {}
        slo.collect(rec, 1.0)
        assert rec["slo"]["burn"]["latency"]["fast"] == 0.0
    for i in range(4):
        clock["t"] += 1
        hist.observe(10_000)         # way over the 100 ms objective
        rec = {}
        slo.collect(rec, 1.0)
    burns = rec["slo"]["burn"]["latency"]
    # fast window (last 5 s): 4 bad of 5 new windows -> 0.8/0.1 = 8
    assert burns["fast"] == pytest.approx(8.0, rel=0.01)
    # slow window (last 20 s): 4 bad of 20 -> 0.2/0.1 = 2
    assert burns["slow"] == pytest.approx(2.0, rel=0.01)
    # both over 1.0 -> breach counted once, gauges live
    assert rec["slo"]["in_breach"] and slo.breaches == 1
    g = reg.gauge("streambench_slo_burn_rate",
                  labels={"objective": "latency", "window": "fast"})
    assert g.value == pytest.approx(8.0, rel=0.01)
    assert reg.counter("streambench_slo_breaches_total").value == 1


def test_breach_transitions_journal_and_flightrec(tmp_path):
    events = []
    fr = FlightRecorder(str(tmp_path))
    reg, slo, hist, clock = _tracker(
        budget=0.5, fast=3, slow=6, flightrec=fr,
        annotate=lambda ev, **kw: events.append((ev, kw)))
    # drive into breach: every window bad
    for _ in range(8):
        clock["t"] += 1
        hist.observe(10_000)
        slo.collect({}, 1.0)
    assert slo.breaches == 1
    assert events and events[0][0] == "slo_breach"
    assert events[0][1]["bad_windows"] == pytest.approx(
        events[0][1]["total_windows"], abs=2)
    kinds = [r["kind"] for r in fr.snapshot()]
    assert "slo_breach" in kinds
    # recover: all-good windows flush the fast+slow windows
    for _ in range(10):
        clock["t"] += 1
        for _ in range(30):
            hist.observe(1)
        slo.collect({}, 1.0)
    assert any(ev == "slo_recovered" for ev, _ in events)
    assert "slo_recovered" in [r["kind"] for r in fr.snapshot()]
    assert slo.breaches == 1        # transition-counted, not per-tick
    v = slo.verdict()
    assert v["pass"] is False        # a breached run can never pass
    assert v["breaches"] == 1


def test_rate_objective_judges_only_flowing_intervals():
    reg, slo, hist, clock = _tracker(p99=0, rate=1000, budget=0.25,
                                     fast=4, slow=8)
    assert slo.active
    # before any events flow, low rate is NOT bad
    for _ in range(5):
        clock["t"] += 1
        slo.collect({"events": 0, "events_per_s": 0.0}, 1.0)
    assert slo.breaches == 0
    ev = 0
    # healthy flow
    for _ in range(8):
        clock["t"] += 1
        ev += 2000
        rec = {"events": ev, "events_per_s": 2000.0}
        slo.collect(rec, 1.0)
    assert rec["slo"]["burn"]["rate"]["fast"] == 0.0
    # sustained under-rate while events still trickle
    for _ in range(8):
        clock["t"] += 1
        ev += 10
        rec = {"events": ev, "events_per_s": 10.0}
        slo.collect(rec, 1.0)
    assert rec["slo"]["burn"]["rate"]["fast"] > 1.0
    assert slo.breaches == 1


def test_inactive_tracker_is_inert():
    reg = MetricsRegistry()
    slo = SloTracker(reg, p99_ms=0, rate_evps=0)
    assert not slo.active
    rec: dict = {}
    slo.collect(rec, 1.0)
    assert "slo" not in rec
    v = slo.verdict()
    assert v["pass"] is True and v["objectives"] == {}


def test_verdict_pass_on_clean_run():
    reg, slo, hist, clock = _tracker(budget=0.01)
    for _ in range(50):
        clock["t"] += 1
        hist.observe(5)
        slo.collect({}, 1.0)
    v = slo.verdict()
    assert v["pass"] is True
    assert v["bad_windows"] == 0 and v["total_windows"] == 50
    assert v["objectives"] == {"p99_ms": 100}


def test_uses_lifecycle_e2e_histogram_when_asked():
    reg = MetricsRegistry()
    # the lifecycle's geometry — the tracker must share the instrument
    e2e = reg.histogram(
        "streambench_window_e2e_ms",
        "end-to-end latency of attribution-tracked windows (ms)",
        lo=0.1, hi=1e7, growth=2 ** 0.125)
    slo = SloTracker(reg, p99_ms=100, use_lifecycle=True)
    assert slo._hist is e2e
