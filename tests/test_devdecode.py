"""On-device event decode (ops.devdecode): oracle equality vs the host
encoders, deadletter parity on adversarial input, probe differentials.

The contract under test (ISSUE 6): with ``jax.decode.device=on`` the
engine's Redis-visible output — per-(campaign, window) counts, dropped
accounting, bad-line counting, dead-letter journal — is identical to
both host arms (native encoder and pure-Python encoder) on ANY input:
well-formed generator output, malformed JSON, re-ordered keys, torn
tails, non-view mixes, unseen ad ids, and non-13-digit timestamps.
Rows the device cannot decode must take the host fallback VERBATIM,
never be silently dropped.
"""

import dataclasses
import json
import os
import random
import uuid

import numpy as np
import pytest

from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import (
    as_redis,
    read_seen_counts,
    seed_campaigns,
)
from streambench_tpu.ops import devdecode


def _mk_mapping(rng, n_campaigns=5, ads_per=3):
    campaigns = gen.make_ids(n_campaigns, rng)
    ads = gen.make_ids(n_campaigns * ads_per, rng)
    return {ad: campaigns[i // ads_per] for i, ad in enumerate(ads)}


def _event(rng, ads, t, event_type="view", ad=None, ad_type="banner"):
    return (
        '{"user_id": "%s", "page_id": "%s", "ad_id": "%s", '
        '"ad_type": "%s", "event_type": "%s", "event_time": "%d", '
        '"ip_address": "1.2.3.4"}'
        % (str(uuid.UUID(int=rng.getrandbits(128), version=4)),
           str(uuid.UUID(int=rng.getrandbits(128), version=4)),
           ad if ad is not None else rng.choice(ads), ad_type,
           event_type, t)).encode()


def _adversarial_block(rng, ads, t0=1_722_700_000_000):
    """A journal block exercising every fallback class next to normal
    rows."""
    lines = [
        _event(rng, ads, t0),                       # plain view
        b"not json at all",                         # malformed -> DLQ
        _event(rng, ads, t0 + 5, "click"),          # filtered, valid
        b'{"event_time": "oops"}',                  # malformed -> DLQ
        _event(rng, ads, t0 + 11, "purchase"),
        # unseen ad id: valid row, campaign -1, NOT dead-lettered
        _event(rng, ads, t0 + 20, ad=str(uuid.uuid4())),
        # out-of-int32-range rebased time: bad line on EVERY arm (the
        # pre-PR-6 python encoder crashed and the native skeleton
        # silently wrapped here — both now reject)
        # re-ordered keys: valid JSON, host slow path parses it
        json.dumps({"event_time": str(t0 + 30), "ad_id": ads[0],
                    "event_type": "view", "user_id": "u", "page_id": "p",
                    "ad_type": "modal"}).encode(),
        # short (non-13-digit) timestamp: valid via host fast path
        _event(rng, ads, 12345),
        # unknown event type: valid row, filtered
        _event(rng, ads, t0 + 40, "hover"),
        # long ad_type value (still quote-free): decodes on device
        _event(rng, ads, t0 + 52, ad_type="sponsored-search"),
        _event(rng, ads, t0 + 60),
        b"",                                        # blank -> DLQ
        _event(rng, ads, t0 + 70),
    ]
    return b"\n".join(lines) + b"\n"


def _run_engine(cfg, mapping, data, dlq_dir=None):
    eng = AdAnalyticsEngine(cfg, mapping)
    dlq = None
    if dlq_dir is not None:
        from streambench_tpu.io.journal import JournalWriter

        dlq = JournalWriter(os.path.join(dlq_dir, "dlq.txt"))
        eng.encoder.set_deadletter(dlq)
    eng.process_block(data)
    eng.flush(final=True)
    if dlq is not None:
        dlq.close()
    return eng


ARMS = ("device", "native", "python")


def _arm_cfg(arm, **over):
    cfg = default_config(jax_batch_size=256, jax_scan_batches=2, **over)
    if arm == "device":
        return dataclasses.replace(cfg, jax_decode_device="on")
    if arm == "python":
        return dataclasses.replace(cfg, jax_use_native_encoder=False)
    return cfg


def _counts_and_accounting(arm, mapping, data, tmp_path, **over):
    cfg = _arm_cfg(arm, **over)
    d = tmp_path / f"dlq-{arm}"
    d.mkdir()
    eng = _run_engine(cfg, mapping, data, dlq_dir=str(d))
    if arm == "device":
        assert eng._devdecode is not None, "device arm did not engage"
        assert eng._devdecode.rows_decoded > 0
    counts = eng.pending_counts()
    dlq_path = d / "dlq.txt"
    dlq = dlq_path.read_bytes() if dlq_path.exists() else b""
    return {
        "counts": counts,
        "dropped": int(eng.dropped),
        "bad_lines": eng.encoder.bad_lines,
        "dlq": sorted(dlq.splitlines()),
        "events": eng.events_processed,
    }


def test_adversarial_block_all_arms_agree(tmp_path):
    rng = random.Random(11)
    mapping = _mk_mapping(rng)
    ads = list(mapping)
    data = _adversarial_block(rng, ads)
    res = {arm: _counts_and_accounting(arm, mapping, data, tmp_path)
           for arm in ARMS}
    base = res["native"]
    # the three malformed lines + the out-of-range timestamp
    assert base["bad_lines"] == 4
    assert base["dlq"], "deadletter sink never fed"
    for arm in ARMS:
        assert res[arm]["counts"] == base["counts"], arm
        assert res[arm]["dropped"] == base["dropped"], arm
        assert res[arm]["bad_lines"] == base["bad_lines"], arm
        assert res[arm]["dlq"] == base["dlq"], arm
        assert res[arm]["events"] == base["events"], arm


def test_generator_journal_oracle_equality(tmp_path):
    """Full catchup over a generator journal: device arm == host arm ==
    golden model, through the real StreamRunner block path."""
    cfg = default_config(jax_batch_size=512, jax_scan_batches=2)
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(None, cfg, broker=broker, events_num=12_000,
                 rng=random.Random(5), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    oracle = gen.dostats(str(tmp_path), mapping=mapping)
    for mode in ("off", "on"):
        r = as_redis(FakeRedisStore())
        seed_campaigns(r, sorted(set(mapping.values())))
        eng = AdAnalyticsEngine(
            dataclasses.replace(cfg, jax_decode_device=mode),
            mapping, redis=r)
        runner = StreamRunner(eng, broker.reader(cfg.kafka_topic))
        runner.run_catchup()
        eng.close()
        got = read_seen_counts(r)
        want = {c: {b * cfg.jax_time_divisor_ms: n
                    for b, n in per.items()}
                for c, per in oracle.items()}
        assert got == want, f"mode={mode}"
        if mode == "on":
            assert eng._devdecode is not None
            assert eng._devdecode.rows_decoded == 12_000


def test_probe_native_numpy_differential():
    """The C probe and the numpy probe are the SAME predicate — one
    adversarial block, bit-identical verdicts, times, boundaries."""
    rng = random.Random(3)
    mapping = _mk_mapping(rng)
    data = _adversarial_block(rng, list(mapping))
    # torn tail: an incomplete trailing record must not be scanned
    data += b'{"user_id": "torn'
    res_np = devdecode.probe_block(data, native=False)
    from streambench_tpu import native

    if native.load() is None:
        pytest.skip("native library unavailable")
    res_c = devdecode.probe_block(data, native=True)
    for a, b, name in zip(res_np, res_c,
                          ("starts", "lens", "times", "ok")):
        assert np.array_equal(a, b), name
    starts, lens, times, ok = res_c
    assert not data[int(starts[-1]):].startswith(b'{"user_id": "torn')


def test_probe_rejects_each_layout_break():
    rng = random.Random(9)
    mapping = _mk_mapping(rng)
    ads = list(mapping)
    good = _event(rng, ads, 1_722_700_000_000)
    mutations = [
        good.replace(b'"user_id"', b'"user_xx"'),      # key literal
        good.replace(b'"ip_address": "1.2.3.4"',
                     b'"ip_address": "9.9.9.9"'),      # suffix literal
        good.replace(b'"event_type": "view"',
                     b'"event_type": "hover"'),        # unknown type
        good[:40] + b'"' + good[41:],                  # quote in uuid
        good.replace(b'"ad_type": "banner"',
                     b'"ad_type": "ban\\"er"'),        # quote in ad_type
    ]
    block = b"\n".join([good] + mutations) + b"\n"
    for native in (False, None):
        starts, lens, times, ok = devdecode.probe_block(
            block, native=native)
        assert ok.tolist() == [True] + [False] * len(mutations), native
        assert int(times[0]) == 1_722_700_000_000


def test_ad_table_join_matches_host():
    rng = random.Random(21)
    mapping = _mk_mapping(rng, n_campaigns=11, ads_per=7)
    from streambench_tpu.encode.encoder import EventEncoder

    enc = EventEncoder(mapping)
    keys, vals, probes = devdecode.build_ad_table(
        [a.encode() for a in enc.ads], enc.join_table[:-1])
    assert probes >= 1
    # every known ad resolves to its campaign; unknown ads to -1
    T = vals.shape[0]
    for ad in list(mapping)[:20] + [str(uuid.uuid4()) for _ in range(5)]:
        h = devdecode.fnv1a32(ad.encode())
        camp = -1
        for p in range(probes):
            slot = (h + p) & (T - 1)
            if bytes(keys[slot]) == ad.encode():
                camp = int(vals[slot])
                break
        want = (enc.join_table[enc.ad_index[ad.encode()]]
                if ad.encode() in enc.ad_index else -1)
        assert camp == int(want)


def test_non_uuid_ads_fall_back_quietly():
    cfg = dataclasses.replace(default_config(), jax_decode_device="on")
    eng = AdAnalyticsEngine(cfg, {"short-ad": "c1", "other-ad": "c1"})
    assert eng._devdecode is None     # fixed 36-byte wire format only
    # ... and the host path still ingests
    eng.process_block(b'{"bad": 1}\n')
    assert eng.encoder.bad_lines == 1


def test_sketch_engines_ineligible():
    from streambench_tpu.engine.sketches import HLLDistinctEngine

    rng = random.Random(2)
    mapping = _mk_mapping(rng)
    cfg = dataclasses.replace(default_config(jax_window_slots=64),
                              jax_decode_device="on")
    eng = HLLDistinctEngine(cfg, mapping)
    assert eng._devdecode is None     # fails closed: kernel reads users


def test_auto_mode_consults_measured_ab(tmp_path, monkeypatch):
    monkeypatch.setenv("STREAMBENCH_METHOD_CACHE",
                       str(tmp_path / "cache.json"))
    from streambench_tpu.ops import methodbench

    import jax

    backend = jax.default_backend()
    assert devdecode.auto_enabled(backend) == (backend != "cpu")
    methodbench.record(f"{backend}/devdecode", {"winner": "device"})
    assert devdecode.auto_enabled(backend) is True
    methodbench.record(f"{backend}/devdecode", {"winner": "host"})
    assert devdecode.auto_enabled(backend) is False
    rng = random.Random(4)
    mapping = _mk_mapping(rng)
    cfg = dataclasses.replace(default_config(), jax_decode_device="auto")
    eng = AdAnalyticsEngine(cfg, mapping)
    assert eng._devdecode is None     # measured: host wins on this box
    methodbench.record(f"{backend}/devdecode", {"winner": "device"})
    eng = AdAnalyticsEngine(cfg, mapping)
    assert eng._devdecode is not None


def test_small_ring_span_guard_still_exact(tmp_path):
    """A ring far smaller than the journal's event-time span forces
    mid-run drains and block halving through the device path; counts
    must stay oracle-exact."""
    cfg = default_config(jax_batch_size=128, jax_scan_batches=2,
                         jax_window_slots=16,
                         jax_allowed_lateness_ms=10_000)
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(None, cfg, broker=broker, events_num=8_000,
                 rng=random.Random(13), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    oracle = gen.dostats(str(tmp_path), mapping=mapping)
    r = as_redis(FakeRedisStore())
    seed_campaigns(r, sorted(set(mapping.values())))
    eng = AdAnalyticsEngine(
        dataclasses.replace(cfg, jax_decode_device="on"), mapping,
        redis=r)
    runner = StreamRunner(eng, broker.reader(cfg.kafka_topic))
    runner.run_catchup()
    eng.close()
    got = read_seen_counts(r)
    want = {c: {b * cfg.jax_time_divisor_ms: n for b, n in per.items()}
            for c, per in oracle.items()}
    assert got == want


def test_checkpoint_resume_with_device_decode(tmp_path):
    """Snapshot/restore mid-journal with decode on: the resumed engine
    re-derives the same base time from the snapshot and the final
    counts stay exact."""
    from streambench_tpu.checkpoint import Checkpointer

    cfg = default_config(jax_batch_size=256, jax_scan_batches=2,
                         jax_checkpoint_interval_ms=0)
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(None, cfg, broker=broker, events_num=6_000,
                 rng=random.Random(17), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    oracle = gen.dostats(str(tmp_path), mapping=mapping)
    cfg_on = dataclasses.replace(cfg, jax_decode_device="on")
    r = as_redis(FakeRedisStore())
    seed_campaigns(r, sorted(set(mapping.values())))
    ckpt = Checkpointer(str(tmp_path / "ckpt"))
    eng = AdAnalyticsEngine(cfg_on, mapping, redis=r)
    runner = StreamRunner(eng, broker.reader(cfg.kafka_topic),
                          checkpointer=ckpt)
    runner.run_catchup(max_events=3_000)
    eng.drain_writes()
    # fresh engine resumes from the snapshot and finishes the journal
    eng2 = AdAnalyticsEngine(cfg_on, mapping, redis=r)
    runner2 = StreamRunner(eng2, broker.reader(cfg.kafka_topic),
                           checkpointer=ckpt)
    assert runner2.resume()
    runner2.run_catchup()
    eng2.close()
    got = read_seen_counts(r)
    want = {c: {b * cfg.jax_time_divisor_ms: n for b, n in per.items()}
            for c, per in oracle.items()}
    assert got == want


def test_chaos_sweep_with_device_decode(tmp_path):
    """The PR-1 three-surface chaos acceptance run with
    ``jax.decode.device=on``: supervised restarts over sink faults, torn
    journal reads, and >= 3 mid-run crashes still satisfy the
    at-least-once bound with the decode on the device (fresh decoder +
    join table per attempt, snapshot base times re-applied)."""
    from tests.test_chaos_recovery import setup_run, supervise
    from streambench_tpu.chaos import FaultPlan, check_at_least_once

    cfg, r, broker, mapping = setup_run(tmp_path,
                                        jax_decode_device="on")
    plan = FaultPlan.generate(
        1234,
        sink_rate=0.25, sink_ops=30, sink_outage=(5, 6),
        journal_rate=0.4, journal_polls=12,
        crashes=0)
    plan = FaultPlan(seed=plan.seed, sink_faults=plan.sink_faults,
                     journal_faults=plan.journal_faults,
                     crashes=(("batch", 5), ("flush", 1), ("batch", 2),
                              ("checkpoint", 1)))
    st, inj, sup = supervise(tmp_path, cfg, r, broker, mapping, plan)
    assert st.crashes >= 3
    # every attempt ran decode-enabled (the final one may legitimately
    # resume past a fully-consumed journal and decode 0 rows itself)
    assert sup.runner.engine._devdecode is not None
    v = check_at_least_once(r, str(tmp_path),
                            broker.topic_path(cfg.kafka_topic),
                            st.replay_segments, st.carried)
    assert v.ok, (v.summary(), v.undercounts[:3], v.overcounts[:3])
    assert v.windows > 0
    assert sup.runner.engine.events_processed == 12_000
