"""Journal broker tests: append/tail, offsets, partial lines, partitions."""

import threading

from streambench_tpu.io.journal import FileBroker, JournalReader, JournalWriter


def test_append_and_poll(tmp_path):
    path = str(tmp_path / "t-0.jsonl")
    with JournalWriter(path) as w:
        w.append("a")
        w.append(b"b\n")
        w.append_many(["c", "d"])
        w.flush()
        with JournalReader(path) as r:
            assert r.poll() == [b"a", b"b", b"c", b"d"]
            assert r.poll() == []          # nothing new
            w.append("e")
            w.flush()
            assert r.poll() == [b"e"]      # tailing picks up appends


def test_offset_resume(tmp_path):
    path = str(tmp_path / "t-0.jsonl")
    with JournalWriter(path) as w:
        w.append_many(["one", "two", "three"])
    r1 = JournalReader(path)
    assert r1.poll(max_records=2) == [b"one", b"two"]
    saved = r1.offset
    r1.close()
    # resume from checkpointed offset, like a Kafka (topic, offset) pair
    r2 = JournalReader(path, offset=saved)
    assert r2.poll() == [b"three"]
    r2.close()


def test_partial_line_not_delivered(tmp_path):
    path = str(tmp_path / "t-0.jsonl")
    with open(path, "wb") as f:
        f.write(b"complete\npart")
        f.flush()
        r = JournalReader(path)
        assert r.poll() == [b"complete"]
        assert r.poll() == []             # "part" has no newline yet
        f.write(b"ial\n")
        f.flush()
        assert r.poll() == [b"partial"]
        r.close()


def test_missing_file_then_created(tmp_path):
    path = str(tmp_path / "late-0.jsonl")
    r = JournalReader(path)
    assert r.poll() == []
    with JournalWriter(path) as w:
        w.append("x")
    assert r.poll_blocking(timeout_s=2.0) == [b"x"]
    r.close()


def test_broker_topics_and_read_all(tmp_path):
    b = FileBroker(str(tmp_path / "broker"))
    b.create_topic("ad-events", partitions=3)
    assert b.partitions("ad-events") == [0, 1, 2]
    for p in range(3):
        with b.writer("ad-events", p) as w:
            w.append(f"p{p}")
    assert sorted(b.read_all("ad-events")) == [b"p0", b"p1", b"p2"]


def test_concurrent_writer_reader(tmp_path):
    path = str(tmp_path / "t-0.jsonl")
    w = JournalWriter(path)
    got = []

    def consume():
        r = JournalReader(path)
        while len(got) < 1000:
            got.extend(r.poll_blocking(timeout_s=5.0))
        r.close()

    t = threading.Thread(target=consume)
    t.start()
    for i in range(1000):
        w.append(f"line-{i}")
        if i % 100 == 0:
            w.flush()
    w.flush()
    t.join(timeout=10)
    assert len(got) == 1000 and got[0] == b"line-0" and got[-1] == b"line-999"
    w.close()


def test_multi_reader_round_robin_and_seek(tmp_path):
    import pytest

    broker = FileBroker(str(tmp_path / "mrb"))
    for p in range(3):
        with broker.writer("t", p) as w:
            w.append_many([f"p{p}-{i}" for i in range(4)])
    mr = broker.multi_reader("t")
    got = mr.poll(max_records=100)
    assert len(got) == 12
    assert {line.decode().split("-")[0] for line in got} == {"p0", "p1", "p2"}
    assert mr.poll() == []
    offs = mr.offsets
    mr.seek_offsets([0, offs[1], offs[2]])  # rewind partition 0 only
    again = mr.poll(max_records=100)
    assert sorted(again) == sorted(f"p0-{i}".encode() for i in range(4))
    with pytest.raises(AttributeError, match="partitions"):
        mr.offset
    with pytest.raises(ValueError):
        mr.seek_offsets([0])
    mr.close()
