"""Triggered profiler capture (ISSUE 9 tentpole, obs.capture): an SLO
breach transition fires a bounded capture that really writes a trace on
the CPU backend, cooldown/cap suppress repeat triggers, and
trace.device_trace shares the ONE process-global profiler path."""

import os
import pathlib
import time

import jax
import jax.numpy as jnp
import pytest

from streambench_tpu.obs import CaptureManager, MetricsRegistry, SloTracker
from streambench_tpu.obs.capture import profiler_window


def _wait_idle(cm, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while cm.active is not None:
        if time.monotonic() > deadline:
            raise AssertionError("capture never finished")
        time.sleep(0.05)


def _capture_files(d):
    return [p for p in pathlib.Path(d).rglob("*") if p.is_file()]


def test_slo_breach_triggers_nonempty_capture(tmp_path):
    """Drive the PR 8 burn-rate tracker into breach with a capture
    manager attached: the breach TRANSITION starts a profiler window
    and the capture dir ends up non-empty on the CPU backend."""
    clock = {"t": 0.0}
    reg = MetricsRegistry()
    cm = CaptureManager(str(tmp_path), cooldown_s=60, max_captures=3,
                        window_s=0.3, registry=reg)
    slo = SloTracker(reg, p99_ms=100, budget=0.5, fast_s=3, slow_s=6,
                     capture=cm, clock=lambda: clock["t"])
    hist = reg.histogram(
        "streambench_window_latency_ms",
        "window writeback latency (time_updated - window_ts), ms")
    for _ in range(8):
        clock["t"] += 1
        hist.observe(10_000)             # way over the objective
        slo.collect({}, 1.0)
        # device work while the window is open -> a non-empty trace
        jax.block_until_ready(jax.jit(lambda x: x * 2)(jnp.ones(512)))
    assert slo.breaches == 1
    assert len(cm.captures) == 1
    rec = cm.captures[0]
    assert rec["reason"] == "slo_breach"
    assert os.path.basename(rec["dir"]).startswith("xprof_")
    _wait_idle(cm)
    cm.close()
    assert _capture_files(rec["dir"]), "trace dir is empty"
    assert reg.counter("streambench_captures_total").value == 1


def test_cooldown_suppresses_second_capture(tmp_path):
    clock = {"t": 0.0}
    reg = MetricsRegistry()
    cm = CaptureManager(str(tmp_path), cooldown_s=30, max_captures=5,
                        window_s=0.2, registry=reg,
                        clock=lambda: clock["t"])
    d1 = cm.trigger("slo_breach")
    assert d1 is not None
    # while the window is still open every trigger is suppressed
    assert cm.trigger("slo_breach") is None
    _wait_idle(cm)
    clock["t"] += 5.0                    # inside the 30 s cooldown
    assert cm.trigger("slo_breach") is None
    assert cm.suppressed == 2
    assert reg.counter(
        "streambench_captures_suppressed_total").value == 2
    clock["t"] += 30.0                   # cooldown elapsed
    d2 = cm.trigger("slo_breach")
    assert d2 is not None and d2 != d1
    _wait_idle(cm)
    cm.close()
    assert len(cm.captures) == 2


def test_max_captures_cap_and_summary(tmp_path):
    clock = {"t": 0.0}
    cm = CaptureManager(str(tmp_path), cooldown_s=0, max_captures=2,
                        window_s=0.2, clock=lambda: clock["t"])
    annotations = []
    cm.annotate = lambda ev, **kw: annotations.append((ev, kw))
    for i in range(4):
        cm.trigger(f"r{i}")
        _wait_idle(cm)
        clock["t"] += 1.0
    s = cm.summary()
    assert len(s["captures"]) == 2       # the cap held
    assert cm.suppressed == 2
    assert s["max_captures"] == 2 and s["window_s"] == 0.2
    assert [ev for ev, _ in annotations] == ["profiler_capture"] * 2
    cm.close()


def test_device_trace_delegates_to_shared_profiler_path(tmp_path):
    """trace.device_trace and the capture manager share one profiler
    lock: a whole-run trace still works alone, and while a triggered
    capture owns the profiler the run-level trace SKIPS instead of
    crashing the run (jax.profiler raises on double-start)."""
    from streambench_tpu.trace import device_trace

    solo = tmp_path / "solo"
    with device_trace(str(solo)):
        jax.block_until_ready(jax.jit(lambda x: x + 1)(jnp.ones(256)))
    assert _capture_files(solo), "run-level trace wrote nothing"
    # None stays a no-op
    with device_trace(None):
        pass

    cm = CaptureManager(str(tmp_path), cooldown_s=0, max_captures=1,
                        window_s=0.5)
    d = cm.trigger("busy")
    assert d is not None
    nested = tmp_path / "nested"
    with device_trace(str(nested)):      # profiler busy -> silent skip
        jax.block_until_ready(jax.jit(lambda x: x - 1)(jnp.ones(256)))
    assert not nested.exists() or not _capture_files(nested)
    _wait_idle(cm)
    cm.close()
    assert _capture_files(d)


def test_profiler_window_nested_is_noop_not_crash(tmp_path):
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    with profiler_window(a):
        with profiler_window(b):         # second start: skipped
            jax.block_until_ready(jnp.ones(8) * 3)
    assert _capture_files(a)
    assert not pathlib.Path(b).exists() or not _capture_files(b)


def test_close_stops_inflight_capture(tmp_path):
    cm = CaptureManager(str(tmp_path), cooldown_s=0, max_captures=1,
                        window_s=30.0)   # would outlive the test
    d = cm.trigger("slow")
    assert d is not None and cm.active == d
    jax.block_until_ready(jax.jit(lambda x: x * 7)(jnp.ones(128)))
    cm.close()                           # stop NOW, not in 30 s
    assert cm.active is None
    assert _capture_files(d), "closed capture dropped its trace"
