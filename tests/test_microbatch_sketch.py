"""Micro-batch mode with sketch engine families + checkpoint/resume
(VERDICT r3 weak #7: '--microbatch composability stops at the CLI').

- ``engine="hll"``: per-window registers, pmax partition merge, merged
  estimates close to the exact distinct count per (window, campaign).
- checkpoint/resume: window-boundary snapshots in the barrier action;
  a resumed run completes to exactly the clean run's merged output.
"""

import json
import random

import numpy as np
import pytest

from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.engine.microbatch import (
    MicroBatchCheckpointer,
    run_microbatch,
)
from streambench_tpu.io.journal import FileBroker


def setup(tmp_path, events=1800, partitions=3, window_size=300):
    cfg = default_config(window_size=window_size, map_partitions=partitions)
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(None, cfg, broker=broker, events_num=events,
                 rng=random.Random(33), workdir=str(tmp_path),
                 partitions=partitions)
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    campaigns, _ = gen.load_ids(str(tmp_path))
    return cfg, broker, mapping, campaigns


def golden_distinct(broker, cfg, mapping, campaigns):
    """Exact distinct users per (window, campaign) over view events."""
    P = cfg.map_partitions
    psize = cfg.window_size // P
    cidx = {c: i for i, c in enumerate(campaigns)}
    per_part = []
    for p in range(P):
        with broker.reader(cfg.kafka_topic, p) as r:
            lines = []
            while True:
                got = r.poll()
                if not got:
                    break
                lines.extend(got)
        per_part.append(lines)
    n_windows = min(len(l) // psize for l in per_part)
    out = []
    for k in range(n_windows):
        users = [set() for _ in campaigns]
        for p in range(P):
            for line in per_part[p][k * psize:(k + 1) * psize]:
                ev = json.loads(line)
                if ev["event_type"] == "view":
                    users[cidx[mapping[ev["ad_id"]]]].add(ev["user_id"])
        out.append(np.array([len(u) for u in users], np.int64))
    return out


def test_microbatch_hll_estimates_close_to_exact(tmp_path):
    cfg, broker, mapping, campaigns = setup(tmp_path)
    merged, results = run_microbatch(cfg, broker, mapping, campaigns,
                                     engine="hll", registers=256)
    expected = golden_distinct(broker, cfg, mapping, campaigns)
    assert len(merged) == len(expected) == 6
    rel = []
    for k in sorted(merged):
        est = merged[k].astype(np.int64)
        exact = expected[k]
        for e, x in zip(est, exact):
            if x:
                rel.append(abs(int(e) - int(x)) / x)
    # 256 registers: ~6.5% std error; the partition-merged estimate must
    # be as good as a single-device fold of the same events
    assert np.mean(rel) < 0.1, np.mean(rel)
    # stamps still agree across partitions (barrier unaffected by family)
    assert results[0].stamps == results[1].stamps == results[2].stamps


def test_microbatch_hll_union_across_partitions(tmp_path):
    """THE sketch-merge correctness property: the same user seen in
    DIFFERENT partitions must count once.  Per-partition intern indices
    would assign that user different ids per partition and the register
    merge would count it ~P times — only stateless id hashing gives the
    cross-partition union the reference's keyed shuffle guarantees."""
    P, psize, distinct = 3, 100, 40
    cfg = default_config(window_size=P * psize, map_partitions=P)
    broker = FileBroker(str(tmp_path / "broker"))
    # one campaign, one window; every partition carries views from the
    # SAME `distinct` users
    mapping = {"ad-0": "camp-0"}
    campaigns = ["camp-0"]
    broker.create_topic(cfg.kafka_topic, partitions=P)
    for p in range(P):
        with broker.writer(cfg.kafka_topic, p) as w:
            for i in range(psize):
                ev = {"user_id": f"user-{i % distinct}",
                      "page_id": f"page-{i}", "ad_id": "ad-0",
                      "ad_type": "banner", "event_type": "view",
                      "event_time": str(100_000 + i),
                      "ip_address": "1.2.3.4"}
                w.append(json.dumps(ev))
    merged, _ = run_microbatch(cfg, broker, mapping, campaigns,
                               engine="hll", registers=256)
    est = int(merged[0][0])
    # 256 registers => ~6.5% std error; 3x overcount would be ~120
    assert abs(est - distinct) <= 12, est


def test_microbatch_session_engine_rejected(tmp_path):
    cfg, broker, mapping, campaigns = setup(tmp_path, events=300,
                                            window_size=300)
    with pytest.raises(ValueError, match="count-window"):
        run_microbatch(cfg, broker, mapping, campaigns, engine="session")


def test_microbatch_checkpoint_resume_matches_clean_run(tmp_path):
    cfg, broker, mapping, campaigns = setup(tmp_path, events=3600)
    clean, _ = run_microbatch(cfg, broker, mapping, campaigns)

    ckdir = str(tmp_path / "ck")
    # First run: checkpoint every 4 windows, stop after 9 (per-run cap) —
    # windows 8..* beyond the k=8 snapshot are folded but unrecorded.
    part1, _ = run_microbatch(cfg, broker, mapping, campaigns,
                              checkpoint_dir=ckdir, checkpoint_every=4,
                              max_windows=9)
    assert len(part1) == 9
    k, meta, _ = MicroBatchCheckpointer(ckdir).load()
    assert k == 8 and meta["engine"] == "exact"

    # Second run resumes at window 8, re-folds 8..11, completes the topic.
    part2, results = run_microbatch(cfg, broker, mapping, campaigns,
                                    checkpoint_dir=ckdir,
                                    checkpoint_every=4)
    assert sorted(part2) == sorted(clean)
    for w in clean:
        np.testing.assert_array_equal(part2[w], clean[w], err_msg=f"w={w}")
    # counters survived the resume (events = full topic per partition)
    assert all(r.windows == 12 and r.events == 1200 for r in results)


def test_microbatch_hll_checkpoint_resume(tmp_path):
    cfg, broker, mapping, campaigns = setup(tmp_path, events=3600)
    clean, _ = run_microbatch(cfg, broker, mapping, campaigns,
                              engine="hll", registers=64)
    ckdir = str(tmp_path / "ck")
    run_microbatch(cfg, broker, mapping, campaigns, engine="hll",
                   registers=64, checkpoint_dir=ckdir, checkpoint_every=4,
                   max_windows=6)
    part2, _ = run_microbatch(cfg, broker, mapping, campaigns,
                              engine="hll", registers=64,
                              checkpoint_dir=ckdir, checkpoint_every=4)
    assert sorted(part2) == sorted(clean)
    for w in clean:
        np.testing.assert_array_equal(part2[w], clean[w], err_msg=f"w={w}")


def test_microbatch_checkpoint_geometry_mismatch_rejected(tmp_path):
    cfg, broker, mapping, campaigns = setup(tmp_path, events=1800)
    ckdir = str(tmp_path / "ck")
    run_microbatch(cfg, broker, mapping, campaigns,
                   checkpoint_dir=ckdir, checkpoint_every=2)
    with pytest.raises(ValueError, match="geometry"):
        run_microbatch(cfg, broker, mapping, campaigns, engine="hll",
                       checkpoint_dir=ckdir)
