"""Multi-tenant observability (obs layer 9, ISSUE 19): tenant-scoped
registry views, the shared-device time ledger + blame matrix, the
measurement-actuated admission controller, and the MultiTenantHost
that wires them — label isolation, the partition conservation law on
synthetic spans, structural controller safety against a fake clock,
the default-off byte-identity pin, and the 3-tenant engine-CLI smoke.
"""

import json
import os
import random
import subprocess
import sys

import pytest

from streambench_tpu.config import default_config, write_local_conf
from streambench_tpu.datagen import gen
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import as_redis
from streambench_tpu.obs import MetricsRegistry
from streambench_tpu.obs.admission import AdmissionController
from streambench_tpu.obs.tenancy import DeviceTimeLedger, TenantRegistry
from streambench_tpu.utils.ids import make_ids

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MS = 1_000_000  # ns per ms


# ----------------------------------------------------------------------
# tenant-scoped registry views
def test_tenant_views_are_disjoint_namespaces():
    reg = MetricsRegistry()
    a = TenantRegistry(reg, "alpha")
    b = TenantRegistry(reg, "beta")
    ca = a.counter("streambench_events_total")
    cb = b.counter("streambench_events_total")
    assert ca is not cb          # same family, disjoint instruments
    ca.inc(7)
    cb.inc(2)
    assert ca.value == 7 and cb.value == 2
    # collect() is label-filtered per view; the shared exposition
    # carries BOTH tenants with the label doing the namespacing
    assert {m.labels.get("tenant") for m in a.collect()} == {"alpha"}
    assert {m.labels.get("tenant") for m in b.collect()} == {"beta"}
    body = a.render_prometheus()
    assert 'tenant="alpha"' in body and 'tenant="beta"' in body


def test_cross_tenant_label_bleed_raises():
    reg = MetricsRegistry()
    view = TenantRegistry(reg, "alpha")
    with pytest.raises(ValueError):
        view.counter("streambench_events_total",
                     labels={"tenant": "beta"})
    with pytest.raises(ValueError):
        TenantRegistry(reg, "")


def test_predeclared_tenant_family_scrapes_before_first_touch():
    # the lazy-instrument gap: a scrape BEFORE any event must already
    # carry the tenant-labeled family with zero samples (the fix that
    # let CI drop its poll-until-appears loop)
    reg = MetricsRegistry()
    view = TenantRegistry(reg, "alpha")
    view.predeclare("counter", "streambench_events_total",
                    "events folded")
    body = reg.render_prometheus()
    assert 'streambench_events_total{tenant="alpha"} 0' in body


# ----------------------------------------------------------------------
# blame matrix + partition invariant on synthetic spans
def test_blame_matrix_attributes_overlap_and_partitions():
    led = DeviceTimeLedger()
    # beta busy [0, 100) ms and [200, 300) ms; alpha busy [500, 510) ms
    led.note_busy("beta", 0, 100 * MS)
    led.note_busy("beta", 200 * MS, 300 * MS)
    led.note_busy("alpha", 500 * MS, 510 * MS)
    led.declare("gamma")
    # gamma waits [50, 250) ms: 50 ms inside beta's first window,
    # 50 ms inside its second, 0 inside alpha's
    led.note_wait("gamma", 50 * MS, 250 * MS)
    # beta also waits on itself [250, 260) ms — diagonal mass
    led.note_wait("beta", 250 * MS, 260 * MS)
    m = led.blame_matrix()
    assert m["tenants"] == ["alpha", "beta", "gamma"]
    assert m["matrix_ms"]["gamma"]["beta"] == 100.0
    assert m["matrix_ms"]["gamma"]["alpha"] == 0.0
    assert m["matrix_ms"]["beta"]["beta"] == 10.0
    assert m["wait_ms"]["gamma"] == 200.0
    # offdiag = gamma->beta 100; diag = beta->beta 10
    assert m["offdiag_ratio"] == round(100.0 / 110.0, 4)
    assert led.aggressor_for("gamma") == ("beta", 100.0)
    # no cross-tenant evidence for alpha -> controller must not act
    assert led.aggressor_for("alpha") is None

    # conservation law: attributed busy == sampler-measured busy
    ok = led.partition_check({"beta": 200 * MS, "alpha": 10 * MS,
                              "gamma": 0})
    assert ok["ok"] and ok["rel_err"] == 0.0
    # a sampler total the ledger never saw fails the check loudly
    bad = led.partition_check({"beta": 400 * MS, "alpha": 10 * MS,
                               "gamma": 0})
    assert not bad["ok"]


def test_busy_sink_feeds_the_owning_tenant():
    led = DeviceTimeLedger()
    sink = led.busy_sink("beta")
    sink(10 * MS, 30 * MS)
    assert led.busy_ns["beta"] == 20 * MS
    assert led.tenants() == ["beta"]


# ----------------------------------------------------------------------
# admission controller: structural safety against a fake clock
def _controller(burn_seq, ledger=None, **kw):
    """Controller over a scripted burn series and a canned ledger."""
    if ledger is None:
        ledger = DeviceTimeLedger()
        ledger.note_busy("beta", 0, 100 * MS)
        ledger.note_wait("gamma", 10 * MS, 60 * MS)   # beta blames 50ms
    it = iter(burn_seq)
    state = {"now": 0.0}

    def burns():
        return {"gamma": next(it)}

    def clock():
        return state["now"]

    kw.setdefault("breach_burn", 1.0)
    ctl = AdmissionController(ledger, burns, clock=clock, **kw)
    return ctl, state


def test_priming_step_never_actuates():
    ctl, _ = _controller([99.0, 99.0, 99.0], breach_ticks=1,
                         cooldown_s=0.0)
    assert ctl.step() is None            # priming: history is not a breach
    dec = ctl.step()
    assert dec is None or dec["decision"] == "defer"


def test_hysteresis_requires_consecutive_breaches():
    ctl, _ = _controller([0.0, 9.0, 0.0, 9.0, 9.0, 9.0],
                         breach_ticks=2, cooldown_s=0.0)
    ctl.step()                           # prime
    assert ctl.step() is None            # breach tick 1
    assert ctl.step() is None            # healthy resets the streak
    assert ctl.step() is None            # breach tick 1 again
    dec = ctl.step()                     # breach tick 2 -> gate
    assert dec["decision"] == "defer"
    assert dec["tenant"] == "beta" and dec["victim"] == "gamma"
    assert dec["blame_ms"] == 50.0 and dec["burn"] == 9.0
    assert ctl.admit("beta") == "defer"
    assert ctl.admit("gamma") == "admit"   # the victim is never gated


def test_no_cross_tenant_evidence_means_no_actuation():
    led = DeviceTimeLedger()
    led.note_busy("gamma", 0, 100 * MS)
    led.note_wait("gamma", 10 * MS, 60 * MS)   # waits only on itself
    ctl, _ = _controller([9.0] * 6, ledger=led, breach_ticks=1,
                         cooldown_s=0.0)
    ctl.step()
    for _ in range(4):
        assert ctl.step() is None
    assert ctl.gates() == {}


def test_cooldown_counts_holds_then_acts_after_expiry():
    ctl, state = _controller([9.0] * 8, breach_ticks=1, cooldown_s=5.0,
                             escalate_ticks=1)
    ctl.step()                           # prime
    dec = ctl.step()
    assert dec["decision"] == "defer"    # first act is never cooled
    assert ctl.step() is None            # escalation due, inside cooldown
    holds0 = ctl.holds
    assert holds0 >= 1
    state["now"] = 10.0                  # cooldown expired
    dec = ctl.step()
    assert dec["decision"] == "shed" and dec.get("escalated")
    assert ctl.admit("beta") == "shed"


def test_release_on_sustained_health_journals_evidence():
    ctl, _ = _controller([9.0, 9.0, 0.0, 0.0, 0.0],
                         breach_ticks=1, healthy_ticks=3,
                         cooldown_s=0.0)
    ctl.step()
    assert ctl.step()["decision"] == "defer"
    assert ctl.step() is None            # healthy 1
    assert ctl.step() is None            # healthy 2
    dec = ctl.step()                     # healthy 3 -> release
    assert dec["decision"] == "release" and dec["released"] == ["beta"]
    assert ctl.admit("beta") == "admit"
    s = ctl.summary()
    assert s["defers"] == 1 and s["releases"] == 1
    assert s["last"]["decision"] == "release"


def test_deferred_and_shed_batches_are_counted():
    ctl, _ = _controller([0.0], cooldown_s=0.0)
    ctl.note_deferred("beta", 3)
    ctl.note_shed("beta", 2)
    s = ctl.summary()
    assert s["batches_deferred"] == 3 and s["batches_shed"] == 2


# ----------------------------------------------------------------------
# host: default-off byte-identity pin + in-process tenant journal
def _world(seed=11, n=10):
    rng = random.Random(seed)
    campaigns = make_ids(n, rng)
    ads = make_ids(n * 10, rng)
    mapping = {a: campaigns[i // 10] for i, a in enumerate(ads)}
    src = gen.EventSource(ads=ads, user_ids=make_ids(200, rng),
                          page_ids=make_ids(20, rng), rng=rng)
    ts = [1_700_000_000_000 + 10 * i for i in range(512)]
    lines = [s.encode() for s in src.events_at(ts)]
    return campaigns, mapping, lines


def _run_host(monkeypatch, lines, mapping, campaigns, **host_kw):
    import itertools

    from streambench_tpu.engine import tenants as tmod
    from streambench_tpu.io import redis_schema

    # window/list UUIDs come from a pid-scoped random-prefix counter;
    # pin it so both arms mint the identical ID sequence
    monkeypatch.setattr(
        redis_schema, "_ID_STATE",
        {"pid": os.getpid(), "prefix": "00" * 8,
         "counter": itertools.count()})
    # ... and freeze the writeback wall-clock stamp for the same reason
    # (pipeline.py imported the symbol at module load, so patch both)
    from streambench_tpu.engine import pipeline as pmod
    monkeypatch.setattr(redis_schema, "now_ms", lambda: 1_700_000_000_000)
    monkeypatch.setattr(pmod, "now_ms", lambda: 1_700_000_000_000)

    # pin the pure-Python store: its dict state is directly dumpable,
    # and both arms use the identical implementation
    stores = []
    monkeypatch.setattr(
        tmod, "make_store",
        lambda: stores.append(FakeRedisStore()) or stores[-1])
    cfg = default_config(jax_batch_size=256)
    host = tmod.MultiTenantHost(cfg, [{"name": "solo", "kind": "exact"}],
                                mapping, campaigns=campaigns,
                                registry=MetricsRegistry(), **host_kw)
    host.warmup()
    host.offer("solo", lines)
    while host.step():
        pass
    host.close(final=True)
    (store,) = stores
    return {"strings": store._strings, "hashes": store._hashes,
            "sets": store._sets, "lists": store._lists}


def test_admission_default_off_is_byte_identical(monkeypatch):
    campaigns, mapping, lines = _world()
    plain = _run_host(monkeypatch, lines, mapping, campaigns,
                      admission=False)
    # an armed-but-idle controller (threshold unreachably high) must
    # leave the sink byte-identical to the default-off path
    armed = _run_host(monkeypatch, lines, mapping, campaigns,
                      admission=True,
                      admission_kw={"breach_burn": 1e9})
    dump = lambda d: json.dumps(d, sort_keys=True, default=sorted)
    assert dump(plain) == dump(armed)


def test_host_journals_disjoint_tenant_blocks(tmp_path):
    from streambench_tpu.engine.tenants import MultiTenantHost
    from streambench_tpu.obs import MetricsSampler

    campaigns, mapping, lines = _world()
    registry = MetricsRegistry()
    sampler = MetricsSampler(str(tmp_path / "metrics.jsonl"),
                             interval_ms=50, registry=registry,
                             role="host")
    cfg = default_config(jax_batch_size=256)
    host = MultiTenantHost(
        cfg, [{"name": "alpha", "kind": "exact"},
              {"name": "beta", "kind": "session"}],
        mapping, campaigns=campaigns, registry=registry,
        sampler=sampler, sample_every=1)
    host.warmup()
    host.offer("alpha", lines)
    host.offer("beta", lines)
    while host.step():
        pass
    host.flush_all()
    summary = host.close()
    sampler.close(final={"multitenant": summary["multitenant"]})

    assert summary["tenants"]["alpha"]["events"] == len(lines)
    assert summary["tenants"]["beta"]["events"] == len(lines)
    assert summary["multitenant"]["partition"]["ok"], \
        summary["multitenant"]["partition"]
    # every tenant-labeled instrument belongs to exactly one namespace
    tenants_seen = {m.labels["tenant"] for m in registry.collect()
                    if "tenant" in m.labels}
    assert tenants_seen == {"alpha", "beta"}
    recs = [json.loads(l) for l in
            open(tmp_path / "metrics.jsonl", encoding="utf-8")]
    final = next(r for r in recs if r.get("kind") == "final")
    blocks = [r["tenants"] for r in recs if isinstance(r.get("tenants"),
                                                       dict)]
    assert blocks and all(set(b) == {"alpha", "beta"} for b in blocks)
    assert final["multitenant"]["partition"]["ok"]


# ----------------------------------------------------------------------
# the 3-tenant engine-CLI smoke (the CI leg runs this same shape)
def test_tenants_cli_smoke(tmp_path):
    wd = str(tmp_path)
    conf = os.path.join(wd, "conf.yaml")
    write_local_conf(conf, {
        "redis.host": ":inprocess:",
        "kafka.topic": "ad-events",
        "jax.batch.size": 256,
        "jax.scan.batches": 2,
        "jax.flush.interval.ms": 100,
        "jax.metrics.interval.ms": 50,
        "jax.metrics.port": -1,
    })
    cfg = default_config()
    broker = FileBroker(os.path.join(wd, "broker"))
    gen.do_setup(as_redis(FakeRedisStore()), cfg, broker=broker,
                 events_num=6000, rng=random.Random(17), workdir=wd,
                 topic="ad-events")
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONUNBUFFERED": "1"}
    p = subprocess.run(
        [sys.executable, "-m", "streambench_tpu.engine",
         "--confPath", conf, "--workdir", wd,
         "--brokerDir", os.path.join(wd, "broker"),
         "--tenants", "alpha:exact,beta:session,gamma:reach",
         "--catchup"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, timeout=240)
    assert p.returncode == 0, p.stderr[-2000:]
    lines = [l for l in p.stdout.splitlines() if l.strip()]
    assert any(l.startswith("tenants up: alpha,beta,gamma")
               for l in lines), p.stdout
    stats = json.loads(lines[-1])
    assert stats["engine"] == "multitenant"
    assert set(stats["tenants"]) == {"alpha", "beta", "gamma"}
    # every tenant tails the same topic: same events folded each
    assert len({t["events"] for t in stats["tenants"].values()}) == 1
    assert stats["tenants"]["alpha"]["events"] > 0
    assert stats["partition_ok"] is True
    # the journal's snapshots carry disjoint tenant namespaces
    recs = [json.loads(l) for l in
            open(os.path.join(wd, "metrics.jsonl"), encoding="utf-8")]
    blocks = [r["tenants"] for r in recs
              if isinstance(r.get("tenants"), dict)]
    assert blocks and set(blocks[-1]) == {"alpha", "beta", "gamma"}
