"""Config loader tests: every reference key honored, reference defaults kept."""

import pytest

from streambench_tpu.config import (
    BenchmarkConfig,
    ConfigError,
    default_config,
    find_and_read_config_file,
    write_local_conf,
)

REFERENCE_YAML = """\
ad_to_campaign_path: "/tmp/ad-camp-map.txt"
events_path: "/tmp/events.tbl"
kafka.brokers:
    - "broker1"
    - "broker2"
zookeeper.servers:
    - "localhost"
kafka.port: 9092
zookeeper.port: 2181
redis.host: "redishost"
kafka.topic: "ad-events"
kafka.partitions: 4
kafka.bootstrap: "kafkahost:9092"
kafka.fake: true
process.hosts: 1
process.cores: 4
storm.workers: 1
storm.ackers: 2
spark.batchtime: 2000
events.num: 10000000
redis.hashtable: "t1"
window.size: 5000
shared_file: "/"
map.partitions: 3
reduce.partitions: 1
"""


def test_reference_yaml_roundtrip(tmp_path):
    p = tmp_path / "benchmarkConf.yaml"
    p.write_text(REFERENCE_YAML)
    c = find_and_read_config_file(p)
    assert c.ad_to_campaign_path == "/tmp/ad-camp-map.txt"
    assert c.events_path == "/tmp/events.tbl"
    assert c.kafka_brokers == ("broker1", "broker2")
    assert c.kafka_port == 9092
    assert c.zookeeper_port == 2181
    assert c.redis_host == "redishost"
    assert c.kafka_topic == "ad-events"
    assert c.kafka_partitions == 4
    assert c.kafka_bootstrap == "kafkahost:9092"
    assert c.kafka_bootstrap_servers == "kafkahost:9092"
    assert c.kafka_fake is True
    assert c.process_hosts == 1 and c.process_cores == 4
    assert c.storm_workers == 1 and c.storm_ackers == 2
    assert c.spark_batchtime == 2000
    assert c.events_num == 10_000_000
    assert c.redis_hashtable == "t1"
    assert c.window_size == 5000
    assert c.shared_file == "/"
    assert c.map_partitions == 3 and c.reduce_partitions == 1
    assert c.kafka_host_list == "broker1:9092,broker2:9092"
    # raw passthrough, like Flink's flattened ParameterTool map
    assert c.get("spark.batchtime") == 2000


def test_defaults_match_reference_conf():
    c = default_config()
    # kafka adapter default-off: empty bootstrap + no fake -> make_broker
    # stays on the file journal (pinned in test_kafka_contract)
    assert c.kafka_bootstrap == "" and c.kafka_bootstrap_servers is None
    assert c.kafka_fake is False
    assert c.window_size == 5000
    assert c.events_num == 10_000_000
    assert c.redis_hashtable == "t1"
    assert c.map_partitions == 3
    assert c.jax_time_divisor_ms == 10_000  # CampaignProcessorCommon time_divisor
    assert c.jax_num_campaigns == 100 and c.num_ads == 1000


def test_missing_file_raises(tmp_path):
    with pytest.raises(ConfigError):
        find_and_read_config_file(tmp_path / "nope.yaml")


def test_empty_file_raises(tmp_path):
    p = tmp_path / "empty.yaml"
    p.write_text("")
    with pytest.raises(ConfigError):
        find_and_read_config_file(p)


def test_non_mapping_raises(tmp_path):
    p = tmp_path / "list.yaml"
    p.write_text("- a\n- b\n")
    with pytest.raises(ConfigError):
        find_and_read_config_file(p)


def test_bad_int_raises():
    with pytest.raises(ConfigError):
        BenchmarkConfig.from_mapping({"kafka.port": "not-a-port"})


def test_write_local_conf(tmp_path):
    p = tmp_path / "localConf.yaml"
    write_local_conf(p, {"redis.host": "h", "kafka.port": 9092})
    c = find_and_read_config_file(p)
    assert c.redis_host == "h"


def test_overrides():
    c = default_config(redis_port=7777, jax_batch_size=64)
    assert c.redis_port == 7777 and c.jax_batch_size == 64


def test_ingest_pipeline_keys():
    c = default_config()
    assert c.jax_ingest_pipeline == "off"
    assert c.jax_ingest_block_queue == 4 and c.jax_ingest_batch_queue == 4
    c = BenchmarkConfig.from_mapping({"jax.ingest.pipeline": "AUTO",
                                      "jax.ingest.block.queue": 2,
                                      "jax.ingest.batch.queue": 8})
    assert c.jax_ingest_pipeline == "auto"
    assert c.jax_ingest_block_queue == 2 and c.jax_ingest_batch_queue == 8
    with pytest.raises(ConfigError):
        BenchmarkConfig.from_mapping({"jax.ingest.pipeline": "maybe"})


def test_mesh_keys():
    """jax.mesh.shape / jax.mesh.axes (the multichip scale-out keys):
    defaults, list round-trip, and the non-int rejection."""
    c = default_config()
    assert c.jax_mesh_shape == (1,)
    assert c.jax_mesh_axes == ("data",)
    c = BenchmarkConfig.from_mapping(
        {"jax.mesh.shape": [4, 2],
         "jax.mesh.axes": ["data", "campaign"]})
    assert c.jax_mesh_shape == (4, 2)
    assert c.jax_mesh_axes == ("data", "campaign")
    with pytest.raises(ConfigError):
        BenchmarkConfig.from_mapping({"jax.mesh.shape": ["wide"]})


def test_committed_reference_conf_roundtrip():
    """The committed ``conf/benchmarkConf.yaml`` documents every honored
    key at its default (VERDICT r5 "What's missing" #3): loading it must
    reproduce ``default_config()`` field-for-field, and every key
    ``config.py`` reads out of the mapping must appear in the file — a
    new config knob cannot land without its line of documentation."""
    import dataclasses
    import os
    import re

    import streambench_tpu.config as config_mod

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "conf", "benchmarkConf.yaml")
    loaded = find_and_read_config_file(path)
    want = default_config()
    for f in dataclasses.fields(BenchmarkConfig):
        if f.name == "raw":
            continue
        assert getattr(loaded, f.name) == getattr(want, f.name), (
            f"conf/benchmarkConf.yaml key for field {f.name!r} does not "
            f"load back to the default: {getattr(loaded, f.name)!r} != "
            f"{getattr(want, f.name)!r}")
    # completeness: every quoted key from_mapping reads must be in the
    # file (source-scanned so the list can't drift from the loader)
    src = open(config_mod.__file__, encoding="utf-8").read()
    # the whole from_mapping body (it nests geti/gets/getb helper defs,
    # so cut at the next MODULE-LEVEL def)
    body = src.split("def from_mapping", 1)[1].split("\ndef ", 1)[0]
    honored = set(re.findall(r"""(?:conf\.get|geti|gets|getb|getf)"""
                             r"""\(\s*['"]([a-z0-9_.]+)['"]""", body))
    assert honored, "key scan found nothing — regex drifted from config.py"
    documented = open(path, encoding="utf-8").read()
    missing = {k for k in honored if k not in documented}
    assert not missing, f"keys honored but undocumented in conf/: {missing}"
