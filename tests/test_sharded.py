"""Sharded (mesh) window counting vs the single-device op: bit-exact.

The virtual 8-device CPU mesh is the stand-in for real multi-chip
hardware, mirroring how the reference validates multi-node behavior with
an embedded in-process cluster (SURVEY.md §4.3).
"""

import random

import jax
import numpy as np
import pytest

from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import as_redis
from streambench_tpu.ops import windowcount as wc
from streambench_tpu.parallel import (
    ShardedWindowEngine,
    build_mesh,
    sharded_init_state,
    sharded_step,
)
from streambench_tpu.engine import StreamRunner


def rand_batches(rng, n_batches, B, n_ads, span_ms=200_000):
    out = []
    t = 70_000
    for _ in range(n_batches):
        ad = rng.integers(0, n_ads, B).astype(np.int32)
        et = rng.integers(0, 3, B).astype(np.int32)
        tm = (t + np.sort(rng.integers(0, span_ms // n_batches, B))
              ).astype(np.int32)
        valid = (rng.random(B) < 0.95)
        t += span_ms // n_batches
        out.append((ad, et, tm, valid))
    return out


MESHES = [(8, 1), (4, 2), (2, 4), (1, 8), (2, 2)]


@pytest.mark.parametrize("dshape", MESHES)
def test_sharded_step_matches_single_device(dshape):
    nd, nc = dshape
    mesh = build_mesh(data=nd, campaign=nc,
                      devices=jax.devices()[: nd * nc])
    rng = np.random.default_rng(7)
    C, W, B = 96, 16, 64  # C divisible by every nc in MESHES
    n_ads = C * 3
    join = np.concatenate(
        [rng.integers(0, C, n_ads).astype(np.int32), [-1]])

    ref = wc.init_state(C, W)
    sh = sharded_init_state(C, W, mesh)
    jt = np.asarray(join)
    for ad, et, tm, valid in rand_batches(rng, 6, B, n_ads):
        ref = wc.step(ref, jt, ad, et, tm, valid)
        sh = sharded_step(mesh, sh, jt, ad, et, tm, valid)

    assert np.array_equal(np.asarray(ref.counts), np.asarray(sh.counts))
    assert np.array_equal(np.asarray(ref.window_ids),
                          np.asarray(sh.window_ids))
    assert int(ref.watermark) == int(sh.watermark)
    assert int(ref.dropped) == int(sh.dropped)


def test_sharded_state_is_actually_sharded():
    mesh = build_mesh(data=1, campaign=8)
    st = sharded_init_state(100, 16, mesh)
    # 100 campaigns pad to 104 (= 8 x 13); each campaign shard holds 13.
    assert st.counts.shape == (104, 16)
    shard_shapes = {s.data.shape for s in st.counts.addressable_shards}
    assert shard_shapes == {(13, 16)}


def test_sharded_flush_deltas_works():
    mesh = build_mesh(data=4, campaign=2)
    st = sharded_init_state(10, 16, mesh)
    rng = np.random.default_rng(0)
    join = np.concatenate([rng.integers(0, 10, 30).astype(np.int32), [-1]])
    ad, et, tm, valid = rand_batches(rng, 1, 64, 30)[0]
    st = sharded_step(mesh, st, join, ad, et, tm, valid)
    deltas, wids, st2 = wc.flush_deltas(st)
    total = int(np.asarray(deltas).sum())
    views = int(((et == 0) & valid).sum()) - int(st.dropped)
    assert total == views
    assert int(np.asarray(st2.counts).sum()) == 0


def test_sharded_engine_end_to_end_oracle(tmp_path):
    cfg = default_config(jax_batch_size=512, jax_window_slots=16)
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=20_000,
                 rng=random.Random(5), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))

    mesh = build_mesh(data=4, campaign=2)
    engine = ShardedWindowEngine(cfg, mapping, mesh, redis=r)
    runner = StreamRunner(engine, broker.reader(cfg.kafka_topic))
    stats = runner.run_catchup()
    engine.close()
    assert stats.events == 20_000
    assert engine.dropped == 0

    logs = []
    correct, differ, missing = gen.check_correct(r, str(tmp_path),
                                                 log=logs.append)
    assert differ == 0 and missing == 0, logs[:5]
    assert correct >= 20


@pytest.mark.parametrize("dshape", [(4, 2), (1, 8)])
def test_sharded_packed_step_and_scan_bit_identical(dshape):
    """The packed-word sharded step/scan (2 data-axis collectives per
    batch instead of 4) must match the unpacked sharded kernels exactly
    on the virtual mesh."""
    from streambench_tpu.parallel.sharded import (
        _build_scan,
        _build_scan_packed,
        _build_step_packed,
    )

    d, c = dshape
    mesh = build_mesh(data=d, campaign=c, devices=jax.devices()[:d * c])
    rng = np.random.default_rng(17)
    C, W, A, B, K = 16, 8, 64, 8 * d, 3
    jt = np.concatenate([rng.integers(0, C, A).astype(np.int32), [-1]])
    batches = rand_batches(rng, K, B, A + 1)

    plain = sharded_init_state(C, W, mesh)
    for ad, et, tm, va in batches:
        plain = sharded_step(mesh, plain, jt, ad, et, tm, va)

    packed_fn = _build_step_packed(mesh, 10_000, 60_000, 0)
    ps = sharded_init_state(C, W, mesh)
    for ad, et, tm, va in batches:
        word = wc.pack_columns(ad, et, va)
        counts, ids, wm, dr = packed_fn(
            ps.counts, ps.window_ids, ps.watermark, ps.dropped,
            jt, word, tm)
        ps = wc.WindowState(counts, ids, wm, dr)
    assert np.array_equal(np.asarray(plain.counts), np.asarray(ps.counts))
    assert np.array_equal(np.asarray(plain.window_ids),
                          np.asarray(ps.window_ids))
    assert int(plain.dropped) == int(ps.dropped)

    # scans: unpacked vs packed over the same [K, B] stacks
    stack = lambda i: np.stack([b[i] for b in batches])
    s0 = sharded_init_state(C, W, mesh)
    scan_fn = _build_scan(mesh, 10_000, 60_000, 0)
    counts, ids, wm, dr = scan_fn(
        s0.counts, s0.window_ids, s0.watermark, s0.dropped, jt,
        stack(0), stack(1), stack(2), stack(3))
    s1 = sharded_init_state(C, W, mesh)
    pscan = _build_scan_packed(mesh, 10_000, 60_000, 0)
    words = np.stack([wc.pack_columns(ad, et, va)
                      for ad, et, tm, va in batches])
    pcounts, pids, pwm, pdr = pscan(
        s1.counts, s1.window_ids, s1.watermark, s1.dropped, jt,
        words, stack(2))
    assert np.array_equal(np.asarray(counts), np.asarray(pcounts))
    assert np.array_equal(np.asarray(ids), np.asarray(pids))
    assert int(dr) == int(pdr)


# ----------------------------------------------------------------------
# ISSUE 7: hoisted-gather scans + non-divisible batch padding
# ----------------------------------------------------------------------

def adversarial_batches(rng, n_batches, B, n_ads):
    """Batches with duplicate rows, rows late beyond allowed lateness,
    and invalid rows — the cases where watermark/ring/drop accounting
    could diverge between the per-batch and hoisted forms."""
    out = []
    t = 70_000
    for k in range(n_batches):
        ad = rng.integers(0, n_ads, B).astype(np.int32)
        et = rng.integers(0, 3, B).astype(np.int32)
        tm = (t + rng.integers(0, 30_000, B)).astype(np.int32)
        # duplicates: a block of rows repeated verbatim
        q = B // 4
        ad[q:2 * q] = ad[:q]
        et[q:2 * q] = et[:q]
        tm[q:2 * q] = tm[:q]
        # late rows: behind the watermark by more than allowed lateness
        # once a couple of batches have advanced it; forced valid views
        # so the sweep is guaranteed to exercise the drop accounting
        tm[:B // 8] = max(5_000, t - 150_000)
        et[:B // 8] = 0
        valid = rng.random(B) < 0.85
        valid[:B // 8] = True
        t += 60_000
        out.append((ad, et, tm, valid))
    return out


@pytest.mark.parametrize("dshape", [(4, 2), (2, 4)])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_sharded_scan_hoisted_bit_identical(dshape, seed):
    """The tentpole equivalence sweep: the hoisted-gather scans (ONE
    [K, B] collective per column per dispatch + one deferred drop psum)
    must match the per-batch-gather scans AND the single-step sequence
    bit for bit — counts, window_ids, watermark, dropped — over seeds
    with late/duplicate/invalid rows, packed and unpacked."""
    from streambench_tpu.parallel.sharded import (
        _build_scan,
        _build_scan_packed,
    )

    d, c = dshape
    mesh = build_mesh(data=d, campaign=c, devices=jax.devices()[:d * c])
    rng = np.random.default_rng(seed)
    C, W, A, B, K = 16, 8, 64, 8 * d, 4
    jt = np.concatenate([rng.integers(0, C, A).astype(np.int32), [-1]])
    batches = adversarial_batches(rng, K, B, A + 1)

    ground = sharded_init_state(C, W, mesh)
    for ad, et, tm, va in batches:
        ground = sharded_step(mesh, ground, jt, ad, et, tm, va)

    stack = lambda i: np.stack([b[i] for b in batches])  # noqa: E731
    words = np.stack([wc.pack_columns(ad, et, va)
                      for ad, et, tm, va in batches])
    arms = {
        "perbatch": (_build_scan(mesh, 10_000, 60_000, 0, False),
                     (stack(0), stack(1), stack(2), stack(3))),
        "hoisted": (_build_scan(mesh, 10_000, 60_000, 0, True),
                    (stack(0), stack(1), stack(2), stack(3))),
        "packed_perbatch": (_build_scan_packed(mesh, 10_000, 60_000, 0,
                                               False), (words, stack(2))),
        "packed_hoisted": (_build_scan_packed(mesh, 10_000, 60_000, 0,
                                              True), (words, stack(2))),
    }
    assert int(ground.dropped) > 0  # the sweep must exercise drops
    for name, (fn, cols) in arms.items():
        s = sharded_init_state(C, W, mesh)
        counts, ids, wm, dr = fn(
            s.counts, s.window_ids, s.watermark, s.dropped, jt, *cols)
        assert np.array_equal(np.asarray(ground.counts),
                              np.asarray(counts)), name
        assert np.array_equal(np.asarray(ground.window_ids),
                              np.asarray(ids)), name
        assert int(ground.watermark) == int(wm), name
        assert int(ground.dropped) == int(dr), name


def test_padded_batch_kernels_bit_identical():
    """A batch size the data axis doesn't divide, padded with invalid
    rows (pad_data_cols), must produce the single-device op's exact
    state — padding rows touch nothing."""
    from streambench_tpu.parallel.sharded import (
        _build_scan,
        data_axis_pad,
        pad_data_cols,
    )

    mesh = build_mesh(data=4, campaign=2)
    rng = np.random.default_rng(9)
    C, W, A, B, K = 16, 8, 64, 30, 3  # 30 % 4 != 0 -> pad 2
    pad = data_axis_pad(B, mesh)
    assert pad == 2
    jt = np.concatenate([rng.integers(0, C, A).astype(np.int32), [-1]])
    batches = adversarial_batches(rng, K, B, A + 1)

    ref = wc.init_state(C, W)
    for ad, et, tm, va in batches:
        ref = wc.step(ref, jt, ad, et, tm, va)

    stack = lambda i: np.stack([b[i] for b in batches])  # noqa: E731
    cols = pad_data_cols(pad, stack(0), stack(1), stack(2), stack(3))
    s = sharded_init_state(C, W, mesh)
    counts, ids, wm, dr = _build_scan(mesh, 10_000, 60_000, 0)(
        s.counts, s.window_ids, s.watermark, s.dropped, jt, *cols)
    assert np.array_equal(np.asarray(ref.counts), np.asarray(counts))
    assert np.array_equal(np.asarray(ref.window_ids), np.asarray(ids))
    assert int(ref.watermark) == int(wm)
    assert int(ref.dropped) == int(dr)


def test_sharded_engine_end_to_end_nondivisible_batch(tmp_path):
    """The remainder case end-to-end: batch size 500 on an 8-wide data
    axis (pad 4) through the real runner, oracle-exact."""
    cfg = default_config(jax_batch_size=500, jax_window_slots=16)
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=10_000,
                 rng=random.Random(15), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))

    mesh = build_mesh(data=8, campaign=1)
    engine = ShardedWindowEngine(cfg, mapping, mesh, redis=r)
    assert engine._data_pad == 4
    stats = StreamRunner(engine, broker.reader(cfg.kafka_topic)).run_catchup()
    engine.close()
    assert stats.events == 10_000
    assert engine.dropped == 0

    logs = []
    correct, differ, missing = gen.check_correct(r, str(tmp_path),
                                                 log=logs.append)
    assert differ == 0 and missing == 0, logs[:5]
    assert correct > 0


def test_mesh_from_config_keys():
    """jax.mesh.shape / jax.mesh.axes drive build_mesh (the conf keys
    documented in conf/benchmarkConf.yaml)."""
    from streambench_tpu.config import BenchmarkConfig
    from streambench_tpu.parallel import mesh_from_config
    from streambench_tpu.parallel.mesh import CAMPAIGN_AXIS, DATA_AXIS

    cfg = BenchmarkConfig.from_mapping(
        {"jax.mesh.shape": [4, 2],
         "jax.mesh.axes": ["data", "campaign"]})
    mesh = mesh_from_config(cfg)
    assert mesh.shape[DATA_AXIS] == 4 and mesh.shape[CAMPAIGN_AXIS] == 2
