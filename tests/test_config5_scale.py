"""BASELINE config #5 at scale: 1e6 campaigns, campaign-sharded state.

The reference scales keyed state by hash-routing events to the worker
owning each campaign (``AdvertisingTopology.java:232-233``); here the
campaign axis of the mesh owns a contiguous shard of the [C, W] count
state and no event moves.  This test proves the sharded engine is exact
at C=1e6 (the multi-tenant operating point) — on the virtual CPU mesh
for correctness, exactly like the reference's embedded-cluster test
(SURVEY.md §4.3) — and that ``default_method`` refuses the one-hot
formulation at this scale (it would materialize a [B, 1.6e7]
intermediate per step).
"""

import jax
import numpy as np
import pytest

from streambench_tpu.engine.pipeline import MATMUL_MAX_CAMPAIGNS, default_method
from streambench_tpu.ops import windowcount as wc
from streambench_tpu.parallel import (
    build_mesh,
    sharded_init_state,
    sharded_step,
)
from streambench_tpu.parallel.sharded import pad_campaigns

C_BIG = 1_000_003  # deliberately not divisible: exercises pad_campaigns
W = 8
DIV = 10_000
LATE = 20_000


def test_default_method_scales_by_campaigns():
    # Small key spaces may pick the MXU formulation; big ones must never
    # pick it regardless of backend (a [B, 1e6] f32 one-hot operand).
    assert default_method(C_BIG) == "scatter"
    assert default_method(MATMUL_MAX_CAMPAIGNS + 1) == "scatter"
    assert default_method() in ("scatter", "matmul")
    assert default_method(100) in ("scatter", "matmul")


def test_million_campaign_sharded_exact():
    mesh = build_mesh(data=2, campaign=4, devices=jax.devices()[:8])
    C_pad = pad_campaigns(C_BIG, mesh)
    assert C_pad >= C_BIG and C_pad % 4 == 0

    rng = np.random.default_rng(11)
    n_ads = 50_000
    B = 512
    # Ads map across the whole campaign range (including the top end, so
    # the padded tail stays empty but the last real shard is exercised).
    join = np.concatenate([
        rng.integers(0, C_BIG, n_ads).astype(np.int32), [-1]])
    join[0] = C_BIG - 1

    state = sharded_init_state(C_BIG, W, mesh)
    assert state.counts.shape == (C_pad, W)

    expected = {}
    t = 70_000
    for _ in range(4):
        ad = rng.integers(0, n_ads, B).astype(np.int32)
        et = rng.integers(0, 3, B).astype(np.int32)
        tm = (t + np.sort(rng.integers(0, 15_000, B))).astype(np.int32)
        valid = rng.random(B) < 0.95
        # Pin one guaranteed view on ad 0 -> campaign C_BIG-1, so the
        # last shard's top row is provably exercised.
        ad[0], et[0], valid[0] = 0, 0, True
        t += 15_000
        state = sharded_step(mesh, state, join, ad, et, tm, valid,
                             divisor_ms=DIV, lateness_ms=LATE)
        for a, e, ts, v in zip(ad.tolist(), et.tolist(), tm.tolist(),
                               valid.tolist()):
            c = int(join[a])
            if v and e == 0 and c >= 0:
                key = (c, ts // DIV)
                expected[key] = expected.get(key, 0) + 1

    deltas, wids, state = wc.flush_deltas(state, divisor_ms=DIV,
                                          lateness_ms=LATE)
    deltas = np.asarray(deltas)
    wids = np.asarray(wids)
    got = {}
    ci, si = np.nonzero(deltas)
    for c, s in zip(ci.tolist(), si.tolist()):
        assert wids[s] >= 0
        got[(c, int(wids[s]))] = int(deltas[c, s])
    # No drops happened (event-time span stayed well inside the ring),
    # so the oracle must match exactly — including campaign C_BIG-1.
    assert int(state.dropped) == 0
    assert got == expected
    assert any(c == C_BIG - 1 for c, _ in got)


def test_all_methods_bit_identical_small():
    # The method choice is a performance decision only; every formulation
    # must agree bit-for-bit wherever it is legal.
    rng = np.random.default_rng(3)
    C, n_ads, B = 64, 200, 128
    join = np.concatenate([rng.integers(0, C, n_ads).astype(np.int32), [-1]])
    args = (
        np.asarray(rng.integers(0, n_ads, B), np.int32),
        np.asarray(rng.integers(0, 3, B), np.int32),
        np.asarray(np.sort(rng.integers(70_000, 150_000, B)), np.int32),
        rng.random(B) < 0.9,
    )
    s1 = wc.step(wc.init_state(C, W), join, *args, divisor_ms=DIV,
                 lateness_ms=LATE, method="scatter")
    for method in ("onehot", "matmul"):
        s2 = wc.step(wc.init_state(C, W), join, *args, divisor_ms=DIV,
                     lateness_ms=LATE, method=method)
        np.testing.assert_array_equal(np.asarray(s1.counts),
                                      np.asarray(s2.counts))
        np.testing.assert_array_equal(np.asarray(s1.window_ids),
                                      np.asarray(s2.window_ids))
