"""Config #4 at scale: heavy-hitter reporting must stay sublinear.

The r02 verdict flagged that ``heavy_hitters`` enumerated every interned
user per report (1e5+ queries at scale).  Now candidates live in a
fixed-size device ring (``ops.cms.TopKState``): these tests pin (a) the
ring finds the true heavy hitters in a skewed 1e5-user stream, (b) the
report queries O(ring) not O(users), and (c) ``user_capacity`` overflow
is counted, not silently wrong.
"""

import json
import random

import jax.numpy as jnp
import numpy as np

from streambench_tpu.config import default_config
from streambench_tpu.engine.sketches import SessionCMSEngine
from streambench_tpu.ops import cms

MAPPING = {f"ad{i}": f"c{i % 5}" for i in range(20)}


def click_line(user: str, t: int) -> bytes:
    return json.dumps({
        "user_id": user, "page_id": "p0",
        "ad_id": f"ad{t % 20}", "ad_type": "banner",
        "event_type": "click", "event_time": str(t),
    }).encode()


def test_topk_ring_finds_true_heavy_hitters_among_1e5_users():
    rng = random.Random(3)
    hot = [f"hot{i}" for i in range(8)]
    t = 1_700_000_000_000
    lines = []
    # 30k events: 60% from 8 hot users, rest from a 1e5-user cold pool;
    # sessions close via the 30s gap as event time advances.
    for i in range(30_000):
        if rng.random() < 0.6:
            u = hot[rng.randrange(8)]
        else:
            u = f"cold{rng.randrange(100_000)}"
        lines.append(click_line(u, t))
        t += 40  # 40 ms stride -> old sessions expire as time passes
    cfg = default_config(jax_batch_size=1024)
    eng = SessionCMSEngine(cfg, MAPPING, user_capacity=1 << 17, top_k=8)
    for off in range(0, len(lines), 1024):
        eng.process_lines(lines[off:off + 1024])
    eng.close()

    # report cost: candidates bounded by the ring, not the user universe
    ring = np.asarray(eng.topk.keys)
    assert ring.shape[0] == 128
    assert eng.encoder.num_interned_users() > 10_000  # ring << universe

    hh = dict(eng.heavy_hitters())
    assert len(hh) <= 8
    # every reported heavy hitter is a hot user (cold users have ~1-2
    # clicks; CMS overestimation is bounded by width 2048 at this load)
    assert set(hh) <= set(hot), hh
    assert len(set(hh) & set(hot)) >= 6, hh


def test_update_topk_dedupes_and_keeps_max_estimate():
    state = cms.init_state(depth=4, width=256)
    topk = cms.init_topk(8)
    keys = jnp.asarray(np.array([5, 5, 9, 3], np.int32))
    w = jnp.asarray(np.array([10, 7, 2, 1], np.int32))
    mask = jnp.asarray(np.array([True, True, True, False]))
    state = cms.update(state, keys, w, mask)
    topk = cms.update_topk(state, topk, keys, mask)
    ks = np.asarray(topk.keys)
    # key 5 appears once despite two batch occurrences; masked key 3 absent
    assert list(ks[ks >= 0]) in ([5, 9], [9, 5])
    assert sorted(ks[ks >= 0].tolist()) == [5, 9]
    es = dict(zip(ks.tolist(), np.asarray(topk.ests).tolist()))
    assert es[5] == 17 and es[9] == 2


def test_user_capacity_overflow_is_counted_not_silent():
    cfg = default_config(jax_batch_size=256)
    eng = SessionCMSEngine(cfg, MAPPING, user_capacity=64, top_k=4)
    t = 1_700_000_000_000
    lines = [click_line(f"u{i}", t + i) for i in range(300)]
    eng.process_lines(lines)
    eng.close()
    # 300 distinct users against capacity 64: the overflow is visible
    assert eng.dropped > 0
    assert eng.dropped >= 300 - 64
    # the engine still reports a bounded, well-formed top-k
    hh = eng.heavy_hitters()
    assert len(hh) <= 4
    for user, est in hh:
        assert est >= 1 and user.startswith("u")


def test_legacy_snapshot_without_ring_reseeds_candidates():
    """Restoring a pre-ring snapshot (no hh_keys) must not silently lose
    pre-crash heavy hitters: the ring reseeds from the restored intern
    universe once at restore time."""
    cfg = default_config(jax_batch_size=512)
    eng = SessionCMSEngine(cfg, MAPPING, user_capacity=1 << 12, top_k=4)
    t = 1_700_000_000_000
    lines = []
    rng = random.Random(5)
    # "star" is hot early then goes silent: its session CLOSES via the
    # 30 s gap as event time advances and feeds the CMS with a big count
    # (a continuously-active user's session never closes pre-snapshot).
    for i in range(4000):
        if i < 1500 and rng.random() < 0.4:
            u = "star"
        else:
            u = f"u{rng.randrange(2000)}"
        lines.append(click_line(u, t))
        t += 50
    for off in range(0, len(lines), 512):
        eng.process_lines(lines[off:off + 512])
    eng.flush()
    snap = eng.snapshot(offset=0)
    del snap.extra["hh_keys"]
    del snap.extra["hh_ests"]

    eng2 = SessionCMSEngine(cfg, MAPPING, user_capacity=1 << 12, top_k=4)
    eng2.restore(snap)
    hh = dict(eng2.heavy_hitters())
    assert "star" in hh, hh


def test_update_topk_dedup_survives_interleaved_estimates():
    """Regression: dedup must group by KEY, not by estimate rank — an
    int64-packed rank truncates to int32 under default JAX and lets the
    same key occupy several ring slots, shrinking effective capacity."""
    state = cms.init_state(depth=4, width=1024)
    topk = cms.init_topk(4)
    # weights chosen so key 7's two updates bracket key 2's estimate
    keys = jnp.asarray(np.array([7, 2, 7, 9, 5], np.int32))
    w = jnp.asarray(np.array([10, 8, 3, 2, 1], np.int32))
    mask = jnp.ones(5, bool)
    state = cms.update(state, keys, w, mask)
    topk = cms.update_topk(state, topk, keys, mask)
    ks = np.asarray(topk.keys)
    live = ks[ks >= 0].tolist()
    assert len(live) == len(set(live)), f"duplicate keys in ring: {live}"
    assert set(live) == {7, 2, 9, 5}
    es = dict(zip(ks.tolist(), np.asarray(topk.ests).tolist()))
    assert es[7] == 13 and es[2] == 8
