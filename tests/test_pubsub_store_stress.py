"""Stress coverage the r01/r02 verdicts kept asking for: the pub/sub
channel's documented bounded-send behavior (slow consumers are dropped,
not allowed to backpressure aggregation; ``dimensions/pubsub.py``) and
the durable store's crash-replay under ongoing writes
(``dimensions/store.py``).  Reference: the Apex gateway pub/sub query
path (``ApplicationDimensionComputation.java:236-259``) and the
HDFS-backed HDHT store (``:201-211``).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

from streambench_tpu.dimensions.pubsub import PubSubClient, PubSubServer
from streambench_tpu.dimensions.store import DurableDimensionStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------
# pub/sub
# ----------------------------------------------------------------------

def test_slow_consumer_is_dropped_without_stalling_publish():
    srv = PubSubServer().start()
    try:
        host, port = srv.address
        # a deliberately tiny receive buffer + a client that never reads
        slow = socket.create_connection((host, port))
        slow.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        slow.sendall(b'{"type": "subscribe", "topic": "agg"}\n')
        deadline = time.monotonic() + 5
        while (srv.subscriber_count("agg") == 0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert srv.subscriber_count("agg") == 1

        # flood with large payloads; the blocked send must time out and
        # evict the consumer instead of stalling the publisher forever
        payload = {"rows": "x" * 262_144}
        t0 = time.monotonic()
        dropped = False
        for _ in range(64):
            if srv.publish("agg", payload) == 0:
                dropped = True
                break
        wall = time.monotonic() - t0
        assert dropped, "slow consumer was never dropped"
        # bounded: one socket-timeout-worth of stall (1 s) + slack
        assert wall < 10.0, f"publish stalled {wall:.1f}s on a slow consumer"
        assert srv.subscriber_count("agg") == 0
        slow.close()

        # the channel still serves a healthy subscriber afterwards
        good = PubSubClient(host, port)
        good.subscribe("agg")
        deadline = time.monotonic() + 5
        while (srv.subscriber_count("agg") == 0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert srv.publish("agg", {"ok": 1}) == 1
        msg = good.recv()
        assert msg["data"] == {"ok": 1}
        good.close()
    finally:
        srv.close()


def test_subscriber_reconnect_resumes_stream():
    srv = PubSubServer().start()
    try:
        host, port = srv.address
        c1 = PubSubClient(host, port)
        c1.subscribe("t")
        while srv.subscriber_count("t") == 0:
            time.sleep(0.01)
        assert srv.publish("t", 1) == 1
        assert c1.recv()["data"] == 1
        c1.close()  # consumer goes away (crash/disconnect)

        # the dead handler is pruned on the next publish, not leaked
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if srv.publish("t", 2) == 0 and srv.subscriber_count("t") == 0:
                break
            time.sleep(0.05)
        assert srv.subscriber_count("t") == 0

        # reconnect: a fresh subscription picks the stream back up
        c2 = PubSubClient(host, port)
        c2.subscribe("t")
        while srv.subscriber_count("t") == 0:
            time.sleep(0.01)
        assert srv.publish("t", 3) == 1
        assert c2.recv()["data"] == 3
        c2.close()
    finally:
        srv.close()


# ----------------------------------------------------------------------
# durable store
# ----------------------------------------------------------------------

_WRITER = """
import os, sys
sys.path.insert(0, {repo!r})
from streambench_tpu.dimensions.store import DurableDimensionStore

store = DurableDimensionStore(sys.argv[1], sync_every=1)
i = 0
while True:
    store.put_rows([(f"k{{i % 50}}", (i // 50) * 10_000,
                     {{"clicks:SUM": i}})], update_time_ms=i)
    i += 1
    if i % 100 == 0:
        print(i, flush=True)   # "durable at least through i" marker
"""


def test_store_crash_replay_under_concurrent_writes(tmp_path):
    """SIGKILL a process mid-append-stream; reopening must replay every
    fsynced record, tolerate the torn tail, and keep accepting writes."""
    d = str(tmp_path / "store")
    p = subprocess.Popen([sys.executable, "-c",
                          _WRITER.format(repo=REPO), d],
                         stdout=subprocess.PIPE, text=True, cwd=REPO)
    # let it write for a bit, tracking its durability watermark
    progress = 0
    deadline = time.monotonic() + 60
    while progress < 500 and time.monotonic() < deadline:
        line = p.stdout.readline()
        if line.strip().isdigit():
            progress = int(line)
    os.kill(p.pid, signal.SIGKILL)
    p.wait(timeout=30)
    assert progress >= 500

    # possibly-torn tail: append garbage half-record like a crash mid-write
    with open(os.path.join(d, "dimensions.log"), "a") as f:
        f.write('{"k": "k1", "b": 0, "t": 9')  # no newline, truncated

    store = DurableDimensionStore(d)
    # every record the writer reported durable must be present: row i
    # lands at (k{i%50}, (i//50)*10000) with clicks:SUM monotone in i,
    # so the max clicks over the index bounds the replayed prefix.
    max_seen = max(v["clicks:SUM"] for _, v in store.items())
    assert max_seen >= progress - 1
    assert len(store) >= 50

    # the reopened store keeps working: new writes, compaction, reread
    store.put_rows([("k1", 0, {"clicks:SUM": 10_000_000})],
                   update_time_ms=123)
    store.compact()
    store.close()
    store2 = DurableDimensionStore(d)
    assert store2.get("k1", 0)["clicks:SUM"] == 10_000_000
    # compaction kept exactly one record per (key, bucket)
    with open(os.path.join(d, "dimensions.log")) as f:
        lines = [json.loads(x) for x in f if x.strip()]
    assert len(lines) == len(store2)
    store2.close()
