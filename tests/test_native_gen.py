"""Native event formatter (native/gen.cpp) + engine warmup.

The formatter renders the reference wire format (``make-kafka-event-at``,
``core.clj:163-181``) from C.  RNG streams differ from the Python path by
design, so the contract tested here is *format* identity (field order,
quoting, value domains) and *distribution* sanity — not byte equality.
"""

import json
import random
import re

import numpy as np
import pytest

from streambench_tpu import native
from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.engine import AdAnalyticsEngine
from streambench_tpu.io.journal import FileBroker

pytestmark = pytest.mark.skipif(native.load() is None,
                                reason="native library unavailable")


def make_source(with_skew=False, seed=7):
    rng = random.Random(seed)
    ads = gen.make_ids(50, rng)
    return gen.EventSource(ads=ads, user_ids=gen.make_ids(10, rng),
                           page_ids=gen.make_ids(10, rng),
                           with_skew=with_skew, rng=rng), ads


def test_blob_format_matches_python_template():
    src, ads = make_source()
    blob = src.events_blob_at(np.arange(100, dtype=np.int64) * 10)
    assert blob is not None and blob.endswith(b"\n")
    lines = blob.split(b"\n")[:-1]
    assert len(lines) == 100
    py = src.event_at(0).encode()
    key_order = re.findall(rb'"(\w+)":', py)
    for i, line in enumerate(lines):
        assert re.findall(rb'"(\w+)":', line) == key_order
        ev = json.loads(line)
        assert ev["event_time"] == str(i * 10)
        assert ev["ad_id"] in ads
        assert ev["ip_address"] == "1.2.3.4"
        assert ev["event_type"] in gen.EVENT_TYPES
        assert ev["ad_type"] in gen.AD_TYPES


def test_blob_deterministic_per_seed_and_distribution():
    src1, _ = make_source(seed=3)
    src2, _ = make_source(seed=3)
    ts = np.arange(30_000, dtype=np.int64)
    assert src1.events_blob_at(ts) == src2.events_blob_at(ts)
    # uniform-ish event_type split (exact thirds would be suspicious too)
    kinds = [json.loads(l)["event_type"]
             for l in src1.events_blob_at(ts).split(b"\n")[:-1]]
    for t in gen.EVENT_TYPES:
        assert 0.25 < kinds.count(t) / len(kinds) < 0.42


def test_blob_skew_semantics():
    """±50 ms skew; ~1/100k late by up to 60 s (core.clj:166-174)."""
    src, _ = make_source(with_skew=True)
    base = 10_000_000
    ts = np.full(300_000, base, dtype=np.int64)
    stamps = [int(json.loads(l)["event_time"])
              for l in src.events_blob_at(ts).split(b"\n")[:-1]]
    assert max(stamps) <= base + 50
    late = [s for s in stamps if s < base - 60]
    assert len(late) < 30                       # ~3 expected at 1/100k
    assert min(stamps) >= base - 50 - 60_000


def test_blob_feeds_engine_oracle_exact(tmp_path):
    """Native-formatted events through the real engine must count exactly
    like the golden model (the oracle is format-blind: it replays the
    journal, ``dostats`` ``core.clj:101-128``)."""
    from streambench_tpu.io.fakeredis import FakeRedisStore
    from streambench_tpu.io.redis_schema import as_redis

    r = as_redis(FakeRedisStore())
    cfg = default_config(jax_batch_size=256, jax_scan_batches=2)
    broker = FileBroker(str(tmp_path / "broker"))
    n = gen.do_setup(r, cfg, broker=broker, events_num=5_000,
                     rng=random.Random(5), workdir=str(tmp_path))
    assert n == 5_000
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    from streambench_tpu.engine import StreamRunner

    eng = AdAnalyticsEngine(cfg, mapping, redis=r)
    runner = StreamRunner(eng, broker.reader(cfg.kafka_topic))
    runner.run_catchup()
    eng.close()
    correct, differ, missing = gen.check_correct(
        r, workdir=str(tmp_path), log=lambda s: None,
        time_divisor_ms=cfg.jax_time_divisor_ms)
    assert differ == 0 and missing == 0 and correct > 0


def test_run_paced_blob_path_counts(tmp_path):
    broker = FileBroker(str(tmp_path / "broker"))
    broker.create_topic("t", 1)
    rng = random.Random(1)
    gen.write_ids(gen.make_ids(10, rng), gen.make_ids(100, rng),
                  str(tmp_path))
    with broker.writer("t", 0) as sink:
        sent = gen.run_paced(sink, 50_000, duration_s=0.5,
                             workdir=str(tmp_path))
    assert sent > 0
    lines = broker.reader("t").poll(max_records=1 << 30)
    assert len(lines) == sent
    json.loads(lines[-1])                       # last record is complete


def test_warmup_compiles_without_state_change():
    cfg = default_config(jax_batch_size=128, jax_scan_batches=4)
    rng = random.Random(2)
    ads = gen.make_ids(20, rng)
    mapping = {a: f"c{i % 4}" for i, a in enumerate(ads)}
    eng = AdAnalyticsEngine(cfg, mapping)
    eng.warmup()
    assert eng.events_processed == 0
    assert not eng._pending
    assert int(np.asarray(eng.state.counts).sum()) == 0
    # engine still counts correctly after warmup
    src = gen.EventSource(ads=ads, user_ids=gen.make_ids(4, rng),
                          page_ids=gen.make_ids(4, rng), rng=rng)
    lines = [l.encode() for l in src.events_at([50_000] * 300)]
    views = sum(1 for l in lines if b'"view"' in l)
    assert views > 0
    eng.process_chunk(lines)
    eng.flush()
    assert eng.events_processed == 300
    assert eng.dropped == 0
    assert eng.windows_written >= 1
    assert sum(eng.latency_tracker.counts.values()
               if hasattr(eng.latency_tracker, "counts") else [1]) >= 1


def test_sketch_engine_warmup():
    from streambench_tpu.engine.sketches import HLLDistinctEngine

    cfg = default_config(jax_batch_size=64, jax_scan_batches=2)
    rng = random.Random(4)
    ads = gen.make_ids(10, rng)
    mapping = {a: f"c{i % 2}" for i, a in enumerate(ads)}
    eng = HLLDistinctEngine(cfg, mapping)
    eng.warmup()
    assert eng.events_processed == 0


def test_run_paced_high_rate_exactness(tmp_path):
    """The pacing loop must deliver the full schedule at rates far above
    the tick resolution (regression: an emit-ahead '+1' in the due
    computation turned the loop into kHz micro-batches whose overhead
    capped the rate at ~160k ev/s)."""
    import os
    import shutil
    import tempfile

    rate, secs = 250_000, 3.0
    # RAM-backed broker when it can hold the journal (~250 B/event):
    # disk writeback throttling or a tiny container /dev/shm would fail
    # the test for environmental reasons (same guard as bench.py).
    base = str(tmp_path)
    try:
        sv = os.statvfs("/dev/shm")
        if sv.f_bavail * sv.f_frsize > rate * secs * 250 * 2:
            base = "/dev/shm"
    except OSError:
        pass
    bdir = tempfile.mkdtemp(dir=base)
    try:
        broker = FileBroker(os.path.join(bdir, "broker"))
        broker.create_topic("t", 1)
        rng = random.Random(3)
        gen.write_ids(gen.make_ids(10, rng), gen.make_ids(100, rng),
                      str(tmp_path))
        with broker.writer("t", 0) as sink:
            sent = gen.run_paced(sink, rate, duration_s=secs,
                                 workdir=str(tmp_path))
        # near-full delivery (generous host-contention allowance; the
        # old '+1' bug lost >40% at this rate)
        assert sent >= rate * secs * 0.90, sent
        # and events carry the exact schedule: event_time of the n-th
        # record advances by ~1000/rate ms
        lines = broker.reader("t").poll(max_records=1000)
        t0 = json.loads(lines[0])["event_time"]
        t999 = json.loads(lines[999])["event_time"]
        assert 0 <= int(t999) - int(t0) <= 10
    finally:
        shutil.rmtree(bdir, ignore_errors=True)
