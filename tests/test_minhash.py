"""MinHash ∪ HLL reach sketches (ops/minhash.py, ISSUE 10): the fold vs
a numpy set-arithmetic oracle, the merge algebra (commutative,
associative, idempotent, shard-order-invariant over random shard splits
— what makes sharded reach trivially correct later), scan/packed-scan
bit-identity, and the numpy hash mirrors the oracle depends on."""

import random

import jax.numpy as jnp
import numpy as np
import pytest

from streambench_tpu.ops import hll, minhash
from streambench_tpu.reach import oracle as ro

C, K, R = 7, 64, 64
JOIN = np.array([0, 0, 1, 1, 2, 3, 4, 5, 6, -1], np.int32)


def rand_batch(rng, B=256, ads=10):
    """One adversarial micro-batch: dup users, invalid rows, non-view
    events, join-miss ads."""
    return dict(
        ad_idx=rng.integers(0, ads, B).astype(np.int32),
        user_idx=rng.integers(-2**31, 2**31 - 1, B,
                              dtype=np.int64).astype(np.int32),
        event_type=rng.integers(0, 3, B).astype(np.int32),
        event_time=rng.integers(0, 10**6, B).astype(np.int32),
        valid=rng.random(B) > 0.15,
    )


def fold(state, batches):
    join = jnp.asarray(JOIN)
    for b in batches:
        state = minhash.step(state, join, jnp.asarray(b["ad_idx"]),
                             jnp.asarray(b["user_idx"]),
                             jnp.asarray(b["event_type"]),
                             jnp.asarray(b["event_time"]),
                             jnp.asarray(b["valid"]))
    return state


def oracle_sets(batches):
    sets = {c: set() for c in range(C)}
    for b in batches:
        for a, u, e, v in zip(b["ad_idx"], b["user_idx"],
                              b["event_type"], b["valid"]):
            camp = JOIN[a]
            if v and e == 0 and camp >= 0:
                sets[camp].add(int(u))
    return sets


def expected(sets):
    names = [str(c) for c in range(C)]
    return ro.expected_state({str(c): sets[c] for c in range(C)},
                             names, K, R)


# ------------------------------------------------------------- hashes
def test_numpy_hash_mirrors_are_bit_identical():
    """The oracle's numpy splitmix32/rank/salts must match the jax ops
    bit-for-bit — everything downstream (expected_state, bench
    bit-exactness) rests on this differential."""
    xs = np.array([0, 1, -1, 2**31 - 1, -2**31, 12345, -98765],
                  np.int64).astype(np.int32)
    got = np.asarray(hll.splitmix32(jnp.asarray(xs)))
    want = ro.splitmix32_np(xs)
    np.testing.assert_array_equal(got, want)
    h = ro.splitmix32_np(np.arange(1000, dtype=np.int64).astype(np.int32))
    for p in (4, 6, 8):
        got = np.asarray(hll._rank(jnp.asarray(h), p))
        np.testing.assert_array_equal(got, ro.rank_np(h, p))
    np.testing.assert_array_equal(np.asarray(minhash.salts(K)),
                                  ro.salts_np(K))


# --------------------------------------------------------------- fold
def test_step_matches_set_arithmetic_oracle():
    rng = np.random.default_rng(3)
    batches = [rand_batch(rng) for _ in range(8)]
    st = fold(minhash.init_state(C, K, R), batches)
    em, er = expected(oracle_sets(batches))
    np.testing.assert_array_equal(np.asarray(st.mins), em)
    np.testing.assert_array_equal(np.asarray(st.registers), er)
    assert int(st.dropped) == 0   # reach never drops: no ring, no cutoff


def test_duplicate_events_are_idempotent():
    """Folding the SAME batches twice changes nothing — running min/max
    absorb duplicates (the dedup-free materialize contract)."""
    rng = np.random.default_rng(4)
    batches = [rand_batch(rng) for _ in range(4)]
    once = fold(minhash.init_state(C, K, R), batches)
    twice = fold(once, batches)
    np.testing.assert_array_equal(np.asarray(once.mins),
                                  np.asarray(twice.mins))
    np.testing.assert_array_equal(np.asarray(once.registers),
                                  np.asarray(twice.registers))


def test_scan_steps_bit_identical_to_step_sequence():
    rng = np.random.default_rng(5)
    batches = [rand_batch(rng, B=128) for _ in range(6)]
    seq = fold(minhash.init_state(C, K, R), batches)
    stacked = {k: np.stack([b[k] for b in batches]) for k in batches[0]}
    scanned = minhash.scan_steps(
        minhash.init_state(C, K, R), jnp.asarray(JOIN),
        jnp.asarray(stacked["ad_idx"]), jnp.asarray(stacked["user_idx"]),
        jnp.asarray(stacked["event_type"]),
        jnp.asarray(stacked["event_time"]), jnp.asarray(stacked["valid"]))
    for a, b in zip(seq, scanned):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_packed_scan_bit_identical():
    from streambench_tpu.ops import windowcount as wc

    rng = np.random.default_rng(6)
    batches = [rand_batch(rng, B=128) for _ in range(4)]
    seq = fold(minhash.init_state(C, K, R), batches)
    packed = np.stack([np.asarray(wc.pack_columns(
        b["ad_idx"], b["event_type"], b["valid"])) for b in batches])
    scanned = minhash.scan_steps_packed(
        minhash.init_state(C, K, R), jnp.asarray(JOIN),
        jnp.asarray(packed),
        jnp.asarray(np.stack([b["user_idx"] for b in batches])),
        jnp.asarray(np.stack([b["event_time"] for b in batches])))
    np.testing.assert_array_equal(np.asarray(seq.mins),
                                  np.asarray(scanned.mins))
    np.testing.assert_array_equal(np.asarray(seq.registers),
                                  np.asarray(scanned.registers))


# ------------------------------------------------------- merge algebra
def _states(rng, n):
    return [fold(minhash.init_state(C, K, R),
                 [rand_batch(rng) for _ in range(2)]) for _ in range(n)]


def assert_state_equal(a, b):
    np.testing.assert_array_equal(np.asarray(a.mins), np.asarray(b.mins))
    np.testing.assert_array_equal(np.asarray(a.registers),
                                  np.asarray(b.registers))


def test_merge_commutative_associative_idempotent():
    rng = np.random.default_rng(7)
    a, b, c = _states(rng, 3)
    assert_state_equal(minhash.merge(a, b), minhash.merge(b, a))
    assert_state_equal(minhash.merge(minhash.merge(a, b), c),
                       minhash.merge(a, minhash.merge(b, c)))
    assert_state_equal(minhash.merge(a, a), a)


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_shard_order_invariance_random_splits(seed):
    """Hypothesis-style sweep: split one stream across S shards at
    random, fold each shard independently, merge in a random order —
    the result is bit-identical to the single-engine fold.  This is
    the property that makes the sharded variant trivially correct."""
    rng = np.random.default_rng(seed)
    pyrng = random.Random(seed)
    batches = [rand_batch(rng, B=128) for _ in range(10)]
    reference = fold(minhash.init_state(C, K, R), batches)
    S = pyrng.choice([2, 3, 4])
    shards = [[] for _ in range(S)]
    for b in batches:
        shards[pyrng.randrange(S)].append(b)
    partials = [fold(minhash.init_state(C, K, R), sh) for sh in shards]
    pyrng.shuffle(partials)
    merged = partials[0]
    for p in partials[1:]:
        merged = minhash.merge(merged, p)
    assert_state_equal(merged, reference)


# ----------------------------------------------------------- estimates
def test_estimate_tracks_true_cardinality():
    """Statistical sanity at R=64: per-campaign estimates within 4
    sigma of the true distinct counts (seeded, deterministic)."""
    rng = np.random.default_rng(21)
    batches = [rand_batch(rng, B=1024, ads=9) for _ in range(12)]
    st = fold(minhash.init_state(C, K, R), batches)
    sets = oracle_sets(batches)
    est = np.asarray(minhash.estimate(st.registers))
    for c in range(C):
        true = len(sets[c])
        if true < 50:
            continue
        rel = abs(est[c] - true) / true
        assert rel < 4 * 1.04 / np.sqrt(R), (c, true, est[c], rel)
