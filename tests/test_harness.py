"""Harness CLI tests — the ``stream-bench.sh`` peer end-to-end.

The composite ``JAX_TEST`` is the same sequence as the reference's
``FLINK_TEST`` (``stream-bench.sh:301-315``): services up -> engine up ->
paced load -> stop load (collect stats to ``seen.txt``/``updated.txt``) ->
teardown.  The run here is real multi-process: a RESP server process, an
engine process, and a generator process, talking over sockets and the
journal broker.
"""

import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SB = os.path.join(REPO, "stream_bench.py")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_harness(ops, env_extra, timeout=240):
    env = dict(os.environ, **env_extra, PYTHONUNBUFFERED="1",
               JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable, SB, *ops], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=timeout)


def test_unknown_operation_lists_supported():
    proc = run_harness(["NO_SUCH_OP"], {"WORKDIR": "/tmp/sb-unknown"})
    assert proc.returncode == 1
    assert "UNKNOWN OPERATION" in proc.stdout
    assert "JAX_TEST" in proc.stdout


def _await_window_progress(port: int, min_windows: int,
                           deadline_s: float) -> int:
    """Poll the canonical Redis schema until >= ``min_windows`` windows
    carry counts.  Awaiting ORACLE-VISIBLE progress (not a fixed sleep)
    is what makes this test immune to full-suite CPU contention — the
    reference's embedded-cluster test likewise runs until work is
    observable, not for a tuned wall-time
    (``ApplicationWithDCWithoutDeserializerTest.java:19-45``)."""
    from streambench_tpu.io.redis_schema import read_stats
    from streambench_tpu.io.resp import RespClient

    deadline = time.monotonic() + deadline_s
    n = 0
    while time.monotonic() < deadline:
        try:
            with RespClient("127.0.0.1", port, timeout_s=2.0) as c:
                n = len(read_stats(c))
        except OSError:
            n = 0
        if n >= min_windows:
            return n
        time.sleep(0.5)
    raise AssertionError(
        f"only {n}/{min_windows} windows visible after {deadline_s}s")


def test_jax_test_end_to_end(tmp_path):
    """The FLINK_TEST-shaped composite, staged so the load phase ends on
    observed window progress rather than a fixed TEST_TIME sleep (the
    fixed-sleep variant flaked under full-suite contention: 15 s could
    elapse entirely inside warmup+catchup, leaving seen.txt empty)."""
    wd = str(tmp_path / "run")
    port = free_port()
    env = {
        "WORKDIR": wd,
        "REDIS_PORT": str(port),
        "LOAD": "400",
        "STOP_STATS_GRACE": "4",
        "TOPIC": "ad-events",
    }
    up = run_harness(
        ["SETUP", "START_REDIS", "START_JAX_PROCESSING", "START_LOAD"],
        env, timeout=360)
    try:
        assert up.returncode == 0, up.stdout + up.stderr
        # paced load at 400 ev/s fills a 10 s window in ~10 s; 3 windows
        # with counts proves ingest -> device fold -> flush -> schema all
        # work.  The deadline only bounds a genuine hang.
        _await_window_progress(port, min_windows=3, deadline_s=120)
    finally:
        down = run_harness(
            ["STOP_LOAD", "STOP_JAX_PROCESSING", "STOP_REDIS"], env,
            timeout=240)
    assert down.returncode == 0, down.stdout + down.stderr

    # stats were collected into the canonical files (core.clj:130-149)
    seen = open(os.path.join(wd, "seen.txt")).read().split()
    updated = open(os.path.join(wd, "updated.txt")).read().split()
    assert seen and updated and len(seen) == len(updated)
    assert all(int(s) > 0 for s in seen)

    # the engine exited cleanly and processed events exactly
    last = open(os.path.join(wd, "logs", "engine.log")).read().strip()
    stats = json.loads(last.splitlines()[-1])
    assert stats["events"] > 0
    assert stats["dropped"] == 0
    total_seen = sum(int(s) for s in seen)
    assert 0 < total_seen <= stats["events"]

    # teardown left no processes behind
    for name in ("redis", "engine", "load"):
        assert not os.path.exists(os.path.join(wd, "pids", f"{name}.pid"))


def test_ops_are_rerunnable(tmp_path):
    """STOP on nothing is a no-op, like stop_if_needed (stream-bench.sh:66)."""
    wd = str(tmp_path / "run2")
    proc = run_harness(["STOP_ALL"], {"WORKDIR": wd})
    assert proc.returncode == 0
    assert "No running instances" in proc.stdout


def test_suite_retry_gated_on_wedge_signature(tmp_path, monkeypatch):
    """op_jax_test_suite retries a family ONCE only on the zero-evidence
    startup-wedge signature; any other failure fails immediately, and
    every attempt's rc lands in jax_test_suite.json."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("sb_mod", SB)
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)
    monkeypatch.setattr(sb, "WORKDIR", str(tmp_path))

    calls = []

    class P:
        def __init__(self, rc, out=""):
            self.returncode = rc
            self.stdout = out
            self.stderr = ""

    def fake_run(cmd, env=None, cwd=None, capture_output=None, text=None):
        engine = env["ENGINE"]
        calls.append(engine)
        if engine == "hll" and calls.count("hll") == 1:
            # first hll attempt: the wedge signature -> retried
            return P(1, "JAX_TEST measured no events — the engine "
                        "processed nothing")
        return P(0)

    monkeypatch.setattr(sb.subprocess, "run", fake_run)
    sb.op_jax_test_suite()
    assert calls == ["exact", "hll", "hll", "sliding", "session"]
    rec = json.load(open(tmp_path / "jax_test_suite.json"))
    by = {f["engine"]: f for f in rec["families"]}
    assert by["hll"]["retried"] and by["hll"]["attempt_rcs"] == [1, 0]
    assert not by["exact"]["retried"]

    # a NON-wedge failure (oracle diff, crash) must fail immediately
    calls.clear()

    def fake_run_hard_fail(cmd, env=None, cwd=None, capture_output=None,
                           text=None):
        calls.append(env["ENGINE"])
        return P(1, "windows DIFFER: 3")

    monkeypatch.setattr(sb.subprocess, "run", fake_run_hard_fail)
    try:
        sb.op_jax_test_suite()
        raise AssertionError("suite must fail on a non-wedge failure")
    except SystemExit:
        pass
    assert calls == ["exact"], "no retry for a non-wedge failure"


def _load_sb(tmp_path, monkeypatch, **over):
    """Fresh stream_bench module instance with paths pinned to tmp."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("sb_ext", SB)
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)
    monkeypatch.setattr(sb, "WORKDIR", str(tmp_path))
    monkeypatch.setattr(sb, "PID_DIR", str(tmp_path / "pids"))
    monkeypatch.setattr(sb, "LOG_DIR", str(tmp_path / "logs"))
    for k, v in over.items():
        monkeypatch.setattr(sb, k, v)
    return sb


def test_pidfile_starttime_match(tmp_path, monkeypatch):
    """pid-match (ROADMAP item 5 slice): a pidfile whose recorded kernel
    start time no longer matches the process reads as 'not running', so
    STOP never signals a recycled pid it didn't start."""
    sb = _load_sb(tmp_path, monkeypatch)
    os.makedirs(sb.PID_DIR, exist_ok=True)
    me = os.getpid()
    started = sb._proc_starttime(me)
    assert started is not None
    # correct starttime -> matches
    with open(sb._pidfile("redis"), "w") as f:
        f.write(f"{me} {started}")
    assert sb.running_pid("redis") == me
    # wrong starttime (recycled pid) -> NOT adopted
    with open(sb._pidfile("redis"), "w") as f:
        f.write(f"{me} 12345")
    assert sb.running_pid("redis") is None
    # stop_if_needed on the mismatch is a no-op (we are still alive)
    sb.stop_if_needed("redis")
    assert os.getpid() == me
    # legacy bare-pid files keep working
    with open(sb._pidfile("redis"), "w") as f:
        f.write(str(me))
    assert sb.running_pid("redis") == me
    os.remove(sb._pidfile("redis"))


def test_external_redis_adopted_not_stopped(tmp_path, monkeypatch):
    """External-Redis drive mode: redis.host/redis.port pointing at an
    already-running server is health-checked (PING) instead of spawned,
    and STOP leaves it running."""
    sys.path.insert(0, REPO)
    from streambench_tpu.io.fakeredis import FakeRedisServer
    from streambench_tpu.io.resp import RespClient

    srv = FakeRedisServer(host="127.0.0.1", port=0).start()
    port = srv.port
    try:
        sb = _load_sb(tmp_path, monkeypatch,
                      REDIS_HOST="127.0.0.1", REDIS_PORT=port)
        assert sb._redis_alive()
        # seeding needs the datagen CLI; run only the adoption half
        sb.os.makedirs(sb.PID_DIR, exist_ok=True)
        assert sb.running_pid("redis") is None
        # op_start_redis would seed via subprocess; drive the adoption
        # logic directly (the marker decides STOP's behavior)
        with open(sb._external_redis_marker(), "w") as f:
            f.write(f"127.0.0.1:{port}\n")
        sb.op_stop_redis()
        assert not os.path.exists(sb._external_redis_marker())
        # the server this harness never started is STILL serving
        with RespClient("127.0.0.1", port, timeout_s=2.0) as c:
            assert c.ping() == "PONG"
        # and a second STOP (no marker, no pidfile) is a clean no-op
        sb.op_stop_redis()
    finally:
        srv.stop()
