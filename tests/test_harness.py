"""Harness CLI tests — the ``stream-bench.sh`` peer end-to-end.

The composite ``JAX_TEST`` is the same sequence as the reference's
``FLINK_TEST`` (``stream-bench.sh:301-315``): services up -> engine up ->
paced load -> stop load (collect stats to ``seen.txt``/``updated.txt``) ->
teardown.  The run here is real multi-process: a RESP server process, an
engine process, and a generator process, talking over sockets and the
journal broker.
"""

import json
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SB = os.path.join(REPO, "stream_bench.py")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_harness(ops, env_extra, timeout=240):
    env = dict(os.environ, **env_extra, PYTHONUNBUFFERED="1",
               JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable, SB, *ops], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=timeout)


def test_unknown_operation_lists_supported():
    proc = run_harness(["NO_SUCH_OP"], {"WORKDIR": "/tmp/sb-unknown"})
    assert proc.returncode == 1
    assert "UNKNOWN OPERATION" in proc.stdout
    assert "JAX_TEST" in proc.stdout


def test_jax_test_end_to_end(tmp_path):
    wd = str(tmp_path / "run")
    env = {
        "WORKDIR": wd,
        "REDIS_PORT": str(free_port()),
        "LOAD": "400",
        # generous: under full-suite CPU contention the engine's warmup
        # can eat several seconds before the first flush lands
        "TEST_TIME": "15",
        "STOP_STATS_GRACE": "4",
        "TOPIC": "ad-events",
    }
    proc = run_harness(["JAX_TEST"], env)
    assert proc.returncode == 0, proc.stdout + proc.stderr

    # stats were collected into the canonical files (core.clj:130-149)
    seen = open(os.path.join(wd, "seen.txt")).read().split()
    updated = open(os.path.join(wd, "updated.txt")).read().split()
    assert seen and updated and len(seen) == len(updated)
    assert all(int(s) > 0 for s in seen)

    # the engine exited cleanly and processed events exactly
    last = open(os.path.join(wd, "logs", "engine.log")).read().strip()
    stats = json.loads(last.splitlines()[-1])
    assert stats["events"] > 0
    assert stats["dropped"] == 0
    total_seen = sum(int(s) for s in seen)
    assert 0 < total_seen <= stats["events"]

    # teardown left no processes behind
    for name in ("redis", "engine", "load"):
        assert not os.path.exists(os.path.join(wd, "pids", f"{name}.pid"))


def test_ops_are_rerunnable(tmp_path):
    """STOP on nothing is a no-op, like stop_if_needed (stream-bench.sh:66)."""
    wd = str(tmp_path / "run2")
    proc = run_harness(["STOP_ALL"], {"WORKDIR": wd})
    assert proc.returncode == 0
    assert "No running instances" in proc.stdout
