"""Crash flight recorder (ISSUE 4 tentpole, obs.flightrec): bounded
ordered ring, atomic dumps with the terminal fault last, the runner's
crash dump under an injected FaultPlan crash, and the supervisor's
give-up black box."""

import glob
import json
import os
import random

import pytest

from streambench_tpu.chaos import (
    CrashScheduler,
    EngineCrash,
    FaultPlan,
    Supervisor,
)
from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import as_redis
from streambench_tpu.obs import FlightRecorder


def _load(path):
    return [json.loads(line) for line in open(path)]


def _assert_monotonic(recs):
    seqs = [r["seq"] for r in recs]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    ts = [r["ts_ms"] for r in recs]
    assert ts == sorted(ts)


# ----------------------------------------------------------------------
def test_ring_is_bounded_and_ordered(tmp_path):
    fr = FlightRecorder(str(tmp_path), capacity=16)
    for i in range(100):
        fr.record("tick", i=i)
    assert len(fr) == 16
    recs = fr.snapshot()
    _assert_monotonic(recs)
    assert recs[0]["i"] == 84 and recs[-1]["i"] == 99  # oldest dropped


def test_dump_terminal_record_last_and_unique_paths(tmp_path):
    fr = FlightRecorder(str(tmp_path), capacity=16)
    fr.record("tick", events=10)
    p1 = fr.dump("crash", terminal={"kind": "fault", "event": "crash",
                                    "error": "boom"})
    assert os.path.basename(p1) == "flight_crash.jsonl"
    recs = _load(p1)
    _assert_monotonic(recs)
    assert recs[0]["kind"] == "tick"
    assert recs[-1] == recs[-1] | {"kind": "fault", "event": "crash",
                                   "error": "boom"}
    # a second dump for the same reason never clobbers the first
    p2 = fr.dump("crash", terminal={"event": "crash", "error": "again"})
    assert p2 != p1 and os.path.exists(p1) and os.path.exists(p2)
    assert fr.dumps == [p1, p2]
    # hostile reason strings become safe filenames
    p3 = fr.dump("../../etc x")
    assert os.path.dirname(p3) == str(tmp_path)
    assert "/" not in os.path.basename(p3)[len("flight_"):]


# ----------------------------------------------------------------------
def test_runner_crash_via_fault_plan_leaves_black_box(tmp_path):
    """The ISSUE's satellite: inject a crash via the existing FaultPlan
    machinery and assert a ``flight_*.jsonl`` appears, records in
    monotonic order, terminal fault last."""
    from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner

    cfg = default_config(jax_batch_size=256, jax_scan_batches=2)
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=4000,
                 rng=random.Random(5), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    engine = AdAnalyticsEngine(cfg, mapping, redis=r)
    fr = FlightRecorder(str(tmp_path), capacity=64)
    plan = FaultPlan(crashes=(("batch", 2),))
    runner = StreamRunner(engine, broker.reader(cfg.kafka_topic),
                          crash_points=CrashScheduler(plan.crashes),
                          flightrec=fr)
    with pytest.raises(EngineCrash):
        runner.run_catchup()
    files = glob.glob(str(tmp_path / "flight_*.jsonl"))
    assert len(files) == 1 and files[0].endswith("flight_crash.jsonl")
    recs = _load(files[0])
    _assert_monotonic(recs)
    last = recs[-1]
    assert last["kind"] == "fault" and last["event"] == "crash"
    assert "EngineCrash" in last["error"]
    assert last["offset"] > 0 and last["events"] > 0


def test_runner_feeds_ticks_and_checkpoints(tmp_path):
    """A surviving run leaves flush-cadence ticks + checkpoint offsets
    in the ring (no dump: nothing terminal happened)."""
    from streambench_tpu.checkpoint import Checkpointer
    from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner

    cfg = default_config(jax_batch_size=256, jax_scan_batches=2)
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=4000,
                 rng=random.Random(5), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    engine = AdAnalyticsEngine(cfg, mapping, redis=r)
    fr = FlightRecorder(str(tmp_path), capacity=64)
    runner = StreamRunner(engine, broker.reader(cfg.kafka_topic),
                          checkpointer=Checkpointer(str(tmp_path / "ck")),
                          flightrec=fr)
    runner.run_catchup()
    engine.close()
    kinds = [rec["kind"] for rec in fr.snapshot()]
    assert "tick" in kinds and "checkpoint" in kinds
    tick = next(rec for rec in fr.snapshot() if rec["kind"] == "tick")
    assert "events" in tick and "watermark_lag_ms" in tick
    assert not glob.glob(str(tmp_path / "flight_*.jsonl"))


# ----------------------------------------------------------------------
def test_supervisor_give_up_dumps_terminal_fault(tmp_path):
    """A supervised run that dies for good (no durable progress) leaves
    ``flight_give_up.jsonl`` whose last record is the give-up fault,
    with the crash/restart history before it."""

    class CrashingRunner:
        checkpointer = None
        crash_points = None

        def resume(self):
            return False

        def _reader_position(self):
            return 10

        def run(self, **kw):
            raise EngineCrash("boom")

    fr = FlightRecorder(str(tmp_path), capacity=32)
    sup = Supervisor(CrashingRunner, max_no_progress_restarts=2,
                     backoff_base_ms=0, sleep=lambda s: None,
                     flightrec=fr)
    st = sup.run()
    assert st.gave_up
    files = glob.glob(str(tmp_path / "flight_*.jsonl"))
    assert files == [str(tmp_path / "flight_give_up.jsonl")]
    recs = _load(files[0])
    _assert_monotonic(recs)
    events = [(r["kind"], r.get("event")) for r in recs]
    assert ("supervisor", "crash") in events
    assert ("supervisor", "restart") in events
    last = recs[-1]
    assert last["kind"] == "fault" and last["event"] == "give_up"
    assert "EngineCrash" in last["error"]
    assert last["crashes"] == st.crashes
