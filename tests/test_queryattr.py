"""Query-path latency attribution for the reach serving tier
(obs/queryattr.py + reach/serve.py wiring, ISSUE 11): segment
decomposition summing to the submit->reply e2e, shed queue-only
records reconciling with the shed counter, the bounded slow-query log,
ingest-contention attribution from the span ring, reply-payload
bit-identity when the flag is off, and the serving flight-recorder
records."""

import json
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from streambench_tpu.obs import MetricsRegistry, SpanTracer
from streambench_tpu.obs.queryattr import (
    SEGMENTS,
    QueryLifecycle,
    _interval_overlap_ns,
)
from streambench_tpu.ops import minhash
from streambench_tpu.reach.serve import ReachQueryServer


def tiny_state(C=4, k=16, R=16, seed=0):
    rng = np.random.default_rng(seed)
    st = minhash.init_state(C, k, R)
    join = jnp.asarray(np.arange(C, dtype=np.int32))
    B = 64
    return minhash.step(
        st, join,
        jnp.asarray(rng.integers(0, C, B).astype(np.int32)),
        jnp.asarray(rng.integers(0, 1 << 20, B).astype(np.int32)),
        jnp.zeros(B, jnp.int32), jnp.zeros(B, jnp.int32),
        jnp.ones(B, bool))


def make_server(campaigns=("a", "b", "c", "d"), *, depth=64, batch=8,
                hold=False, slo_ms=0, slowlog_max=16, spans=None,
                flightrec=None, registry=None):
    reg = registry if registry is not None else MetricsRegistry()
    ql = QueryLifecycle(reg, slo_ms=slo_ms, slowlog_max=slowlog_max,
                        spans=spans)
    srv = ReachQueryServer(list(campaigns), depth=depth, batch=batch,
                           hold=hold, registry=reg, queryattr=ql,
                           spans=spans, flightrec=flightrec)
    st = tiny_state(C=len(campaigns))
    srv.update_state(st.mins, st.registers, epoch=1)
    return srv, ql, reg


def drain(srv, got, n, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while len(got) < n and time.monotonic() < deadline:
        time.sleep(0.005)
    assert len(got) >= n, (len(got), n)


# -------------------------------------------------- segment partition
def test_segments_sum_to_e2e_exactly():
    """The four segment histograms' SUMS total the e2e histogram's sum
    exactly (same stamps, float rounding only) — the partition
    contract, at sample resolution rather than bucket resolution."""
    srv, ql, _ = make_server()
    got = []
    try:
        for i in range(40):
            srv.submit(["a", "b"], "overlap" if i % 2 else "union",
                       lambda d: got.append(d), query_id=i)
        drain(srv, got, 40)
    finally:
        srv.close()
    s = ql.summary()
    assert s["served_records"] == 40 and s["shed_records"] == 0
    seg_sum = sum(s["segments"][seg]["sum"] for seg in SEGMENTS)
    assert s["e2e_ms"]["count"] == 40
    # summary() rounds each sum to 3 decimals: five independent
    # roundings bound the partition check at ±2.5e-3
    assert seg_sum == pytest.approx(s["e2e_ms"]["sum"], abs=3e-3)
    # every segment histogram saw exactly one sample per served query
    assert all(s["segments"][seg]["count"] == 40 for seg in SEGMENTS)


def test_segment_p50_sum_explains_e2e_p50_on_paced_storm():
    """Bucket-resolution check on a paced storm: the per-segment p50s
    sum to within the one-bucket error of the e2e p50 (the acceptance
    criterion's 10%)."""
    srv, ql, _ = make_server(batch=4)
    got = []
    try:
        for i in range(120):
            srv.submit(["a"], "union", lambda d: got.append(d),
                       query_id=i)
            time.sleep(0.001)
        drain(srv, got, 120)
    finally:
        srv.close()
    s = ql.summary()
    p50_sum = sum(s["segments"][seg].get("p50", 0.0)
                  for seg in SEGMENTS)
    e2e_p50 = s["e2e_ms"]["p50"]
    # paced: every query gets its own near-empty batch, so segment
    # p50s compose the typical path.  2^0.125 buckets are ~9% wide and
    # four of them stack, hence the generous-but-meaningful bound.
    assert e2e_p50 > 0
    assert abs(p50_sum - e2e_p50) / e2e_p50 < 0.45, (p50_sum, e2e_p50)


# ------------------------------------------------ shed reconciliation
def test_shed_records_reconcile_with_shed_counter():
    srv, ql, reg = make_server(depth=5, batch=4, hold=True)
    got = []
    try:
        for i in range(17):
            srv.submit(["a"], "union", lambda d: got.append(d),
                       query_id=i)
        assert srv.shed == 12
        srv.resume()
        drain(srv, got, 17)
    finally:
        srv.close()
    s = ql.summary()
    # every query has exactly one lifecycle record, shed or served
    assert s["shed_records"] == 12 == srv.shed
    assert s["served_records"] == 5 == srv.served
    assert s["shed_queue_ms"]["count"] == 12
    # the lifecycle shed count reconciles EXACTLY with the Prometheus
    # shed counter (the acceptance criterion)
    shed_counter = reg.counter("streambench_reach_shed_total")
    assert shed_counter.value == 12
    # shed replies carry the queue-only server block
    shed_replies = [d for d in got if d.get("shed")]
    assert len(shed_replies) == 12
    assert all("queue_ms" in d["server"] for d in shed_replies)


def test_close_time_sheds_count_and_reconcile():
    """Stragglers shed at close (no state) get lifecycle records AND
    bump streambench_reach_shed_total, so the reconciliation holds
    across the drain-at-close path too."""
    reg = MetricsRegistry()
    ql = QueryLifecycle(reg)
    srv = ReachQueryServer(["a"], depth=8, batch=4, registry=reg,
                           queryattr=ql)      # no state pushed
    got = []
    srv.submit(["a"], "union", lambda d: got.append(d), query_id="s")
    srv.close()
    assert got and got[0].get("shed") is True
    assert ql.summary()["shed_records"] == 1 == srv.shed
    assert reg.counter("streambench_reach_shed_total").value == 1


# ------------------------------------------------------ slow-query log
def test_slowlog_captures_decomposition_and_evicts_bounded():
    reg = MetricsRegistry()
    ql = QueryLifecycle(reg, slo_ms=0, slowlog_max=4)
    ql.slo_ms = 0  # capture nothing yet
    rec = ql.admit(trace="t-0", qid=0)
    rec.t_exit = rec.t_admit + 1_000_000
    ql.note_reply(rec, rec.t_exit + 1_000_000, rec.t_exit + 2_000_000)
    assert ql.slowlog() == []          # no objective, no log
    ql.slo_ms = 1                      # 1 ms objective: everything slow
    for i in range(7):
        r = ql.admit(trace=f"t-{i + 1}", qid=i + 1)
        r.t_admit -= 2_000_000                     # admitted 2 ms ago
        r.t_exit = r.t_admit + 2_000_000           # 2 ms queue
        ql.note_reply(r, r.t_exit, r.t_exit)
    log = ql.slowlog()
    assert len(log) == 4 and ql.slowlog_evicted == 3
    assert [e["id"] for e in log] == [4, 5, 6, 7]  # oldest evicted
    e = log[-1]
    assert e["trace"] == "t-7"
    assert set(e) >= {"e2e_ms", "queue_ms", "batch_ms", "dispatch_ms",
                      "reply_ms", "ts_ms"}
    assert e["e2e_ms"] == pytest.approx(
        e["queue_ms"] + e["batch_ms"] + e["dispatch_ms"]
        + e["reply_ms"], rel=1e-6)


# ---------------------------------------------- contention attribution
def test_interval_overlap_helper():
    merged = [(10, 20), (30, 40)]
    assert _interval_overlap_ns(0, 50, merged) == 20
    assert _interval_overlap_ns(15, 35, merged) == 10
    assert _interval_overlap_ns(20, 30, merged) == 0
    assert _interval_overlap_ns(12, 18, merged) == 6


def test_contention_ratio_from_synthetic_ingest_spans():
    """Known geometry: a query whose queue wait half-overlaps one
    ingest dispatch span must report ratio 0.5 (both sides stamp the
    same perf_counter_ns clock)."""
    reg = MetricsRegistry()
    spans = SpanTracer(capacity=64)
    ql = QueryLifecycle(reg, spans=spans)
    t0 = spans.t0_ns
    ms = 1_000_000
    # ingest dispatch [t0+10ms, t0+20ms); an unrelated span is ignored
    spans.add("device_scan", t0 + 10 * ms, 10 * ms, cat="stage")
    spans.add("encode", t0 + 10 * ms, 10 * ms, cat="stage")
    spans.add("query_dispatch", t0 + 10 * ms, 10 * ms, cat="query")
    rec = ql.admit()
    rec.t_admit = t0 + 15 * ms       # wait [15ms, 25ms): 5 ms overlap
    rec.t_exit = t0 + 25 * ms
    ql.note_queue_exit([rec])
    assert ql.contention_ratio() == pytest.approx(0.5, abs=1e-6)
    g = reg.gauge("streambench_reach_contention_ratio")
    assert g.value == pytest.approx(0.5, abs=1e-3)
    # two merged overlapping dispatch spans never double-count
    spans.add("device_step", t0 + 12 * ms, 6 * ms, cat="stage")
    rec2 = ql.admit()
    rec2.t_admit = t0 + 10 * ms
    rec2.t_exit = t0 + 20 * ms       # fully inside the merged busy set
    ql.note_queue_exit([rec2])
    s = ql.summary()["contention"]
    assert s["queue_wait_ms"] == pytest.approx(20.0, abs=1e-3)
    assert s["ingest_overlap_ms"] == pytest.approx(15.0, abs=1e-3)
    assert s["ratio"] == pytest.approx(0.75, abs=1e-3)


def test_contention_zero_without_spans():
    reg = MetricsRegistry()
    ql = QueryLifecycle(reg)       # no span tracer wired
    rec = ql.admit()
    rec.t_exit = rec.t_admit + 1_000_000
    ql.note_queue_exit([rec])
    assert ql.contention_ratio() == 0.0
    assert ql.summary()["contention"]["spans_wired"] is False


# ------------------------------------------------- query-lane spans
def test_query_lane_spans_validate_in_chrome_trace(tmp_path):
    from streambench_tpu.obs.spans import validate_chrome_trace

    spans = SpanTracer(capacity=256)
    spans.add("device_scan", spans.t0_ns, 2_000_000, cat="stage")
    srv, ql, _ = make_server(spans=spans)
    got = []
    try:
        for i in range(12):
            srv.submit(["a", "c"], "union", lambda d: got.append(d),
                       query_id=i)
        drain(srv, got, 12)
    finally:
        srv.close()
    path = str(tmp_path / "trace_q.json")
    spans.dump(path, run="queryattr-test")
    doc = json.load(open(path))
    assert validate_chrome_trace(doc) == []
    cats = {e.get("cat") for e in doc["traceEvents"] if e.get("ph") == "X"}
    # both lanes share one trace: ingest stage spans + query spans
    assert "query" in cats and "stage" in cats
    names = {e["name"] for e in doc["traceEvents"]
             if e.get("cat") == "query"}
    assert {"query_assembly", "query_dispatch", "query_reply"} <= names
    # the query lane rides the worker's real thread
    q_tids = {e["tid"] for e in doc["traceEvents"]
              if e.get("cat") == "query"}
    meta = {e["tid"]: e["args"]["name"] for e in doc["traceEvents"]
            if e.get("ph") == "M"}
    assert all(meta[t] == "reach-query" for t in q_tids)


# -------------------------------------------- off-flag bit-identity
def test_reply_payloads_bit_identical_when_off():
    """With jax.obs.query off the reply payloads are byte-for-byte the
    PR 10 shape; with it on they differ ONLY by the added server
    block."""
    campaigns = ["a", "b", "c", "d"]
    st = tiny_state(C=4)
    off = ReachQueryServer(campaigns, depth=32, batch=4)
    on_srv, _, _ = make_server(campaigns, batch=4)
    off.update_state(st.mins, st.registers, epoch=1)
    queries = [(["a", "b"], "union", 0), (["c"], "union", 1),
               (["a", "b", "d"], "overlap", 2), (["b"], "overlap", 3)]
    got_off, got_on = [], []
    try:
        for sel, op, qid in queries:
            off.submit(sel, op, lambda d: got_off.append(d),
                       query_id=qid)
            on_srv.submit(sel, op, lambda d: got_on.append(d),
                          query_id=qid)
        drain(off, got_off, 4)
        drain(on_srv, got_on, 4)
    finally:
        off.close()
        on_srv.close()
    by_id_off = {d["id"]: d for d in got_off}
    by_id_on = {d["id"]: d for d in got_on}
    for qid in range(4):
        a, b = by_id_off[qid], dict(by_id_on[qid])
        assert "server" not in a          # OFF: the PR 10 payload
        server = b.pop("server")          # ON: exactly one extra key
        assert set(server) == {"queue_ms", "batch_ms", "dispatch_ms",
                               "total_ms"}
        assert json.dumps(a, sort_keys=True) == json.dumps(
            b, sort_keys=True)


def test_trace_id_and_client_stamp_propagate_via_handle():
    srv, ql, _ = make_server()
    got = []
    try:
        srv.handle({"type": "reach", "campaigns": ["a"], "op": "union",
                    "id": 9, "trace": "trc-9", "sent_ms": 1234},
                   lambda d: got.append(d))
        drain(srv, got, 1)
    finally:
        srv.close()
    assert got[0]["server"]["trace"] == "trc-9"


# -------------------------------------------- flight-recorder records
def test_flightrec_carries_shed_and_high_water(tmp_path):
    from streambench_tpu.obs import FlightRecorder

    fr = FlightRecorder(str(tmp_path), capacity=64)
    srv, ql, _ = make_server(depth=4, batch=4, hold=True, flightrec=fr)
    got = []
    try:
        for i in range(20):
            srv.submit(["a"], "union", lambda d: got.append(d),
                       query_id=i)
        srv.resume()
        drain(srv, got, 20)
    finally:
        srv.close()
    kinds = [r["kind"] for r in fr.snapshot()]
    assert "reach_queue_high_water" in kinds
    assert "reach_shed" in kinds
    hw = [r for r in fr.snapshot()
          if r["kind"] == "reach_queue_high_water"]
    # doubling rate limit: at depth 4 the high-water records are O(log)
    assert 1 <= len(hw) <= 4
    assert all(r["depth"] == 4 for r in hw)
    shed_recs = [r for r in fr.snapshot() if r["kind"] == "reach_shed"]
    # rate-limited (1 Hz): the record carries the cumulative count at
    # record time, not necessarily the final one
    assert 1 <= shed_recs[-1]["shed_total"] <= srv.shed
    # a serving crash dump explains the backlog
    path = fr.dump("crash", terminal={"event": "crash",
                                      "error": "Boom()"})
    lines = [json.loads(l) for l in open(path)]
    assert any(r["kind"] == "reach_shed" for r in lines)
    assert lines[-1]["kind"] == "fault"


def test_slo_breach_event_carries_segment_attribution(tmp_path):
    from streambench_tpu.obs import FlightRecorder
    from streambench_tpu.obs.slo import SloTracker
    from streambench_tpu.reach.serve import LATENCY_HIST

    clock = {"t": 0.0}
    reg = MetricsRegistry()
    fr = FlightRecorder(str(tmp_path), capacity=64)
    ql = QueryLifecycle(reg, slo_ms=100)
    # seed one full record so the segment histograms have quantiles
    rec = ql.admit(qid="slow")
    rec.t_exit = rec.t_admit + 5_000_000
    ql.note_reply(rec, rec.t_exit + 1_000_000, rec.t_exit + 2_000_000)
    slo = SloTracker(reg, reach_p99_ms=100, budget=0.1, fast_s=5,
                     slow_s=20, flightrec=fr, queryattr=ql,
                     clock=lambda: clock["t"])
    hist = reg.histogram(LATENCY_HIST)
    for _ in range(20):
        clock["t"] += 1
        hist.observe(10)
        slo.collect({}, 1.0)
    for _ in range(4):
        clock["t"] += 1
        hist.observe(10_000)
        slo.collect({}, 1.0)
    assert slo.breaches == 1
    breach = [r for r in fr.snapshot() if r["kind"] == "slo_breach"]
    assert breach and "reach_segments" in breach[-1]
    assert "queue" in breach[-1]["reach_segments"]
    assert "reach_contention_ratio" in breach[-1]
    v = slo.verdict()
    assert "reach_segments" in v and "reach_contention_ratio" in v


# --------------------------------------- client-side latency split
def test_client_splits_network_vs_server_time():
    from streambench_tpu.dimensions.pubsub import (
        PubSubClient,
        PubSubServer,
    )

    srv, ql, _ = make_server()
    ps = PubSubServer(port=0).start()
    ps.register_query("reach", srv.handle)
    host, port = ps.address
    try:
        c = PubSubClient(host, port, timeout_s=30)
        c.request({"type": "reach", "campaigns": ["a", "b"],
                   "op": "union", "id": 1, "trace": "trc-1",
                   "sent_ms": 1})
        data = c.recv()["data"]
        split = c.latency_split(data)
        c.close()
    finally:
        srv.close()
        ps.close()
    server = data["server"]
    assert server["trace"] == "trc-1"
    assert server["total_ms"] >= (server["queue_ms"] + server["batch_ms"]
                                  + server["dispatch_ms"]) - 1e-6
    assert split["rtt_ms"] >= server["total_ms"] - 1.0
    assert split["network_ms"] == pytest.approx(
        max(split["rtt_ms"] - server["total_ms"], 0.0), abs=1e-6)
    # a second split for the same id: stamp consumed, None
    assert c.latency_split(data) is None


# ---------------------------------------------------- obs serve CLI
def test_obs_serve_cli_renders_and_diffs(tmp_path, capsys):
    from streambench_tpu.obs.__main__ import main as obs_main

    srv, ql, _ = make_server()
    got = []
    try:
        for i in range(8):
            srv.submit(["a"], "union", lambda d: got.append(d),
                       query_id=i)
        drain(srv, got, 8)
    finally:
        srv.close()
    path = tmp_path / "metrics.jsonl"
    path.write_text(json.dumps(
        {"kind": "snapshot", "reach_query": srv.summary()}) + "\n")
    assert obs_main(["serve", str(path)]) == 0
    out = capsys.readouterr().out
    assert "reach serving attribution" in out
    assert "contention ratio" in out and "queue" in out
    assert obs_main(["serve", str(path), str(path)]) == 0
    out = capsys.readouterr().out
    assert "reach serving diff" in out
    # --json emits the dict
    assert obs_main(["serve", str(path), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["reach_query"]["query_obs"]["served_records"] == 8


# ------------------------------------------------ FaultCounters.get
def test_fault_counters_get_default():
    from streambench_tpu.metrics import FaultCounters

    fc = FaultCounters()
    assert fc.get("never_bumped") == 0
    assert fc.get("never_bumped", 7) == 7
    fc.inc("sink_errors", 3)
    assert fc.get("sink_errors", 99) == 3


# --------------------------------- pub/sub server close-before-start
def test_pubsub_close_before_start_is_noop():
    from streambench_tpu.dimensions.pubsub import PubSubServer

    ps = PubSubServer(port=0)       # start() never called
    done = threading.Event()

    def closer():
        ps.close()                  # used to hang on serve_forever ack
        done.set()

    t = threading.Thread(target=closer, daemon=True)
    t.start()
    t.join(timeout=5)
    assert done.is_set(), "close() hung without start()"
    # a started server still closes cleanly (the normal path)
    ps2 = PubSubServer(port=0).start()
    ps2.close()
