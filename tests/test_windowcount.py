"""Window-count op tests: golden-model equivalence, ring semantics, methods."""

import random

import jax.numpy as jnp
import numpy as np

from streambench_tpu.datagen import gen
from streambench_tpu.encode import EventEncoder
from streambench_tpu.ops import windowcount as wc


def encode_events(lines, enc, batch_size):
    batches = []
    for i in range(0, len(lines), batch_size):
        batches.append(enc.encode(lines[i:i + batch_size], batch_size))
    return batches


def run_engine(lines, enc, W=64, B=256, method="scatter", lateness=60_000):
    state = wc.init_state(enc.num_campaigns, W)
    jt = jnp.asarray(enc.join_table)
    for b in encode_events(lines, enc, B):
        state = wc.step(state, jt, jnp.asarray(b.ad_idx),
                        jnp.asarray(b.event_type), jnp.asarray(b.event_time),
                        jnp.asarray(b.valid), method=method,
                        lateness_ms=lateness)
    return state


def golden_counts(lines, mapping, base_ms):
    """Pure-python oracle (dostats semantics) keyed by (campaign, abs wid)."""
    acc = {}
    import json
    for line in lines:
        ev = json.loads(line)
        if ev["event_type"] != "view":
            continue
        c = mapping.get(ev["ad_id"])
        if c is None:
            continue
        wid = int(ev["event_time"]) // 10_000
        acc[(c, wid)] = acc.get((c, wid), 0) + 1
    return acc


def state_counts(state, enc, base_ms):
    """Engine counts keyed the same way as the oracle."""
    counts = np.asarray(state.counts)
    wids = np.asarray(state.window_ids)
    base_wid = base_ms // 10_000
    out = {}
    for slot, wid in enumerate(wids):
        if wid < 0:
            continue
        for ci in np.nonzero(counts[:, slot])[0]:
            out[(enc.campaigns[ci], base_wid + int(wid))] = int(counts[ci, slot])
    return out


def make_dataset(n=2000, seed=0, skew=False, start=1_700_000_000_000):
    campaigns = [f"c{i}" for i in range(10)]
    mapping = {f"ad{i}_{j}": campaigns[i] for i in range(10) for j in range(10)}
    src = gen.EventSource(ads=list(mapping), user_ids=["u%d" % i for i in range(20)],
                          page_ids=["p"], with_skew=skew,
                          rng=random.Random(seed))
    lines = [src.event_at(start + 10 * i).encode() for i in range(n)]
    return lines, mapping, campaigns


def test_counts_match_golden_model():
    lines, mapping, campaigns = make_dataset(3000)
    enc = EventEncoder(mapping, campaigns)
    state = run_engine(lines, enc)
    assert int(state.dropped) == 0
    got = state_counts(state, enc, enc.base_time_ms)
    want = golden_counts(lines, mapping, enc.base_time_ms)
    assert got == want


def test_methods_agree():
    lines, mapping, campaigns = make_dataset(1500, seed=3)
    enc1 = EventEncoder(mapping, campaigns)
    s1 = run_engine(lines, enc1, method="scatter")
    for method in ("onehot", "matmul"):
        enc2 = EventEncoder(mapping, campaigns)
        s2 = run_engine(lines, enc2, method=method)
        assert np.array_equal(np.asarray(s1.counts), np.asarray(s2.counts))
        assert np.array_equal(np.asarray(s1.window_ids),
                              np.asarray(s2.window_ids))
        assert int(s1.dropped) == int(s2.dropped)


def test_skewed_data_matches_golden_within_lateness():
    lines, mapping, campaigns = make_dataset(5000, seed=7, skew=True)
    enc = EventEncoder(mapping, campaigns)
    state = run_engine(lines, enc, W=64)
    got = state_counts(state, enc, enc.base_time_ms)
    want = golden_counts(lines, mapping, enc.base_time_ms)
    # skew is ±50ms and rare 60s-late events; lateness=60s and W*10s=640s
    # ring keeps everything countable -> dropped only if beyond lateness
    dropped = int(state.dropped)
    assert sum(want.values()) - sum(got.values()) == dropped
    if dropped == 0:
        assert got == want


def test_late_event_beyond_lateness_dropped():
    mapping = {"adX": "campX"}
    enc = EventEncoder(mapping)
    t0 = 1_000_000_000
    mk = lambda t, et="view": (
        '{"user_id": "u", "page_id": "p", "ad_id": "adX", "ad_type": "mail",'
        ' "event_type": "%s", "event_time": "%d", "ip_address": "1.2.3.4"}'
        % (et, t)).encode()
    # advance watermark far, then send a 100s-late event (lateness=60s)
    lines = [mk(t0), mk(t0 + 200_000), mk(t0 + 100_000)]
    state = run_engine(lines, enc, W=64, B=1)
    assert int(state.dropped) == 1
    got = state_counts(state, enc, enc.base_time_ms)
    assert sum(got.values()) == 2


def test_negative_wid_never_aliases_empty_slot_sentinel():
    """Regression: a relative window id of exactly -1 must not be counted
    into a phantom slot via the empty-slot sentinel (-1 == -1)."""
    import jax.numpy as jnp
    state = wc.init_state(1, 8)
    jt = jnp.asarray(np.array([0, -1], np.int32))
    # hand-build a batch with event_time < 0 (wid = -1) then a real one
    mk = lambda t: (jnp.asarray(np.array([0], np.int32)),
                    jnp.asarray(np.array([0], np.int32)),
                    jnp.asarray(np.array([t], np.int32)),
                    jnp.asarray(np.array([True])))
    for t in (-5_000, 75_000):  # wid -1, then wid 7 (slot 7 both)
        a, e, tt, v = mk(t)
        state = wc.step(state, jt, a, e, tt, v)
    deltas, wids, _ = wc.flush_deltas(state)
    # only the real event is counted; the wid=-1 event is dropped
    assert int(np.asarray(deltas).sum()) == 1
    assert int(state.dropped) == 1


def test_non_view_events_not_counted():
    mapping = {"adX": "campX"}
    enc = EventEncoder(mapping)
    mk = lambda et: (
        '{"user_id": "u", "page_id": "p", "ad_id": "adX", "ad_type": "mail",'
        ' "event_type": "%s", "event_time": "5000", "ip_address": "1.2.3.4"}'
        % et).encode()
    state = run_engine([mk("view"), mk("click"), mk("purchase")], enc)
    assert int(np.asarray(state.counts).sum()) == 1
    assert int(state.dropped) == 0  # non-views aren't "dropped", just filtered


def test_flush_returns_deltas_and_frees_closed_slots():
    lines, mapping, campaigns = make_dataset(1000, seed=5)
    enc = EventEncoder(mapping, campaigns)
    state = run_engine(lines, enc, W=8)
    deltas, wids, cleared = wc.flush_deltas(state)
    assert np.array_equal(np.asarray(deltas), np.asarray(state.counts))
    assert np.asarray(cleared.counts).sum() == 0
    # dataset spans 10s -> 1-2 windows; watermark ~ last event; windows
    # whose end+lateness <= watermark are freed
    wm = int(state.watermark)
    for slot, wid in enumerate(np.asarray(wids)):
        if wid < 0:
            continue
        closed = (wid + 1) * 10_000 + 60_000 <= wm
        assert (np.asarray(cleared.window_ids)[slot] == -1) == closed


def test_flush_then_more_events_accumulate_as_deltas():
    mapping = {"adX": "campX"}
    enc = EventEncoder(mapping)
    mk = lambda t: (
        '{"user_id": "u", "page_id": "p", "ad_id": "adX", "ad_type": "mail",'
        ' "event_type": "view", "event_time": "%d", "ip_address": "1.2.3.4"}'
        % t).encode()
    import jax.numpy as jnp
    state = run_engine([mk(5000), mk(5001)], enc)
    d1, w1, state = wc.flush_deltas(state)
    assert int(np.asarray(d1).sum()) == 2
    # same window, more events after flush -> only the new delta remains
    b = enc.encode([mk(5002)], 4)
    state = wc.step(state, jnp.asarray(enc.join_table), jnp.asarray(b.ad_idx),
                    jnp.asarray(b.event_type), jnp.asarray(b.event_time),
                    jnp.asarray(b.valid))
    d2, w2, _ = wc.flush_deltas(state)
    assert int(np.asarray(d2).sum()) == 1


def test_scan_steps_equals_loop():
    lines, mapping, campaigns = make_dataset(1024, seed=11)
    enc = EventEncoder(mapping, campaigns)
    looped = run_engine(lines, enc, W=32, B=128)

    enc2 = EventEncoder(mapping, campaigns)
    batches = encode_events(lines, enc2, 128)
    stack = lambda f: jnp.asarray(np.stack([f(b) for b in batches]))
    state = wc.init_state(enc2.num_campaigns, 32)
    scanned = wc.scan_steps(
        state, jnp.asarray(enc2.join_table),
        stack(lambda b: b.ad_idx), stack(lambda b: b.event_type),
        stack(lambda b: b.event_time), stack(lambda b: b.valid))
    assert np.array_equal(np.asarray(looped.counts), np.asarray(scanned.counts))
    assert int(looped.watermark) == int(scanned.watermark)


def test_pallas_method_bit_identical():
    """The hand-fused Pallas kernel (interpret mode on the CPU mesh) must
    match scatter exactly, including masked rows and ragged tiles."""
    lines, mapping, campaigns = make_dataset(1777, seed=21)
    enc1 = EventEncoder(mapping, campaigns)
    s1 = run_engine(lines, enc1, method="scatter", B=300)  # non-tile-multiple B
    enc2 = EventEncoder(mapping, campaigns)
    s2 = run_engine(lines, enc2, method="pallas", B=300)
    assert np.array_equal(np.asarray(s1.counts), np.asarray(s2.counts))
    assert np.array_equal(np.asarray(s1.window_ids),
                          np.asarray(s2.window_ids))
    assert int(s1.dropped) == int(s2.dropped)


def test_packed_step_bit_identical():
    """``step_packed`` over the packed wire word must match ``step`` over
    the unpacked columns exactly — skewed/late data, every method, and
    invalid rows (blank lines encode as valid=False padding)."""
    lines, mapping, campaigns = make_dataset(2100, seed=31, skew=True)
    lines = lines[:500] + [b"", b"not json"] + lines[500:]
    for method in ("scatter", "matmul"):
        enc1 = EventEncoder(mapping, campaigns)
        plain = run_engine(lines, enc1, W=32, B=256, method=method)
        enc2 = EventEncoder(mapping, campaigns)
        jt = jnp.asarray(enc2.join_table)
        state = wc.init_state(enc2.num_campaigns, 32)
        for b in encode_events(lines, enc2, 256):
            packed = wc.pack_columns(b.ad_idx, b.event_type, b.valid)
            state = wc.step_packed(state, jt, jnp.asarray(packed),
                                   jnp.asarray(b.event_time), method=method)
        assert np.array_equal(np.asarray(plain.counts),
                              np.asarray(state.counts))
        assert np.array_equal(np.asarray(plain.window_ids),
                              np.asarray(state.window_ids))
        assert int(plain.watermark) == int(state.watermark)
        assert int(plain.dropped) == int(state.dropped)


def test_pack_columns_roundtrip_domain():
    """The packed word round-trips the full documented domain: ad up to
    2^28-1, event_type in {-1, 0, 1, 2}, both valid polarities."""
    ad = np.array([0, 1, 999, wc.PACK_AD_MAX - 1], np.int32)
    et = np.array([-1, 0, 1, 2], np.int32)
    va = np.array([True, False, True, False])
    packed = wc.pack_columns(ad, et, va)
    a2, e2, v2 = (np.asarray(x) for x in wc.unpack_columns(
        jnp.asarray(packed)))
    assert np.array_equal(a2, ad)
    assert np.array_equal(e2, et)
    assert np.array_equal(v2, va)
    # a packed-zero pad row decodes to (ad 0, type -1, valid False)
    a3, e3, v3 = (np.asarray(x) for x in wc.unpack_columns(
        jnp.zeros(4, jnp.int32)))
    assert np.array_equal(e3, np.full(4, -1)) and not v3.any()


def test_scan_steps_packed_equals_scan_steps():
    lines, mapping, campaigns = make_dataset(1024, seed=13)
    enc = EventEncoder(mapping, campaigns)
    batches = encode_events(lines, enc, 128)
    stack = lambda f: jnp.asarray(np.stack([f(b) for b in batches]))
    jt = jnp.asarray(enc.join_table)
    plain = wc.scan_steps(
        wc.init_state(enc.num_campaigns, 32), jt,
        stack(lambda b: b.ad_idx), stack(lambda b: b.event_type),
        stack(lambda b: b.event_time), stack(lambda b: b.valid))
    packed = wc.scan_steps_packed(
        wc.init_state(enc.num_campaigns, 32), jt,
        stack(lambda b: wc.pack_columns(b.ad_idx, b.event_type, b.valid)),
        stack(lambda b: b.event_time))
    assert np.array_equal(np.asarray(plain.counts),
                          np.asarray(packed.counts))
    assert np.array_equal(np.asarray(plain.window_ids),
                          np.asarray(packed.window_ids))
    assert int(plain.dropped) == int(packed.dropped)


def test_flush_deltas_rows_compact_matches_rows():
    """The on-device rows compaction must report exactly the touched
    cells — including when campaign row 0 has counts AND the rows
    vector is zero-PADDED (the padding re-gathers row 0; unmasked, its
    cells would be duplicated once per pad row)."""
    lines, mapping, campaigns = make_dataset(1200, seed=41)
    enc = EventEncoder(mapping, campaigns)
    state = run_engine(lines, enc, W=16, B=256)
    counts = np.asarray(state.counts)
    touched = np.nonzero(counts.any(axis=1))[0]
    assert counts[0].any(), "fixture must exercise a nonzero row 0"
    R, cap = 16, 64  # rows padded wide: pad entries re-gather row 0
    assert touched.size <= R
    padded = np.zeros(R, np.int32)
    padded[:touched.size] = touched
    idx, vals, nnz, sub, wids, new_state = wc.flush_deltas_rows_compact(
        state, jnp.asarray(padded), jnp.int32(touched.size), cap=cap)
    n = int(nnz)
    assert n == int((counts > 0).sum())
    idx = np.asarray(idx)[:n]
    vals = np.asarray(vals)[:n]
    ci = touched[idx // 16]
    si = idx % 16
    got = {(int(c), int(s)): int(v) for c, s, v in zip(ci, si, vals)}
    want = {(int(c), int(s)): int(counts[c, s])
            for c, s in zip(*np.nonzero(counts))}
    assert got == want
    assert not np.asarray(new_state.counts).any()
    # the gathered fallback block carries the real rows in order
    assert np.array_equal(np.asarray(sub)[:touched.size], counts[touched])


def test_pack_columns_rejects_out_of_domain():
    """pack_columns is public: an ad_idx outside [0, PACK_AD_MAX) or an
    event_type outside {-1..2} must error instead of silently bleeding
    into the neighboring bit fields (ADVICE.md)."""
    import pytest

    ok_ad = np.array([0, 5, wc.PACK_AD_MAX - 1], np.int32)
    ok_et = np.array([-1, 0, 2], np.int32)
    valid = np.array([True, True, False])
    packed = wc.pack_columns(ok_ad, ok_et, valid)
    import jax.numpy as jnp
    ad, et, v = (np.asarray(x)
                 for x in wc.unpack_columns(jnp.asarray(packed)))
    assert np.array_equal(ad, ok_ad) and np.array_equal(et, ok_et)
    assert np.array_equal(v, valid)

    for bad_ad in (np.array([-1, 0, 0], np.int32),
                   np.array([0, wc.PACK_AD_MAX, 0], np.int32)):
        with pytest.raises(ValueError, match="ad_idx"):
            wc.pack_columns(bad_ad, ok_et, valid)
    for bad_et in (np.array([-2, 0, 0], np.int32),
                   np.array([0, 3, 0], np.int32)):
        with pytest.raises(ValueError, match="event_type"):
            wc.pack_columns(ok_ad, bad_et, valid)
    # empty batches skip the reductions entirely
    assert wc.pack_columns(np.empty(0, np.int32), np.empty(0, np.int32),
                           np.empty(0, bool)).size == 0


def test_unique_ts_matches_np_unique():
    """The sort-free window-timestamp dedup (engine.pipeline._unique_ts,
    ISSUE 12: per-flush np.unique over millions of sliding rows was
    ~0.5 s of a 6 s catchup) equals np.unique on every input class:
    tiny (sort path), dense-range (flag path), and sparse-range
    (fallback sort path)."""
    import numpy as np

    from streambench_tpu.engine.pipeline import _unique_ts

    rng = np.random.default_rng(7)
    cases = [
        np.array([5, 3, 5, 3, 9], np.int64),                  # tiny
        70_000 + rng.integers(0, 20_000, 200_000) * np.int64(1000),
        rng.integers(0, 2**60, 10_000).astype(np.int64),      # sparse
        np.full(50_000, 123_000, np.int64),                   # one value
    ]
    for ts in cases:
        got = _unique_ts(ts)
        np.testing.assert_array_equal(np.asarray(got), np.unique(ts))
