"""Native encoder vs pure-Python encoder: identical columns.

Builds libsbnative.so on first run (skips if no toolchain)."""

import random

import numpy as np
import pytest

from streambench_tpu import native
from streambench_tpu.datagen.gen import EventSource
from streambench_tpu.encode.encoder import EventEncoder
from streambench_tpu.encode.native_encoder import NativeEventEncoder
from streambench_tpu.utils.ids import make_ids

pytestmark = pytest.mark.skipif(native.load() is None,
                                reason="native toolchain unavailable")


def make_pair(n_campaigns=10, ads_per=3, seed=3):
    rng = random.Random(seed)
    campaigns = make_ids(n_campaigns, rng)
    ads = make_ids(n_campaigns * ads_per, rng)
    mapping = {a: campaigns[i // ads_per] for i, a in enumerate(ads)}
    return (EventEncoder(mapping), NativeEventEncoder(mapping),
            mapping, ads)


def gen_lines(ads, n, seed=4, skew=True):
    rng = random.Random(seed)
    src = EventSource(ads=ads, user_ids=make_ids(20, rng),
                      page_ids=make_ids(20, rng), with_skew=skew, rng=rng)
    t0 = 1_700_000_000_000
    return [src.event_at(t0 + 10 * i).encode() for i in range(n)]


def assert_batches_equal(a, b, exact_intern=True):
    assert a.n == b.n
    assert a.base_time_ms == b.base_time_ms
    for col in ("ad_idx", "event_type", "event_time", "ad_type", "valid"):
        assert np.array_equal(getattr(a, col), getattr(b, col)), col
    for col in ("user_idx", "page_idx"):
        x, y = getattr(a, col)[:a.n], getattr(b, col)[:a.n]
        if exact_intern:
            assert np.array_equal(x, y), col
        else:
            # intern order may differ when fallback lines interleave;
            # indices must still be a consistent relabeling
            assert len({(int(i), int(j)) for i, j in zip(x, y)}) \
                == len(set(x.tolist())) == len(set(y.tolist())), col


def test_native_matches_python_on_generator_output():
    py, nat, _, ads = make_pair()
    lines = gen_lines(ads, 3000)
    for off in range(0, 3000, 512):
        chunk = lines[off:off + 512]
        assert_batches_equal(py.encode(chunk, 512), nat.encode(chunk, 512))
    assert nat.fallback_lines == 0 and nat.bad_lines == 0


def test_native_fallback_and_bad_lines():
    py, nat, mapping, ads = make_pair()
    ad = ads[0]
    reordered = (
        '{"event_time": "1700000000123", "ad_id": "%s", "user_id": "u1", '
        '"page_id": "p1", "ad_type": "modal", "event_type": "view"}'
        % ad).encode()
    garbage = b"not json at all"
    ok = gen_lines(ads, 5)
    chunk = ok[:2] + [reordered, garbage] + ok[2:]
    a = py.encode(chunk, 16)
    b = nat.encode(chunk, 16)
    assert_batches_equal(a, b, exact_intern=False)
    assert nat.fallback_lines == 2 and nat.bad_lines == 1
    assert py.bad_lines == 1


def test_native_unknown_ad_maps_to_minus_one_campaign():
    py, nat, _, ads = make_pair()
    line = (
        '{"user_id": "u", "page_id": "p", "ad_id": "nope", '
        '"ad_type": "mail", "event_type": "view", '
        '"event_time": "1700000000000", "ip_address": "1.2.3.4"}').encode()
    b = nat.encode([line], 4)
    assert b.n == 1
    assert nat.join_table[b.ad_idx[0]] == -1


def test_native_intern_consistency_across_fallback():
    _, nat, _, ads = make_pair()
    fast = gen_lines(ads, 1)[0]
    # same user via fallback path must get the same index
    import json
    ev = json.loads(fast)
    slow = json.dumps({k: ev[k] for k in
                       ["event_time", "user_id", "page_id", "ad_id",
                        "ad_type", "event_type"]}).encode()
    b1 = nat.encode([fast], 2)
    b2 = nat.encode([slow], 2)
    assert b1.user_idx[0] == b2.user_idx[0]
    assert b1.page_idx[0] == b2.page_idx[0]


def test_negative_base_time_is_stable_across_batches():
    """Regression: small event times (t < divisor + lateness) produce a
    legitimately NEGATIVE base_time_ms; the native encoder's old "< 0 ==
    unset" sentinel re-rebased every batch, shifting window ids between
    chunks (found by hypothesis differential testing)."""
    import pytest

    from streambench_tpu import native
    if native.load() is None:
        pytest.skip("native library unavailable")
    from streambench_tpu.encode.encoder import EventEncoder
    from streambench_tpu.encode.native_encoder import NativeEventEncoder

    mapping = {"adX": "campX"}
    mk = lambda t: (
        '{"user_id": "u", "page_id": "p", "ad_id": "adX", "ad_type":'
        ' "mail", "event_type": "view", "event_time": "%d"}' % t).encode()
    py = EventEncoder(mapping, divisor_ms=10_000, lateness_ms=60_000)
    nat = NativeEventEncoder(mapping, divisor_ms=10_000, lateness_ms=60_000)
    for chunk in ([mk(49_954)], [mk(70_779)], [mk(39_867)]):
        bp = py.encode(chunk, 4)
        bn = nat.encode(chunk, 4)
        assert bp.base_time_ms == bn.base_time_ms == -20_000
        assert bp.event_time[0] == bn.event_time[0]


def test_hash_ids_mode_differential_and_stateless():
    """hash-id mode: native and python encoders emit IDENTICAL crc32
    columns (the cross-partition/restart consistency contract), two
    independent encoders agree, and the columns match zlib.crc32."""
    import zlib

    import pytest

    from streambench_tpu import native
    if native.load() is None:
        pytest.skip("native library unavailable")
    import numpy as np

    from streambench_tpu.encode.encoder import EventEncoder
    from streambench_tpu.encode.native_encoder import NativeEventEncoder

    mapping = {"adX": "campX"}
    mk = lambda u, p: (
        '{"user_id": "%s", "page_id": "%s", "ad_id": "adX", "ad_type":'
        ' "mail", "event_type": "view", "event_time": "100000",'
        ' "ip_address": "1.2.3.4"}' % (u, p)).encode()
    lines = [mk(f"user-{i % 5}", f"page-{i % 3}") for i in range(20)]

    encs = []
    for cls in (EventEncoder, NativeEventEncoder, NativeEventEncoder):
        e = cls(mapping)
        e.set_hash_ids(True)
        encs.append(e.encode(lines, 32))
    for b in encs[1:]:
        assert np.array_equal(encs[0].user_idx, b.user_idx)
        assert np.array_equal(encs[0].page_idx, b.page_idx)

    def crc_i32(s: bytes) -> int:
        c = zlib.crc32(s)
        return c - (1 << 32) if c & 0x80000000 else c

    assert encs[0].user_idx[0] == crc_i32(b"user-0")
    assert encs[0].page_idx[1] == crc_i32(b"page-1")
