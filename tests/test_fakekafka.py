"""``io.fakekafka`` pinned: the protocol units, the delivery model, the
seeded fault determinism, and the harness lifecycle (ISSUE 20).

Three layers of pin:

- **protocol units** — the confluent-kafka lookalikes behave like the
  subset ``io/kafka.py`` touches (delivery callbacks, admin futures,
  assign/seek/EOF/watermarks/pause);
- **delivery semantics through the REAL adapter** — the data-loss fix
  (records in hand are returned, never discarded after the offset
  advanced), redelivery-on-reconnect counted and filtered, dr_fail
  re-produce at flush, and the ``check_kafka_edge`` accounting identity
  over a faulted run;
- **determinism** — same plan + same op schedule => identical counters
  (minus the wall-clock backoff gauge), and a rate-0 plan is byte-
  identical to a pre-kafka plan with zero broker draws.

Plus the process story: the standalone CLI broker and the
START_KAFKA/STOP_KAFKA verbs in ``stream_bench.py``.
"""

import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from streambench_tpu.chaos import FaultInjector, FaultPlan, check_kafka_edge
from streambench_tpu.io import fakekafka, kafka
from streambench_tpu.metrics import FaultCounters

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_seam():
    yield
    kafka.use_clients(None)
    fakekafka.reset_default_cluster()


def _broker(cl, counters=None):
    return kafka.KafkaBroker(fakekafka.INPROC,
                             clients=fakekafka.clients(cl),
                             counters=counters)


# ---------------------------------------------------------------------------
# protocol units: the confluent surface itself
# ---------------------------------------------------------------------------

def test_producer_delivery_callbacks():
    cl = fakekafka.FakeCluster()
    cl.create_topic("t", 1)
    p = fakekafka.FakeProducer({"bootstrap.servers": fakekafka.INPROC},
                               cluster=cl)
    seen = []
    p.produce("t", value=b"a", partition=0,
              on_delivery=lambda err, msg: seen.append((err, msg)))
    # callbacks are served by the poll/flush pump, not at produce time
    assert seen == []
    p.flush()
    assert len(seen) == 1
    err, msg = seen[0]
    assert err is None
    assert msg.value() == b"a"
    assert msg.offset() == 0
    assert cl._topics["t"][0] == [b"a"]


def test_admin_create_list_and_already_exists():
    cl = fakekafka.FakeCluster()
    admin = fakekafka.FakeAdminClient({"bootstrap.servers": fakekafka.INPROC},
                                      cluster=cl)
    futs = admin.create_topics([fakekafka.FakeNewTopic("t", 3)])
    assert futs["t"].result() is None
    meta = admin.list_topics()
    assert sorted(meta.topics["t"].partitions) == [0, 1, 2]
    # second create: the future carries TOPIC_ALREADY_EXISTS, like the
    # real admin client
    futs = admin.create_topics([fakekafka.FakeNewTopic("t", 3)])
    with pytest.raises(fakekafka.FakeKafkaException) as ei:
        futs["t"].result()
    assert ei.value.args[0].code() == fakekafka.ERR_TOPIC_ALREADY_EXISTS


def test_consumer_assign_seek_eof_watermarks_pause():
    cl = fakekafka.FakeCluster()
    cl.create_topic("t", 1)
    for v in (b"a", b"b", b"c"):
        cl.append("t", 0, v)
    c = fakekafka.FakeConsumer({"bootstrap.servers": fakekafka.INPROC,
                                "group.id": "g"}, cluster=cl)
    tp = fakekafka.FakeTopicPartition("t", 0, 0)
    c.assign([tp])
    msgs = c.consume(num_messages=10, timeout=0)
    assert [m.value() for m in msgs] == [b"a", b"b", b"c"]
    # at the tail: a clean fetch yields the EOF marker message
    msgs = c.consume(num_messages=10, timeout=0)
    assert len(msgs) == 1
    assert msgs[0].error().code() == fakekafka.ERR__PARTITION_EOF
    assert c.get_watermark_offsets(tp) == (0, 3)
    # seek rewinds the client-side fetch position
    c.seek(fakekafka.FakeTopicPartition("t", 0, 1))
    assert [m.value() for m in c.consume(10, 0)] == [b"b", b"c"]
    # pause: no records flow; resume: they do again
    c.pause([tp])
    assert c.consume(10, 0) == []
    c.resume([tp])
    c.seek(fakekafka.FakeTopicPartition("t", 0, 0))
    assert [m.value() for m in c.consume(10, 0)] == [b"a", b"b", b"c"]
    c.close()


# ---------------------------------------------------------------------------
# the data-loss pin (satellite a): records in hand are RETURNED, never
# discarded after the offset advanced
# ---------------------------------------------------------------------------

class _ScriptedConsumer:
    """A consumer that returns pre-scripted message batches — the exact
    shape (records, then a mid-batch transient error) the pre-hardening
    adapter mishandled."""

    def __init__(self, batches):
        self._batches = list(batches)

    def assign(self, tps):
        pass

    def consume(self, num_messages=1, timeout=None):
        return self._batches.pop(0) if self._batches else []

    def close(self):
        pass


def test_reader_returns_records_accumulated_before_mid_batch_error():
    err = fakekafka.FakeKafkaError(fakekafka.ERR__TRANSPORT,
                                   "transient mid-batch")
    eof = fakekafka.FakeKafkaError(fakekafka.ERR__PARTITION_EOF, "eof")
    batches = [
        # batch 1: two records delivered, THEN a transient error — the
        # old adapter raised here and the two records (offset already
        # advanced past them) were lost forever on retry
        [fakekafka.FakeMessage("t", 0, 0, b"a", None),
         fakekafka.FakeMessage("t", 0, 1, b"b", None),
         fakekafka.FakeMessage("t", 0, None, None, err)],
        [fakekafka.FakeMessage("t", 0, 2, b"c", None)],
        [fakekafka.FakeMessage("t", 0, 3, None, eof)],
    ]

    class _Clients(fakekafka.FakeClients):
        def Consumer(self, conf):
            return _ScriptedConsumer(batches)

    counters = FaultCounters()
    r = kafka.KafkaReader(fakekafka.INPROC, "t", clients=_Clients(),
                          counters=counters, retry_base_ms=0.01,
                          retry_cap_ms=0.02)
    # the fix: the accumulated records come back THIS call
    assert r.poll() == [b"a", b"b"]
    assert r.offset == 2
    # and the stream continues with nothing lost and nothing doubled
    assert r.poll() == [b"c"]
    assert r.poll() == []
    snap = counters.snapshot()
    assert snap.get("kafka_delivered") == 3
    assert snap.get("kafka_consumed") == 3
    assert "kafka_redeliveries" not in snap


# ---------------------------------------------------------------------------
# delivery semantics through the real adapter, faults armed
# ---------------------------------------------------------------------------

def _produce_clean(cl, counters, values):
    """Produce ``values`` before chaos attaches: the log is the ground
    truth the faulted consume phase is judged against."""
    b = _broker(cl, counters)
    b.create_topic("t", partitions=1)
    w = b.writer("t")
    w.append_many(values)
    w.flush()
    w.close()


def test_conn_drop_redelivery_counted_filtered_never_double_delivered():
    values = [b"r%03d" % i for i in range(80)]
    counters = FaultCounters()
    cl = fakekafka.FakeCluster()
    _produce_clean(cl, counters, values)
    # now arm conn drops: every drop rewinds the consumer to the start
    # of its last returned batch, so un-checkpointed records arrive twice
    cl.attach_chaos(FaultInjector(FaultPlan.generate(
        7, kafka_conn_drop_rate=0.25, kafka_ops=4000)))
    r = kafka.KafkaReader(fakekafka.INPROC, "t",
                          clients=fakekafka.clients(cl), counters=counters,
                          retry_base_ms=0.01, retry_cap_ms=0.02)
    got = []
    for _ in range(600):   # FIXED op schedule: plain poll(), no wall clock
        try:
            got.extend(r.poll(max_records=8))
        except fakekafka.FakeKafkaException:
            pass           # retries exhausted on an empty batch: retry later
    # exactly-once at the engine edge, per-partition order preserved
    assert got == cl._topics["t"][0] == values
    snap = counters.snapshot()
    assert snap.get("kafka_redeliveries", 0) > 0
    assert snap["kafka_consumed"] == \
        snap["kafka_delivered"] + snap["kafka_redeliveries"]
    v = check_kafka_edge(counters, require_redeliveries=True)
    assert v.ok, v.summary()
    r.close()


def test_writer_dr_fail_redo_lands_every_record():
    values = [b"w%03d" % i for i in range(40)]
    counters = FaultCounters()
    cl = fakekafka.FakeCluster(chaos=FaultInjector(FaultPlan.generate(
        11, kafka_dr_fail_rate=0.2, kafka_ops=4000)))
    b = _broker(cl, counters)
    b.create_topic("t", partitions=1)
    w = b.writer("t")
    w.append_many(values)
    w.flush()
    w.close()
    snap = counters.snapshot()
    assert snap.get("kafka_dr_failures", 0) > 0
    # every record landed exactly once; dr_fail'd records were
    # re-produced at flush, so they land LATER in the log (honest retry
    # reordering — the log is the ground truth, not the submit order)
    log = cl._topics["t"][0]
    assert sorted(log) == sorted(values)
    assert snap["kafka_produced"] == len(values)


def test_transient_produce_errors_are_retried_and_counted():
    values = [b"p%03d" % i for i in range(40)]
    counters = FaultCounters()
    cl = fakekafka.FakeCluster(chaos=FaultInjector(FaultPlan.generate(
        3, kafka_produce_rate=0.2, kafka_ops=4000)))
    b = _broker(cl, counters)
    b.create_topic("t", partitions=1)
    w = kafka.KafkaWriter(fakekafka.INPROC, "t",
                          clients=fakekafka.clients(cl), counters=counters,
                          retry_base_ms=0.01, retry_cap_ms=0.02)
    w.append_many(values)
    w.flush()
    w.close()
    snap = counters.snapshot()
    assert snap.get("kafka_produce_retries", 0) > 0
    assert cl._topics["t"][0] == values   # retries preserve submit order
    assert snap["kafka_produced"] == len(values)


def test_broker_down_window_absorbed_by_backoff():
    counters = FaultCounters()
    cl = fakekafka.FakeCluster(chaos=FaultInjector(FaultPlan.generate(
        0, kafka_ops=4000, kafka_down=((2, 6),))))
    b = _broker(cl, counters)
    b.create_topic("t", partitions=1)
    w = kafka.KafkaWriter(fakekafka.INPROC, "t",
                          clients=fakekafka.clients(cl), counters=counters,
                          retry_base_ms=0.01, retry_cap_ms=0.02)
    w.append_many([b"a", b"b", b"c", b"d", b"e"])
    w.flush()
    w.close()
    snap = counters.snapshot()
    assert cl._topics["t"][0] == [b"a", b"b", b"c", b"d", b"e"]
    assert snap.get("kafka_produce_retries", 0) > 0
    assert snap.get("kafka_broker_down_ms", 0) > 0
    assert cl.counters.snapshot().get("fake_kafka_down", 0) > 0


# ---------------------------------------------------------------------------
# seeded fault determinism + rate-0 byte-identity
# ---------------------------------------------------------------------------

def _faulted_run(seed):
    """One full produce+consume pass on a FIXED op schedule; returns
    (delivered, adapter counters, chaos counters, cluster counters)."""
    values = [b"d%03d" % i for i in range(60)]
    counters = FaultCounters()
    inj = FaultInjector(FaultPlan.generate(
        seed, kafka_produce_rate=0.1, kafka_consume_rate=0.1,
        kafka_conn_drop_rate=0.1, kafka_dr_fail_rate=0.05,
        kafka_ops=4000))
    cl = fakekafka.FakeCluster(chaos=inj)
    b = _broker(cl, counters)
    b.create_topic("t", partitions=1)
    w = kafka.KafkaWriter(fakekafka.INPROC, "t",
                          clients=fakekafka.clients(cl), counters=counters,
                          retry_base_ms=0.01, retry_cap_ms=0.02)
    w.append_many(values)
    w.flush()
    w.close()
    r = kafka.KafkaReader(fakekafka.INPROC, "t",
                          clients=fakekafka.clients(cl), counters=counters,
                          retry_base_ms=0.01, retry_cap_ms=0.02)
    got = []
    for _ in range(600):
        try:
            got.extend(r.poll(max_records=8))
        except fakekafka.FakeKafkaException:
            pass
    r.close()
    return (got, counters.snapshot(), inj.counters.snapshot(),
            cl.counters.snapshot())


def _minus_wallclock(snap):
    # kafka_broker_down_ms is real backoff sleep with unseeded jitter —
    # the ONE counter excluded from determinism comparisons
    return {k: v for k, v in snap.items() if k != "kafka_broker_down_ms"}


def test_seeded_faults_are_deterministic():
    a = _faulted_run(21)
    b = _faulted_run(21)
    assert a[0] == b[0]                                   # same stream
    assert _minus_wallclock(a[1]) == _minus_wallclock(b[1])
    assert a[2] == b[2]                                   # chaos draws
    assert a[3] == b[3]                                   # cluster ledger
    assert a[2].get("chaos_kafka_faults", 0) > 0
    # the full edge still balances under mixed faults
    v = check_kafka_edge(a[1], sent=60)
    assert v.ok, v.summary()


def test_rate0_plan_is_byte_identical_and_passthrough():
    # a plan generated with the kafka knobs at their defaults is the
    # exact pre-kafka plan: zero broker draws, nothing perturbed
    base = FaultPlan.generate(5)
    explicit = FaultPlan.generate(5, kafka_produce_rate=0.0,
                                  kafka_consume_rate=0.0,
                                  kafka_dr_fail_rate=0.0,
                                  kafka_conn_drop_rate=0.0,
                                  kafka_ops=0, kafka_down=())
    assert base == explicit
    assert base.kafka_faults == {} and base.kafka_down == ()
    # ... and a non-zero seed with rates 0 but ops > 0 draws nothing
    armed = FaultPlan.generate(5, kafka_ops=500)
    assert armed.kafka_faults == {}
    # passthrough: a zero-rate injector leaves the cluster untouched
    inj = FaultInjector(FaultPlan.generate(5, kafka_ops=500))
    counters = FaultCounters()
    cl = fakekafka.FakeCluster(chaos=inj)
    b = _broker(cl, counters)
    b.create_topic("t", partitions=1)
    w = b.writer("t")
    w.append_many([b"a", b"b", b"c"])
    w.flush()
    r = b.reader("t")
    assert r.poll_blocking(timeout_s=5.0) == [b"a", b"b", b"c"]
    assert inj.counters.snapshot() == {}
    assert cl.counters.snapshot() == {}
    snap = counters.snapshot()
    assert snap.get("kafka_redeliveries", 0) == 0
    assert snap.get("kafka_produce_retries", 0) == 0


def test_check_kafka_edge_accounting():
    ok = check_kafka_edge({"kafka_produced": 10, "kafka_consumed": 12,
                           "kafka_delivered": 10, "kafka_redeliveries": 2})
    assert ok.ok and ok.violations == []
    # a silent drop at the consumer breaks consumed == delivered + redl
    bad = check_kafka_edge({"kafka_produced": 10, "kafka_consumed": 12,
                            "kafka_delivered": 9, "kafka_redeliveries": 2})
    assert not bad.ok and bad.violations
    # delivered != sent: an acked produce never reached the engine
    bad2 = check_kafka_edge({"kafka_produced": 10, "kafka_consumed": 9,
                             "kafka_delivered": 9})
    assert not bad2.ok
    # a faulted sweep must PROVE its conn drops exercised redelivery
    flat = check_kafka_edge({"kafka_produced": 5, "kafka_consumed": 5,
                             "kafka_delivered": 5},
                            require_redeliveries=True)
    assert not flat.ok and "redeliver" in " ".join(flat.violations)


# ---------------------------------------------------------------------------
# the standalone broker process + harness lifecycle
# ---------------------------------------------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _read_ready_line(proc) -> "tuple[str, int]":
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            time.sleep(0.05)
            continue
        if line.startswith("ready "):
            host, port = line.split()[1].rsplit(":", 1)
            return host, int(port)
    raise AssertionError("broker never printed its ready line")


def test_cli_broker_process_roundtrip_and_stop():
    proc = subprocess.Popen(
        [sys.executable, "-m", "streambench_tpu.io.fakekafka",
         "--host", "127.0.0.1", "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO)
    try:
        host, port = _read_ready_line(proc)
        assert fakekafka.ping(host, port)
        # the REAL adapter over a real socket to a real broker process
        b = kafka.KafkaBroker(f"{host}:{port}",
                              clients=fakekafka.clients())
        b.create_topic("t", partitions=1)
        w = b.writer("t")
        w.append_many([b"x", b"y"])
        w.flush()
        r = b.reader("t")
        assert r.poll_blocking(timeout_s=5.0) == [b"x", b"y"]
        w.close()
        r.close()
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=20)
        assert "stopping:" in out and "records=2" in out
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()


def _bench_env(workdir, port):
    env = dict(os.environ)
    env.update({"WORKDIR": str(workdir), "KAFKA_FAKE": "1",
                "KAFKA_BROKERS": f"127.0.0.1:{port}",
                "JAX_PLATFORMS": "cpu"})
    return env


def _bench(verb, env):
    return subprocess.run([sys.executable, "stream_bench.py", verb],
                          cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=60)


def test_start_stop_kafka_harness_verbs(tmp_path):
    port = _free_port()
    env = _bench_env(tmp_path, port)
    p = _bench("START_KAFKA", env)
    assert p.returncode == 0, p.stdout + p.stderr
    try:
        assert (tmp_path / "pids" / "kafka.pid").exists()
        assert fakekafka.ping("127.0.0.1", port)
        # drive the spawned broker through the real adapter
        b = kafka.KafkaBroker(f"127.0.0.1:{port}",
                              clients=fakekafka.clients())
        b.create_topic("h", partitions=1)
        w = b.writer("h")
        w.append(b"hello")
        w.flush()
        r = b.reader("h")
        assert r.poll_blocking(timeout_s=5.0) == [b"hello"]
        w.close()
        r.close()
    finally:
        p = _bench("STOP_KAFKA", env)
    assert p.returncode == 0, p.stdout + p.stderr
    assert not (tmp_path / "pids" / "kafka.pid").exists()
    assert not fakekafka.ping("127.0.0.1", port, timeout_s=0.5)


def test_start_kafka_adopts_external_broker(tmp_path):
    srv = fakekafka.FakeKafkaServer(port=0)
    srv.start()
    try:
        env = _bench_env(tmp_path, srv.port)
        p = _bench("START_KAFKA", env)
        assert p.returncode == 0, p.stdout + p.stderr
        # adopted, not spawned: external marker instead of a pidfile
        assert (tmp_path / "pids" / "kafka.external").exists()
        assert not (tmp_path / "pids" / "kafka.pid").exists()
        p = _bench("STOP_KAFKA", env)
        assert p.returncode == 0, p.stdout + p.stderr
        # an adopted broker is left running — we don't own it
        assert fakekafka.ping("127.0.0.1", srv.port)
    finally:
        srv.stop()
