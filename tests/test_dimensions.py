"""Dimension-computation family (Apex peer, SURVEY.md §2 #19-#23):
schema parsing, the multi-aggregate kernel vs a numpy oracle, unifier
merge equivalence, durable-store replay/compaction, pub/sub queries, and
the whole app end-to-end with sentinel-campaign backfill."""

import json
import os
import random

import numpy as np
import pytest

from streambench_tpu.datagen import gen
from streambench_tpu.dimensions import (
    SENTINEL_CAMPAIGN,
    DimensionApp,
    DimensionsComputation,
    PubSubClient,
    PubSubServer,
)
from streambench_tpu.dimensions.schema import parse_schema, parse_time_bucket
from streambench_tpu.dimensions.store import DurableDimensionStore


# ----------------------------------------------------------------- schema
def test_parse_reference_schema_file():
    # the reference's own eventSchema.json (which has a trailing comma)
    src = """{"keys": [ {"name":"campaignId","type":"string"}, ],
 "timeBuckets":["10s"],
 "values": [
    {"name":"clicks","type":"long","aggregators":["SUM"]},
    {"name":"latency","type":"long","aggregators":["MAX"]} ],
 "dimensions": [ {"combination":["campaignId"]} ]}"""
    s = parse_schema(src)
    assert s.keys == ("campaignId",)
    assert s.time_bucket_ms == 10_000
    assert s.aggregate_slots() == [("clicks", "SUM"), ("latency", "MAX")]
    assert s.combinations == (("campaignId",),)


def test_time_bucket_units_and_validation():
    assert parse_time_bucket("200ms") == 200
    assert parse_time_bucket("1m") == 60_000
    with pytest.raises(ValueError):
        parse_time_bucket("10parsecs")
    with pytest.raises(ValueError, match="unsupported aggregator"):
        parse_schema({"keys": [{"name": "k"}],
                      "values": [{"name": "v", "aggregators": ["MEDIAN"]}]})
    with pytest.raises(ValueError, match="undeclared"):
        parse_schema({"keys": [{"name": "k"}],
                      "values": [{"name": "v", "aggregators": ["SUM"]}],
                      "dimensions": [{"combination": ["nope"]}]})


# ----------------------------------------------------------------- kernel
SCHEMA = parse_schema({
    "keys": [{"name": "campaignId"}],
    "timeBuckets": ["10s"],
    "values": [{"name": "clicks", "aggregators": ["SUM", "COUNT"]},
               {"name": "latency", "aggregators": ["MAX", "MIN"]}],
    "dimensions": [{"combination": ["campaignId"]}],
})


def oracle_fold(rows, divisor=10_000):
    """rows: (key, t, clicks, latency) -> {(key, wid): (sum, count, max, min)}"""
    out = {}
    for k, t, c, l in rows:
        wid = t // divisor
        s, n, mx, mn = out.get((k, wid), (0, 0, -(2**31) + 1, 2**31 - 1))
        out[(k, wid)] = (s + c, n + 1, max(mx, l), min(mn, l))
    return out


def test_kernel_matches_numpy_oracle():
    rng = np.random.default_rng(5)
    K, B, NB = 7, 256, 6
    dc = DimensionsComputation(SCHEMA, num_keys=K, window_slots=8,
                               lateness_ms=20_000)
    state = dc.init_state()
    all_rows = []
    t0 = 100_000
    for b in range(NB):
        key = rng.integers(0, K, B).astype(np.int32)
        t = (t0 + b * 5000 + rng.integers(0, 5000, B)).astype(np.int32)
        clicks = rng.integers(1, 5, B).astype(np.int32)
        lat = rng.integers(0, 1000, B).astype(np.int32)
        valid = np.ones(B, bool)
        state = dc.step(state, key, t, valid,
                        {"clicks": clicks, "latency": lat})
        all_rows += list(zip(key.tolist(), t.tolist(), clicks.tolist(),
                             lat.tolist()))
    rows, state = dc.flush_closed(state, drain=True)
    assert int(state.dropped) == 0
    got = {(k, wid): (a["clicks:SUM"], a["clicks:COUNT"],
                      a["latency:MAX"], a["latency:MIN"])
           for k, wid, a in rows}
    assert got == oracle_fold(all_rows)


def test_closed_vs_open_bucket_flush():
    dc = DimensionsComputation(SCHEMA, num_keys=3, window_slots=8,
                               lateness_ms=10_000)
    state = dc.init_state()
    mk = lambda t: dc.step(
        state, np.array([0], np.int32), np.array([t], np.int32),
        np.array([True]), {"clicks": np.array([1], np.int32),
                           "latency": np.array([5], np.int32)})
    state = mk(10_000)       # bucket 1
    state = dc.step(state, np.array([1], np.int32),
                    np.array([45_000], np.int32), np.array([True]),
                    {"clicks": np.array([2], np.int32),
                     "latency": np.array([9], np.int32)})  # bucket 4
    # watermark 45k: bucket 1 closed (20k + 10k lateness <= 45k), 4 open
    rows, state = dc.flush_closed(state)
    assert [(k, w) for k, w, _ in rows] == [(0, 1)]
    rows2, state = dc.flush_closed(state, drain=True)
    assert [(k, w) for k, w, _ in rows2] == [(1, 4)]
    assert rows2[0][2]["clicks:SUM"] == 2


def test_zero_valued_sum_rows_still_emitted():
    """A (key, bucket) whose only events carry value 0 must still produce
    a row (revenue:SUM == 0), not vanish."""
    schema = parse_schema({"keys": [{"name": "k"}],
                           "timeBuckets": ["10s"],
                           "values": [{"name": "revenue",
                                       "aggregators": ["SUM"]}],
                           "dimensions": [{"combination": ["k"]}]})
    dc = DimensionsComputation(schema, num_keys=2, window_slots=4,
                               lateness_ms=0)
    state = dc.step(dc.init_state(), np.array([1, 1], np.int32),
                    np.array([10_000, 10_001], np.int32),
                    np.array([True, True]),
                    {"revenue": np.array([0, 0], np.int32)})
    rows, _ = dc.flush_closed(state, drain=True)
    assert rows == [(1, 1, {"revenue:SUM": 0})]


def test_overflow_keys_counted_as_dropped():
    """key_idx == -1 (interner overflow) rows must tick ``dropped``."""
    dc = DimensionsComputation(SCHEMA, num_keys=2, window_slots=4,
                               lateness_ms=0)
    state = dc.step(dc.init_state(), np.array([0, -1, -1], np.int32),
                    np.array([10_000, 10_001, 10_002], np.int32),
                    np.array([True, True, True]),
                    {"clicks": np.ones(3, np.int32),
                     "latency": np.ones(3, np.int32)})
    assert int(state.dropped) == 2


def test_unifier_merge_equals_single_fold():
    rng = np.random.default_rng(11)
    K, B = 5, 128
    dc = DimensionsComputation(SCHEMA, num_keys=K, window_slots=8,
                               lateness_ms=20_000)
    key = rng.integers(0, K, 2 * B).astype(np.int32)
    t = (50_000 + rng.integers(0, 20_000, 2 * B)).astype(np.int32)
    clicks = rng.integers(1, 4, 2 * B).astype(np.int32)
    lat = rng.integers(0, 500, 2 * B).astype(np.int32)
    valid = np.ones(2 * B, bool)
    vals = lambda s: {"clicks": clicks[s], "latency": lat[s]}

    whole = dc.step(dc.init_state(), key, t, valid,
                    {"clicks": clicks, "latency": lat})
    h1 = dc.step(dc.init_state(), key[:B], t[:B], valid[:B], vals(slice(0, B)))
    h2 = dc.step(dc.init_state(), key[B:], t[B:], valid[B:], vals(slice(B, None)))
    merged = DimensionsComputation.merge(h1, h2, dc.kinds)
    for a, b in zip(whole.aggs, merged.aggs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(whole.watermark) == int(merged.watermark)


def test_unifier_merge_rejects_divergent_ring_contents():
    """ADVICE r1: if two partials' rings hold DIFFERENT window ids in the
    same slot (divergent watermark progress), an elementwise merge would
    silently sum two windows' aggregates — it must refuse instead."""
    dc = DimensionsComputation(SCHEMA, num_keys=3, window_slots=4,
                               lateness_ms=20_000)
    key = np.zeros(8, np.int32)
    valid = np.ones(8, bool)
    vals = {"clicks": np.ones(8, np.int32),
            "latency": np.ones(8, np.int32)}
    # windows 5..8 and 9..12 share ring slots (W=4) under different ids
    t1 = (50_000 + np.arange(8, dtype=np.int32) * 4_000)
    t2 = (90_000 + np.arange(8, dtype=np.int32) * 4_000)
    h1 = dc.step(dc.init_state(), key, t1, valid, vals)
    h2 = dc.step(dc.init_state(), key, t2, valid, vals)
    with pytest.raises(ValueError, match="divergent ring contents"):
        DimensionsComputation.merge(h1, h2, dc.kinds)
    # empty slots merge freely: a fresh partial is always mergeable
    DimensionsComputation.merge(h1, dc.init_state(), dc.kinds)


# -------------------------------------------------- synthetic + interner
def test_synthetic_source_interner_and_overflow():
    from streambench_tpu.dimensions.synthetic import run_synthetic

    # key capacity far below the campaign universe: overflow keys must be
    # counted as dropped, interned keys aggregated exactly
    rows, interner, dropped = run_synthetic(
        n_events=5000, batch=512, num_campaigns=10_000, key_capacity=256,
        rng=random.Random(4))
    assert interner.overflow > 0 and dropped > 0
    total = sum(a["clicks:SUM"] for _, _, a in rows)
    assert total + dropped == 5000
    assert all(name.startswith("campaign-") for name, _, _ in rows)


def test_synthetic_source_no_overflow_exact():
    from streambench_tpu.dimensions.synthetic import run_synthetic

    rows, interner, dropped = run_synthetic(
        n_events=3000, batch=512, num_campaigns=50, key_capacity=64,
        rng=random.Random(9))
    assert dropped == 0 and interner.overflow == 0
    assert sum(a["clicks:SUM"] for _, _, a in rows) == 3000


# ------------------------------------------------------------------ store
def test_store_replay_compact_and_torn_tail(tmp_path):
    d = str(tmp_path / "store")
    with DurableDimensionStore(d) as st:
        st.put_rows([("c1", 10_000, {"clicks:SUM": 3}),
                     ("c2", 10_000, {"clicks:SUM": 1})],
                    update_time_ms=21_000)
        st.put_rows([("c1", 10_000, {"clicks:SUM": 7})],  # overwrite
                    update_time_ms=22_000)
    # torn tail from a crash mid-append
    with open(os.path.join(d, "dimensions.log"), "a") as f:
        f.write('{"k":"c3","b":20000,"t":')

    st2 = DurableDimensionStore(d)
    assert len(st2) == 2
    assert st2.get("c1", 10_000)["clicks:SUM"] == 7
    assert st2.get("c1", 10_000)["_updated"] == 22_000
    assert st2.scan_key("c2") == {10_000: {"clicks:SUM": 1,
                                           "_updated": 21_000}}
    st2.compact()
    st2.put_rows([("c4", 30_000, {"clicks:SUM": 2})])
    st2.close()
    lines = open(os.path.join(d, "dimensions.log")).read().splitlines()
    assert len(lines) == 3  # compacted c1+c2 + appended c4
    st3 = DurableDimensionStore(d)
    assert st3.get("c1", 10_000)["clicks:SUM"] == 7
    assert st3.get("c4", 30_000)["clicks:SUM"] == 2


def test_compact_mid_delta_chain_keeps_base_and_chain(tmp_path):
    """ISSUE 18: compaction fired mid-delta-chain must keep the newest
    base AND every subsequent delta verbatim — rewriting the base alone
    would orphan the chain for any tailer re-reading the log."""
    import time

    from streambench_tpu.reach.deltaship import ChainTailer, DeltaShipper

    d = str(tmp_path / "store")
    store = DurableDimensionStore(d)
    ship = DeltaShipper(store, ["c0", "c1", "c2"], interval_ms=1,
                        base_every=100)
    rng = np.random.default_rng(31)
    mins = np.full((3, 4), 0xFFFFFFFF, np.uint32)
    regs = np.zeros((3, 4), np.int32)
    for t in range(4):          # base + 3 deltas
        i = rng.integers(0, 3)
        mins[i] = np.minimum(mins[i], rng.integers(
            0, 2**32, 4, dtype=np.uint32))
        regs[i] = np.maximum(regs[i], rng.integers(
            0, 30, 4, dtype=np.int32))
        assert ship.note_state(mins, regs, 1, watermark=t,
                               dirty_rows=np.array([i]))
        time.sleep(0.002)
    store.compact()
    log = os.path.join(d, "dimensions.log")
    kinds = [json.loads(ln)["kind"] for ln in open(log)
             if "reach" in ln]
    assert kinds == ["reach_sketch"] + ["reach_delta"] * 3
    # the compacted log replays to the same folded view...
    store.close()
    re = DurableDimensionStore(d)
    rv = re.reach_sketches()
    assert np.array_equal(rv["mins"], mins)
    assert np.array_equal(rv["registers"], regs)
    assert rv["watermark"] == 3
    # ...and a fresh tailer folds the preserved chain bit-identically
    tail = ChainTailer(log)
    view = tail.poll()
    assert np.array_equal(view["mins"], mins)
    assert np.array_equal(view["registers"], regs)
    st = tail.stats()
    assert st["bases_loaded"] == 1 and st["deltas_folded"] == 3
    re.close()


# ----------------------------------------------------------------- pubsub
def test_pubsub_subscribe_publish_unsubscribe():
    srv = PubSubServer().start()
    try:
        host, port = srv.address
        c = PubSubClient(host, port)
        c.subscribe("dimensions")
        for _ in range(100):
            if srv.subscriber_count("dimensions"):
                break
            import time
            time.sleep(0.01)
        assert srv.publish("dimensions", {"x": 1}) == 1
        msg = c.recv()
        assert msg == {"type": "data", "topic": "dimensions",
                       "data": {"x": 1}}
        assert srv.publish("other-topic", {}) == 0
        c.close()
    finally:
        srv.close()


# ----------------------------------------------------- app end-to-end
def make_events(tmp_path, events=4000):
    rng = random.Random(31)
    campaigns = gen.make_ids(10, rng)
    ads = gen.make_ids(100, rng)
    mapping = {a: campaigns[i % 10] for i, a in enumerate(ads)}
    src = gen.EventSource(ads=ads, user_ids=gen.make_ids(5, rng),
                          page_ids=gen.make_ids(5, rng), rng=rng)
    base = 1_700_000_000_000
    lines = [e.encode() for e in src.events_at(base + 25 * i
                                               for i in range(events))]
    return mapping, campaigns, lines, base


def test_dimension_app_end_to_end_matches_golden(tmp_path):
    mapping, campaigns, lines, base = make_events(tmp_path)
    srv = PubSubServer().start()
    try:
        app = DimensionApp(None, mapping, str(tmp_path / "store"),
                           campaigns=campaigns, pubsub=srv,
                           batch_size=512)
        app.process_lines(lines)
        report = app.close()
        assert app.invalid_tuples == 0 and app.dropped == 0

        # golden: clicks SUM per (campaign, 10s bucket) over view events
        golden: dict[tuple[str, int], int] = {}
        for line in lines:
            ev = json.loads(line)
            if ev["event_type"] != "view":
                continue
            b = int(ev["event_time"]) // 10_000 * 10_000
            k = (mapping[ev["ad_id"]], b)
            golden[k] = golden.get(k, 0) + 1
        st = DurableDimensionStore(str(tmp_path / "store"))
        got = {(k, b): v["clicks:SUM"] for (k, b), v in st.items()}
        assert got == golden
        # MAX latency recorded and sane (events are in the past -> large)
        any_val = next(iter(st.items()))[1]
        assert any_val["latency:MAX"] > 0
        assert "latency report" in report
    finally:
        srv.close()


def test_dimension_app_sentinel_backfill_without_join(tmp_path):
    mapping, campaigns, lines, base = make_events(tmp_path, events=500)
    app = DimensionApp(None, mapping, str(tmp_path / "store2"),
                       campaigns=campaigns, include_join=False)
    app.process_lines(lines)
    app.close()
    st = DurableDimensionStore(str(tmp_path / "store2"))
    keys = {k for (k, _), _ in st.items()}
    assert keys == {SENTINEL_CAMPAIGN}
    views = sum(1 for line in lines
                if json.loads(line)["event_type"] == "view")
    assert sum(v["clicks:SUM"] for _, v in st.items()) == views
