"""Importing streambench_tpu must NOT initialize a JAX backend.

CLI entry points (engine, harness) pin the platform *after* package import
(the image's sitecustomize force-selects the hardware plugin via
jax.config, so the pin must win).  Any module-level jnp/jax array op would
initialize the backend first — on a machine where the hardware tunnel is
busy, that turns `python -m streambench_tpu.engine` into a silent hang
before main() ever runs.  Regression guard for exactly that bug.
"""

import os
import pkgutil
import subprocess
import sys

import streambench_tpu

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_package_import_initializes_no_backend():
    mods = [m.name for m in pkgutil.walk_packages(
        streambench_tpu.__path__, prefix="streambench_tpu.")
        if not m.name.endswith("__main__")
        and "libsbnative" not in m.name]  # raw .so, not a Python module
    assert "streambench_tpu.ops.windowcount" in mods
    code = (
        "import importlib, jax\n"
        f"mods = {mods!r}\n"
        "for m in mods:\n"
        "    importlib.import_module(m)\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge._backends, (\n"
        "    f'package import initialized backends: '\n"
        "    f'{list(xla_bridge._backends)}')\n"
        "print('no backend init')\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                          capture_output=True, text=True, timeout=120,
                          env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "no backend init" in proc.stdout
