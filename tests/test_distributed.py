"""Multi-host distributed backend, tested with REAL process separation:
two OS processes (gloo collectives between them, 4 virtual CPU devices
each = 8 global), one shared RESP server, each process consuming its own
topic partition and flushing only the campaign shards it owns — then the
golden-model oracle over the combined Redis state.

This is the embedded-cluster trick the reference uses for multi-node
coverage (``ApplicationWithDCWithoutDeserializerTest.java:19-45``),
applied to the jax distributed runtime."""

import json
import os
import random
import socket
import subprocess
import sys
import time


from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.io.journal import FileBroker

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import json, os, sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.resp import RespClient
from streambench_tpu.parallel import (
    DistributedWindowEngine, global_mesh, init_distributed,
    run_distributed_catchup)

pid = int(sys.argv[1]); n = int(sys.argv[2])
workdir = sys.argv[3]; coord = sys.argv[4]; redis_port = int(sys.argv[5])

ctx = init_distributed(coord, n, pid)
assert ctx.num_processes == n
mesh = global_mesh(campaign=2)
cfg = default_config(jax_batch_size=256)
mapping = gen.load_ad_mapping_file(
    os.path.join(workdir, gen.AD_TO_CAMPAIGN_FILE))
campaigns, _ = gen.load_ids(workdir)
base = int(open(os.path.join(workdir, "base_time.txt")).read())
r = RespClient("127.0.0.1", redis_port)
eng = DistributedWindowEngine(cfg, mapping, mesh, base_time_ms=base,
                              campaigns=campaigns, redis=r)
reader = FileBroker(os.path.join(workdir, "broker")).reader(
    cfg.kafka_topic, pid)
stats = run_distributed_catchup(eng, reader, flush_every=4)
eng.close()
print(json.dumps(dict(pid=pid, events=eng.events_processed,
                      dropped=eng.dropped, mesh=len(jax.devices()),
                      windows_written=eng.windows_written,
                      steps=stats["steps"], votes=stats["votes"],
                      vote_s=stats["vote_s"])),
      flush=True)
"""


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


import pytest


@pytest.mark.parametrize("nproc", [2, 4])
def test_multi_process_distributed_engine_oracle(tmp_path, nproc):
    wd = str(tmp_path)
    cfg = default_config(jax_batch_size=256)
    broker = FileBroker(os.path.join(wd, "broker"))
    # NOTE: no Redis seeding here; the workers write, the oracle reads.
    gen.do_setup(None, cfg, broker=broker, events_num=6000,
                 rng=random.Random(13), workdir=wd, partitions=nproc)
    # shared rebase origin: derived from the dataset's first event exactly
    # like EventEncoder._rebase, but agreed across hosts up front
    first = json.loads(next(iter(broker.read_all(cfg.kafka_topic))))
    t0 = int(first["event_time"])
    base = t0 - (t0 % 10_000) - 60_000
    with open(os.path.join(wd, "base_time.txt"), "w") as f:
        f.write(str(base))

    redis_port = free_port()
    coord_port = free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=4",
               PYTHONPATH=REPO)
    server = subprocess.Popen(
        [sys.executable, "-m", "streambench_tpu.io.fakeredis",
         "--host", "127.0.0.1", "--port", str(redis_port)],
        env=env, cwd=REPO)
    workers = []
    try:
        # wait for the RESP server
        from streambench_tpu.io.resp import RespClient
        for _ in range(100):
            try:
                RespClient("127.0.0.1", redis_port).ping()
                break
            except OSError:
                time.sleep(0.1)
        # seed the join side-table + campaigns index (what -n/-s does when
        # handed a live Redis, core.clj:206-213) — the oracle reader walks
        # SMEMBERS campaigns
        from streambench_tpu.io.redis_schema import (
            seed_ad_mapping,
            seed_campaigns,
        )
        rc = RespClient("127.0.0.1", redis_port)
        campaigns, _ = gen.load_ids(wd)
        mapping = gen.load_ad_mapping_file(
            os.path.join(wd, gen.AD_TO_CAMPAIGN_FILE))
        seed_campaigns(rc, campaigns)
        seed_ad_mapping(rc, mapping)

        script = WORKER.format(repo=REPO)
        for pid in range(nproc):
            workers.append(subprocess.Popen(
                [sys.executable, "-c", script, str(pid), str(nproc), wd,
                 f"127.0.0.1:{coord_port}", str(redis_port)],
                env=env, cwd=REPO, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True))
        outs = []
        for w in workers:
            out, err = w.communicate(timeout=240)
            assert w.returncode == 0, err[-3000:]
            outs.append(json.loads(out.strip().splitlines()[-1]))
        assert all(o["mesh"] == 4 * nproc for o in outs)
        assert sum(o["events"] for o in outs) == 6000
        assert all(o["dropped"] == 0 for o in outs)
        # the batched vote fires once per ROUND, not per step
        assert all(o["votes"] <= o["steps"] // 2 + 2 for o in outs), outs
        assert all(o["steps"] == outs[0]["steps"] for o in outs), outs
        # shard ownership is balanced: one owner host per campaign shard
        # (2 shards here), spread across hosts rather than all landing on
        # the coordinator
        writers = sum(1 for o in outs if o["windows_written"] > 0)
        assert writers == min(nproc, 2), outs

        r = RespClient("127.0.0.1", redis_port)
        correct, differ, missing = gen.check_correct(r, wd,
                                                     log=lambda s: None)
        assert differ == 0 and missing == 0 and correct > 0
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
        server.kill()
