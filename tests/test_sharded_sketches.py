"""Sharded sketch engines vs their single-device kernels: bit-exact.

The virtual 8-device CPU mesh stands in for real multi-chip hardware
(SURVEY.md §4.3 embedded-cluster discipline).  VERDICT r3 missing #1:
"campaign-shard HLL registers with pmax merge, CMS with psum merge, and a
user-axis-sharded session/CMS path ... prove bit-identity to the
single-device kernels on the 8-CPU mesh".
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.engine import StreamRunner
from streambench_tpu.engine.sketches import HLLDistinctEngine, SessionCMSEngine
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import as_redis
from streambench_tpu.ops import cms, hll, session
from streambench_tpu.parallel import (
    ShardedHLLEngine,
    ShardedSessionCMSEngine,
    build_mesh,
    sharded_hll_init,
    sharded_hll_step,
)
from streambench_tpu.parallel.sketches import (
    _build_hll_scan,
    _build_session_scan,
    _build_session_step,
)


def rand_batches(rng, n_batches, B, n_ads, n_users, span_ms=200_000):
    out = []
    t = 70_000
    for _ in range(n_batches):
        ad = rng.integers(0, n_ads, B).astype(np.int32)
        user = rng.integers(0, n_users, B).astype(np.int32)
        et = rng.integers(0, 3, B).astype(np.int32)
        tm = (t + np.sort(rng.integers(0, span_ms // n_batches, B))
              ).astype(np.int32)
        valid = rng.random(B) < 0.95
        t += span_ms // n_batches
        out.append((ad, user, et, tm, valid))
    return out


MESHES = [(8, 1), (4, 2), (2, 4), (1, 8), (2, 2)]


@pytest.mark.parametrize("dshape", MESHES)
def test_sharded_hll_step_matches_single_device(dshape):
    nd, nc = dshape
    mesh = build_mesh(data=nd, campaign=nc,
                      devices=jax.devices()[: nd * nc])
    rng = np.random.default_rng(11)
    C, W, B, R = 96, 16, 64, 32  # C divisible by every nc in MESHES
    n_ads = C * 3
    join = np.concatenate(
        [rng.integers(0, C, n_ads).astype(np.int32), [-1]])

    ref = hll.init_state(C, W, num_registers=R)
    sh = sharded_hll_init(C, W, mesh, num_registers=R)
    jt = jnp.asarray(join)
    for ad, user, et, tm, valid in rand_batches(rng, 6, B, n_ads, 500):
        ref = hll.step(ref, jt, ad, user, et, tm, valid)
        sh = sharded_hll_step(mesh, sh, jt, ad, user, et, tm, valid)

    assert np.array_equal(np.asarray(ref.registers),
                          np.asarray(sh.registers))
    assert np.array_equal(np.asarray(ref.window_ids),
                          np.asarray(sh.window_ids))
    assert int(ref.watermark) == int(sh.watermark)
    assert int(ref.dropped) == int(sh.dropped)


def test_sharded_hll_scan_matches_step_sequence():
    mesh = build_mesh(data=4, campaign=2)
    rng = np.random.default_rng(3)
    C, W, B, R, K = 32, 8, 32, 16, 5
    n_ads = C * 2
    join = np.concatenate(
        [rng.integers(0, C, n_ads).astype(np.int32), [-1]])
    jt = jnp.asarray(join)
    batches = rand_batches(rng, K, B, n_ads, 200)

    seq = sharded_hll_init(C, W, mesh, num_registers=R)
    for ad, user, et, tm, valid in batches:
        seq = sharded_hll_step(mesh, seq, jt, ad, user, et, tm, valid)

    sc = sharded_hll_init(C, W, mesh, num_registers=R)
    fn = _build_hll_scan(mesh, 10_000, 60_000, 0)
    cols = [np.stack(c) for c in zip(*batches)]
    regs, ids, wm, dropped = fn(sc.registers, sc.window_ids, sc.watermark,
                                sc.dropped, jt, *cols)

    assert np.array_equal(np.asarray(seq.registers), np.asarray(regs))
    assert np.array_equal(np.asarray(seq.window_ids), np.asarray(ids))
    assert int(seq.watermark) == int(wm)
    assert int(seq.dropped) == int(dropped)


def test_sharded_hll_registers_actually_sharded():
    mesh = build_mesh(data=1, campaign=8)
    st = sharded_hll_init(100, 16, mesh, num_registers=32)
    # 100 campaigns pad to 104 (= 8 x 13); each shard holds 13 campaigns.
    assert st.registers.shape == (104, 16, 32)
    shapes = {s.data.shape for s in st.registers.addressable_shards}
    assert shapes == {(13, 16, 32)}


def _session_mesh_setup(dshape, U=64, B=48, n_batches=6, n_users=80,
                        seed=21):
    nd, nc = dshape
    mesh = build_mesh(data=nd, campaign=nc,
                      devices=jax.devices()[: nd * nc])
    rng = np.random.default_rng(seed)
    batches = []
    t = 70_000
    for _ in range(n_batches):
        # n_users > U exercises the capacity-overflow drop accounting
        user = rng.integers(0, n_users, B).astype(np.int32)
        et = rng.integers(0, 3, B).astype(np.int32)
        tm = (t + np.sort(rng.integers(0, 40_000, B))).astype(np.int32)
        valid = rng.random(B) < 0.9
        t += 40_000
        batches.append((user, et, tm, valid))
    return mesh, batches


def _ring_dict(topk):
    keys = np.asarray(topk.keys)
    ests = np.asarray(topk.ests)
    return {int(k): int(e) for k, e in zip(keys, ests) if k >= 0}


@pytest.mark.parametrize("dshape", MESHES)
def test_sharded_session_cms_matches_single_device(dshape):
    mesh, batches = _session_mesh_setup(dshape)
    U, M = 64, 256  # ring capacity > distinct users: no tie-broken evictions
    gap, late = 15_000, 20_000

    ref = session.init_state(U)
    ref_cms = cms.init_state(depth=4, width=256)
    ref_tk = cms.init_topk(M)

    def absorb(cm, tk, closed):
        cm = cms.update(cm, closed.user, closed.clicks, closed.valid)
        tk = cms.update_topk(cm, tk, closed.user, closed.valid)
        return cm, tk

    from streambench_tpu.engine.sketches import LAT_BIN_MS, LAT_BINS

    now_rel = 600_000
    ref_closed = 0
    want_hist = np.zeros(LAT_BINS, np.int64)
    for user, et, tm, valid in batches:
        ref, in_b, carry = session.step(ref, user, et, tm, valid,
                                        gap_ms=gap, lateness_ms=late)
        ref_cms, ref_tk = absorb(ref_cms, ref_tk, in_b)
        ref_cms, ref_tk = absorb(ref_cms, ref_tk, carry)
        n_closed = (int(np.asarray(in_b.valid).sum())
                    + int(np.asarray(carry.valid).sum()))
        ref_closed += n_closed
        det_bin = min(max(now_rel - int(tm[valid].max()), 0) // LAT_BIN_MS,
                      LAT_BINS - 1)
        want_hist[det_bin] += n_closed

    fn = _build_session_step(mesh, gap, late, U)
    lt = jnp.full((U,), -1, jnp.int32)
    ss = jnp.zeros((U,), jnp.int32)
    ck = jnp.zeros((U,), jnp.int32)
    carry_t = (lt, ss, ck, jnp.int32(0), jnp.int32(0),
               jnp.zeros((4, 256), jnp.int32), jnp.int32(0),
               jnp.full((M,), -1, jnp.int32), jnp.full((M,), -1, jnp.int32),
               jnp.int32(0), jnp.int32(0),
               jnp.zeros((LAT_BINS,), jnp.int32))
    for user, et, tm, valid in batches:
        carry_t = fn(*carry_t, jnp.int32(now_rel), user, et, tm, valid)
    (lt, ss, ck, wm, dr, table, total, tkk, tke, cn, cl, hist) = carry_t

    assert np.array_equal(np.asarray(ref.last_time), np.asarray(lt))
    # sess_start/clicks only meaningful where a session is open
    open_ = np.asarray(ref.last_time) >= 0
    assert np.array_equal(np.asarray(ref.sess_start)[open_],
                          np.asarray(ss)[open_])
    assert np.array_equal(np.asarray(ref.clicks)[open_],
                          np.asarray(ck)[open_])
    assert int(ref.watermark) == int(wm)
    assert int(ref.dropped) == int(dr)
    assert np.array_equal(np.asarray(ref_cms.table), np.asarray(table))
    assert int(ref_cms.total) == int(total)
    assert _ring_dict(ref_tk) == _ring_dict(cms.TopKState(tkk, tke))
    assert ref_closed == int(cn)
    # the close->absorb latency histogram matches the per-batch
    # evidence-latency accounting (same bins, same counts)
    assert np.array_equal(want_hist, np.asarray(hist))


@pytest.mark.parametrize("hoist", [False, True])
def test_sharded_session_scan_matches_step_sequence(hoist):
    """Both session scan arms — collectives-in-loop and the ISSUE 12
    hoisted arm (collective-free body, stacked post-scan merges +
    candidate-ring replay) — equal the per-batch step sequence bit for
    bit, CMS table and ring included."""
    mesh, batches = _session_mesh_setup((4, 2), seed=9)
    U, M = 64, 256
    gap, late = 15_000, 20_000

    step_fn = _build_session_step(mesh, gap, late, U)
    scan_fn = _build_session_scan(mesh, gap, late, U, hoist)

    from streambench_tpu.engine.sketches import LAT_BINS

    now_rel = jnp.int32(600_000)
    init = (jnp.full((U,), -1, jnp.int32), jnp.zeros((U,), jnp.int32),
            jnp.zeros((U,), jnp.int32), jnp.int32(0), jnp.int32(0),
            jnp.zeros((4, 256), jnp.int32), jnp.int32(0),
            jnp.full((M,), -1, jnp.int32), jnp.full((M,), -1, jnp.int32),
            jnp.int32(0), jnp.int32(0), jnp.zeros((LAT_BINS,), jnp.int32))

    seq = init
    for user, et, tm, valid in batches:
        seq = step_fn(*seq, now_rel, user, et, tm, valid)

    cols = [np.stack(c) for c in zip(*batches)]
    sc = scan_fn(*init, now_rel, *cols)

    for a, b in zip(seq, sc):
        assert np.array_equal(np.asarray(a), np.asarray(b)), (a, b)


@pytest.mark.parametrize("dshape", [(4, 2), (2, 2)])
def test_sharded_hll_packed_and_hoisted_bit_identical(dshape):
    """ISSUE 7 wire packing for the sketch engines: the packed HLL
    step/scan (3 data-axis gathers instead of 5) and the hoisted scans
    (gathers + drop psum once per dispatch) must match the unpacked
    per-batch kernels register for register."""
    from streambench_tpu.ops import windowcount as wc
    from streambench_tpu.parallel.sketches import (
        _build_hll_scan,
        _build_hll_scan_packed,
        _build_hll_step_packed,
    )

    d, c = dshape
    mesh = build_mesh(data=d, campaign=c, devices=jax.devices()[:d * c])
    rng = np.random.default_rng(23)
    C, W, A, B, K, U = 16, 8, 64, 8 * d, 3, 48
    jt = jnp.asarray(np.concatenate(
        [rng.integers(0, C, A).astype(np.int32), [-1]]))
    batches = rand_batches(rng, K, B, A + 1, U)

    ground = sharded_hll_init(C, W, mesh, num_registers=16)
    psteps = sharded_hll_init(C, W, mesh, num_registers=16)
    pfn = _build_hll_step_packed(mesh, 10_000, 60_000, 0)
    for ad, user, et, tm, va in batches:
        ground = sharded_hll_step(mesh, ground, jt, ad, user, et, tm, va)
        word = wc.pack_columns(ad, et, va)
        regs, ids, wm, dr = pfn(
            psteps.registers, psteps.window_ids, psteps.watermark,
            psteps.dropped, jt, word, user, tm)
        psteps = hll.HLLState(regs, ids, wm, dr)

    def eq(state, arms_name):
        assert np.array_equal(np.asarray(ground.registers),
                              np.asarray(state[0])), arms_name
        assert np.array_equal(np.asarray(ground.window_ids),
                              np.asarray(state[1])), arms_name
        assert int(ground.watermark) == int(state[2]), arms_name
        assert int(ground.dropped) == int(state[3]), arms_name

    eq(psteps, "packed step sequence")

    stack = lambda i: np.stack([b[i] for b in batches])  # noqa: E731
    words = np.stack([wc.pack_columns(ad, et, va)
                      for ad, user, et, tm, va in batches])
    arms = {
        "scan_perbatch": (_build_hll_scan(mesh, 10_000, 60_000, 0, False),
                          (stack(0), stack(1), stack(2), stack(3),
                           stack(4))),
        "scan_hoisted": (_build_hll_scan(mesh, 10_000, 60_000, 0, True),
                         (stack(0), stack(1), stack(2), stack(3),
                          stack(4))),
        "packed_scan_perbatch": (
            _build_hll_scan_packed(mesh, 10_000, 60_000, 0, False),
            (words, stack(1), stack(3))),
        "packed_scan_hoisted": (
            _build_hll_scan_packed(mesh, 10_000, 60_000, 0, True),
            (words, stack(1), stack(3))),
    }
    for name, (fn, cols) in arms.items():
        s = sharded_hll_init(C, W, mesh, num_registers=16)
        out = fn(s.registers, s.window_ids, s.watermark, s.dropped, jt,
                 *cols)
        eq(out, name)


def test_sharded_hll_engine_packed_scan_and_padding(tmp_path):
    """The engine dispatches the packed scan (PACKED_EXTRA_COLS carries
    user ids) and pads a non-divisible batch size — estimates still
    equal the single-device engine's on the same journal."""
    cfg = default_config(jax_batch_size=250, jax_window_slots=16)
    broker = FileBroker(str(tmp_path / "broker"))
    r1 = as_redis(FakeRedisStore())
    gen.do_setup(r1, cfg, broker=broker, events_num=6_000,
                 rng=random.Random(21), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))

    mesh = build_mesh(data=4, campaign=2)
    eng = ShardedHLLEngine(cfg, mapping, mesh, redis=r1)
    assert eng._packed_scan, "packed scan must be eligible"
    assert eng._data_pad == 2  # 250 % 4
    stats = StreamRunner(eng, broker.reader(cfg.kafka_topic)).run_catchup()
    eng.close()
    assert stats.events == 6_000 and eng.dropped == 0

    r2 = as_redis(FakeRedisStore())
    from streambench_tpu.io.redis_schema import seed_campaigns
    seed_campaigns(r2, gen.load_ids(str(tmp_path))[0])
    ref = HLLDistinctEngine(cfg, mapping, redis=r2)
    StreamRunner(ref, broker.reader(cfg.kafka_topic)).run_catchup()
    ref.close()

    from streambench_tpu.io.redis_schema import read_seen_counts
    assert read_seen_counts(r1) == read_seen_counts(r2)


def test_sharded_hll_engine_end_to_end(tmp_path):
    """ShardedHLLEngine through the real runner: estimates equal the
    single-device HLL engine's on the same journal."""
    cfg = default_config(jax_batch_size=256, jax_window_slots=16)
    broker = FileBroker(str(tmp_path / "broker"))
    r1 = as_redis(FakeRedisStore())
    gen.do_setup(r1, cfg, broker=broker, events_num=8_000,
                 rng=random.Random(5), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))

    mesh = build_mesh(data=4, campaign=2)
    eng = ShardedHLLEngine(cfg, mapping, mesh, redis=r1)
    stats = StreamRunner(eng, broker.reader(cfg.kafka_topic)).run_catchup()
    eng.close()
    assert stats.events == 8_000
    assert eng.dropped == 0

    r2 = as_redis(FakeRedisStore())
    from streambench_tpu.io.redis_schema import seed_campaigns
    seed_campaigns(r2, gen.load_ids(str(tmp_path))[0])
    ref = HLLDistinctEngine(cfg, mapping, redis=r2)
    StreamRunner(ref, broker.reader(cfg.kafka_topic)).run_catchup()
    ref.close()

    from streambench_tpu.io.redis_schema import read_seen_counts
    assert read_seen_counts(r1) == read_seen_counts(r2)


def test_sharded_session_engine_end_to_end(tmp_path):
    """ShardedSessionCMSEngine through the real runner: heavy hitters and
    counters equal the single-device engine's on the same journal."""
    cfg = default_config(jax_batch_size=256)
    broker = FileBroker(str(tmp_path / "broker"))
    r1 = as_redis(FakeRedisStore())
    gen.do_setup(r1, cfg, broker=broker, events_num=8_000,
                 rng=random.Random(6), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))

    mesh = build_mesh(data=2, campaign=4)
    eng = ShardedSessionCMSEngine(cfg, mapping, mesh, redis=r1,
                                  user_capacity=1 << 10)
    stats = StreamRunner(eng, broker.reader(cfg.kafka_topic)).run_catchup()
    eng.close()
    assert stats.events == 8_000

    ref = SessionCMSEngine(cfg, mapping, redis=as_redis(FakeRedisStore()),
                           user_capacity=1 << 10)
    StreamRunner(ref, broker.reader(cfg.kafka_topic)).run_catchup()
    ref.close()

    assert eng.sessions_closed == ref.sessions_closed
    assert eng.session_clicks == ref.session_clicks
    assert sorted(eng.heavy_hitters()) == sorted(ref.heavy_hitters())


# ----------------------------------------------------------------------
# Sharded sliding + t-digest (the last sketch family's mesh form)
# ----------------------------------------------------------------------

@pytest.mark.parametrize("dshape", MESHES)
def test_sharded_sliding_step_matches_single_device(dshape):
    """Counts/ids/watermark/dropped bit-identical to ops.sliding.step;
    digest weights exact (sums of unit floats) and means within float
    reassociation tolerance of the single-device tdigest fold."""
    from streambench_tpu.ops import sliding, tdigest
    from streambench_tpu.parallel.sketches import _build_sliding_step

    nd, nc = dshape
    mesh = build_mesh(data=nd, campaign=nc,
                      devices=jax.devices()[: nd * nc])
    rng = np.random.default_rng(17)
    C, W, B, K = 96, 128, 64, 16
    n_ads = C * 3
    join = np.concatenate(
        [rng.integers(0, C, n_ads).astype(np.int32), [-1]])
    jt = jnp.asarray(join)
    now_rel = jnp.int32(400_000)

    from streambench_tpu.ops.windowcount import init_state
    ref = init_state(C, W)
    dg_ref = tdigest.init_state(C, compression=K)

    counts = jnp.zeros((C, W), jnp.int32)
    ids = jnp.full((W,), -1, jnp.int32)
    carry = (counts, ids, jnp.int32(0), jnp.int32(0),
             jnp.zeros((C, K), jnp.float32), jnp.zeros((C, K), jnp.float32))
    fn = _build_sliding_step(mesh, 10_000, 1_000, 60_000)

    for ad, user, et, tm, valid in rand_batches(rng, 5, B, n_ads, 500):
        ref = sliding.step(ref, jt, ad, et, tm, valid,
                           size_ms=10_000, slide_ms=1_000,
                           lateness_ms=60_000)
        campaign = join[ad]
        mask = valid & (et == 0) & (campaign >= 0)
        lat = np.maximum(int(now_rel) - tm, 0)
        dg_ref = tdigest.update(dg_ref, jnp.asarray(campaign),
                                jnp.asarray(lat), jnp.asarray(mask))
        carry = fn(*carry, jt, now_rel, jnp.asarray(ad), jnp.asarray(et),
                   jnp.asarray(tm), jnp.asarray(valid))

    counts, ids, wm, dr, means, weights = carry
    assert np.array_equal(np.asarray(ref.counts), np.asarray(counts))
    assert np.array_equal(np.asarray(ref.window_ids), np.asarray(ids))
    assert int(ref.watermark) == int(wm)
    assert int(ref.dropped) == int(dr)
    assert np.array_equal(np.asarray(dg_ref.weights), np.asarray(weights))
    np.testing.assert_allclose(np.asarray(dg_ref.means),
                               np.asarray(means), rtol=1e-5, atol=1e-3)


def test_sharded_sliding_scan_matches_step_sequence():
    """One scanned dispatch == the same batches stepped one by one.

    Counts/ids/watermark/dropped are exact.  Digests compress once per
    chunk on the scan path vs once per batch on the step path (the
    histogram fold amortizes the compress), so centroid layouts differ
    legitimately — compare what the cadence must conserve: total weight
    per campaign (exactly) and quantiles (within digest tolerance)."""
    from streambench_tpu.parallel.sketches import (
        _build_sliding_scan,
        _build_sliding_step,
    )

    mesh = build_mesh(data=2, campaign=4)
    rng = np.random.default_rng(23)
    C, W, B, K, Kb = 96, 128, 64, 16, 4
    n_ads = C * 3
    join = np.concatenate(
        [rng.integers(0, C, n_ads).astype(np.int32), [-1]])
    jt = jnp.asarray(join)
    now_rel = jnp.int32(400_000)
    batches = rand_batches(rng, Kb, B, n_ads, 500)

    def fresh():
        return (jnp.zeros((C, W), jnp.int32), jnp.full((W,), -1, jnp.int32),
                jnp.int32(0), jnp.int32(0),
                jnp.zeros((C, K), jnp.float32),
                jnp.zeros((C, K), jnp.float32))

    step = _build_sliding_step(mesh, 10_000, 1_000, 60_000)
    carry = fresh()
    for ad, user, et, tm, valid in batches:
        carry = step(*carry, jt, now_rel, jnp.asarray(ad), jnp.asarray(et),
                     jnp.asarray(tm), jnp.asarray(valid))

    scan = _build_sliding_scan(mesh, 10_000, 1_000, 60_000)
    cols = [np.stack([b[i] for b in batches]) for i in (0, 2, 3, 4)]
    got = scan(*fresh(), jt, now_rel, *(jnp.asarray(c) for c in cols))

    for a, b in zip(carry[:4], got[:4]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    from streambench_tpu.ops import tdigest
    w_step = np.asarray(carry[5]).sum(axis=1)
    w_scan = np.asarray(got[5]).sum(axis=1)
    np.testing.assert_allclose(w_scan, w_step, rtol=1e-6)
    qs = jnp.asarray([0.5, 0.99], jnp.float32)
    q_step = np.asarray(tdigest.quantile(
        tdigest.TDigestState(jnp.asarray(carry[4]), jnp.asarray(carry[5])),
        qs))
    q_scan = np.asarray(tdigest.quantile(
        tdigest.TDigestState(jnp.asarray(got[4]), jnp.asarray(got[5])),
        qs))
    sampled = w_step > 0
    np.testing.assert_allclose(q_scan[sampled], q_step[sampled],
                               rtol=0.12, atol=1.0)


@pytest.mark.parametrize("sliced", ["off", "on"])
def test_sharded_sliding_engine_end_to_end(tmp_path, sliced):
    """ShardedSlidingTDigestEngine through the real runner, both folds:
    window rows and quantiles equal the single-device engine's on the
    same journal."""
    from streambench_tpu.engine.sketches import SlidingTDigestEngine
    from streambench_tpu.parallel import ShardedSlidingTDigestEngine

    cfg = default_config(jax_batch_size=256, jax_window_slots=128)
    broker = FileBroker(str(tmp_path / "broker"))
    r1 = as_redis(FakeRedisStore())
    gen.do_setup(r1, cfg, broker=broker, events_num=8_000,
                 rng=random.Random(9), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))

    mesh = build_mesh(data=4, campaign=2)
    eng = ShardedSlidingTDigestEngine(cfg, mapping, mesh, redis=r1,
                                      sliced=sliced)
    assert eng.sliced == (sliced == "on")
    stats = StreamRunner(eng, broker.reader(cfg.kafka_topic)).run_catchup()
    q1 = eng.quantiles()
    eng.close()
    assert stats.events == 8_000

    r2 = as_redis(FakeRedisStore())
    from streambench_tpu.io.redis_schema import seed_campaigns
    seed_campaigns(r2, gen.load_ids(str(tmp_path))[0])
    ref = SlidingTDigestEngine(cfg, mapping, redis=r2, sliced=sliced)
    StreamRunner(ref, broker.reader(cfg.kafka_topic)).run_catchup()
    q2 = ref.quantiles()
    ref.close()

    from streambench_tpu.io.redis_schema import read_seen_counts
    assert read_seen_counts(r1) == read_seen_counts(r2)
    # digests fold per-event host timestamps (now_ms at dispatch time),
    # which legitimately differ between the two runs — only shape and
    # plausibility are comparable here; bit-level equivalence is pinned
    # by the kernel tests above with a fixed now_rel
    assert q1.shape == q2.shape


@pytest.mark.parametrize("hoist", [False, True])
@pytest.mark.parametrize("sliced", [False, True])
def test_sharded_sliding_scan_arms_match_single_device(hoist, sliced):
    """ISSUE 12 sweep: every sharded sliding scan arm — legacy/sliced x
    per-batch/hoisted collectives — reproduces the single-device fold's
    counts plane, ring ids, watermark, and membership-granular dropped
    bit for bit."""
    from streambench_tpu.ops import sliding
    from streambench_tpu.ops.windowcount import init_state
    from streambench_tpu.parallel.sketches import _build_sliding_scan

    mesh = build_mesh(data=4, campaign=2)
    rng = np.random.default_rng(31)
    C, W, B, Kb, S, TD = 96, 128, 64, 4, 10, 16
    n_ads = C * 3
    join = np.concatenate(
        [rng.integers(0, C, n_ads).astype(np.int32), [-1]])
    jt = jnp.asarray(join)
    batches = rand_batches(rng, Kb, B, n_ads, 500, span_ms=60_000)

    if sliced:
        ref = sliding.init_sliced(C, W, S)
        for ad, user, et, tm, valid in batches:
            ref = sliding.step_sliced(ref, jt, ad, et, tm, valid,
                                      size_ms=10_000, slide_ms=1_000,
                                      lateness_ms=60_000)
    else:
        ref = init_state(C, W)
        for ad, user, et, tm, valid in batches:
            ref = sliding.step(ref, jt, ad, et, tm, valid,
                               size_ms=10_000, slide_ms=1_000,
                               lateness_ms=60_000)

    counts0 = (jnp.zeros((C, S, W), jnp.int32) if sliced
               else jnp.zeros((C, W), jnp.int32))
    state0 = (counts0, jnp.full((W,), -1, jnp.int32), jnp.int32(0),
              jnp.int32(0), jnp.zeros((C, TD), jnp.float32),
              jnp.zeros((C, TD), jnp.float32))
    fn = _build_sliding_scan(mesh, 10_000, 1_000, 60_000, 0, hoist,
                             sliced)
    cols = [np.stack([b[i] for b in batches]) for i in (0, 2, 3, 4)]
    got = fn(*state0, jt, jnp.int32(400_000),
             *(jnp.asarray(c) for c in cols))
    np.testing.assert_array_equal(np.asarray(ref.counts),
                                  np.asarray(got[0]))
    np.testing.assert_array_equal(np.asarray(ref.window_ids),
                                  np.asarray(got[1]))
    assert int(ref.watermark) == int(got[2])
    assert int(ref.dropped) == int(got[3])


def test_sliding_and_session_collective_reports(tmp_path):
    """The ISSUE 12 acceptance number from the compiled HLO: hoisted
    sliding/session scans carry ZERO loop-body collectives and a small
    per-dispatch count, where the per-batch arms pay K x per-batch."""
    from streambench_tpu.parallel import (
        ShardedSessionCMSEngine,
        ShardedSlidingTDigestEngine,
    )
    from streambench_tpu.parallel.sketches import (
        _build_session_scan,
        _build_sliding_scan,
    )
    from streambench_tpu.parallel import collectives

    cfg = default_config(jax_batch_size=64, jax_window_slots=128,
                         jax_scan_batches=4)
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(as_redis(FakeRedisStore()), cfg, broker=broker,
                 events_num=500, rng=random.Random(3),
                 workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(
        str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    mesh = build_mesh(data=2, campaign=2)

    eng = ShardedSlidingTDigestEngine(cfg, mapping, mesh,
                                      redis=as_redis(FakeRedisStore()),
                                      sliced="on")
    rep = eng.collective_report(k=4)
    assert rep["sliced"] is True
    assert rep["scan"]["per_loop_iteration"]["ops"] == 0
    # 4 gathered columns + 1 deferred drop psum
    assert rep["scan"]["per_dispatch"]["ops"] == 5
    # the per-batch arm pays K x (cols + 1)
    perbatch = _build_sliding_scan(mesh, eng.size_ms, eng.slide_ms,
                                   eng.base_lateness, 0, False, True)
    B = cfg.jax_batch_size + eng._data_pad
    zi = lambda *s: jnp.zeros(s, jnp.int32)  # noqa: E731
    rep_pb = collectives.report_for(
        perbatch, *eng._carry(), eng.join_table, jnp.int32(0),
        zi(4, B), zi(4, B), zi(4, B), jnp.zeros((4, B), bool),
        scan_len=4)
    assert rep_pb["per_dispatch"]["ops"] == 4 * 5

    sess = ShardedSessionCMSEngine(cfg, mapping, mesh,
                                   redis=as_redis(FakeRedisStore()),
                                   user_capacity=1 << 10)
    srep = sess.collective_report(k=4)
    assert srep["scan"]["per_loop_iteration"]["ops"] == 0
    assert srep["scan"]["per_dispatch"]["ops"] < 10
    spb = _build_session_scan(mesh, sess.gap_ms, sess.lateness,
                              sess.user_capacity, False)
    rep_spb = collectives.report_for(
        spb, *sess._carry(), jnp.int32(0), zi(4, 64), zi(4, 64),
        zi(4, 64), jnp.zeros((4, 64), bool), scan_len=4)
    assert (rep_spb["per_dispatch"]["ops"]
            > 4 * srep["scan"]["per_dispatch"]["ops"])


# ----------------------------------------------------------------------
# SALSA-mode sharded session engine (ISSUE 13): the merge-on-overflow
# plane is folded REPLICATED from the all_gathered closure rows (a
# psum-free merge — the transition is a multiset homomorphism), so the
# sharded per-batch arm, the hoisted scan arm, and the single-device
# engine must all land on bit-identical planes/bitmaps.
# ----------------------------------------------------------------------

@pytest.mark.parametrize("dshape", [(4, 2), (2, 2)])
def test_sharded_session_salsa_matches_single_device(dshape):
    from streambench_tpu.engine.sketches import LAT_BINS
    from streambench_tpu.ops import salsa
    from streambench_tpu.parallel.sketches import (
        _build_session_scan_salsa,
        _build_session_step_salsa,
    )

    mesh, batches = _session_mesh_setup(dshape)
    U, M = 64, 256
    gap, late = 15_000, 20_000
    now_rel = 600_000

    ref = session.init_state(U)
    ref_cms = salsa.init_state(depth=4, width=256)
    ref_tk = cms.init_topk(M)
    for user, et, tm, valid in batches:
        ref, in_b, carry = session.step(ref, user, et, tm, valid,
                                        gap_ms=gap, lateness_ms=late)
        for closed in (in_b, carry):
            ref_cms = salsa.update(ref_cms, closed.user, closed.clicks,
                                   closed.valid)
            ref_tk = cms.update_topk(ref_cms, ref_tk, closed.user,
                                     closed.valid)

    def init_carry():
        return (jnp.full((U,), -1, jnp.int32), jnp.zeros((U,), jnp.int32),
                jnp.zeros((U,), jnp.int32), jnp.int32(0), jnp.int32(0),
                *salsa.init_state(depth=4, width=256),
                jnp.full((M,), -1, jnp.int32),
                jnp.full((M,), -1, jnp.int32),
                jnp.int32(0), jnp.int32(0),
                jnp.zeros((LAT_BINS,), jnp.int32))

    fn = _build_session_step_salsa(mesh, gap, late, U)
    carry_t = init_carry()
    for user, et, tm, valid in batches:
        carry_t = fn(*carry_t, jnp.int32(now_rel), user, et, tm, valid)
    (lt, ss, ck, wm, dr, table, m1, m2, total, tkk, tke, cn, cl,
     hist) = carry_t

    np.testing.assert_array_equal(np.asarray(ref_cms.table),
                                  np.asarray(table))
    np.testing.assert_array_equal(np.asarray(ref_cms.m1), np.asarray(m1))
    np.testing.assert_array_equal(np.asarray(ref_cms.m2), np.asarray(m2))
    assert int(ref_cms.total) == int(total)
    assert _ring_dict(ref_tk) == _ring_dict(cms.TopKState(tkk, tke))

    # hoisted scan arm bit-identical to the per-batch arm
    scan_fn = _build_session_scan_salsa(mesh, gap, late, U)
    stack = [np.stack(x) for x in zip(*batches)]
    carry_s = init_carry()
    K = 3
    for i in range(0, len(batches), K):
        xs = [jnp.asarray(s[i:i + K]) for s in stack]
        carry_s = scan_fn(*carry_s, jnp.int32(now_rel), *xs)
    for a, b in zip(carry_t, carry_s):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_session_engine_refuses_two_stage():
    mesh = build_mesh(data=2, campaign=1)
    cfg = default_config(jax_cms_stages=2)
    with pytest.raises(ValueError, match="stages=2"):
        ShardedSessionCMSEngine(cfg, {"a": "c"}, mesh, campaigns=["c"],
                                user_capacity=1 << 10)
