"""`python -m streambench_tpu.obs` / file-path invocation must work
from ANY cwd, not just the repo root (ISSUE 18 satellite): the
__main__ shim self-locates the package when executed by file path, and
`python -m` works from a foreign cwd with PYTHONPATH derived from the
installed package location.  Also pins the pyproject console entry
points to real, importable callables."""

import json
import os
import re
import subprocess
import sys

import streambench_tpu

REPO = os.path.dirname(os.path.dirname(
    os.path.abspath(streambench_tpu.__file__)))
MAIN_PY = os.path.join(REPO, "streambench_tpu", "obs", "__main__.py")


def write_journal(tmp_path):
    path = os.path.join(str(tmp_path), "metrics.jsonl")
    with open(path, "w") as f:
        for i in range(3):
            f.write(json.dumps({
                "kind": "snapshot", "seq": i, "ts_ms": 1_000 + i * 100,
                "uptime_ms": (i + 1) * 100, "events": (i + 1) * 1_000,
                "events_per_s": 100.0 * (i + 1), "windows_written": i,
                "backlog_bytes": 0, "watermark_lag_ms": 5,
                "rss_bytes": 1 << 20,
            }) + "\n")
    return path


def run(cmd, cwd, env=None):
    e = dict(os.environ)
    e.pop("PYTHONPATH", None)
    if env:
        e.update(env)
    return subprocess.run(cmd, cwd=cwd, env=e, capture_output=True,
                          text=True, timeout=120)


def test_cli_by_file_path_from_temp_cwd(tmp_path):
    """File-path execution from a cwd where the package is NOT
    importable: the shim must put the repo root on sys.path itself."""
    journal = write_journal(tmp_path)
    r = run([sys.executable, MAIN_PY, "report", journal, "--json"],
            cwd=str(tmp_path))
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    assert doc["events"] == 3_000


def test_cli_module_from_temp_cwd_with_pythonpath(tmp_path):
    """`python -m streambench_tpu.obs` from a foreign cwd, PYTHONPATH
    derived from the package location (the documented no-install
    invocation)."""
    journal = write_journal(tmp_path)
    r = run([sys.executable, "-m", "streambench_tpu.obs", "report",
             journal, "--json"],
            cwd=str(tmp_path), env={"PYTHONPATH": REPO})
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["events"] == 3_000


def test_cli_regress_exit_codes_from_temp_cwd(tmp_path):
    journal = write_journal(tmp_path)
    r = run([sys.executable, MAIN_PY, "regress", journal, journal],
            cwd=str(tmp_path))
    assert r.returncode == 0, r.stderr   # identical runs never regress


def test_entry_points_resolve_to_callables():
    """pyproject [project.scripts] targets must import and be callable
    (the `streambench-obs` console script is the install-time answer
    to the cwd problem).  Python 3.10 has no tomllib — parse the
    script lines textually."""
    text = open(os.path.join(REPO, "pyproject.toml")).read()
    block = text.split("[project.scripts]", 1)[1].split("[", 1)[0]
    targets = dict(re.findall(
        r'^\s*([\w-]+)\s*=\s*"([^"]+)"', block, re.M))
    assert "streambench-obs" in targets
    import importlib

    for name, spec in targets.items():
        mod_name, func_name = spec.split(":")
        mod = importlib.import_module(mod_name)
        assert callable(getattr(mod, func_name)), (name, spec)
