"""Native C store (native/store.cpp) vs the Python reference store.

The two implementations must be observably identical: same replies for
the same command sequences (differential test), same canonical window
schema from the bulk writeback as from the per-command path, and the
same behavior under the RESP TCP server and the stats readers.
"""

import random

import pytest

from streambench_tpu import native
from streambench_tpu.io.fakeredis import (
    FakeRedisStore,
    FakeRedisServer,
    NativeRedisStore,
    make_store,
)
from streambench_tpu.io.resp import RespClient, RespError
from streambench_tpu.io.redis_schema import (
    as_redis,
    read_seen_counts,
    read_window_latencies,
    seed_campaigns,
    write_windows_pipelined,
)

pytestmark = pytest.mark.skipif(native.load() is None,
                                reason="native library unavailable")


def native_store() -> NativeRedisStore:
    return NativeRedisStore(native.load())


COMMANDS = [
    ("PING",),
    ("SET", "k1", "v1"),
    ("GET", "k1"),
    ("GET", "missing"),
    ("SADD", "s", "a", "b", "a"),
    ("SADD", "s", "b", "c"),
    ("SMEMBERS", "s"),
    ("SMEMBERS", "missing"),
    ("HSET", "h", "f1", "v1"),
    ("HSET", "h", "f1", "v2", "f2", "x"),
    ("HGET", "h", "f1"),
    ("HGET", "h", "nope"),
    ("HGET", "missing", "f"),
    ("HGETALL", "h"),
    ("HINCRBY", "h", "ctr", "5"),
    ("HINCRBY", "h", "ctr", "-2"),
    ("HINCRBY", "h", "f1", "1"),          # non-integer -> error
    ("HDEL", "h", "f2", "nope"),
    ("LPUSH", "l", "x"),
    ("LPUSH", "l", "y", "z"),
    ("LLEN", "l"),
    ("LRANGE", "l", "0", "-1"),
    ("LRANGE", "l", "1", "1"),
    ("LRANGE", "l", "-2", "-1"),
    ("LRANGE", "l", "5", "9"),
    ("LRANGE", "missing", "0", "-1"),
    ("GET", "h"),                          # WRONGTYPE
    ("LPUSH", "k1", "v"),                  # WRONGTYPE
    ("BOGUS", "x"),                        # unknown command
    ("FLUSHALL",),
    ("GET", "k1"),
]


def run_seq(store, seq):
    out = []
    for cmd in seq:
        try:
            v = store.dispatch(list(cmd))
            # hgetall order is implementation-defined: canonicalize
            if cmd[0] == "HGETALL":
                v = dict(zip(v[0::2], v[1::2]))
            out.append(("ok", v))
        except RespError as e:
            out.append(("err", str(e).split()[0]))  # compare error class
    return out


def test_differential_command_sequences():
    assert run_seq(native_store(), COMMANDS) == run_seq(
        FakeRedisStore(), COMMANDS)


def test_differential_random_sequences():
    rng = random.Random(7)
    keys = ["a", "b", "c"]
    seq = []
    for _ in range(400):
        k = rng.choice(keys)
        seq.append(rng.choice([
            ("SET", k, str(rng.randrange(100))),
            ("GET", k),
            ("HSET", "h" + k, "f" + str(rng.randrange(3)),
             str(rng.randrange(10))),
            ("HGET", "h" + k, "f" + str(rng.randrange(3))),
            ("HINCRBY", "h" + k, "ctr", str(rng.randrange(-5, 6))),
            ("LPUSH", "l" + k, str(rng.randrange(10))),
            ("LRANGE", "l" + k, "0", "-1"),
            ("SADD", "s", k),
            ("SMEMBERS", "s"),
            ("HGETALL", "h" + k),
        ]))
    assert run_seq(native_store(), seq) == run_seq(FakeRedisStore(), seq)


def test_bulk_writeback_matches_python_store():
    """write_windows_pipelined through the native bulk entry must leave
    the same observable schema as through the Python store."""
    camps = [f"c{i:02d}" for i in range(10)]
    rows = [(camps[i % 10], 1_000_000 + (i // 10) * 10_000, 1 + i % 3)
            for i in range(500)]
    stores = {}
    for name, store in (("native", native_store()),
                        ("python", FakeRedisStore())):
        r = as_redis(store)
        seed_campaigns(r, camps)
        write_windows_pipelined(r, rows, time_updated=777)
        write_windows_pipelined(r, rows, time_updated=888)
        stores[name] = (read_seen_counts(r), read_window_latencies(r))
    assert stores["native"][0] == stores["python"][0]
    assert stores["native"][1] == stores["python"][1]


def test_bulk_absolute_mode():
    r = as_redis(native_store())
    seed_campaigns(r, ["c"])
    write_windows_pipelined(r, [("c", 10_000, 5)], time_updated=1,
                            absolute=True)
    write_windows_pipelined(r, [("c", 10_000, 3)], time_updated=2,
                            absolute=True)
    assert read_seen_counts(r)["c"][10_000] == 3  # replace, not +=


def test_native_store_behind_resp_server():
    with FakeRedisServer(store=native_store()) as srv:
        c = RespClient(srv.host, srv.port)
        assert c.execute("PING") == "PONG"
        c.execute("SET", "x", "1")
        assert c.execute("GET", "x") == "1"
        c.execute("HSET", "h", "f", "v")
        assert c.execute("HGETALL", "h") == ["f", "v"]
        replies = c.pipeline_execute([("SADD", "s", "m")] * 3)
        assert replies == [1, 0, 0]
        c.close()


def test_make_store_prefers_native():
    assert isinstance(make_store(), NativeRedisStore)


def test_large_reply_grows_buffer():
    s = native_store()
    for i in range(5000):
        s.lpush("big", f"value-{i:08d}")
    vals = s.lrange("big", 0, -1)
    assert len(vals) == 5000
    assert vals[0] == "value-00004999"  # LPUSH order: last push first


def test_concurrent_clients_reply_isolation():
    """The shared reply buffer must never leak one thread's reply into
    another (regression test for the _cmd lock): hammer the store from
    several threads with distinguishable values and verify every reply."""
    import threading

    s = native_store()
    errors = []

    def worker(tid: int) -> None:
        try:
            for i in range(300):
                key = f"k-{tid}-{i % 7}"
                val = f"v-{tid}-{i}"
                s.set(key, val)
                got = s.get(key)
                # interleaved writers only touch their own keys, so the
                # readback must be a value this thread wrote
                assert got.startswith(f"v-{tid}-"), (got, tid)
                s.lpush(f"l-{tid}", val)
                tail = s.lrange(f"l-{tid}", 0, 0)
                assert tail and tail[0].startswith(f"v-{tid}-")
        except BaseException as e:  # noqa: BLE001 - surface on main thread
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:1]


def test_window_entries_respond_to_generic_commands():
    """Window rows created by the bulk path must behave exactly like
    generic hashes under HGET/HGETALL/HINCRBY/HSET/HDEL (the native
    store specializes them internally and demotes on off-schema
    writes)."""
    s = native_store()
    r = as_redis(s)
    seed_campaigns(r, ["c"])
    write_windows_pipelined(r, [("c", 20_000, 7)], time_updated=999)
    wuuid = s.hget("c", "20000")
    assert wuuid
    assert s.hget(wuuid, "seen_count") == "7"
    assert s.hget(wuuid, "time_updated") == "999"
    assert s.hget(wuuid, "other") is None
    flat = s.hgetall(wuuid)
    assert dict(zip(flat[0::2], flat[1::2])) == {
        "seen_count": "7", "time_updated": "999"}
    assert s.hincrby(wuuid, "seen_count", 3) == 10
    assert s.hincrby(wuuid, "time_updated", 1) == 1000
    # off-schema write demotes; all fields must survive
    s.hset(wuuid, "note", "x")
    flat = s.hgetall(wuuid)
    d = dict(zip(flat[0::2], flat[1::2]))
    assert d == {"seen_count": "10", "time_updated": "1000", "note": "x"}
    # bulk update of a demoted window keeps working (generic branch)
    write_windows_pipelined(r, [("c", 20_000, 5)], time_updated=1234)
    assert s.hget(wuuid, "seen_count") == "15"
    assert s.hget(wuuid, "time_updated") == "1234"
    # WRONGTYPE: a specialized window key is hash-kind
    write_windows_pipelined(r, [("c", 30_000, 1)], time_updated=1)
    w2 = s.hget("c", "30000")
    with pytest.raises(RespError):
        s.get(w2)
    with pytest.raises(RespError):
        s.lpush(w2, "x")
    assert s.hdel(w2, "seen_count") == 1
    assert s.hget(w2, "seen_count") is None
    assert s.hget(w2, "time_updated") == "1"
