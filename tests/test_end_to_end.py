"""The crown-jewel test (SURVEY section 4.1): golden-model oracle vs engine.

Generates a catchup dataset, runs the TPU engine over the broker topic,
writes the canonical Redis schema, then runs the reference's ``-c`` check:
every window must be CORRECT.  This is config #1 of BASELINE.json running
end-to-end in-process.
"""

import random

from streambench_tpu.config import default_config
from streambench_tpu.datagen import gen
from streambench_tpu.engine import AdAnalyticsEngine, StreamRunner
from streambench_tpu.io.fakeredis import FakeRedisStore
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import as_redis, read_latency_hash


def setup_run(tmp_path, events=20_000, batch=512, slots=16):
    cfg = default_config(jax_batch_size=batch, jax_window_slots=slots)
    r = as_redis(FakeRedisStore())
    broker = FileBroker(str(tmp_path / "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=events,
                 rng=random.Random(123), workdir=str(tmp_path))
    mapping = gen.load_ad_mapping_file(str(tmp_path / gen.AD_TO_CAMPAIGN_FILE))
    engine = AdAnalyticsEngine(cfg, mapping, redis=r)
    reader = broker.reader(cfg.kafka_topic)
    return cfg, r, broker, engine, reader


def test_catchup_end_to_end_all_windows_correct(tmp_path):
    # 20k events at 10 ms spacing = 200 s of event time = ~21 windows,
    # far beyond the 16-slot ring: the span guard must keep it correct.
    cfg, r, broker, engine, reader = setup_run(tmp_path)
    runner = StreamRunner(engine, reader)
    stats = runner.run_catchup()
    engine.close()
    assert stats.events == 20_000
    assert engine.dropped == 0

    logs = []
    correct, differ, missing = gen.check_correct(r, str(tmp_path),
                                                 log=logs.append)
    assert differ == 0 and missing == 0, logs[:5]
    assert correct >= 20  # ~21 windows x campaigns touched

    # canonical -g stats exist and latencies are sane
    stats_rows = gen.get_stats(r, workdir=str(tmp_path))
    assert len(stats_rows) == correct
    # catchup event times extend into the future (start + 10ms*n, like the
    # reference's -s mode), so latency = time_updated - window_ts can be
    # negative here; just require the rows to be well-formed.
    assert all(isinstance(lat, int) for _, lat in stats_rows)

    # fork-style latency hash was dumped on close
    running, per_idx = read_latency_hash(r, cfg.redis_hashtable)
    assert running[1] >= 0 and len(per_idx[1]) > 0


def test_streaming_mode_with_partial_batches(tmp_path):
    cfg, r, broker, engine, reader = setup_run(tmp_path, events=3000,
                                               batch=256)
    # stream mode with a short buffer timeout; idle timeout ends the run
    runner = StreamRunner(engine, reader, buffer_timeout_ms=20,
                          flush_interval_ms=100)
    stats = runner.run(idle_timeout_s=0.5)
    engine.close()
    assert stats.events == 3000
    correct, differ, missing = gen.check_correct(r, str(tmp_path),
                                                 log=lambda s: None)
    assert differ == 0 and missing == 0 and correct > 0
    assert stats.flushes >= 1 and stats.windows_written >= correct


def test_tiny_ring_forces_span_guard_drains(tmp_path):
    # W=9 slots x 10s = 90s ring with 60s lateness -> guard span = 10s:
    # every window boundary forces a drain; counts must still be exact.
    cfg, r, broker, engine, reader = setup_run(tmp_path, events=8000,
                                               batch=128, slots=9)
    runner = StreamRunner(engine, reader)
    runner.run_catchup()
    engine.close()
    correct, differ, missing = gen.check_correct(r, str(tmp_path),
                                                 log=lambda s: None)
    assert differ == 0 and missing == 0 and correct > 0


def test_deferred_drain_pull_conserves_counts(tmp_path, monkeypatch):
    """STREAMBENCH_DEFER_DRAIN_PULL=1 (the tunneled-accelerator mode,
    forced here on CPU): periodic flushes materialize one cycle late,
    the final flush drains everything — the -c oracle must still see
    every window CORRECT, and a mid-run flush must leave the fresh
    drain parked for the next cycle."""
    monkeypatch.setenv("STREAMBENCH_DEFER_DRAIN_PULL", "1")
    cfg, r, broker, engine, reader = setup_run(tmp_path, events=12_000,
                                               batch=256, slots=9)
    assert engine._defer_pull
    runner = StreamRunner(engine, reader, buffer_timeout_ms=20,
                          flush_interval_ms=50)
    stats = runner.run(idle_timeout_s=0.5)

    # exercise the rotation invariant directly: with fresh device deltas,
    # a non-final flush parks them (ready list) instead of writing
    ads = [k.decode() for k in engine.encoder.ad_index]
    extra_ms = engine.encoder.base_time_ms + 10_000_000
    engine.process_lines([(
        '{"user_id": "u", "page_id": "p", "ad_id": "%s", '
        '"ad_type": "banner", "event_type": "view", "event_time": "%d", '
        '"ip_address": "1.2.3.4"}' % (ads[0], extra_ms)).encode()])
    engine.flush()
    assert engine._undrained_ready, "fresh drain should be parked one cycle"
    extra_ts = extra_ms // 10_000 * 10_000
    assert extra_ts not in engine.window_latency, \
        "deferred flush must not have written the fresh drain yet"
    engine.close()  # final=True path drains the parked cycle
    assert extra_ts in engine.window_latency, \
        "final flush must write the one-cycle-parked drain"

    assert stats.events == 12_000
    correct, differ, missing = gen.check_correct(r, str(tmp_path),
                                                 log=lambda s: None)
    assert differ == 0 and missing == 0 and correct > 0
