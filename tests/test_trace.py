"""Tracer + StallDetector coverage (ISSUE 2 satellites): span
aggregation, thread-safety of concurrent span recording (the Redis
flusher thread records ``redis_flush`` spans while the host loop records
``encode``/``device_step``), report rendering, and the stall detector's
threshold/reset/fault-counter behavior."""

import threading

from streambench_tpu.metrics import FaultCounters, StallDetector
from streambench_tpu.trace import Tracer, device_trace


def test_span_aggregation_calls_total_max():
    tr = Tracer()
    tr.add("a", 1_000_000)
    tr.add("a", 3_000_000)
    tr.add("b", 2_000_000)
    st = tr.stages["a"]
    assert st.calls == 2
    assert st.total_ns == 4_000_000
    assert st.max_ns == 3_000_000
    assert st.total_ms == 4.0
    assert st.mean_ms == 2.0
    with tr.span("a"):
        pass
    assert tr.stages["a"].calls == 3


def test_add_and_span_share_one_table():
    tr = Tracer()
    with tr.span("encode"):
        pass
    tr.add("encode", 5_000_000)
    assert tr.stages["encode"].calls == 2
    assert tr.stages["encode"].total_ns >= 5_000_000


def test_report_orders_by_total_and_aligns_width():
    tr = Tracer()
    tr.add("tiny", 1_000)
    tr.add("a_much_longer_stage_name", 9_000_000)
    rep = tr.report()
    lines = rep.splitlines()
    assert lines[0].startswith("trace (stage:")
    # descending by total time: the 9 ms stage precedes the 1 us one
    assert lines[1].lstrip().startswith("a_much_longer_stage_name")
    assert lines[2].lstrip().startswith("tiny")
    # both stage-name columns are padded to the longest name
    w = len("a_much_longer_stage_name")
    assert lines[2].lstrip()[:w].rstrip() == "tiny"
    assert len(lines[2].lstrip()[:w]) == w


def test_report_empty_and_as_dict():
    tr = Tracer()
    assert tr.report() == "trace: no spans recorded"
    tr.add("x", 2_000_000)
    d = tr.as_dict()
    assert d["x"]["calls"] == 1
    assert d["x"]["total_ms"] == 2.0
    assert d["x"]["mean_ms"] == 2.0
    assert d["x"]["max_ms"] == 2.0


def test_disabled_tracer_records_nothing():
    tr = Tracer(enabled=False)
    with tr.span("encode"):
        pass
    assert tr.stages == {}


def test_snapshot_is_a_consistent_copy():
    tr = Tracer()
    tr.add("s", 1_000)
    snap = tr.snapshot()
    assert snap == {"s": (1, 1_000, 1_000)}
    tr.add("s", 1_000)
    assert snap["s"][0] == 1  # the copy does not alias live state


def test_concurrent_spans_lose_no_updates():
    """The satellite's actual bug surface: StageStats read-modify-write
    from the writer thread racing the host loop.  With the lock, N
    threads x M spans must land exactly N*M calls."""
    tr = Tracer()
    N, M = 8, 500

    def work():
        for _ in range(M):
            with tr.span("shared"):
                pass
            tr.add("added", 10)

    threads = [threading.Thread(target=work) for _ in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert tr.stages["shared"].calls == N * M
    assert tr.stages["added"].calls == N * M
    assert tr.stages["added"].total_ns == N * M * 10


def test_device_trace_noop_without_logdir():
    # must not touch jax.profiler at all when logdir is falsy
    with device_trace(None):
        pass
    with device_trace(""):
        pass


# ----------------------------------------------------------------------
def test_stall_detector_threshold_boundary():
    sd = StallDetector(expected_period_ms=1000, factor=2.0)
    assert sd.threshold_ms == 2000
    assert sd.tick(0) is None          # baseline
    assert sd.tick(2000) is None       # exactly at threshold: not a stall
    assert sd.tick(4001) == 2001       # one past: stall
    assert sd.stalls == 1


def test_stall_detector_reset_clears_baseline():
    sd = StallDetector(expected_period_ms=1000)
    sd.tick(0)
    sd.reset()
    # a huge gap after reset is a fresh baseline, not a stall (restart
    # downtime must not be billed as a flush stall)
    assert sd.tick(100_000) is None
    assert sd.stalls == 0
    assert sd.tick(103_000) == 3000
    assert sd.stalls == 1


def test_stall_detector_bumps_fault_counters():
    fc = FaultCounters()
    warnings = []
    sd = StallDetector(expected_period_ms=1000, warn=warnings.append,
                       counters=fc)
    sd.tick(0)
    sd.tick(5000)
    sd.tick(6000)
    sd.tick(20_000)
    assert sd.stalls == 2
    assert fc.get("flush_stalls") == 2
    assert fc.snapshot()["flush_stalls"] == 2
    assert len(warnings) == 2
